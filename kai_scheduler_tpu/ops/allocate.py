"""The allocate action — gang all-or-nothing placement as one compiled scan.

Reference hot path (``actions/allocate/allocate.go:52-156`` →
``actions/common/allocate.go:26-355``): pop jobs from the fairness heap;
per job open a Statement, greedily place each task on its best-scoring
feasible node, and commit iff at least ``minMember`` tasks landed —
otherwise roll the Statement back.  The per-task inner loop
(``allocateTask``, ``allocate.go:229``) is O(nodes) of predicate +
scoring work per task, fanned out over goroutines.

TPU-native design: one ``lax.scan`` whose carry is the *functional
cluster state* (free [N,R], per-queue allocation [Q,R], placement
tables).  Each step:

1. selects the next gang on-device (``ordering.select_next_gang`` — the
   dynamic two-level heap), then
2. runs a ``fori_loop`` over the gang's task slots; each task does a
   broadcast predicate mask + score over ALL nodes at once (the vmapped
   replacement for the goroutine fan-out) and a masked argmax pick, and
3. commits or discards the whole gang with ``jnp.where`` — checkpoint/
   rollback (``framework/statement.go:43-60``) becomes selection between
   the pre-gang and post-gang carries; no op log needed.

Pipelining: a task that only fits once terminating pods release
(``Releasing`` resources) is placed with ``pipelined=True`` — the
equivalent of ``stmt.Pipeline`` vs ``stmt.Allocate``.  Accounting runs
against the combined idle+releasing pool, matching the reference's
virtual allocation of releasing capacity.

Queue capacity gates (proportion plugin ``capacity_policy``): each task
checks, along the queue's ancestor chain, that allocation stays within
``limit`` (maxAllowed) and — for non-preemptible gangs — within
``quota`` (deserved).  A gang whose first ``minMember`` tasks cannot all
pass the gate fails wholesale via the same rollback mechanism.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..apis.types import UNLIMITED
from ..state.cluster_state import ClusterState
from . import ordering
from .predicates import feasible_nodes, node_portion
from .scoring import (W_TOPOLOGY, PlacementConfig, gpu_sharing_score,
                      pick_device, score_nodes_for_task)

EPS = 1e-6


class AllocationResult(struct.PyTreeNode):
    """The cycle's running commit set — the Statement, as a value.

    Every action (allocate, reclaim, preempt, consolidation) consumes and
    produces one of these, mirroring how reference actions share the
    Session's Statement/snapshot mutations across the per-cycle pipeline
    (``scheduler.go:158-168``).
    """

    placements: jax.Array     # i32 [G, T]  node index per task, -1 unplaced
    #: shared-device index per fractional task (-1 = whole-device/none) —
    #: feeds BindRequest.selected_accel_groups
    placement_device: jax.Array  # i32 [G, T]
    pipelined: jax.Array      # bool [G, T] placed onto releasing resources
    allocated: jax.Array      # bool [G]    gang committed this cycle
    attempted: jax.Array      # bool [G]    gang was popped and tried
    free: jax.Array           # f32 [N, R]  *idle* pool after commits (may dip
    #                           negative where pipelined tasks drew on
    #                           releasing capacity; feasibility always checks
    #                           idle+releasing sums)
    device_free: jax.Array    # f32 [N, D]  per-device share pool
    #: capacity freed by THIS cycle's victims — it is releasing, not idle
    #: (the pods have not terminated), so tasks placed on it pipeline.
    #: The tensor equivalent of Statement.Evict flipping a pod to
    #: Releasing status mid-cycle (``framework/statement.go``).
    releasing_extra: jax.Array         # f32 [N, R]
    device_releasing_extra: jax.Array  # f32 [N, D]
    queue_allocated: jax.Array  # f32 [Q, R]
    queue_allocated_nonpreemptible: jax.Array  # f32 [Q, R]
    #: running pods evicted this cycle (victims of reclaim/preempt/
    #: consolidation) — bool [M]
    victim: jax.Array
    #: consolidation move target per running pod — i32 [M] node index the
    #: evicted pod is planned to restart on (-1 = not a move); the
    #: equivalent of the pipelined BindRequest the reference creates for
    #: re-placed consolidation victims
    victim_move: jax.Array


def init_result(state: ClusterState) -> AllocationResult:
    """Fresh commit set at cycle start (an empty Statement)."""
    g, n, q = state.gangs, state.nodes, state.queues
    G, T = g.g, g.t
    return AllocationResult(
        placements=jnp.full((G, T), -1, jnp.int32),
        placement_device=jnp.full((G, T), -1, jnp.int32),
        pipelined=jnp.zeros((G, T), bool),
        allocated=jnp.zeros((G,), bool),
        attempted=jnp.zeros((G,), bool),
        free=n.free,
        device_free=n.device_free,
        releasing_extra=jnp.zeros_like(n.free),
        device_releasing_extra=jnp.zeros_like(n.device_free),
        queue_allocated=q.allocated,
        queue_allocated_nonpreemptible=q.allocated_nonpreemptible,
        victim=jnp.zeros((state.running.m,), bool),
        victim_move=jnp.full((state.running.m,), -1, jnp.int32),
    )


def _ancestor_scatter(parent: jax.Array, q: jax.Array, num_levels: int,
                      arr: jax.Array, delta: jax.Array) -> jax.Array:
    """Add ``delta`` [R] to ``arr`` [Q, R] at queue ``q`` and its ancestors."""
    def hop(_, carry):
        arr, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        arr = arr.at[idx].add(jnp.where(valid, delta, 0.0))
        nxt = jnp.where(valid, parent[idx], -1)
        return arr, nxt
    arr, _ = lax.fori_loop(0, num_levels, hop, (arr, q))
    return arr


def _ancestor_gate(parent: jax.Array, q: jax.Array, num_levels: int,
                   used: jax.Array, cap: jax.Array, req: jax.Array) -> jax.Array:
    """True iff ``used[a] + req <= cap[a]`` (per resource, UNLIMITED caps
    skipped) for queue ``q`` and every ancestor ``a``."""
    def hop(_, carry):
        ok, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        cap_q = cap[idx]
        unlimited = cap_q <= UNLIMITED + 0.5
        fits = jnp.all(unlimited | (used[idx] + req <= cap_q + EPS))
        ok = ok & (~valid | fits)
        nxt = jnp.where(valid, parent[idx], -1)
        return ok, nxt
    ok, _ = lax.fori_loop(0, num_levels, hop, (jnp.asarray(True), q))
    return ok


@dataclasses.dataclass(frozen=True)
class AllocateConfig:
    """Knobs of the allocate action (ref CLI flags + SchedulingShard)."""

    placement: PlacementConfig = PlacementConfig()
    #: max gangs attempted per cycle — ref ``QueueDepthPerAction``;
    #: None = all valid gangs.
    queue_depth: int | None = None
    #: re-sort the queue heap after every allocation (exact reference
    #: semantics) vs freeze the order at cycle start (faster at large G).
    dynamic_order: bool = True


def _attempt_gang_in_domain(
        state: ClusterState, gang_idx: jax.Array,
        free: jax.Array, device_free: jax.Array,
        q_alloc: jax.Array, q_alloc_np: jax.Array,
        num_levels: int, config: AllocateConfig,
        domain_mask: jax.Array,        # bool [N] — allowed nodes
        pref_doms: jax.Array,          # i32 [N]  preferred-level domain ids
        has_pref: jax.Array,           # bool []
        extra_releasing: jax.Array,        # f32 [N, R] victim-freed capacity
        extra_device_releasing: jax.Array  # f32 [N, D]
):
    """Place one gang greedily within ``domain_mask`` — the task loop of
    ``allocateTask`` (``actions/common/allocate.go:229``) including the
    fractional-device path (``gpu_sharing/gpu_sharing.go:20-105``).

    ``extra_releasing`` joins the snapshot's releasing pool for the
    pipeline-fit check, so tasks landing on victim-freed capacity are
    marked pipelined (bind later) while tasks on genuinely idle capacity
    bind immediately — matching ``stmt.Allocate`` vs ``stmt.Pipeline``.
    """
    g = state.gangs
    n = state.nodes
    T = g.t
    D = n.d
    task_req = g.task_req[gang_idx]          # [T, R]
    task_valid = g.task_valid[gang_idx]      # [T]
    task_sel = g.task_selector[gang_idx]     # [T, K]
    task_portion = g.task_portion[gang_idx]  # [T]
    task_mem = g.task_accel_mem[gang_idx]    # [T]
    queue = g.queue[gang_idx]
    nonpreempt = ~g.preemptible[gang_idx]

    def task_body(t, carry):
        free_l, dev_l, qa, qan, nodes_t, dev_t, pipe_t, count, pref_dom = carry
        req = task_req[t]
        is_frac = (task_portion[t] > 0) | (task_mem[t] > 0)
        # queue capacity gates up the hierarchy (capacity_policy.go:26-50)
        gate = _ancestor_gate(state.queues.parent, queue, num_levels,
                              qa, state.queues.limit, req)
        gate = gate & jnp.where(
            nonpreempt,
            _ancestor_gate(state.queues.parent, queue, num_levels,
                           qan, state.queues.quota, req),
            True)
        ok = task_valid[t] & gate

        fit_idle = feasible_nodes(
            n, req, task_sel[t], task_portion[t], task_mem[t],
            free=free_l, device_free=dev_l) & domain_mask
        fit_pipe = feasible_nodes(
            n, req, task_sel[t], task_portion[t], task_mem[t],
            free=free_l + extra_releasing,
            device_free=dev_l + extra_device_releasing,
            include_releasing=True) & domain_mask                      # [N]
        # preferred-level locality band (topology plugin node scoring):
        # stick with the domain of the gang's first-placed task.
        topo_band = jnp.where(
            has_pref & (pref_dom >= 0) & (pref_doms == pref_dom),
            W_TOPOLOGY, 0.0)                                           # [N]
        portion_n = node_portion(n, task_portion[t], task_mem[t])      # [N]
        sharing_band = gpu_sharing_score(dev_l, portion_n, is_frac)    # [N]
        scores = score_nodes_for_task(
            n, free_l, req, fit_idle, fit_pipe, config.placement,
            extra=topo_band + sharing_band)                            # [N]
        node = jnp.argmax(scores)
        placed = ok & jnp.any(fit_pipe)
        is_pipe = placed & ~fit_idle[node]

        # ---- device bookkeeping (GPU-group allocation) ------------------
        dev_row = dev_l[node]                                          # [D]
        dev_rel_row = (n.device_releasing[node]
                       + extra_device_releasing[node])
        p = portion_n[node]
        # fractional: GpuOrderFn pick among idle-fitting devices; a
        # pipelined fraction may dip into releasing share (bounded
        # negative, like the node-level free carry)
        frac_row = jnp.where(is_pipe, dev_row + dev_rel_row, dev_row)
        frac_dev = pick_device(frac_row, p, pack=config.placement.device_pack)
        # whole: take ceil(req) devices, idle-free first then releasing
        k = jnp.round(req[0]).astype(jnp.int32)
        eligible = dev_row + dev_rel_row >= 1.0 - EPS
        rank_key = jnp.where(eligible, -dev_row, jnp.inf)
        rank = jnp.sum(
            (rank_key[None, :] < rank_key[:, None])
            | ((rank_key[None, :] == rank_key[:, None])
               & (jnp.arange(D)[None, :] < jnp.arange(D)[:, None])),
            axis=-1)                                                   # [D]
        take_whole = eligible & (rank < k)
        dev_delta = jnp.where(
            is_frac,
            p * (jnp.arange(D) == frac_dev),
            take_whole.astype(dev_row.dtype))
        dev_delta = jnp.where(placed, dev_delta, 0.0)
        dev_l = dev_l.at[node].add(-dev_delta)

        delta = jnp.where(placed, req, 0.0)
        # node-level accel debit uses the node's actual share (memory-
        # based portions differ per node); queue debits stay canonical
        delta_node = delta.at[0].set(
            jnp.where(placed, jnp.where(is_frac, p, req[0]), 0.0))
        free_l = free_l.at[node].add(-delta_node)
        qa = _ancestor_scatter(state.queues.parent, queue, num_levels, qa, delta)
        qan = _ancestor_scatter(
            state.queues.parent, queue, num_levels, qan,
            jnp.where(nonpreempt, delta, 0.0))
        nodes_t = nodes_t.at[t].set(jnp.where(placed, node, -1))
        dev_t = dev_t.at[t].set(
            jnp.where(placed & is_frac, frac_dev, -1))
        pipe_t = pipe_t.at[t].set(is_pipe)
        count = count + placed.astype(jnp.int32)
        pref_dom = jnp.where(placed & (pref_dom < 0), pref_doms[node],
                             pref_dom)
        return free_l, dev_l, qa, qan, nodes_t, dev_t, pipe_t, count, pref_dom

    init = (free, device_free, q_alloc, q_alloc_np,
            jnp.full((T,), -1, jnp.int32), jnp.full((T,), -1, jnp.int32),
            jnp.zeros((T,), bool),
            jnp.asarray(0, jnp.int32), jnp.asarray(-1, jnp.int32))
    free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, count, _ = lax.fori_loop(
        0, T, task_body, init)
    # min_needed (not min_member): pods already bound/running count toward
    # the gang's quorum — elastic scale-up and pipelined-remainder gangs.
    success = count >= g.min_needed[gang_idx]
    return free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, success


def _attempt_gang(state: ClusterState, gang_idx: jax.Array,
                  free: jax.Array, device_free: jax.Array,
                  q_alloc: jax.Array, q_alloc_np: jax.Array,
                  num_levels: int, config: AllocateConfig,
                  extra_releasing: jax.Array | None = None,
                  extra_device_releasing: jax.Array | None = None):
    """Try to place one gang; returns tentative post-gang state + success.

    Topology handling (ref ``plugins/topology`` SubsetNodesFn +
    ``topology/job_filtering.go:34``): a gang with a *required* level is
    attempted domain-by-domain — candidate domains at that level are
    ordered binpack-style (least aggregate free accel first, i.e. fullest
    domain first, ``topology/node_scoring.go``) and each attempt restricts
    feasibility to the domain's nodes; the first succeeding domain wins
    (checkpoint/rollback between attempts is value selection).  A
    *preferred* level adds a locality score band instead (best-effort).
    """
    g, n = state.gangs, state.nodes
    T = g.t
    L = n.topology.shape[1]
    N = n.n
    if extra_releasing is None:
        extra_releasing = jnp.zeros_like(free)
    if extra_device_releasing is None:
        extra_device_releasing = jnp.zeros_like(device_free)

    pl = g.preferred_level[gang_idx]
    has_pref = pl >= 0
    pref_doms = n.topology[:, jnp.maximum(pl, 0)]              # [N]

    rl = g.required_level[gang_idx]
    has_req = rl >= 0

    def unconstrained(_):
        return _attempt_gang_in_domain(
            state, gang_idx, free, device_free, q_alloc, q_alloc_np,
            num_levels, config, n.valid, pref_doms, has_pref,
            extra_releasing, extra_device_releasing)

    def constrained(_):
        doms = n.topology[:, jnp.maximum(rl, 0)]               # [N]
        # domain ids are globally dense over (level, path) — bound N*L
        D = N * L
        dom_seg = jnp.where(n.valid & (doms >= 0), doms, D)
        avail = free + n.releasing + extra_releasing
        agg = jax.ops.segment_sum(
            jnp.where(n.valid[:, None], avail, 0.0), dom_seg,
            num_segments=D + 1)[:D]                            # [D, R]
        has_node = jax.ops.segment_sum(
            (n.valid & (doms >= 0)).astype(jnp.int32), dom_seg,
            num_segments=D + 1)[:D] > 0
        task_req = jnp.where(g.task_valid[gang_idx][:, None],
                             g.task_req[gang_idx], 0.0)
        total_req = task_req.sum(0)
        fits = jnp.all(agg + EPS >= total_req[None, :], axis=-1) & has_node
        # binpack the domain: fullest (least free accel) candidate first
        dom_key = agg[:, 0]

        empty = (free, device_free, q_alloc, q_alloc_np,
                 jnp.full((T,), -1, jnp.int32),
                 jnp.full((T,), -1, jnp.int32), jnp.zeros((T,), bool),
                 jnp.asarray(False))

        def cond(carry):
            tried, done, _ = carry
            return ~done & jnp.any(fits & ~tried)

        def body(carry):
            tried, _, best = carry
            cand = fits & ~tried
            d = jnp.argmin(jnp.where(cand, dom_key, jnp.inf))
            out = _attempt_gang_in_domain(
                state, gang_idx, free, device_free, q_alloc, q_alloc_np,
                num_levels, config, doms == d, pref_doms, has_pref,
                extra_releasing, extra_device_releasing)
            success = out[-1]
            best = jax.tree.map(
                lambda nw, old: jnp.where(success, nw, old), out, best)
            return tried.at[d].set(True), success, best

        _, done, best = lax.while_loop(
            cond, body, (jnp.zeros((D,), bool), jnp.asarray(False), empty))
        return best

    return lax.cond(has_req, constrained, unconstrained, None)


def allocate(
    state: ClusterState,
    fair_share: jax.Array,          # f32 [Q, R]  from ops.drf.set_fair_share
    *,
    num_levels: int,
    config: AllocateConfig = AllocateConfig(),
    init: AllocationResult | None = None,
) -> AllocationResult:
    """Run the allocate action over every pending gang.

    Functional equivalent of ``allocate.Execute`` — jit-compatible; all
    shapes static.  ``num_levels`` bounds the queue-hierarchy depth
    (snapshot-known static).  ``init`` continues an in-progress cycle
    (the previous action's commit set).
    """
    g, n, q = state.gangs, state.nodes, state.queues
    G, T = g.g, g.t
    total = state.total_capacity
    steps = G if config.queue_depth is None else min(G, config.queue_depth)
    if init is None:
        init = init_result(state)

    # Releasing capacity participates in the pool (pipeline placements);
    # the free carry is the *idle* pool and may dip negative by at most
    # each node's releasing amount — feasibility always checks the sum.
    static_order = None
    if not config.dynamic_order:
        static_order = ordering.static_job_order(
            g, q, init.queue_allocated, fair_share, total)

    def step(carry, step_idx):
        res, remaining = carry
        free, dev, qa, qan = (res.free, res.device_free, res.queue_allocated,
                              res.queue_allocated_nonpreemptible)
        if config.dynamic_order:
            gi = ordering.select_next_gang(g, q, qa, fair_share, total, remaining)
        else:
            gi = static_order[step_idx]
        runnable = remaining[gi] & g.valid[gi] & (g.backoff[gi] <= 0)

        def attempt(args):
            free, dev, qa, qan = args
            free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, success = \
                _attempt_gang(state, gi, free, dev, qa, qan, num_levels,
                              config, init.releasing_extra,
                              init.device_releasing_extra)
            # checkpoint/rollback: keep post-gang state only on success
            sel = lambda a, b: jnp.where(success, a, b)
            return (sel(free2, free), sel(dev2, dev), sel(qa2, qa),
                    sel(qan2, qan),
                    jnp.where(success, nodes_t, -jnp.ones_like(nodes_t)),
                    jnp.where(success, dev_t, -jnp.ones_like(dev_t)),
                    jnp.where(success, pipe_t, jnp.zeros_like(pipe_t)),
                    success)

        def skip(args):
            free, dev, qa, qan = args
            return (free, dev, qa, qan, jnp.full((T,), -1, jnp.int32),
                    jnp.full((T,), -1, jnp.int32),
                    jnp.zeros((T,), bool), jnp.asarray(False))

        free, dev, qa, qan, nodes_t, dev_t, pipe_t, success = lax.cond(
            runnable, attempt, skip, (free, dev, qa, qan))
        res = res.replace(
            free=free, device_free=dev, queue_allocated=qa,
            queue_allocated_nonpreemptible=qan,
            placements=res.placements.at[gi].set(
                jnp.where(runnable, nodes_t, res.placements[gi])),
            placement_device=res.placement_device.at[gi].set(
                jnp.where(runnable, dev_t, res.placement_device[gi])),
            pipelined=res.pipelined.at[gi].set(
                jnp.where(runnable, pipe_t, res.pipelined[gi])),
            allocated=res.allocated.at[gi].set(res.allocated[gi] | success),
            attempted=res.attempted.at[gi].set(res.attempted[gi] | runnable),
        )
        remaining = remaining.at[gi].set(False)
        return (res, remaining), None

    remaining0 = g.valid & (g.backoff <= 0) & ~init.allocated
    (res, _), _ = lax.scan(step, (init, remaining0), jnp.arange(steps))
    return res


@functools.partial(jax.jit, static_argnames=("num_levels", "config"))
def allocate_jit(state: ClusterState, fair_share: jax.Array, *,
                 num_levels: int, config: AllocateConfig = AllocateConfig(),
                 init: AllocationResult | None = None) -> AllocationResult:
    return allocate(state, fair_share, num_levels=num_levels, config=config,
                    init=init)

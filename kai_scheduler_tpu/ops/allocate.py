"""The allocate action — gang all-or-nothing placement as one compiled scan.

Reference hot path (``actions/allocate/allocate.go:52-156`` →
``actions/common/allocate.go:26-355``): pop jobs from the fairness heap;
per job open a Statement, greedily place each task on its best-scoring
feasible node, and commit iff at least ``minMember`` tasks landed —
otherwise roll the Statement back.  The per-task inner loop
(``allocateTask``, ``allocate.go:229``) is O(nodes) of predicate +
scoring work per task, fanned out over goroutines.

TPU-native design: one ``lax.scan`` whose carry is the *functional
cluster state* (free [N,R], per-queue allocation [Q,R], placement
tables).  Each step:

1. selects the next gang on-device (``ordering.select_next_gang`` — the
   dynamic two-level heap), then
2. runs a ``fori_loop`` over the gang's task slots; each task does a
   broadcast predicate mask + score over ALL nodes at once (the vmapped
   replacement for the goroutine fan-out) and a masked argmax pick, and
3. commits or discards the whole gang with ``jnp.where`` — checkpoint/
   rollback (``framework/statement.go:43-60``) becomes selection between
   the pre-gang and post-gang carries; no op log needed.

Pipelining: a task that only fits once terminating pods release
(``Releasing`` resources) is placed with ``pipelined=True`` — the
equivalent of ``stmt.Pipeline`` vs ``stmt.Allocate``.  Accounting runs
against the combined idle+releasing pool, matching the reference's
virtual allocation of releasing capacity.

Queue capacity gates (proportion plugin ``capacity_policy``): each task
checks, along the queue's ancestor chain, that allocation stays within
``limit`` (maxAllowed) and — for non-preemptible gangs — within
``quota`` (deserved).  A gang whose first ``minMember`` tasks cannot all
pass the gate fails wholesale via the same rollback mechanism.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..apis.types import UNLIMITED
from ..state.cluster_state import ClusterState
from . import ordering
from .predicates import feasible_nodes
from .scoring import PlacementConfig, score_nodes_for_task

EPS = 1e-6


class AllocationResult(struct.PyTreeNode):
    """The cycle's running commit set — the Statement, as a value.

    Every action (allocate, reclaim, preempt, consolidation) consumes and
    produces one of these, mirroring how reference actions share the
    Session's Statement/snapshot mutations across the per-cycle pipeline
    (``scheduler.go:158-168``).
    """

    placements: jax.Array     # i32 [G, T]  node index per task, -1 unplaced
    pipelined: jax.Array      # bool [G, T] placed onto releasing resources
    allocated: jax.Array      # bool [G]    gang committed this cycle
    attempted: jax.Array      # bool [G]    gang was popped and tried
    free: jax.Array           # f32 [N, R]  idle+releasing pool after commits
    queue_allocated: jax.Array  # f32 [Q, R]
    queue_allocated_nonpreemptible: jax.Array  # f32 [Q, R]
    #: running pods evicted this cycle (victims of reclaim/preempt/
    #: consolidation) — bool [M]
    victim: jax.Array


def init_result(state: ClusterState) -> AllocationResult:
    """Fresh commit set at cycle start (an empty Statement)."""
    g, n, q = state.gangs, state.nodes, state.queues
    G, T = g.g, g.t
    return AllocationResult(
        placements=jnp.full((G, T), -1, jnp.int32),
        pipelined=jnp.zeros((G, T), bool),
        allocated=jnp.zeros((G,), bool),
        attempted=jnp.zeros((G,), bool),
        free=n.free,
        queue_allocated=q.allocated,
        queue_allocated_nonpreemptible=q.allocated_nonpreemptible,
        victim=jnp.zeros((state.running.m,), bool),
    )


def _ancestor_scatter(parent: jax.Array, q: jax.Array, num_levels: int,
                      arr: jax.Array, delta: jax.Array) -> jax.Array:
    """Add ``delta`` [R] to ``arr`` [Q, R] at queue ``q`` and its ancestors."""
    def hop(_, carry):
        arr, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        arr = arr.at[idx].add(jnp.where(valid, delta, 0.0))
        nxt = jnp.where(valid, parent[idx], -1)
        return arr, nxt
    arr, _ = lax.fori_loop(0, num_levels, hop, (arr, q))
    return arr


def _ancestor_gate(parent: jax.Array, q: jax.Array, num_levels: int,
                   used: jax.Array, cap: jax.Array, req: jax.Array) -> jax.Array:
    """True iff ``used[a] + req <= cap[a]`` (per resource, UNLIMITED caps
    skipped) for queue ``q`` and every ancestor ``a``."""
    def hop(_, carry):
        ok, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        cap_q = cap[idx]
        unlimited = cap_q <= UNLIMITED + 0.5
        fits = jnp.all(unlimited | (used[idx] + req <= cap_q + EPS))
        ok = ok & (~valid | fits)
        nxt = jnp.where(valid, parent[idx], -1)
        return ok, nxt
    ok, _ = lax.fori_loop(0, num_levels, hop, (jnp.asarray(True), q))
    return ok


@dataclasses.dataclass(frozen=True)
class AllocateConfig:
    """Knobs of the allocate action (ref CLI flags + SchedulingShard)."""

    placement: PlacementConfig = PlacementConfig()
    #: max gangs attempted per cycle — ref ``QueueDepthPerAction``;
    #: None = all valid gangs.
    queue_depth: int | None = None
    #: re-sort the queue heap after every allocation (exact reference
    #: semantics) vs freeze the order at cycle start (faster at large G).
    dynamic_order: bool = True


def _attempt_gang(state: ClusterState, gang_idx: jax.Array,
                  free: jax.Array, q_alloc: jax.Array, q_alloc_np: jax.Array,
                  num_levels: int, config: AllocateConfig):
    """Try to place one gang; returns tentative post-gang state + success."""
    g = state.gangs
    n = state.nodes
    T = g.t
    task_req = g.task_req[gang_idx]          # [T, R]
    task_valid = g.task_valid[gang_idx]      # [T]
    task_sel = g.task_selector[gang_idx]     # [T, K]
    task_portion = g.task_portion[gang_idx]  # [T]
    queue = g.queue[gang_idx]
    nonpreempt = ~g.preemptible[gang_idx]

    def task_body(t, carry):
        free_l, qa, qan, nodes_t, pipe_t, count = carry
        req = task_req[t]
        # queue capacity gates up the hierarchy (capacity_policy.go:26-50)
        gate = _ancestor_gate(state.queues.parent, queue, num_levels,
                              qa, state.queues.limit, req)
        gate = gate & jnp.where(
            nonpreempt,
            _ancestor_gate(state.queues.parent, queue, num_levels,
                           qan, state.queues.quota, req),
            True)
        ok = task_valid[t] & gate

        fit_idle = feasible_nodes(
            n, req, task_sel[t], task_portion[t], free=free_l)        # [N]
        fit_pipe = feasible_nodes(
            n, req, task_sel[t], task_portion[t], free=free_l,
            include_releasing=True)                                    # [N]
        scores = score_nodes_for_task(
            n, free_l, req, fit_idle, fit_pipe, config.placement)      # [N]
        node = jnp.argmax(scores)
        placed = ok & jnp.any(fit_pipe)
        is_pipe = placed & ~fit_idle[node]

        delta = jnp.where(placed, req, 0.0)
        free_l = free_l.at[node].add(-delta)
        qa = _ancestor_scatter(state.queues.parent, queue, num_levels, qa, delta)
        qan = _ancestor_scatter(
            state.queues.parent, queue, num_levels, qan,
            jnp.where(nonpreempt, delta, 0.0))
        nodes_t = nodes_t.at[t].set(jnp.where(placed, node, -1))
        pipe_t = pipe_t.at[t].set(is_pipe)
        count = count + placed.astype(jnp.int32)
        return free_l, qa, qan, nodes_t, pipe_t, count

    init = (free, q_alloc, q_alloc_np,
            jnp.full((T,), -1, jnp.int32), jnp.zeros((T,), bool),
            jnp.asarray(0, jnp.int32))
    free2, qa2, qan2, nodes_t, pipe_t, count = lax.fori_loop(
        0, T, task_body, init)
    # min_needed (not min_member): pods already bound/running count toward
    # the gang's quorum — elastic scale-up and pipelined-remainder gangs.
    success = count >= g.min_needed[gang_idx]
    return free2, qa2, qan2, nodes_t, pipe_t, success


def allocate(
    state: ClusterState,
    fair_share: jax.Array,          # f32 [Q, R]  from ops.drf.set_fair_share
    *,
    num_levels: int,
    config: AllocateConfig = AllocateConfig(),
    init: AllocationResult | None = None,
) -> AllocationResult:
    """Run the allocate action over every pending gang.

    Functional equivalent of ``allocate.Execute`` — jit-compatible; all
    shapes static.  ``num_levels`` bounds the queue-hierarchy depth
    (snapshot-known static).  ``init`` continues an in-progress cycle
    (the previous action's commit set).
    """
    g, n, q = state.gangs, state.nodes, state.queues
    G, T = g.g, g.t
    total = state.total_capacity
    steps = G if config.queue_depth is None else min(G, config.queue_depth)
    if init is None:
        init = init_result(state)

    # Releasing capacity participates in the pool (pipeline placements);
    # the free carry is the *idle* pool and may dip negative by at most
    # each node's releasing amount — feasibility always checks the sum.
    static_order = None
    if not config.dynamic_order:
        static_order = ordering.static_job_order(
            g, q, init.queue_allocated, fair_share, total)

    def step(carry, step_idx):
        res, remaining = carry
        free, qa, qan = (res.free, res.queue_allocated,
                         res.queue_allocated_nonpreemptible)
        if config.dynamic_order:
            gi = ordering.select_next_gang(g, q, qa, fair_share, total, remaining)
        else:
            gi = static_order[step_idx]
        runnable = remaining[gi] & g.valid[gi] & (g.backoff[gi] <= 0)

        def attempt(args):
            free, qa, qan = args
            free2, qa2, qan2, nodes_t, pipe_t, success = _attempt_gang(
                state, gi, free, qa, qan, num_levels, config)
            # checkpoint/rollback: keep post-gang state only on success
            sel = lambda a, b: jnp.where(success, a, b)
            return (sel(free2, free), sel(qa2, qa), sel(qan2, qan),
                    jnp.where(success, nodes_t, -jnp.ones_like(nodes_t)),
                    jnp.where(success, pipe_t, jnp.zeros_like(pipe_t)),
                    success)

        def skip(args):
            free, qa, qan = args
            return (free, qa, qan, jnp.full((T,), -1, jnp.int32),
                    jnp.zeros((T,), bool), jnp.asarray(False))

        free, qa, qan, nodes_t, pipe_t, success = lax.cond(
            runnable, attempt, skip, (free, qa, qan))
        res = res.replace(
            free=free, queue_allocated=qa,
            queue_allocated_nonpreemptible=qan,
            placements=res.placements.at[gi].set(
                jnp.where(runnable, nodes_t, res.placements[gi])),
            pipelined=res.pipelined.at[gi].set(
                jnp.where(runnable, pipe_t, res.pipelined[gi])),
            allocated=res.allocated.at[gi].set(res.allocated[gi] | success),
            attempted=res.attempted.at[gi].set(res.attempted[gi] | runnable),
        )
        remaining = remaining.at[gi].set(False)
        return (res, remaining), None

    remaining0 = g.valid & (g.backoff <= 0) & ~init.allocated
    (res, _), _ = lax.scan(step, (init, remaining0), jnp.arange(steps))
    return res


@functools.partial(jax.jit, static_argnames=("num_levels", "config"))
def allocate_jit(state: ClusterState, fair_share: jax.Array, *,
                 num_levels: int, config: AllocateConfig = AllocateConfig(),
                 init: AllocationResult | None = None) -> AllocationResult:
    return allocate(state, fair_share, num_levels=num_levels, config=config,
                    init=init)

"""The allocate action — gang all-or-nothing placement as one compiled scan.

Reference hot path (``actions/allocate/allocate.go:52-156`` →
``actions/common/allocate.go:26-355``): pop jobs from the fairness heap;
per job open a Statement, greedily place each task on its best-scoring
feasible node, and commit iff at least ``minMember`` tasks landed —
otherwise roll the Statement back.  The per-task inner loop
(``allocateTask``, ``allocate.go:229``) is O(nodes) of predicate +
scoring work per task, fanned out over goroutines.

TPU-native design: one ``lax.scan`` whose carry is the *functional
cluster state* (free [N,R], per-queue allocation [Q,R], placement
tables).  Each step:

1. selects the next gang on-device (``ordering.select_next_gang`` — the
   dynamic two-level heap), then
2. runs a ``fori_loop`` over the gang's task slots; each task does a
   broadcast predicate mask + score over ALL nodes at once (the vmapped
   replacement for the goroutine fan-out) and a masked argmax pick, and
3. commits or discards the whole gang with ``jnp.where`` — checkpoint/
   rollback (``framework/statement.go:43-60``) becomes selection between
   the pre-gang and post-gang carries; no op log needed.

Pipelining: a task that only fits once terminating pods release
(``Releasing`` resources) is placed with ``pipelined=True`` — the
equivalent of ``stmt.Pipeline`` vs ``stmt.Allocate``.  Accounting runs
against the combined idle+releasing pool, matching the reference's
virtual allocation of releasing capacity.

Queue capacity gates (proportion plugin ``capacity_policy``): each task
checks, along the queue's ancestor chain, that allocation stays within
``limit`` (maxAllowed) and — for non-preemptible gangs — within
``quota`` (deserved).  A gang whose first ``minMember`` tasks cannot all
pass the gate fails wholesale via the same rollback mechanism.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from flax import struct
from jax import lax

from ..apis.types import UNLIMITED
from ..runtime import compile_watch
from ..state.cluster_state import ClusterState
from . import ordering
from .predicates import feasible_nodes, feasible_nodes_dual, node_portion
from .scoring import (BIG_NEG, W_NOMINATED, W_TOPOLOGY, PlacementConfig,
                      gpu_sharing_score, pick_device, score_nodes_for_task)

EPS = 1e-6


class AllocationResult(struct.PyTreeNode):
    """The cycle's running commit set — the Statement, as a value.

    Every action (allocate, reclaim, preempt, consolidation) consumes and
    produces one of these, mirroring how reference actions share the
    Session's Statement/snapshot mutations across the per-cycle pipeline
    (``scheduler.go:158-168``).
    """

    placements: jax.Array     # i32 [G, T]  node index per task, -1 unplaced
    #: extended scalar-resource pool after commits — f32 [N, E]
    extended_free: jax.Array
    #: shared-device index per fractional task (-1 = whole-device/none) —
    #: feeds BindRequest.selected_accel_groups
    placement_device: jax.Array  # i32 [G, T]
    pipelined: jax.Array      # bool [G, T] placed onto releasing resources
    allocated: jax.Array      # bool [G]    gang committed this cycle
    attempted: jax.Array      # bool [G]    gang was popped and tried
    free: jax.Array           # f32 [N, R]  *idle* pool after commits (may dip
    #                           negative where pipelined tasks drew on
    #                           releasing capacity; feasibility always checks
    #                           idle+releasing sums)
    device_free: jax.Array    # f32 [N, D]  per-device share pool
    #: capacity freed by THIS cycle's victims — it is releasing, not idle
    #: (the pods have not terminated), so tasks placed on it pipeline.
    #: The tensor equivalent of Statement.Evict flipping a pod to
    #: Releasing status mid-cycle (``framework/statement.go``).
    releasing_extra: jax.Array         # f32 [N, R]
    device_releasing_extra: jax.Array  # f32 [N, D]
    #: extended (MIG) resources freed by this cycle's victims — credited
    #: to the pipeline-fit pool so a preemptor needing a MIG slice held
    #: only by victims can reclaim it (placements drawing on it pipeline)
    extended_releasing_extra: jax.Array  # f32 [N, E]
    queue_allocated: jax.Array  # f32 [Q, R]
    queue_allocated_nonpreemptible: jax.Array  # f32 [Q, R]
    #: running pods evicted this cycle (victims of reclaim/preempt/
    #: consolidation) — bool [M]
    victim: jax.Array
    #: consolidation move target per running pod — i32 [M] node index the
    #: evicted pod is planned to restart on (-1 = not a move); the
    #: equivalent of the pipelined BindRequest the reference creates for
    #: re-placed consolidation victims
    victim_move: jax.Array
    #: why a gang was not placed this cycle (ref ``api/unschedule_info.go``
    #: fit errors): 0 = placed/not tried, 1 = feasibility prefilter (no
    #: nodes for its tasks), 2 = an equivalent gang already failed
    #: (signature skip), 3 = placement attempt failed — i32 [G]
    fit_reason: jax.Array
    #: in-cycle claimed-domain table — bool [TA+1, AD+1]: row = exclusion
    #: term (see ``GangState.anti_marks``; TA = junk row), column = dense
    #: (node, level) domain id with per-node slots appended (AD = junk).
    #: Shared by ALL placement actions (allocate and the victim
    #: wavefronts), so a reclaim-placed preemptor excludes later
    #: conflicting placements within the same cycle.
    anti_used: jax.Array
    #: victim-wavefront observability counters — i32 [2, 5]: row 0 =
    #: reclaim, row 1 = preempt; cols = (chunks run, live lanes seen,
    #: lane slots offered, dense-fallback count of the sparse preempt
    #: path, lane-chunk demotion events from earlier lanes' net
    #: leftover freed capacity).  Rides the packed commit transfer and
    #: feeds the ``kai_victim_wavefront_*`` gauges
    #: (``framework/metrics.py``).
    wavefront_stats: jax.Array


def init_result(state: ClusterState) -> AllocationResult:
    """Fresh commit set at cycle start (an empty Statement)."""
    g, n, q = state.gangs, state.nodes, state.queues
    G, T = g.g, g.t
    TA = g.anti_term_level.shape[0]
    AD = n.n * n.topology.shape[1] + n.n
    return AllocationResult(
        anti_used=jnp.zeros((TA + 1, AD + 1), bool),
        wavefront_stats=jnp.zeros((2, 5), jnp.int32),
        placements=jnp.full((G, T), -1, jnp.int32),
        extended_free=n.extended_free,
        placement_device=jnp.full((G, T), -1, jnp.int32),
        pipelined=jnp.zeros((G, T), bool),
        allocated=jnp.zeros((G,), bool),
        attempted=jnp.zeros((G,), bool),
        free=n.free,
        device_free=n.device_free,
        releasing_extra=jnp.zeros_like(n.free),
        device_releasing_extra=jnp.zeros_like(n.device_free),
        extended_releasing_extra=jnp.zeros_like(n.extended_free),
        queue_allocated=q.allocated,
        queue_allocated_nonpreemptible=q.allocated_nonpreemptible,
        victim=jnp.zeros((state.running.m,), bool),
        victim_move=jnp.full((state.running.m,), -1, jnp.int32),
        fit_reason=jnp.zeros((G,), jnp.int32),
    )


def anti_domain_tables(state: ClusterState):
    """Static per-LEVEL dense domain ids for the in-cycle exclusion
    table (``AllocationResult.anti_used``): ``dom_static`` [L+1, N] —
    rows 0..L-1 are the topology levels (a node LACKING the level's
    label is its own per-node domain: upstream anti-affinity treats a
    missing topology key as no shared domain), row L is the per-node
    granularity; padded node slots map to the junk id AD."""
    n = state.nodes
    N, L = n.n, n.topology.shape[1]
    ND = N * L
    AD = ND + N
    node_slot = ND + jnp.arange(N)
    rows = []
    for lvl in range(L):
        by = n.topology[:, lvl]
        rows.append(jnp.where(n.valid,
                              jnp.where(by >= 0, by, node_slot), AD))
    rows.append(jnp.where(n.valid, node_slot, AD))
    return jnp.stack(rows), state.gangs.anti_term_level.shape[0]


def anti_forbid_nodes(state: ClusterState, anti_used: jax.Array,
                      dom_static: jax.Array, gang_idx: jax.Array):
    """bool [..., N] — nodes whose domain is already claimed in any of
    the gang's avoid rows this cycle (``gang_idx`` scalar or batched).
    Shared by the allocate wavefront and both victim paths."""
    g = state.gangs
    L = state.nodes.topology.shape[1]
    TA = g.anti_term_level.shape[0]
    if TA <= 0:
        raise ValueError("anti kernels compiled without terms")
    avoids = g.anti_avoids[jnp.maximum(gang_idx, 0)]       # [..., KT]
    t_safe = jnp.clip(avoids, 0, TA - 1)
    lvl = g.anti_term_level[t_safe]
    doms = dom_static[jnp.clip(lvl, 0, L)]                 # [..., KT, N]
    hit = anti_used[t_safe[..., None], doms]
    return jnp.any(hit & (avoids >= 0)[..., None], axis=-2)


def anti_mark_placements(state: ClusterState, anti_used: jax.Array,
                         dom_static: jax.Array, gang_idx: jax.Array,
                         nodes_t: jax.Array, valid: jax.Array):
    """Claim the committed placements' domains in the gang's mark rows
    (junk row/column absorb unused slots; ``valid`` gates whole
    gangs/lanes)."""
    g, n = state.gangs, state.nodes
    L = n.topology.shape[1]
    TA = g.anti_term_level.shape[0]
    if TA <= 0:
        raise ValueError("anti kernels compiled without terms")
    AD = n.n * L + n.n
    marks = g.anti_marks[jnp.maximum(gang_idx, 0)]         # [..., KT]
    t_safe = jnp.clip(marks, 0, TA - 1)
    lvl = g.anti_term_level[t_safe]
    placed = (nodes_t >= 0) & valid[..., None]             # [..., T]
    doms = dom_static[jnp.clip(lvl, 0, L)[..., None],
                      jnp.maximum(nodes_t, 0)[..., None, :]]  # [.., KT, T]
    ok = placed[..., None, :] & (marks >= 0)[..., None]
    rows = jnp.where(ok, t_safe[..., None], TA)
    cols = jnp.where(ok, doms, AD)
    return anti_used.at[rows, cols].max(True)


def anti_defer_lanes(state: ClusterState, cand_g: jax.Array,
                     cand_valid: jax.Array):
    """bool [B] — lanes whose avoid rows intersect an EARLIER valid
    lane's mark rows this chunk: they conflict-retry next chunk against
    the updated table (at most one side of a conflicting pair lands per
    chunk, mirroring the reference's one-at-a-time virtual updates)."""
    g = state.gangs
    B = cand_g.shape[0]
    marks = g.anti_marks[jnp.maximum(cand_g, 0)]           # [B, KT]
    avoids = g.anti_avoids[jnp.maximum(cand_g, 0)]
    inter = jnp.any(
        (avoids[:, None, :, None] == marks[None, :, None, :])
        & (avoids >= 0)[:, None, :, None]
        & (marks >= 0)[None, :, None, :], axis=(2, 3))     # [B, B]
    earlier = jnp.arange(B)[None, :] < jnp.arange(B)[:, None]
    return jnp.any(inter & earlier & cand_valid[None, :], axis=1) \
        & cand_valid


def attract_allow_nodes(state: ClusterState, anti_used: jax.Array,
                        dom_static: jax.Array, gang_idx: jax.Array):
    """bool [..., N] — nodes permitted by the gang's attraction (need)
    rows: EVERY need row must claim the node's domain at the row's
    level, either statically (a running match, ``attract_static``) or
    in-cycle (an anchor gang placed this cycle marked it).  Gangs
    without need slots pass everywhere.  Shared by the allocate
    wavefront and the victim placements (ref upstream InterPodAffinity
    against virtually-allocated state,
    ``k8s_internal/predicates/predicates.go:70-140``)."""
    g = state.gangs
    L = state.nodes.topology.shape[1]
    TA = g.anti_term_level.shape[0]
    if TA <= 0:
        raise ValueError("attract kernels compiled without terms")
    needs = g.attract_needs[jnp.maximum(gang_idx, 0)]      # [..., KP]
    t_safe = jnp.clip(needs, 0, TA - 1)
    lvl = g.anti_term_level[t_safe]
    doms = dom_static[jnp.clip(lvl, 0, L)]                 # [..., KP, N]
    claimed = (anti_used[t_safe[..., None], doms]
               | g.attract_static[t_safe])                 # [..., KP, N]
    ok = claimed | (needs < 0)[..., None]                  # unused pass
    return jnp.all(ok, axis=-2)                            # [..., N]


def attract_defer_lanes(state: ClusterState, cand_g: jax.Array,
                        cand_valid: jax.Array, anti_used: jax.Array):
    """bool [B] — lanes with a still-UNCLAIMED need row that an EARLIER
    valid lane of this chunk would mark: they sit the chunk out and
    retry against the updated table (so an anchor and its depender
    arriving in one chunk land in order instead of the depender failing
    terminally).  Lane 0 never defers, preserving the wavefront's
    progress guarantee."""
    g = state.gangs
    TA = g.anti_term_level.shape[0]
    AD = anti_used.shape[1] - 1
    B = cand_g.shape[0]
    needs = g.attract_needs[jnp.maximum(cand_g, 0)]        # [B, KP]
    marks = g.anti_marks[jnp.maximum(cand_g, 0)]           # [B, KT]
    row_any = (jnp.any(anti_used[:TA, :AD], axis=1)
               | jnp.any(g.attract_static, axis=1))        # [TA]
    open_need = (needs >= 0) & ~row_any[jnp.clip(needs, 0, TA - 1)]
    inter = jnp.any(
        (needs[:, None, :, None] == marks[None, :, None, :])
        & open_need[:, None, :, None]
        & (marks >= 0)[None, :, None, :], axis=(2, 3))     # [B, B]
    earlier = jnp.arange(B)[None, :] < jnp.arange(B)[:, None]
    return jnp.any(inter & earlier & cand_valid[None, :], axis=1) \
        & cand_valid


def sparse_entry_tables(nodes_b: jax.Array, ent_ok: jax.Array, N: int):
    """Node-sorted view of a wavefront chunk's K = B*T sparse placement
    entries — the shared core of the sparse accept-prefix protocol
    (lanes emit placements only; the chunk verifies composed capacity on
    per-entry claims instead of dense [B, N, R] delta cumsums).

    Entries are generated lane-major and sorted stably by node, so
    within a node they stay in lane order and a per-node inclusive
    cumulative claim is exactly the composed demand of lanes ``<= b``.
    Used by the allocate chunk and the victim wavefront's sparse accept.

    Returns (node_e [K] unsorted node per entry with ``N`` as junk,
    lane_e [K] unsorted lane per entry, perm [K] the stable node sort,
    ns [K] sorted nodes, lane_s [K] sorted lanes, sidx [K] index of each
    sorted entry's node-segment start, ok_s [K] sorted entry validity).
    """
    B, T = nodes_b.shape
    node_e = jnp.where(ent_ok, nodes_b, N).ravel()             # [K]
    lane_e = jnp.broadcast_to(
        jnp.arange(B)[:, None], (B, T)).ravel()
    perm = jnp.argsort(node_e, stable=True)
    ns = node_e[perm]
    first = jnp.concatenate(
        [jnp.ones((1,), bool), ns[1:] != ns[:-1]])
    sidx = jax.lax.associative_scan(
        jnp.maximum, jnp.where(first, jnp.arange(ns.shape[0]), -1))
    return node_e, lane_e, perm, ns, lane_e[perm], sidx, \
        ent_ok.ravel()[perm]


def sparse_accept_first_bad(nodes_b: jax.Array, ent_ok: jax.Array,
                            pipe_b: jax.Array, req_b: jax.Array,
                            free: jax.Array, pipe_pool: jax.Array,
                            N: int, credit=None):
    """First lane whose sparse claim entries over-subscribe a node pool
    — THE accept protocol, shared by the allocate chunk and the victim
    wavefront's sparse path (one implementation so a tolerance or
    side= change cannot silently diverge the two).

    Claims sort by node via ``sparse_entry_tables``; each entry's
    node-cumulative demand must fit ``pipe_pool`` (chunk-start free +
    releasing + extra), and the bind-now subset (claims with
    ``~pipe_b``) must collectively fit the chunk-start *idle* pool —
    pipelined flags were derived against chunk-start free, so without
    the second test a later lane could bind immediately onto capacity
    another lane just consumed.  ``credit`` optionally maps
    (lane_s [K], nsafe [K]) to per-entry [K, R] extra capacity granted
    to later lanes (the victim path's lane-prefix freed deltas
    gathered at the claim sites).

    Returns (first_bad lane id — B when every claim fits, node_e [K],
    lane_e [K]: the unsorted entry tables the commit reconstruction
    reuses).
    """
    B = nodes_b.shape[0]
    node_e, lane_e, perm, ns, lane_s, sidx, ok_s = \
        sparse_entry_tables(nodes_b, ent_ok, N)
    req_s = jnp.where(ok_s[:, None], req_b[lane_s], 0.0)      # [K, R]
    cs = jnp.cumsum(req_s, axis=0)
    cum_e = cs - (cs - req_s)[sidx]           # inclusive, per node
    nsafe = jnp.minimum(ns, N - 1)
    real = ns < N
    cap_pipe = pipe_pool[nsafe]
    if credit is not None:
        cap_pipe = cap_pipe + credit(lane_s, nsafe)
    viol = jnp.any(cum_e > cap_pipe + EPS, -1) & real
    bind_e = (ent_ok & ~pipe_b).ravel()[perm]
    reqb_s = jnp.where(bind_e[:, None], req_b[lane_s], 0.0)
    csb = jnp.cumsum(reqb_s, axis=0)
    cumb_e = csb - (csb - reqb_s)[sidx]
    cap_bind = jnp.maximum(free, 0.0)[nsafe] + EPS
    viol = viol | (jnp.any(cumb_e > cap_bind, -1) & real)
    return jnp.min(jnp.where(viol, lane_s, B)), node_e, lane_e


def _replica_count(avail: jax.Array, req: jax.Array,
                   mask: jax.Array) -> jax.Array:
    """i32 [N] whole replicas of ``req`` fitting in each node's ``avail``
    rows, zero outside ``mask`` — the ONE place the count arithmetic
    lives (the uniform kernel's lane path and the chunk-hoisted type
    tables must agree bit-for-bit)."""
    pos = req > EPS
    c = jnp.where(pos[None, :],
                  (avail + EPS) / jnp.maximum(req, EPS)[None, :],
                  jnp.inf)                              # [N, R]
    c = jnp.floor(jnp.min(c, axis=-1))
    return jnp.where(mask, jnp.clip(c, 0.0, 1e9), 0.0).astype(jnp.int32)


def _chain_membership(parent: jax.Array, num_levels: int) -> jax.Array:
    """bool [Q, Q]: ``C[q, a]`` — queue ``a`` is ``q`` or an ancestor of
    ``q``.  Computed once per action; turns per-task ancestor walks into
    single masked reductions."""
    Q = parent.shape[0]
    eye = jnp.eye(Q, dtype=bool)

    def hop(_, carry):
        member, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        member = member | (valid[:, None] & eye[idx])
        return member, jnp.where(valid, parent[idx], -1)

    member, _ = lax.fori_loop(
        0, num_levels, hop, (jnp.zeros((Q, Q), bool), jnp.arange(Q)))
    return member


def _ancestor_scatter(parent: jax.Array, q: jax.Array, num_levels: int,
                      arr: jax.Array, delta: jax.Array) -> jax.Array:
    """Add ``delta`` [R] to ``arr`` [Q, R] at queue ``q`` and its ancestors."""
    def hop(_, carry):
        arr, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        arr = arr.at[idx].add(jnp.where(valid, delta, 0.0))
        nxt = jnp.where(valid, parent[idx], -1)
        return arr, nxt
    arr, _ = lax.fori_loop(0, num_levels, hop, (arr, q))
    return arr


def _ancestor_gate(parent: jax.Array, q: jax.Array, num_levels: int,
                   used: jax.Array, cap: jax.Array, req: jax.Array) -> jax.Array:
    """True iff ``used[a] + req <= cap[a]`` (per resource, UNLIMITED caps
    skipped) for queue ``q`` and every ancestor ``a``."""
    def hop(_, carry):
        ok, cur = carry
        valid = cur >= 0
        idx = jnp.maximum(cur, 0)
        cap_q = cap[idx]
        unlimited = cap_q <= UNLIMITED + 0.5
        fits = jnp.all(unlimited | (used[idx] + req <= cap_q + EPS))
        ok = ok & (~valid | fits)
        nxt = jnp.where(valid, parent[idx], -1)
        return ok, nxt
    ok, _ = lax.fori_loop(0, num_levels, hop, (jnp.asarray(True), q))
    return ok


@dataclasses.dataclass(frozen=True)
class AllocateConfig:
    """Knobs of the allocate action (ref CLI flags + SchedulingShard)."""

    placement: PlacementConfig = PlacementConfig()
    #: max gangs attempted per QUEUE this action — ref
    #: ``QueueDepthPerAction`` ("max number of jobs to try for action per
    #: queue", ``conf/scheduler_conf.go:56``); None = unlimited.
    queue_depth: int | None = None
    #: order gangs by the PREDICTED pop sequence of the reference's
    #: dynamic two-level heap (hoisted — see allocate()), with a live
    #: per-chunk over-fair-share gate, vs freeze the job order at cycle
    #: start.  Exact while pops succeed; placement failures and elastic
    #: re-pushes perturb the tail of the order within an action.
    dynamic_order: bool = True
    #: gangs attempted in parallel per wavefront chunk.  Each chunk
    #: orders the remaining gangs by live fairness keys, attempts the
    #: first ``batch_size`` independently against chunk-start state, and
    #: accepts the maximal order-prefix whose *cumulative* claims fit
    #: (nodes, devices, queue caps).  Conflict-rejected gangs retry next
    #: chunk, so capacity semantics are exact; only the scoring heuristic
    #: sees ≤1 chunk of staleness.  1 = fully sequential (reference-exact).
    #: 256 measured fastest at the 10k-node × 50k-pod baseline scale.
    batch_size: int = 256
    #: maintain the per-device share table.  Set False when the snapshot
    #: holds no fractional/memory-based tasks — the node-level accel
    #: vector is then exact and the device-granular bookkeeping (the
    #: most op-heavy part of the task step) is skipped.  Session derives
    #: this from the snapshot automatically.
    track_devices: bool = True
    #: every gang's pending tasks are identical (same request/selector,
    #: no fractions) — the overwhelmingly common shape (a gang IS T
    #: replicas).  Enables the vectorized whole-gang placement that fills
    #: nodes by score order with per-node copy counts instead of T
    #: sequential task steps.  Requires ``track_devices=False``.  Session
    #: derives this from the snapshot automatically.
    uniform_tasks: bool = False
    #: whole-gang feasibility prefilter over the task-type table — gangs
    #: with no feasible nodes for ``min_needed`` tasks are never attempted
    #: (ref ``actions/common/feasible_nodes.go:11`` FeasibleNodesForJob)
    prefilter: bool = True
    #: compile the required-level machinery (per-subgroup domain locks +
    #: capacity-aware, domain-binpacked first placement — gang-level
    #: required levels route through subgroup slot 0).  An O(N) segment
    #: reduction per task step; False when the snapshot holds no required
    #: topology constraint.  Session derives this automatically.
    subgroup_topology: bool = True
    #: compile extended scalar-resource (MIG/DRA) fit + accounting.
    #: False when the snapshot carries none.  Session derives this
    #: automatically.  Enforcement covers allocate AND the victim
    #: scenarios: evicted pods' extended resources are credited back to
    #: their node's pipeline-fit pool (``extended_releasing_extra``), so
    #: a preemptor that needs a MIG slice held only by victims can
    #: reclaim it (see ``freed_by_mask``/``ops/victims.py`` freed_ext).
    extended: bool = False
    #: node feasibility spans the whole node axis (no selectors, filter
    #: classes, anti-affinity, or topology domains anywhere in the
    #: snapshot) — lets the whole-gang kernel use a cheap cyclic lane
    #: rotation instead of the per-attempt feasible-rank cumsum.  Session
    #: derives this automatically; False is always safe.
    dense_feasibility: bool = False
    #: skip gangs whose scheduling signature already failed this action —
    #: ref ``actions/common/minimal_job_comparison.go`` (MinimalJobRepresentatives)
    signature_skip: bool = True
    #: track in-cycle exclusion terms (mutual AND asymmetric required
    #: anti-affinity between pending gangs, plus shared host ports) in
    #: the cycle's claimed-domain table — ref InterPodAffinity /
    #: NodePorts over virtually-allocated session state.  The Session
    #: enables this when the snapshot emitted term rows
    #: (``GangState.anti_marks``); the table is sized from the state.
    anti_groups: bool = False
    #: enforce in-cycle ATTRACTION terms (required positive affinity
    #: toward a gang placed earlier this cycle): gangs with
    #: ``GangState.attract_needs`` slots place only on nodes whose
    #: domains are claimed in every need row (running matches pre-marked
    #: in ``attract_static``; anchors mark through the shared
    #: ``anti_marks`` machinery).  Requires ``anti_groups``.
    attract_groups: bool = False
    #: compile the PREFERRED-level locality band (anchor the gang near
    #: its best node's preferred domain).  The Session derives this from
    #: the snapshot — gangs without preferred levels skip the band's
    #: per-lane argmax + domain compare over the node axis entirely.
    preferred_topology: bool = True
    #: uniform-kernel wavefront protocol: lanes emit placements only and
    #: the chunk reconstructs capacity deltas with K-entry sparse
    #: scatters (False restores the dense [B, N, R] delta/cumsum accept
    #: path — debug/A-B knob, results are identical)
    sparse_wavefront: bool = True
    #: hoist per-TYPE feasibility/replica-count/score tables out of the
    #: uniform kernel's lane vmap, [Y, N] once per chunk (False restores
    #: the per-lane computation — debug/A-B knob, results are identical)
    hoist_type_tables: bool = True


def _attempt_gang_in_domain(
        state: ClusterState, gang_idx: jax.Array,
        free: jax.Array, device_free: jax.Array,
        q_alloc: jax.Array, q_alloc_np: jax.Array,
        num_levels: int, config: AllocateConfig,
        domain_mask: jax.Array,        # bool [N] — allowed nodes
        pref_doms: jax.Array,          # i32 [N]  preferred-level domain ids
        has_pref: jax.Array,           # bool []
        extra_releasing: jax.Array,        # f32 [N, R] victim-freed capacity
        extra_device_releasing: jax.Array, # f32 [N, D]
        lane: jax.Array,               # i32 [] wavefront lane (tie-break)
        chain: jax.Array,              # bool [Q, Q] ancestor membership
        prior_nodes: jax.Array | None = None,  # i32 [T] prior placements
        quota: jax.Array | None = None,    # i32 [] max new placements
        ext_free: jax.Array | None = None,  # f32 [N, E] extended pool
        extra_extended_releasing: jax.Array | None = None,  # f32 [N, E]
        banned_doms: jax.Array | None = None,  # i32 [S] domains to avoid
        score_bias: jax.Array | None = None  # f32 [N] extra score band
):
    """Place one gang greedily within ``domain_mask`` — the task loop of
    ``allocateTask`` (``actions/common/allocate.go:229``) including the
    fractional-device path (``gpu_sharing/gpu_sharing.go:20-105``).

    ``extra_releasing`` joins the snapshot's releasing pool for the
    pipeline-fit check, so tasks landing on victim-freed capacity are
    marked pipelined (bind later) while tasks on genuinely idle capacity
    bind immediately — matching ``stmt.Allocate`` vs ``stmt.Pipeline``.

    ``lane`` seeds a sub-score-resolution cyclic tie-break over nodes so
    the wavefront's parallel lanes spread over *equal-scoring* nodes
    instead of all argmaxing the same one (which would serialize the
    chunk accept-prefix to one gang).  Real score differences dominate
    the jitter; sequential (B=1) behavior has lane 0 ≡ plain first-index
    tie-break on an idle cluster.

    The task loop is unrolled (T is static): each step is small [N]-wide
    work and an on-device loop would cost more in iteration overhead
    than the unrolled graph.
    """
    g = state.gangs
    n = state.nodes
    T = g.t
    D = n.d
    N = n.n
    L = n.topology.shape[1]
    R_DIM = free.shape[1]
    task_req = g.task_req[gang_idx]          # [T, R]
    task_valid = g.task_valid[gang_idx]      # [T]
    task_sel = g.task_selector[gang_idx]     # [T, K]
    task_portion = g.task_portion[gang_idx]  # [T]
    task_mem = g.task_accel_mem[gang_idx]    # [T]
    task_class = g.task_filter_class[gang_idx]  # [T]
    task_nom = g.task_nominated[gang_idx]    # [T]
    task_ext = g.task_extended[gang_idx]     # [T, E]
    if config.extended:
        # MIG g-number accel equivalents per task (ref resource_info.go
        # GetTotalGPURequest: totalGpusQuota += gpuPortion * count) —
        # folded into the QUEUE accel ledger in-cycle so MIG-heavy
        # queues hit quota/over-share gates the same cycle they place;
        # node pools keep tracking the extended scalars themselves
        ext_gq = task_ext @ g.ext_accel      # [T]
    if ext_free is None:
        ext_free = n.extended_free
    if extra_extended_releasing is None:
        extra_extended_releasing = jnp.zeros_like(ext_free)
    queue = g.queue[gang_idx]
    nonpreempt = ~g.preemptible[gang_idx]
    # gang-internal anti-affinity: no two tasks in the same domain at
    # this level (asl == L means per-node)
    asl = g.anti_self_level[gang_idx]
    has_asl = asl >= 0
    doms_self = jnp.where(asl >= L, jnp.arange(N),
                          n.topology[:, jnp.clip(asl, 0, L - 1)])       # [N]
    # re-push protocol (ref allocate.go:102-104 + getNumTasksToAllocate):
    # an attempt places at most ``quota`` new tasks, skipping tasks a
    # prior attempt already placed; its goal is min(quota, unplaced) and
    # success is all-or-nothing on that chunk.  Legacy callers (victim
    # solver) pass neither and keep quorum semantics.
    legacy = prior_nodes is None and quota is None
    if prior_nodes is None:
        prior_nodes = jnp.full((T,), -1, jnp.int32)
    if quota is None:
        quota = jnp.asarray(T, jnp.int32)
    already = prior_nodes >= 0                                          # [T]
    unplaced_t = task_valid & ~already
    unplaced = jnp.sum(unplaced_t.astype(jnp.int32))
    # seed cross-attempt state from prior placements: anti-self domains
    # and the preferred-level locality anchor
    prior_doms = doms_self[jnp.maximum(prior_nodes, 0)]                 # [T]
    forbidden0 = has_asl & jnp.any(
        (doms_self[:, None] == prior_doms[None, :]) & already[None, :],
        axis=1)                                                         # [N]
    first_prior = jnp.argmax(already)
    pref_dom0 = jnp.where(
        jnp.any(already),
        pref_doms[jnp.maximum(prior_nodes[first_prior], 0)], -1)

    # --- hierarchical subgroups (ref allocateSubGroupSet + the per-
    # subgroup chunks of GetTasksToAllocate): an attempt's eligible task
    # set is, while ANY subgroup is below quorum, the union of per-
    # subgroup quorum chunks (+ extra tasks when the gang's own minMember
    # exceeds the subgroup sum); once quorate, one scale-up task.
    S = g.s
    sub = g.task_subgroup[gang_idx]                                     # [T]
    sub_need = g.subgroup_min_needed[gang_idx]                          # [S]
    srl = g.subgroup_required_level[gang_idx]                           # [S]
    already_s = jax.ops.segment_sum(
        already.astype(jnp.int32), sub, num_segments=S)                 # [S]
    deficit = jnp.maximum(sub_need - already_s, 0)                      # [S]
    in_quorum = jnp.any(deficit > 0) | (
        jnp.sum(already.astype(jnp.int32)) <
        g.min_needed[gang_idx])
    earlier_same_sub = ((sub[None, :] == sub[:, None])
                        & (jnp.arange(T)[None, :] < jnp.arange(T)[:, None]))
    rank_in_sub = jnp.sum(earlier_same_sub & unplaced_t[None, :], axis=1)
    elig_quorum = unplaced_t & (rank_in_sub < deficit[sub])             # [T]
    # extra tasks to honour a gang minMember above the subgroup sum
    extra_needed = jnp.maximum(
        g.min_needed[gang_idx] - jnp.sum(already.astype(jnp.int32))
        - jnp.sum(deficit), 0)
    rest = unplaced_t & ~elig_quorum
    rank_rest = jnp.cumsum(rest.astype(jnp.int32)) - 1
    elig_quorum = elig_quorum | (rest & (rank_rest < extra_needed))
    first_unplaced = unplaced_t & (
        jnp.cumsum(unplaced_t.astype(jnp.int32)) - 1 < 1)
    eligible_new = jnp.where(in_quorum, elig_quorum, first_unplaced)
    goal = jnp.sum(eligible_new.astype(jnp.int32))
    if legacy:
        goal = jnp.minimum(quota, unplaced)
    # remaining per-subgroup request of this attempt's chunk — steers a
    # constrained subgroup's first placement into a domain big enough for
    # the whole chunk (the tensor stand-in for allocateSubGroupSet's
    # subset checkpoint/rollback search)
    sub_rem0 = jax.ops.segment_sum(
        jnp.where((eligible_new if not legacy else task_valid)[:, None],
                  task_req, 0.0),
        sub, num_segments=S)                                            # [S, R]
    # per-domain aggregate availability over the GLOBAL dense domain-id
    # space (all levels share it), computed once per attempt and
    # maintained incrementally — a per-task-step segment reduction blew
    # TPU scratch limits at wavefront width
    ND = N * L
    if config.subgroup_topology:
        avail0 = free + n.releasing + extra_releasing                   # [N, R]
        agg0 = jnp.zeros((ND + 1, R_DIM), avail0.dtype)
        for lvl in range(L):
            ids = jnp.where(n.valid & (n.topology[:, lvl] >= 0),
                            n.topology[:, lvl], ND)
            agg0 = agg0.at[ids].add(jnp.where(n.valid[:, None], avail0,
                                              0.0))
    else:
        agg0 = jnp.zeros((1, R_DIM), free.dtype)

    # Queue capacity gates (capacity_policy.go:26-50), hoisted out of the
    # task loop: all tasks of a gang share one queue chain, so the gate
    # for task t is "qa + cumulative request through t stays within every
    # ancestor's cap".  Computed for all T prefixes in one reduction.
    # (Slightly conservative vs the reference when a mid-gang task fails
    # placement: its request still counts toward later tasks' prefix.)
    anc = chain[queue]                                          # [Q]
    limit_eff = jnp.where(state.queues.limit <= UNLIMITED + 0.5,
                          jnp.inf, state.queues.limit)          # [Q, R]
    quota_eff = jnp.where(state.queues.quota <= UNLIMITED + 0.5,
                          jnp.inf, state.queues.quota)
    eligible_t = task_valid if legacy else eligible_new         # [T]
    req_valid = jnp.where(eligible_t[:, None], task_req, 0.0)   # [T, R]
    if config.extended:
        # the quota/limit prefix gates see the MIG g-equivalents too,
        # matching the snapshot-side rollups (GetTotalGPURequest)
        req_valid = req_valid.at[:, 0].add(
            jnp.where(eligible_t, ext_gq, 0.0))
    cum_req = jnp.cumsum(req_valid, axis=0)                     # [T, R]
    exempt = ~anc[None, :, None]
    gate_lim = jnp.all(
        (q_alloc[None] + cum_req[:, None, :] <= limit_eff[None] + EPS)
        | exempt, axis=(1, 2))                                  # [T]
    gate_quota = jnp.all(
        (q_alloc_np[None] + cum_req[:, None, :] <= quota_eff[None] + EPS)
        | exempt, axis=(1, 2))
    gate_t = gate_lim & jnp.where(nonpreempt, gate_quota, True)  # [T]

    def task_body(t, carry):
        (free_l, dev_l, ext_l, bind_used, dev_bind, ext_bind, forbidden,
         sub_dom, sub_rem, agg, nodes_t, dev_t, pipe_t, count, q_delta,
         pref_dom) = carry
        req = task_req[t]
        is_frac = (task_portion[t] > 0) | (task_mem[t] > 0)
        ok = eligible_t[t] & gate_t[t]

        fit_idle, fit_pipe = feasible_nodes_dual(
            n, req, task_sel[t], task_portion[t], task_mem[t],
            free=free_l, device_free=dev_l,
            extra_releasing=extra_releasing,
            extra_device_releasing=extra_device_releasing,
            devices=config.track_devices,
            task_class=task_class[t])
        if config.extended:
            te = task_ext[t]                                           # [E]
            fit_idle = fit_idle & jnp.all(
                ext_l + EPS >= te[None, :], axis=-1)
            fit_pipe = fit_pipe & jnp.all(
                ext_l + n.extended_releasing + extra_extended_releasing
                + EPS >= te[None, :], axis=-1)
        allowed = domain_mask & ~forbidden
        # per-subgroup required level: once the subgroup's first task
        # lands, its whole domain at that level is locked for the rest.
        # The pick is greedy and single-shot — the aggregate-capacity
        # gate (dom_ok below) stands in for allocateSubGroupSet's
        # per-subset rollback search, so a domain whose aggregate fits
        # but is fragmented across nodes can still fail the attempt
        # (retried next cycle); the whole-gang kernel's per-node replica
        # counts are fragmentation-exact for uniform gangs.
        s_t = sub[t]
        level_t = srl[s_t]
        has_srl = level_t >= 0
        dom_col = jnp.take(n.topology, jnp.clip(level_t, 0, L - 1),
                           axis=1)                                     # [N]
        locked = sub_dom[s_t]
        dom_band = jnp.zeros((N,), jnp.float32)
        if config.subgroup_topology:
            allowed = allowed & (
                ~has_srl | (locked < 0) | (dom_col == locked))
            # a constrained subgroup's FIRST placement must pick a domain
            # whose aggregate capacity still fits the subgroup's
            # remaining chunk, or the lock would doom the attempt
            needs_pick = has_srl & (locked < 0)
            node_agg = agg[jnp.maximum(dom_col, 0)]                    # [N, R]
            dom_ok = jnp.all(
                node_agg + EPS >= sub_rem[s_t][None, :],
                axis=-1) & (dom_col >= 0)
            if banned_doms is not None:
                # in-cycle retry after a fragmented-domain failure: the
                # previously locked domain is off the table this attempt
                dom_ok = dom_ok & (dom_col != banned_doms[s_t])
            allowed = allowed & (~needs_pick | dom_ok)
            # binpack the domain choice: fullest fitting domain first
            # (ref topology/node_scoring.go domain ordering) — scaled
            # into the topology band so node-level bands stay subordinate
            agg_accel = node_agg[:, 0]
            mx = jnp.max(jnp.where(dom_ok, agg_accel, 0.0))
            dom_band = jnp.where(
                needs_pick & dom_ok,
                W_TOPOLOGY * (1.0 - agg_accel / jnp.maximum(mx, EPS)),
                0.0)
        fit_idle = fit_idle & allowed
        fit_pipe = fit_pipe & allowed                                  # [N]
        # preferred-level locality band (topology plugin node scoring):
        # stick with the domain of the gang's first-placed task.
        topo_band = jnp.where(
            has_pref & (pref_dom >= 0) & (pref_doms == pref_dom),
            W_TOPOLOGY, 0.0)                                           # [N]
        # per-lane tie-break by rank WITHIN the feasible set: equal-scoring
        # nodes spread across wavefront lanes even when feasibility is
        # confined to a small domain (an absolute-index rotation would
        # collapse every lane onto the same first feasible node there,
        # serializing the chunk to one accepted gang)
        rank_feas = jnp.cumsum(fit_pipe.astype(jnp.int32)) - 1
        tie_jitter = (-1e-4 / N) * jnp.mod(rank_feas - lane, N).astype(
            jnp.float32)                                               # [N]
        # soft filter bands (PreferNoSchedule / preferred pod-affinity)
        # + the nominatednode plugin's dominating bonus + the required-
        # domain binpack band
        extra_bands = (topo_band + dom_band + tie_jitter
                       + n.soft_scores[task_class[t]]
                       + jnp.where(jnp.arange(N) == task_nom[t],
                                   W_NOMINATED, 0.0))
        if score_bias is not None:
            extra_bands = extra_bands + score_bias
        if config.track_devices:
            portion_n = node_portion(n, task_portion[t], task_mem[t])  # [N]
            extra_bands = extra_bands + gpu_sharing_score(
                dev_l, portion_n, is_frac)                             # [N]
        scores = score_nodes_for_task(
            n, free_l, req, fit_idle, fit_pipe, config.placement,
            extra=extra_bands)                                         # [N]
        node = jnp.argmax(scores)
        placed = ok & jnp.any(fit_pipe)
        is_pipe = placed & ~fit_idle[node]

        if config.track_devices:
            # ---- device bookkeeping (GPU-group allocation) --------------
            dev_row = dev_l[node]                                      # [D]
            dev_rel_row = (n.device_releasing[node]
                           + extra_device_releasing[node])
            p = portion_n[node]
            # fractional: GpuOrderFn pick among idle-fitting devices; a
            # pipelined fraction may dip into releasing share (bounded
            # negative, like the node-level free carry)
            frac_row = jnp.where(is_pipe, dev_row + dev_rel_row, dev_row)
            frac_dev = pick_device(frac_row, p,
                                   pack=config.placement.device_pack)
            # whole: take ceil(req) devices, idle-free first then releasing
            k = jnp.round(req[0]).astype(jnp.int32)
            eligible = dev_row + dev_rel_row >= 1.0 - EPS
            rank_key = jnp.where(eligible, -dev_row, jnp.inf)
            rank = jnp.sum(
                (rank_key[None, :] < rank_key[:, None])
                | ((rank_key[None, :] == rank_key[:, None])
                   & (jnp.arange(D)[None, :] < jnp.arange(D)[:, None])),
                axis=-1)                                               # [D]
            take_whole = eligible & (rank < k)
            dev_delta = jnp.where(
                is_frac,
                p * (jnp.arange(D) == frac_dev),
                take_whole.astype(dev_row.dtype))
            dev_delta = jnp.where(placed, dev_delta, 0.0)
            dev_l = dev_l.at[node].add(-dev_delta)
            dev_bind = dev_bind.at[node].add(
                jnp.where(is_pipe, 0.0, dev_delta))
        else:
            p = req[0]
            frac_dev = jnp.asarray(-1, jnp.int32)

        delta = jnp.where(placed, req, 0.0)
        # node-level accel debit uses the node's actual share (memory-
        # based portions differ per node); queue debits stay canonical
        delta_node = delta.at[0].set(
            jnp.where(placed, jnp.where(is_frac, p, req[0]), 0.0))
        free_l = free_l.at[node].add(-delta_node)
        # bind-now claims tracked separately: the wavefront accept check
        # must verify that *immediately bound* tasks collectively fit the
        # chunk-start idle pool (pipelined tasks legitimately overdraw it)
        bind_used = bind_used.at[node].add(
            jnp.where(is_pipe, 0.0, delta_node))
        if config.extended:
            ext_delta = jnp.where(placed, task_ext[t], 0.0)
            ext_l = ext_l.at[node].add(-ext_delta)
            ext_bind = ext_bind.at[node].add(
                jnp.where(is_pipe, 0.0, ext_delta))
        delta_queue = delta
        if config.extended:
            # queue ledger counts MIG g-equivalents in-cycle
            delta_queue = delta.at[0].add(jnp.where(placed, ext_gq[t], 0.0))
        q_delta = q_delta + delta_queue
        # anti-self: the chosen node's whole domain is off-limits for the
        # gang's remaining tasks
        forbidden = forbidden | (
            has_asl & placed & (doms_self == doms_self[node]))
        sub_dom = sub_dom.at[s_t].set(
            jnp.where(placed & has_srl & (locked < 0), dom_col[node],
                      locked))
        sub_rem = sub_rem.at[s_t].add(-jnp.where(placed, req, 0.0))
        if config.subgroup_topology:
            # keep the per-domain aggregate current: the chosen node's
            # domain at EVERY level loses this placement
            for lvl in range(L):
                did = n.topology[node, lvl]
                agg = agg.at[jnp.where(did >= 0, did, ND)].add(
                    -jnp.where(placed, delta_node, 0.0))
        nodes_t = nodes_t.at[t].set(jnp.where(placed, node, -1))
        dev_t = dev_t.at[t].set(
            jnp.where(placed & is_frac, frac_dev, -1))
        pipe_t = pipe_t.at[t].set(is_pipe)
        count = count + placed.astype(jnp.int32)
        pref_dom = jnp.where(placed & (pref_dom < 0), pref_doms[node],
                             pref_dom)
        return (free_l, dev_l, ext_l, bind_used, dev_bind, ext_bind,
                forbidden, sub_dom, sub_rem, agg, nodes_t, dev_t, pipe_t,
                count, q_delta, pref_dom)

    # seed subgroup domain locks from prior placements
    prior_level = srl[sub]                                              # [T]
    prior_sub_dom = n.topology[jnp.maximum(prior_nodes, 0),
                               jnp.clip(prior_level, 0, L - 1)]         # [T]
    sub_dom0 = jnp.full((S,), -1, jnp.int32).at[sub].max(
        jnp.where(already & (prior_level >= 0), prior_sub_dom, -1))

    carry = (free, device_free, ext_free,
             jnp.zeros_like(free), jnp.zeros_like(device_free),
             jnp.zeros_like(ext_free),
             forbidden0, sub_dom0, sub_rem0, agg0,
             jnp.full((T,), -1, jnp.int32), jnp.full((T,), -1, jnp.int32),
             jnp.zeros((T,), bool),
             jnp.asarray(0, jnp.int32), jnp.zeros_like(task_req[0]),
             pref_dom0.astype(jnp.int32))
    # fori_loop, not a static unroll: the task step's graph is large and
    # appears in several kernel variants (wavefront lanes, domain loop,
    # victim solver) — unrolling T copies made compile time the suite's
    # bottleneck while saving only ~µs of loop overhead per step
    carry = lax.fori_loop(0, T, task_body, carry)
    (free2, dev2, ext2, bind_used, dev_bind, ext_bind, _, sub_dom_out, _,
     _, nodes_t, dev_t, pipe_t, count, q_delta, _) = carry
    # queue accounting applied once for the whole gang along its chain
    qa2 = q_alloc + anc[:, None] * q_delta[None, :]
    qan2 = q_alloc_np + jnp.where(nonpreempt,
                                  anc[:, None] * q_delta[None, :], 0.0)
    if legacy:
        # min_needed (not min_member): pods already bound/running count
        # toward the gang's quorum — elastic scale-up and pipelined-
        # remainder gangs (victim-solver semantics).
        success = count >= g.min_needed[gang_idx]
    else:
        # re-push protocol: the attempt's chunk is all-or-nothing
        success = (goal > 0) & (count >= goal)
    return (free2, dev2, qa2, qan2, nodes_t, dev_t, pipe_t, success,
            bind_used, dev_bind, ext2, ext_bind, sub_dom_out)


def _attempt_gang_in_domain_uniform(
        state: ClusterState, gang_idx: jax.Array,
        free: jax.Array, device_free: jax.Array,
        q_alloc: jax.Array, q_alloc_np: jax.Array,
        num_levels: int, config: AllocateConfig,
        domain_mask: jax.Array, pref_doms: jax.Array, has_pref: jax.Array,
        extra_releasing: jax.Array, extra_device_releasing: jax.Array,
        lane: jax.Array, chain: jax.Array,
        prior_nodes: jax.Array | None = None,
        quota: jax.Array | None = None,
        ext_free: jax.Array | None = None,
        extra_extended_releasing: jax.Array | None = None,
        banned_doms: jax.Array | None = None,
        score_bias: jax.Array | None = None,
        topo_tables=None,
        sparse_out: bool = False,
        type_tables_u=None):
    """Whole-gang placement for uniform-task gangs, no per-task loop.

    A gang whose T pending tasks are identical replicas (the dominant
    real shape — and the one the reference's benchmarks use) admits a
    closed-form greedy: per node, how many replicas fit (`copies`); fill
    nodes in score order until the gang is whole.  Equivalent to the
    sequential task loop under binpack scoring (a node's binpack score
    only rises as it fills, so the sequential greedy would keep hitting
    the same node until it is full, which is exactly the capacity-count
    fill); spread scoring drifts from the loop by design.

    Same signature/returns as :func:`_attempt_gang_in_domain`.
    """
    g, n = state.gangs, state.nodes
    T, N = g.t, n.n
    req = g.task_req[gang_idx, 0]                       # [R] the replica
    sel = g.task_selector[gang_idx, 0]                  # [K]
    task_class = g.task_filter_class[gang_idx, 0]       # []
    task_valid = g.task_valid[gang_idx]                 # [T]
    tcount = jnp.sum(task_valid.astype(jnp.int32))
    queue = g.queue[gang_idx]
    nonpreempt = ~g.preemptible[gang_idx]
    # per-node anti-self (one replica per node) is the only granularity
    # this path supports — the snapshot builder gates uniform_gangs on it
    one_per_node = g.anti_self_level[gang_idx] >= 0
    anc = chain[queue]                                  # [Q]
    # re-push protocol (see _attempt_gang_in_domain)
    legacy = prior_nodes is None and quota is None
    if prior_nodes is None:
        prior_nodes = jnp.full((T,), -1, jnp.int32)
    if quota is None:
        quota = jnp.asarray(T, jnp.int32)
    already = prior_nodes >= 0
    already_count = jnp.sum(already.astype(jnp.int32))
    unplaced = tcount - already_count
    goal = jnp.minimum(quota, unplaced)
    prior_on_node = jnp.zeros((N,), jnp.int32).at[
        jnp.maximum(prior_nodes, 0)].add(already.astype(jnp.int32)) > 0

    # ---- queue capacity gate: max replicas within every ancestor cap ----
    limit_eff = jnp.where(state.queues.limit <= UNLIMITED + 0.5,
                          jnp.inf, state.queues.limit)
    quota_eff = jnp.where(state.queues.quota <= UNLIMITED + 0.5,
                          jnp.inf, state.queues.quota)
    req_pos = req > EPS

    def max_copies(used, cap):
        head = jnp.where(req_pos[None, :],
                         (cap - used) / jnp.maximum(req, EPS)[None, :],
                         jnp.inf)                       # [Q, R]
        head = jnp.where(anc[:, None], head, jnp.inf)
        m = jnp.min(jnp.floor(head + EPS))
        return jnp.clip(m, 0.0, 1e9).astype(jnp.int32)

    m_gate = max_copies(q_alloc, limit_eff)
    m_gate = jnp.where(nonpreempt,
                       jnp.minimum(m_gate, max_copies(q_alloc_np, quota_eff)),
                       m_gate)

    # ---- per-node replica capacity --------------------------------------
    zero = jnp.zeros((), req.dtype)

    def lane_clamp(c, mask):
        """Per-lane adjustments on a raw replica count: domain/feasibility
        mask, then anti-self (one replica per node; nodes holding a
        replica from a prior attempt are off-limits)."""
        c = jnp.where(mask, c, 0)
        c = jnp.where(one_per_node & prior_on_node, 0, c)
        return jnp.where(one_per_node, jnp.minimum(c, 1), c)

    if type_tables_u is not None:
        # chunk-hoisted per-TYPE tables (see allocate()): feasibility,
        # raw replica counts, and base scores depend only on the lane's
        # task type and chunk-start free — the per-lane work left is
        # gathers, masks, and the tie-jitter/top-k passes
        ty = g.task_type[gang_idx, 0]
        fit_idle_y, fit_pipe_y, c_idle_y, c_pipe_y, scores0_y = \
            type_tables_u
        fit_idle = fit_idle_y[ty] & domain_mask
        fit_pipe = fit_pipe_y[ty] & domain_mask
        c_pipe = lane_clamp(c_pipe_y[ty], fit_pipe)     # [N]
    else:
        fit_idle, fit_pipe = feasible_nodes_dual(
            n, req, sel, zero, zero,
            free=free, device_free=device_free,
            extra_releasing=extra_releasing,
            extra_device_releasing=extra_device_releasing, devices=False,
            task_class=task_class)
        fit_idle = fit_idle & domain_mask
        fit_pipe = fit_pipe & domain_mask

    def copies(avail, mask):
        return lane_clamp(_replica_count(avail, req, mask), mask)

    if type_tables_u is None:
        c_pipe = copies(free + n.releasing + extra_releasing,
                        fit_pipe)                       # [N]

    if config.subgroup_topology:
        # required topology level (gang-level routes through subgroup
        # slot 0): choose ONE domain that can host the whole chunk —
        # fullest fitting first (ref topology domain binpack) — and
        # confine the fill to it.  Re-push attempts stay in the domain
        # the quorum locked.
        L = n.topology.shape[1]
        srl0 = g.subgroup_required_level[gang_idx, 0]
        has_req = srl0 >= 0
        dom_col = jnp.take(n.topology, jnp.clip(srl0, 0, L - 1), axis=1)
        NDu = N * L
        want0 = jnp.minimum(goal if not legacy else tcount, m_gate)
        if topo_tables is not None:
            # chunk-hoisted tables (see allocate()): per-lane work is
            # gathers + one cumsum — the vmapped per-lane argsort +
            # segment-sums over the domain axis dominated the wavefront
            # at 5k nodes
            dom_caps_y, level_of_dom, order_by_agg = topo_tables
            dom_caps = dom_caps_y[g.task_type[gang_idx, 0]]   # [ND]
            fits_dom = ((dom_caps >= jnp.maximum(want0, 1))
                        & (level_of_dom == srl0))
            if banned_doms is not None:
                fits_dom = fits_dom & (
                    jnp.arange(NDu) != jnp.maximum(banned_doms[0], -1))
            fs = fits_dom[order_by_agg]
            n_fit = jnp.sum(fs.astype(jnp.int32))
            sel = jnp.mod(lane, jnp.maximum(n_fit, 1)) + 1
            pos = jnp.argmax(fs & (jnp.cumsum(fs.astype(jnp.int32))
                                   == sel))
            target = jnp.where(n_fit > 0, order_by_agg[pos], -1)
        else:
            ids = jnp.where(n.valid & (dom_col >= 0), dom_col, NDu)
            dom_caps = jax.ops.segment_sum(
                c_pipe, ids, num_segments=NDu + 1)[:NDu]  # [ND] replicas
            avail_accel = (free[:, 0] + n.releasing[:, 0]
                           + extra_releasing[:, 0])
            agg_accel = jax.ops.segment_sum(
                jnp.where(n.valid, avail_accel, 0.0), ids,
                num_segments=NDu + 1)[:NDu]
            fits_dom = dom_caps >= jnp.maximum(want0, 1)
            if banned_doms is not None:
                fits_dom = fits_dom & (
                    jnp.arange(NDu) != jnp.maximum(banned_doms[0], -1))
            # spread wavefront lanes across the fitting domains, fullest
            # first: lane 0 takes the binpack choice, lane k the k-th-
            # fullest — otherwise every lane of a chunk fills the same
            # domain and the accept prefix caps at one domain's capacity
            order_dom = jnp.argsort(
                jnp.where(fits_dom, agg_accel, jnp.inf))
            n_fit = jnp.sum(fits_dom.astype(jnp.int32))
            target = order_dom[jnp.mod(lane, jnp.maximum(n_fit, 1))]
            target = jnp.where(jnp.any(fits_dom), target, -1)
        prior_dom = jnp.where(
            jnp.any(already),
            dom_col[jnp.maximum(prior_nodes[jnp.argmax(already)], 0)], -1)
        target = jnp.where(prior_dom >= 0, prior_dom, target)
        # target == -1 (no domain fits) must FAIL the gang, not fall
        # through to nodes that lack the level's label (their dom_col is
        # also -1)
        in_dom = ~has_req | ((target >= 0) & (dom_col == target))
        fit_idle = fit_idle & in_dom
        fit_pipe = fit_pipe & in_dom
        c_pipe = jnp.where(in_dom, c_pipe, 0)
        target_out = jnp.where(has_req, target, -1)
    else:
        target_out = jnp.asarray(-1, jnp.int32)

    if type_tables_u is not None:
        c_idle = jnp.minimum(lane_clamp(c_idle_y[ty], fit_idle), c_pipe)
    else:
        c_idle = jnp.minimum(copies(free, fit_idle), c_pipe)

    if config.dense_feasibility:
        # feasibility spans the node axis (no selectors/filters/domains
        # in the snapshot): a stride-apart cyclic rotation spreads lanes
        # equally well without the per-attempt cumsum
        stride = max(1, N // max(1, config.batch_size))
        tie_jitter = (-1e-4 / N) * jnp.mod(
            jnp.arange(N) - lane * stride, N).astype(jnp.float32)
    else:
        # per-lane tie-break by rank WITHIN the feasible set (see the
        # per-task kernel): spreads equal-scoring nodes across lanes even
        # when selectors/filters/domains confine feasibility to a sliver
        # of the index space (an absolute rotation would collapse every
        # lane onto the same first feasible node there)
        rank_feas = jnp.cumsum(fit_pipe.astype(jnp.int32)) - 1
        tie_jitter = (-1e-4 / N) * jnp.mod(rank_feas - lane, N).astype(
            jnp.float32)                                # [N]

    # ---- scores (one pass; locality band anchored at the best node) -----
    if type_tables_u is not None:
        # hoisted base already holds the plugin bands + soft scores for
        # the lane's type, masked by TYPE feasibility; the lane adds its
        # jitter/bias and re-masks for its domain restriction
        base_u = scores0_y[ty] + tie_jitter
        if score_bias is not None:
            base_u = base_u + score_bias
        scores0 = jnp.where(fit_pipe, base_u, BIG_NEG)  # [N]
    else:
        extra_bands_u = tie_jitter + n.soft_scores[task_class]
        if score_bias is not None:
            extra_bands_u = extra_bands_u + score_bias
        scores0 = score_nodes_for_task(
            n, free, req, fit_idle, fit_pipe, config.placement,
            extra=extra_bands_u)                        # [N]
    if config.preferred_topology:
        best = jnp.argmax(scores0)
        topo_band = jnp.where(
            has_pref & (pref_doms == pref_doms[best]), W_TOPOLOGY, 0.0)
        scores = jnp.where(fit_pipe, scores0 + topo_band, scores0)
    else:
        scores = scores0

    # ---- greedy fill by score order -------------------------------------
    # top_k instead of a full argsort: at most T replicas place and every
    # feasible node holds >= 1 (c_pipe >= 1 where fit), so the T best-
    # scoring nodes are exactly the prefix the full sort would fill —
    # O(N log T) instead of O(N log N) per lane, the hot win at 10k nodes
    k = min(T, N)
    _, order = jax.lax.top_k(scores, k)                 # [k]
    feas_sorted = fit_pipe[order]
    c_sorted = jnp.where(feas_sorted, c_pipe[order], 0)
    want = jnp.minimum(goal if not legacy else tcount, m_gate)
    cum = jnp.cumsum(c_sorted)                          # [k]
    placed_sorted = jnp.clip(want - (cum - c_sorted), 0, c_sorted)
    total_placed = jnp.minimum(cum[-1], want)

    placed_per_node = jnp.zeros((N,), jnp.int32).at[order].add(placed_sorted)
    # new placements land in the first `total_placed` still-unplaced
    # slots, taking their chosen nodes in ASCENDING NODE ORDER: uniform
    # replicas are interchangeable, so the replica->node bijection is a
    # free choice — canonicalizing it on node id (instead of the score
    # order, whose ties cascade from earlier placements' density/
    # availability deltas) keeps the per-task cells bit-identical
    # between the sequential scan and the victim wavefront whenever
    # both pick the same node multiset, and makes binds deterministic
    # under score-input drift generally
    cum_n = jnp.cumsum(placed_per_node)                 # [N]
    elig_rank = jnp.cumsum((task_valid & ~already).astype(jnp.int32)) - 1
    npos = jnp.where(task_valid & ~already, elig_rank, T)   # [T]
    nidx = jnp.minimum(jnp.searchsorted(cum_n, npos, side="right"),
                       N - 1)                           # [T] node id
    placed_t = task_valid & ~already & (npos < total_placed)
    nodes_t = jnp.where(placed_t, nidx, -1)
    # within a node the first c_idle replicas bind now, the rest pipeline
    rank_in_node = npos - (cum_n[nidx] - placed_per_node[nidx])
    pipe_t = placed_t & (rank_in_node >= c_idle[nidx])
    free2 = free - placed_per_node[:, None].astype(free.dtype) * req[None, :]
    # replicas past a node's idle headroom pipeline; the rest bind now
    bind_per_node = jnp.minimum(placed_per_node, c_idle)
    bind_used = bind_per_node[:, None].astype(free.dtype) * req[None, :]
    q_delta = total_placed.astype(free.dtype) * req
    qa2 = q_alloc + anc[:, None] * q_delta[None, :]
    qan2 = q_alloc_np + jnp.where(nonpreempt,
                                  anc[:, None] * q_delta[None, :], 0.0)
    if legacy:
        success = total_placed >= g.min_needed[gang_idx]
    else:
        success = (goal > 0) & (total_placed >= goal)
    if sparse_out:
        # wavefront sparse protocol: a replica's node + pipeline flag
        # fully determine its free/bind deltas (amount = the uniform
        # replica request), so the chunk reconstructs them from
        # (nodes_t, pipe_t) with K-entry scatters instead of carrying
        # dense [N, R] copies per lane through the vmap
        return (qa2, qan2, nodes_t, pipe_t, success)
    dev_t = jnp.full((T,), -1, jnp.int32)
    # extended resources take the per-task path (snapshot builder gates
    # uniform_gangs off when any exist) — pass the pool through untouched
    if ext_free is None:
        ext_free = state.nodes.extended_free
    sub_dom_out = jnp.full((g.s,), -1, jnp.int32).at[0].set(
        target_out.astype(jnp.int32))
    return (free2, device_free, qa2, qan2, nodes_t, dev_t, pipe_t, success,
            bind_used, jnp.zeros_like(device_free), ext_free,
            jnp.zeros_like(ext_free), sub_dom_out)


def _attempt_gang(state: ClusterState, gang_idx: jax.Array,
                  free: jax.Array, device_free: jax.Array,
                  q_alloc: jax.Array, q_alloc_np: jax.Array,
                  num_levels: int, config: AllocateConfig,
                  extra_releasing: jax.Array | None = None,
                  extra_device_releasing: jax.Array | None = None,
                  lane: jax.Array | None = None,
                  chain: jax.Array | None = None,
                  prior_nodes: jax.Array | None = None,
                  quota: jax.Array | None = None,
                  ext_free: jax.Array | None = None,
                  extra_extended_releasing: jax.Array | None = None,
                  topo_tables=None,
                  domain_mask: jax.Array | None = None,
                  score_bias: jax.Array | None = None,
                  sparse_out: bool = False,
                  type_tables_u=None):
    """Try to place one gang; returns tentative post-gang state + success.

    Topology handling (ref ``plugins/topology`` SubsetNodesFn +
    ``topology/job_filtering.go:34``): a *required* level — gang-level
    levels are inherited into every subgroup slot at snapshot build — is
    enforced by the per-subgroup domain locks inside the task kernel: the
    subgroup's first placement picks a domain with aggregate capacity for
    its whole chunk, binpacked fullest-first (``topology/node_scoring.go``
    domain ordering as a score band), and the rest of the subgroup is
    confined to it.  A *preferred* level adds a locality score band
    instead (best-effort).
    """
    g, n = state.gangs, state.nodes
    if extra_releasing is None:
        extra_releasing = jnp.zeros_like(free)
    if extra_device_releasing is None:
        extra_device_releasing = jnp.zeros_like(device_free)
    if lane is None:
        lane = jnp.asarray(0, jnp.int32)
    if chain is None:
        chain = _chain_membership(state.queues.parent, num_levels)

    pl = g.preferred_level[gang_idx]
    has_pref = pl >= 0
    pref_doms = n.topology[:, jnp.maximum(pl, 0)]              # [N]

    if config.uniform_tasks:
        if config.track_devices:
            raise ValueError(
                "uniform_tasks fast path requires track_devices=False")
        in_domain = _attempt_gang_in_domain_uniform
    else:
        in_domain = _attempt_gang_in_domain

    dmask = n.valid if domain_mask is None else (n.valid & domain_mask)

    def run(banned):
        extras = ((topo_tables, sparse_out, type_tables_u)
                  if config.uniform_tasks else ())
        return in_domain(
            state, gang_idx, free, device_free, q_alloc, q_alloc_np,
            num_levels, config, dmask, pref_doms, has_pref,
            extra_releasing, extra_device_releasing, lane, chain,
            prior_nodes, quota, ext_free, extra_extended_releasing,
            banned, score_bias, *extras)

    out = run(None)
    if config.uniform_tasks and sparse_out:
        return out
    if config.subgroup_topology and not config.uniform_tasks:
        # In-cycle retry over the NEXT domain: the aggregate-capacity
        # domain gate stands in for allocateSubGroupSet's per-subset
        # rollback search, so a fragmented domain can pass the gate and
        # fail the fill — one bounded retry with the failed attempt's
        # locked domains banned places the gang in the next-fullest
        # domain within the same cycle instead of waiting one out.
        # The uniform kernel needs no retry: its domain pick counts real
        # per-node replica capacities, so a picked domain always fits.
        # (Under the wavefront vmap this cond lowers to a select that
        # executes both branches — tolerable on the B<=64 per-task path,
        # ruinous on the wide uniform path.)
        success1, sub_dom1 = out[7], out[12]
        retry_ok = ~success1 & jnp.any(sub_dom1 >= 0)
        out = lax.cond(retry_ok, lambda _: run(sub_dom1),
                       lambda _: out, None)
    return out[:12]


def allocate(
    state: ClusterState,
    fair_share: jax.Array,          # f32 [Q, R]  from ops.drf.set_fair_share
    *,
    num_levels: int,
    config: AllocateConfig = AllocateConfig(),
    init: AllocationResult | None = None,
) -> AllocationResult:
    """Run the allocate action over every pending gang.

    Functional equivalent of ``allocate.Execute`` — jit-compatible; all
    shapes static.  ``num_levels`` bounds the queue-hierarchy depth
    (snapshot-known static).  ``init`` continues an in-progress cycle
    (the previous action's commit set).
    """
    g, n, q = state.gangs, state.nodes, state.queues
    G, T = g.g, g.t
    total = state.total_capacity
    B = max(1, min(config.batch_size, G))
    if config.subgroup_topology and not config.uniform_tasks:
        # the per-task kernel's domain segment reduction multiplies lane
        # scratch by the N*L segment count; wide wavefronts exceed TPU
        # scratch limits (observed device faults at B=256, 5k nodes)
        B = min(B, 64)
    if init is None:
        init = init_result(state)

    extra, extra_dev = init.releasing_extra, init.device_releasing_extra
    rel_floor = -(n.releasing + extra) - EPS          # [N, R] free lower bound
    dev_floor = -(n.device_releasing + extra_dev) - EPS
    limit_eff = jnp.where(q.limit <= UNLIMITED + 0.5, jnp.inf, q.limit)
    quota_eff = jnp.where(q.quota <= UNLIMITED + 0.5, jnp.inf, q.quota)

    remaining0 = g.valid & (g.backoff <= 0) & ~init.allocated
    if config.prefilter:
        # whole-gang feasibility over the task-type table: a gang whose
        # min_needed tasks cannot each find ANY node (ignoring cross-task
        # capacity interaction) is hopeless this cycle — at 50k pending
        # gangs this is the difference between attempting everything and
        # attempting only the schedulable frontier.  Cost: [Y, N] for the
        # Y distinct task types, not [G, T, N].
        type_fit = jax.vmap(lambda y: jnp.any(feasible_nodes(
            n, g.type_req[y], g.type_selector[y], g.type_portion[y],
            g.type_mem[y], task_class=g.type_class[y],
            free=n.free + init.releasing_extra,
            device_free=n.device_free + init.device_releasing_extra,
            include_releasing=True)))(
                jnp.arange(g.type_req.shape[0]))          # [Y]
        task_ok = type_fit[g.task_type] & g.task_valid    # [G, T]
        feas = jnp.sum(task_ok.astype(jnp.int32), -1) >= g.min_needed
        pre_dropped = remaining0 & ~feas
        remaining0 = remaining0 & feas
        init = init.replace(
            fit_reason=jnp.where(pre_dropped, 1, init.fit_reason))
    static_rank = None
    if not config.dynamic_order:
        order0 = ordering.job_order_perm(
            g, q, init.queue_allocated, fair_share, total, remaining0)
        static_rank = jnp.zeros((G,), jnp.int32).at[order0].set(
            jnp.arange(G, dtype=jnp.int32))
    else:
        # Dynamic ordering PREDICTS the reference heap's whole pop
        # sequence, hoisted: when pops succeed, queue allocation after a
        # queue's first j pops is exactly qa_start plus those pops'
        # cumulative request — so every gang's AT-POP queue key
        # (over_fs, over_quota, -priority, dominant share) is a static
        # function of the snapshot, and ONE hoisted lexsort reproduces
        # the interleaved pop order the heap's per-pop re-sort would
        # produce.  Chunks then just take the first B remaining gangs of
        # this order (a cumsum compaction — no in-loop sort at all).
        # Divergence from the prediction — placement failures, accept
        # conflicts, elastic re-pushes — is bounded per action (see the
        # fairness-gate note in the chunk) and corrected next cycle.
        below_min = g.running_count < g.min_member
        sjr_perm = jnp.lexsort((
            g.creation_order.astype(jnp.float32),
            -g.priority.astype(jnp.float32),
            (~below_min).astype(jnp.float32)))
        static_job_rank = jnp.zeros((G,), jnp.int32).at[sjr_perm].set(
            jnp.arange(G, dtype=jnp.int32))                   # [G]
        gq0 = jnp.maximum(g.queue, 0)
        # only gangs this action can actually pop contribute to the
        # prediction — backed-off/prefiltered gangs never pop, and
        # already-allocated gangs' requests are in qa0 already
        gang_req_all = jnp.sum(jnp.where(
            (g.task_valid & remaining0[:, None])[:, :, None],
            g.task_req, 0.0), axis=1)                           # [G, R]
        if config.extended:
            # the predicted at-pop queue keys see MIG g-equivalents like
            # the snapshot rollups and the placement queue delta do
            gang_req_all = gang_req_all.at[:, 0].add(jnp.sum(jnp.where(
                g.task_valid & remaining0[:, None],
                g.task_extended @ g.ext_accel, 0.0), axis=1))
        # exclusive per-queue cumulative request along the static job
        # order, O(G·R): queue-major sort, one cumsum, subtract each
        # queue's segment-start prefix (a [G, Q, R] one-hot cumsum
        # would be ~GB-scale at 50k gangs × many queues)
        ord2 = jnp.lexsort((static_job_rank.astype(jnp.float32),
                            gq0.astype(jnp.float32)))
        req2 = gang_req_all[ord2]
        cs_excl = jnp.cumsum(req2, axis=0) - req2               # [G, R]
        qm = gq0[ord2]
        is_first = jnp.concatenate(
            [jnp.ones((1,), bool), qm[1:] != qm[:-1]])
        base = jnp.zeros((q.q + 1,) + req2.shape[1:], req2.dtype).at[
            jnp.where(is_first, qm, q.q)].set(cs_excl)[:q.q]    # [Q, R]
        cum_excl_g = jnp.zeros_like(gang_req_all).at[ord2].set(
            cs_excl - base[qm])                                 # [G, R]
        qa0 = init.queue_allocated
        at_pop = qa0[gq0] + cum_excl_g                          # [G, R]
        pop_fs = jnp.any(at_pop > fair_share[gq0] + EPS, -1)
        pop_qt = jnp.any(at_pop > quota_eff[gq0] + EPS, -1)
        pop_dom = jnp.max(at_pop / jnp.maximum(total, EPS)[None, :], -1)
        nprio_q = -q.priority.astype(jnp.float32)
        pop_order = jnp.lexsort((
            static_job_rank.astype(jnp.float32),
            pop_dom,
            nprio_q[gq0],
            pop_qt.astype(jnp.float32),
            pop_fs.astype(jnp.float32)))                        # [G]

    chain = _chain_membership(q.parent, num_levels)

    L = n.topology.shape[1]
    ND = n.n * L
    hoist_topo = config.uniform_tasks and config.subgroup_topology
    if hoist_topo:
        # domain-id → topology level (the global dense id space spans
        # all levels; each id belongs to exactly one)
        level_of_dom = jnp.full((ND + 1,), -1, jnp.int32)
        for lvl in range(L):
            ids_l = jnp.where(n.valid & (n.topology[:, lvl] >= 0),
                              n.topology[:, lvl], ND)
            level_of_dom = level_of_dom.at[ids_l].set(lvl)
        level_of_dom = level_of_dom[:ND]

    if hoist_topo:
        Y = g.type_req.shape[0]
        #: node → dense domain id per level (static; junk ND)
        dom_of = jnp.stack([
            jnp.where(n.valid & (n.topology[:, lvl] >= 0),
                      n.topology[:, lvl], ND)
            for lvl in range(L)])                             # [L, N]
        #: static (capacity-independent + build-capacity) feasibility —
        #: free only SHRINKS within allocate, so a node infeasible at
        #: build never recovers and the live replica count alone tracks
        #: capacity afterwards
        zero_s = jnp.zeros((), n.free.dtype)
        fp_build = jax.vmap(lambda y: feasible_nodes_dual(
            n, g.type_req[y], g.type_selector[y], zero_s, zero_s,
            free=init.free, device_free=init.device_free,
            extra_releasing=extra, extra_device_releasing=extra_dev,
            devices=False, task_class=g.type_class[y])[1])(
                jnp.arange(Y)) & n.valid[None, :]             # [Y, N]

        def _replicas_at(avail_rows):
            """Replica counts per type for the given avail rows [K, R]
            (the capacity part of caps_of_type, recomputable per touched
            node without the feasibility machinery)."""
            def per_type(y):
                req = g.type_req[y]
                c = jnp.where(req[None, :] > EPS,
                              (avail_rows + EPS)
                              / jnp.maximum(req, EPS)[None, :], jnp.inf)
                return jnp.clip(jnp.floor(jnp.min(c, axis=-1)),
                                0.0, 1e9).astype(jnp.int32)
            return jax.vmap(per_type)(jnp.arange(Y))          # [Y, K]

    def topo_tables_build(free):
        """Initial domain tables for the uniform+topology path: per-TYPE
        replica capacity per node (``c_y``, junk column N) and per
        domain (``dom_caps_y``), plus the per-domain aggregate accel.
        Built ONCE per action; chunks maintain all three incrementally —
        only nodes touched by committed placements change, so the
        full per-chunk rebuild (per-type feasibility + divisions + Y·L
        node-axis reductions, the dominant wavefront cost at 5k nodes ×
        3 levels) reduces to placement-sized gathers and L sparse
        scatter-adds."""
        avail = free + n.releasing + extra
        c_all = _replicas_at(avail)                           # [Y, N]
        c_all = jnp.where(fp_build, c_all, 0)
        c_y = jnp.concatenate(
            [c_all, jnp.zeros((Y, 1), jnp.int32)], axis=1)    # [Y, N+1]

        def caps_of_type(c_row):
            caps = jnp.zeros((ND + 1,), jnp.int32)
            for lvl in range(L):
                caps = caps.at[dom_of[lvl]].add(c_row)
            return caps[:ND]

        dom_caps_y = jax.vmap(caps_of_type)(c_all)            # [Y, ND]
        agg = jnp.zeros((ND + 1,), free.dtype)
        for lvl in range(L):
            agg = agg.at[dom_of[lvl]].add(
                jnp.where(n.valid, avail[:, 0], 0.0))
        return dom_caps_y, agg[:ND], c_y

    def topo_tables_update(dom_caps_y, agg, c_y, free_new,
                           take, cand, nodes_b):
        """Incremental maintenance after a chunk's commit: recompute
        replica counts for the touched nodes only (duplicate touches
        write identical values, so scatter-set is well defined), then
        push the per-node deltas into the domain tables."""
        B_, T_ = nodes_b.shape
        placed = take[:, None] & (nodes_b >= 0)               # [B, T]
        idxs = jnp.where(placed, nodes_b, n.n).ravel()        # [K] junk N
        isafe = jnp.minimum(idxs, n.n - 1)
        avail_rows = (free_new + n.releasing + extra)[isafe]  # [K, R]
        c_new = jnp.where(fp_build[:, isafe],
                          _replicas_at(avail_rows), 0)        # [Y, K]
        c_new = jnp.where((idxs < n.n)[None, :], c_new, 0)
        # per-node delta via a junk-columned scratch: duplicates carry
        # the SAME c_new (same node), so .set is deterministic
        c_at = jnp.zeros((Y, n.n + 1), jnp.int32).at[:, idxs].set(c_new)
        touched = jnp.zeros((n.n + 1,), bool).at[idxs].set(True)
        d_node = jnp.where(touched[None, :], c_at - c_y, 0)   # [Y, N+1]
        c_y = jnp.where(touched[None, :], c_at, c_y)
        # accel delta per node: one replica consumes its type's accel —
        # exact for the aggregate regardless of type mix
        ty = g.task_type[jnp.minimum(cand, G - 1), 0]         # [B]
        req0 = g.type_req[ty, 0]                              # [B]
        accel = jnp.where(placed,
                          jnp.broadcast_to(req0[:, None], (B_, T_)),
                          0.0).ravel()
        for lvl in range(L):
            dom_caps_y = dom_caps_y.at[:, dom_of[lvl]].add(
                d_node[:, :n.n], mode="drop")
            dom = jnp.where(idxs < n.n, dom_of[lvl][isafe], ND)
            agg = agg.at[jnp.minimum(dom, ND - 1)].add(
                jnp.where(dom < ND, -accel, 0.0))
        return dom_caps_y, agg, c_y

    # in-cycle exclusion-term tracking (config.anti_groups): dense
    # domain id per (node, level) with per-node slots appended for the
    # hostname granularity; AD+1 = junk slot (see anti_domain_tables)
    AD = ND + n.n
    if config.anti_groups:
        dom_static, TA = anti_domain_tables(state)

    # the uniform kernel's lanes emit placements only (nodes/pipeline
    # flags); the chunk reconstructs capacity deltas with K-entry sparse
    # scatters instead of carrying dense [B, N, R] tensors through the
    # vmap and the accept cumsums — the dominant HBM traffic at
    # 10k nodes x 256 lanes
    sparse = (config.uniform_tasks and not config.extended
              and not config.track_devices and config.sparse_wavefront
              # measured: sparse lanes lose to the dense path when the
              # required-topology domain machinery is active (the
              # hoisted domain caps already carry the dense tensors)
              and not config.subgroup_topology)
    # chunk-hoisted per-TYPE tables for the uniform kernel: feasibility,
    # raw replica counts, and plugin-band scores depend only on the
    # lane's task TYPE and chunk-start free — computing them [Y, N] once
    # per chunk (instead of [B, N] per lane under the vmap) leaves only
    # gathers + tie-jitter + top-k as per-lane node-axis work
    Yu = g.type_req.shape[0]
    hoist_types = (config.uniform_tasks and Yu <= B
                   and config.hoist_type_tables)

    def build_type_tables(free_c, dev_c):
        zero_t = jnp.zeros((), free_c.dtype)

        def per_type(y):
            fi, fp = feasible_nodes_dual(
                n, g.type_req[y], g.type_selector[y], zero_t, zero_t,
                free=free_c, device_free=dev_c, extra_releasing=extra,
                extra_device_releasing=extra_dev, devices=False,
                task_class=g.type_class[y])
            reqy = g.type_req[y]
            cp = _replica_count(free_c + n.releasing + extra, reqy, fp)
            ci = _replica_count(free_c, reqy, fi)
            sc = score_nodes_for_task(
                n, free_c, reqy, fi, fp, config.placement,
                extra=n.soft_scores[g.type_class[y]])
            return fi, fp, ci, cp, sc

        return jax.vmap(per_type)(jnp.arange(Yu))

    def attempt_one(gi, lane, prior, quota, dmask, free, dev, qa, qan,
                    ext, topo_tables, utables):
        return _attempt_gang(state, gi, free, dev, qa, qan, num_levels,
                             config, extra, extra_dev, lane, chain,
                             prior_nodes=prior, quota=quota, ext_free=ext,
                             extra_extended_releasing=init.
                             extended_releasing_extra,
                             topo_tables=topo_tables,
                             domain_mask=dmask, sparse_out=sparse,
                             type_tables_u=utables)

    def cond(carry):
        return jnp.any(carry[1]) & (carry[4] > 0)

    def chunk(carry):
        res, remaining, q_attempts, failed_sig, fuel = carry[:5]
        if hoist_topo:
            dom_caps_y, dom_agg, c_y_store = carry[5:8]
        free, dev, qa, qan = (res.free, res.device_free, res.queue_allocated,
                              res.queue_allocated_nonpreemptible)
        if config.dynamic_order:
            # first B remaining gangs of the hoisted pop order (cumsum
            # compaction — no in-loop sort), with the LIVE over-fs gate:
            # while ANY under-fair-share queue still has remaining
            # gangs, over-fs queues (incl. re-pushed elastic gangs whose
            # quorum already drove their queue over) sit the chunk out —
            # the reference heap's tier-1 treatment
            over_fs_live = jnp.any(
                qa > fair_share + EPS, axis=-1)                   # [Q]
            elig = remaining & ~over_fs_live[jnp.maximum(g.queue, 0)]
            elig = jnp.where(jnp.any(elig), elig, remaining)
            flags = elig[pop_order]                               # [G]
            rnk = jnp.cumsum(flags.astype(jnp.int32)) - 1
            pos = jnp.where(flags & (rnk < B), rnk, B)
            cand = jnp.full((B + 1,), G, jnp.int32).at[pos].set(
                pop_order)[:B]
            cand_valid = jnp.zeros((B + 1,), bool).at[pos].set(
                True)[:B]
            # junk slots KEEP the out-of-range index G: their commit
            # scatters drop (out-of-bounds) instead of racing a real
            # gang's row; gathers at G clamp to harmless reads that
            # cand_valid discards
        else:
            # frozen keys, retired gangs pushed last
            composite = static_rank + jnp.where(remaining, 0, 2 * G)
            cand = jnp.argsort(composite)[:B]                     # [B]
            cand_valid = remaining[cand]
        if config.queue_depth is not None:
            # per-queue attempt budget (ref QueueDepthPerAction): a
            # candidate is eligible while its queue's prior attempts plus
            # its rank among earlier same-queue candidates of this chunk
            # stay under the depth.  Over-budget candidates simply sit out
            # the chunk; fully exhausted queues drain below.
            qc = g.queue[cand]                                    # [B]
            earlier = (jnp.arange(B)[None, :] < jnp.arange(B)[:, None])
            rank_q = jnp.sum(
                (qc[None, :] == qc[:, None]) & earlier
                & cand_valid[None, :], axis=1)                    # [B]
            cand_valid = cand_valid & (
                q_attempts[qc] + rank_q < config.queue_depth)

        # re-push protocol (ref allocate.go:102-104): a below-quorum gang
        # attempts its whole remaining quorum chunk; an at/above-quorum
        # gang scales up ONE task per attempt and re-enters the heap, so
        # elastic growth interleaves fairly with other queues' jobs.
        prior_b = res.placements[cand]                            # [B, T]
        placed_cnt = jnp.sum((prior_b >= 0).astype(jnp.int32), -1)
        need = g.min_needed[cand]
        quota_b = jnp.where(placed_cnt < need, need - placed_cnt, 1)

        # NOTE on mid-action fairness drift: the hoisted pop order is
        # exact while pops succeed; placement failures and accept
        # conflicts can let a queue fall behind its predicted
        # allocation, after which the frozen order may favour it
        # slightly ahead of the live heap for the rest of the action —
        # bounded by the failed requests, corrected next cycle.  (A live
        # per-chunk heap-key lookahead was tried and reverted: its
        # per-chunk op cost exceeded the entire sort it replaced.)

        # independent attempts against chunk-start state (the vmapped
        # replacement for the reference's one-job-at-a-time hot loop);
        # each lane's feasible-rank tie-break starts at its own offset so
        # a chunk of identical gangs fans out over equal-scoring nodes
        # instead of colliding on one
        lanes = jnp.arange(B, dtype=jnp.int32)
        ext = res.extended_free
        if hoist_topo:
            # live caps (incrementally maintained), live fullest-first
            # order (one single-key argsort per chunk)
            order_by_agg = jnp.argsort(
                jnp.where(level_of_dom >= 0, dom_agg, jnp.inf))
            tables = (dom_caps_y, level_of_dom, order_by_agg)
        else:
            tables = None
        if config.anti_groups:
            # a lane may not use domains already claimed in any of its
            # avoid rows, and only one side of a conflicting pair may
            # land per chunk (the rest conflict-retry against the
            # updated table)
            dmask_b = ~anti_forbid_nodes(state, res.anti_used,
                                         dom_static, cand)       # [B, N]
            dup_b = anti_defer_lanes(state, cand, cand_valid)
            if config.attract_groups:
                # a lane with need rows is confined to claimed domains;
                # one whose unclaimed need an earlier lane would mark
                # retries next chunk against the updated table
                dmask_b = dmask_b & attract_allow_nodes(
                    state, res.anti_used, dom_static, cand)
                dup_b = dup_b | attract_defer_lanes(
                    state, cand, cand_valid, res.anti_used)
        else:
            dmask_b = None
            dup_b = jnp.zeros((B,), bool)
        dmask_ax = None if dmask_b is None else 0
        if dmask_b is None:
            dmask_b = n.valid
        utables = build_type_tables(free, dev) if hoist_types else None
        if sparse:
            (qa2_b, qan2_b, nodes_b, pipe_b, succ_b) = \
                jax.vmap(attempt_one,
                         in_axes=(0, 0, 0, 0, dmask_ax, None, None, None,
                                  None, None, None, None))(
                    cand, lanes, prior_b, quota_b, dmask_b, free, dev, qa,
                    qan, ext, tables, utables)
            devt_b = jnp.full((B, T), -1, jnp.int32)
        else:
            (free2_b, dev2_b, qa2_b, qan2_b, nodes_b, devt_b, pipe_b,
             succ_b, bind_b, devbind_b, ext2_b, extbind_b) = \
                jax.vmap(attempt_one,
                         in_axes=(0, 0, 0, 0, dmask_ax, None, None, None,
                                  None, None, None, None))(
                    cand, lanes, prior_b, quota_b, dmask_b, free, dev, qa,
                    qan, ext, tables, utables)
        # a same-group duplicate lane is CONFLICT-rejected (retries next
        # chunk), never counted as a genuine fit failure
        succ_all = succ_b & cand_valid
        succ_b = succ_all & ~dup_b

        ok = succ_b[:, None, None]
        d_qa = jnp.where(ok, qa2_b - qa, 0.0)                     # [B, Q, R]
        d_qan = jnp.where(ok, qan2_b - qan, 0.0)

        # maximal order-prefix whose cumulative claims still fit.  Deltas
        # are non-negative, so the per-prefix feasibility flags are
        # monotone and the accept mask IS the prefix mask.
        cum_qa = jnp.cumsum(d_qa, axis=0)
        cum_qan = jnp.cumsum(d_qan, axis=0)
        if sparse:
            # sparse prefix test: each accepted replica claims exactly
            # its gang's uniform request on its node, so sort the K=B*T
            # placement entries by node (stable -> lane-major within a
            # node), segment-cumsum the claims, and the first lane whose
            # cumulative claim overruns a node pool bounds the prefix.
            req_b = g.task_req[jnp.minimum(cand, G - 1), 0]       # [B, R]
            ent_ok = succ_b[:, None] & (nodes_b >= 0)             # [B, T]
            first_bad, node_e, lane_e = sparse_accept_first_bad(
                nodes_b, ent_ok, pipe_b, req_b, free,
                free + n.releasing + extra, n.n)
            prefix_ok = jnp.arange(B) < first_bad                 # [B]
        else:
            d_free = jnp.where(ok, free - free2_b, 0.0)           # [B, N, R]
            d_bind = jnp.where(ok, bind_b, 0.0)                   # [B, N, R]
            cum_free = jnp.cumsum(d_free, axis=0)
            cum_bind = jnp.cumsum(d_bind, axis=0)
            ok_node = jnp.all(free[None] - cum_free >= rel_floor[None],
                              axis=(1, 2))                        # [B]
            # bind-now claims must collectively fit the chunk-start
            # *idle* pool: each lane computed its pipelined flags against
            # chunk-start free, so without this a later lane could bind
            # immediately onto capacity another lane just consumed
            # (capacity that is really still held by terminating pods).
            # Rejected lanes retry next chunk and re-derive their flags
            # against the updated pool.
            ok_bind = jnp.all(
                cum_bind <= jnp.maximum(free[None], 0.0) + EPS,
                axis=(1, 2))                                      # [B]
            prefix_ok = ok_node & ok_bind
        # capacity gates re-checked jointly; queues untouched by the
        # chunk (zero delta) are exempt — they may legitimately sit over
        # limit from pre-existing allocation
        ok_qa = jnp.all((qa[None] + cum_qa <= limit_eff[None] + EPS)
                        | (cum_qa <= EPS), axis=(1, 2))
        ok_qan = jnp.all((qan[None] + cum_qan <= quota_eff[None] + EPS)
                         | (cum_qan <= EPS), axis=(1, 2))
        accept = prefix_ok & ok_qa & ok_qan                       # [B]
        if config.extended:
            d_ext = jnp.where(ok, ext - ext2_b, 0.0)              # [B, N, E]
            d_extbind = jnp.where(ok, extbind_b, 0.0)
            cum_ext = jnp.cumsum(d_ext, axis=0)
            cum_extbind = jnp.cumsum(d_extbind, axis=0)
            ext_floor = -(n.extended_releasing[None]
                          + init.extended_releasing_extra[None]) - EPS
            accept = accept & jnp.all(
                ext[None] - cum_ext >= ext_floor, axis=(1, 2))
            accept = accept & jnp.all(
                cum_extbind <= jnp.maximum(ext[None], 0.0) + EPS,
                axis=(1, 2))
        if config.track_devices:
            d_dev = jnp.where(ok, dev - dev2_b, 0.0)              # [B, N, D]
            d_devbind = jnp.where(ok, devbind_b, 0.0)
            cum_dev = jnp.cumsum(d_dev, axis=0)
            cum_devbind = jnp.cumsum(d_devbind, axis=0)
            accept = accept & jnp.all(
                dev[None] - cum_dev >= dev_floor[None], axis=(1, 2))
            accept = accept & jnp.all(
                cum_devbind <= jnp.maximum(dev[None], 0.0) + EPS,
                axis=(1, 2))

        take = succ_b & accept
        w = take.astype(free.dtype)
        if sparse:
            take_e = take[lane_e] & ent_ok.ravel()                # [K]
            upd = jnp.zeros((n.n + 1, free.shape[1]), free.dtype).at[
                node_e].add(jnp.where(take_e[:, None],
                                      req_b[lane_e], 0.0),
                            mode="drop")
            free = free - upd[:n.n]
        else:
            free = free - jnp.einsum("b,bnr->nr", w, d_free)
        qa = qa + jnp.einsum("b,bqr->qr", w, d_qa)
        qan = qan + jnp.einsum("b,bqr->qr", w, d_qan)
        if config.track_devices:
            dev = dev - jnp.einsum("b,bnd->nd", w, d_dev)
        if config.extended:
            ext = ext - jnp.einsum("b,bne->ne", w, d_ext)

        nodes_b = jnp.where(take[:, None], nodes_b, -1)
        devt_b = jnp.where(take[:, None], devt_b, -1)
        pipe_b = jnp.where(take[:, None], pipe_b, False)
        new_cnt = jnp.sum((nodes_b >= 0).astype(jnp.int32), -1)   # [B]
        total_cnt = placed_cnt + new_cnt
        valid_cnt = jnp.sum(g.task_valid[cand].astype(jnp.int32), -1)
        # done: the gang is whole (take, nothing left to scale up), or the
        # attempt failed (failure is final — capacity only shrinks).
        # Successful partial gangs re-enter the heap (re-push); conflict-
        # rejected successes (incl. same-anti-group duplicates, whose
        # succ_b was cleared above) retry next chunk.
        done_b = cand_valid & ((take & (total_cnt >= valid_cnt))
                               | ~(succ_b | dup_b))
        fail_b = cand_valid & ~(succ_b | dup_b)
        # a scale-up failure of an already-quorate gang is not a fit
        # failure of the gang (its quorum stands)
        fail_fresh = fail_b & (placed_cnt == 0)
        res = res.replace(
            fit_reason=res.fit_reason.at[cand].set(
                jnp.where(fail_fresh, 3,
                          jnp.where(take, 0, res.fit_reason[cand]))),
        )
        # merge this attempt's new placements over prior attempts'
        new_t = nodes_b >= 0                                      # [B, T]
        res = res.replace(
            free=free, device_free=dev, queue_allocated=qa,
            queue_allocated_nonpreemptible=qan,
            extended_free=ext,
            placements=res.placements.at[cand].set(
                jnp.where(new_t, nodes_b, res.placements[cand])),
            placement_device=res.placement_device.at[cand].set(
                jnp.where(new_t, devt_b, res.placement_device[cand])),
            pipelined=res.pipelined.at[cand].set(
                jnp.where(new_t, pipe_b, res.pipelined[cand])),
            allocated=res.allocated.at[cand].set(
                res.allocated[cand] | (take & (total_cnt >= need))),
            attempted=res.attempted.at[cand].set(
                res.attempted[cand] | cand_valid),
        )
        remaining = remaining.at[cand].set(remaining[cand] & ~done_b)
        if config.queue_depth is not None:
            # retired lanes consume their queue's budget (conflict-
            # rejected lanes re-attempt, so they count only once)
            q_attempts = q_attempts + jax.ops.segment_sum(
                done_b.astype(jnp.int32), g.queue[cand],
                num_segments=q.q)
            remaining = remaining & (
                q_attempts[g.queue] < config.queue_depth)
        if config.signature_skip:
            # one quorum-attempt failure retires every equivalent gang —
            # the signature groups (queue, task types, quorum,
            # constraints).  Scale-up failures of quorate gangs don't
            # poison the signature: equivalents may be at earlier stages.
            failed_sig = failed_sig.at[g.sig[cand]].max(fail_fresh)
            skip_now = remaining & failed_sig[g.sig]
            res = res.replace(
                fit_reason=jnp.where(skip_now, 2, res.fit_reason))
            remaining = remaining & ~skip_now
        if config.anti_groups:
            # taken lanes claim their placements' domains in their mark
            # rows (junk row/column absorb unused slots)
            res = res.replace(anti_used=anti_mark_placements(
                state, res.anti_used, dom_static, cand, nodes_b, take))
        out = (res, remaining, q_attempts, failed_sig, fuel - 1)
        if hoist_topo:
            dom_caps_y, dom_agg, c_y_store = topo_tables_update(
                dom_caps_y, dom_agg, c_y_store, res.free,
                take, cand, nodes_b)
            out = out + (dom_caps_y, dom_agg, c_y_store)
        return out

    # fuel: every chunk either retires ≥1 remaining gang (the first
    # remaining gang in order always lands in the accept prefix, or its
    # exhausted queue drains from `remaining`) or places ≥1 new task of a
    # re-pushed gang, so G*(T+1) chunks is a hard upper bound; the common
    # case is ceil(G/B) + elastic re-pushes + a few conflicts.
    carry0 = (init, remaining0, jnp.zeros((q.q,), jnp.int32),
              jnp.zeros((G,), bool), jnp.asarray(G * (T + 1), jnp.int32))
    if hoist_topo:
        carry0 = carry0 + topo_tables_build(init.free)
    out = lax.while_loop(cond, chunk, carry0)
    return out[0]


@functools.partial(jax.jit, static_argnames=("num_levels", "config"))
def allocate_jit(state: ClusterState, fair_share: jax.Array, *,
                 num_levels: int, config: AllocateConfig = AllocateConfig(),
                 init: AllocationResult | None = None) -> AllocationResult:
    return allocate(state, fair_share, num_levels=num_levels, config=config,
                    init=init)


# kai-wire compile watcher: attribute every cache miss of this entry to
# its (entry, abstract-shape-signature) pair (runtime/compile_watch.py)
allocate_jit = compile_watch.watch("allocate", allocate_jit)

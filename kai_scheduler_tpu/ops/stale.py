"""stalegangeviction — evict gangs that fell below minMember.

Reference (``actions/stalegangeviction/stalegangeviction.go:29-60``): a
gang whose active pod count dropped under ``minMember`` after it started
(pods failed / were deleted) is given a staleness grace period (default
60s, ``cmd/scheduler/app/options/options.go:34``); past it, the whole
remaining gang is evicted so its resources return to the pool and the
group can be rescheduled atomically.

Staleness bookkeeping is host-side (the podgroup controller stamps
``PodGroup.stale_since``); the snapshot carries per-gang ``stale_s`` and
``running_count`` so the decision itself is one broadcast expression.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..state.cluster_state import ClusterState
from .allocate import AllocationResult


def stale_gangs(state: ClusterState, grace_s: float) -> jax.Array:
    """bool [G] — gangs to evict wholesale this cycle."""
    g = state.gangs
    return ((g.stale_s >= grace_s)
            & (g.running_count > 0)
            & (g.running_count < g.min_member))


def stale_gang_eviction(
    state: ClusterState,
    result: AllocationResult,
    *,
    grace_s: float = 60.0,
    num_levels: int = 2,
) -> AllocationResult:
    """Mark every surviving pod of a stale gang as a victim and return
    their resources to the commit set's free pool / queue accounting."""
    from .victims import _chain_membership, freed_by_mask

    r = state.running
    G = state.gangs.g
    stale = stale_gangs(state, grace_s)                       # [G]
    gang_of_pod = jnp.where(r.gang >= 0, r.gang, G)
    pod_stale = jnp.concatenate(
        [stale, jnp.zeros((1,), bool)])[jnp.minimum(gang_of_pod, G)]
    victims = (r.valid & ~r.releasing & (r.node >= 0) & pod_stale
               & ~result.victim)

    chain = _chain_membership(state.queues.parent, num_levels)
    freed_nodes, freed_dev, freed_q, freed_q_np, freed_ext = freed_by_mask(
        state, victims, chain)
    # the evicted pods' capacity is releasing (they have not terminated) —
    # tasks placed on it must pipeline, so it joins releasing_extra
    return result.replace(
        victim=result.victim | victims,
        releasing_extra=result.releasing_extra + freed_nodes,
        device_releasing_extra=result.device_releasing_extra + freed_dev,
        extended_releasing_extra=(result.extended_releasing_extra
                                  + freed_ext),
        queue_allocated=jnp.maximum(result.queue_allocated - freed_q, 0.0),
        queue_allocated_nonpreemptible=jnp.maximum(
            result.queue_allocated_nonpreemptible - freed_q_np, 0.0),
    )

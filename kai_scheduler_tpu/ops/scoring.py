"""Node-order scoring — the NodeOrderFn plugin family, tensorized.

The reference sums per-plugin scores for every candidate node in a
goroutine fan-out (``framework/session.go:234-263`` ``OrderedNodesByTask``)
then picks the best (``FittingNode``).  Here each plugin is a pure
function producing a ``[..., N]`` score tensor and composition is a
weighted sum — one fused XLA kernel per cycle instead of pods×nodes
goroutine hops.

Score bands follow ``plugins/scores/scores.go:7-14`` so plugin priorities
compose exactly as in the reference: a higher band always dominates all
lower bands combined (each band's raw score is ≤ MAX_HIGH_DENSITY = 9).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp

from ..apis.types import RESOURCE_ACCEL, RESOURCE_CPU
from ..state.cluster_state import NodeState

# ref plugins/scores/scores.go
MAX_HIGH_DENSITY = 9.0
W_RESOURCE_TYPE = 10.0
W_AVAILABILITY = 100.0
W_GPU_SHARING = 1_000.0
W_TOPOLOGY = 10_000.0
W_K8S_PLUGINS = 100_000.0
W_NOMINATED = 1_000_000.0
#: wavefront-only band (no reference counterpart): a victim-action lane
#: prefers nodes freed by ITS OWN victim range — the sequential solver
#: implicitly does this (each preemptor is placed right after its own
#: victims flip to Releasing, so the newly-available capacity IS its
#: victims').  Slotted strictly between the binpack/spread density band
#: (raw <= MAX_HIGH_DENSITY) and W_RESOURCE_TYPE, so it breaks the
#: cross-lane argmax collisions that serialized the victim wavefront
#: WITHOUT overriding any reference plugin band (a CPU-only preemptor
#: still prefers a CPU-only node over its own freed accel node).
W_OWN_FREED = 9.5

BIG_NEG = -1e30


@dataclasses.dataclass(frozen=True)
class PlacementConfig:
    """binpack vs spread per resource type — ref nodeplacement plugin args
    (``conf_util/scheduler_conf_util.go:54-57``, default binpack) and
    SchedulingShard.PlacementStrategy.
    """

    binpack_accel: bool = True
    binpack_cpu: bool = True
    #: gpupack vs gpuspread at the device granularity: pack puts fractions
    #: on the most-used fitting device, spread on the least-used
    device_pack: bool = True
    #: the scoring plugin tiers (registry names, ordered; ref the default
    #: plugin list in ``conf_util/scheduler_conf_util.go:40-60``) — a
    #: config string reorders/disables plugins without code edits via
    #: ``plugins.parse_tiers``
    tiers: tuple[str, ...] = ("nodeplacement", "resourcetype",
                              "nodeavailability")


def pick_device(device_row: jax.Array,       # f32 [D] free share per device
                portion: jax.Array,          # f32 []
                *, pack: bool) -> jax.Array:
    """Choose the device for a fractional task on one node — the
    GpuOrderFn (``plugins/gpupack/gpupack.go`` / ``gpuspread``): pack
    prefers the most-used device that still fits, spread the least-used.
    Returns i32 device index (undefined when nothing fits — callers mask).
    """
    fits = device_row >= portion - 1e-6
    if pack:
        key = jnp.where(fits, device_row, jnp.inf)
        return jnp.argmin(key)
    key = jnp.where(fits, device_row, -jnp.inf)
    return jnp.argmax(key)


def gpu_sharing_score(
    device_free: jax.Array,    # f32 [N, D]
    portion_n: jax.Array,      # f32 [..., N]  per-node effective portion
    is_frac: jax.Array,        # bool [...]
) -> jax.Array:
    """gpusharingorder plugin: +W_GPU_SHARING on nodes where the fraction
    can join an already-shared (partially used) device, keeping whole
    devices free for whole-device tasks."""
    partially_used = (device_free > 1e-6) & (device_free < 1.0 - 1e-6)
    shared_fit = jnp.any(
        partially_used & (device_free >= portion_n[..., None] - 1e-6),
        axis=-1)
    return jnp.where(is_frac[..., None] & shared_fit, W_GPU_SHARING, 0.0)


def density_score(
    non_allocated: jax.Array,  # f32 [N]   allocatable - used  (free + releasing)
    allocatable: jax.Array,    # f32 [N]
    fit_mask: jax.Array,       # bool [..., N]  candidate nodes per task
    *,
    binpack: bool,
) -> jax.Array:
    """Binpack/spread score in [0, MAX_HIGH_DENSITY] — ref
    ``nodeplacement/pack.go`` ``getScoreOfCurrentNode``: normalize each
    node's non-allocated amount into the [min, max] range over *fitting*
    nodes that have the resource at all; binpack rewards fuller nodes,
    spread emptier ones.  min==max degenerates to max score for all.
    """
    has_res = allocatable > 0
    cand = fit_mask & has_res
    big = jnp.asarray(jnp.finfo(non_allocated.dtype).max)
    mn = jnp.min(jnp.where(cand, non_allocated, big), axis=-1, keepdims=True)
    mx = jnp.max(jnp.where(cand, non_allocated, -big), axis=-1, keepdims=True)
    span = mx - mn
    frac = jnp.where(span > 0, (non_allocated - mn) / jnp.maximum(span, 1e-30), 0.0)
    raw = jnp.where(span > 0, (1.0 - frac) if binpack else frac, 1.0)
    return jnp.where(cand, MAX_HIGH_DENSITY * raw, 0.0)


def placement_score(
    nodes: NodeState,
    free: jax.Array,          # f32 [N, R]  current free (mid-allocation)
    task_req: jax.Array,      # f32 [..., R]
    fit_mask: jax.Array,      # bool [..., N]
    config: PlacementConfig = PlacementConfig(),
) -> jax.Array:
    """nodeplacement plugin: density score on the task's dominant resource
    type — accel nodes scored by accel density for accel tasks, cpu density
    for cpu-only tasks (ref ``nodeplacement/nodeplacement.go`` jobType
    switch).
    """
    non_alloc = free + nodes.releasing
    is_accel_task = task_req[..., RESOURCE_ACCEL] > 0
    accel_s = density_score(
        non_alloc[:, RESOURCE_ACCEL], nodes.allocatable[:, RESOURCE_ACCEL],
        fit_mask, binpack=config.binpack_accel)
    cpu_s = density_score(
        non_alloc[:, RESOURCE_CPU], nodes.allocatable[:, RESOURCE_CPU],
        fit_mask, binpack=config.binpack_cpu)
    return jnp.where(is_accel_task[..., None], accel_s, cpu_s)


def resource_type_score(
    nodes: NodeState,
    task_req: jax.Array,      # f32 [..., R]
) -> jax.Array:
    """resourcetype plugin (``plugins/resourcetype``): +W_RESOURCE_TYPE when
    a CPU-only task lands on a CPU-only node, keeping accel nodes clear for
    accel work.
    """
    cpu_only_task = task_req[..., RESOURCE_ACCEL] <= 0
    cpu_only_node = nodes.allocatable[:, RESOURCE_ACCEL] <= 0
    return jnp.where(
        cpu_only_task[..., None] & cpu_only_node, W_RESOURCE_TYPE, 0.0)


def availability_score(
    idle_fit: jax.Array,      # bool [..., N]  fits on idle (not releasing) res
) -> jax.Array:
    """nodeavailability plugin: +W_AVAILABILITY when the task fits on idle
    resources now (vs only after terminating pods release) — biases toward
    immediate binds over pipelined ones.
    """
    return jnp.where(idle_fit, W_AVAILABILITY, 0.0)


def compose_scores(
    fit_mask: jax.Array,       # bool [..., N]  hard feasibility (pipeline incl.)
    *components: jax.Array,    # f32 [..., N] already weighted into their bands
) -> jax.Array:
    """Sum plugin bands and mask infeasible nodes to -inf — equivalent of
    the per-node score accumulation in ``session.go:243-262``.
    """
    total = jnp.zeros_like(fit_mask, dtype=jnp.float32)
    for c in components:
        total = total + c
    return jnp.where(fit_mask, total, BIG_NEG)


def score_nodes_for_task(
    nodes: NodeState,
    free: jax.Array,           # f32 [N, R]
    task_req: jax.Array,       # f32 [..., R]
    fit_idle: jax.Array,       # bool [..., N]
    fit_pipeline: jax.Array,   # bool [..., N]
    config: PlacementConfig = PlacementConfig(),
    extra: jax.Array | None = None,   # e.g. topology band, [..., N]
) -> jax.Array:
    """The configured scoring stack — ``config.tiers`` selects and orders
    registered score plugins (default mirrors the reference's default
    tiers, ``conf_util/scheduler_conf_util.go``).  Returns f32 [..., N]
    with infeasible nodes at BIG_NEG.
    """
    from ..plugins import ScoreContext, compose
    ctx = ScoreContext(nodes=nodes, free=free, task_req=task_req,
                       fit_idle=fit_idle, fit_pipe=fit_pipeline,
                       placement=config)
    comps = [compose(ctx, config.tiers)]
    if extra is not None:
        comps.append(extra)
    return compose_scores(fit_pipeline, *comps)


# ---------------------------------------------------------------------------
# Builtin plugin registrations (ref plugins/factory.go:47-75 entries that
# score at node granularity; device-granularity and cross-attempt bands —
# gpusharingorder, topology, nominatednode, k8s soft scores — are composed
# by the allocation kernel as `extra` since they need per-attempt state)
# ---------------------------------------------------------------------------

def _register_builtins() -> None:
    from ..plugins import register_score_plugin

    @register_score_plugin("nodeplacement")
    def _nodeplacement(ctx):
        return placement_score(ctx.nodes, ctx.free, ctx.task_req,
                               ctx.fit_pipe, ctx.placement)

    @register_score_plugin("resourcetype")
    def _resourcetype(ctx):
        return resource_type_score(ctx.nodes, ctx.task_req)

    @register_score_plugin("nodeavailability")
    def _nodeavailability(ctx):
        return availability_score(ctx.fit_idle)


_register_builtins()

"""kai-pulse — on-device cluster-health analytics.

The runtime is observable (kai-trace phase spans, the kai-wire transfer
ledger) but the *cluster state* the solver works on was a black box:
nothing reported how fragmented free capacity is, how far actual
allocation drifts from the DRF fair-share target, or how long gangs
starve.  This kernel runs over the device-resident snapshot each cycle
(or every K cycles — ``SchedulerConfig.analytics_every``) and emits one
compact fixed-shape stats bundle that rides the packed commit transfer:
no extra host↔device round trip, zero bytes added to the wire ledger
(the kernel consumes state already on device; its only host input is
the tiny pending-age vector that rides the jit dispatch).

Four gauge families:

* **fragmentation** — per-node free-fraction histograms per resource, a
  largest-placeable-gang probe over a ladder of canonical gang sizes
  (reusing the allocate action's ``resource_fit_mask`` predicate for
  the unit-pod fit), and a rack-level stranded-capacity score: the
  fraction of ladder rungs the cluster could serve by raw free units
  but NO single rack domain can host.  This is the gauge ROADMAP item 5
  gates the repack solver behind ("Priority Matters", arxiv 2511.08373,
  treats fragmentation as the signal that triggers constraint-based
  repacking) — it reads high exactly while a rack-required large gang
  is unplaceable and drops once capacity consolidates.
* **goodput / utilization** — allocated-vs-capacity per resource axis,
  plus cluster goodput in Gavel's effective-throughput sense (arxiv
  2008.09213): sum of per-accelerator throughputs of work that is
  running (or bound this cycle) over cluster accel capacity.  Unit
  throughput per device today; the ROADMAP item-4 per-(job, accel-type)
  throughput tensors slot into ``_goodput`` without changing the bundle
  shape.
* **fairness drift** — per-queue ``max_r |allocated − fair_share| /
  capacity`` deviation from the DRF division (``ops/drf.py``), with
  max / mean / Gini rollups over the dominant allocated shares.
* **starvation** — per-gang pending age in cycles (host-fed, the
  scheduler owns the name-keyed counters across snapshot reindexing)
  with an on-device top-K oldest table.

Everything is f32/i32 fixed-shape tensor math: the op is registered in
the jaxpr probe (``analysis/trace_probe.py``) with its own eqn/const
baselines, wrapped by the CompileWatcher, and lives in the kai-lint jit
region like every other cycle kernel.
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
from flax import struct

from ..runtime import compile_watch
from ..state.cluster_state import ClusterState
from .allocate import AllocationResult
from .predicates import resource_fit_mask

EPS = 1e-6


@dataclasses.dataclass(frozen=True)
class AnalyticsConfig:
    """Static knobs of the cluster-health kernel (hashable — rides the
    jit signature like ``AllocateConfig``)."""

    #: free-fraction histogram bins per resource axis
    hist_bins: int = 8
    #: canonical gang sizes (unit pods) for the largest-placeable probe;
    #: the top rung matches ROADMAP item 5's 256-pod repack scenario
    gang_ladder: tuple[int, ...] = (1, 2, 4, 8, 16, 32, 64, 128, 256)
    #: the canonical unit-pod request (accel, cpu, mem) the ladder and
    #: stranded-capacity gauges probe with; accel-only by default so
    #: the gauge reads as "whole idle devices"
    unit_req: tuple[float, float, float] = (1.0, 0.0, 0.0)
    #: topology level index treated as the rack for the stranded-
    #: capacity probe (0 = outermost; clamped to the snapshot's level
    #: count; topology-free snapshots degrade to per-node domains)
    rack_level: int = 0
    #: starvation table size (oldest pending gangs)
    top_k: int = 8


class AnalyticsBundle(struct.PyTreeNode):
    """The fixed-shape stats bundle one analytics pass emits.

    Rides the packed commit transfer (``framework/session._pack_commit``
    appends the flattened bundle), so surfacing it costs zero extra
    device→host transfers.
    """

    free_hist: jax.Array          # f32 [R, BINS]  valid-node counts
    ladder_cluster_ok: jax.Array  # f32 [LAD] 1 = total free units cover rung
    ladder_rack_ok: jax.Array     # f32 [LAD] 1 = some rack covers rung alone
    total_units: jax.Array        # f32 []  placeable unit pods cluster-wide
    max_rack_units: jax.Array     # f32 []  placeable unit pods, best rack
    stranded_frac: jax.Array      # f32 [R] free stuck on nodes unfit for 1 unit
    frag_score: jax.Array         # f32 []  rack-stranded rungs / feasible rungs
    util: jax.Array               # f32 [R] allocated / capacity
    goodput: jax.Array            # f32 []  effective throughput / accel capacity
    queue_drift: jax.Array        # f32 [Q] max_r |alloc - fair| / cap_r
    drift_max: jax.Array          # f32 []
    drift_mean: jax.Array         # f32 []  over valid queues
    drift_gini: jax.Array         # f32 []  over dominant allocated shares
    starv_age: jax.Array          # f32 [K] top-K pending ages (cycles)
    starv_gang: jax.Array         # i32 [K] gang index per table row
    pending_gangs: jax.Array      # f32 []  gangs still pending after the cycle


#: bundle fields in flatten/unpack order — f32 parts then i32 parts;
#: shapes derived from (config, Q, R) by :func:`field_shapes`
F32_FIELDS = (
    "free_hist", "ladder_cluster_ok", "ladder_rack_ok", "total_units",
    "max_rack_units", "stranded_frac", "frag_score", "util", "goodput",
    "queue_drift", "drift_max", "drift_mean", "drift_gini", "starv_age",
    "pending_gangs")
I32_FIELDS = ("starv_gang",)


def field_shapes(config: AnalyticsConfig, *, q: int, r: int,
                 g: int) -> dict:
    """Field name → shape for a (Q, R, G)-shaped snapshot — the single
    source of truth keeping :func:`flatten` and :func:`host_unpack` in
    lockstep."""
    lad = len(config.gang_ladder)
    k = min(config.top_k, max(g, 1))
    return {
        "free_hist": (r, config.hist_bins),
        "ladder_cluster_ok": (lad,), "ladder_rack_ok": (lad,),
        "total_units": (), "max_rack_units": (),
        "stranded_frac": (r,), "frag_score": (),
        "util": (r,), "goodput": (),
        "queue_drift": (q,), "drift_max": (), "drift_mean": (),
        "drift_gini": (),
        "starv_age": (k,),
        "pending_gangs": (),
        "starv_gang": (k,),
    }


def _shape_len(shape: tuple) -> int:
    n = 1
    for s in shape:
        n *= s
    return n


def f32_len(config: AnalyticsConfig, *, q: int, r: int, g: int) -> int:
    shapes = field_shapes(config, q=q, r=r, g=g)
    return sum(_shape_len(shapes[f]) for f in F32_FIELDS)


def i32_len(config: AnalyticsConfig, *, q: int, r: int, g: int) -> int:
    shapes = field_shapes(config, q=q, r=r, g=g)
    return sum(_shape_len(shapes[f]) for f in I32_FIELDS)


def flatten(bundle: AnalyticsBundle) -> tuple[jax.Array, jax.Array]:
    """Bundle → (flat f32, flat i32) in the canonical field order —
    traced inside ``_pack_commit`` so the bundle rides the ONE packed
    commit transfer."""
    f32 = jnp.concatenate(
        [getattr(bundle, f).reshape(-1).astype(jnp.float32)
         for f in F32_FIELDS])
    i32 = jnp.concatenate(
        [getattr(bundle, f).reshape(-1).astype(jnp.int32)
         for f in I32_FIELDS])
    return f32, i32


def host_unpack(flat_f32, flat_i32, *, config: AnalyticsConfig,
                q: int, r: int, g: int) -> dict:
    """Flat host copies → field name → numpy array (gather_host side)."""
    shapes = field_shapes(config, q=q, r=r, g=g)
    out = {}
    off = 0
    for f in F32_FIELDS:
        n = _shape_len(shapes[f])
        out[f] = flat_f32[off:off + n].reshape(shapes[f])
        off += n
    off = 0
    for f in I32_FIELDS:
        n = _shape_len(shapes[f])
        out[f] = flat_i32[off:off + n].reshape(shapes[f])
        off += n
    return out


# ---------------------------------------------------------------------------
# the kernel
# ---------------------------------------------------------------------------


def _free_hist_one(frac: jax.Array, valid: jax.Array,
                   bins: int) -> jax.Array:
    """Histogram of one resource's free fraction over valid nodes —
    vmapped over the resource axis."""
    idx = jnp.clip(jnp.floor(frac * bins).astype(jnp.int32), 0, bins - 1)
    idx = jnp.where(valid, idx, bins)  # invalid nodes → junk bin
    return jax.ops.segment_sum(
        jnp.ones_like(frac), idx, num_segments=bins + 1)[:bins]


def _unit_pods_per_node(free: jax.Array, valid: jax.Array,
                        unit: jax.Array) -> jax.Array:
    """f32 [N] — canonical unit pods each node can host, gated on the
    allocate fit predicate (``resource_fit_mask``) so the probe agrees
    with what the placement kernel would accept."""
    fits_one = resource_fit_mask(free, unit)                # [N]
    per_axis = jnp.where(unit[None, :] > 0,
                         jnp.floor(free / jnp.maximum(unit, EPS)[None, :]),
                         jnp.inf)
    units = jnp.min(per_axis, axis=1)
    units = jnp.where(jnp.isfinite(units), units, 0.0)
    return jnp.where(valid & fits_one, jnp.maximum(units, 0.0), 0.0)


def rack_domain_ids(state: ClusterState, rack_level: int) -> jax.Array:
    """i32 [N] — dense rack-domain id per node at the given topology
    level: nodes without the rack label (or topology-free snapshots)
    count as their own one-node domain (the degenerate per-node
    reading); invalid node slots map to the junk id ``N*L + N``.

    The SINGLE source of the rack-domain partition: the fragmentation
    gauges here and the repack solver (``ops/repack.py``) both derive
    their domains from this function and one ``AnalyticsConfig.
    rack_level`` knob, so the trigger and the solver can never disagree
    about what a rack is.
    """
    n = state.nodes
    N, L = n.n, n.topology.shape[1]
    rl = min(max(rack_level, 0), L - 1)
    dom = n.topology[:, rl]
    node_slot = N * L + jnp.arange(N)
    junk = N * L + N
    return jnp.where(n.valid, jnp.where(dom >= 0, dom, node_slot), junk)


def _rack_units(state: ClusterState, units: jax.Array,
                rack_level: int) -> jax.Array:
    """f32 [] — unit pods placeable inside the single best rack domain
    (domains from :func:`rack_domain_ids`)."""
    n = state.nodes
    junk = n.n * n.topology.shape[1] + n.n
    seg = rack_domain_ids(state, rack_level)
    per_dom = jax.ops.segment_sum(units, seg, num_segments=junk + 1)
    return jnp.max(per_dom.at[junk].set(0.0))


def _gini(shares: jax.Array, valid: jax.Array) -> jax.Array:
    """Gini coefficient of ``shares`` over valid queues (0 when fewer
    than two live queues or no allocation)."""
    s = jnp.where(valid, shares, 0.0)
    n = jnp.sum(valid.astype(jnp.float32))
    pair = jnp.abs(s[:, None] - s[None, :]) \
        * (valid[:, None] & valid[None, :])
    total = jnp.sum(s)
    return jnp.where((n > 1) & (total > 0),
                     jnp.sum(pair) / jnp.maximum(2.0 * n * total, EPS),
                     0.0)


def cluster_analytics(state: ClusterState, result: AllocationResult,
                      pending_age: jax.Array, *,
                      config: AnalyticsConfig) -> AnalyticsBundle:
    """One analytics pass over the POST-decision cluster state.

    The **fragmentation** family reads the PRE-decision snapshot free
    pool (``state.nodes.free``): it describes the capacity shape the
    cycle's decisions — and a future repack solver — act ON, so the
    gauge drops the moment capacity consolidates, in the same cycle the
    stranded gang finally places (the predictive property the frag
    scenario test pins).  The **outcome** families (utilization,
    goodput, fairness drift, starvation) read the cycle's final commit
    set: ``result.free`` is the idle pool after commits,
    ``result.queue_allocated`` the post-commit queue ledger,
    ``result.allocated`` the gangs that made it.  ``pending_age``
    (f32 [G]) is the host-owned pending-cycles counter per gang slot
    BEFORE this cycle; the kernel advances it for gangs that stayed
    pending (+1) and zeroes gangs that placed, so the top-K table
    reflects end-of-cycle ages.
    """
    nodes, queues, gangs = state.nodes, state.queues, state.gangs
    R = nodes.free.shape[1]

    # --- fragmentation (pre-decision capacity shape) ----------------------
    free = jnp.maximum(nodes.free, 0.0)
    alloc_cap = nodes.allocatable
    frac = jnp.where(alloc_cap > 0, free / jnp.maximum(alloc_cap, EPS), 0.0)
    free_hist = jax.vmap(_free_hist_one, in_axes=(1, None, None),
                         out_axes=0)(frac, nodes.valid, config.hist_bins)
    unit = jnp.asarray(config.unit_req, jnp.float32)
    units = _unit_pods_per_node(free, nodes.valid, unit)
    total_units = jnp.sum(units)
    max_rack_units = _rack_units(state, units, config.rack_level)
    ladder = jnp.asarray(config.gang_ladder, jnp.float32)
    ladder_cluster_ok = (total_units >= ladder).astype(jnp.float32)
    ladder_rack_ok = (max_rack_units >= ladder).astype(jnp.float32)
    # rungs the cluster could serve by raw free units but no single rack
    # can host — the stranded-rung fraction IS the fragmentation score
    stranded_rungs = ladder_cluster_ok * (1.0 - ladder_rack_ok)
    frag_score = jnp.sum(stranded_rungs) / jnp.maximum(
        jnp.sum(ladder_cluster_ok), 1.0)
    free_valid = jnp.where(nodes.valid[:, None], free, 0.0)
    stuck = jnp.where((units <= 0)[:, None], free_valid, 0.0)
    free_tot = jnp.sum(free_valid, axis=0)
    stranded_frac = jnp.where(free_tot > 0,
                              jnp.sum(stuck, axis=0)
                              / jnp.maximum(free_tot, EPS), 0.0)

    # --- goodput / utilization (post-decision) ---------------------------
    cap = jnp.sum(jnp.where(nodes.valid[:, None], alloc_cap, 0.0), axis=0)
    post_free = jnp.where(nodes.valid[:, None],
                          jnp.maximum(result.free, 0.0), 0.0)
    releasing = jnp.where(nodes.valid[:, None],
                          nodes.releasing + result.releasing_extra, 0.0)
    idle = post_free + jnp.maximum(releasing, 0.0)
    util = jnp.where(cap > 0,
                     1.0 - jnp.sum(idle, axis=0) / jnp.maximum(cap, EPS),
                     0.0)
    # Gavel effective throughput, unit throughput per accel device:
    # running survivors keep contributing, this cycle's victims stop,
    # and this cycle's non-pipelined placements start.  The item-4
    # throughput tensors replace the two `* 1.0` unit factors.
    run = state.running
    surviving = run.valid & ~run.releasing & ~result.victim
    thr_running = jnp.sum(
        jnp.where(surviving, run.req[:, 0], 0.0) * 1.0)
    placed = (result.placements >= 0) & gangs.task_valid \
        & result.allocated[:, None] & ~result.pipelined
    thr_placed = jnp.sum(
        jnp.where(placed, gangs.task_req[:, :, 0], 0.0) * 1.0)
    goodput = (thr_running + thr_placed) / jnp.maximum(cap[0], EPS)

    # --- fairness drift ---------------------------------------------------
    qvalid = queues.valid
    dev = jnp.abs(result.queue_allocated - queues.fair_share) \
        / jnp.maximum(cap, 1.0)[None, :]
    queue_drift = jnp.where(qvalid, jnp.max(dev, axis=1), 0.0)
    nq = jnp.sum(qvalid.astype(jnp.float32))
    drift_max = jnp.max(queue_drift)
    drift_mean = jnp.sum(queue_drift) / jnp.maximum(nq, 1.0)
    dom_share = jnp.max(result.queue_allocated
                        / jnp.maximum(cap, 1.0)[None, :], axis=1)
    drift_gini = _gini(dom_share, qvalid)

    # --- starvation -------------------------------------------------------
    still_pending = gangs.valid & ~result.allocated
    age_next = jnp.where(still_pending, pending_age + 1.0, 0.0)
    k = min(config.top_k, age_next.shape[0])
    starv_age, starv_gang = jax.lax.top_k(age_next, k)
    pending_gangs = jnp.sum(still_pending.astype(jnp.float32))

    return AnalyticsBundle(
        free_hist=free_hist.astype(jnp.float32),
        ladder_cluster_ok=ladder_cluster_ok,
        ladder_rack_ok=ladder_rack_ok,
        total_units=total_units, max_rack_units=max_rack_units,
        stranded_frac=stranded_frac, frag_score=frag_score,
        util=util, goodput=goodput,
        queue_drift=queue_drift, drift_max=drift_max,
        drift_mean=drift_mean, drift_gini=drift_gini,
        starv_age=starv_age, starv_gang=starv_gang.astype(jnp.int32),
        pending_gangs=pending_gangs)


# kai-wire compile watcher: per-(entry, signature) cache-miss
# attribution (runtime/compile_watch.py)
cluster_analytics_jit = compile_watch.watch(
    "analytics",
    functools.partial(jax.jit,
                      static_argnames=("config",))(cluster_analytics))

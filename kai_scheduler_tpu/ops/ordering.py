"""Queue-of-queues job ordering — the two-level priority heap, tensorized.

The reference pops the next job from a heap of queues ordered by the
proportion plugin's QueueOrderFn and, within a queue, by JobOrderFn
(``actions/utils/job_order_by_queue.go:38`` JobsOrderByQueues).  The heap
is *dynamic*: every allocation changes the owning queue's allocated share
and re-sorts it.  Here the pop is an on-device ``lexsort`` over composite
keys, recomputed each scan step from the live allocation tensors — same
semantics, no heap.

Queue comparison tiers (``plugins/proportion/queue_order/queue_order.go``
``GetQueueOrderResult``):
1. under-fair-share queues before over-fair-share queues
2. under-quota before over-quota
3. higher queue priority first
4. smaller dominant resource share (allocated / cluster total) first
5. creation time (older first)

Job tiers within a queue (priority plugin + elastic plugin +
default creation order):
1. below-min-member gangs first (elastic ``plugins/elastic/elastic.go:38``)
2. higher podgroup priority first
3. older first
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

from ..apis.types import UNLIMITED
from ..state.cluster_state import GangState, QueueState

BIG = jnp.float32(1e30)


def queue_order_keys(
    queues: QueueState,
    queue_allocated: jax.Array,   # f32 [Q, R]  live allocation (incl. this cycle)
    fair_share: jax.Array,        # f32 [Q, R]  DRF division output
    total: jax.Array,             # f32 [R]     cluster capacity
) -> tuple[jax.Array, jax.Array, jax.Array, jax.Array]:
    """Per-queue comparison keys (smaller = schedule sooner).

    Returns (over_fair_share, over_quota, neg_priority, dominant_share),
    each [Q] float32.
    """
    eps = 1e-6
    over_fs = jnp.any(queue_allocated > fair_share + eps, axis=-1)
    quota_eff = jnp.where(queues.quota <= UNLIMITED + 0.5, BIG, queues.quota)
    over_quota = jnp.any(queue_allocated > quota_eff + eps, axis=-1)
    safe_total = jnp.maximum(total, eps)
    dom_share = jnp.max(queue_allocated / safe_total[None, :], axis=-1)
    return (
        over_fs.astype(jnp.float32),
        over_quota.astype(jnp.float32),
        -queues.priority.astype(jnp.float32),
        dom_share,
    )


def job_order_perm(
    gangs: GangState,
    queues: QueueState,
    queue_allocated: jax.Array,   # f32 [Q, R]
    fair_share: jax.Array,        # f32 [Q, R]
    total: jax.Array,             # f32 [R]
    remaining: jax.Array,         # bool [G]  gangs not yet attempted
) -> jax.Array:
    """Full gang permutation [G] by the two-level heap order, remaining
    gangs first — one heap rebuild against the *live* allocation tensors.
    """
    over_fs, over_quota, neg_prio, dom_share = queue_order_keys(
        queues, queue_allocated, fair_share, total)
    qi = gangs.queue
    not_rem = (~remaining).astype(jnp.float32)
    # elastic plugin: gangs whose *active* pods are below minMember first
    below_min = gangs.running_count < gangs.min_member
    # lexsort: LAST key is most significant.
    return jnp.lexsort((
        gangs.creation_order.astype(jnp.float32),
        -gangs.priority.astype(jnp.float32),
        (~below_min).astype(jnp.float32),   # elastic: below-min gangs first
        dom_share[qi],
        neg_prio[qi],
        over_quota[qi],
        over_fs[qi],
        not_rem,                            # exhausted gangs last
    ))


def select_next_gang(
    gangs: GangState,
    queues: QueueState,
    queue_allocated: jax.Array,   # f32 [Q, R]
    fair_share: jax.Array,        # f32 [Q, R]
    total: jax.Array,             # f32 [R]
    remaining: jax.Array,         # bool [G]  gangs not yet attempted
) -> jax.Array:
    """Index of the next gang to attempt (i32 scalar; any index if none
    remain — callers must also branch on ``jnp.any(remaining)``).

    Equivalent to one ``PopNextJob`` from the two-level heap — computed
    as a cascade of masked min-reductions instead of a full lexsort: the
    pop only needs the MINIMUM in lexicographic order, and eight [G]
    reductions are far cheaper than a [G] multi-key sort inside a
    per-step ``while_loop`` body (same result, including the smallest-
    index tie-break).
    """
    over_fs, over_quota, neg_prio, dom_share = queue_order_keys(
        queues, queue_allocated, fair_share, total)
    qi = gangs.queue
    below_min = gangs.running_count < gangs.min_member
    keys = (
        (~remaining).astype(jnp.float32),
        over_fs[qi], over_quota[qi], neg_prio[qi], dom_share[qi],
        (~below_min).astype(jnp.float32),
        -gangs.priority.astype(jnp.float32),
        gangs.creation_order.astype(jnp.float32),
    )
    best = jnp.ones_like(remaining)
    for k in keys:
        m = jnp.min(jnp.where(best, k, jnp.inf))
        best = best & (k <= m)
    return jnp.argmax(best)

from .scheduler import (CycleResult, Scheduler, SchedulerConfig,
                        action_names, register_action)
from .session import Session, SessionConfig

__all__ = [
    "CycleResult", "Scheduler", "SchedulerConfig", "Session",
    "SessionConfig", "action_names", "register_action",
]

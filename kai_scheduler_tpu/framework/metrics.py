"""The scheduler's metric catalog — ref ``pkg/scheduler/metrics/metrics.go:39-58``
and this repo's generated ``docs/metrics/METRICS.md``, same metric
names (kai_ prefix).

Every metric registers HERE (one module, one registry) so the catalog
doc can be generated — and drift-checked — from a single source:

    python -m kai_scheduler_tpu.framework.metrics > docs/metrics/METRICS.md

``tests/test_metrics_catalog.py`` asserts the committed doc equals the
registry exactly (name, type, labels, help); ``scripts/lint.py`` runs
the same check jax-free by AST-parsing this module's registrations.
"""
from __future__ import annotations

from ..utils.metrics import Registry, render_catalog

registry = Registry()

e2e_latency = registry.histogram(
    "kai_e2e_scheduling_latency_seconds",
    "End-to-end scheduling cycle latency")
open_session_latency = registry.histogram(
    "kai_open_session_latency_seconds",
    "Snapshot + plugin-init (session open) latency")
action_latency = registry.histogram(
    "kai_action_scheduling_latency_seconds",
    "Per-action latency", label_names=("action",))
plugin_latency = registry.histogram(
    "kai_plugin_scheduling_latency_seconds",
    "Per-plugin latency", label_names=("plugin", "extension"))
pod_scheduling = registry.histogram(
    "kai_pod_scheduling_latency_seconds", "Per-pod scheduling latency")
podgroups_scheduled = registry.counter(
    "kai_podgroups_scheduled_total", "Pod groups scheduled by action",
    label_names=("action",))
podgroups_considered = registry.counter(
    "kai_podgroups_considered_total", "Pod groups considered per cycle")
scenarios_simulated = registry.counter(
    "kai_scenarios_simulated_total",
    "Victim scenarios simulated", label_names=("action",))
scenarios_filtered = registry.counter(
    "kai_scenarios_filtered_total",
    "Victim scenarios pruned before simulation", label_names=("action",))
preemption_attempts = registry.counter(
    "kai_preemption_attempts_total", "Preemption attempts")
queue_fair_share = registry.gauge(
    "kai_queue_fair_share", "Per-queue fair share",
    label_names=("queue", "resource"))
queue_allocated = registry.gauge(
    "kai_queue_allocated", "Per-queue allocated amount",
    label_names=("queue", "resource"))
queue_usage = registry.gauge(
    "kai_queue_usage", "Per-queue normalized historical usage",
    label_names=("queue", "resource"))
# victim-wavefront observability (ops/victims.py chunked engine): chunk
# count and lane occupancy per action per cycle, plus how often the
# sparse preempt path fell back to the dense composed path (compact
# unit-table overflow)
victim_wavefront_chunks = registry.gauge(
    "kai_victim_wavefront_chunks",
    "Victim-wavefront chunks run last cycle", label_names=("action",))
victim_wavefront_lane_occupancy = registry.gauge(
    "kai_victim_wavefront_lane_occupancy",
    "Live lanes / lane slots across last cycle's victim chunks",
    label_names=("action",))
victim_wavefront_sparse_fallbacks = registry.gauge(
    "kai_victim_wavefront_sparse_fallbacks",
    "Sparse-path actions that fell back to the dense composed path "
    "last cycle", label_names=("action",))
victim_wavefront_leftover_demotions = registry.gauge(
    "kai_victim_wavefront_leftover_demotions",
    "Lane-chunk demotion events last cycle (a lane demoted to "
    "conflict-retry because an earlier lane's victims freed more than "
    "its claims consumed; the same lane re-demoted in a later chunk "
    "counts again — the gauge measures serialization pressure, not "
    "distinct lanes)", label_names=("action",))
# kai-trace phase attribution (runtime/tracing.py): the cycle timeline
# partitioned into contiguous phases — snapshot (host build/patch),
# upload (changed-leaves transfer DISPATCH; device_put is async, so the
# transfer itself overlaps the solve), solve_dispatch (async kernel
# dispatch), device_wait (first blocking sync: link + device + any
# still-inflight transfer time), host_decode (tensors ->
# BindRequests/evictions), commit (API writes, status, bookkeeping).
# The phases sum to the cycle wall time.
cycle_phase_seconds = registry.histogram(
    "kai_cycle_phase_seconds",
    "Per-phase scheduling cycle latency (phases partition the cycle "
    "wall time; device_wait brackets the first blocking transfer)",
    label_names=("phase",))
# continuous profiler push counters (runtime/profiling.py) — were bare
# instance attributes invisible to /metrics
profiler_pushed_windows = registry.counter(
    "kai_profiler_pushed_windows_total",
    "Continuous-profiler windows pushed to the ingest server")
profiler_push_errors = registry.counter(
    "kai_profiler_push_errors_total",
    "Continuous-profiler window pushes that failed (swallowed after "
    "counting — a profiling sink never affects scheduling)")


def catalog() -> list[dict]:
    """Every registered metric as ``{name, type, labels, help}`` — the
    source of truth for ``docs/metrics/METRICS.md``."""
    return sorted(({"name": m.name, "type": m.kind,
                    "labels": list(m.label_names), "help": m.help}
                   for m in registry.metrics()),
                  key=lambda r: r["name"])


if __name__ == "__main__":
    print(render_catalog(catalog()), end="")

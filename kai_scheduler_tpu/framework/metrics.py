"""The scheduler's metric catalog — ref ``pkg/scheduler/metrics/metrics.go:39-58``
and this repo's generated ``docs/metrics/METRICS.md``, same metric
names (kai_ prefix).

Every metric registers HERE (one module, one registry) so the catalog
doc can be generated — and drift-checked — from a single source:

    python -m kai_scheduler_tpu.framework.metrics > docs/metrics/METRICS.md

``tests/test_metrics_catalog.py`` asserts the committed doc equals the
registry exactly (name, type, labels, help); ``scripts/lint.py`` runs
the same check jax-free by AST-parsing this module's registrations.
"""
from __future__ import annotations

from ..utils.metrics import Registry, render_catalog

registry = Registry()

e2e_latency = registry.histogram(
    "kai_e2e_scheduling_latency_seconds",
    "End-to-end scheduling cycle latency")
open_session_latency = registry.histogram(
    "kai_open_session_latency_seconds",
    "Snapshot + plugin-init (session open) latency")
action_latency = registry.histogram(
    "kai_action_scheduling_latency_seconds",
    "Per-action latency", label_names=("action",))
plugin_latency = registry.histogram(
    "kai_plugin_scheduling_latency_seconds",
    "Per-plugin latency", label_names=("plugin", "extension"))
pod_scheduling = registry.histogram(
    "kai_pod_scheduling_latency_seconds", "Per-pod scheduling latency")
podgroups_scheduled = registry.counter(
    "kai_podgroups_scheduled_total", "Pod groups scheduled by action",
    label_names=("action",))
podgroups_considered = registry.counter(
    "kai_podgroups_considered_total", "Pod groups considered per cycle")
scenarios_simulated = registry.counter(
    "kai_scenarios_simulated_total",
    "Victim scenarios simulated", label_names=("action",))
scenarios_filtered = registry.counter(
    "kai_scenarios_filtered_total",
    "Victim scenarios pruned before simulation", label_names=("action",))
preemption_attempts = registry.counter(
    "kai_preemption_attempts_total", "Preemption attempts")
queue_fair_share = registry.gauge(
    "kai_queue_fair_share", "Per-queue fair share",
    label_names=("queue", "resource"))
queue_allocated = registry.gauge(
    "kai_queue_allocated", "Per-queue allocated amount",
    label_names=("queue", "resource"))
queue_usage = registry.gauge(
    "kai_queue_usage", "Per-queue normalized historical usage",
    label_names=("queue", "resource"))
# victim-wavefront observability (ops/victims.py chunked engine): chunk
# count and lane occupancy per action per cycle, plus how often the
# sparse preempt path fell back to the dense composed path (compact
# unit-table overflow)
victim_wavefront_chunks = registry.gauge(
    "kai_victim_wavefront_chunks",
    "Victim-wavefront chunks run last cycle", label_names=("action",))
victim_wavefront_lane_occupancy = registry.gauge(
    "kai_victim_wavefront_lane_occupancy",
    "Live lanes / lane slots across last cycle's victim chunks",
    label_names=("action",))
victim_wavefront_sparse_fallbacks = registry.gauge(
    "kai_victim_wavefront_sparse_fallbacks",
    "Sparse-path actions that fell back to the dense composed path "
    "last cycle", label_names=("action",))
victim_wavefront_leftover_demotions = registry.gauge(
    "kai_victim_wavefront_leftover_demotions",
    "Lane-chunk demotion events last cycle (a lane demoted to "
    "conflict-retry because an earlier lane's victims freed more than "
    "its claims consumed; the same lane re-demoted in a later chunk "
    "counts again — the gauge measures serialization pressure, not "
    "distinct lanes)", label_names=("action",))
# kai-trace phase attribution (runtime/tracing.py): the cycle timeline
# partitioned into contiguous phases — snapshot (host build/patch),
# upload (changed-leaves transfer DISPATCH; device_put is async, so the
# transfer itself overlaps the solve), solve_dispatch (async kernel
# dispatch), device_wait (first blocking sync: link + device + any
# still-inflight transfer time), host_decode (tensors ->
# BindRequests/evictions), commit (API writes, status, bookkeeping).
# The phases sum to the cycle wall time.
cycle_phase_seconds = registry.histogram(
    "kai_cycle_phase_seconds",
    "Per-phase scheduling cycle latency (phases partition the cycle "
    "wall time; device_wait brackets the first blocking transfer)",
    label_names=("phase",))
# continuous profiler push counters (runtime/profiling.py) — were bare
# instance attributes invisible to /metrics
profiler_pushed_windows = registry.counter(
    "kai_profiler_pushed_windows_total",
    "Continuous-profiler windows pushed to the ingest server")
profiler_push_errors = registry.counter(
    "kai_profiler_push_errors_total",
    "Continuous-profiler window pushes that failed (swallowed after "
    "counting — a profiling sink never affects scheduling)")
# kai-wire transfer ledger (runtime/wire_ledger.py): every host→device
# upload in the package flows through the TransferLedger choke point
# (KAI071), labeled with WHY it shipped — full-build (build_snapshot's
# one-shot transfer), journal-patch (incremental changed-leaves ship),
# fallback (incremental engine rebuilt in full), verify (patched==fresh
# reference rebuild), mesh-shard (mesh placement).
wire_uploaded_bytes = registry.counter(
    "kai_wire_uploaded_bytes_total",
    "Bytes shipped host→device through the transfer ledger",
    label_names=("reason",))
wire_uploaded_leaves = registry.counter(
    "kai_wire_uploaded_leaves_total",
    "Pytree leaves shipped host→device through the transfer ledger",
    label_names=("reason",))
wire_dispatches = registry.counter(
    "kai_wire_dispatches_total",
    "device_put dispatch calls (one batched dispatch may carry many "
    "leaves — leaves/dispatches exposes unbatched transfer loops)",
    label_names=("reason",))
wire_redundant_bytes = registry.counter(
    "kai_wire_redundant_bytes_total",
    "Re-uploaded-IDENTICAL bytes: the uploaded leaf's content "
    "fingerprint matched the last upload of the same leaf — the "
    "invariant ROADMAP item 1 must drive to zero on the patch path",
    label_names=("reason",))
wire_dispatch_seconds = registry.counter(
    "kai_wire_dispatch_seconds_total",
    "Wall seconds spent in device_put dispatch calls (async enqueue, "
    "not transfer completion — that is the cycle's device_wait phase)",
    label_names=("reason",))
wire_resident_bytes = registry.gauge(
    "kai_wire_resident_bytes",
    "Ledger-known device-resident bytes (last upload per leaf key)")
wire_resident_buffers = registry.gauge(
    "kai_wire_resident_buffers",
    "Ledger-known device-resident buffer count")
# kai-resident (ops/resident.py): the device-resident-state payoff
# gauge pair — per cycle, resident snapshot bytes REUSED on device
# without touching the wire vs bytes actually uploaded (the packed
# journal delta in steady state).  Donated delta buffers are transient
# and never double-count into the residency watermark.
wire_resident_reused_bytes = registry.gauge(
    "kai_wire_resident_reused_bytes",
    "Device-resident bytes reused last cycle without re-upload "
    "(resident snapshot leaves not touched by the wire)")
wire_resident_uploaded_bytes = registry.gauge(
    "kai_wire_resident_uploaded_bytes",
    "Bytes uploaded last cycle (steady resident cycles: the packed "
    "journal-delta size)")
wire_downloaded_bytes = registry.counter(
    "kai_wire_downloaded_bytes_total",
    "Accounted device→host readback bytes through the ledger's "
    "device_get (verify gathers, rare repack-plan readbacks) — "
    "booked apart from uploads so patch-bytes invariants stay exact",
    label_names=("reason",))
wire_cycle_uploaded_bytes = registry.histogram(
    "kai_wire_cycle_uploaded_bytes",
    "Per-cycle bytes on the wire (all reasons; observed at cycle roll)",
    buckets=(4096.0, 65536.0, 1048576.0, 4194304.0, 16777216.0,
             67108864.0, 268435456.0, 1073741824.0))
# kai-wire compile watcher (runtime/compile_watch.py): every jit entry
# point of the package is wrapped, and each first-seen abstract shape
# signature is attributed as that entry's compile
compile_cache_misses = registry.counter(
    "kai_compile_cache_misses_total",
    "Jit cache misses attributed per entry point (first call with an "
    "unseen abstract shape signature)", label_names=("entry",))
compile_seconds = registry.counter(
    "kai_compile_seconds_total",
    "Wall seconds spent in cache-miss dispatches (trace + XLA compile "
    "dominated)", label_names=("entry",))
compile_storm_alarms = registry.counter(
    "kai_compile_storm_alarms_total",
    "Recompile-storm alarms: misses on one entry reached the storm "
    "threshold inside the sliding window (padded-capacity oscillation "
    "or unstable static config)", label_names=("entry",))
# kai-pulse cluster-health analytics (ops/analytics.py): the on-device
# gauge kernel that rides the packed commit every K cycles —
# fragmentation, goodput/utilization, fairness drift, starvation
cluster_fragmentation_score = registry.gauge(
    "kai_cluster_fragmentation_score",
    "Rack-stranded fraction of the canonical gang ladder: rungs the "
    "cluster could serve by raw free unit pods but NO single rack "
    "domain can host (0 = consolidated, 1 = fully stranded) — the "
    "gauge the repack solver is gated behind")
cluster_stranded_free_frac = registry.gauge(
    "kai_cluster_stranded_free_frac",
    "Fraction of free capacity sitting on nodes that cannot fit even "
    "one canonical unit pod", label_names=("resource",))
cluster_largest_rack_gang = registry.gauge(
    "kai_cluster_largest_rack_gang_units",
    "Canonical unit pods placeable inside the single best rack domain "
    "(the largest-placeable-gang probe)")
cluster_free_unit_pods = registry.gauge(
    "kai_cluster_free_unit_pods",
    "Canonical unit pods placeable cluster-wide (allocate fit "
    "predicate over the post-cycle free pool)")
cluster_utilization = registry.gauge(
    "kai_cluster_utilization",
    "Allocated / capacity per resource axis (post-cycle, releasing "
    "counted as idle)", label_names=("resource",))
cluster_goodput = registry.gauge(
    "kai_cluster_goodput",
    "Cluster goodput in Gavel's effective-throughput sense: running + "
    "newly-bound accel throughput over accel capacity (unit throughput "
    "per device until the per-(job, accel-type) tensors land)")
cluster_fairness_drift = registry.gauge(
    "kai_cluster_fairness_drift",
    "Per-queue max_r |allocated - DRF fair share| / cluster capacity",
    label_names=("queue",))
cluster_fairness_drift_max = registry.gauge(
    "kai_cluster_fairness_drift_max",
    "Largest per-queue fairness drift this analytics cycle")
cluster_fairness_drift_gini = registry.gauge(
    "kai_cluster_fairness_drift_gini",
    "Gini coefficient of the dominant allocated shares across valid "
    "queues (0 = equal, 1 = maximally concentrated)")
cluster_pending_gangs = registry.gauge(
    "kai_cluster_pending_gangs",
    "Gangs still pending after the cycle (kai-pulse starvation family)")
gang_starvation_age = registry.gauge(
    "kai_gang_starvation_age_cycles",
    "Pending age in cycles for the top-K oldest starving gangs (the "
    "kai-pulse on-device top-K table; series update on analytics "
    "cycles)", label_names=("gang",))
# kai-repack proactive defragmentation (ops/repack.py): the
# constraint-based migration solver the fragmentation gauge gates —
# fired when frag_score stays above SchedulerConfig.repack_frag_threshold
# for repack_trigger_cycles consecutive analytics cycles while a
# rack-required gang starves cluster-feasible-but-rack-stranded
repack_trigger_firings = registry.counter(
    "kai_repack_trigger_firings_total",
    "Repack solver dispatches (the fragmentation trigger fired; "
    "feasible or not, each firing starts the cooldown)")
repack_migrations_planned = registry.counter(
    "kai_repack_migrations_planned_total",
    "Migrations in feasible repack plans (bounded per firing by "
    "min(repack_max_migrations, VictimConfig.max_victim_pods))")
repack_migrations_executed = registry.counter(
    "kai_repack_migrations_executed_total",
    "Repack migrations committed as evictions with pipelined rebinds "
    "(planned moves dropped by cross-dispatch guards are not executed)")
repack_solve_seconds = registry.histogram(
    "kai_repack_solve_seconds",
    "Host-side repack solve dispatch latency per firing (device time "
    "overlaps the cycle's device_wait phase)")
repack_gangs_unblocked = registry.counter(
    "kai_repack_gangs_unblocked_total",
    "Target gangs that placed within the post-firing observation "
    "window after their repack migrations committed")
# kai-intake multi-lane mutation front end (intake/router.py): cluster
# deltas hash-shard by entity key into bounded lanes, drain workers
# admission-check them in vectorized batches, and a cycle-boundary
# coalesce merges the staged events into the hub journal — replacing
# the per-mutation single-writer wall with explicit, metered
# backpressure
intake_accepted = registry.counter(
    "kai_intake_accepted_total",
    "Events accepted into an intake lane (queued for admission + "
    "coalesce)")
intake_shed = registry.counter(
    "kai_intake_shed_total",
    "Events shed by lane backpressure (the offered group exceeded the "
    "lane bound; the whole group is refused atomically — HTTP 429, "
    "nothing journaled)", label_names=("lane",))
intake_rejected = registry.counter(
    "kai_intake_rejected_total",
    "Events rejected by the batched admission sweep (unknown "
    "collection, malformed document, resource scalar non-finite / "
    "negative / absurd)", label_names=("lane",))
intake_coalesced = registry.counter(
    "kai_intake_coalesced_total",
    "Staged events merged into the hub journal at cycle-boundary "
    "coalesce (global sequence order, bit-identical to the sequential "
    "classic path)")
intake_apply_errors = registry.counter(
    "kai_intake_apply_errors_total",
    "Admitted events the coalesce applier had to skip (doc passed the "
    "door check but failed object construction) — skipped, not fatal: "
    "one poisoned doc must never destroy other clients' accepted "
    "events or fail the cycle")
intake_sync_degrades = registry.counter(
    "kai_intake_sync_degrades_total",
    "Overflow requests that degraded to the synchronous path "
    "(policy=sync: drain inline + flush a coalesce through the commit "
    "lock, then retry)")
intake_lane_depth = registry.gauge(
    "kai_intake_lane_depth",
    "Queued + staged events per lane (observed at coalesce)",
    label_names=("lane",))
intake_coalesce_seconds = registry.histogram(
    "kai_intake_coalesce_seconds",
    "Cycle-boundary coalesce latency (take staged + seq sort + "
    "sequential apply + bulk journal merge)")
# kai-twin digital twin (twin/): recorded-stream replay, differential
# oracle, scenario fuzzer, and the closed-loop policy tuner
twin_recorded_events = registry.counter(
    "kai_twin_recorded_events_total",
    "Mutation events mirrored into the twin stream recorder at the "
    "shared intake apply choke point")
twin_replayed_events = registry.counter(
    "kai_twin_replayed_events_total",
    "Mutation events applied by the twin replayer (fresh scheduler + "
    "cluster driven through a recorded or generated stream)")
twin_replay_cycles = registry.counter(
    "kai_twin_replay_cycles_total",
    "Scheduling cycles executed by the twin replayer")
twin_oracle_checks = registry.counter(
    "kai_twin_oracle_checks_total",
    "Digest fields compared by the differential oracle (binds, "
    "evictions, decisions, journal cursor/generation, analytics, "
    "clock, determinism anchors)")
twin_oracle_divergences = registry.counter(
    "kai_twin_oracle_divergences_total",
    "Digest divergences the differential oracle found — any nonzero "
    "value is a determinism bug")
twin_fuzz_violations = registry.counter(
    "kai_twin_fuzz_violations_total",
    "Invariant violations found by the scenario fuzzer",
    label_names=("family",))
twin_fuzz_minimized = registry.counter(
    "kai_twin_fuzz_minimized_total",
    "Events dropped by the greedy event-drop delta-debugging minimizer")
twin_tuner_rollouts = registry.counter(
    "kai_twin_tuner_rollouts_total",
    "Candidate-config rollouts replayed by the closed-loop policy "
    "tuner")
twin_tuner_best_score = registry.gauge(
    "kai_twin_tuner_best_score",
    "Best composite objective the policy tuner has found (weighted "
    "goodput minus fairness drift, starvation age, and cycle p99)")


def catalog() -> list[dict]:
    """Every registered metric as ``{name, type, labels, help}`` — the
    source of truth for ``docs/metrics/METRICS.md``."""
    return sorted(({"name": m.name, "type": m.kind,
                    "labels": list(m.label_names), "help": m.help}
                   for m in registry.metrics()),
                  key=lambda r: r["name"])


if __name__ == "__main__":
    print(render_catalog(catalog()), end="")

"""Cycle driver — ``scheduler.go`` ``Scheduler.Run``/``runOnce`` rebuilt.

The reference loop (``pkg/scheduler/scheduler.go:109-170``): every
``schedulePeriod`` open a session (snapshot + plugin init), execute the
configured action pipeline (default ``allocate, consolidation, reclaim,
preempt, stalegangeviction``), close the session (flush status).  The
TPU rebuild keeps that exact shape; each action is a host function that
invokes one compiled kernel and merges its commit set.

Actions register by name (ref ``actions/factory.go:31-37``
RegisterAction) so configuration strings select and order them the same
way ``SchedulerConfiguration.Actions`` does.
"""
from __future__ import annotations

import dataclasses
import time
import weakref
from typing import Callable, Protocol

import functools

import jax
import numpy as np

from ..apis import types as apis
from ..ops import drf
from ..ops import resident as resident_ops
from ..ops.allocate import (AllocationResult, allocate, allocate_jit,
                            init_result)
from ..ops.analytics import cluster_analytics, cluster_analytics_jit
from ..ops.repack import RepackConfig, plan_repack_jit
from ..ops.stale import stale_gang_eviction
from ..ops.victims import run_victim_action, run_victim_action_jit
from ..runtime import compile_watch
from ..runtime import wire_ledger as _wire
from ..runtime.cluster import Cluster
from ..runtime import events as gang_events
from ..runtime.events import DecisionLog
from ..runtime.tracing import CycleTracer
from .session import FIT_REASONS, Session, SessionConfig, _pack_commit

stale_eviction_jit = compile_watch.watch(
    "stale_gang_eviction",
    functools.partial(jax.jit, static_argnames=(
        "grace_s", "num_levels"))(stale_gang_eviction))

#: pure (unjitted) action bodies — composed into ONE jitted program per
#: cycle when every configured action is built in.  Separate per-action
#: jit calls cost a dispatch round trip each (expensive through a
#: tunneled TPU) and hide cross-action fusion from XLA.
_PURE_ACTIONS = {
    "allocate": lambda st, fs, res, nl, acfg, vcfg, grace: allocate(
        st, fs, num_levels=nl, config=acfg, init=res),
    "consolidation": lambda st, fs, res, nl, acfg, vcfg, grace:
        run_victim_action(st, fs, res, num_levels=nl, mode="consolidate",
                          config=vcfg),
    "reclaim": lambda st, fs, res, nl, acfg, vcfg, grace:
        run_victim_action(st, fs, res, num_levels=nl, mode="reclaim",
                          config=vcfg),
    "preempt": lambda st, fs, res, nl, acfg, vcfg, grace:
        run_victim_action(st, fs, res, num_levels=nl, mode="preempt",
                          config=vcfg),
    "stalegangeviction": lambda st, fs, res, nl, acfg, vcfg, grace:
        stale_gang_eviction(st, res, grace_s=grace, num_levels=nl),
}


def run_actions(state, fair_share, *, actions, num_levels, acfg, vcfg,
                grace_s):
    """Pure composition of the action pipeline over a fresh commit set —
    shared by the jitted production pipeline below and by harnesses
    (e.g. the multichip dryrun) that must compile EXACTLY what
    production compiles."""
    res = init_result(state)
    for name in actions:
        res = _PURE_ACTIONS[name](state, fair_share, res, num_levels,
                                  acfg, vcfg, grace_s)
    return res


@functools.partial(jax.jit, static_argnames=(
    "actions", "num_levels", "acfg", "vcfg", "grace_s"))
def _fused_pipeline(state, fair_share, *, actions, num_levels, acfg,
                    vcfg, grace_s):
    return run_actions(state, fair_share, actions=actions,
                       num_levels=num_levels, acfg=acfg, vcfg=vcfg,
                       grace_s=grace_s)


# kai-wire compile watcher: per-(entry, signature) cache-miss
# attribution (runtime/compile_watch.py)
_fused_pipeline = compile_watch.watch("fused_pipeline", _fused_pipeline)

#: ``_pack_commit``'s raw (unjitted) body — inlined into the fused
#: resident entry below so the commit pack costs no second dispatch
_PACK_COMMIT_FN = getattr(_pack_commit, "__wrapped__", _pack_commit)


def resident_cycle(state, delta, ages, k_value, *, actions, num_levels,
                   acfg, vcfg, grace_s, track_devices, analytics_cfg):
    """kai-resident: ONE fused program for a steady-state patched cycle.

    ``state`` is the device-resident snapshot (DONATED — the caller must
    never touch the passed-in value again, KAI081); ``delta`` the packed
    journal delta (``ops/resident.py``).  The chain that used to be up
    to four dispatches — fair-share division, the action pipeline,
    kai-pulse analytics, and the packed commit — runs as one XLA
    program over the in-place-updated state, so a steady cycle is: one
    small delta upload, one dispatch, one device sync.

    Returns ``(new_state, result, packed)``: the post-delta resident
    state for the next cycle (aliasing the donated buffers), the
    commit-set tensors, and the i16 commit array ``gather_host`` syncs.
    ``analytics_cfg=None`` is an analytics-skipped cadence cycle.
    """
    state = resident_ops.apply_delta(state, delta)
    fair_share = drf.set_fair_share(state, num_levels=num_levels,
                                    k_value=k_value)
    solved = state.replace(
        queues=state.queues.replace(fair_share=fair_share))
    res = run_actions(solved, fair_share, actions=actions,
                      num_levels=num_levels, acfg=acfg, vcfg=vcfg,
                      grace_s=grace_s)
    bundle = None
    if analytics_cfg is not None:
        bundle = cluster_analytics(solved, res, ages,
                                   config=analytics_cfg)
    packed = _PACK_COMMIT_FN(res, solved, track_devices=track_devices,
                             track_analytics=analytics_cfg is not None,
                             analytics=bundle)
    # the resident state returns WITHOUT the fair-share replacement:
    # fair share is derived per cycle, and the device state must stay
    # leaf-identical to the snapshotter's host mirror (verify compares)
    return state, res, packed


def _resident_donate_argnums() -> tuple[int, ...]:
    """Donate the resident state only on accelerator backends.

    Donation exists to update the snapshot in place in device memory —
    on the CPU backend there is no transfer to save, and XLA:CPU's
    donation path has been OBSERVED to corrupt the scattered-into state
    under the multi-device host config the test mesh uses (the fused
    program returns a state whose free pool drifted from the bitwise
    mirror; identical program without donation is exact).  The CPU
    carve-out keeps tier-1 bit-exactness unconditional; on TPU the
    ``verify_incremental`` device gather-and-compare is the guard.
    """
    try:
        backend = jax.default_backend()
    except Exception:  # noqa: BLE001 — no backend = nothing to donate
        return ()
    return () if backend == "cpu" else (0,)


#: jitted fused entries keyed by donation tuple — created LAZILY at the
#: first resident dispatch, never at import: an import-time
#: ``jax.default_backend()`` would both force backend initialisation on
#: every package import and freeze the CPU donation carve-out before
#: the process has picked its platform (a stale ``(0,)`` on a
#: later-selected CPU backend is exactly the corruption mode the
#: carve-out exists to prevent)
_RESIDENT_JIT_CACHE: dict = {}

#: static argnames of the resident fused entry — ONE source of truth
#: shared by the production jit build below and the kai-cost donation
#: audit (``analysis/costmodel.py``), which re-jits the same signature
#: with donation forced on
RESIDENT_STATIC_ARGNAMES = ("actions", "num_levels", "acfg", "vcfg",
                            "grace_s", "track_devices",
                            "analytics_cfg")


def _resident_jit():
    donate = _resident_donate_argnums()
    fn = _RESIDENT_JIT_CACHE.get(donate)
    if fn is None:
        # built ONCE per donation tuple and cached above — the KAI032
        # hazard (a fresh jit callable per call missing the compile
        # cache) cannot occur; the in-function build is deliberate so
        # the backend choice is read at first use, not at import
        fn = functools.partial(  # kai-lint: disable=KAI032
            jax.jit, donate_argnums=donate,
            static_argnames=RESIDENT_STATIC_ARGNAMES)(resident_cycle)
        _RESIDENT_JIT_CACHE[donate] = fn
        # forward the jit cache probe through the public watched
        # wrapper so the trace probe's compile-once assertion keeps
        # seeing the real cache
        probe = getattr(fn, "_cache_size", None)
        if probe is not None:
            _resident_cycle._cache_size = probe
        _resident_cycle.__kai_jit__ = fn
    return fn


@functools.wraps(resident_cycle)
def _resident_dispatch(*args, **kwargs):
    return _resident_jit()(*args, **kwargs)


_resident_cycle = compile_watch.watch("resident_cycle",
                                      _resident_dispatch)


@dataclasses.dataclass
class CycleResult:
    """Everything one ``runOnce`` decided (the Statement commit set)."""

    bind_requests: list[apis.BindRequest] = dataclasses.field(default_factory=list)
    evictions: list[apis.Eviction] = dataclasses.field(default_factory=list)
    #: pipelined rebinds for consolidation-moved victims
    move_bind_requests: list[apis.BindRequest] = dataclasses.field(
        default_factory=list)
    #: the on-device commit set threaded through the action pipeline
    tensors: AllocationResult | None = None
    #: action name -> wall seconds (ref per-action latency metrics).
    #: NOTE: kernels dispatch async — an action's time is dispatch cost;
    #: device execution overlaps and is absorbed by the ``device_wait``
    #: phase (the first host transfer syncs).
    action_seconds: dict[str, float] = dataclasses.field(default_factory=dict)
    session_seconds: float = 0.0
    #: Session.open wall seconds (host snapshot build + DRF dispatch)
    open_seconds: float = 0.0
    #: tensors→BindRequests/evictions + API writes wall seconds
    #: (= device_wait + host_decode + the commit phase's write section)
    commit_seconds: float = 0.0
    #: kai-trace phase attribution: contiguous checkpoints on ONE clock
    #: partition the cycle into snapshot / upload / solve_dispatch /
    #: device_wait / host_decode / commit, so the phases sum to the
    #: cycle wall time by construction (see runtime/tracing.py)
    phase_seconds: dict[str, float] = dataclasses.field(default_factory=dict)
    #: kai-wire per-cycle transfer summary (runtime/wire_ledger.py):
    #: bytes/leaves/dispatches/redundant-bytes by reason plus the
    #: device-residency gauge — the ledger window rolled at cycle end
    wire: dict = dataclasses.field(default_factory=dict)
    #: kai-pulse cluster-health document (ops/analytics.py) — empty on
    #: cycles the analytics cadence skipped (``analytics_every``)
    analytics: dict = dataclasses.field(default_factory=dict)
    #: host-side dispatch cost of the analytics pass (the device work
    #: itself overlaps the solve and lands in ``device_wait``)
    analytics_seconds: float = 0.0
    #: kai-repack migration-plan document (ops/repack.py) — empty on
    #: every cycle the trigger did not fire (the overwhelming majority:
    #: non-fired cycles dispatch nothing and ship zero extra bytes)
    repack: dict = dataclasses.field(default_factory=dict)
    #: host-side dispatch cost of the repack solve (0.0 when not fired)
    repack_seconds: float = 0.0
    #: kai-twin determinism anchors: the cycle's logical index and the
    #: per-cycle seed derived from ``SchedulerConfig.seed`` — pure
    #: functions of (config seed, cycle index), never of wall clock or
    #: process RNG, so two replays of the same stream observe identical
    #: pairs by construction (twin/replay.py digests them)
    cycle_index: int = 0
    cycle_seed: int = 0


def cycle_seed_for(seed: int, cycle_index: int) -> int:
    """Deterministic per-cycle seed: a splitmix64-style mix of the
    configured stream seed and the logical cycle index.  Stateless and
    wall-clock-free on purpose — this is the ONLY randomness anchor the
    decision path may consume, and it makes replay determinism a
    construction rather than an audit finding (kai-twin's oracle pins
    it per digest)."""
    mask = 0xFFFFFFFFFFFFFFFF
    x = (seed * 0x9E3779B97F4A7C15 + cycle_index + 1) & mask
    x ^= x >> 30
    x = (x * 0xBF58476D1CE4E5B9) & mask
    x ^= x >> 27
    x = (x * 0x94D049BB133111EB) & mask
    x ^= x >> 31
    return x & 0x7FFFFFFF


class Action(Protocol):
    """An action mutates the cycle's commit set — ref ``framework/interface.go``."""

    def __call__(self, session: Session, result: CycleResult) -> None: ...


_ACTION_REGISTRY: dict[str, Callable[[], Action]] = {}


def register_action(name: str):
    """ref ``framework.RegisterAction`` (``actions/factory.go:31-37``)."""
    def deco(builder: Callable[[], Action]):
        _ACTION_REGISTRY[name] = builder
        return builder
    return deco


def action_names() -> list[str]:
    return list(_ACTION_REGISTRY)


@register_action("allocate")
def _allocate_action() -> Action:
    def run(session: Session, result: CycleResult) -> None:
        result.tensors = allocate_jit(
            session.state, session.state.queues.fair_share,
            num_levels=session.config.num_levels,
            config=session.config.allocate,
            init=result.tensors)
    return run


def _victim_action(mode: str) -> Action:
    def run(session: Session, result: CycleResult) -> None:
        result.tensors = run_victim_action_jit(
            session.state, session.state.queues.fair_share, result.tensors,
            num_levels=session.config.num_levels, mode=mode,
            config=session.config.victims)
    return run


@register_action("reclaim")
def _reclaim_action() -> Action:
    """Cross-queue fairness enforcement — ref ``actions/reclaim``."""
    return _victim_action("reclaim")


@register_action("preempt")
def _preempt_action() -> Action:
    """Intra-queue priority preemption — ref ``actions/preempt``."""
    return _victim_action("preempt")


@register_action("consolidation")
def _consolidation_action() -> Action:
    """Evict-and-reallocate defragmentation — ref ``actions/consolidation``
    (every victim must be re-placed; see ``victim_move``)."""
    return _victim_action("consolidate")


@register_action("stalegangeviction")
def _stale_action() -> Action:
    """Evict gangs below minMember past grace — ref
    ``actions/stalegangeviction``."""
    def run(session: Session, result: CycleResult) -> None:
        result.tensors = stale_eviction_jit(
            session.state, result.tensors,
            grace_s=session.config.stale_grace_s,
            num_levels=session.config.num_levels)
    return run


#: builders as shipped — the fused pipeline only engages when the
#: configured actions still resolve to these (a re-registered override
#: must run through the per-action path)
_BUILTIN_BUILDERS = dict(_ACTION_REGISTRY)


@dataclasses.dataclass
class SchedulerConfig:
    """ref ``conf/scheduler_conf.go:49-62`` SchedulerConfiguration.

    Default action pipeline matches the reference default order
    (``conf_util/scheduler_conf_util.go:37``).
    """

    actions: tuple[str, ...] = ("allocate", "consolidation", "reclaim",
                                "preempt", "stalegangeviction")
    session: SessionConfig = dataclasses.field(default_factory=SessionConfig)
    schedule_period_s: float = 1.0
    #: the shard this instance serves: filters the snapshot to the
    #: shard's node-pool partition and applies the shard's args
    #: (placement strategy, k_value, queue depth) — ref SchedulingShard
    shard: apis.SchedulingShard | None = None
    node_pool_label_key: str = apis.NODE_POOL_LABEL_KEY
    #: HA: a shared runtime.leader.Lease gating the cycle — only the
    #: elected instance schedules (ref cmd/scheduler/app/server.go:60-63
    #: leader election); None = single instance, always leads
    leader_lease: object | None = None
    #: this instance's election identity (pod name in the reference)
    identity: str = "scheduler-0"
    #: continuous-profiling push target (ref ``pyroscope-address``
    #: flag, ``cmd/scheduler/app/options/options.go:110-113``); "" with
    #: profiler_sample_hz=0 leaves the sampler off, "" with a rate
    #: retains windows locally for ``/debug/pprof/continuous``
    pyroscope_address: str = ""
    #: wall-stack samples per second (the mutex/block-rate analogue for
    #: a Python runtime); None = unset (an address alone implies
    #: 100 Hz), an explicit 0 disables even with an address
    profiler_sample_hz: float | None = None
    #: journaled incremental snapshot refresh (state/incremental.py):
    #: re-derive only dirty rows each cycle instead of the full host
    #: rebuild, falling back to the full builder on structural change,
    #: feature pods, or churn above the threshold.  Disabled
    #: automatically for sharded instances (the shard filter re-shapes
    #: the object set per cycle).
    incremental: bool = True
    #: kai-resident (ops/resident.py): keep the snapshot resident on
    #: device across cycles — patched cycles upload only a packed
    #: journal delta and run the WHOLE dispatch chain (delta apply →
    #: fair share → action pipeline → analytics → packed commit) as
    #: one fused jit entry with donated state buffers.  Requires the
    #: incremental engine; structural changes fall back to the full
    #: build + re-upload path automatically.  Off by default so the
    #: classic per-leaf patch ship stays the verified reference path;
    #: the resident bench config and production deployments opt in.
    resident: bool = False
    #: after every patched refresh, rebuild from scratch and assert the
    #: patched ClusterState is element-wise identical (debug/CI flag).
    #: On the resident path this additionally gathers the device-
    #: resident state back and compares it leaf-wise against the host
    #: mirror after every fused apply (non-verify runs never read the
    #: donated state back).
    verify_incremental: bool = False
    #: dirty fraction above which patching falls back to a full rebuild
    incremental_dirty_threshold: float = 0.35
    #: kai-pulse cadence: run the cluster-health analytics kernel every
    #: K cycles (1 = every cycle, 0 = off).  Skipped cycles pay nothing
    #: — no dispatch, no extra bytes on the packed commit transfer.
    analytics_every: int = 1
    #: pending age (in cycles) at which a gang fires a ``starved``
    #: DecisionLog event + the starvation alarm gauges; 0 disables
    starvation_alarm_cycles: int = 32
    #: kai-repack (ops/repack.py): proactively migrate movable running
    #: pods to defragment rack-level capacity for a stranded gang.
    #: The trigger is host-side and cheap — it fires ONLY when the
    #: kai-pulse fragmentation score exceeded ``repack_frag_threshold``
    #: for ``repack_trigger_cycles`` CONSECUTIVE analytics cycles AND
    #: the last analytics doc shows a starving gang plus a
    #: cluster-feasible-but-rack-stranded ladder rung AND the snapshot
    #: carries required topology at all; every other cycle pays zero
    #: dispatches and zero wire bytes.  Disabled = byte-identical
    #: commits to the repack-free scheduler.
    repack_enable: bool = True
    #: kai-pulse ``frag_score`` above which a cycle counts toward the
    #: trigger streak
    repack_frag_threshold: float = 0.5
    #: consecutive high-fragmentation analytics cycles required to fire
    repack_trigger_cycles: int = 2
    #: cycles to wait after a firing (feasible or not) before the next
    #: — repack must never storm migrations
    repack_cooldown: int = 8
    #: per-firing migration cap and plan width; the effective budget is
    #: ``min(repack_max_migrations, VictimConfig.max_victim_pods)`` so
    #: repack can never out-migrate the victim machinery.  0 disables.
    repack_max_migrations: int = 64
    #: kai-intake (intake/router.py): the server's async multi-lane
    #: mutation front end — ``POST /intake`` hash-shards delta events
    #: into this many bounded lanes (one drain worker each), admission
    #: runs in vectorized batches, and the staged stream coalesces into
    #: the hub journal at cycle boundaries under the commit lock
    intake_lanes: int = 4
    #: per-lane bound on queued + staged events; overflow sheds (429)
    #: or degrades to sync per ``intake_policy``
    intake_lane_capacity: int = 65536
    #: lane-overflow policy: "shed" refuses the offered group atomically
    #: (HTTP 429, nothing journaled), "sync" drains inline + flushes a
    #: coalesce through the commit lock and retries (the classic
    #: single-writer behavior as the pressure valve, never the steady
    #: state)
    intake_policy: str = "shed"
    #: max events per worker drain round (the vectorized admission batch)
    intake_batch: int = 512
    #: kai-twin (twin/): the explicit determinism seed threaded through
    #: ``run_once`` — each cycle derives ``cycle_seed_for(seed, index)``
    #: onto its ``CycleResult``/trace, the only sanctioned randomness
    #: anchor on the decision path (wall clock feeds timings ONLY).
    #: Replays pin this from the stream header so same seed → same
    #: stream → bit-identical decisions twice.
    seed: int = 0
    #: attach a kai-twin stream recorder to the server's stored cluster
    #: at startup (``twin/stream.StreamRecorder`` via the shared intake
    #: applier's choke point); recording is ring-bounded and costs one
    #: list append per applied event
    twin_record: bool = True


def apply_shard_args(session: SessionConfig,
                     shard: apis.SchedulingShard) -> SessionConfig:
    """Render a shard's args over the base session config — the operator's
    per-shard config rendering (ref ``schedulingshard_types.go:34-64``)."""
    from ..ops.scoring import PlacementConfig
    placement = PlacementConfig(
        binpack_accel=(shard.placement_strategy_accel
                       == apis.PlacementStrategy.BINPACK),
        binpack_cpu=(shard.placement_strategy_cpu
                     == apis.PlacementStrategy.BINPACK))
    return dataclasses.replace(
        session,
        k_value=shard.k_value,
        allocate=dataclasses.replace(
            session.allocate, placement=placement,
            queue_depth=shard.queue_depth_per_action.get(
                "allocate", session.allocate.queue_depth)),
        victims=dataclasses.replace(
            session.victims,
            queue_depth=shard.queue_depth_per_action.get(
                "reclaim", session.victims.queue_depth)))


class Scheduler:
    """The cycle driver.  One instance per SchedulingShard.

    ``usage_lister`` (optional, a ``runtime.usagedb.UsageLister``) feeds
    time-based fairshare: each cycle polls it and threads the normalized
    per-queue usage into the snapshot, where the proportion kernel's
    ``k_value`` term consumes it (ref ``cache/usagedb``).
    """

    def __init__(self, config: SchedulerConfig | None = None,
                 usage_lister=None, status_updater=None, tracer=None):
        self.config = config or SchedulerConfig()
        #: kai-trace flight recorder: every cycle records its
        #: phase-attributed span tree into the tracer's bounded ring
        #: (served as Chrome-trace JSON by GET /debug/trace)
        self.tracer = tracer or CycleTracer()
        #: per-gang decision event log (GET /debug/events?gang=)
        self.decisions = DecisionLog()
        if self.config.shard is not None:
            self.config = dataclasses.replace(
                self.config,
                session=apply_shard_args(self.config.session,
                                         self.config.shard))
        self.usage_lister = usage_lister
        #: optional runtime.status_updater.AsyncStatusUpdater — fit
        #: failure / condition writes go through its worker pool instead
        #: of the cycle thread (ref cache/status_updater)
        self.status_updater = status_updater
        self._elector = None
        if self.config.leader_lease is not None:
            from ..runtime.leader import LeaderElector
            self._elector = LeaderElector(self.config.leader_lease,
                                          self.config.identity)
        #: cycle-side view of fit-failure counts whose status writes may
        #: still be queued (see _record_fit_status).  Scoped to ONE
        #: cluster document: the HTTP server reuses a Scheduler across
        #: POST /cycle requests, and a stale entry for a same-named gang
        #: of an unrelated document would inflate its failure count —
        #: ``_fit_shadow_cluster`` (a weakref) detects the switch and
        #: clears the shadow.
        self._fit_shadow: dict[str, int] = {}
        self._fit_shadow_cluster = None
        #: per-cluster incremental snapshotter (weakref-scoped like the
        #: fit shadow: the HTTP server reuses a Scheduler across
        #: documents, and a snapshotter only understands ONE journal)
        self._snapshotter = None
        self._snapshotter_cluster = None
        #: kai-pulse: gang name → pending age in cycles (host-owned so
        #: the counters survive snapshot reindexing; weakref-scoped to
        #: one cluster document like the fit shadow)
        self._pending_age: dict[str, int] = {}
        self._age_cluster = None
        #: cycles this Scheduler has run — drives the analytics cadence
        self._cycle_index = 0
        #: gang labels currently carrying a nonzero starvation-age
        #: gauge series — zeroed when they leave the top-K table, so a
        #: placed gang never keeps reporting its last starving age
        self._starv_gauge_gangs: set[str] = set()
        #: last kai-pulse document, served by GET /debug/cluster.
        #: Swapped whole (never mutated after publication) so handler
        #: threads read it without the server's state lock.
        #: (atomic-swap discipline: handler threads read the current
        #: binding; the cycle thread swaps in a fresh immutable dict)
        self._last_analytics: dict = {}
        #: kai-repack trigger state (host-owned, cycle-thread only):
        #: consecutive analytics cycles with frag_score above the
        #: threshold, cycles left in the post-firing cooldown, gangs a
        #: firing migrated for (name -> cycles left to observe the
        #: unblock), and the last firing's immutable plan document
        #: (atomic-swap, served by GET /debug/repack)
        self._frag_streak: int = 0
        self._repack_cooldown: int = 0
        self._repack_watch: dict[str, int] = {}
        self._last_repack: dict = {}
        self._actions: list[tuple[str, Action]] = [
            (name, _ACTION_REGISTRY[name]()) for name in self.config.actions]

    def _shard_filter(self, nodes, queues, groups, pods, topology):
        """Restrict the snapshot to this shard's partition (ref
        ``SchedulingNodePoolParams.GetLabelSelector``): label == value,
        or label-absent for the default (value-less) shard."""
        shard = self.config.shard
        key = self.config.node_pool_label_key
        if shard is None:
            return nodes, queues, groups, pods, topology
        val = shard.partition_label_value

        def selects(labels: dict) -> bool:
            # empty-string label values are legal: only None means "the
            # default shard" (label-absent selector)
            if val is None:
                return key not in labels
            return labels.get(key) == val

        nodes = [n for n in nodes if selects(n.labels)]
        groups = [g for g in groups if selects(g.labels)]
        keep = {g.name for g in groups}
        pods = [p for p in pods if p.group in keep]
        return nodes, queues, groups, pods, topology

    def _builtin_pipeline(self) -> bool:
        """True when every configured action still resolves to the
        shipped builders — the precondition for running the pipeline as
        one fused program (classic or resident)."""
        return all(name in _PURE_ACTIONS
                   and _ACTION_REGISTRY.get(name)
                   is _BUILTIN_BUILDERS.get(name)
                   for name in self.config.actions)

    def run_once(self, cluster: Cluster) -> CycleResult:
        """One scheduling cycle: snapshot → actions → commit set.

        Under leader election, a non-leader instance performs NO work
        and commits nothing (the reference's followers block inside
        ``leaderelection`` until elected)."""
        if self._elector is not None and not self._elector.is_leader(
                cluster.now):
            return CycleResult()
        t0 = time.perf_counter()
        with self.tracer.cycle() as trace:
            result = self._run_traced(cluster, trace, t0)
            trace.root.attrs.update(
                binds=len(result.bind_requests),
                evictions=len(result.evictions),
                cycle_index=result.cycle_index,
                cycle_seed=result.cycle_seed)
        return result

    def _run_traced(self, cluster: Cluster, trace, t0: float) -> CycleResult:
        """The cycle body, recorded under an open kai-trace cycle.
        Phase timings are CONTIGUOUS checkpoints on one clock, so
        ``phase_seconds`` partitions the wall time exactly (the
        acceptance property BENCH phase attribution relies on)."""
        from . import metrics
        with self.tracer.span("snapshot") as snap_sp:
            queue_usage = None
            if self.usage_lister is not None:
                self.usage_lister.maybe_fetch(cluster.now)
                queue_usage = self.usage_lister.queue_usage(cluster.now)
            # NOTE on concurrent status writes: the cycle NEVER blocks on
            # the async status pool (a slow store must not stall
            # scheduling — test-pinned), so a snapshot can race an
            # in-flight apply.  Each attribute store is GIL-atomic,
            # applies are serialized under the updater's apply_lock, and
            # the apply closures order their writes so every observable
            # prefix is a conservative state (see _record_fit_status) —
            # a racing snapshot at worst treats a gang as schedulable for
            # one extra cycle, never spuriously unschedulable with a
            # stale reason.
            upload_s = 0.0
            resident_mode = False
            staged_delta = None
            # kai-resident engages only over the built-in fused action
            # pipeline (an overridden action must run eagerly, outside
            # the one fused entry) and never for sharded instances
            use_resident = (self.config.resident
                            and self.config.incremental
                            and self.config.shard is None
                            and self._builtin_pipeline())
            if self.config.incremental and self.config.shard is None:
                # journaled incremental refresh: the snapshotter patches
                # the previous cycle's snapshot from the cluster's
                # mutation journal (dirty rows only, changed leaves only
                # to device), falling back to build_snapshot whenever the
                # patch cannot be proven identical — see
                # state/incremental.py
                if (self._snapshotter_cluster is None
                        or self._snapshotter_cluster() is not cluster):
                    from ..state.incremental import IncrementalSnapshotter
                    self._snapshotter = IncrementalSnapshotter(
                        verify=self.config.verify_incremental,
                        dirty_threshold=self.config
                        .incremental_dirty_threshold,
                        tracer=self.tracer)
                    self._snapshotter_cluster = weakref.ref(cluster)
                if use_resident:
                    # kai-resident: on patched cycles the snapshotter
                    # stages only a packed journal delta (uploaded as
                    # the cycle's ONE device_put) and the device state
                    # stays put; structural changes land here as mode
                    # "full" with a freshly built + re-uploaded state
                    rr = self._snapshotter.refresh_resident(
                        cluster, now=cluster.now,
                        queue_usage=queue_usage)
                    if rr.mode == "resident":
                        resident_mode = True
                        staged_delta = rr.delta
                        session = Session.resident(
                            rr.index, config=self.config.session,
                            host_state=rr.host)
                    else:
                        session = Session.from_state(
                            rr.state, rr.index,
                            config=self.config.session)
                        session.host_state = rr.host
                else:
                    state, index = self._snapshotter.refresh(
                        cluster, now=cluster.now,
                        queue_usage=queue_usage)
                    session = Session.from_state(
                        state, index, config=self.config.session)
                # journal-delta stats of THIS refresh onto the span:
                # mode (patched/full/resident), fallback reason, dirty
                # rows, changed leaves and bytes actually uploaded
                snap_sp.attrs.update(self._snapshotter.stats.last)
                upload_s = float(
                    self._snapshotter.stats.last.get("ship_seconds", 0.0))
            else:
                session = Session.open(
                    *self._shard_filter(*cluster.snapshot_lists()),
                    config=self.config.session,
                    now=cluster.now, queue_usage=queue_usage,
                    resource_claims=cluster.resource_claims,
                    device_classes=cluster.device_classes,
                    volume_claims=cluster.volume_claims,
                    storage_classes=cluster.storage_classes)
                snap_sp.attrs["mode"] = "open"
        t_open = time.perf_counter()
        open_s = t_open - t0
        metrics.open_session_latency.observe(value=open_s)
        result = CycleResult()
        # kai-twin determinism anchor: logical index + derived seed,
        # fixed before any action runs (pure function of config seed
        # and index — never of wall clock)
        result.cycle_index = self._cycle_index
        result.cycle_seed = cycle_seed_for(self.config.seed,
                                           self._cycle_index)
        if not resident_mode:
            result.tensors = init_result(session.state)
        result.open_seconds = open_s
        packed = None
        with self.tracer.span("solve_dispatch"):
            every = self.config.analytics_every
            run_analytics = every > 0 and self._cycle_index % every == 0
            self._cycle_index += 1
            bundle = None
            ages = None
            if resident_mode:
                # kai-resident fast path: delta apply + fair share +
                # action pipeline + analytics + packed commit as ONE
                # fused dispatch over the donated device-resident state
                cfg = session.config
                ta = time.perf_counter()
                if run_analytics:
                    ages = self._pending_age_vector(cluster, session)
                    ages_arg = ages
                else:
                    # cadence-skipped cycle: the fused entry never
                    # reads `ages` (analytics_cfg=None drops it at
                    # trace time) — a zeros placeholder skips the
                    # O(pending) host walk the classic path also
                    # skips.  `ages` itself stays None so the repack
                    # block below still computes REAL ages when its
                    # trigger fires on a non-analytics cycle (an
                    # all-zero vector would make every plan_repack
                    # target gate fail and burn the cooldown for
                    # nothing).
                    src = (session.host_state
                           if session.host_state is not None
                           else session.state)
                    ages_arg = np.zeros((src.gangs.g,), np.float32)
                with self.tracer.span("action:resident_cycle"):
                    donated = self._snapshotter.device_state
                    new_state, tensors, packed = _resident_cycle(
                        donated, staged_delta, ages_arg,
                        np.float32(cfg.k_value),
                        actions=tuple(self.config.actions),
                        num_levels=cfg.num_levels, acfg=cfg.allocate,
                        vcfg=cfg.victims, grace_s=cfg.stale_grace_s,
                        track_devices=session.index.needs_device_table,
                        analytics_cfg=(cfg.analytics if run_analytics
                                       else None))
                # `donated` is dead past this point (buffers consumed
                # in place); the post-delta state takes over as both
                # the session's state and the next cycle's resident base
                self._snapshotter.adopt_device_state(new_state)
                session.state = new_state
                result.tensors = tensors
                result.action_seconds["resident_cycle"] = \
                    time.perf_counter() - ta
                metrics.action_latency.observe(
                    "resident_cycle",
                    value=result.action_seconds["resident_cycle"])
                if self.config.verify_incremental:
                    self._snapshotter.verify_device_residency()
            elif self._builtin_pipeline():
                # fast path: the whole action pipeline as one compiled
                # program
                cfg = session.config
                ta = time.perf_counter()
                with self.tracer.span("action:pipeline"):
                    result.tensors = _fused_pipeline(
                        session.state, session.state.queues.fair_share,
                        actions=tuple(self.config.actions),
                        num_levels=cfg.num_levels, acfg=cfg.allocate,
                        vcfg=cfg.victims, grace_s=cfg.stale_grace_s)
                result.action_seconds["pipeline"] = \
                    time.perf_counter() - ta
                metrics.action_latency.observe(
                    "pipeline", value=result.action_seconds["pipeline"])
            else:
                for name, action in self._actions:
                    ta = time.perf_counter()
                    with self.tracer.span(f"action:{name}"):
                        action(session, result)
                    result.action_seconds[name] = time.perf_counter() - ta
                    metrics.action_latency.observe(
                        name, value=result.action_seconds[name])
            # kai-pulse: dispatch the cluster-health kernel over the
            # final commit set (ops/analytics.py) — async like the
            # actions above, so its device time overlaps and lands in
            # device_wait; the bundle rides the packed commit transfer.
            # (On resident cycles the kernel already ran INSIDE the
            # fused entry and the bundle is in `packed` — no dispatch.)
            if run_analytics and not resident_mode:
                ta = time.perf_counter()
                with self.tracer.span("analytics"):
                    ages = self._pending_age_vector(cluster, session)
                    bundle = cluster_analytics_jit(
                        session.state, result.tensors, ages,
                        config=session.config.analytics)
                result.analytics_seconds = time.perf_counter() - ta
            # kai-repack: dispatch the defragmentation solve ONLY when
            # the host trigger fires (ops/repack.py) — every other
            # cycle pays a few attribute reads and nothing else (the
            # zero-overhead-below-threshold acceptance bar)
            repack_plan = None
            if self._repack_trigger(cluster, session):
                ta = time.perf_counter()
                with self.tracer.span("repack"):
                    if ages is None:
                        ages = self._pending_age_vector(cluster, session)
                    # destinations draw on the POST-decision idle pool
                    # (result.tensors.free) so the plan never races the
                    # cycle's own placements for the same capacity
                    repack_plan = plan_repack_jit(
                        session.state, ages, result.tensors.free,
                        config=RepackConfig(
                            analytics=session.config.analytics,
                            max_migrations=min(
                                self.config.repack_max_migrations,
                                session.config.victims.max_victim_pods)))
                result.repack_seconds = time.perf_counter() - ta
                metrics.repack_trigger_firings.inc()
                metrics.repack_solve_seconds.observe(
                    value=result.repack_seconds)
        t_solve = time.perf_counter()
        # commit: translate the final tensors into BindRequests/evictions
        # and write them back through the API hub (Statement.Commit).
        # ONE batched device→host transfer feeds every host-side step —
        # the device_wait span brackets it as the cycle's explicit
        # device-sync marker (dispatches above were async, so this wait
        # is link + device time, not host work).
        with self.tracer.span("device_wait", device_sync=True):
            # ONE batched transfer: the packed commit (analytics bundle
            # and — on fired classic cycles — the repack plan ride it;
            # see Session.gather_host).  Resident cycles sync the
            # packed array the fused entry already produced.
            if resident_mode:
                host = session.gather_host(
                    result.tensors, packed=packed,
                    packed_analytics=run_analytics,
                    repack_plan=repack_plan)
            else:
                host = session.gather_host(
                    result.tensors, analytics=bundle,
                    repack_plan=repack_plan)
            plan_host = host.get("repack_plan")
        t_gather = time.perf_counter()
        repack_target = ""
        with self.tracer.span("host_decode"):
            result.bind_requests = session.bind_requests_from(
                result.tensors, host=host)
            result.evictions = session.evictions_from(
                result.tensors.victim, result.tensors.victim_move,
                host=host)
            if plan_host is not None:
                tg = int(plan_host["target_gang"])
                names = session.index.gang_names
                repack_target = names[tg] if 0 <= tg < len(names) else ""
                repack_evs = session.repack_evictions(
                    plan_host, host, repack_target)
                # repack migrations join the ONE eviction list: the
                # commit loop below moves them through the same
                # pipelined-rebind path as consolidation victims
                result.evictions = result.evictions + repack_evs
                self._record_repack(plan_host, repack_evs, repack_target,
                                    result)
        t_decode = time.perf_counter()
        with self.tracer.span("commit"):
            with self.tracer.span("writes"):
                for br in result.bind_requests:
                    cluster.create_bind_request(br)
                for ev in result.evictions:
                    # moved victims (consolidation moves AND kai-repack
                    # migrations) restart and get a pipelined rebind on
                    # their verified target node — evicted, not lost
                    # (ref consolidation.go allPodsReallocated + stmt
                    # pipelining); both flavors commit through the ONE
                    # Session.pipelined_rebind helper
                    cluster.evict_pod(ev.pod_name,
                                      restart=ev.move_to is not None)
                    if ev.move_to is not None:
                        rebind = session.pipelined_rebind(cluster, ev)
                        if rebind is not None:
                            result.move_bind_requests.append(rebind)
                            cluster.create_bind_request(rebind)
            result.commit_seconds = time.perf_counter() - t_solve
            with self.tracer.span("status_updates") as st_sp:
                self._record_fit_status(cluster, session, result, host)
                if self.status_updater is not None:
                    st_sp.attrs.update(
                        pending=self.status_updater.pending,
                        applied=self.status_updater.applied,
                        errors=self.status_updater.errors)
            events, dropped, counts = session.decision_events(
                result.tensors, host=host, evictions=result.evictions,
                limit=self.decisions.max_events_per_cycle,
                repack_for=repack_target)
            # kai-pulse starvation: advance the per-gang pending-age
            # counters and fire `starved` events for gangs crossing the
            # alarm threshold this cycle (crossings counted EXACTLY;
            # only event construction is bounded)
            starved, crossings = self._advance_starvation(
                cluster, session, host)
            if crossings:
                counts[gang_events.OUTCOME_STARVED] = crossings
                room = max(0, self.decisions.max_events_per_cycle
                           - len(events))
                events = events + starved[:room]
            self.decisions.record_cycle(trace.cycle_id, events,
                                        dropped=dropped, counts=counts)
            self._record_metrics(session, result, host)
            if host.get("analytics") is not None:
                result.analytics = session.analytics_doc(
                    host,
                    alarm_cycles=self.config.starvation_alarm_cycles)
                self._record_analytics(session, host)
                # atomic swap: published doc is never mutated, so
                # /debug/cluster reads it without the server state lock
                self._last_analytics = result.analytics
                # kai-repack trigger streak: consecutive analytics
                # cycles with the fragmentation gauge above threshold
                score = float(host["analytics"]["frag_score"])
                self._frag_streak = (
                    self._frag_streak + 1
                    if score > self.config.repack_frag_threshold else 0)
            # kai-repack unblock accounting: a gang a firing migrated
            # for that places within the observation window counts as
            # unblocked (the kai_repack_gangs_unblocked_total payoff
            # metric).  The dict is empty on every non-repack cycle.
            if self._repack_watch:
                self._watch_repack_unblocks(session, host)
            # kai-wire: close this cycle's transfer window.  The
            # summary rides the result (healthz/bench) and the trace as
            # Chrome counter lanes — bytes-on-wire and live-bytes step
            # charts aligned with the phase spans above.
            result.wire = _wire.LEDGER.roll_cycle(trace.cycle_id)
            trace.counters.append(("wire bytes/cycle", {
                "uploaded": result.wire["bytes"],
                "redundant": result.wire["redundant_bytes"]}))
            trace.counters.append(("device resident bytes", {
                "live": result.wire["resident_bytes"]}))
        t_end = time.perf_counter()
        result.phase_seconds = {
            "snapshot": max(0.0, open_s - upload_s),
            "upload": upload_s,
            "solve_dispatch": t_solve - t_open,
            "device_wait": t_gather - t_solve,
            "host_decode": t_decode - t_gather,
            "commit": t_end - t_decode,
        }
        for phase, secs in result.phase_seconds.items():
            metrics.cycle_phase_seconds.observe(phase, value=secs)
        result.session_seconds = time.perf_counter() - t0
        metrics.e2e_latency.observe(value=result.session_seconds)
        return result

    def _record_metrics(self, session: Session, result: CycleResult,
                        host: dict) -> None:
        """Per-cycle metric updates (ref metrics.go counters/gauges)."""
        from . import metrics
        from ..apis.types import RESOURCE_NAMES
        metrics.podgroups_considered.inc(
            by=float(host["attempted"].sum()))
        metrics.podgroups_scheduled.inc(
            "all", by=float(host["allocated"].sum()))
        # victim-wavefront counters ride the packed commit transfer
        # (AllocationResult.wavefront_stats): per action, chunk count,
        # lane occupancy, and sparse→dense fallbacks of this cycle
        ws = host.get("wavefront_stats")
        if ws is not None:
            for row, action in ((0, "reclaim"), (1, "preempt")):
                chunks, live, slots, fb, demo = (int(x) for x in ws[row])
                metrics.victim_wavefront_chunks.set(
                    action, value=float(chunks))
                metrics.victim_wavefront_lane_occupancy.set(
                    action, value=(live / slots) if slots else 0.0)
                if action == "preempt":
                    # reclaim has no sparse path or leftover demotion,
                    # so no fallback/demotion series
                    metrics.victim_wavefront_sparse_fallbacks.set(
                        action, value=float(fb))
                    metrics.victim_wavefront_leftover_demotions.set(
                        action, value=float(demo))
        # arrays come from the cycle's single batched transfer; change
        # detection is VECTORIZED against the previous cycle's tables so
        # the Python loop touches only cells that moved — O(changed)
        # rather than 3·Q·R dict probes per cycle (round-3 advisor)
        fs = host["fair_share"]
        alloc = host["queue_allocated"]
        usage = host["queue_usage"]
        prev = getattr(self, "_gauge_prev", None)
        if prev is None:
            prev = self._gauge_prev = {}
        qnames = tuple(session.index.queue_names)
        nq = len(qnames)
        for key, gauge, table in (("fs", metrics.queue_fair_share, fs),
                                  ("alloc", metrics.queue_allocated, alloc),
                                  ("usage", metrics.queue_usage, usage)):
            old = prev.get(key)
            # the diff is positional, so it is only valid while index →
            # queue-name is unchanged; any queue churn/reorder falls
            # back to a full update (a swapped queue with a coinciding
            # value would otherwise keep a stale series)
            if (old is not None and old[0] == qnames
                    and old[1].shape == table.shape):
                rows, cols = np.nonzero(old[1] != table)
            else:
                rows, cols = np.nonzero(np.ones_like(table, bool))
            for qi, ri in zip(rows.tolist(), cols.tolist()):
                if qi < nq:
                    gauge.set(qnames[qi], RESOURCE_NAMES[ri],
                              value=float(table[qi, ri]))
            prev[key] = (qnames, table.copy())

    @property
    def last_analytics(self) -> dict:
        """The most recent kai-pulse cluster-health document (empty
        before the first analytics cycle) — the ``GET /debug/cluster``
        payload.  Atomic-swap discipline: published docs are immutable."""
        return self._last_analytics

    def _scope_ages(self, cluster: Cluster) -> None:
        """Reset the pending-age counters — and the kai-repack trigger
        state derived from them — when the Scheduler is pointed at a
        different cluster document (the HTTP server reuses one
        Scheduler across documents — same discipline as the fit
        shadow)."""
        if (self._age_cluster is None
                or self._age_cluster() is not cluster):
            self._pending_age.clear()
            self._frag_streak = 0
            self._repack_cooldown = 0
            self._repack_watch.clear()
            # the trigger reads this doc — a new cluster must not
            # inherit the previous document's stranded/starving signal
            self._last_analytics = {}
            self._age_cluster = weakref.ref(cluster)

    # -- kai-repack (ops/repack.py) ---------------------------------------

    def _repack_trigger(self, cluster: Cluster,
                        session: Session) -> bool:
        """The host-side repack gate — a handful of attribute reads per
        cycle, no device work.  Fires when the fragmentation gauge has
        been high for ``repack_trigger_cycles`` consecutive analytics
        cycles AND the last kai-pulse doc shows a starving gang plus a
        cluster-feasible-but-rack-stranded ladder rung AND the snapshot
        carries required topology (no rack-required gang can exist
        without it), outside the post-firing cooldown."""
        cfg = self.config
        # scope BEFORE reading trigger state: a re-pointed Scheduler
        # must not fire off the previous cluster's streak/doc
        self._scope_ages(cluster)
        if (not cfg.repack_enable or cfg.repack_max_migrations <= 0
                or cfg.analytics_every <= 0):
            return False
        if self._repack_cooldown > 0:
            self._repack_cooldown -= 1
            return False
        if self._frag_streak < max(cfg.repack_trigger_cycles, 1):
            return False
        if not session.index.has_required_topology:
            return False
        doc = self._last_analytics
        if not doc:
            return False
        ladder = doc.get("fragmentation", {}).get("gang_ladder", ())
        stranded = any(r["cluster_feasible"] and not r["rack_placeable"]
                       for r in ladder)
        starving = bool(doc.get("starvation", {}).get("oldest"))
        return stranded and starving

    def _record_repack(self, plan: dict, executed: list,
                       target: str, result: CycleResult) -> None:
        """Account one repack firing: metrics, the cooldown that keeps
        repack from storming, the unblock watch, and the immutable
        ``GET /debug/repack`` plan document (atomic-swap)."""
        from . import metrics
        cfg = self.config
        planned = int(plan["num_moves"])
        metrics.repack_migrations_planned.inc(by=float(planned))
        metrics.repack_migrations_executed.inc(by=float(len(executed)))
        # cooldown applies whether or not the solve found a feasible
        # plan — an infeasible instance will stay infeasible until the
        # cluster changes, and re-solving it every cycle IS the storm
        self._repack_cooldown = max(cfg.repack_cooldown, 0)
        if executed and target:
            # +2, not +1: _watch_repack_unblocks already decrements this
            # entry later in the SAME cycle (the firing cycle, where the
            # target is pending by construction), so the window must
            # survive cooldown + 1 further cycles of observation
            self._repack_watch[target] = max(cfg.repack_cooldown, 0) + 2
        doc = {
            "feasible": bool(plan["feasible"]),
            "target_gang": target,
            "target_rack": int(plan["target_rack"]),
            "needed_unit_pods": float(plan["needed"]),
            "rack_units_before": float(plan["rack_units_before"]),
            "rack_units_after": float(plan["rack_units_after"]),
            "total_unit_pods": float(plan["total_units"]),
            "migrations_planned": planned,
            "migrations_executed": len(executed),
            "solve_seconds": result.repack_seconds,
            # complete by construction: executed is already bounded by
            # min(repack_max_migrations, VictimConfig.max_victim_pods)
            "moves": [{"pod": ev.pod_name, "to": ev.move_to}
                      for ev in executed],
        }
        result.repack = doc
        self._last_repack = doc

    def _watch_repack_unblocks(self, session: Session,
                               host: dict) -> None:
        from . import metrics
        names = session.index.gang_names
        allocated = host["allocated"]
        for nm in list(self._repack_watch):
            try:
                gi = names.index(nm)
            except ValueError:
                gi = -1
            if 0 <= gi < len(allocated) and allocated[gi]:
                metrics.repack_gangs_unblocked.inc()
                del self._repack_watch[nm]
                continue
            self._repack_watch[nm] -= 1
            if self._repack_watch[nm] <= 0:
                del self._repack_watch[nm]

    @property
    def last_repack(self) -> dict:
        """The most recent kai-repack firing's plan document (empty
        before the first firing) — atomic-swap discipline like
        ``last_analytics``."""
        return self._last_repack

    def repack_status(self) -> dict:
        """The ``GET /debug/repack`` payload: trigger knobs + live
        trigger state + the last firing's plan document."""
        cfg = self.config
        return {
            "ok": bool(self._last_repack),
            "enabled": cfg.repack_enable,
            "frag_threshold": cfg.repack_frag_threshold,
            "trigger_cycles": cfg.repack_trigger_cycles,
            "cooldown_cycles": cfg.repack_cooldown,
            "max_migrations": cfg.repack_max_migrations,
            "frag_high_streak": self._frag_streak,
            "cooldown_remaining": self._repack_cooldown,
            "last": self._last_repack,
        }

    def _pending_age_vector(self, cluster: Cluster,
                            session: Session) -> "np.ndarray":
        """f32 [G] — each gang slot's pending age BEFORE this cycle,
        aligned to the current snapshot (the host owns the name-keyed
        counters; the analytics kernel advances them on device for the
        top-K table, and ``_advance_starvation`` advances the host copy
        identically after decode)."""
        self._scope_ages(cluster)
        # shapes come from the host mirror on resident cycles (the
        # device state is not constructed until the fused dispatch)
        src = (session.host_state if session.host_state is not None
               else session.state)
        ages = np.zeros((src.gangs.g,), np.float32)
        if self._pending_age:
            names = session.index.gang_names
            valid = session.index.host_tables["gang_valid"]
            for gi in np.nonzero(valid[:len(names)])[0].tolist():
                a = self._pending_age.get(names[gi])
                if a:
                    ages[gi] = a
        return ages

    #: per-cycle bound on starved-event construction (the alarm fires
    #: once per gang at the crossing, so bursts only happen when many
    #: gangs starve in lockstep)
    MAX_STARVED_EVENTS = 64

    def _advance_starvation(self, cluster: Cluster, session: Session,
                            host: dict) -> tuple[list, int]:
        """Advance the per-gang pending-age counters from this cycle's
        outcome (+1 for still-pending gangs, reset on placement/exit)
        and return ``(events, crossings)``: bounded ``starved``
        GangDecision events for gangs whose age crossed
        ``starvation_alarm_cycles`` exactly this cycle, plus the EXACT
        crossing count (event construction is capped, the count never
        is — the DecisionLog summary invariant)."""
        alarm = self.config.starvation_alarm_cycles
        if alarm <= 0 and self.config.analytics_every <= 0:
            # feature fully off: no alarm to fire and no analytics
            # kernel consuming the ages — skip the O(pending) walk
            return [], 0
        self._scope_ages(cluster)
        names = session.index.gang_names
        valid = host["gang_valid"][:len(names)]
        alloc = host["allocated"][:len(names)]
        reasons = host["fit_reason"]
        old = self._pending_age
        new: dict[str, int] = {}
        starved: list = []
        crossings = 0
        qnames = session.index.queue_names
        queues_of = None
        for gi in np.nonzero(valid & ~alloc)[0].tolist():
            name = names[gi]
            age = old.get(name, 0) + 1
            new[name] = age
            if alarm > 0 and age == alarm:
                crossings += 1
                if len(starved) < self.MAX_STARVED_EVENTS:
                    code = int(reasons[gi])
                    if queues_of is None:
                        queues_of = session._gangs_queue_host()
                    qi = int(queues_of[gi])
                    starved.append(gang_events.GangDecision(
                        gang=name,
                        queue=(qnames[qi]
                               if 0 <= qi < len(qnames) else ""),
                        outcome=gang_events.OUTCOME_STARVED,
                        detail=(f"pending {age} cycles; blocker: "
                                + FIT_REASONS.get(code,
                                                  f"code {code}"))))
        # rebuilt each cycle: placed/vanished gangs fall out (the reset
        # path) and the dict never outgrows the live pending set
        self._pending_age = new
        return starved, crossings

    def _record_analytics(self, session: Session, host: dict) -> None:
        """kai_cluster_* / kai_gang_* gauge updates from the analytics
        bundle that rode this cycle's packed commit."""
        from . import metrics
        from ..apis.types import RESOURCE_NAMES
        a = host["analytics"]
        metrics.cluster_fragmentation_score.set(
            value=float(a["frag_score"]))
        metrics.cluster_largest_rack_gang.set(
            value=float(a["max_rack_units"]))
        metrics.cluster_free_unit_pods.set(value=float(a["total_units"]))
        metrics.cluster_goodput.set(value=float(a["goodput"]))
        metrics.cluster_fairness_drift_max.set(
            value=float(a["drift_max"]))
        metrics.cluster_fairness_drift_gini.set(
            value=float(a["drift_gini"]))
        metrics.cluster_pending_gangs.set(
            value=float(a["pending_gangs"]))
        for r, rn in enumerate(RESOURCE_NAMES):
            metrics.cluster_stranded_free_frac.set(
                rn, value=float(a["stranded_frac"][r]))
            metrics.cluster_utilization.set(rn, value=float(a["util"][r]))
        drift = a["queue_drift"]
        for qi, qn in enumerate(session.index.queue_names):
            metrics.cluster_fairness_drift.set(
                qn, value=float(drift[qi]))
        gnames = session.index.gang_names
        current: set[str] = set()
        for age, gi in zip(a["starv_age"].tolist(),
                           a["starv_gang"].tolist()):
            if age > 0 and 0 <= gi < len(gnames):
                metrics.gang_starvation_age.set(
                    gnames[gi], value=float(age))
                current.add(gnames[gi])
        # a gang that placed (or fell out of the top-K) must stop
        # reporting its last starving age — zero its stale series
        for name in self._starv_gauge_gangs - current:
            metrics.gang_starvation_age.set(name, value=0.0)
        self._starv_gauge_gangs = current

    def _record_fit_status(self, cluster: Cluster, session: Session,
                           result: CycleResult, host: dict) -> None:
        """Write fit failures back to PodGroup status — the
        status_updater's UnschedulableOnNodePool marking (ref
        ``cache/status_updater``, ``utils/pod_group_utils.go``): after
        ``scheduling_backoff`` consecutive failed cycles the group is
        marked unschedulable and the snapshot skips it until pod churn
        clears the condition (podgroup controller)."""
        allocated = host["allocated"]
        explanations = session.unschedulable_explanations(
            result.tensors, host=host)
        names = session.index.gang_names
        # touch only gangs whose status actually changed: successes reset,
        # failures (the explanations keys) accumulate — O(changed), not
        # O(G) Python work on the cycle path
        # Writes go through the async worker pool when configured, so a
        # slow status store never stalls the cycle (ref
        # cache/status_updater/concurrency.go); inline otherwise.  The
        # pool coalesces per key (latest wins), so every queued write is
        # an ABSOLUTE status computed on the cycle thread — the shadow
        # dict is the cycle's authoritative failure count while writes
        # are in flight (the reference's in-flight pod-group records).
        def write(key, fn):
            if self.status_updater is None:
                fn()
            else:
                self.status_updater.enqueue(key, fn)

        if (self._fit_shadow_cluster is None
                or self._fit_shadow_cluster() is not cluster):
            self._fit_shadow.clear()
            self._fit_shadow_cluster = weakref.ref(cluster)
        shadow = self._fit_shadow

        # Write ORDER inside the apply closures matters: a racing
        # snapshot (the cycle never blocks on the status pool) observes
        # some GIL-atomic prefix of these stores, so each prefix must be
        # a conservative state.  reset() clears the skip flag FIRST (a
        # partially-reset gang is at worst re-attempted with a stale
        # count); fail() sets the flag/phase LAST (a partially-failed
        # gang is at worst attempted one more cycle — never skipped with
        # a stale reason).
        def reset(group):
            def apply():
                group.unschedulable = False
                group.unschedulable_reason = ""
                group.fit_failures = 0
            return apply

        def fail(group, failures, reason):
            unsched = (group.scheduling_backoff >= 1
                       and failures >= group.scheduling_backoff)

            def apply():
                group.fit_failures = failures
                group.unschedulable_reason = reason
                if unsched:
                    group.phase = apis.PodGroupPhase.UNSCHEDULABLE
                    group.unschedulable = True
            return apply

        for gi in np.nonzero(allocated[:len(names)])[0]:
            group = cluster.pod_groups.get(names[gi])
            if group is None:
                continue
            had = shadow.get(names[gi])
            if had or group.fit_failures or group.unschedulable:
                # record the reset IN the shadow (0), don't drop the
                # entry: per-key coalescing means a later fail write can
                # supersede this queued reset, and reading the stale
                # pre-reset group.fit_failures then would prematurely
                # trip the unschedulable backoff
                shadow[names[gi]] = 0
                write(names[gi], reset(group))
        for name, reason in explanations.items():
            group = cluster.pod_groups.get(name)
            if group is None:
                continue
            failures = shadow.get(name, group.fit_failures) + 1
            shadow[name] = failures
            write(name, fail(group, failures, reason))

"""Cycle driver — ``scheduler.go`` ``Scheduler.Run``/``runOnce`` rebuilt.

The reference loop (``pkg/scheduler/scheduler.go:109-170``): every
``schedulePeriod`` open a session (snapshot + plugin init), execute the
configured action pipeline (default ``allocate, consolidation, reclaim,
preempt, stalegangeviction``), close the session (flush status).  The
TPU rebuild keeps that exact shape; each action is a host function that
invokes one compiled kernel and merges its commit set.

Actions register by name (ref ``actions/factory.go:31-37``
RegisterAction) so configuration strings select and order them the same
way ``SchedulerConfiguration.Actions`` does.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Protocol

from ..apis import types as apis
from ..ops.allocate import allocate_jit
from ..runtime.cluster import Cluster
from .session import Session, SessionConfig


@dataclasses.dataclass
class CycleResult:
    """Everything one ``runOnce`` decided (the Statement commit set)."""

    bind_requests: list[apis.BindRequest] = dataclasses.field(default_factory=list)
    evictions: list[apis.Eviction] = dataclasses.field(default_factory=list)
    #: action name -> wall seconds (ref per-action latency metrics)
    action_seconds: dict[str, float] = dataclasses.field(default_factory=dict)
    session_seconds: float = 0.0


class Action(Protocol):
    """An action mutates the cycle's commit set — ref ``framework/interface.go``."""

    def __call__(self, session: Session, result: CycleResult) -> None: ...


_ACTION_REGISTRY: dict[str, Callable[[], Action]] = {}


def register_action(name: str):
    """ref ``framework.RegisterAction`` (``actions/factory.go:31-37``)."""
    def deco(builder: Callable[[], Action]):
        _ACTION_REGISTRY[name] = builder
        return builder
    return deco


def action_names() -> list[str]:
    return list(_ACTION_REGISTRY)


@register_action("allocate")
def _allocate_action() -> Action:
    def run(session: Session, result: CycleResult) -> None:
        alloc = allocate_jit(
            session.state, session.state.queues.fair_share,
            num_levels=session.config.num_levels,
            config=session.config.allocate)
        result.bind_requests.extend(session.bind_requests_from(alloc))
    return run


@dataclasses.dataclass
class SchedulerConfig:
    """ref ``conf/scheduler_conf.go:49-62`` SchedulerConfiguration."""

    actions: tuple[str, ...] = ("allocate",)
    session: SessionConfig = dataclasses.field(default_factory=SessionConfig)
    schedule_period_s: float = 1.0


class Scheduler:
    """The cycle driver.  One instance per SchedulingShard."""

    def __init__(self, config: SchedulerConfig | None = None):
        self.config = config or SchedulerConfig()
        self._actions: list[tuple[str, Action]] = [
            (name, _ACTION_REGISTRY[name]()) for name in self.config.actions]

    def run_once(self, cluster: Cluster) -> CycleResult:
        """One scheduling cycle: snapshot → actions → commit set."""
        t0 = time.perf_counter()
        session = Session.open(
            *cluster.snapshot_lists(), config=self.config.session)
        result = CycleResult()
        for name, action in self._actions:
            ta = time.perf_counter()
            action(session, result)
            result.action_seconds[name] = time.perf_counter() - ta
        # commit: write BindRequests + evictions back through the API hub
        for br in result.bind_requests:
            cluster.create_bind_request(br)
        for ev in result.evictions:
            cluster.evict_pod(ev.pod_name)
        result.session_seconds = time.perf_counter() - t0
        return result

"""Cycle driver — ``scheduler.go`` ``Scheduler.Run``/``runOnce`` rebuilt.

The reference loop (``pkg/scheduler/scheduler.go:109-170``): every
``schedulePeriod`` open a session (snapshot + plugin init), execute the
configured action pipeline (default ``allocate, consolidation, reclaim,
preempt, stalegangeviction``), close the session (flush status).  The
TPU rebuild keeps that exact shape; each action is a host function that
invokes one compiled kernel and merges its commit set.

Actions register by name (ref ``actions/factory.go:31-37``
RegisterAction) so configuration strings select and order them the same
way ``SchedulerConfiguration.Actions`` does.
"""
from __future__ import annotations

import dataclasses
import time
from typing import Callable, Protocol

import functools

import jax

from ..apis import types as apis
from ..ops.allocate import AllocationResult, allocate_jit, init_result
from ..ops.stale import stale_gang_eviction
from ..ops.victims import run_victim_action_jit
from ..runtime.cluster import Cluster
from .session import Session, SessionConfig

stale_eviction_jit = functools.partial(jax.jit, static_argnames=(
    "grace_s", "num_levels"))(stale_gang_eviction)


@dataclasses.dataclass
class CycleResult:
    """Everything one ``runOnce`` decided (the Statement commit set)."""

    bind_requests: list[apis.BindRequest] = dataclasses.field(default_factory=list)
    evictions: list[apis.Eviction] = dataclasses.field(default_factory=list)
    #: pipelined rebinds for consolidation-moved victims
    move_bind_requests: list[apis.BindRequest] = dataclasses.field(
        default_factory=list)
    #: the on-device commit set threaded through the action pipeline
    tensors: AllocationResult | None = None
    #: action name -> wall seconds (ref per-action latency metrics)
    action_seconds: dict[str, float] = dataclasses.field(default_factory=dict)
    session_seconds: float = 0.0


class Action(Protocol):
    """An action mutates the cycle's commit set — ref ``framework/interface.go``."""

    def __call__(self, session: Session, result: CycleResult) -> None: ...


_ACTION_REGISTRY: dict[str, Callable[[], Action]] = {}


def register_action(name: str):
    """ref ``framework.RegisterAction`` (``actions/factory.go:31-37``)."""
    def deco(builder: Callable[[], Action]):
        _ACTION_REGISTRY[name] = builder
        return builder
    return deco


def action_names() -> list[str]:
    return list(_ACTION_REGISTRY)


@register_action("allocate")
def _allocate_action() -> Action:
    def run(session: Session, result: CycleResult) -> None:
        result.tensors = allocate_jit(
            session.state, session.state.queues.fair_share,
            num_levels=session.config.num_levels,
            config=session.config.allocate,
            init=result.tensors)
    return run


def _victim_action(mode: str) -> Action:
    def run(session: Session, result: CycleResult) -> None:
        result.tensors = run_victim_action_jit(
            session.state, session.state.queues.fair_share, result.tensors,
            num_levels=session.config.num_levels, mode=mode,
            config=session.config.victims)
    return run


@register_action("reclaim")
def _reclaim_action() -> Action:
    """Cross-queue fairness enforcement — ref ``actions/reclaim``."""
    return _victim_action("reclaim")


@register_action("preempt")
def _preempt_action() -> Action:
    """Intra-queue priority preemption — ref ``actions/preempt``."""
    return _victim_action("preempt")


@register_action("consolidation")
def _consolidation_action() -> Action:
    """Evict-and-reallocate defragmentation — ref ``actions/consolidation``
    (every victim must be re-placed; see ``victim_move``)."""
    return _victim_action("consolidate")


@register_action("stalegangeviction")
def _stale_action() -> Action:
    """Evict gangs below minMember past grace — ref
    ``actions/stalegangeviction``."""
    def run(session: Session, result: CycleResult) -> None:
        result.tensors = stale_eviction_jit(
            session.state, result.tensors,
            grace_s=session.config.stale_grace_s,
            num_levels=session.config.num_levels)
    return run


@dataclasses.dataclass
class SchedulerConfig:
    """ref ``conf/scheduler_conf.go:49-62`` SchedulerConfiguration.

    Default action pipeline matches the reference default order
    (``conf_util/scheduler_conf_util.go:37``).
    """

    actions: tuple[str, ...] = ("allocate", "consolidation", "reclaim",
                                "preempt", "stalegangeviction")
    session: SessionConfig = dataclasses.field(default_factory=SessionConfig)
    schedule_period_s: float = 1.0


class Scheduler:
    """The cycle driver.  One instance per SchedulingShard."""

    def __init__(self, config: SchedulerConfig | None = None):
        self.config = config or SchedulerConfig()
        self._actions: list[tuple[str, Action]] = [
            (name, _ACTION_REGISTRY[name]()) for name in self.config.actions]

    def run_once(self, cluster: Cluster) -> CycleResult:
        """One scheduling cycle: snapshot → actions → commit set."""
        t0 = time.perf_counter()
        session = Session.open(
            *cluster.snapshot_lists(), config=self.config.session,
            now=cluster.now)
        result = CycleResult(tensors=init_result(session.state))
        for name, action in self._actions:
            ta = time.perf_counter()
            action(session, result)
            result.action_seconds[name] = time.perf_counter() - ta
        # commit: translate the final tensors into BindRequests/evictions
        # and write them back through the API hub (Statement.Commit).
        result.bind_requests = session.bind_requests_from(result.tensors)
        result.evictions = session.evictions_from(
            result.tensors.victim, result.tensors.victim_move)
        for br in result.bind_requests:
            cluster.create_bind_request(br)
        for ev in result.evictions:
            # consolidation victims restart and get a pipelined rebind on
            # their verified target node — evicted, not lost
            # (ref consolidation.go allPodsReallocated + stmt pipelining)
            cluster.evict_pod(ev.pod_name, restart=ev.move_to is not None)
            if ev.move_to is not None:
                pod = cluster.pods.get(ev.pod_name)
                if pod is not None:
                    rebind = session.move_bind_request(pod, ev.move_to)
                    result.move_bind_requests.append(rebind)
                    cluster.create_bind_request(rebind)
        result.session_seconds = time.perf_counter() - t0
        return result

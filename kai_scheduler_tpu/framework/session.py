"""Session — the per-cycle unit of work.

Reference: ``framework/framework.go:33-79`` OpenSession builds a snapshot
and lets every plugin register callbacks on it; actions then drive the
cycle through those callbacks and a Statement transaction log, and
CloseSession flushes status.  Here the Session is a *value*: the
tensorized snapshot plus the solver outputs, and "commit" is a pure
translation from placement tensors back to BindRequest/Eviction objects
via the SnapshotIndex (the reverse of ``build_snapshot``).

The Statement's checkpoint/rollback machinery lives *inside* the
compiled kernels (functional state selection, see ``ops/allocate.py``);
by the time tensors reach the Session they are already committed in the
transactional sense — this mirrors how the reference only materializes
BindRequests at ``Statement.Commit`` (``framework/statement.go``).
"""
from __future__ import annotations

import dataclasses
import functools

import jax
import jax.numpy as jnp
import numpy as np

from ..apis import types as apis
from ..ops import analytics as pulse
from ..ops import drf
from ..runtime import compile_watch
from ..runtime import events as gang_events
from ..runtime import wire_ledger as _wire
from ..ops.allocate import AllocateConfig, AllocationResult
from ..ops.victims import VictimConfig
from ..state.cluster_state import (ClusterState, SnapshotIndex,
                                   _pow2_ceil, build_snapshot)

#: ``set_fair_share`` must run compiled: eagerly, the vmapped waterfill
#: while_loop re-traces (and recompiles) every cycle — measured ~2.5 s per
#: Session.open at 10k nodes vs ~ms jitted.  ``k_value`` rides as a traced
#: array so sweeping it never recompiles.
_set_fair_share_jit = compile_watch.watch(
    "set_fair_share",
    functools.partial(
        jax.jit, static_argnames=("num_levels",))(drf.set_fair_share))

#: The commit-path host bundle.  Two principles keep it small — it moves
#: through a tunneled TPU link whose D2H costs ~70 ms + ~0.2 ms/KB:
#: 1. snapshot-side arrays (task portions/requests, running-pod gangs,
#:    usage) came FROM the host at build time — the SnapshotIndex keeps
#:    the numpy originals, so only RESULT tensors transfer back;
#: 2. results pack into ONE i16 array (indices are < 32k; bools ride 8
#:    per lane; the small f32 queue tables bitcast to i16 pairs).


def _bitpack(b: jax.Array) -> jax.Array:
    """bool [K] → i16 [ceil(K/8)], bit k = element 8i+k (zero-padded —
    snapshot padding is caller-settable, so K need not divide 8)."""
    pad = (-b.shape[0]) % 8
    if pad:
        b = jnp.pad(b, (0, pad))
    pb = b.reshape(-1, 8).astype(jnp.int16)
    return jnp.sum(pb * (2 ** jnp.arange(8, dtype=jnp.int16)), axis=-1
                   ).astype(jnp.int16)


def _bitunpack(p: "np.ndarray", k: int) -> "np.ndarray":
    return (((p.astype(np.int32)[:, None] >> np.arange(8)) & 1)
            .astype(bool).reshape(-1)[:k])


@functools.partial(jax.jit, static_argnames=("track_devices",
                                              "track_analytics",
                                              "track_repack"))
def _pack_commit(result: AllocationResult, state: ClusterState,
                 *, track_devices: bool, track_analytics: bool = False,
                 analytics=None, track_repack: bool = False,
                 repack_plan=None) -> jax.Array:
    q = state.queues
    parts = [
        (result.placements + 1).ravel().astype(jnp.int16),
        _bitpack(result.pipelined.ravel()),
        _bitpack(result.allocated),
        _bitpack(result.attempted),
        result.fit_reason.astype(jnp.int16),
        _bitpack(result.victim),
        (result.victim_move + 1).astype(jnp.int16),
        jax.lax.bitcast_convert_type(
            result.queue_allocated, jnp.int16).ravel(),
        jax.lax.bitcast_convert_type(q.fair_share, jnp.int16).ravel(),
        jax.lax.bitcast_convert_type(
            result.wavefront_stats, jnp.int16).ravel(),
    ]
    if track_devices:
        parts.append(
            (result.placement_device + 1).ravel().astype(jnp.int16))
    if track_analytics:
        # kai-pulse: the cluster-health bundle rides the SAME packed
        # transfer (ops/analytics.py) — zero extra dispatches or bytes
        # beyond its own payload
        a32, ai = pulse.flatten(analytics)
        parts.append(
            jax.lax.bitcast_convert_type(a32, jnp.int16).ravel())
        parts.append(
            jax.lax.bitcast_convert_type(ai, jnp.int16).ravel())
    if track_repack:
        # kai-repack: a fired cycle's migration plan rides the packed
        # commit too (pod indices can exceed i16, so i32/f32 fields
        # bitcast to i16 pairs) — the plan never costs its own
        # device→host readback on the classic path
        parts.append(jax.lax.bitcast_convert_type(
            repack_plan.move_pod, jnp.int16).ravel())
        parts.append(jax.lax.bitcast_convert_type(
            repack_plan.move_node, jnp.int16).ravel())
        ints = jnp.stack([
            repack_plan.num_moves, repack_plan.target_gang,
            repack_plan.target_rack,
            repack_plan.feasible.astype(jnp.int32)])
        parts.append(
            jax.lax.bitcast_convert_type(ints, jnp.int16).ravel())
        fls = jnp.stack([
            repack_plan.needed, repack_plan.rack_units_before,
            repack_plan.rack_units_after, repack_plan.total_units])
        parts.append(
            jax.lax.bitcast_convert_type(fls, jnp.int16).ravel())
    return jnp.concatenate(parts)


# kai-wire compile watcher: per-(entry, signature) cache-miss
# attribution (runtime/compile_watch.py)
_pack_commit = compile_watch.watch("pack_commit", _pack_commit)


def _pow4_ceil(x: int) -> int:
    b = 1
    while b < int(x):
        b <<= 2
    return b


def _preempt_lane_width(batch_size: int, num_pending: int,
                        num_leaf_queues: int, padded_nodes: int) -> int:
    """Victim-wavefront lane width for preempt (auto-tuning v2).

    The chunk wants one lane per live preemptor up to a memory bound:
    every lane carries [N, R]-sized freed/score tensors through the
    placement vmap, so width is capped where B·N crosses ~4M elements
    (≈50 MB of f32 per per-lane tensor at R=3).  The final width is
    clamped to the snapshot's pending-gang count — junk lanes past the
    live preemptor spread pay full freed-pool cost for nothing.

    The width is a STATIC jit arg, so every distinct value compiles
    the victim kernels once: the spread buckets to powers of FOUR
    ({1, 4, 16, 64, 256} before the cap) so a cluster whose pending
    count wanders across cycles settles into a handful of compiled
    configs, at the price of ≤4x junk lanes at the narrow end where
    lanes are cheapest.  The memory cap itself halves in powers of TWO
    (512→256→128→64), so a node count crossing the B·N bound can add
    one off-bucket width (e.g. 128) to the compiled set."""
    cap = 512
    while cap > 64 and cap * max(padded_nodes, 1) > (1 << 22):
        cap //= 2
    if num_pending < 0:
        # hint unavailable (hand-built index): leaf-queue heuristic
        spread = num_leaf_queues if num_leaf_queues > 64 else batch_size
    else:
        spread = max(num_pending, 1)
    return max(1, min(cap, _pow4_ceil(spread)))


def _sparse_unit_width(padded_pods: int, num_leaf_queues: int) -> int:
    """Compact victim-table width when ``VictimConfig.sparse_unit_k``
    is None (auto): a few multiples of the mean running-pod count per
    leaf queue, pow2-bucketed, floored at 256 so sparsely-populated
    snapshots never shrink below a useful table.  An explicitly-set
    ``sparse_unit_k`` bypasses this entirely."""
    per_leaf = padded_pods // max(num_leaf_queues, 1)
    return max(256, min(1024, _pow2_ceil(4 * max(per_leaf, 1))))


#: fit_reason code → message (ref ``api/unschedule_info.go`` fit errors).
#: Module-level: a class attribute dict is shared across instances and
#: every thread touching any of them (KAI104)
FIT_REASONS = {
    1: ("no node satisfies the pod requirements "
        "(resources / selector / taints / affinity)"),
    2: "an equivalent pod group already failed this cycle",
    3: "placement attempt failed (capacity or queue gates)",
}


@dataclasses.dataclass
class SessionConfig:
    """Cycle-level knobs (ref ``conf/scheduler_conf.go`` SchedulerConfiguration)."""

    allocate: AllocateConfig = dataclasses.field(default_factory=AllocateConfig)
    victims: VictimConfig = dataclasses.field(default_factory=VictimConfig)
    #: kai-pulse cluster-health kernel knobs (ops/analytics.py); the
    #: cadence itself is a Scheduler-level knob (analytics_every)
    analytics: pulse.AnalyticsConfig = dataclasses.field(
        default_factory=pulse.AnalyticsConfig)
    #: derive kernel fast-path flags (track_devices / uniform_tasks) from
    #: the snapshot shape at session open — a snapshot with no fractional
    #: requests skips the per-device bookkeeping, and one whose gangs are
    #: all identical replicas uses the whole-gang placement kernel
    auto_tune: bool = True
    #: queue-hierarchy depth for fair-share recursion / capacity walks
    num_levels: int = 2
    #: proportion plugin kValue (time-based fairshare coupling)
    k_value: float = 0.0
    default_bind_backoff_limit: int = 3
    #: stalegangeviction grace period (ref options.go:34, default 60s)
    stale_grace_s: float = 60.0


def _auto_tune(config: SessionConfig, index: SnapshotIndex,
               padded_nodes: int, padded_running: int) -> SessionConfig:
    """Derive the kernel fast-path flags + wavefront widths from the
    snapshot's index hints and padded shapes — shared verbatim by the
    classic :meth:`Session.from_state` open and the kai-resident open
    (which has only the host mirror's shapes in hand), so the two paths
    always compile and run the SAME static config."""
    # a hierarchy deeper than the configured recursion would
    # leave leaf levels undivided — widen to the snapshot depth
    if index.max_queue_depth + 1 > config.num_levels:
        config = dataclasses.replace(
            config, num_levels=index.max_queue_depth + 1)
    devices = index.needs_device_table
    # the whole-gang kernel is exactly the sequential greedy
    # under BINPACK scoring only (a filling node's score rises,
    # so the greedy keeps hitting it — the capacity-count fill);
    # under spread the per-task loop re-ranks after every task,
    # so spread-configured shards keep the per-task kernel
    uniform = (index.uniform_gangs and not devices
               and config.allocate.placement.binpack_accel
               and config.allocate.placement.binpack_cpu)
    sub_topo = (index.has_subgroup_topology
                or index.has_required_topology)
    ext = index.has_extended_resources
    dense = index.dense_feasibility
    return dataclasses.replace(
        config,
        allocate=dataclasses.replace(
            config.allocate, track_devices=devices,
            uniform_tasks=uniform, subgroup_topology=sub_topo,
            extended=ext, dense_feasibility=dense,
            preferred_topology=index.has_preferred_topology,
            anti_groups=index.has_anti_groups,
            attract_groups=index.has_attract_groups),
        victims=dataclasses.replace(
            config.victims,
            chunk_reclaim=not index.has_reclaim_minruntime,
            # auto-tuning v2: lane width follows the snapshot's
            # live preemptor spread (clamped so junk lanes past
            # the pending-gang count stop paying freed-pool
            # cost) under a padded-node-count memory bound; the
            # compact victim-table width follows running-pod
            # density per leaf queue (see VictimConfig)
            batch_size_preempt=(
                _preempt_lane_width(
                    config.victims.batch_size,
                    index.num_pending_gangs,
                    index.num_leaf_queues, padded_nodes)
                if config.victims.batch_size_preempt is None
                else config.victims.batch_size_preempt),
            sparse_unit_k=(
                _sparse_unit_width(
                    padded_running, index.num_leaf_queues)
                if config.victims.sparse_unit_k is None
                else config.victims.sparse_unit_k),
            placement=dataclasses.replace(
                config.victims.placement, track_devices=devices,
                uniform_tasks=uniform, subgroup_topology=sub_topo,
                extended=ext, dense_feasibility=dense,
                preferred_topology=index.has_preferred_topology,
                anti_groups=index.has_anti_groups,
                attract_groups=index.has_attract_groups)))


@dataclasses.dataclass
class Session:
    """One cycle's snapshot + derived tensors."""

    state: ClusterState
    index: SnapshotIndex
    config: SessionConfig
    #: kai-resident: the snapshotter's numpy mirror of ``state``.  When
    #: set, host-side decode paths read snapshot columns (gang→queue)
    #: from it instead of pulling a device-resident leaf back over the
    #: wire — and never touch a leaf a donated dispatch may have
    #: consumed (KAI081).
    host_state: ClusterState | None = None

    @classmethod
    def open(
        cls,
        nodes: list[apis.Node],
        queues: list[apis.Queue],
        pod_groups: list[apis.PodGroup],
        pods: list[apis.Pod],
        topology: apis.Topology | None = None,
        config: SessionConfig | None = None,
        **snapshot_kwargs,
    ) -> "Session":
        """OpenSession: snapshot + proportion plugin share division."""
        config = config or SessionConfig()
        state, index = build_snapshot(
            nodes, queues, pod_groups, pods, topology, **snapshot_kwargs)
        return cls.from_state(state, index, config)

    @classmethod
    def from_state(cls, state: ClusterState, index: SnapshotIndex,
                   config: SessionConfig | None = None) -> "Session":
        """Open a session over an already-built snapshot — the entry the
        incremental snapshotter uses (``state/incremental.py``): auto-tune
        the kernel config from the index hints, then run the proportion
        plugin's share division exactly as :meth:`open` would."""
        config = config or SessionConfig()
        if config.auto_tune:
            config = _auto_tune(config, index, state.nodes.n,
                                state.running.m)
        fair_share = _set_fair_share_jit(
            state, num_levels=config.num_levels,
            k_value=jnp.float32(config.k_value))
        state = state.replace(queues=state.queues.replace(fair_share=fair_share))
        return cls(state=state, index=index, config=config)

    @classmethod
    def resident(cls, index: SnapshotIndex,
                 config: SessionConfig | None = None,
                 host_state: ClusterState | None = None) -> "Session":
        """Open a session for a kai-resident cycle: the snapshot is
        already resident on device and the WHOLE dispatch chain —
        fair-share division included — runs inside the one fused
        ``resident_cycle`` entry, so this constructor dispatches
        nothing.  Auto-tuning reads the host mirror's padded shapes
        (identical to the device state's by construction); ``state`` is
        assigned by the scheduler after the fused dispatch returns the
        post-delta device state."""
        config = config or SessionConfig()
        if config.auto_tune and host_state is not None:
            config = _auto_tune(config, index, host_state.nodes.n,
                                host_state.running.m)
        return cls(state=None, index=index, config=config,
                   host_state=host_state)

    def _gangs_queue_host(self) -> "np.ndarray":
        """The gang→queue column as host numpy — from the mirror when
        one exists (resident cycles must not read device leaves back,
        and must NEVER touch a donated previous-cycle state)."""
        src = self.host_state if self.host_state is not None else self.state
        return np.asarray(src.gangs.queue)

    # -- commit path ------------------------------------------------------

    def gather_host(self, result: AllocationResult,
                    analytics=None, *, packed=None,
                    packed_analytics: bool = False,
                    repack_plan=None) -> dict:
        """ONE compact device→host transfer of the cycle's results,
        merged with the snapshot-side numpy tables the host never let go
        of (see ``_pack_commit``).  ``analytics`` (an
        ``ops.analytics.AnalyticsBundle``, optional) rides the same
        packed array — the kai-pulse bundle never costs a second
        transfer — and so does a fired cycle's kai-repack plan
        (``repack_plan``), decoded into ``host["repack_plan"]``.

        kai-resident cycles pass ``packed=`` — the i16 commit array the
        fused ``resident_cycle`` entry already produced on device
        (``packed_analytics`` says whether the analytics bundle rode
        it); this method then only syncs that one array.  A repack plan
        on a resident cycle (rare: the trigger fired) is read back as
        one accounted batched ``LEDGER.device_get`` instead — the plan
        was solved in its own dispatch after the fused entry, so it
        cannot ride the fused pack.
        """
        g, q, r = self.state.gangs, self.state.queues, self.state.running
        G, T, M, Q = g.g, g.t, r.m, q.q
        R_ = self.state.nodes.free.shape[1]
        if self.state.nodes.n + 1 >= 2**15:
            # survives `python -O`: silently wrapped i16 node indices
            # would bind pods to the wrong nodes
            raise ValueError("i16 commit packing needs < 32k nodes")
        devices = self.index.needs_device_table
        plan_from_pack = repack_plan is not None and packed is None
        if packed is None:
            has_analytics = analytics is not None
            flat = np.asarray(_pack_commit(
                result, self.state, track_devices=devices,
                track_analytics=has_analytics, analytics=analytics,
                track_repack=plan_from_pack, repack_plan=repack_plan))
        else:
            has_analytics = packed_analytics
            flat = np.asarray(packed)

        def take(n):
            nonlocal off
            part = flat[off:off + n]
            off += n
            return part

        def bits(k):
            return (k + 7) // 8

        off = 0
        out = dict(self.index.host_tables)
        out["placements"] = (take(G * T).astype(np.int32) - 1
                             ).reshape(G, T)
        out["pipelined"] = _bitunpack(take(bits(G * T)),
                                      G * T).reshape(G, T)
        out["allocated"] = _bitunpack(take(bits(G)), G)
        out["attempted"] = _bitunpack(take(bits(G)), G)
        out["fit_reason"] = take(G).astype(np.int32)
        out["victim"] = _bitunpack(take(bits(M)), M)
        out["victim_move"] = take(M).astype(np.int32) - 1
        out["queue_allocated"] = np.frombuffer(
            take(Q * R_ * 2).tobytes(), np.float32).reshape(Q, R_)
        out["fair_share"] = np.frombuffer(
            take(Q * R_ * 2).tobytes(), np.float32).reshape(Q, R_)
        out["wavefront_stats"] = np.frombuffer(
            take(2 * 5 * 2).tobytes(), np.int32).reshape(2, 5)
        if devices:
            out["placement_device"] = (take(G * T).astype(np.int32) - 1
                                       ).reshape(G, T)
        else:
            out["placement_device"] = np.full((G, T), -1, np.int32)
        if has_analytics:
            acfg = self.config.analytics
            nf = pulse.f32_len(acfg, q=Q, r=R_, g=G)
            ni = pulse.i32_len(acfg, q=Q, r=R_, g=G)
            a32 = np.frombuffer(take(nf * 2).tobytes(), np.float32)
            ai = np.frombuffer(take(ni * 2).tobytes(), np.int32)
            out["analytics"] = pulse.host_unpack(
                a32, ai, config=acfg, q=Q, r=R_, g=G)
        if plan_from_pack:
            P = repack_plan.move_pod.shape[0]
            mp = np.frombuffer(take(2 * P).tobytes(), np.int32)
            mn = np.frombuffer(take(2 * P).tobytes(), np.int32)
            ints = np.frombuffer(take(8).tobytes(), np.int32)
            fls = np.frombuffer(take(8).tobytes(), np.float32)
            out["repack_plan"] = {
                "move_pod": mp, "move_node": mn,
                "num_moves": ints[0], "target_gang": ints[1],
                "target_rack": ints[2], "feasible": bool(ints[3]),
                "needed": fls[0], "rack_units_before": fls[1],
                "rack_units_after": fls[2], "total_units": fls[3]}
        elif repack_plan is not None:
            # resident cycle + fired trigger: the plan is tiny and
            # rare — one accounted batched readback through the ledger
            out["repack_plan"] = _wire.LEDGER.device_get(
                {f: getattr(repack_plan, f)
                 for f in repack_plan.__dataclass_fields__},
                reason="repack-plan")
        return out

    def bind_requests_from(self, result: AllocationResult,
                           host: dict | None = None) -> list[apis.BindRequest]:
        """Placement tensors → BindRequest objects (``cache.Bind`` analogue).

        Only gangs with ``allocated=True`` produce requests — the kernels
        guarantee those rows are internally consistent (all-or-nothing).
        Pipelined placements (tasks waiting on releasing/victim resources)
        do NOT bind yet: the reference queues them in the Statement and
        binds on a later cycle once capacity actually frees
        (``stmt.Pipeline`` vs ``stmt.Allocate``).
        """
        if host is None:
            host = self.gather_host(result)
        placements = host["placements"]
        devices = host["placement_device"]
        allocated = host["allocated"]
        pipelined = host["pipelined"]
        # columnar translation: vectorized selection + per-column gathers,
        # then ONE tight zip constructing the objects — never per-row
        # numpy scalar indexing (that was ~0.5 s at 50k placements)
        sel = allocated[:, None] & (placements >= 0) & ~pipelined
        sel[len(self.index.gang_names):] = False
        gi, ti = np.nonzero(sel)
        names = self.index.task_names_arr[gi, ti]
        keep = names != None  # noqa: E711  (object-array elementwise)
        if not keep.all():
            gi, ti, names = gi[keep], ti[keep], names[keep]
        node_names = self.index.node_names_arr[placements[gi, ti]]
        portion = host["task_portion"][gi, ti]
        mem = host["task_accel_mem"][gi, ti]
        is_frac = (portion > 0) | (mem > 0)
        count = np.where(
            is_frac, 0,
            np.rint(host["task_req0"][gi, ti]).astype(np.int64))
        dev = devices[gi, ti]
        dra = host["task_dra"][gi, ti]
        # DRA claim allocations: pods with real ResourceClaims record the
        # claim NAMES (the binder allocates concrete devices onto the
        # claim objects); bare dra_accel_count pods keep legacy integer
        # placeholders (ref ResourceClaimAllocations)
        claims = self.index.claims_by_pod
        frac_t = apis.ReceivedResourceType.FRACTION
        reg_t = apis.ReceivedResourceType.REGULAR
        backoff = self.config.default_bind_backoff_limit
        return [
            apis.BindRequest(
                pod_name=nm,
                selected_node=nn,
                received_resource_type=frac_t if fr else reg_t,
                received_accel_portion=po,
                received_accel_memory_gib=me,
                received_accel_count=ct,
                selected_accel_groups=[dv] if dv >= 0 else [],
                resource_claim_allocations=(
                    claims.get(nm) or list(range(dr))),
                backoff_limit=backoff,
            )
            for nm, nn, fr, po, me, ct, dv, dr in zip(
                names.tolist(), node_names.tolist(), is_frac.tolist(),
                portion.tolist(), mem.tolist(), count.tolist(),
                dev.tolist(), dra.tolist())
        ]

    def evictions_from(self, victim_mask, victim_move=None,
                       host: dict | None = None) -> list[apis.Eviction]:
        """Victim tensor [M] → Eviction objects (``cache.Evict`` analogue).

        ``victim_move`` ([M] node index, -1 = none) attaches the
        consolidation move target so the commit path can emit the
        pipelined rebind for the relocated pod.
        """
        if host is not None:
            mask = host["victim"].copy()
            moves_all = host["victim_move"]
            gang_all = host["running_gang"]
        else:
            mask = np.asarray(victim_mask).copy()
            moves_all = (None if victim_move is None
                         else np.asarray(victim_move))
            gang_all = np.asarray(self.state.running.gang)
        mask[len(self.index.running_pod_names):] = False
        mi = np.nonzero(mask)[0]
        names = self.index.running_pod_names_arr[mi]
        keep = names != ""
        if not keep.all():
            mi, names = mi[keep], names[keep]
        gangs = gang_all[mi]
        ok_g = (gangs >= 0) & (gangs < len(self.index.gang_names))
        if len(self.index.gang_names):
            groups = np.where(ok_g, self.index.gang_names_arr[
                np.clip(gangs, 0, len(self.index.gang_names) - 1)], "")
        else:
            groups = np.full(len(mi), "", object)
        if moves_all is None:
            targets = [None] * len(mi)
        else:
            moves = moves_all[mi]
            targets = [
                self.index.node_names[m] if m >= 0 else None
                for m in moves.tolist()]
        return [apis.Eviction(pod_name=nm, group=gr, move_to=mv)
                for nm, gr, mv in zip(names.tolist(), groups.tolist(),
                                      targets)]

    def unschedulable_explanations(
            self, result: AllocationResult,
            host: dict | None = None) -> dict[str, str]:
        """Per-gang fit-failure messages for gangs that ended the cycle
        unplaced — the UnschedulableExplanation surface."""
        if host is not None:
            reasons, allocated = host["fit_reason"], host["allocated"]
        else:
            reasons = np.asarray(result.fit_reason)
            allocated = np.asarray(result.allocated)
        out: dict[str, str] = {}
        # touch only failing gangs (O(failed), not O(G) int conversions)
        ng = len(self.index.gang_names)
        for gi in np.nonzero((reasons[:ng] != 0) & ~allocated[:ng])[0]:
            out[self.index.gang_names[gi]] = FIT_REASONS.get(
                int(reasons[gi]), f"code {int(reasons[gi])}")
        return out

    def analytics_doc(self, host: dict, *,
                      alarm_cycles: int = 0) -> dict:
        """The kai-pulse bundle as a JSON-able cluster-health document —
        the ``GET /debug/cluster`` payload and ``CycleResult.analytics``.
        Names come from the SnapshotIndex; array data from the bundle
        that rode this cycle's packed commit transfer (``host``)."""
        a = host.get("analytics")
        if a is None:
            return {}
        from ..apis.types import RESOURCE_NAMES
        acfg = self.config.analytics
        qnames = self.index.queue_names
        gnames = self.index.gang_names
        reasons = host["fit_reason"]
        queues_of = self._gangs_queue_host()
        drift = a["queue_drift"][:len(qnames)]
        top_q = np.argsort(-drift)[:5]
        oldest = []
        for age, gi in zip(a["starv_age"].tolist(),
                           a["starv_gang"].tolist()):
            if age <= 0 or not 0 <= gi < len(gnames):
                continue
            qi = int(queues_of[gi])
            code = int(reasons[gi])
            oldest.append({
                "gang": gnames[gi],
                "queue": qnames[qi] if 0 <= qi < len(qnames) else "",
                "age_cycles": int(age),
                "blocker": FIT_REASONS.get(code, f"code {code}")
                if code else "",
            })
        return {
            "fragmentation": {
                "score": round(float(a["frag_score"]), 4),
                "total_unit_pods": float(a["total_units"]),
                "largest_rack_unit_pods": float(a["max_rack_units"]),
                "unit_req": list(acfg.unit_req),
                "stranded_free_frac": {
                    RESOURCE_NAMES[r]: round(float(v), 4)
                    for r, v in enumerate(a["stranded_frac"].tolist())},
                "free_hist": {
                    RESOURCE_NAMES[r]: [int(c) for c in row]
                    for r, row in enumerate(a["free_hist"].tolist())},
                "gang_ladder": [
                    {"pods": int(p), "cluster_feasible": bool(c > 0),
                     "rack_placeable": bool(k > 0)}
                    for p, c, k in zip(acfg.gang_ladder,
                                       a["ladder_cluster_ok"].tolist(),
                                       a["ladder_rack_ok"].tolist())],
            },
            "utilization": {
                RESOURCE_NAMES[r]: round(float(v), 4)
                for r, v in enumerate(a["util"].tolist())},
            "goodput": round(float(a["goodput"]), 4),
            "fairness": {
                "drift_max": round(float(a["drift_max"]), 4),
                "drift_mean": round(float(a["drift_mean"]), 4),
                "drift_gini": round(float(a["drift_gini"]), 4),
                "top_drift": [
                    {"queue": qnames[int(qi)],
                     "drift": round(float(drift[int(qi)]), 4)}
                    for qi in top_q if drift[int(qi)] > 0],
            },
            "starvation": {
                "pending_gangs": int(a["pending_gangs"]),
                "alarm_cycles": int(alarm_cycles),
                "oldest": oldest,
            },
        }

    #: per-cycle caps on decision-event CONSTRUCTION (the commit path
    #: must not spend milliseconds building event objects; exact
    #: outcome COUNTS are always recorded regardless).  Failures keep
    #: the larger budget — they are the diagnostic payload — and
    #: ``allocated`` success events the smallest.
    MAX_FAILURE_EVENTS = 1024
    MAX_ALLOCATED_EVENTS = 512

    def decision_events(self, result: AllocationResult,
                        host: dict | None = None, evictions=None,
                        limit: int = 4096, repack_for: str = ""):
        """Per-gang outcome events for the cycle — the "why is my job
        not running" surface (``runtime/events.py``).  Returns
        ``(events, dropped, counts)``: a bounded list of
        :class:`~..runtime.events.GangDecision`, how many candidate
        events the bounds cut, and the EXACT per-outcome counts
        (computed vectorized, unaffected by truncation).

        Ordering is by diagnostic value: fit failures first (the answer
        an operator is actually looking for), then preemption victims,
        then allocations (bounded hardest — see
        ``MAX_ALLOCATED_EVENTS``).
        """
        if host is None:
            host = self.gather_host(result)
        names = self.index.gang_names
        ng = len(names)
        allocated = host["allocated"][:ng]
        reasons = host["fit_reason"][:ng]
        pipelined = host["pipelined"][:ng]
        queues_of = self._gangs_queue_host()[:ng]
        qnames = self.index.queue_names
        nq = len(qnames)

        def queue_name(gi: int) -> str:
            qi = int(queues_of[gi])
            return qnames[qi] if 0 <= qi < nq else ""

        out: list = []
        dropped = 0
        # beneficiaries of freed capacity: gangs whose placements
        # pipelined onto releasing/victim resources this cycle
        pipe_g = np.nonzero(pipelined.any(axis=1))[0]
        beneficiaries = ", ".join(names[int(g)] for g in pipe_g[:3])
        if len(pipe_g) > 3:
            beneficiaries += f", +{len(pipe_g) - 3} more"
        # exact outcome counts, vectorized — truncation below never
        # skews the /healthz summary
        failed = (reasons != 0) & ~allocated
        # victim GANGS split by eviction reason: kai-repack migrations
        # surface as `repacked-for`, everything else as `preempted-for`
        # — the commit path for both is the ONE pipelined-rebind
        # helper.  A gang can legitimately appear in BOTH sets in one
        # cycle (some pods migrated, others plainly preempted) and then
        # counts — and events below — report both outcomes.
        repack_groups = {ev.group for ev in evictions or ()
                         if ev.group and ev.reason == self.REPACK_REASON}
        plain_groups = {ev.group for ev in evictions or ()
                        if ev.group and ev.reason != self.REPACK_REASON}
        counts = {
            gang_events.OUTCOME_ALLOCATED: int(allocated.sum()),
            gang_events.OUTCOME_QUOTA_GATE: int(
                (failed & (reasons == 3)).sum()),
            gang_events.OUTCOME_FIT_FAILURE: int(
                (failed & (reasons != 3)).sum()),
            gang_events.OUTCOME_PREEMPTED_FOR: len(plain_groups),
            gang_events.OUTCOME_REPACKED_FOR: len(repack_groups),
        }
        counts = {k: v for k, v in counts.items() if v}
        # 1. fit failures (reason code -> outcome + FIT_REASONS detail).
        # Every section SLICES to its remaining room and counts the
        # overflow arithmetically — the loops never iterate past the
        # bound (this runs on the commit path of every cycle)
        fail_g = np.nonzero(failed)[0]
        take = fail_g[:min(limit, self.MAX_FAILURE_EVENTS)].tolist()
        dropped += len(fail_g) - len(take)
        for gi in take:
            code = int(reasons[gi])
            outcome = (gang_events.OUTCOME_QUOTA_GATE if code == 3
                       else gang_events.OUTCOME_FIT_FAILURE)
            out.append(gang_events.GangDecision(
                gang=names[gi], queue=queue_name(gi), outcome=outcome,
                detail=FIT_REASONS.get(code, f"code {code}")))
        # 2. preemption/reclaim/consolidation victims, one event per
        # victim GANG (bounded like everything else)
        if evictions:
            # first NON-repack eviction decides a group's plain "moved"
            # reading (the consolidation-move detail)
            moved: dict[str, bool] = {}
            entries: list[tuple[str, str]] = []
            seen: set[tuple[str, str]] = set()
            for ev in evictions:
                if not ev.group:
                    continue
                kind = ("repack" if ev.reason == self.REPACK_REASON
                        else "plain")
                if kind == "plain" and ev.group not in moved:
                    moved[ev.group] = ev.move_to is not None
                if (ev.group, kind) not in seen:
                    seen.add((ev.group, kind))
                    entries.append((ev.group, kind))
            room = max(0, limit - len(out))
            dropped += max(0, len(entries) - room)
            for group, kind in entries[:room]:
                if kind == "repack":
                    out.append(gang_events.GangDecision(
                        gang=group, queue="",
                        outcome=gang_events.OUTCOME_REPACKED_FOR,
                        detail=("repack move (pipelined rebind); "
                                f"frees a rack for: {repack_for}")))
                    continue
                detail = ("consolidation move (pipelined rebind)"
                          if moved.get(group)
                          else (f"freed capacity for: {beneficiaries}"
                                if beneficiaries else "over fair share"))
                out.append(gang_events.GangDecision(
                    gang=group, queue="",
                    outcome=gang_events.OUTCOME_PREEMPTED_FOR,
                    detail=detail))
        # 3. allocations (bounded hardest; the exact counts above keep
        # the summary honest about the rest)
        alloc_g = np.nonzero(allocated)[0]
        room = max(0, min(limit - len(out), self.MAX_ALLOCATED_EVENTS))
        take = alloc_g[:room].tolist()
        dropped += len(alloc_g) - len(take)
        pipe_set = set(pipe_g.tolist())
        for gi in take:
            out.append(gang_events.GangDecision(
                gang=names[gi], queue=queue_name(gi),
                outcome=gang_events.OUTCOME_ALLOCATED,
                detail=("pipelined onto releasing capacity"
                        if gi in pipe_set else "")))
        return out, dropped, counts

    def pipelined_rebind(self, cluster,
                         ev: apis.Eviction) -> apis.BindRequest | None:
        """THE pipelined-rebind path for a moved victim — consolidation
        moves and kai-repack migrations both commit through this one
        helper (the scheduler's commit loop calls it for every eviction
        carrying a ``move_to`` target), so the two can never drift in
        bind shape.  Returns None when the pod vanished between solve
        and commit."""
        pod = cluster.pods.get(ev.pod_name)
        if pod is None or ev.move_to is None:
            return None
        return self.move_bind_request(pod, ev.move_to)

    #: Eviction.reason marking a kai-repack migration (vs a plain
    #: consolidation move) — selects the ``repacked-for`` decision
    #: outcome; the bind/commit path is IDENTICAL for both
    REPACK_REASON = "repack"

    def repack_evictions(self, plan: dict, host: dict,
                         target_gang: str) -> list[apis.Eviction]:
        """A feasible repack plan (host copies of ``RepackPlan`` fields)
        → evictions with move targets, committed through the SAME
        pipelined-rebind path as consolidation moves.

        Cross-dispatch guards: pods the cycle's own victim actions
        already evicted are dropped (their capacity frees anyway), and
        a plan whose target gang placed this cycle is discarded whole
        (``[]``) — repack must never migrate for a gang that no longer
        needs it.
        """
        gi = int(plan["target_gang"])
        if (not bool(plan["feasible"]) or int(plan["num_moves"]) <= 0
                or not 0 <= gi < len(self.index.gang_names)
                or self.index.gang_names[gi] != target_gang):
            return []
        if host["allocated"][gi]:
            return []
        victim = host["victim"]
        out: list[apis.Eviction] = []
        names = self.index.running_pod_names_arr
        gang_all = host["running_gang"]
        ng = len(self.index.gang_names)
        for pi, ni in zip(plan["move_pod"].tolist(),
                          plan["move_node"].tolist()):
            if pi < 0 or ni < 0 or pi >= len(names) or victim[pi]:
                continue
            name = names[pi]
            if not name:
                continue
            gidx = int(gang_all[pi])
            out.append(apis.Eviction(
                pod_name=name,
                group=(self.index.gang_names[gidx]
                       if 0 <= gidx < ng else ""),
                reason=self.REPACK_REASON,
                move_to=self.index.node_names[ni]))
        return out

    def move_bind_request(self, pod: apis.Pod,
                          target_node: str) -> apis.BindRequest:
        """The pipelined rebind for a consolidation-moved victim: binds
        once the old pod has vacated and its replacement is pending —
        the persistent equivalent of the reference's pipelined victim
        re-allocation inside the committed Statement."""
        is_frac = pod.accel_portion > 0 or pod.accel_memory_gib > 0
        return apis.BindRequest(
            pod_name=pod.name,
            selected_node=target_node,
            received_resource_type=(
                apis.ReceivedResourceType.FRACTION if is_frac
                else apis.ReceivedResourceType.REGULAR),
            received_accel_portion=pod.accel_portion,
            received_accel_memory_gib=pod.accel_memory_gib,
            received_accel_count=(
                0 if is_frac else int(round(pod.resources.accel))),
            backoff_limit=self.config.default_bind_backoff_limit,
        )

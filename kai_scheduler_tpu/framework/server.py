"""Scheduler sidecar server — the PluginServer + the snapshot-in /
placements-out wire boundary.

Three reference surfaces collapse into one stdlib HTTP server:

- ``GET /job-order``  — the reflectjoborder plugin
  (``plugins/reflectjoborder``): the computed job order of the last (or
  an on-demand) session, for debugging fairness.
- ``GET /snapshot``   — the snapshot plugin (``plugins/snapshot``):
  the full cluster state as JSON, replayable by ``snapshot_tool.py``.
- ``POST /cycle``     — the sidecar protocol (SURVEY.md §7d): POST a
  cluster snapshot document, receive the cycle's commit set.  This is
  the cache→session boundary as a wire protocol, so a host harness in
  another language can mount the TPU solver behind its own registries.
- ``GET /metrics``    — Prometheus text exposition
  (``pkg/scheduler/metrics``).

The server is deliberately dependency-free (http.server); a production
deployment would front it with gRPC — the payloads are already the
stable JSON documents of ``runtime/snapshot.py``.
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..runtime.cluster import Cluster
from ..runtime.snapshot import dump_cluster, load_cluster
from . import metrics
from .scheduler import Scheduler
from .session import Session


def job_order(cluster: Cluster, scheduler: Scheduler) -> list[dict]:
    """The fairness-ordered gang list a cycle would attempt —
    reflectjoborder's payload."""
    from ..ops import ordering
    session = Session.open(*cluster.snapshot_lists(),
                           config=scheduler.config.session,
                           now=cluster.now)
    st = session.state
    perm = np.asarray(ordering.job_order_perm(
        st.gangs, st.queues, st.queues.allocated, st.queues.fair_share,
        st.total_capacity, st.gangs.valid))
    valid = np.asarray(st.gangs.valid)
    queues = np.asarray(st.gangs.queue)
    out = []
    for gi in perm.tolist():
        if gi < len(session.index.gang_names) and valid[gi]:
            out.append({
                "pod_group": session.index.gang_names[gi],
                "queue": session.index.queue_names[queues[gi]],
            })
    return out


def run_cycle_doc(doc: dict, scheduler: Scheduler | None = None) -> dict:
    """POST /cycle body → commit-set document (the sidecar protocol)."""
    cluster = load_cluster(doc)
    scheduler = scheduler or Scheduler()
    result = scheduler.run_once(cluster)
    return {
        "bind_requests": [{
            "pod": br.pod_name, "node": br.selected_node,
            "type": br.received_resource_type.value,
            "accel_count": br.received_accel_count,
            "accel_portion": br.received_accel_portion,
            "accel_memory_gib": br.received_accel_memory_gib,
            "accel_groups": br.selected_accel_groups,
        } for br in result.bind_requests],
        "evictions": [{
            "pod": ev.pod_name, "group": ev.group, "move_to": ev.move_to,
        } for ev in result.evictions],
        "action_seconds": result.action_seconds,
    }


class SchedulerServer:
    """Serve the debug/sidecar endpoints for one cluster + scheduler."""

    def __init__(self, cluster: Cluster, scheduler: Scheduler | None = None,
                 port: int = 0):
        self.cluster = cluster
        self.scheduler = scheduler or Scheduler()
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send(self, payload, code=200):
                body = json.dumps(payload).encode()
                self.send_response(code)
                self.send_header("Content-Type", "application/json")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_GET(self):  # noqa: N802
                if self.path == "/job-order":
                    self._send(job_order(outer.cluster, outer.scheduler))
                elif self.path == "/snapshot":
                    self._send(dump_cluster(outer.cluster))
                elif self.path == "/metrics":
                    body = metrics.registry.render().encode()
                    self.send_response(200)
                    self.send_header("Content-Type",
                                     "text/plain; version=0.0.4")
                    self.send_header("Content-Length", str(len(body)))
                    self.end_headers()
                    self.wfile.write(body)
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802
                if self.path != "/cycle":
                    self.send_error(404)
                    return
                length = int(self.headers.get("Content-Length", 0))
                try:
                    doc = json.loads(self.rfile.read(length).decode())
                    self._send(run_cycle_doc(doc, outer.scheduler))
                except Exception as exc:  # noqa: BLE001
                    self.send_error(400, str(exc))

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def start(self) -> "SchedulerServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        return self

    def stop(self) -> None:
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)

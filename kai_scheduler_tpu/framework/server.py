"""Scheduler sidecar server — the PluginServer + the snapshot-in /
placements-out wire boundary.

Reference surfaces collapse into one stdlib HTTP server:

- ``GET /job-order``  — the reflectjoborder plugin
  (``plugins/reflectjoborder``): the computed job order of the last (or
  an on-demand) session, for debugging fairness.
- ``GET /snapshot``   — the snapshot plugin (``plugins/snapshot``):
  the full cluster state as JSON, replayable by ``snapshot_tool.py``.
- ``POST /cycle``     — the sidecar protocol (SURVEY.md §7d): POST a
  cluster snapshot document, receive the cycle's commit set.  This is
  the cache→session boundary as a wire protocol, so a host harness in
  another language can mount the TPU solver behind its own registries.
- ``GET /metrics``    — Prometheus text exposition
  (``pkg/scheduler/metrics``).
- ``GET /debug/trace``  — the kai-trace flight recorder
  (``runtime/tracing.py``): the last N cycles' phase-attributed span
  trees as Chrome-trace JSON (``?cycles=`` bounds the window).
- ``GET /debug/events`` — per-gang decision events
  (``runtime/events.py``): every considered gang's cycle outcome
  (allocated / fit-failure / quota-gate / preempted-for);
  ``?gang=<name>`` filters to one pod group.
- ``GET /debug/wire``   — the kai-wire transfer ledger + compile
  watcher (``runtime/wire_ledger.py`` / ``runtime/compile_watch.py``):
  per-cycle, per-leaf host→device upload events with redundancy
  accounting, the device-residency gauge, and per-entry jit cache-miss
  attribution (``?cycles=`` bounds the ring window).
- ``GET /debug/cluster`` — the kai-pulse cluster-health document
  (``ops/analytics.py``): fragmentation (gang ladder, stranded
  capacity, free histograms), utilization/goodput, fairness drift, and
  the starvation top-K table of the latest analytics cycle.
- ``GET /debug/repack`` — the kai-repack defragmentation solver
  (``ops/repack.py``): trigger knobs, live trigger state (consecutive
  high-fragmentation cycles, cooldown remaining), and the last
  firing's bounded migration plan.
- ``GET /debug/intake`` — the kai-intake multi-lane mutation front end
  (``intake/router.py``): per-lane queued/staged depth, accepted/shed/
  rejected counters, recent admission rejections, coalesce totals.
- ``POST /intake``      — queue a delta document through the async
  lanes instead of applying it under the commit lock: hash-sharded by
  entity key, admission-checked in vectorized batches, coalesced into
  the hub journal at the next cycle boundary.  Lane overflow sheds
  with 429 (atomically per lane group — nothing journaled) or
  degrades to sync, per ``SchedulerConfig.intake_policy``.
- ``GET /debug``        — machine-readable index of every debug
  surface with one-line descriptions and live query params, so
  operators stop grepping this file.

The server is deliberately dependency-free (http.server); a production
deployment would front it with gRPC — the payloads are already the
stable JSON documents of ``runtime/snapshot.py``.
"""
from __future__ import annotations

import copy
import cProfile
import json
import pstats
import threading
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer

import numpy as np

from ..intake import apply as intake_apply
from ..intake.router import IntakeConfig, IntakeRouter
from ..runtime import compile_watch, wire_ledger
from ..runtime.cluster import Cluster
from ..runtime.snapshot import dump_cluster, load_cluster
from . import metrics
from .scheduler import Scheduler
from .session import Session


#: every debug surface the server mounts, with live query params — the
#: ``GET /debug`` index payload (an endpoint test pins this list
#: against the actual routes, so it cannot rot)
DEBUG_SURFACES = (
    {"path": "/debug", "params": (),
     "desc": "this index: every debug surface with query params"},
    {"path": "/debug/trace", "params": ("cycles",),
     "desc": ("kai-trace flight recorder: retained cycles' "
              "phase-attributed span trees as Chrome-trace JSON")},
    {"path": "/debug/events", "params": ("gang",),
     "desc": ("per-gang decision events: allocated / fit-failure / "
              "quota-gate / preempted-for / starved")},
    {"path": "/debug/wire", "params": ("cycles",),
     "desc": ("kai-wire transfer ledger + compile watcher: per-leaf "
              "uploads, redundancy accounting, device residency, "
              "per-entry jit cache misses")},
    {"path": "/debug/cluster", "params": (),
     "desc": ("kai-pulse cluster health: fragmentation gang ladder + "
              "stranded capacity, utilization/goodput, fairness "
              "drift, starvation top-K (latest analytics cycle)")},
    {"path": "/debug/repack", "params": (),
     "desc": ("kai-repack defragmentation solver: trigger knobs + live "
              "trigger state (frag streak, cooldown) and the last "
              "firing's bounded migration plan")},
    {"path": "/debug/intake", "params": (),
     "desc": ("kai-intake multi-lane mutation front end: per-lane "
              "queued/staged depth, accepted/shed/rejected counters, "
              "recent admission rejections, coalesce totals, worker "
              "liveness")},
    {"path": "/debug/twin", "params": ("stream",),
     "desc": ("kai-twin digital twin: stream recorder status "
              "(attached/events/dropped) and the last differential-"
              "oracle replay verdict; ?stream=1 inlines the full "
              "recorded stream document")},
    {"path": "/debug/pprof", "params": (),
     "desc": ("one profiled cycle (cProfile): hottest host functions "
              "+ kai-trace phase breakdown")},
    {"path": "/debug/pprof/continuous", "params": (),
     "desc": ("continuous-profiler folded-stack windows (404 while "
              "the sampler is off)")},
)


def job_order(cluster: Cluster, scheduler: Scheduler) -> list[dict]:
    """The fairness-ordered gang list a cycle would attempt —
    reflectjoborder's payload."""
    from ..ops import ordering
    session = Session.open(*cluster.snapshot_lists(),
                           config=scheduler.config.session,
                           now=cluster.now)
    st = session.state
    perm = np.asarray(ordering.job_order_perm(
        st.gangs, st.queues, st.queues.allocated, st.queues.fair_share,
        st.total_capacity, st.gangs.valid))
    valid = np.asarray(st.gangs.valid)
    queues = np.asarray(st.gangs.queue)
    out = []
    for gi in perm.tolist():
        if gi < len(session.index.gang_names) and valid[gi]:
            out.append({
                "pod_group": session.index.gang_names[gi],
                "queue": session.index.queue_names[queues[gi]],
            })
    return out


def profile_cycle(cluster: Cluster, scheduler: Scheduler,
                  top: int = 25) -> dict:
    """One scheduling cycle under cProfile — the pprof
    ``/debug/pprof/profile`` analogue (ref ``cmd/scheduler/profiling``):
    returns the hottest host-side functions plus the cycle's kai-trace
    phase breakdown (``CycleResult.phase_seconds`` — the tracer's
    attribution, not ad-hoc timers; device time is the ``device_wait``
    phase)."""
    # profile against private copies: a profiling GET must never write
    # bind requests or evictions into the server's stored cluster, and
    # the synthetic cProfile-inflated cycle must not pollute the LIVE
    # scheduler's trace ring / decision log or repoint its warm
    # incremental snapshotter at the throwaway deepcopy
    cluster = copy.deepcopy(cluster)
    scheduler = Scheduler(scheduler.config,
                          usage_lister=scheduler.usage_lister)
    prof = cProfile.Profile()
    prof.enable()
    result = scheduler.run_once(cluster)
    prof.disable()
    stats = pstats.Stats(prof)
    stats.sort_stats("cumulative")
    rows = []
    for func, (cc, nc, tt, ct, _) in stats.stats.items():  # type: ignore
        fname, line, name = func
        rows.append({"function": f"{fname}:{line}({name})",
                     "calls": nc, "total_s": round(tt, 6),
                     "cumulative_s": round(ct, 6)})
    rows.sort(key=lambda r: -r["cumulative_s"])
    return {
        "phases": dict(result.phase_seconds),
        "total_seconds": result.session_seconds,
        "action_seconds": result.action_seconds,
        "hottest": rows[:top],
    }


def apply_cluster_delta(cluster: Cluster, delta: dict) -> None:
    """Apply an incremental update to the stored cluster — the
    delta/incremental wire protocol: instead of shipping the full
    cluster document every cycle (tens of MB at 10k nodes × 50k pods),
    a sidecar PATCHes only what changed.  Collections accept
    ``{collection}_upsert`` (object docs, partial docs merge over the
    stored object) and ``{collection}_delete`` (names); ``now``
    advances the clock.

    This is the CLASSIC synchronous path — it delegates to the same
    decompose + apply pipeline the kai-intake router's coalesce replays
    (``intake/apply.py``), which is what makes the async lanes'
    storm-vs-sequential differential bar a shared-code identity rather
    than a parallel reimplementation."""
    intake_apply.apply_cluster_delta(cluster, delta)


def run_cycle_doc(doc: dict, scheduler: Scheduler | None = None) -> dict:
    """POST /cycle body → commit-set document (the sidecar protocol)."""
    cluster = load_cluster(doc)
    scheduler = scheduler or Scheduler()
    result = scheduler.run_once(cluster)
    return _commit_doc(result)


def _commit_doc(result) -> dict:
    return {
        "bind_requests": [{
            "pod": br.pod_name, "node": br.selected_node,
            "type": br.received_resource_type.value,
            "accel_count": br.received_accel_count,
            "accel_portion": br.received_accel_portion,
            "accel_memory_gib": br.received_accel_memory_gib,
            "accel_groups": br.selected_accel_groups,
        } for br in result.bind_requests],
        "evictions": [{
            "pod": ev.pod_name, "group": ev.group, "move_to": ev.move_to,
        } for ev in result.evictions],
        "action_seconds": result.action_seconds,
    }


class SchedulerServer:
    """Serve the debug/sidecar endpoints for one cluster + scheduler.

    Concurrency model: ``ThreadingHTTPServer`` runs every request in its
    own thread, so the stored cluster document and the (stateful)
    Scheduler are shared mutable state.  All handler access to them is
    serialized under ``_state_lock`` — payloads are computed under the
    lock and written to the socket after releasing it, so a slow client
    never stalls the next request's state access.  ``GET /healthz``
    serves ``_cycle_stats``, an immutable per-cycle stats document
    swapped (never mutated) after each cycle run through the server.
    The cluster/scheduler pair handed to a running server is owned by
    it: driving ``run_once`` on the same objects from another thread
    bypasses this lock.

    kai-intake (PR 12) shrinks what the lock serializes: mutations
    posted to ``POST /intake`` shard into the router's bounded lanes
    (their own locks), admission-check off the commit path, and touch
    ``_state_lock`` only at the cycle-boundary ``coalesce`` inside
    ``POST /cycle/stored``.  The classic ``POST /cluster/delta`` stays
    the synchronous reference path (same applier, applied immediately
    under the lock).
    """

    def __init__(self, cluster: Cluster, scheduler: Scheduler | None = None,
                 port: int = 0):
        self._state_lock = threading.Lock()
        self.cluster = cluster  # kai-race: guarded-by=_state_lock
        self.scheduler = scheduler or Scheduler()
        # kai-intake multi-lane front end: lanes/capacity/policy come
        # from the scheduler config (conf `intake.*` document keys).
        # The sync_flush valve lets policy="sync" degrade an overflowing
        # request to the classic behavior: quiesce the lanes and run a
        # coalesce under the commit lock, then retry.
        icfg = self.scheduler.config
        self.intake = IntakeRouter(
            IntakeConfig(lanes=icfg.intake_lanes,
                         lane_capacity=icfg.intake_lane_capacity,
                         policy=icfg.intake_policy,
                         batch=icfg.intake_batch),
            sync_flush=self._intake_flush)
        #: immutable per-cycle stats document (GET /healthz); handler
        #: threads swap in a fresh dict under _state_lock, readers take
        #: the current binding without it
        self._cycle_stats: dict | None = None  # kai-race: guarded-by=atomic-swap
        # kai-twin stream recorder: attached to the stored cluster so
        # the shared intake applier (intake/apply.py choke point)
        # mirrors every applied mutation; /cycle/stored appends cycle
        # marks.  The recorder is internally locked; the last oracle
        # verdict is an immutable atomic-swapped doc, so GET
        # /debug/twin and the healthz twin slice never take
        # _state_lock.
        self.recorder = None
        self._twin_doc: dict | None = None  # kai-race: guarded-by=atomic-swap
        if getattr(self.scheduler.config, "twin_record", False):
            from ..twin import stream as twin_stream
            self.recorder = twin_stream.StreamRecorder()
            self._twin_attach(cluster)
        # continuous profiling (the Pyroscope analogue) — created here,
        # STARTED in start() so a never-started server leaks no sampler
        self.profiler = None
        cfg = self.scheduler.config
        hz = getattr(cfg, "profiler_sample_hz", None)
        addr = getattr(cfg, "pyroscope_address", "")
        # an address with an UNSET rate defaults to 100 Hz; an explicit
        # rate of 0 keeps the sampler off even with an address
        if (hz or 0) > 0 or (addr and hz is None):
            from ..runtime.profiling import ContinuousProfiler
            self.profiler = ContinuousProfiler(
                sample_hz=hz if hz else 100.0,
                server_address=addr,
            )
        outer = self

        class Handler(BaseHTTPRequestHandler):
            def _send_text(self, body: bytes,
                           ctype: str = "text/plain",
                           code: int = 200) -> None:
                self.send_response(code)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def _send(self, payload, code=200):
                self._send_text(json.dumps(payload).encode(),
                                "application/json", code)

            def do_GET(self):  # noqa: N802
                # cluster/scheduler reads happen under the state lock;
                # the response is written AFTER release so a slow client
                # cannot hold every other endpoint hostage
                if self.path == "/job-order":
                    with outer._state_lock:
                        payload = job_order(outer.cluster, outer.scheduler)
                    self._send(payload)
                elif self.path == "/snapshot":
                    with outer._state_lock:
                        payload = dump_cluster(outer.cluster)
                    self._send(payload)
                elif self.path == "/healthz":
                    # _cycle_stats is swapped atomically (never mutated
                    # in place), so this read needs no lock; the
                    # kai-intake slice reads only lane/router locks —
                    # a health scrape never blocks behind the commit
                    # lock or a full intake lane
                    stats = outer._cycle_stats
                    self._send({"ok": True, "last_cycle": stats,
                                "intake": outer.intake.health(),
                                "twin": outer._twin_health()})
                elif self.path.startswith("/debug/trace"):
                    # kai-trace flight recorder: the retained cycle ring
                    # as Chrome-trace JSON.  Only the scheduler HANDLE
                    # is read under the state lock; the export itself
                    # runs outside it — the tracer rings only COMPLETED,
                    # immutable traces under its own lock, so the export
                    # can never tear and must not stall cycle POSTs.
                    params = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    try:
                        cycles = (int(params["cycles"][0])
                                  if "cycles" in params else None)
                    except ValueError:
                        self.send_error(400, "cycles must be an integer")
                        return
                    with outer._state_lock:
                        tracer = outer.scheduler.tracer
                    self._send(tracer.export_chrome(cycles=cycles))
                elif self.path.startswith("/debug/events"):
                    # per-gang decision events: ?gang=<name> filters.
                    # Same discipline as /debug/trace: handle under the
                    # lock, the (internally locked) log reads outside
                    params = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    gang = params.get("gang", [None])[0]
                    with outer._state_lock:
                        log = outer.scheduler.decisions
                    self._send({"gang": gang,
                                "events": log.events(gang=gang),
                                "summary": log.summary()})
                elif self.path.startswith("/debug/wire"):
                    # kai-wire transfer ledger + compile watcher: the
                    # rolled per-cycle upload ring (?cycles= bounds),
                    # residency gauge, and per-entry compile-miss
                    # attribution.  Computed OUTSIDE _state_lock —
                    # ledger/watcher are process-global and internally
                    # locked, ring entries are immutable once rolled,
                    # so the document can never tear and never stalls
                    # a concurrent cycle POST.
                    params = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    try:
                        cycles = (int(params["cycles"][0])
                                  if "cycles" in params else None)
                    except ValueError:
                        self.send_error(400, "cycles must be an integer")
                        return
                    doc = wire_ledger.LEDGER.wire_doc(cycles=cycles)
                    doc["compile"] = compile_watch.WATCHER.report()
                    self._send(doc)
                elif self.path.startswith("/debug/cluster"):
                    # kai-pulse cluster-health document: the LAST
                    # analytics cycle's immutable doc.  Only the
                    # scheduler handle is read under the state lock;
                    # the doc itself is atomic-swapped by the cycle
                    # thread and never mutated after publication, so
                    # this can never tear and never stalls a cycle.
                    with outer._state_lock:
                        sched = outer.scheduler
                    doc = sched.last_analytics
                    self._send({
                        "analytics": doc,
                        "analytics_every":
                            sched.config.analytics_every,
                        "starvation_alarm_cycles":
                            sched.config.starvation_alarm_cycles,
                        "ok": bool(doc)})
                elif self.path.startswith("/debug/intake"):
                    # kai-intake lane document: per-lane depth/shed/
                    # rejection stats + coalesce totals.  Computed from
                    # the router's own per-lane and router locks ONLY —
                    # never _state_lock — so a scrape can never block
                    # behind a running cycle or a full intake lane.
                    self._send(outer.intake.debug_doc())
                elif self.path.startswith("/debug/repack"):
                    # kai-repack status: knobs + trigger state + the
                    # LAST firing's plan doc.  Same discipline as
                    # /debug/cluster — only the scheduler handle is
                    # read under the state lock; the plan doc is
                    # atomic-swapped by the cycle thread and never
                    # mutated after publication, the trigger counters
                    # are single-writer ints (GIL-atomic reads).
                    with outer._state_lock:
                        sched = outer.scheduler
                    self._send(sched.repack_status())
                elif self.path.startswith("/debug/twin"):
                    # kai-twin status: recorder stats + the last
                    # differential-oracle verdict; ?stream=1 inlines
                    # the recorded stream document.  NO _state_lock —
                    # the recorder is internally locked and the
                    # verdict doc is atomic-swapped, so this scrape
                    # can never block behind a running cycle.
                    params = urllib.parse.parse_qs(
                        urllib.parse.urlparse(self.path).query)
                    rec = outer.recorder
                    twin = outer._twin_doc or {}
                    doc = {"recording": rec is not None
                           and rec.attached,
                           "recorder": rec.stats() if rec else None,
                           "last_replay": twin.get("last_replay")}
                    if rec is not None and params.get("stream"):
                        doc["stream"] = rec.doc()
                    self._send(doc)
                elif self.path in ("/debug", "/debug/"):
                    # index of every debug surface — static doc plus
                    # which optional surfaces are live right now
                    surfaces = [dict(s, params=list(s["params"]))
                                for s in DEBUG_SURFACES]
                    for s in surfaces:
                        if s["path"] == "/debug/pprof/continuous":
                            s["live"] = outer.profiler is not None
                        else:
                            s["live"] = True
                    self._send({"surfaces": surfaces})
                elif self.path.startswith("/debug/pprof/continuous"):
                    # the continuous-profiling (Pyroscope) analogue:
                    # retained folded-stack windows (profiler state is
                    # internally locked)
                    if outer.profiler is None:
                        self.send_error(404, "continuous profiler off")
                        return
                    self._send_text(outer.profiler.render().encode())
                elif self.path.startswith("/debug/pprof"):
                    # the --enable-profiler pprof endpoint analogue
                    with outer._state_lock:
                        payload = profile_cycle(outer.cluster,
                                                outer.scheduler)
                    self._send(payload)
                elif self.path == "/metrics":
                    # Registry.render snapshots each metric under its
                    # own lock — the text is a consistent point-in-time
                    # view even while a cycle thread observes
                    self._send_text(metrics.registry.render().encode(),
                                    "text/plain; version=0.0.4")
                else:
                    self.send_error(404)

            def _send_pb(self, msg, code=200):
                body = msg.SerializeToString()
                self.send_response(code)
                self.send_header("Content-Type", "application/x-protobuf")
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def do_POST(self):  # noqa: N802
                length = int(self.headers.get("Content-Length", 0))
                # the sidecar protocol speaks two framings over the same
                # endpoints: the stable JSON documents, and the typed
                # protobuf schema (wire/sidecar.proto — SURVEY §7d's
                # proto boundary; HTTP Content-Length is the length
                # prefix).  Content-Type selects.
                proto = self.headers.get(
                    "Content-Type", "").startswith("application/x-protobuf")
                try:
                    # socket read happens before taking the state lock;
                    # the reply goes out after releasing it
                    body = self.rfile.read(length)
                    if proto:
                        from ..wire import codec, sidecar_pb2 as pb
                        if self.path == "/cycle":
                            doc = pb.ClusterDoc()
                            doc.ParseFromString(body)
                            # deserialize outside the lock (a tens-of-MB
                            # snapshot must not stall other endpoints)
                            cycle_cluster = codec.cluster_from_msg(doc)
                            with outer._state_lock:
                                result = outer.scheduler.run_once(
                                    cycle_cluster)
                                outer._record_cycle(result)
                            self._send_pb(codec.commit_to_msg(result))
                        elif self.path == "/cluster":
                            doc = pb.ClusterDoc()
                            doc.ParseFromString(body)
                            fresh = codec.cluster_from_msg(doc)  # no lock
                            with outer._state_lock:
                                outer.cluster = fresh
                                outer._twin_attach(fresh)
                            self._send_pb(pb.CommitSet())
                        elif self.path == "/cluster/delta":
                            delta = pb.ClusterDelta()
                            delta.ParseFromString(body)
                            with outer._state_lock:
                                codec.apply_delta_msg(outer.cluster, delta)
                            self._send_pb(pb.CommitSet())
                        elif self.path == "/cycle/stored":
                            with outer._state_lock:
                                outer.intake.coalesce(outer.cluster)
                                result = outer.scheduler.run_once(
                                    outer.cluster)
                                outer._record_cycle(result)
                                if outer.recorder is not None:
                                    outer.recorder.record_cycle()
                            self._send_pb(codec.commit_to_msg(result))
                        else:
                            self.send_error(404)
                        return
                    if self.path == "/cycle":
                        doc = json.loads(body.decode())
                        cycle_cluster = load_cluster(doc)
                        with outer._state_lock:
                            result = outer.scheduler.run_once(
                                cycle_cluster)
                            outer._record_cycle(result)
                        self._send(_commit_doc(result))
                    elif self.path == "/cluster":
                        # replace the stored cluster (upload once ...)
                        doc = json.loads(body.decode())
                        fresh = load_cluster(doc)
                        with outer._state_lock:
                            outer.cluster = fresh
                            outer._twin_attach(fresh)
                        self._send({"ok": True})
                    elif self.path == "/cluster/delta":
                        # ... then PATCH deltas instead of re-shipping
                        # the full document every cycle
                        doc = json.loads(body.decode())
                        with outer._state_lock:
                            apply_cluster_delta(outer.cluster, doc)
                        self._send({"ok": True})
                    elif self.path == "/intake":
                        # kai-intake: queue the delta through the async
                        # multi-lane front end instead of applying it
                        # under the commit lock.  Parse + lane offers
                        # touch NO server state lock; the staged events
                        # coalesce into the hub at the next cycle
                        # boundary.  A backpressured (shed) request
                        # reports 429 with the per-request counts —
                        # atomically refused per lane group, nothing
                        # journaled.
                        doc = json.loads(body.decode())
                        # all-or-nothing at the HTTP boundary: a 429
                        # means NOTHING was queued, so a client's
                        # blind full retry can never double-apply a
                        # partially accepted delta.  Counts only on
                        # the wire — the shed ops echo is for
                        # in-process retriers.
                        out = outer.intake.submit_delta(
                            doc, all_or_nothing=True)
                        self._send({"accepted": out["accepted"],
                                    "shed": out["shed"],
                                    "total": out["total"]},
                                   code=429 if out["shed"] else 200)
                    elif self.path == "/cycle/stored":
                        # run a cycle against the stored cluster: the
                        # incremental sidecar protocol's execute step.
                        # Cycle boundary = the kai-intake coalesce
                        # point: staged lane events merge into the hub
                        # journal (global seq order) before the cycle
                        # snapshots it.
                        with outer._state_lock:
                            outer.intake.coalesce(outer.cluster)
                            result = outer.scheduler.run_once(
                                outer.cluster)
                            outer._record_cycle(result)
                            if outer.recorder is not None:
                                outer.recorder.record_cycle()
                        self._send(_commit_doc(result))
                    elif self.path == "/twin/record":
                        # kai-twin recorder control: start re-anchors
                        # the stream at the CURRENT stored cluster,
                        # stop freezes it (the stream stays readable
                        # through /debug/twin?stream=1)
                        doc = json.loads(body.decode()) if body else {}
                        action = doc.get("action", "start")
                        if outer.recorder is None:
                            self.send_error(
                                400, "twin recording disabled "
                                     "(twinRecord: false)")
                            return
                        with outer._state_lock:
                            if action in ("start", "reset"):
                                outer._twin_attach(outer.cluster)
                            elif action == "stop":
                                outer.recorder.detach()
                                outer.cluster.twin_recorder = None
                            else:
                                self.send_error(
                                    400, f"unknown action {action!r}")
                                return
                        self._send({"ok": True, "action": action,
                                    "recorder":
                                        outer.recorder.stats()})
                    elif self.path == "/twin/replay":
                        # differential-oracle replay of the recorded
                        # stream: snapshot the stream under the
                        # recorder's own lock, replay it twice OUTSIDE
                        # _state_lock (a long replay must never stall
                        # the live scheduler), then atomic-swap the
                        # verdict for /debug/twin and healthz.
                        if (outer.recorder is None
                                or not outer.recorder.attached):
                            self.send_error(
                                400, "no twin stream recorded")
                            return
                        stream = outer.recorder.stream()
                        from ..twin import replay as twin_replay
                        verdict = twin_replay.oracle(stream)
                        outer._twin_doc = {"last_replay": verdict}
                        self._send(verdict)
                    else:
                        self.send_error(404)
                except Exception as exc:  # noqa: BLE001
                    self.send_error(400, str(exc))

            def log_message(self, *args):
                pass

        self._httpd = ThreadingHTTPServer(("127.0.0.1", port), Handler)
        self.port = self._httpd.server_address[1]
        self._thread: threading.Thread | None = None

    def _twin_attach(self, cluster: Cluster) -> None:
        """(Re-)anchor the recorder: snapshot the stored cluster as the
        stream header and hook the shared applier.  Called at
        construction and whenever ``POST /cluster`` replaces the
        stored document (under ``_state_lock`` there)."""
        if self.recorder is None:
            return
        from .. import conf as conf_mod
        cfg = self.scheduler.config
        self.recorder.attach(dump_cluster(cluster), seed=cfg.seed,
                             config=conf_mod.effective_config_doc(cfg))
        cluster.twin_recorder = self.recorder

    def _twin_health(self) -> dict:
        """The healthz twin slice — recorder + last-oracle state, no
        ``_state_lock`` (recorder is internally locked, the verdict
        doc is atomic-swapped)."""
        if self.recorder is None:
            return {"recording": False}
        out = dict(self.recorder.stats())
        twin = self._twin_doc
        if twin and twin.get("last_replay"):
            out["last_replay_ok"] = twin["last_replay"]["ok"]
            out["last_replay_divergences"] = len(
                twin["last_replay"]["divergences"])
        return out

    def _record_cycle(self, result) -> None:
        """Swap in a fresh immutable per-cycle stats document (served
        by ``GET /healthz``).  Called under ``_state_lock``; readers
        take the current binding without it (atomic-swap discipline —
        the dict is never mutated after publication)."""
        prev = self._cycle_stats
        stats = {"cycles": (prev["cycles"] + 1) if prev else 1}
        if result is not None:
            stats.update(
                open_seconds=result.open_seconds,
                commit_seconds=result.commit_seconds,
                total_seconds=result.session_seconds,
                phase_seconds=dict(result.phase_seconds),
                decisions=self.scheduler.decisions.summary(),
                bind_requests=len(result.bind_requests),
                evictions=len(result.evictions),
                # kai-wire summary of the cycle: bytes on the wire by
                # reason, redundant re-uploads, device residency
                wire=dict(result.wire))
            # kai-pulse slice: the headline cluster-health gauges of
            # the latest analytics cycle (this one, or — on cycles the
            # cadence skipped — the last one that ran)
            pulse = (result.analytics
                     or self.scheduler.last_analytics)
            if pulse:
                stats["cluster"] = {
                    "fragmentation_score":
                        pulse["fragmentation"]["score"],
                    "largest_rack_unit_pods":
                        pulse["fragmentation"]["largest_rack_unit_pods"],
                    "goodput": pulse["goodput"],
                    "utilization": dict(pulse["utilization"]),
                    "fairness_drift_max":
                        pulse["fairness"]["drift_max"],
                    "pending_gangs":
                        pulse["starvation"]["pending_gangs"],
                    "oldest_pending_age_cycles": max(
                        [o["age_cycles"] for o
                         in pulse["starvation"]["oldest"]], default=0),
                }
            # kai-repack slice: present only on cycles the trigger fired
            if result.repack:
                stats["repack"] = {
                    "feasible": result.repack["feasible"],
                    "target_gang": result.repack["target_gang"],
                    "migrations_executed":
                        result.repack["migrations_executed"],
                }
        self._cycle_stats = stats

    def _intake_flush(self) -> None:
        """Degrade-to-sync valve (``intake_policy="sync"``): coalesce
        everything staged into the stored cluster under the commit lock
        so an overflowing lane empties.  Called by the router from the
        submitting handler thread, which holds NO lane locks here."""
        with self._state_lock:
            self.intake.coalesce(self.cluster)

    def start(self) -> "SchedulerServer":
        self._thread = threading.Thread(
            target=self._httpd.serve_forever, daemon=True)
        self._thread.start()
        self.intake.start()
        if self.profiler is not None:
            self.profiler.start()
        return self

    def stop(self) -> None:
        if self.profiler is not None:
            self.profiler.stop()
        self.intake.stop()
        self._httpd.shutdown()
        if self._thread is not None:
            self._thread.join(timeout=5)

"""kai-twin: deterministic cluster digital twin (ROADMAP item 5).

Three layers over the observability stack of PRs 6-12:

- ``twin.stream``  — versioned on-disk stream format for journal event
  sequences (explicit seed + logical clocks) and the live-server
  recorder hooked at the shared intake-apply choke point.  Stdlib-only
  module: ``scripts/lint.py`` imports it to validate checked-in
  scenario streams without pulling jax.
- ``twin.replay``  — drives a fresh ``Scheduler`` + ``Cluster`` through
  a recorded stream via the SAME ``intake/apply.py`` path the live
  server uses, digesting every cycle's commits/decisions/journal/
  analytics; the differential oracle asserts two replays (or a replay
  vs the recorded live run) are bit-exact.
- ``twin.fuzz``    — seeded scenario generator families with invariant
  sets and a greedy event-drop minimizer; minimized streams are
  checked in under ``tests/scenarios/streams/``.
- ``twin.tune``    — closed-loop policy autotuner over the live conf
  knobs, scoring rollouts against the kai-pulse objectives; winners
  emit a ``conf.py``-loadable overlay.

Submodules import lazily on purpose — ``twin.stream`` must stay
importable without the jax-heavy framework packages.
"""

"""kai-twin adversarial scenario fuzzer.

Seeded generator families emit valid twin streams plus an invariant
set; :func:`evaluate` replays a stream through the twin (shared apply
path) probing the invariants each cycle; any violating stream is
shrunk by :func:`minimize` — greedy event-drop delta-debugging — and
checked in under ``tests/scenarios/streams/`` as a permanent
regression (``scripts/lint.py`` gates the files' validity, and
``tests/test_twin.py`` re-evaluates their invariants every run).

Families:

- ``diurnal``        — traffic waves: arrival bursts rise and fall,
  finished gangs drain out
- ``rack_failure``   — a correlated rack outage under load, nodes
  restored later; pending must drain and fragmentation recover
- ``quota_storm``    — two tenants storm past their queue limits;
  bound usage must never overshoot a limit and the starvation alarm
  must fire within K cycles
- ``burst_trains``   — arrival/cancel trains with same-key
  create→delete→create races
- ``priority_churn`` — high-priority gangs land on a full cluster and
  priorities are rewritten mid-flight (preemption churn)

Regenerate the checked-in scenarios with::

    python -m kai_scheduler_tpu.twin.fuzz --write-scenarios \
        tests/scenarios/streams
"""
from __future__ import annotations

import os
import random

from ..apis import types as apis
from . import stream as stream_mod
from .stream import Stream

#: decision outcomes (runtime/events.py) the signatures key on
_STARVED = "starved"
_QUOTA_GATE = "quota-gate"
_PREEMPTED = "preempted-for"


# ---------------------------------------------------------------------------
# base snapshots + delta builders
# ---------------------------------------------------------------------------


def _base_snapshot(num_nodes: int = 4, node_accel: float = 8.0,
                   queues_per_department: int = 2,
                   topology_levels: tuple[int, ...] = (2,),
                   num_gangs: int = 0, tasks_per_gang: int = 2,
                   task_accel: float = 1.0,
                   running_fraction: float = 0.0,
                   accel_limit: float | None = None,
                   seed: int = 0) -> dict:
    """A ``dump_cluster`` doc from the synthetic builder — one
    department, leaf queues ``queue-0-*``; optional per-leaf accel
    limit (the quota-storm shape)."""
    from ..runtime.cluster import Cluster
    from ..runtime.snapshot import dump_cluster
    from ..state import make_cluster
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=node_accel,
        num_departments=1, queues_per_department=queues_per_department,
        num_gangs=num_gangs, tasks_per_gang=tasks_per_gang,
        task_accel=task_accel, running_fraction=running_fraction,
        topology_levels=topology_levels, seed=seed)
    if accel_limit is not None:
        for q in queues:
            if q.parent is not None:
                q.accel = apis.QueueResource(quota=q.accel.quota,
                                             limit=accel_limit)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    return dump_cluster(cluster)


def _gang_delta(name: str, queue: str, tasks: int, accel: float,
                priority: int = 0) -> dict:
    return {
        "pod_groups_upsert": [{"name": name, "queue": queue,
                               "min_member": tasks,
                               "priority": priority}],
        "pods_upsert": [{"name": f"{name}-t{i}", "group": name,
                         "resources": {"accel": accel}}
                        for i in range(tasks)],
    }


def _gang_delete(name: str, tasks: int) -> dict:
    return {"pods_delete": [f"{name}-t{i}" for i in range(tasks)],
            "pod_groups_delete": [name]}


def _step(st: Stream, ticks: float = 1.0) -> None:
    """One simulated control-loop step: cycle, bind, advance time."""
    st.append("cycle")
    st.append("reconcile")
    st.append("tick", seconds=ticks)


def _node_doc(i: int, num_nodes: int, accel: float,
              levels: tuple[int, ...] = (2,)) -> dict:
    """Re-create the synthetic builder's node doc (for restore-after-
    failure upserts) — labels must match ``make_cluster``'s nesting."""
    labels = {"kubernetes.io/hostname": f"node-{i}"}
    span, idx = num_nodes, i
    for li, size in enumerate(levels):
        span = max(1, span // size)
        labels[f"topo/level{li}"] = f"level{li}-{idx // span}"
        idx = idx % span
    return {"name": f"node-{i}", "labels": labels,
            "allocatable": {"accel": accel, "cpu": 64.0,
                            "memory": 256.0}}


# ---------------------------------------------------------------------------
# generator families
# ---------------------------------------------------------------------------


def _gen_diurnal(rng: random.Random, scale: float) -> Stream:
    st = Stream(snapshot=_base_snapshot(num_nodes=4),
                config={"analyticsEvery": 1},
                meta={"family": "diurnal"})
    wave = [1, 2, 3, 2, 1, 0, 1, 2]
    phases = max(4, int(len(wave) * scale))
    alive: list[str] = []
    gid = 0
    for ph in range(phases):
        arrivals = wave[ph % len(wave)]
        for _ in range(arrivals):
            name = f"wave-{gid}"
            gid += 1
            st.append("delta", delta=_gang_delta(
                name, f"queue-0-{rng.randrange(2)}", 2, 2.0))
            alive.append(name)
        _step(st)
        # the oldest gangs finish and drain out (diurnal fall)
        while len(alive) > 6:
            done = alive.pop(0)
            st.append("delta", delta=_gang_delete(done, 2))
    _step(st)
    st.invariants = [{"name": "no_lost_gang"},
                     {"name": "clock_monotonic"},
                     {"name": "journal_generation_monotonic"}]
    return st


def _gen_rack_failure(rng: random.Random, scale: float) -> Stream:
    num_nodes, accel = 4, 8.0
    st = Stream(snapshot=_base_snapshot(num_nodes=num_nodes,
                                        node_accel=accel),
                config={"analyticsEvery": 1},
                meta={"family": "rack_failure"})
    # demand fits the FULL cluster but not the degraded one
    for g in range(6):
        st.append("delta", delta=_gang_delta(
            f"job-{g}", f"queue-0-{g % 2}", 2, 2.0))
    # rack 0 (nodes 0..1) fails before anything binds
    st.append("delta", delta={"nodes_delete": ["node-0", "node-1"]})
    degraded = max(2, int(3 * scale))
    for _ in range(degraded):
        _step(st)
    # rack restored; everything must drain
    st.append("delta", delta={"nodes_upsert": [
        _node_doc(i, num_nodes, accel) for i in (0, 1)]})
    for _ in range(max(3, int(4 * scale))):
        _step(st)
    st.invariants = [{"name": "no_lost_gang"},
                     {"name": "clock_monotonic"},
                     {"name": "pending_drains"},
                     {"name": "frag_recovers"}]
    return st


def _gen_quota_storm(rng: random.Random, scale: float) -> Stream:
    st = Stream(snapshot=_base_snapshot(num_nodes=4, accel_limit=12.0),
                config={"analyticsEvery": 1,
                        "starvationAlarmCycles": 4},
                meta={"family": "quota_storm"})
    # both tenants storm to 2x their limit — the surplus MUST pend
    for g in range(6):
        for q in (0, 1):
            st.append("delta", delta=_gang_delta(
                f"storm-q{q}-{g}", f"queue-0-{q}", 2, 2.0))
    for _ in range(max(8, int(8 * scale))):
        _step(st)
    st.invariants = [{"name": "no_lost_gang"},
                     {"name": "clock_monotonic"},
                     {"name": "no_quota_overshoot"},
                     {"name": "starvation_alarm_fires",
                      "k": 4, "slack": 4}]
    return st


def _gen_burst_trains(rng: random.Random, scale: float) -> Stream:
    st = Stream(snapshot=_base_snapshot(num_nodes=4),
                config={"analyticsEvery": 1},
                meta={"family": "burst_trains"})
    trains = max(2, int(3 * scale))
    for t in range(trains):
        burst = [f"burst-{t}-{i}" for i in range(4)]
        for name in burst:
            st.append("delta", delta=_gang_delta(
                name, f"queue-0-{rng.randrange(2)}", 2, 2.0))
        _step(st)
        # cancel half the train mid-flight ...
        for name in burst[:2]:
            st.append("delta", delta=_gang_delete(name, 2))
        # ... and re-arrive under the SAME key with a new shape (the
        # same-key create→delete→create race)
        st.append("delta", delta=_gang_delta(
            burst[0], "queue-0-0", 1, 4.0))
        _step(st)
        st.append("delta", delta=_gang_delete(burst[0], 1))
        st.append("delta", delta=_gang_delete(burst[2], 2))
        st.append("delta", delta=_gang_delete(burst[3], 2))
    _step(st)
    st.invariants = [{"name": "no_lost_gang"},
                     {"name": "clock_monotonic"},
                     {"name": "journal_generation_monotonic"}]
    return st


def _gen_priority_churn(rng: random.Random, scale: float) -> Stream:
    # the cluster starts FULL of low-priority running gangs (4 gangs x
    # 4 tasks x 2 accel = all 32 devices) — a VIP arrival MUST preempt
    st = Stream(snapshot=_base_snapshot(num_nodes=4, num_gangs=4,
                                        tasks_per_gang=4,
                                        task_accel=2.0,
                                        running_fraction=1.0),
                config={"analyticsEvery": 1},
                meta={"family": "priority_churn"})
    rounds = max(2, int(3 * scale))
    for r in range(rounds):
        # high-priority arrivals outrank the residents of their queue
        st.append("delta", delta=_gang_delta(
            f"vip-{r}", f"queue-0-{r % 2}", 2, 2.0, priority=10))
        _step(st)
        # churn: rewrite a resident's priority mid-flight
        st.append("delta", delta={"pod_groups_upsert": [
            {"name": f"gang-{r % 4}", "priority": rng.randrange(12)}]})
        _step(st)
    _step(st)
    st.invariants = [{"name": "no_lost_gang"},
                     {"name": "clock_monotonic"},
                     {"name": "journal_generation_monotonic"}]
    return st


FAMILIES = {
    "diurnal": _gen_diurnal,
    "rack_failure": _gen_rack_failure,
    "quota_storm": _gen_quota_storm,
    "burst_trains": _gen_burst_trains,
    "priority_churn": _gen_priority_churn,
}


def generate(family: str, seed: int = 0, scale: float = 1.0) -> Stream:
    """One seeded stream from a family — same (family, seed, scale) →
    identical stream document, by construction (the determinism
    property test pins this)."""
    if family not in FAMILIES:
        raise ValueError(f"unknown family {family!r}; "
                         f"have {sorted(FAMILIES)}")
    st = FAMILIES[family](random.Random(seed), scale)
    st.seed = seed
    st.meta.setdefault("family", family)
    st.meta["generator_seed"] = seed
    st.meta["scale"] = scale
    return st


# ---------------------------------------------------------------------------
# invariant evaluation (per-cycle probes over a twin replay)
# ---------------------------------------------------------------------------


def _queue_bound_accel(cluster) -> dict[str, float]:
    """Accel actively held per queue: BOUND/RUNNING pods plus pending
    pods with an in-flight Pending BindRequest (the snapshot presents
    those as bound — the quota machinery already charges them)."""
    usage: dict[str, float] = {}
    for p in cluster.pods.values():
        active = p.status in (apis.PodStatus.BOUND,
                              apis.PodStatus.RUNNING)
        if not active and p.status == apis.PodStatus.PENDING:
            br = cluster.bind_requests.get(p.name)
            active = br is not None and br.phase == "Pending"
        if not active:
            continue
        g = cluster.pod_groups.get(p.group)
        if g is None:
            continue
        usage[g.queue] = usage.get(g.queue, 0.0) + p.resources.accel
    return usage


def _pending_gangs(cluster) -> set[str]:
    pending: set[str] = set()
    for g in cluster.pod_groups.values():
        for p in cluster.pods.values():
            if p.group != g.name:
                continue
            if p.status == apis.PodStatus.PENDING and \
                    cluster.bind_requests.get(p.name) is None:
                pending.add(g.name)
                break
    return pending


def _expected_gangs(stream: Stream) -> set[str]:
    """Replay the stream's pod_group upserts/deletes symbolically."""
    expected = set()
    if stream.snapshot:
        expected |= {g["name"] for g in
                     stream.snapshot.get("pod_groups", [])}
    for ev in stream.events:
        if ev["op"] == "delta":
            d = ev["delta"]
            expected |= {g["name"]
                         for g in d.get("pod_groups_upsert", [])}
            expected -= set(d.get("pod_groups_delete", []))
        elif ev["op"] == "events":
            for op, coll, key, payload in ev["events"]:
                if coll != "pod_groups":
                    continue
                if op == "upsert":
                    expected.add(payload.get("name") or key)
                elif op == "delete":
                    expected.discard(payload)
    return expected


def _inv_no_lost_gang(ctx, **_) -> list[str]:
    final = set(ctx["cluster"].pod_groups)
    missing = _expected_gangs(ctx["stream"]) - final
    return [f"no_lost_gang: gang {g!r} vanished without a delete"
            for g in sorted(missing)]


def _inv_clock_monotonic(ctx, **_) -> list[str]:
    nows = ctx["obs"]["now"]
    return [f"clock_monotonic: now went backwards at cycle {i} "
            f"({a} -> {b})"
            for i, (a, b) in enumerate(zip(nows, nows[1:])) if b < a]


def _inv_journal_monotonic(ctx, **_) -> list[str]:
    gens = ctx["obs"]["generation"]
    return [f"journal_generation_monotonic: generation regressed at "
            f"cycle {i} ({a} -> {b})"
            for i, (a, b) in enumerate(zip(gens, gens[1:])) if b < a]


def _inv_no_quota_overshoot(ctx, tol: float = 1e-6, **_) -> list[str]:
    out = []
    for cyc, queue, used, limit in ctx["obs"]["overshoot"]:
        if used > limit + tol:
            out.append(f"no_quota_overshoot: queue {queue!r} holds "
                       f"{used} accel > limit {limit} at cycle {cyc}")
    return out


def _inv_starvation_alarm(ctx, k: int = 4, slack: int = 4,
                          **_) -> list[str]:
    streak: dict[str, int] = {}
    worst = 0
    for pending in ctx["obs"]["pending"]:
        for g in pending:
            streak[g] = streak.get(g, 0) + 1
            worst = max(worst, streak[g])
        for g in list(streak):
            if g not in pending:
                streak[g] = 0
    if worst < k + slack:
        return []  # nothing starved long enough to demand an alarm
    if ctx["obs"]["starved"]:
        return []
    return [f"starvation_alarm_fires: a gang stayed pending {worst} "
            f"cycles but no `starved` decision fired (k={k})"]


def _inv_pending_drains(ctx, **_) -> list[str]:
    pending = ctx["obs"]["pending"]
    last = pending[-1] if pending else set()
    return [f"pending_drains: {sorted(last)} still pending at stream "
            f"end"] if last else []


def _inv_frag_recovers(ctx, tol: float = 1e-6, **_) -> list[str]:
    frags = ctx["obs"]["frag"]
    if len(frags) < 2:
        return []
    peak, final = max(frags[:-1]), frags[-1]
    return [f"frag_recovers: final fragmentation {final} exceeds "
            f"the in-stream peak {peak}"] if final > peak + tol else []


INVARIANTS = {
    "no_lost_gang": _inv_no_lost_gang,
    "clock_monotonic": _inv_clock_monotonic,
    "journal_generation_monotonic": _inv_journal_monotonic,
    "no_quota_overshoot": _inv_no_quota_overshoot,
    "starvation_alarm_fires": _inv_starvation_alarm,
    "pending_drains": _inv_pending_drains,
    "frag_recovers": _inv_frag_recovers,
}


def evaluate(stream: Stream, base=None) -> dict:
    """Replay a stream through the twin, probing its invariant set
    each cycle.  Returns ``{"violations": [...], "report": ...,
    "obs": ...}`` — empty violations means the scenario holds."""
    from ..framework import metrics
    from . import replay as replay_mod
    obs = {"now": [], "generation": [], "pending": [], "frag": [],
           "overshoot": [], "starved": set(), "binds_by_cycle": [],
           "cycle": 0}

    def on_cycle(cluster, result, digest):
        cyc = obs["cycle"]
        obs["cycle"] += 1
        obs["now"].append(cluster.now)
        obs["generation"].append(cluster.journal.generation)
        obs["pending"].append(_pending_gangs(cluster))
        obs["binds_by_cycle"].append(len(result.bind_requests))
        usage = _queue_bound_accel(cluster)
        for qname, used in usage.items():
            q = cluster.queues.get(qname)
            limit = q.accel.limit if q is not None else apis.UNLIMITED
            if limit >= 0:
                obs["overshoot"].append((cyc, qname, used, limit))
        if digest:
            for gang, _q, outcome, _d in digest["decisions"]:
                if outcome == _STARVED:
                    obs["starved"].add(gang)
        a = result.analytics
        if a:
            obs["frag"].append(a["fragmentation"]["score"])

    report = replay_mod.replay(stream, base=base, on_cycle=on_cycle)
    ctx = {"stream": stream, "report": report, "obs": obs,
           "cluster": report.cluster}
    violations: list[str] = []
    for inv in stream.invariants:
        fn = INVARIANTS.get(inv["name"])
        if fn is None:
            violations.append(f"unknown invariant {inv['name']!r}")
            continue
        params = {k: v for k, v in inv.items() if k != "name"}
        violations.extend(fn(ctx, **params))
    if violations:
        family = stream.meta.get("family", "unknown")
        metrics.twin_fuzz_violations.inc(family, by=len(violations))
    return {"violations": violations, "report": report, "obs": obs}


def fuzz(families=None, seeds=range(2), scale: float = 1.0,
         base=None) -> list[dict]:
    """Sweep family × seed; returns one record per violating stream."""
    found = []
    for family in (families or sorted(FAMILIES)):
        for seed in seeds:
            st = generate(family, seed=seed, scale=scale)
            res = evaluate(st, base=base)
            if res["violations"]:
                found.append({"family": family, "seed": seed,
                              "stream": st,
                              "violations": res["violations"]})
    return found


# ---------------------------------------------------------------------------
# greedy event-drop delta-debugging
# ---------------------------------------------------------------------------


def minimize(stream: Stream, predicate, budget: int = 200) -> Stream:
    """Shrink a stream to a minimal event list still satisfying
    ``predicate(candidate) -> bool`` (ddmin-style: halves, then
    smaller chunks, down to single events).  ``budget`` bounds the
    number of candidate replays."""
    from ..framework import metrics
    events = list(stream.events)
    original = len(events)
    tries = 0

    def ok(evts: list[dict]) -> bool:
        nonlocal tries
        if tries >= budget:
            return False
        tries += 1
        try:
            return bool(predicate(stream.copy_with_events(evts)))
        except Exception:  # noqa: BLE001 — a broken candidate is
            # simply "not interesting", never a minimizer crash
            return False

    size = max(1, len(events) // 2)
    while size >= 1 and tries < budget:
        i = 0
        while i < len(events) and tries < budget:
            cand = events[:i] + events[i + size:]
            if cand and ok(cand):
                events = cand
            else:
                i += size
        if size == 1:
            break
        size = max(1, size // 2)
    dropped = original - len(events)
    if dropped > 0:
        metrics.twin_fuzz_minimized.inc(by=dropped)
    out = stream.copy_with_events(events)
    out.meta = dict(stream.meta, minimized_from=original,
                    minimized_to=len(events))
    return out


# ---------------------------------------------------------------------------
# scenario check-in (family signatures + regeneration entry point)
# ---------------------------------------------------------------------------


def _sig_diurnal(stream: Stream, res: dict) -> bool:
    busy = [b for b in res["obs"]["binds_by_cycle"] if b > 0]
    return len(busy) >= 2


def _sig_rack_failure(stream: Stream, res: dict) -> bool:
    deleted = any(ev["op"] == "delta"
                  and ev["delta"].get("nodes_delete")
                  for ev in stream.events)
    restored = any(ev["op"] == "delta"
                   and ev["delta"].get("nodes_upsert")
                   for ev in stream.events)
    return (deleted and restored
            and sum(res["obs"]["binds_by_cycle"]) > 0)


def _sig_quota_storm(stream: Stream, res: dict) -> bool:
    gated = any(outcome in (_QUOTA_GATE, _STARVED)
                for d in res["report"].digests
                for _g, _q, outcome, _det in d["decisions"])
    return gated or bool(res["obs"]["starved"])


def _sig_burst_trains(stream: Stream, res: dict) -> bool:
    seen: dict[str, str] = {}
    race = False
    for ev in stream.events:
        if ev["op"] != "delta":
            continue
        for g in ev["delta"].get("pod_groups_upsert", []):
            if seen.get(g["name"]) == "deleted":
                race = True
            seen[g["name"]] = "live"
        for name in ev["delta"].get("pod_groups_delete", []):
            seen[name] = "deleted"
    return race and sum(res["obs"]["binds_by_cycle"]) > 0


def _sig_priority_churn(stream: Stream, res: dict) -> bool:
    return any(d["evictions"] or any(o == _PREEMPTED for _g, _q, o, _d
                                     in d["decisions"])
               for d in res["report"].digests)


SIGNATURES = {
    "diurnal": _sig_diurnal,
    "rack_failure": _sig_rack_failure,
    "quota_storm": _sig_quota_storm,
    "burst_trains": _sig_burst_trains,
    "priority_churn": _sig_priority_churn,
}


def make_scenario(family: str, seed: int = 0, scale: float = 1.0,
                  budget: int = 120) -> Stream:
    """The check-in pipeline for one family: generate, verify the
    invariants hold AND the family's signature behavior shows, then
    minimize while preserving both — the smallest stream that still
    exercises the scenario, pinned as a permanent regression."""
    st = generate(family, seed=seed, scale=scale)
    sig = SIGNATURES[family]

    def interesting(cand: Stream) -> bool:
        res = evaluate(cand)
        return not res["violations"] and sig(cand, res)

    if not interesting(st):
        raise RuntimeError(
            f"family {family!r} seed {seed} does not exercise its own "
            f"signature — regenerate with another seed/scale")
    return minimize(st, interesting, budget=budget)


def write_scenarios(outdir: str, seed: int = 0,
                    budget: int = 120) -> list[str]:
    os.makedirs(outdir, exist_ok=True)
    written = []
    for family in sorted(FAMILIES):
        st = make_scenario(family, seed=seed, budget=budget)
        path = os.path.join(outdir, f"{family}.stream.json")
        stream_mod.write_stream(st, path)
        written.append(path)
    return written


if __name__ == "__main__":  # pragma: no cover - regeneration tool
    import sys
    if len(sys.argv) == 3 and sys.argv[1] == "--write-scenarios":
        for p in write_scenarios(sys.argv[2]):
            print(f"wrote {p}")
    else:
        print("usage: python -m kai_scheduler_tpu.twin.fuzz "
              "--write-scenarios DIR", file=sys.stderr)
        raise SystemExit(2)

"""kai-twin stream format + live recorder.

A *stream* is everything a deterministic replay needs: the starting
cluster snapshot (``runtime/snapshot.dump_cluster`` form), an explicit
seed, an optional ``conf.py`` config overlay, and an ordered event list
where every event carries a monotonically increasing logical clock
(``lc``).  Five event kinds:

- ``events``    — a batch of already-decomposed intake events
  ``[op, coll, key, payload]`` (the recorder's output: exactly what the
  shared applier applied, in order)
- ``delta``     — a delta document (``POST /cluster/delta`` shape), the
  synthetic-generator form; replay decomposes it through the same
  ``intake/apply.decompose_delta``
- ``cycle``     — run one scheduling cycle
- ``tick``      — advance the cluster clock (``seconds``)
- ``reconcile`` — run the binder over pending BindRequests

The recorder (:class:`StreamRecorder`) hooks the ONE choke point both
live mutation paths share — ``intake/apply.apply_events`` — via the
``Cluster.twin_recorder`` attribute, so a recorded stream is the
applied event sequence by construction, not a reconstruction.

This module is deliberately stdlib-only at import time:
``scripts/lint.py`` uses :func:`validate_stream_doc` to gate the
checked-in scenario streams without importing jax.
"""
from __future__ import annotations

import copy
import dataclasses
import gzip
import json
import threading

FORMAT = "kai-twin-stream"
VERSION = 1

EVENT_OPS = ("events", "delta", "cycle", "tick", "reconcile")

#: recorder ring bound — keep-first/drop-new: the header snapshot is
#: the state at recording start, so the retained PREFIX stays
#: replayable; dropping old events would orphan the snapshot
DEFAULT_EVENT_LIMIT = 200_000


@dataclasses.dataclass
class Stream:
    """One recorded (or generated) twin stream."""

    seed: int = 0
    #: ``dump_cluster`` document of the starting state; None = empty
    snapshot: dict | None = None
    #: ``conf.load_config`` overlay applied to the replaying scheduler
    config: dict | None = None
    #: ordered events, each ``{"op": ..., "lc": n, ...}``
    events: list[dict] = dataclasses.field(default_factory=list)
    #: fuzzer invariant set: ``[{"name": ..., **params}, ...]``
    invariants: list[dict] = dataclasses.field(default_factory=list)
    meta: dict = dataclasses.field(default_factory=dict)

    def append(self, op: str, **fields) -> dict:
        """Append one event, assigning the next logical clock."""
        if op not in EVENT_OPS:
            raise ValueError(f"unknown stream op {op!r}")
        lc = (self.events[-1]["lc"] + 1) if self.events else 0
        ev = {"op": op, "lc": lc, **fields}
        self.events.append(ev)
        return ev

    def to_doc(self) -> dict:
        return {
            "format": FORMAT,
            "version": VERSION,
            "seed": self.seed,
            "snapshot": self.snapshot,
            "config": self.config,
            "invariants": self.invariants,
            "meta": self.meta,
            "events": self.events,
        }

    @classmethod
    def from_doc(cls, doc: dict) -> "Stream":
        problems = validate_stream_doc(doc)
        if problems:
            raise ValueError("invalid twin stream: " + "; ".join(problems))
        return cls(seed=int(doc.get("seed", 0)),
                   snapshot=doc.get("snapshot"),
                   config=doc.get("config"),
                   events=list(doc.get("events", [])),
                   invariants=list(doc.get("invariants", [])),
                   meta=dict(doc.get("meta", {})))

    def copy_with_events(self, events: list[dict]) -> "Stream":
        """A new stream with the same header and the given events,
        logical clocks renumbered (the minimizer's rebuild step)."""
        out = Stream(seed=self.seed, snapshot=self.snapshot,
                     config=self.config,
                     invariants=list(self.invariants),
                     meta=dict(self.meta))
        for ev in events:
            fields = {k: v for k, v in ev.items() if k not in ("op", "lc")}
            out.append(ev["op"], **fields)
        return out


def validate_stream_doc(doc, require_invariants: bool = False) -> list[str]:
    """Structural validity of a stream document — one message per
    problem, empty when valid.  Pure (no package imports): the lint
    gate runs this over every checked-in scenario stream."""
    problems: list[str] = []
    if not isinstance(doc, dict):
        return ["stream document must be a mapping"]
    if doc.get("format") != FORMAT:
        problems.append(f"format must be {FORMAT!r}, got "
                        f"{doc.get('format')!r}")
    if doc.get("version") != VERSION:
        problems.append(f"unsupported stream version {doc.get('version')!r}"
                        f" (expected {VERSION})")
    if problems:
        return problems  # wrong container: field checks would be noise
    if not isinstance(doc.get("seed", 0), int):
        problems.append("seed must be an integer")
    snap = doc.get("snapshot")
    if snap is not None and not isinstance(snap, dict):
        problems.append("snapshot must be a mapping or null")
    cfg = doc.get("config")
    if cfg is not None and not isinstance(cfg, dict):
        problems.append("config must be a mapping or null")
    invs = doc.get("invariants", [])
    if not isinstance(invs, list):
        problems.append("invariants must be a list")
        invs = []
    for i, inv in enumerate(invs):
        if not isinstance(inv, dict) or not inv.get("name"):
            problems.append(f"invariants[{i}] must be a mapping with "
                            f"a non-empty `name`")
    if require_invariants and not invs:
        problems.append("invariant set is empty — a checked-in scenario "
                        "must pin at least one invariant")
    events = doc.get("events")
    if not isinstance(events, list):
        problems.append("events must be a list")
        return problems
    prev_lc = -1
    for i, ev in enumerate(events):
        if not isinstance(ev, dict):
            problems.append(f"events[{i}] must be a mapping")
            continue
        op = ev.get("op")
        if op not in EVENT_OPS:
            problems.append(f"events[{i}] has unknown op {op!r}")
            continue
        lc = ev.get("lc")
        if not isinstance(lc, int) or lc <= prev_lc:
            problems.append(f"events[{i}] logical clock {lc!r} does not "
                            f"increase monotonically (prev {prev_lc})")
        else:
            prev_lc = lc
        if op == "events":
            batch = ev.get("events")
            if not isinstance(batch, list) or not all(
                    isinstance(e, (list, tuple)) and len(e) == 4
                    for e in batch):
                problems.append(f"events[{i}] batch must be a list of "
                                f"[op, coll, key, payload] quadruples")
        elif op == "delta":
            if not isinstance(ev.get("delta"), dict):
                problems.append(f"events[{i}] delta must be a mapping")
        elif op == "tick":
            if not isinstance(ev.get("seconds", None), (int, float)):
                problems.append(f"events[{i}] tick needs numeric seconds")
    return problems


def write_stream(stream: Stream, path: str) -> None:
    """Write a stream file (gzipped when the path ends ``.gz``)."""
    data = json.dumps(stream.to_doc(), sort_keys=True).encode()
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "wb") as f:
        f.write(data)


def read_doc(path: str):
    """Read a JSON document (gzip by ``.gz``) WITHOUT validating it —
    the format sniff ``snapshot_tool.py replay`` uses to route between
    twin streams and classic cluster snapshots."""
    opener = gzip.open if path.endswith(".gz") else open
    with opener(path, "rb") as f:
        return json.loads(f.read().decode())


def read_stream(path: str) -> Stream:
    """Read + validate a stream file; raises ``ValueError`` on a wrong
    format/version or any structural problem."""
    return Stream.from_doc(read_doc(path))


class StreamRecorder:
    """Thread-safe bounded recorder for a live cluster's applied
    mutation stream.

    Attach it with a snapshot of the cluster at recording start; the
    shared applier (``intake/apply.apply_events``) mirrors every event
    it successfully applied via ``Cluster.twin_recorder``, and the
    server's stored-cycle handler records cycle boundaries.  When the
    ring fills, NEW events are dropped (and counted) so the retained
    prefix + header snapshot stay a valid replayable stream.
    """

    def __init__(self, limit: int = DEFAULT_EVENT_LIMIT):
        self._lock = threading.Lock()
        self._limit = int(limit)
        # every field below is guarded by _lock (handler threads and
        # the cycle thread both write through the public methods)
        self._events: list[dict] = []
        self._dropped = 0
        self._snapshot: dict | None = None
        self._seed = 0
        self._config: dict | None = None
        self._attached = False

    def __deepcopy__(self, memo):
        # a deepcopied cluster (profiling twin, differential copy) must
        # NOT re-record its own replay into the live recorder — the
        # copy's twin_recorder hook drops to None
        return None

    def attach(self, snapshot: dict | None, seed: int = 0,
               config: dict | None = None) -> None:
        """(Re)start recording from this snapshot — resets the ring."""
        with self._lock:
            self._snapshot = snapshot
            self._seed = int(seed)
            self._config = config
            self._events = []
            self._dropped = 0
            self._attached = True

    def detach(self) -> None:
        """Stop recording; the captured prefix stays readable."""
        with self._lock:
            self._attached = False

    @property
    def attached(self) -> bool:
        return self._attached

    def _append(self, op: str, fields: dict) -> None:
        with self._lock:
            if not self._attached:
                return
            if len(self._events) >= self._limit:
                self._dropped += 1
                return
            lc = (self._events[-1]["lc"] + 1) if self._events else 0
            self._events.append({"op": op, "lc": lc, **fields})

    def record_events(self, applied: list[tuple]) -> None:
        """One applied batch of ``(op, coll, key, payload)`` tuples —
        called by the shared applier AFTER the events landed in the hub
        journal.  Payload docs are deep-copied: callers may reuse or
        mutate their delta documents after the apply returns."""
        if not applied:
            return
        self._append("events", {
            "events": [[op, coll, key, copy.deepcopy(payload)]
                       for op, coll, key, payload in applied]})

    def record_cycle(self) -> None:
        self._append("cycle", {})

    def record_tick(self, seconds: float) -> None:
        self._append("tick", {"seconds": float(seconds)})

    def record_reconcile(self) -> None:
        self._append("reconcile", {})

    def stats(self) -> dict:
        with self._lock:
            return {"recording": self._attached,
                    "events": len(self._events),
                    "dropped": self._dropped,
                    "limit": self._limit}

    def stream(self) -> Stream:
        """The captured stream (a consistent copy)."""
        with self._lock:
            return Stream(seed=self._seed,
                          snapshot=copy.deepcopy(self._snapshot),
                          config=copy.deepcopy(self._config),
                          events=copy.deepcopy(self._events),
                          meta={"source": "recorder",
                                "dropped": self._dropped})

    def doc(self) -> dict:
        return self.stream().to_doc()

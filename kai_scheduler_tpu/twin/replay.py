"""kai-twin replayer + bit-exact differential oracle.

The replayer drives a FRESH ``Scheduler`` + ``Cluster`` through a
recorded stream using the same shared apply path the live server uses
(``intake/apply.py`` — PR 12's choke point), so twin-vs-live is a
shared-code identity rather than a parallel reimplementation.  Every
``cycle`` event produces a :func:`cycle_digest`: the commit set (binds
+ evictions, in commit order), the cycle's DecisionLog events, the
journal generation and the consumed cursor batch, the canonicalized
analytics document, the cluster clock, and the kai-twin
``(cycle_index, cycle_seed)`` determinism anchors.

The **differential oracle** (:func:`oracle`) replays a stream twice and
diffs the digest sequences field-by-field — any divergence is a
determinism bug by definition (same stream, same code).  The live
differential (``tests/test_twin.py``) computes the SAME digests on the
live run via :func:`cycle_digest` and diffs them against the replay of
the recorded stream — the twin == live bit-exactness bar.
"""
from __future__ import annotations

import dataclasses
import time

from .. import conf as conf_mod
from ..framework.scheduler import Scheduler, SchedulerConfig
from ..intake import apply as intake_apply
from ..runtime.cluster import Cluster
from ..runtime.snapshot import load_cluster
from . import stream as stream_mod

#: the journal cursor fields the oracle compares (state/incremental.py
#: ``JournalBatch`` — sets/lists of dirty keys plus the time flag)
CURSOR_FIELDS = ("pods_dirty", "pods_added", "pods_removed",
                 "gangs_dirty", "gangs_added", "nodes_dirty",
                 "structural", "time_dirty")

#: DecisionLog event fields digested per cycle (runtime/events.py)
_DECISION_FIELDS = ("gang", "queue", "outcome", "detail")


def _plain(x):
    """Canonicalize a value for digesting: numpy scalars → python,
    containers recursed, everything else passed through."""
    if isinstance(x, dict):
        return {k: _plain(v) for k, v in x.items()}
    if isinstance(x, (list, tuple)):
        return [_plain(v) for v in x]
    item = getattr(x, "item", None)
    if callable(item) and getattr(x, "shape", None) == ():
        return x.item()
    return x


def _canon_analytics(doc: dict) -> dict:
    """The analytics document minus wall-clock noise: any ``*seconds``
    key is a timing, excluded from bit-exactness (the oracle compares
    DECISIONS, not how long they took to compute)."""
    def strip(d):
        if isinstance(d, dict):
            return {k: strip(v) for k, v in d.items()
                    if not str(k).endswith("seconds")}
        if isinstance(d, (list, tuple)):
            return [strip(v) for v in d]
        return _plain(d)
    return strip(doc or {})


def _batch_doc(batch) -> dict:
    out = {}
    for f in CURSOR_FIELDS:
        v = getattr(batch, f)
        out[f] = bool(v) if isinstance(v, bool) else sorted(v)
    return out


def cycle_digest(cluster, scheduler, result, batch) -> dict:
    """Everything one cycle decided, in a comparable form.  Binds and
    evictions keep their COMMIT ORDER (stronger than set equality);
    DecisionLog events are the cycle's own, sorted (the log may cap and
    drop — order within a cycle is presentation, membership is not)."""
    evs = scheduler.decisions.events(limit=100000)
    cycles = [e["cycle"] for e in evs]
    last = max(cycles, default=None)
    decisions = sorted(tuple(e[f] for f in _DECISION_FIELDS)
                       for e in evs if e["cycle"] == last)
    return {
        "cycle_index": result.cycle_index,
        "cycle_seed": result.cycle_seed,
        "now": cluster.now,
        "binds": [(br.pod_name, br.selected_node,
                   br.received_resource_type.value,
                   _plain(br.received_accel_count),
                   _plain(br.received_accel_portion),
                   _plain(br.received_accel_memory_gib),
                   tuple(br.selected_accel_groups or ()))
                  for br in (list(result.bind_requests)
                             + list(result.move_bind_requests))],
        "evictions": [(ev.pod_name, ev.group, ev.move_to)
                      for ev in result.evictions],
        "decisions": decisions,
        "journal_generation": cluster.journal.generation,
        "cursor": _batch_doc(batch),
        "analytics": _canon_analytics(result.analytics),
    }


def diff_digests(a: list[dict], b: list[dict], limit: int = 20) -> list[str]:
    """Field-by-field divergence report between two digest sequences —
    empty means bit-exact."""
    out: list[str] = []
    if len(a) != len(b):
        out.append(f"cycle count diverged: {len(a)} != {len(b)}")
    for i, (da, db) in enumerate(zip(a, b)):
        for key in sorted(da.keys() | db.keys()):
            if da.get(key) != db.get(key):
                out.append(f"cycle[{i}].{key} diverged: "
                           f"{da.get(key)!r} != {db.get(key)!r}")
                if len(out) >= limit:
                    out.append("... (diff truncated)")
                    return out
    return out


@dataclasses.dataclass
class ReplayReport:
    """One replay run's outcome (``doc()`` is the /debug/twin form)."""

    digests: list[dict] = dataclasses.field(default_factory=list)
    events_applied: int = 0
    apply_errors: int = 0
    cycles: int = 0
    wall_seconds: float = 0.0
    cluster: Cluster | None = None
    scheduler: Scheduler | None = None

    @property
    def events_per_s(self) -> float:
        return self.events_applied / max(self.wall_seconds, 1e-9)

    def doc(self) -> dict:
        return {"events_applied": self.events_applied,
                "apply_errors": self.apply_errors,
                "cycles": self.cycles,
                "wall_seconds": round(self.wall_seconds, 6),
                "events_per_s": round(self.events_per_s, 1)}


def replay_config(stream: stream_mod.Stream,
                  base: SchedulerConfig | None = None,
                  overlay: dict | None = None) -> SchedulerConfig:
    """The replaying scheduler's config: stream overlay over ``base``
    (over compiled defaults), an extra ``overlay`` doc on top (the
    tuner's candidate), and the stream's seed pinned last so the
    determinism anchor always comes from the stream header."""
    cfg = conf_mod.load_config(stream.config, base=base)
    if overlay:
        cfg = conf_mod.load_config(overlay, base=cfg)
    return dataclasses.replace(cfg, seed=stream.seed)


def replay(stream: stream_mod.Stream,
           base: SchedulerConfig | None = None,
           overlay: dict | None = None,
           pace_s: float = 0.0,
           digest: bool = True,
           on_cycle=None) -> ReplayReport:
    """Drive a fresh scheduler through the stream.

    ``pace_s`` > 0 sleeps that long after every cycle event (paced
    replay for live-dashboard demos); 0 replays as fast as possible.
    ``digest=False`` skips per-cycle digesting — the raw-throughput
    mode ``bench.py twin`` measures oracle overhead against.
    ``on_cycle(cluster, result, digest_or_None)`` runs after each
    cycle — the fuzzer's per-cycle invariant probe.
    """
    from ..framework import metrics
    cfg = replay_config(stream, base=base, overlay=overlay)
    cluster = (load_cluster(stream.snapshot) if stream.snapshot
               else Cluster())
    sched = Scheduler(cfg)
    cursor = cluster.journal.register()
    cursor.consume()  # the snapshot itself is not a delta
    report = ReplayReport(cluster=cluster, scheduler=sched)
    errors: list = []
    t0 = time.perf_counter()
    for ev in stream.events:
        op = ev["op"]
        if op == "events":
            report.events_applied += intake_apply.apply_events(
                cluster,
                [tuple(e) for e in ev["events"]], errors=errors)
        elif op == "delta":
            report.events_applied += intake_apply.apply_events(
                cluster, intake_apply.decompose_delta(ev["delta"]),
                errors=errors)
        elif op == "tick":
            cluster.tick(float(ev["seconds"]))
        elif op == "reconcile":
            from ..binder.binder import Binder
            Binder().reconcile(cluster)
        elif op == "cycle":
            result = sched.run_once(cluster)
            report.cycles += 1
            d = None
            if digest:
                d = cycle_digest(cluster, sched, result,
                                 cursor.consume())
                report.digests.append(d)
            if on_cycle is not None:
                on_cycle(cluster, result, d)
            if pace_s > 0:
                time.sleep(pace_s)
    report.wall_seconds = time.perf_counter() - t0
    report.apply_errors = len(errors)
    metrics.twin_replayed_events.inc(by=report.events_applied)
    metrics.twin_replay_cycles.inc(by=report.cycles)
    return report


def oracle(stream: stream_mod.Stream,
           base: SchedulerConfig | None = None,
           overlay: dict | None = None) -> dict:
    """The determinism oracle: replay the stream twice through the
    shared apply path and diff the digest sequences.  Returns the
    verdict document (``/debug/twin``'s ``last_replay``)."""
    from ..framework import metrics
    ra = replay(stream, base=base, overlay=overlay)
    rb = replay(stream, base=base, overlay=overlay)
    divergences = diff_digests(ra.digests, rb.digests)
    checks = len(ra.digests) * 8  # digest fields compared per cycle
    metrics.twin_oracle_checks.inc(by=checks)
    if divergences:
        metrics.twin_oracle_divergences.inc(by=len(divergences))
    return {"ok": not divergences,
            "checks": checks,
            "divergences": divergences,
            "replay": ra.doc(),
            "verify": rb.doc()}

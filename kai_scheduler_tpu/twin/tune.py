"""kai-twin closed-loop policy autotuner.

Random-search-with-elites over the scheduler's live knob surface,
scored by replaying a recorded (or fuzz-generated) stream through the
twin with each candidate overlaid on the stream's own config.  The
objective is the kai-pulse composite: goodput up, fairness drift down,
starvation age down, cycle p99 down — candidate metric rows are scored
as one batched dot product (``jax.vmap`` when jax is importable, numpy
otherwise; the scorer is a pure linear form so both are bit-identical).

The winner is emitted as a ``conf.load_config``-loadable overlay
document — drop it into the ConfigMap (or POST it to ``/config``) and
the live scheduler runs the tuned policy.  The ``_twinTune`` key
carries the score breakdown; ``load_config`` ignores unknown keys by
design, so the provenance rides along harmlessly.
"""
from __future__ import annotations

import dataclasses
import random

import numpy as np

try:  # the scorer vmaps on jax when present; numpy is bit-identical
    import jax
    import jax.numpy as jnp
except Exception:  # noqa: BLE001 — jax-free envs score on numpy
    jax = jnp = None

from . import stream as stream_mod

#: composite objective weights over the metric row
#: (goodput_mean, drift_mean, starv_age_max, cycle_p99_seconds) —
#: goodput dominates; the wall-clock term is a tie-breaker only, so
#: measurement noise (and residual jax compiles — ``tune`` burns an
#: unscored warmup rollout to keep them out of the scored rows) can
#: never outvote a scheduling-quality difference
WEIGHTS = (1.0, -0.5, -0.01, -0.002)

#: metric row labels, index-aligned with WEIGHTS
METRIC_NAMES = ("goodput_mean", "drift_mean", "starv_age_max",
                "cycle_p99_s")


@dataclasses.dataclass(frozen=True)
class Knob:
    """One tunable config-document leaf.

    ``path`` addresses the overlay doc (nested keys); ``kind`` is
    ``int`` / ``float`` / ``choice``.  ``placementGpu`` is the one
    special case — it renders as a ``tiers`` plugin-arguments doc
    rather than a scalar leaf.
    """

    name: str
    path: tuple[str, ...]
    kind: str
    lo: float = 0.0
    hi: float = 1.0
    choices: tuple = ()

    def sample(self, rng: random.Random):
        if self.kind == "choice":
            return rng.choice(self.choices)
        if self.kind == "int":
            return rng.randint(int(self.lo), int(self.hi))
        return round(rng.uniform(self.lo, self.hi), 4)

    def mutate(self, value, rng: random.Random):
        if self.kind == "choice":
            return rng.choice(self.choices)
        if self.kind == "int":
            span = max(1, int((self.hi - self.lo) * 0.25))
            v = int(value) + rng.randint(-span, span)
            return int(min(self.hi, max(self.lo, v)))
        span = (self.hi - self.lo) * 0.25
        v = float(value) + rng.uniform(-span, span)
        return round(min(self.hi, max(self.lo, v)), 4)


KNOBS = (
    Knob("kValue", ("kValue",), "float", 0.05, 1.0),
    Knob("allocateDepth", ("queueDepthPerAction", "allocate"),
         "int", 1, 32),
    Knob("reclaimDepth", ("queueDepthPerAction", "reclaim"),
         "int", 1, 16),
    Knob("preemptDepth", ("queueDepthPerAction", "preempt"),
         "int", 1, 16),
    Knob("repackFragThreshold", ("repack", "fragThreshold"),
         "float", 0.2, 0.9),
    Knob("repackCooldown", ("repack", "cooldownCycles"), "int", 2, 16),
    Knob("repackTrigger", ("repack", "triggerCycles"), "int", 1, 4),
    Knob("analyticsEvery", ("analyticsEvery",), "int", 1, 4),
    Knob("starvationAlarmCycles", ("starvationAlarmCycles",),
         "int", 4, 64),
    Knob("intakeLanes", ("intake", "lanes"), "int", 1, 8),
    Knob("intakeLaneCapacity", ("intake", "laneCapacity"),
         "int", 1024, 65536),
    Knob("sparseUnitK", ("victims", "sparseUnitK"), "int", 64, 512),
    Knob("maxVictimPods", ("victims", "maxVictimPods"),
         "int", 64, 1024),
    Knob("placementGpu", ("placementGpu",), "choice",
         choices=("binpack", "spread")),
)

_KNOBS_BY_NAME = {k.name: k for k in KNOBS}


def to_overlay(candidate: dict) -> dict:
    """A candidate (knob-name → value) as a conf-loadable document."""
    doc: dict = {}
    for name, value in candidate.items():
        knob = _KNOBS_BY_NAME[name]
        if name == "placementGpu":
            doc["tiers"] = [{"plugins": [{
                "name": "nodeplacement",
                "arguments": {"gpu": value}}]}]
            continue
        node = doc
        for key in knob.path[:-1]:
            node = node.setdefault(key, {})
        node[knob.path[-1]] = value
    return doc


# ---------------------------------------------------------------------------
# rollout + batched scoring
# ---------------------------------------------------------------------------


def _p99(xs: list[float]) -> float:
    if not xs:
        return 0.0
    s = sorted(xs)
    return s[min(len(s) - 1, int(0.99 * len(s)))]


def rollout(stream: stream_mod.Stream, candidate: dict,
            base=None) -> list[float]:
    """Replay the stream under one candidate overlay; return its
    metric row (see :data:`METRIC_NAMES`)."""
    from ..framework import metrics
    from . import replay as replay_mod
    goodput: list[float] = []
    drift: list[float] = []
    starv: list[float] = []
    cycle_s: list[float] = []

    def probe(cluster, result, digest):
        acts = result.action_seconds
        act_s = (sum(acts.values()) if isinstance(acts, dict)
                 else float(acts or 0.0))
        cycle_s.append(result.session_seconds + act_s)
        a = result.analytics
        if not a:
            return
        goodput.append(float(a["goodput"]))
        drift.append(float(a["fairness"]["drift_mean"]))
        ages = [o["age_cycles"] for o in a["starvation"]["oldest"]]
        starv.append(float(max(ages, default=0)))

    replay_mod.replay(stream, base=base, overlay=to_overlay(candidate),
                      digest=False, on_cycle=probe)
    metrics.twin_tuner_rollouts.inc()
    mean = lambda xs: sum(xs) / len(xs) if xs else 0.0  # noqa: E731
    return [mean(goodput), mean(drift), max(starv, default=0.0),
            _p99(cycle_s)]


def score_rows(rows: list[list[float]]) -> list[float]:
    """Batched composite scores — one vmapped dot product over the
    candidate × metric matrix (numpy fallback is bit-identical: the
    scorer is a pure linear form)."""
    mat = np.asarray(rows, dtype=np.float32)
    w = np.asarray(WEIGHTS, dtype=np.float32)
    if jax is not None:
        scores = jax.vmap(lambda r: jnp.dot(r, w))(jnp.asarray(mat))
        return [float(s) for s in scores]
    return [float(s) for s in mat @ w]


# ---------------------------------------------------------------------------
# the search loop
# ---------------------------------------------------------------------------


@dataclasses.dataclass
class TuneReport:
    """The tuner's outcome: the winning overlay + full history."""

    best_candidate: dict = dataclasses.field(default_factory=dict)
    best_score: float = float("-inf")
    best_metrics: list[float] = dataclasses.field(default_factory=list)
    baseline_score: float = 0.0
    baseline_metrics: list[float] = dataclasses.field(
        default_factory=list)
    rollouts: int = 0
    history: list[dict] = dataclasses.field(default_factory=list)

    @property
    def improvement(self) -> float:
        return self.best_score - self.baseline_score

    def overlay_doc(self) -> dict:
        """The conf-loadable winner, score breakdown riding along
        under ``_twinTune`` (``load_config`` ignores unknown keys)."""
        doc = to_overlay(self.best_candidate)
        doc["_twinTune"] = {
            "score": round(self.best_score, 6),
            "baselineScore": round(self.baseline_score, 6),
            "improvement": round(self.improvement, 6),
            "metrics": {n: round(v, 6) for n, v in
                        zip(METRIC_NAMES, self.best_metrics)},
            "baselineMetrics": {n: round(v, 6) for n, v in
                                zip(METRIC_NAMES,
                                    self.baseline_metrics)},
            "rollouts": self.rollouts,
        }
        return doc


def _initial_population(rng: random.Random, size: int,
                        knobs) -> list[dict]:
    """Baseline + one axis probe per knob (hi then lo) + random fill.
    The axis probes guarantee the sweep covers each knob's extremes
    regardless of seed — a planted bad knob in the stream config is
    always countered by some candidate."""
    pop: list[dict] = [{}]  # the stream's own config, untouched
    for knob in knobs:
        if knob.kind == "choice":
            for c in knob.choices:
                pop.append({knob.name: c})
        else:
            hi = int(knob.hi) if knob.kind == "int" else knob.hi
            lo = int(knob.lo) if knob.kind == "int" else knob.lo
            pop.append({knob.name: hi})
            pop.append({knob.name: lo})
    while len(pop) < size:
        pop.append({k.name: k.sample(rng)
                    for k in knobs if rng.random() < 0.4})
    return pop[:max(size, 1)]


def tune(stream: stream_mod.Stream, rounds: int = 2,
         population: int = 8, elites: int = 2, seed: int = 0,
         base=None, knobs=None) -> TuneReport:
    """Closed-loop search: evaluate a population of overlays against
    the stream, keep the elites, mutate them into the next round.
    Fully deterministic for a given (stream, seed, rounds,
    population)."""
    from ..framework import metrics
    knobs = tuple(knobs if knobs is not None else KNOBS)
    rng = random.Random(seed)
    report = TuneReport()
    # unscored warmup: the first replay pays every jax compile; its
    # timings must not leak into any scored row (the p99 term would
    # otherwise be compile noise, not steady-state cycle latency)
    rollout(stream, {}, base=base)
    report.baseline_metrics = rollout(stream, {}, base=base)
    report.baseline_score = score_rows([report.baseline_metrics])[0]
    report.rollouts = 1
    report.best_score = report.baseline_score
    report.best_metrics = list(report.baseline_metrics)
    scored: list[tuple[float, dict, list[float]]] = [
        (report.baseline_score, {}, report.baseline_metrics)]
    pop = _initial_population(rng, population, knobs)
    for rnd in range(rounds):
        rows = [rollout(stream, cand, base=base) for cand in pop]
        report.rollouts += len(pop)
        for cand, row, score in zip(pop, rows, score_rows(rows)):
            scored.append((score, cand, row))
            report.history.append({"round": rnd, "candidate": cand,
                                   "metrics": row, "score": score})
        scored.sort(key=lambda t: t[0], reverse=True)
        scored = scored[:max(elites, 1)]
        # next round: mutate the elites, fill with fresh samples
        pop = []
        for _score, cand, _row in scored:
            child = dict(cand)
            for knob in knobs:
                if rng.random() < 0.3:
                    cur = child.get(knob.name, knob.sample(rng))
                    child[knob.name] = knob.mutate(cur, rng)
            pop.append(child)
        while len(pop) < population:
            pop.append({k.name: k.sample(rng)
                        for k in knobs if rng.random() < 0.4})
    best_score, best_cand, best_row = scored[0]
    report.best_score = best_score
    report.best_candidate = best_cand
    report.best_metrics = best_row
    metrics.twin_tuner_best_score.set(value=best_score)
    return report


if __name__ == "__main__":  # pragma: no cover - operator tool
    import json
    import sys
    if len(sys.argv) < 2:
        print("usage: python -m kai_scheduler_tpu.twin.tune "
              "STREAM [ROUNDS [POP]]", file=sys.stderr)
        raise SystemExit(2)
    st = stream_mod.read_stream(sys.argv[1])
    rounds = int(sys.argv[2]) if len(sys.argv) > 2 else 2
    pop = int(sys.argv[3]) if len(sys.argv) > 3 else 8
    rep = tune(st, rounds=rounds, population=pop)
    print(json.dumps(rep.overlay_doc(), indent=2))

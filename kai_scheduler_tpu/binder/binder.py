"""Binder — consumes BindRequests and commits pod→node bindings.

Reference: a separate controller process (``pkg/binder``) watching
BindRequest CRs; per request it runs a PreBind plugin chain (volume
binding, DRA claims, GPU-sharing env injection), calls the
``pods/binding`` subresource, and on failure rolls back and retries up
to ``BackoffLimit`` (``binder/controllers/bindrequest_controller.go:55``,
``binder/binding/binder.go:34-130``).

Here the binder is an in-process reconciler over ``Cluster``: the plugin
chain is the same Name/PreBind/PostBind/Rollback protocol
(``binder/plugins/interface.go:16-24``), and async-ness is modeled by
processing whatever requests exist when ``reconcile`` runs.
"""
from __future__ import annotations

import dataclasses
from typing import Protocol

from ..apis import types as apis
from ..runtime.cluster import Cluster


class BinderPlugin(Protocol):
    """ref ``binder/plugins/interface.go:16-24``."""

    name: str

    def pre_bind(self, cluster: Cluster, pod: apis.Pod,
                 request: apis.BindRequest) -> None: ...

    def post_bind(self, cluster: Cluster, pod: apis.Pod,
                  request: apis.BindRequest) -> None: ...

    def rollback(self, cluster: Cluster, pod: apis.Pod,
                 request: apis.BindRequest) -> None: ...


@dataclasses.dataclass
class GpuSharingPlugin:
    """Fractional-accelerator bind support.

    The reference's gpusharing binder plugin injects visible-device env
    vars resolved through a reservation pod per shared GPU group
    (``binder/binding/resourcereservation/``).  TPU-native equivalent:
    fractional tasks are tagged with their device *group* so the runtime
    can map them onto the same chip; no reservation round-trip is needed
    because assignment is decided by the scheduler's device-group tensor.
    """

    name: str = "gpusharing"
    _saved_portions: dict = dataclasses.field(default_factory=dict)

    def pre_bind(self, cluster, pod, request):
        if request.received_resource_type == apis.ReceivedResourceType.FRACTION:
            self._saved_portions[pod.name] = pod.accel_portion
            pod.accel_portion = request.received_accel_portion or pod.accel_portion

    def post_bind(self, cluster, pod, request):
        self._saved_portions.pop(pod.name, None)

    def rollback(self, cluster, pod, request):
        if pod.name in self._saved_portions:
            pod.accel_portion = self._saved_portions.pop(pod.name)


@dataclasses.dataclass
class BindResult:
    bound: list[str] = dataclasses.field(default_factory=list)
    failed: list[str] = dataclasses.field(default_factory=list)
    retrying: list[str] = dataclasses.field(default_factory=list)


class Binder:
    """BindRequest reconciler with backoff."""

    def __init__(self, plugins: list[BinderPlugin] | None = None):
        self.plugins = plugins if plugins is not None else [GpuSharingPlugin()]

    def reconcile(self, cluster: Cluster) -> BindResult:
        """Process all pending BindRequests once (one controller sweep)."""
        result = BindResult()
        for br in list(cluster.bind_requests.values()):
            if br.phase != "Pending":
                continue
            pod = cluster.pods.get(br.pod_name)
            if pod is None or pod.status in (apis.PodStatus.SUCCEEDED,
                                             apis.PodStatus.FAILED):
                br.phase = "Failed"
                result.failed.append(br.pod_name)
                continue
            if pod.status == apis.PodStatus.RELEASING:
                # pipelined rebind: the old pod is still vacating; wait
                # for its restart (consolidation move path)
                result.retrying.append(br.pod_name)
                continue
            if pod.status != apis.PodStatus.PENDING:
                br.phase = "Failed"
                result.failed.append(br.pod_name)
                continue
            done: list[BinderPlugin] = []
            try:
                for plugin in self.plugins:
                    plugin.pre_bind(cluster, pod, br)
                    done.append(plugin)
                cluster.bind_pod(br.pod_name, br.selected_node,
                                 devices=br.selected_accel_groups or None)
            except Exception:
                for plugin in reversed(done):
                    plugin.rollback(cluster, pod, br)
                br.failures += 1
                if br.failures > br.backoff_limit:
                    br.phase = "Failed"
                    result.failed.append(br.pod_name)
                else:
                    result.retrying.append(br.pod_name)
                continue
            for plugin in self.plugins:
                plugin.post_bind(cluster, pod, br)
            br.phase = "Succeeded"
            result.bound.append(br.pod_name)
        return result

"""Binder — consumes BindRequests and commits pod→node bindings.

Reference: a separate controller process (``pkg/binder``) watching
BindRequest CRs; per request it runs a PreBind plugin chain (volume
binding, DRA claims, GPU-sharing env injection), calls the
``pods/binding`` subresource, and on failure rolls back and retries up
to ``BackoffLimit`` (``binder/controllers/bindrequest_controller.go:55``,
``binder/binding/binder.go:34-130``).

Here the binder is an in-process reconciler over ``Cluster``: the plugin
chain is the same Name/PreBind/PostBind/Rollback protocol
(``binder/plugins/interface.go:16-24``), and async-ness is modeled by
processing whatever requests exist when ``reconcile`` runs.
"""
from __future__ import annotations

import dataclasses
from typing import Protocol

from ..apis import types as apis
from ..intake import gate as _gate
from ..runtime.cluster import Cluster


class BinderPlugin(Protocol):
    """ref ``binder/plugins/interface.go:16-24``."""

    name: str

    def pre_bind(self, cluster: Cluster, pod: apis.Pod,
                 request: apis.BindRequest) -> None: ...

    def post_bind(self, cluster: Cluster, pod: apis.Pod,
                  request: apis.BindRequest) -> None: ...

    def rollback(self, cluster: Cluster, pod: apis.Pod,
                 request: apis.BindRequest) -> None: ...


@dataclasses.dataclass
class GpuSharingPlugin:
    """Fractional-accelerator bind support.

    The reference's gpusharing binder plugin injects visible-device env
    vars resolved through a reservation pod per shared GPU group
    (``binder/binding/resourcereservation/``).  TPU-native equivalent:
    device identity is scheduler-owned (no discovery round trip), and
    the share group is pinned through the cluster's
    ``ReservationRegistry`` — PreBind joins the target device's
    reservation (creating it for the first sharer), Rollback leaves it,
    and the registry's UUID is what the runtime mounts.
    """

    name: str = "gpusharing"
    _saved_portions: dict = dataclasses.field(default_factory=dict)
    _acquired: dict = dataclasses.field(default_factory=dict)

    def pre_bind(self, cluster, pod, request):
        if request.received_resource_type == apis.ReceivedResourceType.FRACTION:
            self._saved_portions[pod.name] = pod.accel_portion
            pod.accel_portion = request.received_accel_portion or pod.accel_portion
            if request.selected_accel_groups:
                dev = request.selected_accel_groups[0]
                cluster.reservations.acquire(
                    request.selected_node, dev, pod.name)
                self._acquired[pod.name] = (request.selected_node, dev)

    def post_bind(self, cluster, pod, request):
        self._saved_portions.pop(pod.name, None)
        self._acquired.pop(pod.name, None)

    def rollback(self, cluster, pod, request):
        if pod.name in self._saved_portions:
            pod.accel_portion = self._saved_portions.pop(pod.name)
        if pod.name in self._acquired:
            node, dev = self._acquired.pop(pod.name)
            cluster.reservations.release(pod.name, node, dev)


@dataclasses.dataclass
class DynamicResourcesPlugin:
    """DRA claim binding — the k8s-plugins binder plugin's claim path
    (``pkg/binder/plugins/k8s-plugins`` binding ResourceClaims through
    the upstream DRA manager).

    PreBind allocates each named claim onto the target node: verifies
    the claim's DeviceClass constraints against the node, picks concrete
    fully-free devices (first-fit over the runtime device table), and
    writes the allocation onto the claim object.  Rollback deallocates.
    """

    name: str = "dynamicresources"
    _bound: dict = dataclasses.field(default_factory=dict)

    def pre_bind(self, cluster, pod, request):
        names = [c for c in request.resource_claim_allocations
                 if isinstance(c, str)]
        if not names:
            return
        node = cluster.nodes[request.selected_node]
        done: list[str] = []
        try:
            for cname in names:
                claim = cluster.resource_claims.get(cname)
                if claim is None:
                    raise RuntimeError(f"unknown ResourceClaim {cname}")
                if claim.node is not None and claim.owner_pod != pod.name:
                    raise RuntimeError(
                        f"claim {cname} already allocated on {claim.node}")
                if (claim.node == node.name
                        and claim.owner_pod == pod.name):
                    # already satisfied for THIS pod on THIS node (a
                    # retried bind after snapshot/restore) — its devices
                    # are the ones node_device_free counts as taken;
                    # re-allocating would demand count MORE
                    continue
                dc = cluster.device_classes.get(claim.device_class)
                if dc is not None:
                    if (dc.min_memory_gib > 0
                            and node.accel_memory_gib < dc.min_memory_gib):
                        raise RuntimeError(
                            f"node {node.name} devices below class "
                            f"{dc.name} min memory")
                    for k, v in dc.node_selector.items():
                        if node.labels.get(k) != v:
                            raise RuntimeError(
                                f"node {node.name} fails class {dc.name} "
                                f"selector {k}={v}")
                free = cluster.node_device_free(node.name)
                fully = [d for d, f in enumerate(free) if f >= 1.0 - 1e-6]
                if len(fully) < claim.count:
                    raise RuntimeError(
                        f"only {len(fully)} free devices on {node.name} "
                        f"for claim {cname} (needs {claim.count})")
                claim.node = node.name
                claim.devices = fully[:claim.count]
                claim.owner_pod = pod.name
                done.append(cname)
            self._bound[pod.name] = done
        except Exception:
            for cname in done:  # deallocate this pod's partial progress
                claim = cluster.resource_claims[cname]
                claim.node = None
                claim.devices = []
                claim.owner_pod = None
            raise

    def post_bind(self, cluster, pod, request):
        self._bound.pop(pod.name, None)

    def rollback(self, cluster, pod, request):
        for cname in self._bound.pop(pod.name, []):
            claim = cluster.resource_claims.get(cname)
            if claim is not None:
                claim.node = None
                claim.devices = []
                claim.owner_pod = None


@dataclasses.dataclass
class VolumeBindingPlugin:
    """Volume binding at PreBind — the k8s-plugins binder plugin's
    volumebinding path (``pkg/binder/plugins/`` binding
    WaitForFirstConsumer PVCs once the pod's node is chosen).

    PreBind binds each unbound claim: verifies its StorageClass
    allowedTopologies against the target node, then records the
    volume's topology as the node's matching labels (hostname fallback)
    so future cycles pin co-users to it.  Rollback unbinds claims bound
    in this attempt.
    """

    name: str = "volumebinding"
    _bound: dict = dataclasses.field(default_factory=dict)

    def pre_bind(self, cluster, pod, request):
        if not pod.volume_claims:
            return
        node = cluster.nodes[request.selected_node]

        def node_label(k):
            # hostname falls back to the node name — per-node volume
            # pins must work on unlabeled nodes
            return node.labels.get(
                k, node.name if k == "kubernetes.io/hostname" else None)

        done: list[str] = []
        try:
            for vname in pod.volume_claims:
                pvc = cluster.volume_claims.get(vname)
                if pvc is None:
                    raise RuntimeError(f"unknown PVC {vname}")
                if pvc.bound:
                    if any(node_label(k) != v
                           for k, v in pvc.node_affinity.items()):
                        raise RuntimeError(
                            f"PVC {vname} volume not reachable from "
                            f"{node.name}")
                    continue
                sc = cluster.storage_classes.get(pvc.storage_class)
                topo = dict(sc.allowed_topology) if sc else {}
                if any(node.labels.get(k) != v for k, v in topo.items()):
                    raise RuntimeError(
                        f"node {node.name} outside PVC {vname} class "
                        "topology")
                # the provisioned volume's topology: the class topology,
                # or pinned to the node when the class does not restrict
                pvc.node_affinity = topo or {
                    "kubernetes.io/hostname": node.name}
                pvc.bound = True
                done.append(vname)
            self._bound[pod.name] = done
        except Exception:
            for vname in done:
                pvc = cluster.volume_claims[vname]
                pvc.bound = False
                pvc.node_affinity = {}
            raise

    def post_bind(self, cluster, pod, request):
        self._bound.pop(pod.name, None)

    def rollback(self, cluster, pod, request):
        for vname in self._bound.pop(pod.name, []):
            pvc = cluster.volume_claims.get(vname)
            if pvc is not None:
                pvc.bound = False
                pvc.node_affinity = {}


@dataclasses.dataclass
class BindResult:
    bound: list[str] = dataclasses.field(default_factory=list)
    failed: list[str] = dataclasses.field(default_factory=list)
    retrying: list[str] = dataclasses.field(default_factory=list)


class Binder:
    """BindRequest reconciler with backoff."""

    def __init__(self, plugins: list[BinderPlugin] | None = None):
        self.plugins = plugins if plugins is not None else [
            VolumeBindingPlugin(), DynamicResourcesPlugin(),
            GpuSharingPlugin()]

    def reconcile(self, cluster: Cluster) -> BindResult:
        """Process all pending BindRequests once (one controller sweep)."""
        result = BindResult()
        for br in list(cluster.bind_requests.values()):
            if br.phase != "Pending":
                continue
            pod = cluster.pods.get(br.pod_name)
            if pod is None or pod.status in (apis.PodStatus.SUCCEEDED,
                                             apis.PodStatus.FAILED):
                br.phase = "Failed"
                _gate.pod_touched(cluster.journal, br.pod_name)
                result.failed.append(br.pod_name)
                continue
            if pod.status == apis.PodStatus.RELEASING:
                # pipelined rebind: the old pod is still vacating; wait
                # for its restart (consolidation move path)
                result.retrying.append(br.pod_name)
                continue
            if pod.status != apis.PodStatus.PENDING:
                br.phase = "Failed"
                _gate.pod_touched(cluster.journal, br.pod_name)
                result.failed.append(br.pod_name)
                continue
            done: list[BinderPlugin] = []
            try:
                for plugin in self.plugins:
                    plugin.pre_bind(cluster, pod, br)
                    done.append(plugin)
                cluster.bind_pod(br.pod_name, br.selected_node,
                                 devices=br.selected_accel_groups or None)
            except Exception:
                for plugin in reversed(done):
                    plugin.rollback(cluster, pod, br)
                br.failures += 1
                if br.failures > br.backoff_limit:
                    br.phase = "Failed"
                    _gate.pod_touched(cluster.journal, br.pod_name)
                    result.failed.append(br.pod_name)
                else:
                    result.retrying.append(br.pod_name)
                continue
            for plugin in self.plugins:
                plugin.post_bind(cluster, pod, br)
            br.phase = "Succeeded"
            _gate.pod_touched(cluster.journal, br.pod_name)
            result.bound.append(br.pod_name)
        return result

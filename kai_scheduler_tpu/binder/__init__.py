from .binder import Binder, BinderPlugin, BindResult, GpuSharingPlugin

__all__ = ["Binder", "BinderPlugin", "BindResult", "GpuSharingPlugin"]

"""Cost-analysis + scaling probe for the allocate hot path (dev tool)."""
import functools
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np

sys.path.insert(0, ".")
from kai_scheduler_tpu.framework.session import Session
from kai_scheduler_tpu.state import make_cluster
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import allocate
import dataclasses


def build(num_nodes=10_000, num_gangs=6250, tasks_per_gang=8, **kw):
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=8.0, num_gangs=num_gangs,
        tasks_per_gang=tasks_per_gang, **kw)
    return Session.open(nodes, queues, groups, pods, topo)


def timeit(fn, iters=8, pipeline=5):
    """``fn(eps)``: eps must ride the output so every dispatch has a
    distinct cache key (the harness link serves a content-keyed result
    cache for repeated identical dispatches — see bench._next_eps)."""
    eps = [jnp.float32(i * 1e-10) for i in range(iters * pipeline + 1)]
    jax.block_until_ready(eps)
    seq = iter(eps)
    jax.block_until_ready(fn(next(seq)))
    best = []
    for _ in range(iters):
        t0 = time.perf_counter()
        jax.block_until_ready([fn(next(seq)) for _ in range(pipeline)])
        best.append((time.perf_counter() - t0) / pipeline)
    return np.median(best) * 1e3, np.percentile(best, 99) * 1e3


def main():
    shape = sys.argv[1] if len(sys.argv) > 1 else "headline"
    kw = {}
    if shape == "headline":
        kw = dict(num_nodes=10_000, num_gangs=6250, tasks_per_gang=8)
    elif shape == "gang":
        kw = dict(num_nodes=2000, num_gangs=1000, tasks_per_gang=8)
    elif shape == "half":
        kw = dict(num_nodes=10_000, num_gangs=3125, tasks_per_gang=8)
    ses = build(**kw)
    num_levels = ses.config.num_levels
    config = ses.config.allocate
    for field in ("uniform_tasks", "dense_feasibility", "anti_groups",
                  "track_devices", "extended", "batch_size",
                  "dynamic_order"):
        print(field, getattr(config, field))
    if len(sys.argv) > 2:
        for kv in sys.argv[2].split(","):
            k, v = kv.split("=")
            if v in ("True", "False"):
                val = v == "True"
            else:
                val = int(v)  # raises on anything unrecognized
            config = dataclasses.replace(config, **{k: val})

    @jax.jit
    def cycle(state, e):
        fair_share = drf.set_fair_share(state, num_levels=num_levels)
        st = state.replace(
            queues=state.queues.replace(fair_share=fair_share))
        res = allocate(st, fair_share, num_levels=num_levels, config=config)
        return res.placements, res.allocated, e + 1.0

    lowered = cycle.lower(ses.state, jnp.float32(0.0))
    compiled = lowered.compile()
    ca = compiled.cost_analysis()
    if isinstance(ca, list):
        ca = ca[0]
    print("flops", ca.get("flops"), "bytes", ca.get("bytes accessed"))

    placements, alloc, _ = jax.block_until_ready(
        cycle(ses.state, jnp.float32(0.0)))
    placed = int((np.asarray(placements) >= 0).sum())
    med, p99 = timeit(lambda e: cycle(ses.state, e))
    print(f"placed={placed} median={med:.2f}ms p99={p99:.2f}ms")

    @jax.jit
    def drf_only(state, e):
        return drf.set_fair_share(state, num_levels=num_levels) + e
    med, p99 = timeit(lambda e: drf_only(ses.state, e))
    print(f"drf only: median={med:.2f}ms p99={p99:.2f}ms")


if __name__ == "__main__":
    main()

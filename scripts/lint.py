#!/usr/bin/env python
"""Thin kai-lint wrapper for local / pre-commit use.

Runs the AST layers only — the KAI0xx trace-safety rules AND the
KAI1xx kai-race concurrency pass (both pure AST, no jax import) — and
exits nonzero on any new finding:

    python scripts/lint.py             # lint the repo (incl. kai-race)
    python scripts/lint.py --json      # machine-readable
    python scripts/lint.py --select KAI041,KAI052
    python scripts/lint.py --select KAI101,KAI102,KAI105  # race only

Hook it up with::

    printf 'python scripts/lint.py || exit 1\n' >> .git/hooks/pre-commit

The full gate (AST lint + jaxpr probe) is
``python -m kai_scheduler_tpu.analysis``; the tier-1 suite runs it via
``tests/test_analysis.py``.
"""
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kai_scheduler_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--no-probe", "--root", REPO_ROOT, *sys.argv[1:]]))

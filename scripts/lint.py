#!/usr/bin/env python
"""Thin kai-lint wrapper for local / pre-commit use.

Runs the AST layer only (no jax import — sub-second), exits nonzero on
any new finding:

    python scripts/lint.py             # lint the repo
    python scripts/lint.py --json      # machine-readable
    python scripts/lint.py --select KAI041,KAI052

Hook it up with::

    printf 'python scripts/lint.py || exit 1\n' >> .git/hooks/pre-commit

The full gate (AST lint + jaxpr probe) is
``python -m kai_scheduler_tpu.analysis``; the tier-1 suite runs it via
``tests/test_analysis.py``.
"""
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kai_scheduler_tpu.analysis.__main__ import main  # noqa: E402

if __name__ == "__main__":
    sys.exit(main(["--no-probe", "--root", REPO_ROOT, *sys.argv[1:]]))

#!/usr/bin/env python
"""Thin kai-lint wrapper for local / pre-commit use.

Runs the AST layers only — the KAI0xx trace-safety rules AND the
KAI1xx kai-race concurrency pass (both pure AST, no jax import) — and
exits nonzero on any new finding:

    python scripts/lint.py             # lint the repo (incl. kai-race)
    python scripts/lint.py --json      # machine-readable
    python scripts/lint.py --select KAI041,KAI052
    python scripts/lint.py --select KAI101,KAI102,KAI105  # race only

It also drift-checks the generated metrics catalog: the registrations
in ``kai_scheduler_tpu/framework/metrics.py`` (extracted by AST, so
this stays jax-free) must agree exactly — name, type, labels, help —
with the committed ``docs/metrics/METRICS.md``.  Regenerate with::

    python -m kai_scheduler_tpu.framework.metrics > docs/metrics/METRICS.md

(``tests/test_metrics_catalog.py`` runs the same check against the
LIVE registry, plus a meta-check that this AST extraction matches it.)

It also drift-checks the **kai-cost and kai-comms baseline coverage**
without importing jax: probe, cost, and comms coverage ride ONE
registry (``analysis/trace_probe._registry``), so ``baseline.json``'s
``probe`` keys, ``cost_baseline.json``'s ``entries`` keys, and
``comm_baseline.json``'s ``entries`` keys must be identical sets — a
new jit entry baselined for the probe but missing a cost budget or a
comm budget (or vice versa) fails here pre-commit, before the
jax-heavy gate ever runs.  Refresh all three in one invocation with::

    python -m kai_scheduler_tpu.analysis --update-baseline

Hook it up with::

    printf 'python scripts/lint.py || exit 1\n' >> .git/hooks/pre-commit

Exit status: 0 clean; 1 on any lint/race finding, metrics-doc drift,
or cost-baseline coverage drift.  The full gate (AST lint + jaxpr
probe + the kai-cost dataflow audit) is
``python -m kai_scheduler_tpu.analysis`` (``--cost`` for the cost
stage alone); the tier-1 suite runs it via ``tests/test_analysis.py``
and ``tests/test_costmodel.py``.
"""
import ast
import os
import sys

REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO_ROOT)

from kai_scheduler_tpu.analysis.__main__ import main  # noqa: E402
from kai_scheduler_tpu.utils.metrics import parse_catalog  # noqa: E402

METRICS_SRC = os.path.join(REPO_ROOT, "kai_scheduler_tpu", "framework",
                           "metrics.py")
METRICS_DOC = os.path.join(REPO_ROOT, "docs", "metrics", "METRICS.md")
PROBE_BASELINE = os.path.join(REPO_ROOT, "kai_scheduler_tpu",
                              "analysis", "baseline.json")
COST_BASELINE = os.path.join(REPO_ROOT, "kai_scheduler_tpu",
                             "analysis", "cost_baseline.json")
COMM_BASELINE = os.path.join(REPO_ROOT, "kai_scheduler_tpu",
                             "analysis", "comm_baseline.json")


def check_cost_baseline(probe_path: str = PROBE_BASELINE,
                        cost_path: str = COST_BASELINE) -> list[str]:
    """kai-cost coverage drift, jax-free: the probe and cost baselines
    budget the SAME registry of entries, so their key sets must match
    exactly.  One message per divergence, empty when in sync."""
    import json
    if not os.path.exists(cost_path):
        return [f"{cost_path} is missing — generate with `python -m "
                f"kai_scheduler_tpu.analysis --cost --update-baseline`"]
    if not os.path.exists(probe_path):
        return [f"{probe_path} is missing — generate with `python -m "
                f"kai_scheduler_tpu.analysis --probe --update-baseline`"]
    with open(probe_path, encoding="utf-8") as f:
        probe = set(json.load(f).get("probe", {}))
    with open(cost_path, encoding="utf-8") as f:
        cost = set(json.load(f).get("entries", {}))
    problems = []
    for name in sorted(probe - cost):
        problems.append(
            f"entry `{name}` has a probe baseline but no kai-cost "
            f"budget in cost_baseline.json")
    for name in sorted(cost - probe):
        problems.append(
            f"cost_baseline.json budgets `{name}` but the probe "
            f"baseline has no such entry (stale?)")
    if problems:
        problems.append("refresh both in one invocation: python -m "
                        "kai_scheduler_tpu.analysis --update-baseline")
    return problems


def check_comm_baseline(probe_path: str = PROBE_BASELINE,
                        comm_path: str = COMM_BASELINE) -> list[str]:
    """kai-comms coverage drift, jax-free: the comm baseline budgets
    the same registry the probe baseline covers, so their key sets must
    match exactly.  One message per divergence, empty when in sync."""
    import json
    if not os.path.exists(comm_path):
        return [f"{comm_path} is missing — generate with `python -m "
                f"kai_scheduler_tpu.analysis --comms --update-baseline`"]
    if not os.path.exists(probe_path):
        return [f"{probe_path} is missing — generate with `python -m "
                f"kai_scheduler_tpu.analysis --probe --update-baseline`"]
    with open(probe_path, encoding="utf-8") as f:
        probe = set(json.load(f).get("probe", {}))
    with open(comm_path, encoding="utf-8") as f:
        comm = set(json.load(f).get("entries", {}))
    problems = []
    for name in sorted(probe - comm):
        problems.append(
            f"entry `{name}` has a probe baseline but no kai-comms "
            f"budget in comm_baseline.json")
    for name in sorted(comm - probe):
        problems.append(
            f"comm_baseline.json budgets `{name}` but the probe "
            f"baseline has no such entry (stale?)")
    if problems:
        problems.append("refresh all baselines in one invocation: "
                        "python -m kai_scheduler_tpu.analysis "
                        "--update-baseline")
    return problems


def registered_metrics_ast(path: str = METRICS_SRC) -> list[dict]:
    """Every ``registry.counter/gauge/histogram(...)`` registration in
    the metrics module, extracted without importing it (importing the
    framework package pulls jax; this wrapper must stay sub-second)."""
    with open(path, encoding="utf-8") as f:
        tree = ast.parse(f.read(), filename=path)
    rows = []
    for node in ast.walk(tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr in ("counter", "gauge", "histogram")
                and isinstance(node.func.value, ast.Name)
                and node.func.value.id == "registry"):
            continue
        args = list(node.args)
        kwargs = {k.arg: k.value for k in node.keywords}
        name_node = args[0] if args else kwargs.get("name")
        help_node = args[1] if len(args) > 1 else kwargs.get("help")
        labels_node = (args[2] if len(args) > 2
                       else kwargs.get("label_names"))
        name = name_node.value if isinstance(name_node,
                                             ast.Constant) else None
        if name is None:
            continue
        help_text = (help_node.value
                     if isinstance(help_node, ast.Constant) else "")
        labels = []
        if isinstance(labels_node, (ast.Tuple, ast.List)):
            labels = [e.value for e in labels_node.elts
                      if isinstance(e, ast.Constant)]
        rows.append({"name": name, "type": node.func.attr,
                     "labels": labels,
                     "help": " ".join(str(help_text).split())})
    rows.sort(key=lambda r: r["name"])
    return rows


def check_metrics_doc() -> list[str]:
    """Drift between the registrations and the committed catalog doc —
    one message per divergence, empty when in sync."""
    if not os.path.exists(METRICS_DOC):
        return [f"{METRICS_DOC} is missing — regenerate with "
                f"`python -m kai_scheduler_tpu.framework.metrics`"]
    with open(METRICS_DOC, encoding="utf-8") as f:
        doc_rows = {r["name"]: r for r in parse_catalog(f.read())}
    src_rows = {r["name"]: r for r in registered_metrics_ast()}
    problems = []
    for name in sorted(src_rows.keys() - doc_rows.keys()):
        problems.append(f"metric `{name}` is registered but missing "
                        f"from docs/metrics/METRICS.md")
    for name in sorted(doc_rows.keys() - src_rows.keys()):
        problems.append(f"docs/metrics/METRICS.md lists `{name}` but "
                        f"no such registration exists")
    for name in sorted(src_rows.keys() & doc_rows.keys()):
        for field in ("type", "labels", "help"):
            if src_rows[name][field] != doc_rows[name][field]:
                problems.append(
                    f"metric `{name}` {field} drifted: registered "
                    f"{src_rows[name][field]!r} != documented "
                    f"{doc_rows[name][field]!r}")
    if problems:
        problems.append("regenerate: python -m "
                        "kai_scheduler_tpu.framework.metrics "
                        "> docs/metrics/METRICS.md")
    return problems


SCENARIO_STREAM_DIR = os.path.join(REPO_ROOT, "tests", "scenarios",
                                   "streams")


def check_scenario_streams(dirpath: str = SCENARIO_STREAM_DIR) -> list[str]:
    """Validity gate over the checked-in kai-twin scenario streams,
    jax-free (``twin/stream.py`` is stdlib-only by design): every
    ``*.stream.json[.gz]`` must parse, carry the exact format/version,
    pass structural validation, and declare a non-empty invariant set.
    Regenerate with ``python -m kai_scheduler_tpu.twin.fuzz
    --write-scenarios tests/scenarios/streams``."""
    import json
    from kai_scheduler_tpu.twin.stream import (read_doc,
                                               validate_stream_doc)
    if not os.path.isdir(dirpath):
        return [f"{dirpath} is missing — the fuzzer's minimized "
                f"scenarios must be checked in"]
    files = sorted(f for f in os.listdir(dirpath)
                   if f.endswith((".stream.json", ".stream.json.gz")))
    if not files:
        return [f"{dirpath} holds no *.stream.json files"]
    problems = []
    for fname in files:
        path = os.path.join(dirpath, fname)
        try:
            doc = read_doc(path)
        except (OSError, json.JSONDecodeError, ValueError) as exc:
            problems.append(f"{fname}: unreadable ({exc})")
            continue
        for msg in validate_stream_doc(doc, require_invariants=True):
            problems.append(f"{fname}: {msg}")
    if problems:
        problems.append("regenerate: python -m kai_scheduler_tpu."
                        "twin.fuzz --write-scenarios "
                        "tests/scenarios/streams")
    return problems


if __name__ == "__main__":
    rc = main(["--no-probe", "--root", REPO_ROOT, *sys.argv[1:]])
    drift = check_metrics_doc()
    for msg in drift:
        print(f"METRICS-DOC DRIFT: {msg}", file=sys.stderr)
    cost_drift = check_cost_baseline()
    for msg in cost_drift:
        print(f"COST-BASELINE DRIFT: {msg}", file=sys.stderr)
    comm_drift = check_comm_baseline()
    for msg in comm_drift:
        print(f"COMM-BASELINE DRIFT: {msg}", file=sys.stderr)
    stream_drift = check_scenario_streams()
    for msg in stream_drift:
        print(f"SCENARIO-STREAM DRIFT: {msg}", file=sys.stderr)
    sys.exit(rc or (1 if drift or cost_drift or comm_drift
                    or stream_drift else 0))

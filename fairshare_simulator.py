#!/usr/bin/env python
"""Fairshare simulator — HTTP parity harness for the DRF division kernel.

Re-implements ``cmd/fairshare-simulator`` (see its README): POST
``/simulate`` with the same JSON schema —

    {"totalResource": {"GPU": 100, "CPU": 16000, "Memory": 32e6},
     "queues": [{"uid": "q1", "priority": 0,
                 "resourceShare": {"gpu": {"deserved": 10, "request": 100,
                                           "overQuotaWeight": 3,
                                           "maxAllowed": -1, "usage": 0}}}]}

— and receive ``{uid: {"gpu": fair, "cpu": fair, "memory": fair}}``.
``kValue`` may be set per request (the time-based-fairshare-simulator's
knob); per-resource ``usage`` feeds the k term (normalized
usage/clusterCapacity, ref ``resource_division.go:238-246``).

Run: ``python fairshare_simulator.py --port 8080`` or one-shot:
``python fairshare_simulator.py --simulate request.json``.
"""
from __future__ import annotations

import argparse
import json
import sys
from http.server import BaseHTTPRequestHandler, HTTPServer

_RES_KEYS = ("gpu", "cpu", "memory")   # maps to (accel, cpu, memory)
_UNLIMITED = -1.0


def simulate(request: dict) -> dict:
    """Pure function: request dict → {uid: {gpu, cpu, memory}}."""
    import jax.numpy as jnp
    import numpy as np

    from kai_scheduler_tpu.ops import drf
    from kai_scheduler_tpu.state.cluster_state import QueueState, _round_up

    queues = request.get("queues", [])
    total_in = {k.lower(): float(v)
                for k, v in request.get("totalResource", {}).items()}
    total = np.array([total_in.get("gpu", 0.0), total_in.get("cpu", 0.0),
                      total_in.get("memory", 0.0)], np.float32)
    k_value = float(request.get("kValue", 0.0))

    nq = len(queues)
    Q = _round_up(max(nq, 1), 8)
    quota = np.zeros((Q, 3), np.float32)
    weight = np.ones((Q, 3), np.float32)
    limit = np.full((Q, 3), _UNLIMITED, np.float32)
    req = np.zeros((Q, 3), np.float32)
    usage = np.zeros((Q, 3), np.float32)
    prio = np.zeros((Q,), np.int32)
    valid = np.zeros((Q,), bool)
    for i, q in enumerate(queues):
        valid[i] = True
        prio[i] = int(q.get("priority", 0))
        share = {k.lower(): v
                 for k, v in q.get("resourceShare", {}).items()}
        for r, key in enumerate(_RES_KEYS):
            spec = share.get(key, {}) or {}
            quota[i, r] = float(spec.get("deserved", 0.0))
            weight[i, r] = float(spec.get("overQuotaWeight", 1.0))
            limit[i, r] = float(spec.get("maxAllowed", _UNLIMITED))
            req[i, r] = float(spec.get("request", 0.0))
            usage[i, r] = float(spec.get("usage", 0.0))

    qs = QueueState(
        parent=jnp.full((Q,), -1, jnp.int32),
        depth=jnp.zeros((Q,), jnp.int32),
        priority=jnp.asarray(prio),
        quota=jnp.asarray(quota),
        over_quota_weight=jnp.asarray(weight),
        limit=jnp.asarray(limit),
        allocated=jnp.zeros((Q, 3), jnp.float32),
        allocated_nonpreemptible=jnp.zeros((Q, 3), jnp.float32),
        request=jnp.asarray(req),
        usage=jnp.asarray(usage),
        fair_share=jnp.zeros((Q, 3), jnp.float32),
        valid=jnp.asarray(valid),
        creation_order=jnp.arange(Q, dtype=jnp.int32),
        preempt_min_runtime=jnp.zeros((Q,), jnp.float32),
        reclaim_min_runtime=jnp.zeros((Q,), jnp.float32),
        preempt_min_runtime_eff=jnp.zeros((Q,), jnp.float32),
        reclaim_min_runtime_eff=jnp.zeros((Q, Q), jnp.float32),
    )
    seg_total = jnp.concatenate(
        [jnp.asarray(total)[None, :], jnp.zeros((Q, 3), jnp.float32)],
        axis=0)
    fs = np.asarray(drf.divide_level(
        qs, seg_total, jnp.asarray(valid), jnp.asarray(k_value)))
    out = {}
    for i, q in enumerate(queues):
        uid = q.get("uid", q.get("name", f"queue{i}"))
        out[uid] = {"gpu": float(fs[i, 0]), "cpu": float(fs[i, 1]),
                    "memory": float(fs[i, 2])}
    return out


class _Handler(BaseHTTPRequestHandler):
    def do_POST(self):  # noqa: N802 (stdlib naming)
        if self.path != "/simulate":
            self.send_error(404)
            return
        length = int(self.headers.get("Content-Length", 0))
        try:
            req = json.loads(self.rfile.read(length).decode())
            resp = json.dumps(simulate(req)).encode()
        except Exception as exc:  # noqa: BLE001 — mirror the ref's 400
            self.send_error(400, str(exc))
            return
        self.send_response(200)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(resp)))
        self.end_headers()
        self.wfile.write(resp)

    def log_message(self, *args):  # quiet
        pass


def main() -> int:
    ap = argparse.ArgumentParser()
    ap.add_argument("--port", type=int, default=8080)
    ap.add_argument("--simulate", metavar="REQUEST_JSON",
                    help="one-shot: read request file ('-' = stdin), "
                         "print response, exit")
    args = ap.parse_args()
    if args.simulate:
        src = (sys.stdin if args.simulate == "-"
               else open(args.simulate, encoding="utf-8"))
        with src:
            print(json.dumps(simulate(json.load(src)), indent=2,
                             sort_keys=True))
        return 0
    srv = HTTPServer(("", args.port), _Handler)
    print(f"fairshare-simulator listening on :{args.port}")
    srv.serve_forever()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())

"""kai-intake tests (PR 12) — the async multi-lane mutation front end.

The load-bearing assertion is the DIFFERENTIAL: a randomized storm of
interleaved creates/deletes/updates (including same-key races, which
lane-sharding must confine to one lane) routed through the
IntakeRouter's queue → admit → stage → coalesce pipeline yields a hub
cluster, a hub journal (cursor-for-cursor), and a next scheduling
cycle's binds/evictions/DecisionLog **bit-identical** to the same
events applied sequentially through the classic synchronous path.
Plus: atomic shed (429, nothing journaled), degrade-to-sync,
vectorized admission rejections, the /intake + /debug/intake server
surfaces, and a storm-vs-scrapes endpoint hammer.
"""
import copy
import json
import random
import threading
import urllib.error
import urllib.request

import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler, SchedulerConfig
from kai_scheduler_tpu.framework.server import SchedulerServer
from kai_scheduler_tpu.intake import apply as intake_apply
from kai_scheduler_tpu.intake.router import IntakeConfig, IntakeRouter
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.snapshot import dump_cluster
from kai_scheduler_tpu.state import make_cluster
from kai_scheduler_tpu.state.incremental import MutationJournal

pytestmark = pytest.mark.core

CURSOR_FIELDS = ("pods_dirty", "pods_added", "pods_removed",
                 "gangs_dirty", "gangs_added", "nodes_dirty",
                 "structural", "time_dirty")


def _cluster():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def _assert_cursor_equal(batch_a, batch_b):
    for field in CURSOR_FIELDS:
        va, vb = getattr(batch_a, field), getattr(batch_b, field)
        assert va == vb, (field, va, vb)


def _storm_deltas(rng: random.Random, n: int) -> list[dict]:
    """Interleaved creates / partial updates / deletes / clock ticks
    over a small key space, so same-key races (update-after-delete,
    delete-then-recreate) occur by construction."""
    deltas = []
    for i in range(n):
        kind = rng.randrange(5)
        pid = rng.randrange(12)
        pod = f"storm-p{pid}"
        gang = f"storm-g{pid % 5}"
        if kind == 0:  # create (gang + pod)
            deltas.append({
                "pod_groups_upsert": [
                    {"name": gang, "queue": "queue-0-0", "min_member": 1}],
                "pods_upsert": [{
                    "name": pod, "group": gang,
                    "resources": {"accel": 1.0, "cpu": 1.0,
                                  "memory": 1.0}}]})
        elif kind == 1:  # partial update over whatever is stored
            deltas.append({"pods_upsert": [
                {"name": pod, "priority": rng.randrange(3)}]})
        elif kind == 2:  # delete (possibly of a never-created key)
            deltas.append({"pods_delete": [pod]})
        elif kind == 3:  # clock advance
            deltas.append({"now": float(i)})
        else:  # mixed multi-collection document
            deltas.append({
                "pods_upsert": [{"name": pod, "group": gang}],
                "pods_delete": [f"storm-p{(pid + 1) % 12}"],
            })
    return deltas


# ---------------------------------------------------------------------------
# journal merge
# ---------------------------------------------------------------------------


def test_journal_merge_identical_to_sequential_marks():
    """MutationJournal.merge replays (kind, name) batches with the
    exact per-mark semantics — including the order-sensitive
    pod-readded structural escalation — under one lock acquisition."""
    j_seq, j_merge = MutationJournal(), MutationJournal()
    cur_seq, cur_merge = j_seq.register(), j_merge.register()
    ops = [("pod", "a"), ("pod_added", "b"), ("pod_removed", "c"),
           ("pod_added", "c"),           # removed-then-readded
           ("gang", "g"), ("gang_added", "h"), ("node", "n"),
           ("structural", "why"), ("time", ""), ("pod_added", "a")]
    j_seq.mark_pod("a")
    j_seq.mark_pod_added("b")
    j_seq.mark_pod_removed("c")
    j_seq.mark_pod_added("c")
    j_seq.mark_gang("g")
    j_seq.mark_gang_added("h")
    j_seq.mark_node("n")
    j_seq.mark_structural("why")
    j_seq.mark_time()
    j_seq.mark_pod_added("a")
    j_merge.merge(ops)
    assert j_seq.generation == j_merge.generation == len(ops)
    _assert_cursor_equal(cur_seq.consume(), cur_merge.consume())

    with pytest.raises(ValueError, match="unknown journal mark"):
        j_merge.merge([("bogus", "x")])


# ---------------------------------------------------------------------------
# lane routing
# ---------------------------------------------------------------------------


def test_same_key_events_route_to_one_lane():
    router = IntakeRouter(IntakeConfig(lanes=4, lane_capacity=1000))
    ops = [("upsert", "pods", "same-pod", {"name": "same-pod",
                                           "group": "g"})] * 16
    router.submit_ops(ops)
    occupied = [s for s in router.debug_doc()["lane_stats"]
                if s["queued"] or s["staged"]]
    assert len(occupied) == 1 and occupied[0]["accepted"] == 16

    many = [("upsert", "pods", f"p{i}", {"name": f"p{i}", "group": "g"})
            for i in range(64)]
    router.submit_ops(many)
    spread = [s for s in router.debug_doc()["lane_stats"]
              if s["queued"] or s["staged"]]
    assert len(spread) >= 3  # 64 keys over 4 hash lanes


# ---------------------------------------------------------------------------
# THE differential: storm through lanes == sequential classic path
# ---------------------------------------------------------------------------


def test_storm_vs_sequential_bit_identical():
    """Randomized 4-lane storm (creates/deletes/updates/clock, same-key
    races included) → drain → coalesce must produce a hub cluster, a
    hub journal, and a next cycle's binds + evictions + DecisionLog
    bit-identical to applying the same deltas sequentially through the
    classic path."""
    c_classic = _cluster()
    c_intake = copy.deepcopy(c_classic)
    cur_classic = c_classic.journal.register()
    cur_intake = c_intake.journal.register()

    rng = random.Random(1234)
    deltas = _storm_deltas(rng, 400)

    for d in deltas:
        intake_apply.apply_cluster_delta(c_classic, d)

    router = IntakeRouter(IntakeConfig(lanes=4, lane_capacity=100000,
                                       batch=64)).start()
    try:
        for d in deltas:
            out = router.submit_delta(d)
            assert out["shed"] == 0
        assert router.drain_inline(timeout=30)
        summary = router.coalesce(c_intake)
    finally:
        router.stop()
    assert summary["events"] > 400  # multi-op documents decompose

    # hub journal: cursor-for-cursor and generation bit-identical
    _assert_cursor_equal(cur_classic.consume(), cur_intake.consume())
    assert c_classic.journal.generation == c_intake.journal.generation
    # hub document: object-for-object identical
    assert dump_cluster(c_classic) == dump_cluster(c_intake)

    # next cycle: binds / evictions / DecisionLog bit-identical
    s_classic, s_intake = Scheduler(), Scheduler()
    r_classic = s_classic.run_once(c_classic)
    r_intake = s_intake.run_once(c_intake)
    assert r_classic.bind_requests == r_intake.bind_requests
    assert r_classic.evictions == r_intake.evictions

    def last_events(sched):
        evs = sched.decisions.events(limit=100000)
        if not evs:
            return []
        last = max(e["cycle"] for e in evs)
        return sorted((e["gang"], e["queue"], e["outcome"], e["detail"])
                      for e in evs if e["cycle"] == last)

    assert last_events(s_classic) == last_events(s_intake)


def test_concurrent_producers_storm_converges():
    """4 producer threads with disjoint key spaces hammer the router
    while workers drain; after coalesce every accepted event landed
    exactly once (per-key ordering is lane-FIFO by construction)."""
    cluster = Cluster()
    cluster.queues["q"] = apis.Queue("q")
    router = IntakeRouter(IntakeConfig(lanes=4, lane_capacity=200000,
                                       batch=256)).start()
    per_producer = 300

    def produce(tid: int):
        for i in range(per_producer):
            router.submit_delta({"pods_upsert": [{
                "name": f"t{tid}-p{i}", "group": f"t{tid}-g"}]})

    try:
        threads = [threading.Thread(target=produce, args=(t,))
                   for t in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert router.drain_inline(timeout=30)
        router.coalesce(cluster)
    finally:
        router.stop()
    assert len(cluster.pods) == 4 * per_producer
    health = router.health()
    assert health["accepted"] == health["coalesced_events"] \
        == 4 * per_producer
    assert health["shed"] == health["rejected"] == 0


# ---------------------------------------------------------------------------
# backpressure
# ---------------------------------------------------------------------------


def test_shed_is_atomic_and_never_half_journals():
    """A lane-overflowing group is refused WHOLE: kai_intake_shed_total
    increments, nothing reaches the queue, nothing ever reaches the
    journal — no partial write."""
    from kai_scheduler_tpu.framework import metrics
    cluster = Cluster()
    cursor = cluster.journal.register()
    gen0 = cluster.journal.generation
    # no workers started: the queue can only fill
    router = IntakeRouter(IntakeConfig(lanes=2, lane_capacity=4))
    shed_before = sum(
        s["shed"] for s in router.debug_doc()["lane_stats"])
    assert shed_before == 0
    ops = [("upsert", "pods", "hot-key",
            {"name": "hot-key", "priority": i}) for i in range(6)]
    metric_before = metrics.intake_shed.value(
        str(router._lane_of("hot-key").idx))
    out = router.submit_ops(ops)
    assert (out["accepted"], out["shed"], out["total"]) == (0, 6, 6)
    # the shed echo names exactly the refused ops, for exact retries
    assert [o[2] for o in out["shed_ops"]] == ["hot-key"] * 6
    lane = router._lane_of("hot-key")
    assert metrics.intake_shed.value(str(lane.idx)) \
        == metric_before + 6
    # nothing queued, nothing staged, nothing journaled
    assert router.health()["queued"] == 0
    router.coalesce(cluster)
    assert cluster.journal.generation == gen0
    batch = cursor.consume()
    for field in CURSOR_FIELDS:
        assert not getattr(batch, field), field
    # a smaller group still fits afterwards
    assert router.submit_ops(ops[:3])["shed"] == 0


def test_all_or_nothing_submit_refuses_whole_request():
    """The HTTP boundary's contract: with all_or_nothing=True a shed
    refuses the WHOLE request even when other lanes had room — a 429
    means nothing was queued, so a client's blind full retry can never
    double-apply a partially accepted delta."""
    router = IntakeRouter(IntakeConfig(lanes=4, lane_capacity=4))
    router.submit_ops([("upsert", "pods", "hot",
                        {"name": "hot", "priority": i})
                       for i in range(4)])  # fill hot's lane
    assert router.health()["queued"] == 4
    ops = [("upsert", "pods", f"aon-{i}", {"name": f"aon-{i}"})
           for i in range(3)] + [("upsert", "pods", "hot",
                                  {"name": "hot"})]
    out = router.submit_ops(ops, all_or_nothing=True)
    assert out["accepted"] == 0 and out["shed"] == 4
    assert router.health()["queued"] == 4  # nothing new anywhere
    # shed blame lands on the saturated lane only — healthy lanes
    # collaterally refused with it must not be charged
    hot_idx = router._lane_of("hot").idx
    for s in router.debug_doc()["lane_stats"]:
        assert (s["shed"] > 0) == (s["lane"] == hot_idx), s
    # without the flag, the fitting lanes' slices are accepted and the
    # shed echo names exactly the refused portion
    out = router.submit_ops(ops)
    assert out["shed"] >= 1
    assert {o[2] for o in out["shed_ops"]} <= {"hot", "aon-0",
                                               "aon-1", "aon-2"}


def test_sync_policy_degrades_instead_of_shedding():
    """policy="sync" + an overflowing lane: the submitter quiesces the
    lanes, flushes a coalesce through the (caller-supplied) commit
    valve, and retries — every event lands, nothing sheds, the degrade
    is counted."""
    cluster = Cluster()
    flushes = []

    router = IntakeRouter(
        IntakeConfig(lanes=2, lane_capacity=8, policy="sync"),
        sync_flush=lambda: flushes.append(router.coalesce(cluster)))
    total = 0
    for i in range(10):
        out = router.submit_ops([
            ("upsert", "pods", f"sync-p{i}-{j}",
             {"name": f"sync-p{i}-{j}", "group": "g"})
            for j in range(6)])
        assert out["shed"] == 0
        total += out["accepted"]
    router.drain_inline(timeout=10)
    router.coalesce(cluster)
    assert total == 60 and len(cluster.pods) == 60
    assert flushes, "overflow never exercised the sync valve"
    health = router.health()
    assert health["sync_degrades"] == len(flushes)
    # a refusal the degrade path then DELIVERED is not a drop: both
    # shed surfaces (health totals and per-lane stats) must stay zero
    assert health["shed"] == 0
    assert all(s["shed"] == 0 for s in router.debug_doc()["lane_stats"])


# ---------------------------------------------------------------------------
# vectorized admission
# ---------------------------------------------------------------------------


def test_admission_rejects_bad_events_in_batch():
    cluster = Cluster()
    router = IntakeRouter(IntakeConfig(lanes=2, lane_capacity=100))
    bad = [
        ("upsert", "pods", "neg",
         {"name": "neg", "resources": {"cpu": -1.0}}),
        ("upsert", "pods", "nan",
         {"name": "nan", "resources": {"accel": float("nan")}}),
        ("upsert", "pods", "huge",
         {"name": "huge", "resources": {"memory": 1e12}}),
        ("upsert", "pods", "frac",
         {"name": "frac", "accel_portion": 1.5}),
        # one float32 ulp past the bounds: a single-precision sweep
        # would round these ONTO the cap / 1.0 and admit them
        ("upsert", "pods", "ulp-cap",
         {"name": "ulp-cap", "resources": {"cpu": 1.0e9 + 63.0}}),
        ("upsert", "pods", "ulp-frac",
         {"name": "ulp-frac", "accel_portion": 1.0 + 1e-8}),
        ("upsert", "frobs", "x", {"name": "x"}),
        ("upsert", "pods", "", {"group": "g"}),
        ("delete", "pods", "", ""),
        ("now", "", "", "not-a-clock"),
    ]
    good = [
        ("upsert", "pods", "ok-1",
         {"name": "ok-1", "group": "g",
          "resources": {"accel": 1.0, "cpu": 1.0, "memory": 1.0}}),
        ("upsert", "pods", "ok-2",
         {"name": "ok-2", "group": "g", "accel_portion": 0.5}),
        ("delete", "pods", "ok-1", "ok-1"),
        ("now", "", "", 7.5),
    ]
    out = router.submit_ops(bad + good)
    assert out["shed"] == 0
    router.drain_inline(timeout=10)
    router.coalesce(cluster)
    assert set(cluster.pods) == {"ok-2"}
    assert cluster.now == 7.5
    health = router.health()
    assert health["rejected"] == len(bad)
    assert health["coalesced_events"] == len(good)
    # the rejection ring surfaces reasons on /debug/intake
    reasons = {e["reason"]
               for s in router.debug_doc()["lane_stats"]
               for e in s["errors"]}
    assert any("out of range" in r for r in reasons)
    assert any("unknown collection" in r for r in reasons)


def test_oversized_int_resource_rejected_without_killing_worker():
    """A JSON integer wider than a double (1e400 as an int literal)
    must reject per-event — unguarded it raised OverflowError inside
    the batched np.asarray, killing the lane's drain worker forever
    and leaking the inflight count."""
    cluster = Cluster()
    router = IntakeRouter(IntakeConfig(lanes=1, lane_capacity=100)).start()
    try:
        out = router.submit_ops([
            ("upsert", "pods", "fat",
             {"name": "fat", "resources": {"cpu": 10 ** 400}}),
            ("upsert", "pods", "ok",
             {"name": "ok", "group": "g"}),
        ])
        assert out["shed"] == 0
        assert router.drain_inline(timeout=10)
        router.coalesce(cluster)
        assert set(cluster.pods) == {"ok"}
        assert router.health()["rejected"] == 1
        # the worker survived and the lane still drains
        assert router.debug_doc()["workers_alive"] == 1
        router.submit_ops([("upsert", "pods", "after",
                            {"name": "after", "group": "g"})])
        assert router.drain_inline(timeout=10)
        router.coalesce(cluster)
        assert "after" in cluster.pods
    finally:
        router.stop()


def test_mid_batch_failure_journals_applied_prefix():
    """An event that raises mid-delta must not discard the journal
    marks of events already applied: the store and the journal would
    silently diverge and the incremental snapshotter would serve a
    stale patch (the per-event marking this code replaced kept them
    consistent)."""
    cluster = Cluster()
    cursor = cluster.journal.register()
    with pytest.raises(TypeError):
        intake_apply.apply_cluster_delta(cluster, {"pods_upsert": [
            {"name": "good", "group": "g"},
            {"name": "bad", "resources": {"bogus_axis": 1.0}},
        ]})
    assert "good" in cluster.pods and "bad" not in cluster.pods
    batch = cursor.consume()
    assert batch.pods_added == ["good"]


def test_admitted_but_unappliable_event_skipped_not_fatal():
    """An event that passes the admission door check but fails object
    construction at coalesce must be skipped and counted — never abort
    the coalesce and destroy later-seq accepted events (clients were
    already acknowledged), and never fail the cycle.  Non-dict
    resources docs are now rejected at admission outright."""
    cluster = Cluster()
    router = IntakeRouter(IntakeConfig(lanes=1, lane_capacity=100))
    out = router.submit_ops([
        ("upsert", "pods", "good-a", {"name": "good-a", "group": "g"}),
        # passes admission (values numeric) but ResourceVec(**v)
        # rejects the unknown axis at apply time
        ("upsert", "pods", "poison",
         {"name": "poison", "resources": {"bogus_axis": 1.0}}),
        ("upsert", "pods", "good-b", {"name": "good-b", "group": "g"}),
    ])
    assert out["shed"] == 0
    summary = router.coalesce(cluster)
    assert summary["events"] == 2
    assert [s for s, _r in summary["apply_errors"]] == [out["total"] - 2]
    assert set(cluster.pods) == {"good-a", "good-b"}
    assert router.health()["apply_errors"] == 1
    # scalar-where-vector docs bounce at the door instead
    out = router.submit_ops([
        ("upsert", "pods", "scalar", {"name": "scalar", "resources": 5})])
    router.drain_inline(timeout=10)
    router.coalesce(cluster)
    assert "scalar" not in cluster.pods
    assert router.health()["rejected"] == 1


def test_coalesce_watermark_defers_post_boundary_events():
    """The coalesce window is cut by a seq watermark taken at entry:
    staged events at-or-after it are put back (in order) for the next
    window, so a submit racing the lane sweep can never have half its
    delta in this cycle and half in the next."""
    from kai_scheduler_tpu.intake.apply import IntakeEvent
    cluster = Cluster()
    router = IntakeRouter(IntakeConfig(lanes=1, lane_capacity=100))
    router.submit_ops([("upsert", "pods", "pre",
                        {"name": "pre", "group": "g"})])
    assert router.drain_inline(timeout=10)
    lane = router._lanes[0]
    # simulate a racing submit: an event stamped AT the watermark
    # (== router._seq) lands in staged — after "pre", preserving the
    # lane's seq-ascending staging order — before the sweep reads it
    lane.stage([IntakeEvent(router._seq, "upsert", "pods", "post",
                            {"name": "post", "group": "g"})], [], 0)
    summary = router.coalesce(cluster)
    assert summary["events"] == 1
    assert set(cluster.pods) == {"pre"}  # "post" deferred, not lost
    # once the seq clock passes it, the next boundary applies it
    router.submit_ops([("upsert", "pods", "later",
                        {"name": "later", "group": "g"})])
    summary = router.coalesce(cluster)
    assert summary["events"] == 2
    assert set(cluster.pods) == {"pre", "post", "later"}


def test_coalesce_predrains_submitted_backlog():
    """A cycle boundary must sweep everything submitted before it even
    if no worker has drained yet — otherwise one delta's events can
    split across cycles by worker timing (pods placed a cycle before
    their gang document exists, a state the sequential path can never
    produce)."""
    cluster = Cluster()
    router = IntakeRouter(IntakeConfig(lanes=4))  # workers NOT started
    router.submit_delta({
        "pod_groups_upsert": [{"name": "pg", "queue": "q"}],
        "pods_upsert": [{"name": f"pg-{i}", "group": "pg"}
                        for i in range(8)]})
    assert router.health()["staged"] == 0  # nothing drained yet
    summary = router.coalesce(cluster)
    assert summary["events"] == 9
    assert "pg" in cluster.pod_groups and len(cluster.pods) == 8


def test_concurrent_drainers_preserve_lane_fifo():
    """A lane's stage order must equal its pop order even when an
    inline helper (the sync degrade path) races the lane's worker —
    ``_Lane.drain_lock`` serializes whole drain rounds.  Without it, a
    later batch can stage before an earlier in-flight one and a
    coalesce landing in the gap applies same-key events out of order
    across windows."""
    router = IntakeRouter(IntakeConfig(lanes=1, lane_capacity=100000,
                                       batch=16))
    lane = router._lanes[0]
    for _round in range(5):
        router.submit_ops([
            ("upsert", "pods", "k", {"name": "k", "priority": i})
            for i in range(800)])
        threads = [threading.Thread(
            target=lambda: [router._drain_lane(lane)
                            for _ in range(80)]) for _ in range(3)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        router.drain_inline(timeout=10)
        seqs = [e.seq for e in lane.take_staged()]
        assert seqs == sorted(seqs)
        assert len(seqs) == 800


def test_fast_pod_construction_matches_generic_parser():
    """The storm-rate create path builds new plain pods directly
    (shared immutable defaults + fresh containers); it must stay
    value-identical to the generic default-doc + parser path on every
    eligible doc, bail (None) on irregular ones, and never alias a
    mutable container between pods."""
    rng = random.Random(7)
    for i in range(300):
        doc = {"name": f"fp{i}", "group": f"g{i % 5}"}
        if rng.random() < 0.6:
            doc["resources"] = {"accel": float(rng.randrange(4)),
                                "cpu": 2.0, "memory": 4.0}
        if rng.random() < 0.3:
            doc["priority"] = rng.randrange(5)
        if rng.random() < 0.2:
            doc["status"] = rng.choice([0, 1, 2])
        if rng.random() < 0.2:
            doc["accel_devices"] = [0, 1]
        if rng.random() < 0.2:
            doc["labels"] = {"tier": "x"}
        fast = intake_apply._fast_new_pod(doc)
        full = intake_apply._default_doc("pods")
        full.update(doc)
        slow = intake_apply._PARSERS["pods"](full)
        assert fast == slow, doc
    # irregular / unknown fields take the generic parser
    assert intake_apply._fast_new_pod(
        {"name": "x", "tolerations": []}) is None
    assert intake_apply._fast_new_pod({"name": "x", "bogus": 1}) is None
    # defaulted containers are per-object, never shared
    a = intake_apply._fast_new_pod({"name": "a", "group": "g"})
    b = intake_apply._fast_new_pod({"name": "b", "group": "g"})
    assert a.accel_devices is not b.accel_devices
    assert a.labels is not b.labels
    assert a.resources is not b.resources


# ---------------------------------------------------------------------------
# server surfaces
# ---------------------------------------------------------------------------


def _get_json(base, path):
    return json.load(urllib.request.urlopen(base + path, timeout=30))


def _post(base, path, doc):
    req = urllib.request.Request(
        base + path, data=json.dumps(doc).encode(),
        headers={"Content-Type": "application/json"})
    return urllib.request.urlopen(req, timeout=60)


def test_intake_endpoint_shed_429_and_debug_doc():
    cfg = SchedulerConfig(intake_lanes=1, intake_lane_capacity=4)
    server = SchedulerServer(Cluster(), Scheduler(cfg))
    # only the HTTP thread runs — intake workers stay off so the lane
    # can only fill and the overflow path is deterministic
    server_thread = threading.Thread(
        target=server._httpd.serve_forever, daemon=True)
    server_thread.start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        doc = {"pods_upsert": [{"name": f"e{i}", "group": "g"}
                               for i in range(3)]}
        with _post(base, "/intake", doc) as resp:
            assert resp.status == 200
            assert json.load(resp) == {"accepted": 3, "shed": 0,
                                       "total": 3}
        with pytest.raises(urllib.error.HTTPError) as err:
            _post(base, "/intake", doc)
        assert err.value.code == 429
        assert json.load(err.value) == {"accepted": 0, "shed": 3,
                                        "total": 3}
        dbg = _get_json(base, "/debug/intake")
        assert dbg["policy"] == "shed" and dbg["lanes"] == 1
        assert dbg["queued"] == 3 and dbg["shed"] == 3
        health = _get_json(base, "/healthz")
        assert health["intake"]["shed"] == 3
        index = _get_json(base, "/debug")
        assert "/debug/intake" in {s["path"] for s in index["surfaces"]}
    finally:
        server._httpd.shutdown()
        server_thread.join(timeout=5)


def test_intake_coalesces_at_cycle_boundary_e2e():
    """POST /intake queues; POST /cycle/stored coalesces the staged
    events into the stored cluster and schedules them in the SAME
    request — the cycle boundary is the commit point."""
    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        with _post(base, "/intake", {
                "pod_groups_upsert": [
                    {"name": "late-gang", "queue": "queue-0-0",
                     "min_member": 1}],
                "pods_upsert": [{
                    "name": "late-pod", "group": "late-gang",
                    "resources": {"accel": 1.0, "cpu": 1.0,
                                  "memory": 1.0}}]}) as resp:
            assert resp.status == 200
        with _post(base, "/cycle/stored", {}) as resp:
            cycle = json.load(resp)
        bound = {b["pod"] for b in cycle["bind_requests"]}
        assert "late-pod" in bound
        snap = _get_json(base, "/snapshot")
        assert "late-pod" in {p["name"] for p in snap["pods"]}
        assert _get_json(base, "/healthz")["intake"]["staged"] == 0
    finally:
        server.stop()


def test_endpoint_hammer_storm_vs_scrapes():
    """Concurrent storm POSTs vs /healthz, /debug/wire and
    /debug/intake scrapes and stored-cycle runs: every response is a
    complete document; scrapes never block behind intake lanes (they
    read only router/lane locks) and never tear."""
    import concurrent.futures

    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"

    def post_storm(i):
        doc = {"pods_upsert": [
            {"name": f"hammer-{i}-{j}", "group": f"hammer-g{i}",
             "resources": {"accel": 1.0, "cpu": 1.0, "memory": 1.0}}
            for j in range(20)]}
        with _post(base, "/intake", doc) as resp:
            return resp.status

    def post_cycle(_i):
        with _post(base, "/cycle/stored", {}) as resp:
            return resp.status

    def get_intake(_i):
        doc = _get_json(base, "/debug/intake")
        assert {"lanes", "queued", "staged", "accepted", "shed",
                "rejected", "policy", "lane_stats",
                "workers_alive"} <= set(doc)
        assert len(doc["lane_stats"]) == doc["lanes"]
        return 200

    def get_health(_i):
        doc = _get_json(base, "/healthz")
        assert "intake" in doc
        return 200

    def get_wire(_i):
        doc = _get_json(base, "/debug/wire")
        assert {"cycles", "window", "residency", "compile"} <= set(doc)
        return 200

    try:
        post_cycle(0)  # compile before the storm
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futures = []
            for i in range(10):
                futures.append(pool.submit(post_storm, i))
                futures.append(pool.submit(get_intake, i))
                futures.append(pool.submit(get_health, i))
                futures.append(pool.submit(get_wire, i))
                if i % 5 == 0:
                    futures.append(pool.submit(post_cycle, i))
            statuses = [f.result() for f in futures]
        assert all(s == 200 for s in statuses)
        # a final boundary lands everything the storm queued
        post_cycle(99)
        snap = _get_json(base, "/snapshot")
        names = {p["name"] for p in snap["pods"]}
        assert {f"hammer-{i}-0" for i in range(10)} <= names
    finally:
        server.stop()


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def test_conf_intake_keys_round_trip():
    from kai_scheduler_tpu import conf
    cfg = conf.load_config({"intake": {"lanes": 8, "laneCapacity": 1024,
                                       "policy": "sync", "batch": 128}})
    assert (cfg.intake_lanes, cfg.intake_lane_capacity,
            cfg.intake_policy, cfg.intake_batch) == (8, 1024, "sync", 128)
    doc = conf.effective_config_doc(cfg)
    assert doc["intake"] == {"lanes": 8, "laneCapacity": 1024,
                             "policy": "sync", "batch": 128}
    with pytest.raises(ValueError):
        IntakeConfig(policy="yolo")
    with pytest.raises(ValueError):
        IntakeConfig(lanes=0)

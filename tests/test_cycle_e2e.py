"""End-to-end cycle tests: Cluster → Scheduler.run_once → Binder.reconcile.

Analogue of the reference's action integration suites
(``actions/integration_tests/``) and the envtest component tests
(``pkg/env-tests``), on the in-memory Cluster hub.
"""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.binder import Binder
from kai_scheduler_tpu.framework import Scheduler, SchedulerConfig
from kai_scheduler_tpu.runtime import Cluster
from kai_scheduler_tpu.state import make_cluster


def build(**kw) -> Cluster:
    nodes, queues, groups, pods, topo = make_cluster(**kw)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_full_cycle_binds_pods():
    cluster = build(num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    sched, binder = Scheduler(), Binder()
    result = sched.run_once(cluster)
    assert len(result.bind_requests) == 8
    bind = binder.reconcile(cluster)
    assert len(bind.bound) == 8
    assert all(p.status == apis.PodStatus.BOUND
               for p in cluster.pods.values())
    assert all(p.node is not None for p in cluster.pods.values())


def test_cycle_is_idempotent_when_everything_bound():
    cluster = build(num_nodes=4, num_gangs=4, tasks_per_gang=2)
    sched, binder = Scheduler(), Binder()
    sched.run_once(cluster)
    binder.reconcile(cluster)
    cluster.tick()
    result2 = sched.run_once(cluster)
    assert result2.bind_requests == []


def test_pending_backlog_drains_over_cycles():
    """Demand 2x capacity: first cycle fills the cluster; once running
    gangs finish, the next cycles place the rest."""
    cluster = build(num_nodes=2, node_accel=4.0, node_cpu=1000.0,
                    node_mem=1000.0, num_gangs=8, tasks_per_gang=2)
    sched, binder = Scheduler(), Binder()
    sched.run_once(cluster)
    bound_first = len(binder.reconcile(cluster).bound)
    assert bound_first == 8  # 8 accel capacity / 1 accel per pod
    # finish the first wave
    for p in cluster.pods.values():
        if p.status == apis.PodStatus.BOUND:
            p.status = apis.PodStatus.SUCCEEDED
    sched.run_once(cluster)
    bound_second = len(binder.reconcile(cluster).bound)
    assert bound_second == 8


def test_binder_backoff_on_missing_node():
    cluster = build(num_nodes=2, num_gangs=1, tasks_per_gang=1)
    sched, binder = Scheduler(), Binder()
    result = sched.run_once(cluster)
    assert len(result.bind_requests) == 1
    # sabotage: node disappears between scheduling and binding
    br = result.bind_requests[0]
    del cluster.nodes[br.selected_node]
    bind = binder.reconcile(cluster)
    assert bind.retrying == [br.pod_name]
    assert cluster.bind_requests[br.pod_name].failures == 1
    assert cluster.pods[br.pod_name].status == apis.PodStatus.PENDING


def test_inflight_bindrequest_not_rescheduled():
    """A pod with a Pending BindRequest must be snapshotted as bound on
    its selected node: no double-allocation, no clobbered retry counter
    (ref cache snapshotBindRequests)."""
    cluster = build(num_nodes=2, num_gangs=1, tasks_per_gang=1)
    sched = Scheduler()
    result = sched.run_once(cluster)
    br = result.bind_requests[0]
    cluster.bind_requests[br.pod_name].failures = 2
    # binder has NOT run yet — next cycle must not re-schedule the pod
    result2 = sched.run_once(cluster)
    assert result2.bind_requests == []
    assert cluster.bind_requests[br.pod_name].failures == 2


def test_gang_atomicity_across_the_stack():
    """A gang that cannot fully fit leaves zero bind requests."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=10))]
    groups = [apis.PodGroup("gang", queue="q", min_member=3)]
    pods = [apis.Pod(f"p{i}", "gang", apis.ResourceVec(1, 1, 1))
            for i in range(3)]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)
    result = Scheduler().run_once(cluster)
    assert result.bind_requests == []


def test_eviction_flow_releases_then_reaps():
    cluster = build(num_nodes=2, num_gangs=2, tasks_per_gang=1,
                    running_fraction=0.5)
    running = [p for p in cluster.pods.values()
               if p.status == apis.PodStatus.RUNNING]
    assert running
    cluster.evict_pod(running[0].name)
    assert cluster.pods[running[0].name].status == apis.PodStatus.RELEASING
    cluster.tick()
    assert running[0].name not in cluster.pods

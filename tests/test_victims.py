"""Reclaim / preempt action tests — mirroring the reference suites
``actions/reclaim/reclaim_test.go`` and ``actions/preempt/preempt_test.go``
(fake-cluster scenario style, SURVEY.md §4 tier 2)."""
import jax.numpy as jnp
import numpy as np
import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import init_result
from kai_scheduler_tpu.ops.victims import VictimConfig, run_victim_action
from kai_scheduler_tpu.state import build_snapshot

Vec = apis.ResourceVec
QR = apis.QueueResource


def two_queue_cluster(*, victim_gpus=8, q0_quota=4.0, q1_quota=4.0,
                      victim_preemptible=True, reclaim_mrt=0.0,
                      victim_runtime=100.0):
    """One 8-GPU node; queue-1's running gang holds `victim_gpus` GPUs;
    queue-0 has a pending gang wanting 4 GPUs."""
    nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
    queues = [
        apis.Queue("q0", accel=QR(quota=q0_quota)),
        apis.Queue("q1", accel=QR(quota=q1_quota),
                   reclaim_min_runtime=reclaim_mrt),
    ]
    running = apis.PodGroup(
        "running-gang", queue="q1", min_member=1,
        preemptibility=(apis.Preemptibility.PREEMPTIBLE if victim_preemptible
                        else apis.Preemptibility.NON_PREEMPTIBLE),
        creation_timestamp=0.0, last_start_timestamp=0.0)
    pending = apis.PodGroup("pending-gang", queue="q0", min_member=2,
                            creation_timestamp=1.0)
    pods = []
    for i in range(int(victim_gpus)):
        pods.append(apis.Pod(
            f"victim-{i}", "running-gang", resources=Vec(1.0, 1.0, 4.0),
            status=apis.PodStatus.RUNNING, node="node-0",
            creation_timestamp=0.0))
    for i in range(2):
        pods.append(apis.Pod(
            f"pending-{i}", "pending-gang", resources=Vec(2.0, 1.0, 4.0),
            creation_timestamp=1.0))
    groups = [running, pending]
    state, index = build_snapshot(
        nodes, queues, groups, pods, now=victim_runtime)
    return state, index


def run_reclaim(state, num_levels=1, **cfg):
    fair_share = drf.set_fair_share(state, num_levels=num_levels)
    res = run_victim_action(
        state, fair_share, init_result(state), num_levels=num_levels,
        mode="reclaim", config=VictimConfig(**cfg))
    return res, fair_share


class TestReclaim:
    def test_reclaims_over_quota_queue(self):
        # q1 uses all 8 GPUs (quota 4); q0 (quota 4) pending 4 GPUs ->
        # reclaim should evict enough victims and place the pending gang.
        state, index = two_queue_cluster()
        res, fs = run_reclaim(state)
        pending_gi = index.gang_names.index("pending-gang")
        assert bool(res.allocated[pending_gi])
        # both tasks placed, pipelined (await victim termination)
        assert int((np.asarray(res.placements[pending_gi]) >= 0).sum()) == 2
        assert bool(res.pipelined[pending_gi, 0])
        n_victims = int(np.asarray(res.victim).sum())
        assert n_victims >= 4  # at least the 4 GPUs worth of pods
        # q1 must keep its deserved quota: can't evict below 4 GPUs
        assert n_victims <= 4

    def test_no_reclaim_when_victim_queue_within_fair_share(self):
        # q1 only uses 4 GPUs = its fair share; nothing to reclaim.
        state, index = two_queue_cluster(victim_gpus=4)
        res, _ = run_reclaim(state)
        pending_gi = index.gang_names.index("pending-gang")
        assert not bool(res.allocated[pending_gi])
        assert int(np.asarray(res.victim).sum()) == 0

    def test_no_reclaim_of_nonpreemptible_victims(self):
        state, index = two_queue_cluster(victim_preemptible=False)
        res, _ = run_reclaim(state)
        assert int(np.asarray(res.victim).sum()) == 0

    def test_reclaimer_over_fair_share_gated(self):
        # q0 quota 0 => fair share gives q0 only surplus; with q1 over its
        # 4-GPU quota... make q0 fair share tiny by quota 0 + weight 0.
        nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
        queues = [
            apis.Queue("q0", accel=QR(quota=0.0, over_quota_weight=0.0)),
            apis.Queue("q1", accel=QR(quota=8.0)),
        ]
        running = apis.PodGroup("rg", queue="q1", min_member=1,
                                last_start_timestamp=0.0)
        pending = apis.PodGroup("pg", queue="q0", min_member=1)
        pods = [apis.Pod(f"v{i}", "rg", resources=Vec(1.0, 1.0, 4.0),
                         status=apis.PodStatus.RUNNING, node="node-0")
                for i in range(8)]
        pods.append(apis.Pod("p0", "pg", resources=Vec(1.0, 1.0, 4.0)))
        state, index = build_snapshot(nodes, queues, [running, pending],
                                      pods, now=100.0)
        res, _ = run_reclaim(state)
        assert not bool(res.allocated[index.gang_names.index("pg")])
        assert int(np.asarray(res.victim).sum()) == 0

    def test_minruntime_protects_quorum_not_surplus(self):
        # victims have run 10s < reclaimMinRuntime 60s -> protected.  The
        # running gang is ELASTIC (minMember 1, 8 pods): protection keeps
        # its quorum but surplus pods remain reclaimable (ref
        # minruntime reclaimFilterFn passing elastic jobs through to the
        # below-minAvailable scenario validator).
        state, index = two_queue_cluster(reclaim_mrt=60.0,
                                         victim_runtime=10.0)
        res, _ = run_reclaim(state)
        n_vic = int(np.asarray(res.victim).sum())
        assert 0 < n_vic <= 7  # at least minMember=1 pod survives
        # once they've run long enough, reclaim proceeds
        state2, index2 = two_queue_cluster(reclaim_mrt=60.0,
                                           victim_runtime=120.0)
        res2, _ = run_reclaim(state2)
        assert bool(res2.allocated[index2.gang_names.index("pending-gang")])

    def test_minruntime_fully_protects_nonelastic_gang(self):
        # minMember == pod count: no surplus, the whole gang is its
        # quorum — a protected gang yields zero victims.
        nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=4.0)),
                  apis.Queue("q1", accel=QR(quota=4.0),
                             reclaim_min_runtime=60.0)]
        running = apis.PodGroup("rg", queue="q1", min_member=8,
                                creation_timestamp=0.0,
                                last_start_timestamp=0.0)
        pending = apis.PodGroup("pg", queue="q0", min_member=2,
                                creation_timestamp=1.0)
        pods = [apis.Pod(f"v{i}", "rg", resources=Vec(1.0, 1.0, 4.0),
                         status=apis.PodStatus.RUNNING, node="node-0")
                for i in range(8)]
        pods += [apis.Pod(f"p{i}", "pg", resources=Vec(2.0, 1.0, 4.0),
                          creation_timestamp=1.0) for i in range(2)]
        state, _ = build_snapshot(nodes, queues, [running, pending], pods,
                                  now=10.0)
        res, _ = run_reclaim(state)
        assert int(np.asarray(res.victim).sum()) == 0

    def test_minruntime_inherited_from_parent_queue(self):
        """A leaf without reclaimMinRuntime inherits its department's —
        ref plugins/minruntime/resolver.go inheritance walk."""
        nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
        queues = [
            apis.Queue("dept-a", accel=QR(quota=4.0)),
            apis.Queue("dept-b", accel=QR(quota=4.0),
                       reclaim_min_runtime=60.0),
            apis.Queue("qa", parent="dept-a", accel=QR(quota=4.0)),
            apis.Queue("qb", parent="dept-b", accel=QR(quota=4.0)),
        ]
        running = apis.PodGroup("rg", queue="qb", min_member=8,
                                creation_timestamp=0.0,
                                last_start_timestamp=0.0)
        pending = apis.PodGroup("pg", queue="qa", min_member=2,
                                creation_timestamp=1.0)
        pods = [apis.Pod(f"v{i}", "rg", resources=Vec(1.0, 1.0, 4.0),
                         status=apis.PodStatus.RUNNING, node="node-0")
                for i in range(8)]
        pods += [apis.Pod(f"p{i}", "pg", resources=Vec(2.0, 1.0, 4.0),
                          creation_timestamp=1.0) for i in range(2)]
        state, _ = build_snapshot(nodes, queues, [running, pending], pods,
                                  now=10.0)
        res, _ = run_reclaim(state, num_levels=2)
        assert int(np.asarray(res.victim).sum()) == 0  # qb inherits 60s


def preempt_cluster(*, preemptor_priority=100, victim_priority=50,
                    victim_preemptible=True, nonpreempt_preemptor=False):
    """Single queue, full node: high-priority pending gang vs low-priority
    running gang in the same queue."""
    nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
    queues = [apis.Queue("q0", accel=QR(quota=8.0))]
    running = apis.PodGroup(
        "low-gang", queue="q0", min_member=1, priority=victim_priority,
        preemptibility=(apis.Preemptibility.PREEMPTIBLE if victim_preemptible
                        else apis.Preemptibility.NON_PREEMPTIBLE),
        last_start_timestamp=0.0)
    pending = apis.PodGroup(
        "high-gang", queue="q0", min_member=2, priority=preemptor_priority,
        preemptibility=(apis.Preemptibility.NON_PREEMPTIBLE
                        if nonpreempt_preemptor
                        else apis.Preemptibility.PREEMPTIBLE),
        creation_timestamp=1.0)
    pods = [apis.Pod(f"victim-{i}", "low-gang", resources=Vec(1.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-0")
            for i in range(8)]
    pods += [apis.Pod(f"high-{i}", "high-gang", resources=Vec(2.0, 1.0, 4.0),
                      creation_timestamp=1.0) for i in range(2)]
    return build_snapshot(nodes, queues, [running, pending], pods, now=100.0)


def run_preempt(state, num_levels=1, **cfg):
    fair_share = drf.set_fair_share(state, num_levels=num_levels)
    return run_victim_action(
        state, fair_share, init_result(state), num_levels=num_levels,
        mode="preempt", config=VictimConfig(**cfg))


class TestPreempt:
    def test_higher_priority_preempts(self):
        state, index = preempt_cluster()
        res = run_preempt(state)
        hi = index.gang_names.index("high-gang")
        assert bool(res.allocated[hi])
        assert int(np.asarray(res.victim).sum()) >= 4

    def test_equal_priority_does_not_preempt(self):
        state, index = preempt_cluster(preemptor_priority=50)
        res = run_preempt(state)
        assert not bool(res.allocated[index.gang_names.index("high-gang")])
        assert int(np.asarray(res.victim).sum()) == 0

    def test_nonpreemptible_victims_protected(self):
        state, index = preempt_cluster(victim_preemptible=False)
        res = run_preempt(state)
        assert int(np.asarray(res.victim).sum()) == 0

    def test_nonpreemptible_preemptor_over_quota_gated(self):
        # queue quota 0: a non-preemptible preemptor would put the queue's
        # non-preemptible allocation over deserved -> gate refuses.
        nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=0.0))]
        running = apis.PodGroup("low", queue="q0", min_member=1, priority=1,
                                last_start_timestamp=0.0)
        pending = apis.PodGroup(
            "high", queue="q0", min_member=1, priority=9,
            preemptibility=apis.Preemptibility.NON_PREEMPTIBLE)
        pods = [apis.Pod(f"v{i}", "low", resources=Vec(1.0, 1.0, 4.0),
                         status=apis.PodStatus.RUNNING, node="node-0")
                for i in range(8)]
        pods.append(apis.Pod("h0", "high", resources=Vec(1.0, 1.0, 4.0)))
        state, index = build_snapshot(nodes, queues, [running, pending],
                                      pods, now=100.0)
        res = run_preempt(state)
        assert not bool(res.allocated[index.gang_names.index("high")])


class TestElasticScaleUp:
    def test_running_pods_count_toward_min_member(self):
        """A gang with min_member=4 and 2 pods already running needs only
        2 more placements (min_needed) — regression for the pipelined-
        remainder deadlock."""
        from kai_scheduler_tpu.ops import drf
        from kai_scheduler_tpu.ops.allocate import allocate

        nodes = [apis.Node("node-0", Vec(4.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=4.0))]
        group = apis.PodGroup("g0", queue="q0", min_member=4,
                              last_start_timestamp=0.0)
        pods = [apis.Pod(f"r{i}", "g0", resources=Vec(1.0, 1.0, 4.0),
                         status=apis.PodStatus.RUNNING, node="node-0")
                for i in range(2)]
        pods += [apis.Pod(f"p{i}", "g0", resources=Vec(1.0, 1.0, 4.0))
                 for i in range(2)]
        state, index = build_snapshot(nodes, queues, [group], pods)
        gi = index.gang_names.index("g0")
        assert int(state.gangs.min_needed[gi]) == 2
        fair_share = drf.set_fair_share(state, num_levels=1)
        res = allocate(state, fair_share, num_levels=1)
        assert bool(res.allocated[gi])
        assert int((np.asarray(res.placements[gi]) >= 0).sum()) == 2


class TestCycleWithVictims:
    def test_full_cycle_reclaim_then_rebind(self):
        """allocate fails -> reclaim evicts -> next cycle binds preemptor."""
        from kai_scheduler_tpu.binder import Binder
        from kai_scheduler_tpu.framework import Scheduler, SchedulerConfig
        from kai_scheduler_tpu.runtime.cluster import Cluster

        nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=4.0)),
                  apis.Queue("q1", accel=QR(quota=4.0))]
        running = apis.PodGroup("rg", queue="q1", min_member=1,
                                last_start_timestamp=0.0)
        pending = apis.PodGroup("pg", queue="q0", min_member=2,
                                creation_timestamp=1.0)
        pods = [apis.Pod(f"v{i}", "rg", resources=Vec(1.0, 1.0, 4.0),
                         status=apis.PodStatus.RUNNING, node="node-0",
                         creation_timestamp=0.0)
                for i in range(8)]
        pods += [apis.Pod(f"p{i}", "pg", resources=Vec(2.0, 1.0, 4.0),
                          creation_timestamp=1.0) for i in range(2)]
        cluster = Cluster.from_objects(nodes, queues, [running, pending], pods)
        cluster.now = 100.0

        from kai_scheduler_tpu.framework.session import SessionConfig
        sched = Scheduler(SchedulerConfig(
            actions=("allocate", "reclaim", "preempt"),
            session=SessionConfig(num_levels=1)))
        binder = Binder()

        r1 = sched.run_once(cluster)
        assert len(r1.evictions) == 4          # 4 GPUs reclaimed from q1
        assert len(r1.bind_requests) == 0      # preemptor pipelined
        binder.reconcile(cluster)
        cluster.tick()                          # releasing pods vanish

        r2 = sched.run_once(cluster)
        assert {br.pod_name for br in r2.bind_requests} == {"p0", "p1"}
        binder.reconcile(cluster)
        assert cluster.pods["p0"].status == apis.PodStatus.BOUND


class TestEvictionUnitAccounting:
    """ADVICE r1 (medium): surplus must be sized from the *effective*
    active count — running pods minus victims already taken this cycle —
    so successive actions cannot shrink a gang below minMember without
    evicting the whole remainder as one unit (ref Statement.Evict
    updating the counts GetTasksToEvict reads)."""

    def _state(self):
        nodes = [apis.Node("node-0", Vec(16.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=16.0))]
        gang = apis.PodGroup("elastic", queue="q0", min_member=8,
                             last_start_timestamp=0.0)
        pods = [apis.Pod(f"p{i}", "elastic", resources=Vec(1.0, 1.0, 1.0),
                         status=apis.PodStatus.RUNNING, node="node-0",
                         creation_timestamp=float(i))
                for i in range(10)]
        # a pending gang so G > 1 (not used by the unit ranking directly)
        pending = apis.PodGroup("pend", queue="q0", min_member=1,
                                creation_timestamp=20.0)
        pods.append(apis.Pod("pend-0", "pend", resources=Vec(1.0, 1.0, 1.0),
                             creation_timestamp=20.0))
        return build_snapshot(nodes, queues, [gang, pending], pods,
                              now=100.0)

    def test_surplus_shrinks_with_accumulated_victims(self):
        from kai_scheduler_tpu.ops.victims import _rank_eviction_units

        state, index = self._state()
        M = state.running.m
        fair_share = drf.set_fair_share(state, num_levels=1)
        gang_row = np.asarray(state.running.gang)
        gi = index.gang_names.index("elastic")
        cand_np = (np.asarray(state.running.valid)
                   & (gang_row == gi))

        # fresh cycle: 10 running, minMember 8 -> 2 single-pod units + 1
        # whole-gang unit
        no_victims = jnp.zeros((M,), bool)
        _, num_units = _rank_eviction_units(
            state, jnp.asarray(cand_np), state.queues.allocated,
            fair_share, no_victims)
        assert int(num_units) == 3

        # 2 pods already victimised this cycle: gang sits AT minMember —
        # the only remaining unit is the whole remaining gang
        prior = np.zeros((M,), bool)
        prior[np.nonzero(cand_np)[0][:2]] = True
        cand2 = jnp.asarray(cand_np & ~prior)
        _, num_units2 = _rank_eviction_units(
            state, cand2, state.queues.allocated, fair_share,
            jnp.asarray(prior))
        assert int(num_units2) == 1

"""Snapshot/replay tests — ref ``plugins/snapshot`` + ``cmd/snapshot-tool``:
round-trip fidelity and deterministic replay."""
import subprocess
import sys

import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.snapshot import (dump_cluster, load,
                                                load_cluster, save)
from kai_scheduler_tpu.state import make_cluster


def _demo_cluster() -> Cluster:
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    # exercise the richer fields through the round trip
    nodes[0].taints.append(apis.Taint("dedicated", "infra"))
    pods[0].tolerations.append(apis.Toleration("dedicated", "Exists"))
    pods[1].node_affinity.append(apis.AffinityExpr("zone", "In", ("z1",)))
    pods[2].pod_affinity.append(
        apis.PodAffinityTerm(match_labels=(("app", "x"),), anti=True))
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_round_trip_preserves_objects():
    cluster = _demo_cluster()
    doc = dump_cluster(cluster)
    back = load_cluster(doc)
    assert dump_cluster(back) == doc


def test_replay_is_deterministic(tmp_path):
    cluster = _demo_cluster()
    path = str(tmp_path / "snap.json.gz")
    save(cluster, path)

    def commits():
        c = load(path)
        res = Scheduler().run_once(c)
        return ([(b.pod_name, b.selected_node) for b in res.bind_requests],
                [(e.pod_name, e.move_to) for e in res.evictions])

    assert commits() == commits()


def test_snapshot_tool_cli(tmp_path):
    path = str(tmp_path / "snap.json")
    env_cmd = [sys.executable, "snapshot_tool.py"]
    out1 = subprocess.run(env_cmd + ["dump", path], capture_output=True,
                          text=True, timeout=300)
    assert out1.returncode == 0, out1.stderr
    r1 = subprocess.run(env_cmd + ["replay", path], capture_output=True,
                        text=True, timeout=600)
    assert r1.returncode == 0, r1.stderr
    r2 = subprocess.run(env_cmd + ["replay", path], capture_output=True,
                        text=True, timeout=600)
    assert r1.stdout == r2.stdout
    assert '"kind": "BindRequest"' in r1.stdout

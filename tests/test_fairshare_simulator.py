"""Parity tests for the fairshare simulator (ref
``cmd/fairshare-simulator`` README example + time-based k term)."""
from fairshare_simulator import simulate


def _req(queues, total_gpu=100, k=None):
    req = {"totalResource": {"GPU": total_gpu, "CPU": 0, "Memory": 0},
           "queues": queues}
    if k is not None:
        req["kValue"] = k
    return req


def _q(uid, deserved=10, request=100, weight=1.0, priority=0, usage=0.0,
       max_allowed=-1):
    return {"uid": uid, "priority": priority,
            "resourceShare": {"gpu": {
                "deserved": deserved, "request": request,
                "overQuotaWeight": weight, "usage": usage,
                "maxAllowed": max_allowed}}}


def test_readme_example_split():
    out = simulate(_req([_q("q1", weight=3), _q("q2", weight=1)]))
    assert out["q1"]["gpu"] == 70.0
    assert out["q2"]["gpu"] == 30.0


def test_deserved_capped_by_request():
    out = simulate(_req([_q("q1", deserved=50, request=20),
                         _q("q2", deserved=10, request=100)]))
    assert out["q1"]["gpu"] == 20.0
    assert out["q2"]["gpu"] == 80.0


def test_max_allowed_caps_fair_share():
    out = simulate(_req([_q("q1", max_allowed=25), _q("q2")]))
    assert out["q1"]["gpu"] == 25.0
    assert out["q2"]["gpu"] == 75.0


def test_priority_tier_first():
    out = simulate(_req([_q("hi", priority=10, request=80),
                         _q("lo", priority=0, request=100)]))
    # hi's tier drains first: deserved 10 + surplus up to its request
    assert out["hi"]["gpu"] == 80.0
    assert out["lo"]["gpu"] == 20.0


def test_k_value_usage_shrinks_share():
    base = simulate(_req([_q("a", deserved=0, usage=0.5),
                          _q("b", deserved=0, usage=0.0)], k=0.0))
    skew = simulate(_req([_q("a", deserved=0, usage=0.5),
                          _q("b", deserved=0, usage=0.0)], k=2.0))
    assert abs(base["a"]["gpu"] - base["b"]["gpu"]) <= 1.0
    assert skew["a"]["gpu"] < skew["b"]["gpu"] - 1.0

"""Sidecar/PluginServer tests — ref ``plugins/reflectjoborder``,
``plugins/snapshot`` HTTP endpoints and the snapshot-in/placements-out
wire boundary (SURVEY.md §7d)."""
import json
import urllib.request

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.server import SchedulerServer, run_cycle_doc
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.snapshot import dump_cluster
from kai_scheduler_tpu.state import make_cluster


def _cluster():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_run_cycle_doc_round_trip():
    doc = dump_cluster(_cluster())
    out = run_cycle_doc(doc)
    assert len(out["bind_requests"]) == 8
    assert out["evictions"] == []
    # deterministic across calls on the same document
    assert run_cycle_doc(doc)["bind_requests"] == out["bind_requests"]


def test_http_endpoints():
    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        order = json.load(urllib.request.urlopen(f"{base}/job-order"))
        assert len(order) == 4 and {"pod_group", "queue"} <= set(order[0])

        snap = json.load(urllib.request.urlopen(f"{base}/snapshot"))
        assert len(snap["nodes"]) == 4

        req = urllib.request.Request(
            f"{base}/cycle", data=json.dumps(snap).encode(),
            headers={"Content-Type": "application/json"})
        cycle = json.load(urllib.request.urlopen(req))
        assert len(cycle["bind_requests"]) == 8

        metrics_text = urllib.request.urlopen(
            f"{base}/metrics").read().decode()
        assert "kai_e2e_scheduling_latency_seconds" in metrics_text
    finally:
        server.stop()

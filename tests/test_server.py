"""Sidecar/PluginServer tests — ref ``plugins/reflectjoborder``,
``plugins/snapshot`` HTTP endpoints and the snapshot-in/placements-out
wire boundary (SURVEY.md §7d)."""
import json
import urllib.request

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.server import SchedulerServer, run_cycle_doc
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.snapshot import dump_cluster
from kai_scheduler_tpu.state import make_cluster


def _cluster():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_run_cycle_doc_round_trip():
    doc = dump_cluster(_cluster())
    out = run_cycle_doc(doc)
    assert len(out["bind_requests"]) == 8
    assert out["evictions"] == []
    # deterministic across calls on the same document
    assert run_cycle_doc(doc)["bind_requests"] == out["bind_requests"]


def test_http_endpoints():
    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        order = json.load(urllib.request.urlopen(f"{base}/job-order"))
        assert len(order) == 4 and {"pod_group", "queue"} <= set(order[0])

        snap = json.load(urllib.request.urlopen(f"{base}/snapshot"))
        assert len(snap["nodes"]) == 4

        req = urllib.request.Request(
            f"{base}/cycle", data=json.dumps(snap).encode(),
            headers={"Content-Type": "application/json"})
        cycle = json.load(urllib.request.urlopen(req))
        assert len(cycle["bind_requests"]) == 8

        metrics_text = urllib.request.urlopen(
            f"{base}/metrics").read().decode()
        assert "kai_e2e_scheduling_latency_seconds" in metrics_text
    finally:
        server.stop()


class TestContinuousProfiler:
    """The Pyroscope analogue (ref cmd/scheduler/profiling/pyroscope.go
    + the pyroscope-address / profiler-rate flags, options.go:110-113):
    a wall-stack sampler with windowed retain + push."""

    def test_sampler_folds_and_rolls_windows(self):
        import threading
        import time as _t

        from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

        stop = threading.Event()

        def busy_beacon():
            while not stop.is_set():
                _t.sleep(0.001)

        t = threading.Thread(target=busy_beacon, daemon=True)
        t.start()
        prof = ContinuousProfiler(sample_hz=200, window_s=0.2).start()
        _t.sleep(0.7)
        prof.stop()
        stop.set()
        t.join(timeout=1)
        assert len(prof.windows) >= 2  # rolled at least twice
        body = prof.render()
        assert "busy_beacon" in body  # the beacon thread was sampled
        # folded format: "frame;frame;... count"
        line = next(ln for ln in body.splitlines()
                    if "busy_beacon" in ln)
        assert line.rsplit(" ", 1)[1].isdigit()

    def test_stop_start_cycle_resumes_sampling(self):
        """start() must clear the stop event a previous stop() left set,
        or the re-started sampler thread exits immediately and
        profiling silently stops (ADVICE r5)."""
        import threading
        import time as _t

        from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

        stop = threading.Event()

        def busy_beacon():
            while not stop.is_set():
                _t.sleep(0.001)

        t = threading.Thread(target=busy_beacon, daemon=True)
        t.start()
        prof = ContinuousProfiler(sample_hz=200, window_s=10.0).start()
        _t.sleep(0.2)
        prof.stop()
        assert prof._thread is None and prof._stop.is_set()
        prof.start()   # restart: must clear the event and sample again
        _t.sleep(0.3)
        assert prof._thread is not None and prof._thread.is_alive()
        prof.stop()
        stop.set()
        t.join(timeout=1)
        # the post-restart window saw the beacon thread
        assert "busy_beacon" in prof.render_folded(prof.windows[-1][2])

    def test_push_hits_ingest_endpoint(self):
        import http.server
        import threading
        import time as _t

        from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

        received = []

        class Sink(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append((self.path, self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Sink)
        port = httpd.server_address[1]
        st = threading.Thread(target=httpd.serve_forever, daemon=True)
        st.start()
        try:
            prof = ContinuousProfiler(
                sample_hz=200, window_s=0.15,
                server_address=f"http://127.0.0.1:{port}",
                app_name="kai-test").start()
            _t.sleep(0.5)
            prof.stop()
            assert prof.pushed >= 1, (prof.pushed, prof.push_errors)
            path, body = received[0]
            assert "name=kai-test" in path and "format=folded" in path
            assert b";" in body or b" " in body
        finally:
            httpd.shutdown()

    def test_server_endpoint_serves_retained_windows(self):
        import dataclasses
        import json
        import time as _t
        import urllib.request

        from kai_scheduler_tpu.apis import types as apis
        from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                           SchedulerConfig)
        from kai_scheduler_tpu.framework.server import SchedulerServer
        from kai_scheduler_tpu.runtime.cluster import Cluster

        cluster = Cluster.from_objects(
            [apis.Node("n0", apis.ResourceVec(1, 4, 16))],
            [apis.Queue("q", accel=apis.QueueResource(quota=1))], [], [])
        sched = Scheduler(SchedulerConfig(profiler_sample_hz=100.0))
        server = SchedulerServer(cluster, sched).start()
        try:
            _t.sleep(0.3)
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/pprof/continuous",
                timeout=5).read().decode()
            assert "# window" in body
            # print-config surfaces the flags
            from kai_scheduler_tpu import conf
            doc = json.loads(conf.dumps_effective(sched.config))
            assert doc["profilerSampleHz"] == 100.0
        finally:
            server.stop()


# ---------------------------------------------------------------------------
# Concurrency (PR 4): serialized handler state access, the /healthz
# per-cycle stats snapshot, and the profiler stop/start lifecycle
# ---------------------------------------------------------------------------


def test_healthz_serves_swapped_cycle_stats():
    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        doc = json.load(urllib.request.urlopen(f"{base}/healthz"))
        assert doc["ok"] is True and doc["last_cycle"] is None
        req = urllib.request.Request(
            f"{base}/cycle/stored", data=b"{}",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req)
        doc = json.load(urllib.request.urlopen(f"{base}/healthz"))
        stats = doc["last_cycle"]
        assert stats["cycles"] == 1
        assert stats["bind_requests"] == 8
        assert stats["total_seconds"] >= 0.0
    finally:
        server.stop()


def test_concurrent_deltas_and_reads_stay_consistent():
    """ThreadingHTTPServer runs handlers on per-request threads; deltas
    mutating the stored cluster must serialize against snapshot/metrics
    reads instead of tearing the document (pre-PR-4 a delta could
    resize dicts mid-GET)."""
    import concurrent.futures

    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"

    def post_delta(i):
        body = json.dumps({"pods_upsert": [{
            "name": f"stress-{i}", "group": "gang-0"}]}).encode()
        req = urllib.request.Request(
            f"{base}/cluster/delta", data=body,
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=10).status

    def get_snapshot(_i):
        snap = json.load(urllib.request.urlopen(
            f"{base}/snapshot", timeout=10))
        # a torn document would lose invariants like this one
        assert {"nodes", "pods", "pod_groups"} <= set(snap)
        return 200

    def get_metrics(_i):
        urllib.request.urlopen(f"{base}/metrics", timeout=10).read()
        return 200

    try:
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futures = []
            for i in range(12):
                futures.append(pool.submit(post_delta, i))
                futures.append(pool.submit(get_snapshot, i))
                futures.append(pool.submit(get_metrics, i))
            statuses = [f.result() for f in futures]
        assert all(s == 200 for s in statuses)
        # every delta landed exactly once
        snap = json.load(urllib.request.urlopen(f"{base}/snapshot"))
        names = {p["name"] for p in snap["pods"]}
        assert {f"stress-{i}" for i in range(12)} <= names
    finally:
        server.stop()


def test_profiler_second_start_after_failed_join_raises():
    """stop() joins with a timeout; if the sampler refuses to die, a
    second start() must raise instead of leaking a second daemon
    sampler writing into the same windows (PR-4 satellite)."""
    import threading
    import time as _t

    import pytest as _pytest

    from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

    prof = ContinuousProfiler(sample_hz=50, window_s=10.0)
    release = threading.Event()

    class _Stubborn(threading.Thread):
        """Stands in for a wedged sampler: ignores the stop event until
        released."""

        def run(self):
            release.wait(10.0)

    stub = _Stubborn(daemon=True)
    stub.start()
    prof._thread = stub
    prof.stop(timeout=0.05)  # join times out — sampler still alive
    assert prof._thread is stub  # the straggler is NOT forgotten
    with _pytest.raises(RuntimeError, match="has not stopped"):
        prof.start()
    release.set()
    stub.join(timeout=5)
    # once the straggler exits, start() recovers cleanly
    prof.start()
    _t.sleep(0.05)
    assert prof._thread is not None and prof._thread.is_alive()
    prof.stop()
    assert prof._thread is None

"""Sidecar/PluginServer tests — ref ``plugins/reflectjoborder``,
``plugins/snapshot`` HTTP endpoints and the snapshot-in/placements-out
wire boundary (SURVEY.md §7d)."""
import json
import urllib.request

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.server import SchedulerServer, run_cycle_doc
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.snapshot import dump_cluster
from kai_scheduler_tpu.state import make_cluster


def _cluster():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_run_cycle_doc_round_trip():
    doc = dump_cluster(_cluster())
    out = run_cycle_doc(doc)
    assert len(out["bind_requests"]) == 8
    assert out["evictions"] == []
    # deterministic across calls on the same document
    assert run_cycle_doc(doc)["bind_requests"] == out["bind_requests"]


def test_http_endpoints():
    server = SchedulerServer(_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        order = json.load(urllib.request.urlopen(f"{base}/job-order"))
        assert len(order) == 4 and {"pod_group", "queue"} <= set(order[0])

        snap = json.load(urllib.request.urlopen(f"{base}/snapshot"))
        assert len(snap["nodes"]) == 4

        req = urllib.request.Request(
            f"{base}/cycle", data=json.dumps(snap).encode(),
            headers={"Content-Type": "application/json"})
        cycle = json.load(urllib.request.urlopen(req))
        assert len(cycle["bind_requests"]) == 8

        metrics_text = urllib.request.urlopen(
            f"{base}/metrics").read().decode()
        assert "kai_e2e_scheduling_latency_seconds" in metrics_text
    finally:
        server.stop()


class TestContinuousProfiler:
    """The Pyroscope analogue (ref cmd/scheduler/profiling/pyroscope.go
    + the pyroscope-address / profiler-rate flags, options.go:110-113):
    a wall-stack sampler with windowed retain + push."""

    def test_sampler_folds_and_rolls_windows(self):
        import threading
        import time as _t

        from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

        stop = threading.Event()

        def busy_beacon():
            while not stop.is_set():
                _t.sleep(0.001)

        t = threading.Thread(target=busy_beacon, daemon=True)
        t.start()
        prof = ContinuousProfiler(sample_hz=200, window_s=0.2).start()
        _t.sleep(0.7)
        prof.stop()
        stop.set()
        t.join(timeout=1)
        assert len(prof.windows) >= 2  # rolled at least twice
        body = prof.render()
        assert "busy_beacon" in body  # the beacon thread was sampled
        # folded format: "frame;frame;... count"
        line = next(ln for ln in body.splitlines()
                    if "busy_beacon" in ln)
        assert line.rsplit(" ", 1)[1].isdigit()

    def test_stop_start_cycle_resumes_sampling(self):
        """start() must clear the stop event a previous stop() left set,
        or the re-started sampler thread exits immediately and
        profiling silently stops (ADVICE r5)."""
        import threading
        import time as _t

        from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

        stop = threading.Event()

        def busy_beacon():
            while not stop.is_set():
                _t.sleep(0.001)

        t = threading.Thread(target=busy_beacon, daemon=True)
        t.start()
        prof = ContinuousProfiler(sample_hz=200, window_s=10.0).start()
        _t.sleep(0.2)
        prof.stop()
        assert prof._thread is None and prof._stop.is_set()
        prof.start()   # restart: must clear the event and sample again
        _t.sleep(0.3)
        assert prof._thread is not None and prof._thread.is_alive()
        prof.stop()
        stop.set()
        t.join(timeout=1)
        # the post-restart window saw the beacon thread
        assert "busy_beacon" in prof.render_folded(prof.windows[-1][2])

    def test_push_hits_ingest_endpoint(self):
        import http.server
        import threading
        import time as _t

        from kai_scheduler_tpu.runtime.profiling import ContinuousProfiler

        received = []

        class Sink(http.server.BaseHTTPRequestHandler):
            def do_POST(self):
                n = int(self.headers.get("Content-Length", 0))
                received.append((self.path, self.rfile.read(n)))
                self.send_response(200)
                self.end_headers()

            def log_message(self, *a):
                pass

        httpd = http.server.HTTPServer(("127.0.0.1", 0), Sink)
        port = httpd.server_address[1]
        st = threading.Thread(target=httpd.serve_forever, daemon=True)
        st.start()
        try:
            prof = ContinuousProfiler(
                sample_hz=200, window_s=0.15,
                server_address=f"http://127.0.0.1:{port}",
                app_name="kai-test").start()
            _t.sleep(0.5)
            prof.stop()
            assert prof.pushed >= 1, (prof.pushed, prof.push_errors)
            path, body = received[0]
            assert "name=kai-test" in path and "format=folded" in path
            assert b";" in body or b" " in body
        finally:
            httpd.shutdown()

    def test_server_endpoint_serves_retained_windows(self):
        import dataclasses
        import json
        import time as _t
        import urllib.request

        from kai_scheduler_tpu.apis import types as apis
        from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                           SchedulerConfig)
        from kai_scheduler_tpu.framework.server import SchedulerServer
        from kai_scheduler_tpu.runtime.cluster import Cluster

        cluster = Cluster.from_objects(
            [apis.Node("n0", apis.ResourceVec(1, 4, 16))],
            [apis.Queue("q", accel=apis.QueueResource(quota=1))], [], [])
        sched = Scheduler(SchedulerConfig(profiler_sample_hz=100.0))
        server = SchedulerServer(cluster, sched).start()
        try:
            _t.sleep(0.3)
            body = urllib.request.urlopen(
                f"http://127.0.0.1:{server.port}/debug/pprof/continuous",
                timeout=5).read().decode()
            assert "# window" in body
            # print-config surfaces the flags
            from kai_scheduler_tpu import conf
            doc = json.loads(conf.dumps_effective(sched.config))
            assert doc["profilerSampleHz"] == 100.0
        finally:
            server.stop()

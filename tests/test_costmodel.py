"""kai-cost tests — liveness/FLOP units, KAI2xx fixtures, production
audit, coverage meta-tests, cross-validation, scaling, CLI.

Mirrors the three-layer guarantee structure of ``test_analysis.py``:

1. **Unit pins** — the liveness scan, the per-primitive FLOP table,
   and the worst-case-resident sub-jaxpr rule against hand-computed
   jaxprs (the model itself is under test, not just its outputs).
2. **Rule fixtures** — KAI201/KAI202 carry must-trigger and
   must-not-trigger fixtures like every AST rule; both directions run.
3. **Package invariants** — every CompileWatcher-tracked production
   entry has a cost report and a checked-in budget (the watcher entry
   list is the coverage oracle, so a new jit entry cannot dodge the
   auditor), the production package audits clean with zero baselined
   findings, the fused resident entry's donation verifies leaf-exact,
   and the model's memory-traffic ranking agrees with measured
   dispatch ordering (model vs reality, tolerance-gated).
"""
import importlib.util
import json
import os
import shutil
import time

import jax
import jax.numpy as jnp
import pytest

from kai_scheduler_tpu.analysis import costmodel as cm
from kai_scheduler_tpu.analysis import trace_probe as tp
from kai_scheduler_tpu.analysis.callgraph import PackageGraph

pytestmark = pytest.mark.core

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def cost_reports():
    """One full audit (shared walk + donation check) for the module —
    the donating compile rides the suite's persistent XLA cache."""
    base = cm.load_cost_baseline()
    reports = cm.run_cost(baseline=base.get("entries", {}))
    return base, {r.name: r for r in reports}


# ---------------------------------------------------------------------------
# 1. model unit pins (hand-computed jaxprs)

def test_liveness_chain_peak():
    """Three sequential elementwise steps over f32[256]: inputs are
    caller-held (1024B) and at every eqn exactly two internal values
    overlap (operand + result, 2048B) — peak 3072B, not the 4096B a
    no-liveness sum-of-intermediates would charge."""
    def chain(x):
        a = x * jnp.float32(2.0)
        b = a + jnp.float32(1.0)
        return b * b
    closed = jax.make_jaxpr(chain)(jnp.zeros((256,), jnp.float32))
    r = cm._report_from_closed("chain", closed,
                               config=cm.DEFAULT_CONFIG,
                               base_entry=None)
    assert r.peak_live_bytes == 3072
    assert r.flops == 3 * 256
    assert r.unknown_prims == {}


def test_flops_dot_general_from_dimension_numbers():
    """(8,16) @ (16,4) = 2·M·N·K = 1024 FLOPs."""
    def dot(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))
    closed = jax.make_jaxpr(dot)(jnp.zeros((8, 16), jnp.float32),
                                 jnp.zeros((16, 4), jnp.float32))
    r = cm._report_from_closed("dot", closed,
                               config=cm.DEFAULT_CONFIG,
                               base_entry=None)
    assert r.flops == 2 * 8 * 4 * 16


def test_cond_branches_are_worst_case_resident():
    """A cond whose big branch materializes 2×64KB must charge the big
    branch's internal peak on top of the inputs — and the small branch
    must NOT dilute it (worst case, not average)."""
    def condfn(x, p):
        return jax.lax.cond(
            p,
            lambda v: jnp.sum(jnp.broadcast_to(v, (64, 256))
                              * jnp.float32(1.5)),
            jnp.sum, x)
    closed = jax.make_jaxpr(condfn)(jnp.zeros((256,), jnp.float32),
                                    True)
    r = cm._report_from_closed("cond", closed,
                               config=cm.DEFAULT_CONFIG,
                               base_entry=None)
    assert r.peak_live_bytes > 2 * 64 * 256 * 4   # both 64KB temps live


def test_scan_flops_multiply_by_trip_count():
    def scanfn(x):
        def body(c, _):
            return c * jnp.float32(2.0), None
        out, _ = jax.lax.scan(body, x, None, length=10)
        return out
    closed = jax.make_jaxpr(scanfn)(jnp.zeros((256,), jnp.float32))
    r = cm._report_from_closed("scan", closed,
                               config=cm.DEFAULT_CONFIG,
                               base_entry=None)
    assert r.flops == 10 * 256
    assert r.unbounded_whiles == 0


def test_unknown_primitives_are_reported_not_silently_zeroed():
    """A primitive outside the cost table must land in unknown_prims —
    the table's coverage rots loudly."""
    def rng(x):
        key = jax.random.PRNGKey(0)
        return x + jax.random.uniform(key, (8,))
    closed = jax.make_jaxpr(rng)(jnp.zeros((8,), jnp.float32))
    r = cm._report_from_closed("rng", closed,
                               config=cm.DEFAULT_CONFIG,
                               base_entry=None)
    assert r.unknown_prims, "random bits should be outside the table"


# ---------------------------------------------------------------------------
# 2. KAI2xx fixtures — both directions, like every AST rule

@pytest.mark.parametrize("code", sorted(cm.COST_RULES))
def test_cost_rule_fixture_triggers(code):
    findings = cm.audit_fixture(code, "bad")
    assert any(f.code == code for f in findings), (
        f"{code} must-trigger fixture produced no {code} finding: "
        f"{findings}")


@pytest.mark.parametrize("code", sorted(cm.COST_RULES))
def test_cost_rule_fixture_negative(code):
    findings = cm.audit_fixture(code, "good")
    assert not any(f.code == code for f in findings), (
        f"{code} must-NOT-trigger fixture still fires: "
        f"{[f.render() for f in findings]}")


def test_cost_rules_listed_in_catalog():
    from kai_scheduler_tpu.analysis.engine import rule_catalog
    cat = rule_catalog()
    for code in cm.COST_RULES:
        assert code in cat


def test_blowup_allowance_respects_baselined_ratio():
    """An entry with a checked-in max_blowup gets ratio×(1+tol)
    headroom — the same measured ratio passes with its baseline and
    fails as a fresh entry."""
    def blow(x):
        return jnp.sum(jnp.broadcast_to(x, (64, 8)) * jnp.float32(2.0))
    closed = jax.make_jaxpr(blow)(jnp.zeros((8,), jnp.float32))
    fresh = cm._report_from_closed(
        "blow", closed, config=cm.CostConfig(blowup_factor=16.0),
        base_entry=None)
    assert [f.code for f in fresh.findings] == ["KAI201"]
    assert fresh.max_blowup == 64.0
    based = cm._report_from_closed(
        "blow", closed, config=cm.CostConfig(blowup_factor=16.0),
        base_entry={"max_blowup": 64.0})
    assert based.findings == []


def test_cost_findings_ride_engine_baseline_rows():
    """KAI2xx findings flow through the engine's count-based baseline
    machinery (cost_baseline.json 'baselined' rows)."""
    findings = cm.audit_fixture("KAI201", "bad")
    eaten = cm.cost_findings(
        [cm.CostReport(name="f", peak_live_bytes=0, input_bytes=0,
                       largest_input_bytes=0, flops=0, traffic_bytes=0,
                       max_blowup=0.0, top_intermediates=[],
                       unknown_prims={}, unbounded_whiles=0,
                       donation=None, findings=findings)],
        {"baselined": [{"file": findings[0].file, "code": "KAI201",
                        "count": 1}]})
    assert eaten == []
    kept = cm.cost_findings([cm.CostReport(
        name="f", peak_live_bytes=0, input_bytes=0,
        largest_input_bytes=0, flops=0, traffic_bytes=0,
        max_blowup=0.0, top_intermediates=[], unknown_prims={},
        unbounded_whiles=0, donation=None, findings=findings)], {})
    assert [f.code for f in kept] == ["KAI201"]


# ---------------------------------------------------------------------------
# 3. the package itself

def test_production_package_audits_clean(cost_reports):
    """The acceptance bar: every production entry within its budgets,
    zero KAI2xx findings beyond the (empty) baselined rows."""
    base, reports = cost_reports
    problems = cm.check_against_cost_baseline(
        list(reports.values()), base)
    assert not problems, "\n".join(problems)
    findings = cm.cost_findings(list(reports.values()), base)
    assert findings == [], "\n".join(f.render() for f in findings)
    for row in base.get("baselined", []):
        # the documented escape hatch: a parked KAI2xx finding is
        # allowed ONLY with an inline justification (the KAI032
        # precedent) — an unjustified row fails tier-1
        assert row.get("justification", "").strip(), (
            f"unjustified baselined cost finding: {row}")


def test_resident_donation_verifies_leaf_exact(cost_reports):
    """The KAI202 production check: the fused resident entry's
    donating build must alias EVERY donated state leaf to an output in
    the compiled executable — the static form of the PR-11 guard.
    ``verified`` must be True (an introspection regression fails
    loudly, never passes vacuously)."""
    _base, reports = cost_reports
    doc = reports["resident_cycle"].donation
    assert doc is not None and doc["verified"] is True
    assert doc["donated_leaves"] > 0
    assert doc["compiled_aliased"] == doc["donated_leaves"], doc
    assert doc["lowered_aliased"] == doc["donated_leaves"], doc


def test_unverifiable_donation_is_always_a_problem():
    """A donating entry whose executable exposed no aliasing
    introspection fails the baseline check AND blocks
    ``--update-baseline`` (the CLI's update branch calls the same
    helper) — the KAI202 guard can never pass or be absorbed
    vacuously."""
    rep = cm.CostReport(
        name="r", peak_live_bytes=1, input_bytes=1,
        largest_input_bytes=1, flops=1, traffic_bytes=1,
        max_blowup=1.0, top_intermediates=[], unknown_prims={},
        unbounded_whiles=0,
        donation={"entry": "r", "donate_argnums": [0],
                  "donated_leaves": 3, "lowered_aliased": 3,
                  "compiled_aliased": None, "verified": False},
        findings=[])
    probs = cm.unverifiable_donations([rep])
    assert len(probs) == 1 and "UNVERIFIABLE" in probs[0]
    checked = cm.check_against_cost_baseline(
        [rep], {"entries": {"r": {"peak_live_bytes": 1, "flops": 1,
                                  "traffic_bytes": 1}}},
        full_coverage=False)
    assert checked == probs


def test_peak_mb_for_state_is_a_pure_retrace(cost_reports):
    """The bench's cost_model_peak_mb column traces with
    ShapeDtypeStruct leaves (no compile, no dispatch at the bench
    shape) and must agree exactly with the concrete-state report at
    the same canonical shapes."""
    _base, reports = cost_reports
    state, _ = tp._canonical_env(now=1000.0)
    peak_mb = cm.peak_mb_for_state(state)["fused_pipeline"]
    assert peak_mb == round(
        reports["fused_pipeline"].peak_live_bytes / 1e6, 2)


def test_watcher_entries_are_the_coverage_oracle(cost_reports):
    """Every CompileWatcher-tracked production entry maps to cost
    coverage, both directions — a new watched jit entry fails here
    until WATCHER_COVERAGE, the registry, and the baseline learn it
    (mirrors the probe-coverage meta-test in test_analysis.py)."""
    # ground truth: the callgraph's jit entry set, via the same
    # qualname->watcher-entry map test_wire_ledger.py pins
    entry_to_watch = {
        "_fused_pipeline": "fused_pipeline",
        "_pack_commit": "pack_commit",
        "allocate_jit": "allocate",
        "set_fair_share": "set_fair_share",
        "stale_gang_eviction": "stale_gang_eviction",
        "run_victim_action_jit": "run_victim_action",
        "cluster_analytics": "analytics",
        "plan_repack": "repack",
        "resident_cycle": "resident_cycle",
        "cumsum_ds": None,      # analysis-only probe helper
    }
    graph = PackageGraph(ROOT)
    entries = {q for _m, q in graph._entries()}
    assert entries == set(entry_to_watch), (
        f"jit entry set changed: {sorted(entries)} — extend "
        f"costmodel.WATCHER_COVERAGE and this map")
    watched = {w for w in entry_to_watch.values() if w is not None}
    assert set(cm.WATCHER_COVERAGE) == watched
    _base, reports = cost_reports
    ops = set(cm.registered_cost_entries())
    covered = set().union(*cm.WATCHER_COVERAGE.values())
    for watcher_entry, names in cm.WATCHER_COVERAGE.items():
        missing = names - set(reports)
        assert not missing, (
            f"watcher entry `{watcher_entry}` lost cost reports "
            f"{missing}")
    assert ops - covered == {"cumsum_ds"}, (
        "every registered op except the analysis-only helper must "
        "audit a watcher entry")


def test_every_entry_has_cost_baseline_budget(cost_reports):
    """Report coverage == checked-in budget coverage == the probe
    baseline's coverage (one registry; scripts/lint.py drift-checks
    the same equality jax-free pre-commit)."""
    base, reports = cost_reports
    assert sorted(base["entries"]) == sorted(reports)
    assert sorted(base["entries"]) == sorted(
        cm.registered_cost_entries())
    with open(os.path.join(ROOT, "kai_scheduler_tpu", "analysis",
                           "baseline.json"), encoding="utf-8") as f:
        probe_keys = set(json.load(f)["probe"])
    assert probe_keys == set(base["entries"])


def test_cost_registry_rides_the_shared_walk(cost_reports):
    """The probe and cost layers consume ONE EntryTrace per entry: a
    pre-built trace feeds probe_op without a re-trace and yields the
    same eqn count the probe baselines."""
    _base, reports = cost_reports
    spec = {s.name: s for s in tp._registry()}["pack_commit"]
    trace = tp.trace_entries(["pack_commit"])[0]
    rep = tp.probe_op(spec, trace)
    assert rep.eqns == len(trace.eqns)
    assert reports["pack_commit"].peak_live_bytes > 0


# ---------------------------------------------------------------------------
# 3b. cross-validation — model vs measured (tolerance-gated)

def test_traffic_ranking_matches_measured_dispatch_order(cost_reports):
    """Model-vs-reality sanity pin at canonical shapes: for entry
    pairs where the model's memory-traffic estimate differs by ≥64×,
    the measured dispatch time must order the same way.  Only
    clear-margin pairs are asserted (tolerance gate: CPU dispatch has
    a ~100µs floor, and the loaded tier-1 container adds scheduling
    noise on top — two sub-ms dispatches a few × apart can invert, so
    the gate keeps only pairs where the fat fused entries face the
    tiny commit/analytics kernels).  Best-of-5 timing for the same
    reason."""
    _base, reports = cost_reports
    entries = ["fused_pipeline", "pack_commit", "analytics",
               "stale_gang_eviction", "set_fair_share"]
    env = tp._canonical_env(now=1000.0)
    specs = {s.name: s for s in tp._registry()}
    measured = {}
    for name in entries:
        spec = specs[name]
        args, kwargs = spec.make_args(env)
        jax.block_until_ready(spec.jit_fn(*args, **kwargs))  # warm
        samples = []
        for _ in range(5):
            t0 = time.perf_counter()
            jax.block_until_ready(spec.jit_fn(*args, **kwargs))
            samples.append(time.perf_counter() - t0)
        measured[name] = min(samples)
    checked = 0
    for hi in entries:
        for lo in entries:
            model_hi = reports[hi].traffic_bytes
            model_lo = reports[lo].traffic_bytes
            if model_hi >= 64 * max(model_lo, 1):
                checked += 1
                assert measured[hi] > measured[lo], (
                    f"model ranks {hi} ({model_hi}B) ≥64× over {lo} "
                    f"({model_lo}B) but measured {measured[hi]*1e3:.3f}"
                    f"ms !> {measured[lo]*1e3:.3f}ms")
    assert checked >= 4, "margin gate left nothing to cross-validate"


@pytest.mark.slow
def test_cost_ranking_at_phases_bench_shape():
    """The satellite's full-size pin: at the `phases` bench snapshot
    shape (10k nodes × 50k pods) the model's traffic/peak ordering
    holds and the bench's cost_model_peak_mb column is derivable."""
    from kai_scheduler_tpu.state import make_cluster
    from kai_scheduler_tpu.state.cluster_state import build_snapshot
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=10_000, node_accel=8.0, num_gangs=6250,
        tasks_per_gang=8, running_fraction=0.5)
    state, _index = build_snapshot(nodes, queues, groups, pods, topo,
                                   now=1000.0)
    traces = tp.trace_entries(
        ["fused_pipeline", "pack_commit", "analytics"],
        env=(state, None))
    reps = {t.name: cm._report_from_closed(
        t.name, t.closed, config=cm.DEFAULT_CONFIG, base_entry=None)
        for t in traces}
    assert (reps["fused_pipeline"].traffic_bytes
            > 8 * reps["pack_commit"].traffic_bytes)
    assert (reps["fused_pipeline"].traffic_bytes
            > 8 * reps["analytics"].traffic_bytes)
    assert (reps["fused_pipeline"].peak_live_bytes
            > reps["pack_commit"].peak_live_bytes)
    peak_mb = cm.peak_mb_for_state(state)["fused_pipeline"]
    assert peak_mb > 0


# ---------------------------------------------------------------------------
# 4. scaling mode

def test_fit_exponent_flags_superlinear():
    lin = cm.fit_exponent([32, 64, 128], [32_000, 64_000, 128_000])
    quad = cm.fit_exponent([32, 64, 128],
                           [32_000, 128_000, 512_000])
    assert abs(lin - 1.0) < 0.05
    assert abs(quad - 2.0) < 0.05
    assert lin <= cm.SUPERLINEAR_EXPONENT < quad


def test_scaling_report_rejects_unknown_entries():
    """A renamed/typoed entry must raise, never vanish into a clean
    'nothing super-linear' report — and the shipped default names must
    stay registry-valid."""
    import inspect
    with pytest.raises(ValueError, match="ghost"):
        cm.scaling_report(names=("ghost",), node_counts=(32, 64))
    defaults = inspect.signature(
        cm.scaling_report).parameters["names"].default
    assert set(defaults) <= set(cm.registered_cost_entries())


def test_scaling_report_on_a_real_entry():
    """End-to-end over the cheap fair-share entry at two padded node
    widths: structure, monotone peaks, and a sane (sub-quadratic)
    exponent for a production kernel."""
    rep = cm.scaling_report(names=("set_fair_share",),
                            node_counts=(32, 64))
    row = rep["entries"]["set_fair_share"]
    assert len(row["peak_live_bytes"]) == 2
    assert row["peak_live_bytes"][1] >= row["peak_live_bytes"][0]
    assert row["exponent"] < 2.0
    assert rep["threshold"] == cm.SUPERLINEAR_EXPONENT


# ---------------------------------------------------------------------------
# 5. CLI + scripts/lint.py registration

def test_cost_cli_json_section(capsys):
    from kai_scheduler_tpu.analysis.__main__ import main
    rc = main(["--cost", "--ops", "pack_commit,cumsum_ds", "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert {r["name"] for r in out["cost"]} == {"pack_commit",
                                                "cumsum_ds"}
    assert out["cost_problems"] == []
    assert out["cost_findings"] == []
    for r in out["cost"]:
        assert r["peak_live_bytes"] > 0
        assert r["traffic_bytes"] > 0


@pytest.mark.parametrize("argv", [
    ["--probe", "--scaling"],       # cost AND comms stages skipped
    ["--no-probe", "--scaling"],
    ["--no-probe", "--select", "KAI201"],   # not an engine rule
    ["--no-probe", "--select", "KAI301"],   # kai-comms: also jaxpr-level
])
def test_cli_rejects_flags_the_selected_stages_would_ignore(argv):
    """--scaling without a scaling-capable stage, or a KAI2xx/KAI3xx
    code on the lint --select path, must be an argparse error — never a
    clean exit that silently dropped the requested check (the
    --race/--select precedent)."""
    from kai_scheduler_tpu.analysis.__main__ import main
    with pytest.raises(SystemExit) as exc:
        main(argv)
    assert exc.value.code == 2


def test_list_rules_includes_cost_family(capsys):
    from kai_scheduler_tpu.analysis.__main__ import main
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    assert "KAI201" in out and "KAI202" in out


def test_update_baseline_refreshes_all_in_one_invocation(
        tmp_path, monkeypatch, capsys):
    """The satellite contract: one default-mode ``--update-baseline``
    invocation rewrites the probe stats, the cost budgets, AND the
    kai-comms collective budgets."""
    from kai_scheduler_tpu.analysis import comms
    from kai_scheduler_tpu.analysis.__main__ import main
    pkg = os.path.join(ROOT, "kai_scheduler_tpu", "analysis")
    probe_tmp = tmp_path / "baseline.json"
    cost_tmp = tmp_path / "cost_baseline.json"
    comm_tmp = tmp_path / "comm_baseline.json"
    with open(os.path.join(pkg, "baseline.json"),
              encoding="utf-8") as f:
        probe_data = json.load(f)
    with open(os.path.join(pkg, "cost_baseline.json"),
              encoding="utf-8") as f:
        cost_data = json.load(f)
    with open(os.path.join(pkg, "comm_baseline.json"),
              encoding="utf-8") as f:
        comm_data = json.load(f)
    probe_data["probe"].pop("cumsum_ds")
    cost_data["entries"].pop("cumsum_ds")
    comm_data["entries"].pop("cumsum_ds")
    probe_tmp.write_text(json.dumps(probe_data))
    cost_tmp.write_text(json.dumps(cost_data))
    comm_tmp.write_text(json.dumps(comm_data))
    monkeypatch.setattr(cm, "COST_BASELINE_PATH", str(cost_tmp))
    monkeypatch.setattr(comms, "COMM_BASELINE_PATH", str(comm_tmp))
    rc = main(["--root", ROOT, "--baseline", str(probe_tmp),
               "--ops", "cumsum_ds", "--update-baseline", "--json"])
    assert rc == 0
    assert "cumsum_ds" in json.loads(
        probe_tmp.read_text())["probe"]
    assert "cumsum_ds" in json.loads(
        cost_tmp.read_text())["entries"]
    assert "cumsum_ds" in json.loads(
        comm_tmp.read_text())["entries"]


def test_update_baseline_is_joint_or_nothing(tmp_path, monkeypatch):
    """A probe-invariant failure holds ALL baselines back: neither the
    cost stats nor the comm budgets are absorbed while baseline.json
    stays stale (a half-refresh would tolerate growth caused by the
    very change the probe blocked on)."""
    from kai_scheduler_tpu.analysis import comms, trace_probe
    from kai_scheduler_tpu.analysis.__main__ import main
    pkg = os.path.join(ROOT, "kai_scheduler_tpu", "analysis")
    probe_tmp = tmp_path / "baseline.json"
    cost_tmp = tmp_path / "cost_baseline.json"
    comm_tmp = tmp_path / "comm_baseline.json"
    shutil.copy(os.path.join(pkg, "baseline.json"), probe_tmp)
    shutil.copy(os.path.join(pkg, "cost_baseline.json"), cost_tmp)
    shutil.copy(os.path.join(pkg, "comm_baseline.json"), comm_tmp)
    probe_before = probe_tmp.read_text()
    cost_before = cost_tmp.read_text()
    comm_before = comm_tmp.read_text()
    monkeypatch.setattr(cm, "COST_BASELINE_PATH", str(cost_tmp))
    monkeypatch.setattr(comms, "COMM_BASELINE_PATH", str(comm_tmp))
    monkeypatch.setattr(trace_probe, "check_invariants",
                        lambda reports: ["synthetic invariant failure"])
    rc = main(["--root", ROOT, "--baseline", str(probe_tmp),
               "--ops", "cumsum_ds", "--update-baseline", "--json"])
    assert rc == 1
    assert probe_tmp.read_text() == probe_before
    assert cost_tmp.read_text() == cost_before
    assert comm_tmp.read_text() == comm_before


def _load_lint_script():
    spec = importlib.util.spec_from_file_location(
        "lint_script", os.path.join(ROOT, "scripts", "lint.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lint_script_cost_baseline_drift_check(tmp_path):
    """scripts/lint.py's jax-free stage: probe/cost baseline coverage
    in sync == clean; a missing cost budget (or a stale one) is a
    nonzero-exit drift message naming --update-baseline."""
    lint = _load_lint_script()
    assert lint.check_cost_baseline() == []
    pkg = os.path.join(ROOT, "kai_scheduler_tpu", "analysis")
    probe_tmp = tmp_path / "baseline.json"
    cost_tmp = tmp_path / "cost_baseline.json"
    shutil.copy(os.path.join(pkg, "baseline.json"), probe_tmp)
    with open(os.path.join(pkg, "cost_baseline.json"),
              encoding="utf-8") as f:
        cost_data = json.load(f)
    cost_data["entries"].pop("allocate")
    cost_data["entries"]["ghost_entry"] = {"peak_live_bytes": 1,
                                           "flops": 1,
                                           "traffic_bytes": 1,
                                           "max_blowup": 1.0}
    cost_tmp.write_text(json.dumps(cost_data))
    problems = lint.check_cost_baseline(str(probe_tmp), str(cost_tmp))
    assert any("allocate" in p for p in problems)
    assert any("ghost_entry" in p for p in problems)
    assert any("--update-baseline" in p for p in problems)
    assert lint.check_cost_baseline(
        str(probe_tmp), str(tmp_path / "missing.json"))
    # a missing PROBE baseline is the same graceful one-line drift
    # message, never an unhandled FileNotFoundError in the pre-commit
    assert lint.check_cost_baseline(
        str(tmp_path / "missing.json"), str(cost_tmp))

"""kai-twin: stream format, recorder, replay/differential oracle,
scenario fuzzer + minimizer, policy tuner, tool + server surfaces."""
import copy
import glob
import gzip
import json
import os
import random
import urllib.request

import pytest

from kai_scheduler_tpu.twin import stream as stream_mod
from kai_scheduler_tpu.twin.stream import Stream, StreamRecorder

STREAM_DIR = os.path.join(os.path.dirname(__file__), "scenarios",
                          "streams")


# ---------------------------------------------------------------------------
# stream format
# ---------------------------------------------------------------------------


@pytest.mark.core
def test_stream_round_trip_and_version_reject():
    st = Stream(seed=5, snapshot={"version": 1}, config={"kValue": 0.3},
                invariants=[{"name": "clock_monotonic"}])
    st.append("delta", delta={"pods_delete": ["p0"]})
    st.append("cycle")
    st.append("tick", seconds=2.0)
    doc = st.to_doc()
    assert stream_mod.validate_stream_doc(doc) == []
    rt = Stream.from_doc(doc)
    assert rt.to_doc() == doc
    assert rt.seed == 5 and len(rt.events) == 3
    # wrong version / format are rejected outright
    for k, v in (("version", 999), ("format", "not-a-stream")):
        bad = dict(doc, **{k: v})
        with pytest.raises(ValueError):
            Stream.from_doc(bad)


@pytest.mark.core
def test_stream_validator_catches_structural_problems():
    base = Stream(seed=0)
    base.append("cycle")
    doc = base.to_doc()
    # non-monotonic logical clocks
    bad = copy.deepcopy(doc)
    bad["events"].append({"op": "cycle", "lc": 0})
    assert any("clock" in p for p in stream_mod.validate_stream_doc(bad))
    # unknown op
    bad = copy.deepcopy(doc)
    bad["events"][0]["op"] = "frobnicate"
    assert any("op" in p for p in stream_mod.validate_stream_doc(bad))
    # tick without seconds
    bad = copy.deepcopy(doc)
    bad["events"][0] = {"op": "tick", "lc": 0}
    assert stream_mod.validate_stream_doc(bad)
    # invariants demanded but absent
    assert any("invariant" in p for p in stream_mod.validate_stream_doc(
        doc, require_invariants=True))


@pytest.mark.core
def test_stream_file_io_gzip(tmp_path):
    st = Stream(seed=1)
    st.append("tick", seconds=1.0)
    for name in ("s.stream.json", "s.stream.json.gz"):
        path = str(tmp_path / name)
        stream_mod.write_stream(st, path)
        assert stream_mod.read_stream(path).to_doc() == st.to_doc()
    with gzip.open(str(tmp_path / "s.stream.json.gz"), "rb") as f:
        json.loads(f.read().decode())  # really gzipped


@pytest.mark.core
def test_recorder_bounded_ring_and_deepcopy_drop():
    rec = StreamRecorder(limit=2)
    rec.attach({"version": 1}, seed=3)
    rec.record_cycle()
    rec.record_events([("upsert", "pods", "p0", {"name": "p0"})])
    rec.record_cycle()  # over the limit: dropped, counted
    rec.record_tick(1.0)
    stats = rec.stats()
    assert stats["events"] == 2 and stats["dropped"] == 2
    st = rec.stream()
    assert [e["op"] for e in st.events] == ["cycle", "events"]
    assert st.seed == 3
    # detached recorder records nothing further
    rec.detach()
    rec.record_cycle()
    assert rec.stats()["events"] == 2
    # a deepcopied holder drops the hook (profiling twins must never
    # re-record their own replay)
    assert copy.deepcopy({"r": rec})["r"] is None


@pytest.mark.core
def test_recorder_payloads_are_isolated():
    rec = StreamRecorder()
    rec.attach(None)
    payload = {"name": "g0", "priority": 1}
    rec.record_events([("upsert", "pod_groups", "g0", payload)])
    payload["priority"] = 99  # caller reuses its doc — must not leak
    ev = rec.stream().events[0]["events"][0]
    assert ev[3]["priority"] == 1


# ---------------------------------------------------------------------------
# determinism anchors
# ---------------------------------------------------------------------------


@pytest.mark.core
def test_cycle_seed_for_is_deterministic_and_spread():
    from kai_scheduler_tpu.framework.scheduler import cycle_seed_for
    assert cycle_seed_for(7, 3) == cycle_seed_for(7, 3)
    seen = {cycle_seed_for(7, i) for i in range(64)}
    assert len(seen) == 64  # no collisions across a cycle window
    assert cycle_seed_for(7, 0) != cycle_seed_for(8, 0)
    assert all(0 <= s < 2 ** 31 for s in seen)


def test_run_once_stamps_cycle_anchors():
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig,
                                                       cycle_seed_for)
    from kai_scheduler_tpu.runtime.snapshot import load_cluster
    from kai_scheduler_tpu.twin import fuzz
    # same 4-node shape as the differential/tuner tests — one compile
    cluster = load_cluster(fuzz._base_snapshot(num_nodes=4,
                                               num_gangs=2))
    sched = Scheduler(SchedulerConfig(seed=13))
    r0 = sched.run_once(cluster)
    r1 = sched.run_once(cluster)
    assert (r0.cycle_index, r1.cycle_index) == (0, 1)
    assert r0.cycle_seed == cycle_seed_for(13, 0)
    assert r1.cycle_seed == cycle_seed_for(13, 1)
    assert r0.cycle_seed != r1.cycle_seed


@pytest.mark.core
def test_conf_twin_keys_round_trip():
    from kai_scheduler_tpu import conf
    doc = {"seed": 21, "analyticsEvery": 3, "starvationAlarmCycles": 9,
           "twinRecord": False,
           "victims": {"sparseUnitK": 128, "maxVictimPods": 256},
           "queueDepthPerAction": {"allocate": None}}
    cfg = conf.load_config(doc)
    assert cfg.seed == 21 and cfg.analytics_every == 3
    assert cfg.starvation_alarm_cycles == 9
    assert cfg.twin_record is False
    assert cfg.session.victims.sparse_unit_k == 128
    assert cfg.session.victims.max_victim_pods == 256
    assert cfg.session.allocate.queue_depth is None  # null = unlimited
    # the effective doc reloads to the same config (the recorded
    # stream's header config replays through this exact round trip)
    eff = conf.effective_config_doc(cfg)
    cfg2 = conf.load_config(eff)
    assert conf.effective_config_doc(cfg2) == eff


# ---------------------------------------------------------------------------
# the differential oracle: twin == live, bit-exact
# ---------------------------------------------------------------------------


def test_replay_matches_live_bit_exact_300_events():
    """Drive a LIVE scheduler through ~45 randomized rounds (>=300
    mutation events, same-key create/delete/create races, ticks,
    reconciles) while the recorder captures the stream; then replay the
    stream through the twin and demand digest-for-digest equality."""
    from kai_scheduler_tpu import conf as conf_mod
    from kai_scheduler_tpu.binder.binder import Binder
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    from kai_scheduler_tpu.intake.apply import apply_cluster_delta
    from kai_scheduler_tpu.runtime.snapshot import (dump_cluster,
                                                    load_cluster)
    from kai_scheduler_tpu.twin import fuzz
    from kai_scheduler_tpu.twin import replay as twin_replay

    rng = random.Random(29)
    cluster = load_cluster(fuzz._base_snapshot(num_nodes=4))
    cfg = SchedulerConfig(seed=11)
    sched = Scheduler(cfg)
    rec = StreamRecorder()
    rec.attach(dump_cluster(cluster), seed=11,
               config=conf_mod.effective_config_doc(cfg))
    cluster.twin_recorder = rec
    cursor = cluster.journal.register()
    cursor.consume()

    live_digests = []
    alive, dead = [], []
    gid = 0
    applied = 0
    for rnd in range(34):
        for _ in range(rng.randrange(2, 4)):
            if dead and rng.random() < 0.4:
                name = dead.pop(rng.randrange(len(dead)))  # same-key race
            else:
                name = f"g{gid}"
                gid += 1
            tasks = rng.randrange(1, 3)
            apply_cluster_delta(cluster, fuzz._gang_delta(
                name, f"queue-0-{rng.randrange(2)}", tasks,
                float(rng.randrange(1, 3))))
            alive.append((name, tasks))
        while len(alive) > 6:
            name, tasks = alive.pop(0)
            apply_cluster_delta(cluster,
                                fuzz._gang_delete(name, tasks))
            dead.append(name)
        if rnd % 4 == 0:
            result = sched.run_once(cluster)
            rec.record_cycle()
            live_digests.append(twin_replay.cycle_digest(
                cluster, sched, result, cursor.consume()))
            Binder().reconcile(cluster)
            rec.record_reconcile()
            cluster.tick(1.0)
            rec.record_tick(1.0)
    stream = rec.stream()
    applied = sum(len(e["events"]) for e in stream.events
                  if e["op"] == "events")
    assert applied >= 300, f"only {applied} mutation events recorded"
    assert rec.stats()["dropped"] == 0

    report = twin_replay.replay(stream)
    assert report.apply_errors == 0
    assert report.events_applied == applied
    divergences = twin_replay.diff_digests(live_digests,
                                           report.digests)
    assert divergences == [], "\n".join(divergences)
    # at least one digest carries real work or the bar is hollow
    assert any(d["binds"] for d in live_digests)


def test_oracle_is_deterministic_same_seed_twice():
    from kai_scheduler_tpu.twin import fuzz
    from kai_scheduler_tpu.twin import replay as twin_replay
    a = fuzz.generate("diurnal", seed=4, scale=0.5)
    b = fuzz.generate("diurnal", seed=4, scale=0.5)
    assert a.to_doc() == b.to_doc()  # generation is seed-pure
    c = fuzz.generate("diurnal", seed=5, scale=0.5)
    assert c.to_doc() != a.to_doc()
    verdict = twin_replay.oracle(a)
    assert verdict["ok"], verdict["divergences"]
    assert verdict["checks"] > 0


# ---------------------------------------------------------------------------
# scenario corpus (regenerate: python -m kai_scheduler_tpu.twin.fuzz
# --write-scenarios tests/scenarios/streams)
# ---------------------------------------------------------------------------


def _scenario_files():
    return sorted(glob.glob(os.path.join(STREAM_DIR, "*.stream.json*")))


@pytest.mark.core
def test_scenario_corpus_is_checked_in_and_valid():
    files = _scenario_files()
    families = {os.path.basename(f).split(".")[0] for f in files}
    assert families >= {"diurnal", "rack_failure", "quota_storm",
                        "burst_trains", "priority_churn"}
    for path in files:
        doc = stream_mod.read_doc(path)
        problems = stream_mod.validate_stream_doc(
            doc, require_invariants=True)
        assert problems == [], f"{path}: {problems}"
        assert doc["meta"].get("minimized_to") is not None


@pytest.mark.parametrize("path", [
    pytest.param(p, id=os.path.basename(p).split(".")[0])
    for p in _scenario_files()])
def test_scenario_invariants_hold(path):
    from kai_scheduler_tpu.twin import fuzz
    st = stream_mod.read_stream(path)
    res = fuzz.evaluate(st)
    assert res["violations"] == []
    family = st.meta["family"]
    assert fuzz.SIGNATURES[family](st, res), (
        f"minimized {family} scenario no longer exercises its "
        f"signature behavior")


@pytest.mark.core
def test_minimizer_drops_irrelevant_events():
    from kai_scheduler_tpu.twin import fuzz
    st = Stream(seed=0)
    for i in range(10):
        st.append("tick", seconds=1.0)
    st.append("delta", delta={"pods_delete": ["the-one"]})
    for i in range(10):
        st.append("tick", seconds=1.0)

    def predicate(cand):  # structural: keeps only the delta
        return any(ev["op"] == "delta" for ev in cand.events)

    out = fuzz.minimize(st, predicate)
    assert len(out.events) == 1
    assert out.events[0]["op"] == "delta"
    assert out.events[0]["lc"] == 0  # logical clocks renumbered
    assert out.meta["minimized_from"] == 21


def test_fuzz_invariants_catch_planted_violations():
    """The invariant probes must actually fire — feed them observation
    sets with planted violations (no replay needed: the checkers are
    pure functions over the probe observations)."""
    from kai_scheduler_tpu.twin import fuzz
    ctx = {"stream": Stream(seed=0), "obs": {
        "now": [0.0, 2.0, 1.0], "generation": [5, 4],
        "pending": [{"g"}] * 9, "starved": set(), "frag": [0.1, 0.5],
        "overshoot": [(0, "q", 20.0, 12.0)], "binds_by_cycle": []},
        "cluster": None, "report": None}
    assert fuzz._inv_clock_monotonic(ctx)
    assert fuzz._inv_journal_monotonic(ctx)
    assert fuzz._inv_no_quota_overshoot(ctx)
    assert fuzz._inv_starvation_alarm(ctx, k=4, slack=4)
    assert fuzz._inv_pending_drains(ctx)
    assert fuzz._inv_frag_recovers(ctx)
    # and stay silent on clean observations
    ok = {"stream": Stream(seed=0), "obs": {
        "now": [0.0, 1.0], "generation": [1, 2], "pending": [set()],
        "starved": set(), "frag": [0.5, 0.2], "overshoot": [],
        "binds_by_cycle": [1]}, "cluster": None, "report": None}
    assert not fuzz._inv_clock_monotonic(ok)
    assert not fuzz._inv_journal_monotonic(ok)
    assert not fuzz._inv_no_quota_overshoot(ok)
    assert not fuzz._inv_starvation_alarm(ok)
    assert not fuzz._inv_pending_drains(ok)
    assert not fuzz._inv_frag_recovers(ok)


# ---------------------------------------------------------------------------
# policy tuner
# ---------------------------------------------------------------------------


def test_tuner_improves_planted_bad_knob():
    """The planted fixture throttles allocate depth to 1 on a burst of
    8 gangs — goodput suffers for cycles.  The tuner's axis probes
    must find a deeper queue and demonstrably beat the baseline, and
    the winning overlay must load through conf.load_config."""
    from kai_scheduler_tpu import conf
    from kai_scheduler_tpu.twin import fuzz, tune
    st = Stream(snapshot=fuzz._base_snapshot(num_nodes=4),
                config={"analyticsEvery": 1,
                        "queueDepthPerAction": {"allocate": 1}})
    for g in range(8):
        st.append("delta", delta=fuzz._gang_delta(
            f"g{g}", f"queue-0-{g % 2}", 2, 2.0))
    for _ in range(2):
        st.append("cycle")
        st.append("reconcile")
        st.append("tick", seconds=1.0)
    # one knob keeps the fixture to 3 distinct configs (each distinct
    # config is a fresh jit compile); axis probes still guarantee the
    # antidote (depth 32) is in round 0
    knobs = tuple(k for k in tune.KNOBS if k.name == "allocateDepth")
    rep = tune.tune(st, rounds=1, population=3, seed=0, knobs=knobs)
    assert rep.improvement > 0.1, (rep.baseline_metrics,
                                   rep.best_metrics)
    assert rep.best_candidate.get("allocateDepth", 0) > 1
    # goodput (not the wall-clock tie-breaker) carries the win
    assert rep.best_metrics[0] > rep.baseline_metrics[0]
    doc = rep.overlay_doc()
    assert doc["_twinTune"]["improvement"] > 0
    cfg = conf.load_config(doc)  # unknown _twinTune key ignored
    assert cfg.session.allocate.queue_depth == \
        rep.best_candidate["allocateDepth"]


@pytest.mark.core
def test_tuner_overlay_and_scoring_shapes():
    from kai_scheduler_tpu.twin import tune
    cand = {"allocateDepth": 8, "repackFragThreshold": 0.5,
            "placementGpu": "spread", "sparseUnitK": 128}
    doc = tune.to_overlay(cand)
    assert doc["queueDepthPerAction"]["allocate"] == 8
    assert doc["repack"]["fragThreshold"] == 0.5
    assert doc["victims"]["sparseUnitK"] == 128
    assert doc["tiers"][0]["plugins"][0]["arguments"]["gpu"] == "spread"
    scores = tune.score_rows([[1.0, 0.0, 0.0, 0.0],
                              [0.0, 1.0, 0.0, 0.0]])
    assert scores[0] == pytest.approx(tune.WEIGHTS[0])
    assert scores[1] == pytest.approx(tune.WEIGHTS[1])
    # knob sampling respects bounds and is seed-deterministic
    rng_a, rng_b = random.Random(3), random.Random(3)
    for knob in tune.KNOBS:
        va, vb = knob.sample(rng_a), knob.sample(rng_b)
        assert va == vb
        if knob.kind == "int":
            assert knob.lo <= va <= knob.hi


# ---------------------------------------------------------------------------
# snapshot_tool CLI
# ---------------------------------------------------------------------------


def test_snapshot_tool_record_and_oracle_replay(tmp_path, capsys):
    import snapshot_tool
    out = str(tmp_path / "diurnal.stream.json")
    rc = snapshot_tool.main(["snapshot_tool", "record", out,
                             "--family", "diurnal", "--seed", "1",
                             "--scale", "0.5"])
    assert rc == 0
    assert stream_mod.read_stream(out).meta["family"] == "diurnal"
    capsys.readouterr()
    rc = snapshot_tool.main(["snapshot_tool", "replay", out])
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    assert rc == 0
    verdicts = [l for l in lines if l["kind"] == "TwinOracle"]
    assert len(verdicts) == 1 and verdicts[0]["ok"]
    assert verdicts[0]["divergences"] == 0


@pytest.mark.core
def test_snapshot_tool_classic_replay_still_works(tmp_path, capsys):
    import snapshot_tool
    snap = str(tmp_path / "snap.json")
    assert snapshot_tool.main(["snapshot_tool", "dump", snap]) == 0
    capsys.readouterr()
    assert snapshot_tool.main(["snapshot_tool", "replay", snap]) == 0
    lines = [json.loads(l) for l in
             capsys.readouterr().out.strip().splitlines()]
    assert any(l["kind"] == "Summary" for l in lines)


# ---------------------------------------------------------------------------
# server surfaces
# ---------------------------------------------------------------------------


def _post_json(base, path, doc):
    req = urllib.request.Request(
        f"{base}{path}", data=json.dumps(doc).encode(), method="POST")
    return json.load(urllib.request.urlopen(req, timeout=60))


def _get_json(base, path):
    return json.load(urllib.request.urlopen(f"{base}{path}",
                                            timeout=30))


def test_server_twin_record_replay_endpoints():
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.framework.server import SchedulerServer
    from kai_scheduler_tpu.runtime.snapshot import load_cluster
    from kai_scheduler_tpu.twin import fuzz
    cluster = load_cluster(fuzz._base_snapshot(num_nodes=4))
    srv = SchedulerServer(cluster, Scheduler()).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        # the surface answers before anything is recorded
        doc = _get_json(base, "/debug/twin")
        assert doc["recording"] is True
        assert doc["recorder"]["events"] == 0
        # mutate + cycle through the stored path — both are recorded
        for g in range(3):
            _post_json(base, "/cluster/delta", fuzz._gang_delta(
                f"g{g}", f"queue-0-{g % 2}", 2, 2.0))
            _post_json(base, "/cycle/stored", {})
        doc = _get_json(base, "/debug/twin")
        assert doc["recorder"]["events"] == 6
        # ?stream=1 inlines a valid stream document
        full = _get_json(base, "/debug/twin?stream=1")
        assert stream_mod.validate_stream_doc(full["stream"]) == []
        # differential oracle over the recorded stream
        verdict = _post_json(base, "/twin/replay", {})
        assert verdict["ok"] is True
        assert verdict["divergences"] == []
        assert verdict["replay"]["events_applied"] > 0
        # verdict lands on /debug/twin and the healthz twin slice
        doc = _get_json(base, "/debug/twin")
        assert doc["last_replay"]["ok"] is True
        hz = _get_json(base, "/healthz")
        assert hz["twin"]["recording"] is True
        assert hz["twin"]["last_replay_ok"] is True
        assert hz["twin"]["last_replay_divergences"] == 0
        # stop freezes the ring; start re-anchors at the live cluster
        _post_json(base, "/twin/record", {"action": "stop"})
        _post_json(base, "/cluster/delta", fuzz._gang_delta(
            "late", "queue-0-0", 1, 1.0))
        assert _get_json(base, "/debug/twin")["recording"] is False
        out = _post_json(base, "/twin/record", {"action": "start"})
        assert out["recorder"]["events"] == 0  # fresh anchor
    finally:
        srv.stop()


def test_server_twin_disabled_by_config():
    from kai_scheduler_tpu import conf
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.framework.server import SchedulerServer
    from kai_scheduler_tpu.runtime.cluster import Cluster
    cfg = conf.load_config({"twinRecord": False})
    srv = SchedulerServer(Cluster(), Scheduler(cfg)).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        doc = _get_json(base, "/debug/twin")
        assert doc["recording"] is False and doc["recorder"] is None
        assert _get_json(base, "/healthz")["twin"] == {
            "recording": False}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post_json(base, "/twin/replay", {})
        assert ei.value.code == 400
    finally:
        srv.stop()

"""Shard partitioning, plugin-tier config, operator assembly, and
node-scale-adjuster tests (ref SchedulingShard CRD semantics,
plugins/factory.go tiers, pkg/operator, pkg/nodescaleadjuster)."""
import numpy as np

from kai_scheduler_tpu import plugins
from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.controllers.nodescale_adjuster import (SCALING_GROUP,
                                                              ScaleAdjuster)
from kai_scheduler_tpu.framework.scheduler import Scheduler, SchedulerConfig
from kai_scheduler_tpu.operator import Operator
from kai_scheduler_tpu.runtime.cluster import Cluster

import pytest

pytestmark = pytest.mark.slow

POOL = apis.NODE_POOL_LABEL_KEY


def _partitioned_cluster():
    nodes = [
        apis.Node("na", apis.ResourceVec(8, 64, 256), labels={POOL: "a"}),
        apis.Node("nb", apis.ResourceVec(8, 64, 256), labels={POOL: "b"}),
    ]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100))]
    groups = [
        apis.PodGroup("ga", queue="q", min_member=1, labels={POOL: "a"}),
        apis.PodGroup("gb", queue="q", min_member=1, labels={POOL: "b"}),
    ]
    pods = [apis.Pod("pa", "ga", apis.ResourceVec(1, 1, 1)),
            apis.Pod("pb", "gb", apis.ResourceVec(1, 1, 1))]
    return Cluster.from_objects(nodes, queues, groups, pods)


def test_shards_schedule_disjoint_partitions():
    cluster = _partitioned_cluster()
    shard_a = Scheduler(SchedulerConfig(
        shard=apis.SchedulingShard("a", partition_label_value="a")))
    shard_b = Scheduler(SchedulerConfig(
        shard=apis.SchedulingShard("b", partition_label_value="b")))
    ra = shard_a.run_once(cluster)
    rb = shard_b.run_once(cluster)
    assert [(b.pod_name, b.selected_node) for b in ra.bind_requests] == \
        [("pa", "na")]
    assert [(b.pod_name, b.selected_node) for b in rb.bind_requests] == \
        [("pb", "nb")]


def test_default_shard_takes_unlabeled_objects():
    cluster = _partitioned_cluster()
    cluster.nodes["nu"] = apis.Node("nu", apis.ResourceVec(8, 64, 256))
    cluster.pod_groups["gu"] = apis.PodGroup("gu", queue="q", min_member=1)
    cluster.pods["pu"] = apis.Pod("pu", "gu", apis.ResourceVec(1, 1, 1))
    default = Scheduler(SchedulerConfig(shard=apis.SchedulingShard()))
    r = default.run_once(cluster)
    assert [(b.pod_name, b.selected_node) for b in r.bind_requests] == \
        [("pu", "nu")]


def test_plugin_tiers_config_string():
    assert plugins.parse_tiers("nodeplacement,resourcetype") == (
        "nodeplacement", "resourcetype")
    assert set(plugins.available_plugins()) >= {
        "nodeplacement", "resourcetype", "nodeavailability"}
    try:
        plugins.resolve(("nope",))
        raise AssertionError("unknown plugin must raise")
    except KeyError:
        pass


def test_disabling_availability_plugin_changes_scoring():
    """With nodeavailability disabled, a task no longer prefers the
    idle-fitting node over one that only fits on releasing capacity."""
    from kai_scheduler_tpu.framework.session import SessionConfig
    from kai_scheduler_tpu.ops.allocate import AllocateConfig
    from kai_scheduler_tpu.ops.scoring import PlacementConfig

    nodes = [apis.Node("idle", apis.ResourceVec(4, 64, 256)),
             apis.Node("busy", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=10))]
    groups = [apis.PodGroup("old", queue="q", min_member=1,
                            last_start_timestamp=0.0),
              apis.PodGroup("new", queue="q", min_member=1)]
    pods = [apis.Pod("vic", "old", apis.ResourceVec(2, 1, 1),
                     status=apis.PodStatus.RELEASING, node="busy"),
            apis.Pod("inc", "new", apis.ResourceVec(1, 1, 1))]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)

    def run(tiers):
        cfg = SchedulerConfig(session=SessionConfig(
            allocate=AllocateConfig(placement=PlacementConfig(tiers=tiers))))
        res = Scheduler(cfg).run_once(cluster)
        pl = {b.pod_name: b.selected_node for b in res.bind_requests}
        for br in list(cluster.bind_requests):
            del cluster.bind_requests[br]
        return pl

    with_avail = run(("nodeplacement", "resourcetype", "nodeavailability"))
    # availability band (100) dominates binpack (<=9): picks the idle node
    assert with_avail.get("inc") == "idle"
    without = run(("nodeplacement", "resourcetype"))
    # binpack alone prefers the fuller (releasing) node — and without the
    # availability band the task pipelines there instead of binding now
    assert "inc" not in without


def test_operator_builds_shard_schedulers_and_runs():
    cluster = _partitioned_cluster()
    config = apis.Config(shards=[
        apis.SchedulingShard("a", partition_label_value="a"),
        apis.SchedulingShard("b", partition_label_value="b"),
    ])
    op = Operator(config=config, cluster=cluster)
    assert set(op.schedulers) == {"a", "b"}
    results = op.run_cycle()
    bound = {p.name for p in cluster.pods.values()
             if p.status == apis.PodStatus.BOUND}
    assert bound == {"pa", "pb"}
    assert set(results) == {"a", "b"}

    # dropping a shard from the config removes its scheduler
    op.config = apis.Config(shards=[
        apis.SchedulingShard("a", partition_label_value="a")])
    op.reconcile()
    assert set(op.schedulers) == {"a"}


def test_scale_adjuster_creates_and_deletes_scaling_pods():
    nodes = [apis.Node("n0", apis.ResourceVec(0, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100))]
    groups = [apis.PodGroup("g", queue="q", min_member=1, fit_failures=1)]
    pods = [apis.Pod("frac", "g", apis.ResourceVec(0.5, 1, 1),
                     accel_portion=0.5)]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)
    adj = ScaleAdjuster(cool_down_s=30.0)
    out = adj.adjust(cluster)
    assert out["created"] == ["scaling-pod-frac"]
    scaling = cluster.pods["scaling-pod-frac"]
    assert scaling.group == SCALING_GROUP
    assert scaling.resources.accel == 1.0  # ceil(0.5 portion) whole device

    # scheduler snapshots must not see scaling pods
    from kai_scheduler_tpu.state import build_snapshot
    state, idx = build_snapshot(*cluster.snapshot_lists())
    assert all(n is None or not n.startswith("scaling-pod-")
               for row in idx.task_names for n in row)

    # trigger pod schedules -> scaling pod cleaned up
    cluster.pod_groups["g"].fit_failures = 0
    pods[0].status = apis.PodStatus.BOUND
    out2 = adj.adjust(cluster)
    assert out2["deleted"] == ["scaling-pod-frac"]

"""DRF division parity tests.

Scenario expectations mirror the reference's behavioral spec in
``pkg/scheduler/plugins/proportion/resource_division/resource_division_test.go``
(setResourceShare / divideOverQuotaResource tables) — same inputs, same
expected fair shares, computed by the TPU kernel instead of Go.
"""
import jax.numpy as jnp
import numpy as np
import pytest

from kai_scheduler_tpu.apis.types import UNLIMITED
from kai_scheduler_tpu.ops import drf

pytestmark = pytest.mark.core


def one_level(total, quota, weight, limit, request, priority=None, usage=None,
              creation=None, k=0.0):
    """Divide `total` among one flat group of queues; returns fair shares."""
    n = len(quota)
    as_f = lambda x: jnp.asarray(x, jnp.float32)
    fs = drf._divide_one_resource(
        seg_total=as_f([total]),
        quota=as_f(quota),
        weight=as_f(weight),
        limit=as_f(limit),
        request=as_f(request),
        usage=as_f(usage if usage is not None else [0.0] * n),
        priority=jnp.asarray(priority if priority is not None else [0] * n, jnp.int32),
        seg=jnp.zeros((n,), jnp.int32),
        creation=jnp.asarray(creation if creation is not None else list(range(n)), jnp.int32),
        active=jnp.ones((n,), bool),
        k_value=jnp.asarray(k, jnp.float32),
    )
    return np.asarray(fs)


U = UNLIMITED


class TestSingleQueue:
    """Ref: 'single queue within quota (sanity)' table."""

    def test_gives_requested_no_remaining(self):
        assert one_level(2, [3], [0], [U], [2]) == [2.0]

    def test_gives_requested_with_remaining(self):
        assert one_level(3, [3], [0], [U], [2]) == [2.0]

    def test_respects_max_allowed(self):
        assert one_level(3, [3], [0], [2], [2]) == [2.0]

    def test_oversubscribed_gives_requested_deserved(self):
        # deserved min(3, 2)=2 even when total is 1 (deserved pass is a
        # guarantee, not bounded by the total — ref setDeservedResource)
        assert one_level(1, [3], [0], [U], [2]) == [2.0]

    def test_caps_at_deserved(self):
        assert one_level(7, [3], [0], [U], [5]) == [3.0]

    def test_fractional_deserved(self):
        assert one_level(2, [1.5], [0], [U], [2]) == [1.5]

    def test_fractional_request(self):
        assert one_level(2, [3], [0], [U], [1.5]) == [1.5]

    def test_zero_deserved_gives_nothing(self):
        assert one_level(2, [0], [0], [U], [2]) == [0.0]


class TestSingleQueueOverQuota:
    """Ref: 'single queue over quota (sanity)' table."""

    def test_over_quota_up_to_request(self):
        assert one_level(5, [3], [1], [U], [5]) == [5.0]

    def test_over_quota_respects_max_allowed(self):
        assert one_level(5, [3], [1], [4], [5]) == [4.0]

    def test_zero_weight_gets_no_over_quota(self):
        assert one_level(5, [3], [0], [U], [5]) == [3.0]

    def test_fractional_over_quota_request(self):
        assert one_level(5, [3], [1], [U], [4.5]) == [4.5]

    def test_remainder_fraction(self):
        assert one_level(3.5, [3], [1], [U], [5]) == [3.5]

    def test_zero_deserved_still_gets_over_quota(self):
        assert one_level(6, [0], [1], [U], [5]) == [5.0]


class TestTwoQueues:
    """Ref: 'two queues' DescribeTable."""

    def test_allocates_many_available(self):
        fs = one_level(15, [2, 2], [2, 2], [U, U], [6, 6])
        np.testing.assert_allclose(fs, [6, 6])

    def test_allocates_exact(self):
        fs = one_level(12, [2, 2], [2, 2], [U, U], [6, 6])
        np.testing.assert_allclose(fs, [6, 6])

    def test_allocates_proportionally(self):
        fs = one_level(8, [2, 2], [1, 3], [U, U], [6, 6])
        np.testing.assert_allclose(fs, [3, 5])

    def test_respects_max_allowed(self):
        fs = one_level(12, [2, 2], [2, 2], [5, U], [6, 6])
        np.testing.assert_allclose(fs, [5, 6])

    def test_remainder_by_largest_remaining(self):
        # 7 surplus, weights 1:4 -> fair 1.4/5.6 floored to 1/5; the last
        # whole unit goes to queue 2 (largest fractional remainder)
        fs = one_level(11, [2, 2], [1, 4], [U, U], [10, 10])
        np.testing.assert_allclose(fs, [3, 8])

    def test_remainder_by_creation_time(self):
        # equal weights -> 3.5/3.5 floored to 3/3; extra unit to the older
        fs = one_level(11, [2, 2], [2, 2], [U, U], [6, 6], creation=[0, 1])
        np.testing.assert_allclose(fs, [6, 5])

    def test_priority_does_not_affect_deserved(self):
        fs = one_level(4, [2, 2], [2, 2], [U, U], [6, 6], priority=[1, 2])
        np.testing.assert_allclose(fs, [2, 2])

    def test_priority_affects_over_quota(self):
        fs = one_level(6, [2, 2], [2, 2], [U, U], [6, 6], priority=[1, 2])
        np.testing.assert_allclose(fs, [2, 4])

    def test_priority_beats_weight(self):
        fs = one_level(6, [2, 2], [100, 1], [U, U], [6, 6], priority=[1, 2])
        np.testing.assert_allclose(fs, [2, 4])


class TestKValueUsage:
    """shareWeight = max(0, w + k*(w - usage)) — the time-based fairshare
    hook (ref calcShareWeights)."""

    def test_usage_penalizes_share(self):
        # equal weights, queue 0 has historical usage: with k=1 its share
        # weight halves (0.5 + 1*(0.5-0.25)=0.75 vs 0.5+1*(0.5-0)=1.0... )
        fs = one_level(8, [0, 0], [1, 1], [U, U], [8, 8], usage=[0.25, 0.0], k=1.0)
        assert fs[0] < fs[1]
        np.testing.assert_allclose(fs.sum(), 8.0)

    def test_k_zero_ignores_usage(self):
        fs = one_level(8, [0, 0], [1, 1], [U, U], [8, 8], usage=[0.25, 0.0], k=0.0)
        np.testing.assert_allclose(fs, [4, 4])


class TestHierarchy:
    def _mini_state(self):
        from kai_scheduler_tpu.apis import types as apis
        from kai_scheduler_tpu.state import build_snapshot, make_cluster
        nodes, queues, groups, pods, topo = make_cluster(
            num_nodes=4, node_accel=8.0,  # 32 accel total
            num_departments=2, queues_per_department=2,
            num_gangs=8, tasks_per_gang=8, task_accel=1.0)  # every queue asks 16
        return build_snapshot(nodes, queues, groups, pods, topo)

    def test_two_level_division(self):
        state, index = self._mini_state()
        fs = drf.set_fair_share(state, num_levels=2)
        fs = np.asarray(fs)
        i = {n: j for j, n in enumerate(index.queue_names)}
        # each department deserves 16 accel; children 8 each; surplus splits
        # evenly -> every leaf queue should land on its 8-quota
        for d in range(2):
            np.testing.assert_allclose(fs[i[f"dept-{d}"], 0], 16.0)
            for j in range(2):
                np.testing.assert_allclose(fs[i[f"queue-{d}-{j}"], 0], 8.0)

    def test_children_cannot_exceed_parent_share(self):
        from kai_scheduler_tpu.apis import types as apis
        from kai_scheduler_tpu.state import build_snapshot
        nodes = [apis.Node(f"n{k}", apis.ResourceVec(8, 0, 0)) for k in range(2)]
        queues = [
            apis.Queue("deptA", accel=apis.QueueResource(quota=4, over_quota_weight=1)),
            apis.Queue("deptB", accel=apis.QueueResource(quota=12, over_quota_weight=1)),
            apis.Queue("a1", parent="deptA", accel=apis.QueueResource(quota=4, over_quota_weight=1)),
            apis.Queue("b1", parent="deptB", accel=apis.QueueResource(quota=12, over_quota_weight=1)),
        ]
        groups = [apis.PodGroup(f"g{k}", queue=q, min_member=1) for k, q in
                  enumerate(["a1", "b1"])]
        pods = []
        for k, g in enumerate(groups):
            for t in range(16):
                pods.append(apis.Pod(f"p{k}-{t}", group=g.name,
                                     resources=apis.ResourceVec(1, 0, 0)))
        state, index = build_snapshot(nodes, queues, groups, pods, None)
        fs = np.asarray(drf.set_fair_share(state, num_levels=2))
        i = {n: j for j, n in enumerate(index.queue_names)}
        # 16 total: deserved 4+12; a1 limited by deptA's share
        np.testing.assert_allclose(fs[i["deptA"], 0], 4.0)
        np.testing.assert_allclose(fs[i["deptB"], 0], 12.0)
        np.testing.assert_allclose(fs[i["a1"], 0], 4.0)
        np.testing.assert_allclose(fs[i["b1"], 0], 12.0)

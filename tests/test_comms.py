"""kai-comms tests — sharding-propagation units, KAI3xx fixtures,
production audit, baseline coverage, lowering cross-validation,
scaling, CLI.

Mirrors the guarantee structure of ``test_costmodel.py``:

1. **Unit pins** — the PartitionSpec lattice, the ring byte model, and
   the per-primitive propagation rules against hand-computed jaxprs
   (the interpreter itself is under test, not just its outputs).
2. **Rule fixtures** — KAI301/KAI302/KAI303 carry must-trigger and
   must-not-trigger fixtures like every AST rule; both directions run.
3. **Package invariants** — every registered entry audits with zero
   conservative fallbacks and zero findings, the checked-in comm
   baseline covers exactly the registry, the declared mesh layout
   agrees leaf-exact with the inferred seeds (KAI302 both directions),
   the compiled HLO's collectives fall inside the model's predicted
   set on the 8-device virtual mesh, and modeled comm bytes grow
   sublinearly with the mesh.
"""
import importlib.util
import json
import os
import shutil

import jax
import jax.numpy as jnp
import pytest

from kai_scheduler_tpu.analysis import comms
from kai_scheduler_tpu.analysis import trace_probe as tp

pytestmark = pytest.mark.core

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

NODES = "nodes"  # the mesh axis name (mesh.NODE_AXIS)


@pytest.fixture(scope="module")
def comm_reports():
    """One full audit for the module — a pure re-trace, no compiles."""
    base = comms.load_comm_baseline()
    reports = comms.run_comms()
    return base, {r.name: r for r in reports}


def _analyze(fn, args, seeds, **kw):
    closed = jax.make_jaxpr(fn)(*args)
    return comms.analyze_closed("unit", closed, seeds, **kw)


# ---------------------------------------------------------------------------
# 1. lattice + byte-model unit pins

def test_meet_is_agreement_toward_replicated():
    a = comms.Spec((NODES, None))
    b = comms.Spec((NODES, "model"))
    assert comms._meet(a, a) == a
    assert comms._meet(a, b) == comms.Spec((NODES, None))
    assert comms._meet(a, comms.Spec((None, None))).sharded is False


def test_dedupe_first_occurrence_wins():
    assert comms._dedupe([NODES, NODES, None]) == \
        comms.Spec((NODES, None, None))


def test_collective_bytes_ring_model():
    # gather/scatter families move b·(d-1)/d; all-reduce is 2×
    assert comms.collective_bytes("all_gather", 800, 8) == 700
    assert comms.collective_bytes("reduce_scatter", 800, 8) == 700
    assert comms.collective_bytes("all_reduce", 800, 8) == 1400
    # a 1-device "mesh" still prices as a 2-ring (never free)
    assert comms.collective_bytes("all_gather", 800, 1) == 400


def test_elementwise_keeps_node_axis_sharded():
    """x*2+1 over a sharded node axis: zero collectives modeled."""
    r = _analyze(lambda x: x * jnp.float32(2.0) + jnp.float32(1.0),
                 (jnp.zeros((64, 8), jnp.float32),),
                 [comms.Spec((NODES, None))])
    assert r.collective_sites == 0
    assert r.comm_bytes == 0
    assert r.conservative_prims == {}


def test_reduce_over_sharded_axis_is_all_reduce():
    """sum over the sharded dim crosses devices: one all-reduce of the
    OUTPUT bytes."""
    r = _analyze(lambda x: jnp.sum(x, axis=0),
                 (jnp.zeros((64, 8), jnp.float32),),
                 [comms.Spec((NODES, None))])
    assert r.kinds == ["all_reduce"]
    assert r.collective_sites == 1
    assert r.comm_bytes == comms.collective_bytes("all_reduce", 8 * 4, 8)


def test_reduce_over_replicated_axis_is_free():
    """sum over the OTHER dim stays device-local — and the result
    keeps the node axis, so a following elementwise is free too."""
    r = _analyze(lambda x: jnp.sum(x, axis=1) * jnp.float32(3.0),
                 (jnp.zeros((64, 8), jnp.float32),),
                 [comms.Spec((NODES, None))])
    assert r.collective_sites == 0


def test_dot_general_contracted_sharding_is_all_reduce():
    """Contracting over a sharded dim = partial products per device +
    one all-reduce of the result."""
    def dot(a, b):
        return jax.lax.dot_general(a, b, (((1,), (0,)), ((), ())))
    r = _analyze(dot, (jnp.zeros((16, 64), jnp.float32),
                       jnp.zeros((64, 32), jnp.float32)),
                 [comms.Spec((None, NODES)), comms.Spec((NODES, None))])
    assert r.kinds == ["all_reduce"]
    assert r.comm_bytes == comms.collective_bytes(
        "all_reduce", 16 * 32 * 4, 8)


def test_scan_multiplies_trip_count():
    """A collective inside a 5-trip scan is charged 5×."""
    x = jnp.zeros((64, 8), jnp.float32)

    def looped(x):
        def body(c, _):
            return c + jnp.sum(x), None
        out, _ = jax.lax.scan(body, jnp.float32(0.0), None, length=5)
        return out

    r = _analyze(looped, (x,), [comms.Spec((NODES, None))])
    assert r.collective_sites == 1
    (site,) = r.sites
    assert site.mult == 5
    assert r.loop_comm_bytes == r.comm_bytes > 0


def test_unknown_primitive_is_conservative_and_reported():
    """An unmodeled primitive over a sharded input gathers it (upper
    bound) and is COUNTED — never silently dropped."""
    r = _analyze(lambda x: jnp.fft.fft(x).real,
                 (jnp.zeros((64, 8), jnp.float32),),
                 [comms.Spec((NODES, None))])
    assert sum(r.conservative_prims.values()) >= 1
    assert "all_gather" in r.kinds


# ---------------------------------------------------------------------------
# 2. rule fixtures — both directions, every KAI3xx rule

@pytest.mark.parametrize("code", sorted(comms.COMM_RULES))
def test_rule_fixture_triggers(code):
    findings = comms.audit_fixture(code, "bad")
    assert [f.code for f in findings] == [code]


@pytest.mark.parametrize("code", sorted(comms.COMM_RULES))
def test_rule_fixture_clean_direction(code):
    assert comms.audit_fixture(code, "good") == []


def test_comm_rules_family_is_exactly_kai3xx():
    assert comms.COMM_RULES
    assert all(c.startswith("KAI3") for c in comms.COMM_RULES)


def test_audit_fixture_rejects_unknown_rule():
    with pytest.raises(ValueError, match="unknown comm rule"):
        comms.audit_fixture("KAI999")


# ---------------------------------------------------------------------------
# 3. seed registry

def test_seed_state_specs_shard_node_axis_only():
    state, _ = tp._canonical_env(now=1000.0)
    seeds = comms.seed_state_specs(state)
    assert seeds.nodes.valid.dims[0] == NODES
    # the [X, N] tables carry the node axis SECOND
    assert seeds.nodes.filter_masks.dims[:2] == (None, NODES)
    assert seeds.nodes.soft_scores.dims[:2] == (None, NODES)
    for leaf in jax.tree_util.tree_leaves(seeds.queues):
        assert not leaf.sharded
    for leaf in jax.tree_util.tree_leaves(seeds.gangs):
        assert not leaf.sharded


def test_seed_state_specs_rejects_unclassified_section(monkeypatch):
    """A new ClusterState section must be classified before it can
    ride the mesh — the guard is a hard error, not a silent
    replicated default."""
    state, _ = tp._canonical_env(now=1000.0)
    monkeypatch.setattr(comms, "_STATE_SECTIONS",
                        ("nodes", "queues", "gangs"))
    with pytest.raises(ValueError, match="running"):
        comms.seed_state_specs(state)


def test_entry_seeds_line_up_with_jaxpr_invars():
    """The seed flattening mirrors trace_entry's arg flattening —
    leaf-for-leaf, including the k_value kwarg tail."""
    env = tp._canonical_env(now=1000.0)
    spec = {s.name: s for s in tp._registry()}["victims_preempt_sparse"]
    (trace,) = tp.trace_entries(["victims_preempt_sparse"], env=env)
    seeds = comms._entry_seed_specs(spec, env, trace.closed)
    assert len(seeds) == len(trace.closed.jaxpr.invars)
    assert any(s.sharded for s in seeds)


# ---------------------------------------------------------------------------
# 4. production invariants

def test_every_registered_entry_audits_clean(comm_reports):
    """Zero findings, zero conservative fallbacks, full coverage — the
    acceptance bar: the interpreter models every primitive the
    production entries actually use."""
    _, reports = comm_reports
    assert set(reports) == set(comms.registered_comm_entries())
    for r in reports.values():
        assert r.findings == [], r.name
        assert r.conservative_prims == {}, r.name


def test_fused_entries_model_collectives(comm_reports):
    """The flagship fused entries really exercise the model: sharded
    compute with all three collective families present."""
    _, reports = comm_reports
    for nm in comms.LOWERING_ENTRIES:
        r = reports[nm]
        assert r.comm_bytes > 0
        assert "all_reduce" in r.kinds and "all_gather" in r.kinds
        assert r.top_collectives[0]["total_bytes"] >= \
            r.top_collectives[-1]["total_bytes"]


def test_comm_baseline_matches_measurements(comm_reports):
    base, reports = comm_reports
    assert set(base["entries"]) == set(reports)
    assert base.get("num_devices") == comms.DEFAULT_CONFIG.num_devices
    assert comms.check_against_comm_baseline(
        list(reports.values()), base) == []
    # zero baselined KAI3xx rows ship with the audit (acceptance)
    assert base.get("baselined", []) == []


def test_declared_shardings_agree_with_seeds():
    """KAI302 production direction: mesh.state_shardings and the
    auditor's seed registry agree leaf-exact."""
    assert comms.check_declared_shardings() == []


def test_baseline_regression_and_coverage_messages(comm_reports):
    base, reports = comm_reports
    rep = reports["fused_pipeline"]
    doctored = {"num_devices": rep.num_devices,
                "entries": {"fused_pipeline": {
                    "collective_sites": 1,
                    "comm_bytes": 1,
                    "loop_comm_bytes": 1},
                    "ghost_entry": dict(
                        base["entries"]["fused_pipeline"])}}
    problems = comms.check_against_comm_baseline([rep], doctored)
    assert any("collective sites" in p for p in problems)
    assert any("comm bytes" in p for p in problems)
    assert any("ghost_entry" in p and "stale" in p for p in problems)
    # an entry with NO baseline row names the refresh command
    problems = comms.check_against_comm_baseline(
        [rep], {"num_devices": rep.num_devices, "entries": {}},
        full_coverage=False)
    assert any("--update-baseline" in p for p in problems)


def test_baseline_device_count_mismatch_flagged(comm_reports):
    base, reports = comm_reports
    doctored = dict(base, num_devices=4)
    problems = comms.check_against_comm_baseline(
        list(reports.values()), doctored)
    assert any("4 devices" in p for p in problems)


def test_baselined_kai3xx_rows_require_justification(comm_reports):
    base, reports = comm_reports
    rep = reports["fused_pipeline"]
    row = {"file": "jaxpr:fused_pipeline", "code": "KAI301", "count": 1}
    doctored = dict(base, baselined=[dict(row)])
    problems = comms.check_against_comm_baseline([rep], doctored,
                                                 full_coverage=False)
    assert any("justification" in p for p in problems)
    justified = dict(base, baselined=[
        dict(row, justification="measured harmless at this shape")])
    problems = comms.check_against_comm_baseline([rep], justified,
                                                 full_coverage=False)
    assert not any("justification" in p for p in problems)


def test_update_comm_baseline_merges_subset(tmp_path, comm_reports):
    """An --ops subset refresh must not drop the other entries."""
    base, reports = comm_reports
    path = tmp_path / "comm_baseline.json"
    path.write_text(json.dumps(base))
    comms.update_comm_baseline([reports["cumsum_ds"]], str(path))
    data = json.loads(path.read_text())
    assert set(data["entries"]) == set(base["entries"])
    assert data["baselined"] == base.get("baselined", [])


# ---------------------------------------------------------------------------
# 5. lowering cross-validation (HLO vs model)

def test_lowering_check_verifies_small_entry(virtual_devices):
    """Tier-1 smoke on the cheapest collective-bearing entry: the
    compiled HLO's collectives fall inside the predicted set."""
    (doc,) = comms.lowering_check(names=("set_fair_share",))
    assert doc["verified"] is True, doc
    assert doc["num_devices"] == len(virtual_devices)
    assert set(doc["hlo"]) <= comms._allowed_hlo_kinds(
        set(doc["predicted"]))


@pytest.mark.slow
def test_lowering_check_verifies_fused_entries(virtual_devices):
    """The acceptance bar: both fused production entries compile with
    real in_shardings on the 8-device mesh and every HLO collective is
    explained by the model."""
    docs = comms.lowering_check()
    assert [d["entry"] for d in docs] == list(comms.LOWERING_ENTRIES)
    for d in docs:
        assert d["verified"] is True, d
    assert comms.lowering_problems(docs) == []


def test_lowering_check_rejects_unknown_entry():
    with pytest.raises(ValueError, match="unknown entries"):
        comms.lowering_check(names=("ghost",))


def test_lowering_problems_gate_semantics():
    ok = {"entry": "e", "num_devices": 8, "predicted": ["all_reduce"],
          "hlo": ["all_reduce"], "unexplained": [], "verified": True}
    assert comms.lowering_problems([ok]) == []
    unexplained = dict(ok, unexplained=["collective_permute"],
                       verified=False)
    (p,) = comms.lowering_problems([unexplained])
    assert "did not predict" in p
    unverifiable = {"entry": "e", "num_devices": 8,
                    "predicted": ["all_reduce"], "verified": False,
                    "error": "no HLO introspection"}
    (p,) = comms.lowering_problems([unverifiable])
    assert "UNVERIFIABLE" in p


def test_hlo_kind_extraction_and_decompositions():
    text = ("%ar = f32[8] all-reduce(f32[8] %x)\n"
            "%ag = f32[8] all-gather-start(f32[1] %y)\n")
    assert comms._hlo_collective_kinds(text) == {"all_reduce",
                                                 "all_gather"}
    # a predicted all-reduce licenses its reduce-scatter + all-gather
    # decomposition; a bare all_gather licenses only itself
    assert comms._allowed_hlo_kinds({"all_reduce"}) == {
        "all_reduce", "reduce_scatter", "all_gather"}
    assert comms._allowed_hlo_kinds({"all_gather"}) == {"all_gather"}


# ---------------------------------------------------------------------------
# 6. scaling + bench hook

def test_comm_scaling_is_sublinear(comm_reports):
    """Ring collectives cost b·(d-1)/d — modeled comm plateaus with
    mesh growth (the ROADMAP-2 "go" signal), it must not grow
    linearly."""
    _, reports = comm_reports
    rep = comms.comm_scaling_report(reports=list(reports.values()))
    assert rep["device_counts"] == [2, 4, 8]
    for nm in comms.LOWERING_ENTRIES:
        row = rep["entries"][nm]
        assert row["sublinear"] is True
        assert row["exponent"] < comms.SUBLINEAR_EXPONENT_BAR
        assert row["comm_bytes"] == sorted(row["comm_bytes"])


def test_comm_scaling_rejects_unknown_entries():
    with pytest.raises(ValueError, match="unknown entries"):
        comms.comm_scaling_report(names=("ghost",))


def test_comm_bytes_for_state_matches_audit(comm_reports):
    """The bench hook's abstract re-trace prices identically to the
    concrete audit at the same shapes."""
    _, reports = comm_reports
    state, _ = tp._canonical_env(now=1000.0)
    got = comms.comm_bytes_for_state(state)
    assert got == {"fused_pipeline":
                   reports["fused_pipeline"].comm_bytes}


# ---------------------------------------------------------------------------
# 7. CLI + lint-script drift check

def test_cli_comms_subset_json(capsys):
    """--comms with an --ops subset: reports + KAI302 drift check run,
    the expensive lowering stage is skipped (no fused entry named)."""
    from kai_scheduler_tpu.analysis.__main__ import main
    rc = main(["--comms", "--ops", "set_fair_share,cumsum_ds",
               "--json"])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert {r["name"] for r in out["comms"]} == {"set_fair_share",
                                                 "cumsum_ds"}
    assert out["comms_problems"] == []
    assert out["comms_findings"] == []
    assert out["comms_lowering"] == []


def test_lint_script_comm_baseline_drift_check(tmp_path):
    """scripts/lint.py's jax-free stage: probe/comms baseline coverage
    in sync == clean; a missing comm budget (or a stale one) is a
    drift message naming --update-baseline."""
    spec = importlib.util.spec_from_file_location(
        "lint_script", os.path.join(ROOT, "scripts", "lint.py"))
    lint = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(lint)
    assert lint.check_comm_baseline() == []
    pkg = os.path.join(ROOT, "kai_scheduler_tpu", "analysis")
    probe_tmp = tmp_path / "baseline.json"
    comm_tmp = tmp_path / "comm_baseline.json"
    shutil.copy(os.path.join(pkg, "baseline.json"), probe_tmp)
    with open(os.path.join(pkg, "comm_baseline.json"),
              encoding="utf-8") as f:
        comm_data = json.load(f)
    comm_data["entries"].pop("allocate")
    comm_data["entries"]["ghost_entry"] = {"collective_sites": 0,
                                           "comm_bytes": 0,
                                           "loop_comm_bytes": 0}
    comm_tmp.write_text(json.dumps(comm_data))
    problems = lint.check_comm_baseline(str(probe_tmp), str(comm_tmp))
    assert any("allocate" in p for p in problems)
    assert any("ghost_entry" in p for p in problems)
    assert any("--update-baseline" in p for p in problems)
    assert lint.check_comm_baseline(
        str(probe_tmp), str(tmp_path / "missing.json"))

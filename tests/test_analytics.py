"""kai-pulse tests — the on-device cluster-health analytics kernel
(``ops/analytics.py``) and its surfaces.

Layers:

1. **NumPy-oracle equivalence** on randomized snapshots: fragmentation
   histogram, fairness drift, and starvation ages must be BIT-exact vs
   a sequential host reference (integer-valued test resources keep f32
   sums exact, so reduction order cannot blur the comparison); ratio
   gauges (gini/goodput/util) are checked to float tolerance.
2. **Predictive fragmentation scenario** (the acceptance property): a
   fragmented two-rack cluster where a rack-required gang is
   cluster-feasible but rack-unplaceable reads a HIGH fragmentation
   score; freeing one rack places the gang and drops the score.
3. **Cadence soak**: ``analytics_every=K`` adds ZERO wire-ledger bytes
   — per-cycle uploads are byte-identical to an analytics-off twin,
   and the redundant-identical count stays 0 on the patch path.
4. **Coverage meta**: the kernel is registered in the jaxpr probe and
   wrapped by the CompileWatcher like every production jit entry.
5. **Endpoints**: ``GET /debug`` (the index enumerates real routes)
   and ``GET /debug/cluster`` (torn-proof latest analytics doc).
"""
import json
import urllib.request

import numpy as np
import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import analytics as pulse
from kai_scheduler_tpu.ops.allocate import init_result

EPS = pulse.EPS


def _snapshot(seed=0, **kw):
    from kai_scheduler_tpu.state.cluster_state import build_snapshot
    from kai_scheduler_tpu.state.synthetic import make_cluster
    kw.setdefault("num_nodes", 12)
    kw.setdefault("num_gangs", 10)
    kw.setdefault("tasks_per_gang", 2)
    kw.setdefault("running_fraction", 0.5)
    kw.setdefault("topology_levels", (3,))
    kw.setdefault("seed", seed)
    nodes, queues, groups, pods, topo = make_cluster(**kw)
    return build_snapshot(nodes, queues, groups, pods, topo, now=100.0)


def _oracle(state, res, ages, cfg):
    """Sequential host reference of the kernel's exact formulas (the
    fragmentation family reads the PRE-decision snapshot free pool)."""
    f32 = np.float32
    free = np.maximum(np.asarray(state.nodes.free), f32(0.0))
    valid = np.asarray(state.nodes.valid)
    alloc = np.asarray(state.nodes.allocatable)
    N, R = free.shape
    bins = cfg.hist_bins
    hist = np.zeros((R, bins), f32)
    for n in range(N):
        if not valid[n]:
            continue
        for r in range(R):
            frac = (free[n, r] / max(alloc[n, r], f32(EPS))
                    if alloc[n, r] > 0 else f32(0.0))
            b = min(max(int(np.floor(f32(frac * bins))), 0), bins - 1)
            hist[r, b] += 1
    # unit pods per node (allocate fit predicate + floor)
    unit = np.asarray(cfg.unit_req, f32)
    units = np.zeros((N,), f32)
    for n in range(N):
        if not valid[n]:
            continue
        if not all(free[n, r] + f32(1e-6) >= unit[r] for r in range(R)):
            continue
        u = np.inf
        for r in range(R):
            if unit[r] > 0:
                u = min(u, np.floor(f32(free[n, r] / max(unit[r],
                                                         f32(EPS)))))
        units[n] = 0.0 if not np.isfinite(u) else max(u, 0.0)
    # fairness drift
    cap = np.sum(np.where(valid[:, None], alloc, f32(0.0)),
                 axis=0, dtype=f32)
    qalloc = np.asarray(res.queue_allocated)
    fs = np.asarray(state.queues.fair_share)
    qvalid = np.asarray(state.queues.valid)
    drift = np.zeros((qalloc.shape[0],), f32)
    for q in range(qalloc.shape[0]):
        if not qvalid[q]:
            continue
        drift[q] = max(
            f32(abs(f32(qalloc[q, r] - fs[q, r])) / max(cap[r], f32(1.0)))
            for r in range(R))
    # starvation ages
    gvalid = np.asarray(state.gangs.valid)
    allocated = np.asarray(res.allocated)
    age_next = np.where(gvalid & ~allocated, ages + f32(1.0), f32(0.0))
    return hist, units, drift, age_next.astype(f32)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_numpy_oracle_equivalence(seed):
    import jax.numpy as jnp
    state, index = _snapshot(seed=seed)
    rng = np.random.default_rng(seed)
    # randomize the kernel inputs without running the solver:
    G = state.gangs.g
    # perturb the PRE-decision free pool (fragmentation inputs) ...
    state = state.replace(nodes=state.nodes.replace(
        free=jnp.maximum(
            state.nodes.free
            - jnp.asarray(rng.integers(0, 3, state.nodes.free.shape)
                          .astype(np.float32)), 0.0)))
    # ... and the post-decision outcome tensors independently
    res = init_result(state)
    res = res.replace(
        allocated=jnp.asarray(rng.random(G) < 0.3),
        queue_allocated=state.queues.allocated
        + jnp.asarray(rng.integers(0, 5, state.queues.allocated.shape)
                      .astype(np.float32)))
    ages = rng.integers(0, 40, G).astype(np.float32)
    cfg = pulse.AnalyticsConfig()
    b = pulse.cluster_analytics_jit(state, res, ages, config=cfg)
    hist, units, drift, age_next = _oracle(state, res, ages, cfg)
    np.testing.assert_array_equal(np.asarray(b.free_hist), hist)
    np.testing.assert_array_equal(np.asarray(b.queue_drift), drift)
    k = min(cfg.top_k, G)
    expect_top = np.sort(age_next)[::-1][:k]
    np.testing.assert_array_equal(np.asarray(b.starv_age), expect_top)
    # the table indexes real gangs with those exact ages
    got_idx = np.asarray(b.starv_gang)
    np.testing.assert_array_equal(age_next[got_idx],
                                  np.asarray(b.starv_age))
    assert float(b.total_units) == float(units.sum())
    # ratio gauges to tolerance (reduction order may differ)
    qvalid = np.asarray(state.queues.valid)
    nq = qvalid.sum()
    assert np.isclose(float(b.drift_max), drift.max())
    assert np.isclose(float(b.drift_mean), drift.sum() / max(nq, 1))
    assert float(b.pending_gangs) == int(
        (np.asarray(state.gangs.valid)
         & ~np.asarray(res.allocated)).sum())


def test_flatten_unpack_roundtrip():
    import jax.numpy as jnp
    state, _ = _snapshot()
    res = init_result(state)
    cfg = pulse.AnalyticsConfig()
    ages = jnp.zeros((state.gangs.g,), jnp.float32)
    b = pulse.cluster_analytics_jit(state, res, ages, config=cfg)
    f32, i32 = pulse.flatten(b)
    q, r, g = state.queues.q, 3, state.gangs.g
    assert f32.shape[0] == pulse.f32_len(cfg, q=q, r=r, g=g)
    assert i32.shape[0] == pulse.i32_len(cfg, q=q, r=r, g=g)
    d = pulse.host_unpack(np.asarray(f32), np.asarray(i32),
                          config=cfg, q=q, r=r, g=g)
    for f in pulse.F32_FIELDS + pulse.I32_FIELDS:
        np.testing.assert_array_equal(d[f], np.asarray(getattr(b, f)))


# ---------------------------------------------------------------------------
# the predictive fragmentation scenario (acceptance property)
# ---------------------------------------------------------------------------


def _frag_cluster():
    """Two racks x 4 nodes x 4 accel; every node 3/4 full with
    NON-preemptible fillers, so each rack strands 4 free devices — a
    rack-required 8-pod gang is cluster-feasible (8 free devices) but
    unplaceable in any single rack, and no victim action may move the
    fillers for it."""
    from kai_scheduler_tpu.runtime.cluster import Cluster
    level = "topo/rack"
    topo = apis.Topology(name="default",
                         levels=[level, "kubernetes.io/hostname"])
    nodes, pods, groups = [], [], []
    for i in range(8):
        name = f"node-{i}"
        nodes.append(apis.Node(
            name, apis.ResourceVec(4, 64, 256),
            labels={level: f"rack-{i // 4}",
                    "kubernetes.io/hostname": name}))
    queues = [apis.Queue("fill", accel=apis.QueueResource(quota=24)),
              apis.Queue("big", accel=apis.QueueResource(quota=8))]
    for i in range(8):
        g = apis.PodGroup(
            f"fill-{i}", queue="fill", min_member=3,
            preemptibility=apis.Preemptibility.NON_PREEMPTIBLE)
        groups.append(g)
        for t in range(3):
            pods.append(apis.Pod(
                f"fill-{i}-{t}", g.name, apis.ResourceVec(1, 1, 4),
                status=apis.PodStatus.RUNNING, node=f"node-{i}"))
    gang = apis.PodGroup(
        "big-gang", queue="big", min_member=8,
        topology_constraint=apis.TopologyConstraint(
            topology="default", required_level=level))
    groups.append(gang)
    for t in range(8):
        pods.append(apis.Pod(f"big-{t}", "big-gang",
                             apis.ResourceVec(1, 1, 4)))
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_fragmentation_gauge_is_predictive():
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    cluster = _frag_cluster()
    sched = Scheduler(SchedulerConfig())
    from kai_scheduler_tpu.framework import metrics
    res = sched.run_once(cluster)
    # the rack-required gang cannot place while capacity is stranded
    assert res.bind_requests == []
    assert metrics.gang_starvation_age.value("big-gang") == 1.0
    frag = res.analytics["fragmentation"]
    assert frag["total_unit_pods"] == 8.0
    assert frag["largest_rack_unit_pods"] == 4.0
    rung8 = [r for r in frag["gang_ladder"] if r["pods"] == 8][0]
    assert rung8["cluster_feasible"] and not rung8["rack_placeable"]
    high = frag["score"]
    assert high > 0.2
    # free one rack: evict a filler pod from each rack-0 node and let
    # the releasing capacity reap — rack-0 then holds 8 whole devices
    for i in range(4):
        cluster.evict_pod(f"fill-{i}-0")
    cluster.tick()
    cluster.tick()
    res2 = sched.run_once(cluster)
    frag2 = res2.analytics["fragmentation"]
    assert len(res2.bind_requests) == 8           # the gang placed
    rung8b = [r for r in frag2["gang_ladder"] if r["pods"] == 8][0]
    assert frag2["score"] < high
    assert res2.analytics["goodput"] >= res.analytics["goodput"]
    assert rung8b["rack_placeable"] or frag2["score"] == 0.0
    # the placed gang left the starvation top-K — its gauge series is
    # zeroed, not frozen at the last starving age
    assert metrics.gang_starvation_age.value("big-gang") == 0.0


# ---------------------------------------------------------------------------
# cadence soak — zero extra wire bytes
# ---------------------------------------------------------------------------


def _soak_cluster(seed=0):
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state.synthetic import make_cluster
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=16, num_gangs=12, tasks_per_gang=2,
        running_fraction=0.5, seed=seed)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def _churn(cluster, step: int):
    """Deterministic journaled churn shared by both soak twins."""
    running = sorted(p.name for p in cluster.pods.values()
                     if p.status == apis.PodStatus.RUNNING)
    if running:
        cluster.evict_pod(running[step % len(running)])
    cluster.tick()


def test_cadence_knob_adds_zero_wire_bytes():
    """``analytics_every=K``: uploads are byte-identical to an
    analytics-off twin on EVERY cycle (the kernel consumes only
    device-resident state), and the patch path stays free of
    redundant-identical bytes on analytics-carrying cycles."""
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)

    def run(every: int):
        cluster = _soak_cluster()
        sched = Scheduler(SchedulerConfig(analytics_every=every))
        rows = []
        for step in range(8):
            res = sched.run_once(cluster)
            patch = res.wire["by_reason"].get("journal-patch", {})
            rows.append((res.wire["bytes"], res.wire["redundant_bytes"],
                         patch.get("redundant_bytes", 0),
                         bool(res.analytics)))
            _churn(cluster, step)
        return rows

    on = run(every=3)
    off = run(every=0)
    assert [r[3] for r in off] == [False] * 8
    assert [r[3] for r in on] == [True, False, False] * 2 + [True, False]
    for cyc, (a, b) in enumerate(zip(on, off)):
        # the core claim: analytics (on its cycles AND on skipped ones)
        # ships nothing — bytes-on-wire match the analytics-off twin
        # exactly.  (redundant_bytes is NOT compared across twins: the
        # ledger's content-fingerprint detector is process-global, so
        # the twin's identical full build legitimately counts as a
        # re-upload of the first run's leaves.)
        assert a[0] == b[0], (
            f"cycle {cyc}: analytics changed bytes-on-wire "
            f"{a[0]} != {b[0]}")
    # analytics-carrying patched cycles add zero redundant-identical
    # bytes (the acceptance invariant; cycle 0 is the full build)
    for cyc, row in enumerate(on[1:], start=1):
        assert row[2] == 0, f"cycle {cyc}: redundant patch bytes"


# ---------------------------------------------------------------------------
# coverage meta — probe + compile watcher
# ---------------------------------------------------------------------------


def test_analytics_registered_in_probe_and_watcher():
    from kai_scheduler_tpu.analysis.trace_probe import registered_ops
    from kai_scheduler_tpu.runtime.compile_watch import WATCHER
    assert "analytics" in registered_ops()
    assert "analytics" in WATCHER.entries()
    from kai_scheduler_tpu.ops.analytics import cluster_analytics_jit
    # the watcher wrapper forwards the jit cache probe (the trace
    # probe's compile-once assertion depends on it)
    assert hasattr(cluster_analytics_jit, "_cache_size")


# ---------------------------------------------------------------------------
# endpoints
# ---------------------------------------------------------------------------


def _get_json(base, path):
    return json.load(urllib.request.urlopen(f"{base}{path}", timeout=10))


def test_debug_index_and_cluster_endpoints():
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    from kai_scheduler_tpu.framework.server import (DEBUG_SURFACES,
                                                    SchedulerServer)
    cluster = _soak_cluster(seed=3)
    srv = SchedulerServer(cluster,
                          Scheduler(SchedulerConfig())).start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        doc = _get_json(base, "/debug")
        paths = {s["path"] for s in doc["surfaces"]}
        assert paths == {s["path"] for s in DEBUG_SURFACES}
        for s in doc["surfaces"]:
            assert s["desc"] and isinstance(s["params"], list)
        # the index enumerates REAL routes: every live surface answers
        # (the pprof cycle profile is skipped — it runs a full cycle)
        for s in doc["surfaces"]:
            if s["path"].startswith("/debug/pprof"):
                continue
            _get_json(base, s["path"])
        # continuous profiler is off for this config and marked so
        cont = [s for s in doc["surfaces"]
                if s["path"] == "/debug/pprof/continuous"][0]
        assert cont["live"] is False
        # /debug/cluster: empty before the first cycle, populated after
        before = _get_json(base, "/debug/cluster")
        assert before["ok"] is False and before["analytics"] == {}
        req = urllib.request.Request(f"{base}/cycle/stored", data=b"",
                                     method="POST")
        urllib.request.urlopen(req, timeout=60).read()
        after = _get_json(base, "/debug/cluster")
        assert after["ok"] is True
        assert "fragmentation" in after["analytics"]
        assert "goodput" in after["analytics"]
        assert after["analytics_every"] == 1
        # the /healthz doc carries the kai-pulse slice
        hz = _get_json(base, "/healthz")
        assert "cluster" in hz["last_cycle"]
        assert "fragmentation_score" in hz["last_cycle"]["cluster"]
    finally:
        srv.stop()

"""kai-resident — device-resident cluster state (ops/resident.py).

Tier-1 coverage for ROADMAP item 1's endgame:

* packed-delta unit properties: pack/apply round-trip bit-exactness on
  randomized mirror mutations, identity reuse for unchanged leaves,
  NaN stability, shape-change rejection, fixed pytree structure;
* THE soak: 20+ churn cycles where the resident scheduler's bind
  requests, evictions, DecisionLog events, and analytics docs are
  bit-identical to a full-rebuild twin — including a mid-soak
  structural-change fallback and recovery back to resident mode —
  while every steady resident cycle performs exactly ONE watched jit
  dispatch and ONE ``device_put`` whose bytes equal the packed
  journal-delta size (asserted via the TransferLedger), with zero
  redundant-identical bytes and the full snapshot counted as reused
  device-resident bytes;
* the desync guard (a staged-but-never-adopted delta forces a full
  rebuild instead of serving a mirror the device never saw) and the
  verify gather (``verify_device_residency`` catches a device/mirror
  divergence).
"""
import copy

import jax
import numpy as np
import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                   SchedulerConfig)
from kai_scheduler_tpu.ops import resident as resident_ops
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.compile_watch import WATCHER
from kai_scheduler_tpu.runtime.wire_ledger import (LEDGER,
                                                   REASON_DELTA_APPLY)
from kai_scheduler_tpu.state.cluster_state import build_snapshot
from kai_scheduler_tpu.state.incremental import (IncrementalSnapshotter,
                                                 IncrementalVerifyError)
from kai_scheduler_tpu.state.synthetic import make_cluster


# ---------------------------------------------------------------------------
# delta pack/apply units
# ---------------------------------------------------------------------------


def _host_mirror(now=100.0):
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=8, node_accel=8.0, num_gangs=8, tasks_per_gang=2,
        running_fraction=0.5)
    _state, _index, host = build_snapshot(
        nodes, queues, groups, pods, topo, now=now, _return_host=True)
    return host


def _mutate(host, rng, leaf_fraction=0.5, elem_fraction=0.05):
    """A randomized same-shape mirror mutation: copy the pytree and
    perturb a few elements in a random subset of leaves."""
    paths, treedef = jax.tree_util.tree_flatten_with_path(host)
    out = []
    for _path, leaf in paths:
        if rng.random() > leaf_fraction or leaf.size == 0:
            out.append(leaf)
            continue
        new = leaf.copy()
        k = max(1, int(leaf.size * elem_fraction))
        idx = rng.choice(leaf.size, size=min(k, leaf.size),
                         replace=False)
        flat = new.reshape(-1)
        if new.dtype.kind == "f":
            flat[idx] += 1.5
        elif new.dtype.kind == "b":
            flat[idx] = ~flat[idx]
        else:
            flat[idx] = flat[idx] + 1
        out.append(new)
    return jax.tree_util.tree_unflatten(treedef, out)


def test_pack_apply_roundtrip_bit_exact():
    rng = np.random.default_rng(0)
    old = _host_mirror()
    apply_jit = jax.jit(resident_ops.apply_delta)
    dev = jax.device_put(old)
    for trial in range(4):
        new = _mutate(old, rng)
        delta, merged, stats = resident_ops.pack_delta(old, new)
        assert stats["bytes"] == resident_ops.delta_nbytes(delta)
        dev = apply_jit(dev, jax.device_put(delta))
        for (p, want), got, kept in zip(
                jax.tree_util.tree_flatten_with_path(new)[0],
                jax.tree_util.tree_leaves(dev),
                jax.tree_util.tree_leaves(merged)):
            name = jax.tree_util.keystr(p)
            assert np.array_equal(np.asarray(got), want,
                                  equal_nan=want.dtype.kind == "f"), name
            assert np.array_equal(kept, want,
                                  equal_nan=want.dtype.kind == "f"), name
        old = merged


def test_pack_reuses_unchanged_leaf_objects_and_empty_delta():
    old = _host_mirror()
    # identical mirrors: every class ships zero-size segments and the
    # merged mirror is the OLD leaf objects (identity short-circuit)
    same = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(old),
        [leaf.copy() for leaf in jax.tree_util.tree_leaves(old)])
    delta, merged, stats = resident_ops.pack_delta(old, same)
    assert (stats["leaves"], stats["elements"], stats["bytes"]) \
        == (0, 0, 0)
    assert all(k == 0 for k in stats["buckets"].values())
    for a, b in zip(jax.tree_util.tree_leaves(merged),
                    jax.tree_util.tree_leaves(old)):
        assert a is b
    # fixed structure: the no-op delta and a real one flatten alike
    real = resident_ops.pack_delta(old, _mutate(
        old, np.random.default_rng(1)))[0]
    assert (jax.tree_util.tree_structure(delta)
            == jax.tree_util.tree_structure(real))
    assert (jax.tree_util.tree_structure(delta)
            == jax.tree_util.tree_structure(
                resident_ops.empty_delta(old)))


def test_pack_bucket_hysteresis_pins_the_signature():
    """Fed back as ``min_buckets``, chosen segment lengths never
    shrink — a smaller later delta reuses the same padded shapes, so
    the fused entry's abstract signature cannot flip cycle-to-cycle
    (every flip would be a full XLA recompile)."""
    rng = np.random.default_rng(5)
    old = _host_mirror()
    big = _mutate(old, rng, leaf_fraction=0.9, elem_fraction=0.2)
    delta1, merged, stats1 = resident_ops.pack_delta(old, big)
    small = _mutate(merged, rng, leaf_fraction=0.2,
                    elem_fraction=0.01)
    delta2, _m, stats2 = resident_ops.pack_delta(
        merged, small, min_buckets=stats1["buckets"])
    for part in ("idx", "val"):
        assert {k: v.shape for k, v in delta2[part].items()} \
            == {k: v.shape for k, v in delta1[part].items()}
    assert all(stats2["buckets"][k] >= v
               for k, v in stats1["buckets"].items())


def test_pack_is_nan_stable():
    old = _host_mirror()
    leaves = jax.tree_util.tree_leaves(old)
    f32 = next(l for l in leaves if l.dtype == np.float32 and l.size > 4)
    f32.reshape(-1)[1] = np.nan
    new = jax.tree_util.tree_unflatten(
        jax.tree_util.tree_structure(old),
        [l.copy() for l in jax.tree_util.tree_leaves(old)])
    _delta, _merged, stats = resident_ops.pack_delta(old, new)
    # the NaN cell matches its NaN twin: nothing to ship
    assert stats["elements"] == 0 and stats["bytes"] == 0


def test_pack_rejects_shape_change():
    old = _host_mirror()
    paths, treedef = jax.tree_util.tree_flatten_with_path(old)
    bad = [leaf for _p, leaf in paths]
    bad[0] = np.zeros(np.asarray(bad[0]).shape + (2,), bad[0].dtype)
    with pytest.raises(resident_ops.DeltaShapeError):
        resident_ops.pack_delta(
            old, jax.tree_util.tree_unflatten(treedef, bad))


# ---------------------------------------------------------------------------
# THE soak: resident vs full-rebuild twin, bit-exact, one dispatch
# ---------------------------------------------------------------------------


def _steady_cluster(num_nodes=24, num_gangs=24):
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=8.0, num_gangs=num_gangs,
        tasks_per_gang=2, running_fraction=0.5)
    cursor: dict = {}
    for p in pods:
        if p.status == apis.PodStatus.RUNNING:
            c = cursor.get(p.node, 0)
            p.accel_devices = [c]
            cursor[p.node] = c + 1
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def _churn(cluster, rng, frac, num_nodes):
    k = max(1, int(len(cluster.pods) * frac / 2))
    running = [p.name for p in cluster.pods.values()
               if p.status == apis.PodStatus.RUNNING][:k]
    # restart=True: the controller recreates the evicted pods, so they
    # re-enter PENDING and the next (resident) cycle actually has to
    # PLACE them — the bit-exact compare sees real bind decisions, not
    # an idle equilibrium
    for nm in running:
        cluster.evict_pod(nm, restart=True)
    pending = [p for p in cluster.pods.values()
               if p.status == apis.PodStatus.PENDING][:k]
    for p in pending:
        try:
            cluster.bind_pod(p.name, f"node-{rng.integers(0, num_nodes)}")
        except RuntimeError:
            pass
    cluster.tick()


def _submit_extra_gang(cluster, cyc):
    """A fresh 2-pod gang through the journal's gangs_added/pods_added
    path — exercised ON resident cycles (appends are patchable)."""
    queue = next(iter(cluster.pod_groups.values())).queue
    name = f"soak-extra-{cyc}"
    group = apis.PodGroup(name, queue=queue, min_member=2)
    pods = [apis.Pod(f"{name}-{t}", name, apis.ResourceVec(1, 1, 4))
            for t in range(2)]
    cluster.submit(group, pods)


def _last_cycle_events(sched):
    evs = sched.decisions.events(limit=100000)
    if not evs:
        return []
    last = max(e["cycle"] for e in evs)
    return sorted((e["gang"], e["queue"], e["outcome"], e["detail"])
                  for e in evs if e["cycle"] == last)


def test_soak_resident_bit_exact_vs_rebuild_twin_one_dispatch():
    """ROADMAP-1 acceptance: ≥20 churn cycles where the resident path
    is bit-exact against a full-rebuild twin, every steady resident
    cycle is ONE watched dispatch + ONE device_put whose bytes equal
    the packed delta size, and a forced mid-soak structural change
    falls back to the full build and recovers to resident mode."""
    num_nodes = 24
    c_res = _steady_cluster(num_nodes=num_nodes)
    c_twin = copy.deepcopy(c_res)
    s_res = Scheduler(SchedulerConfig(resident=True))
    s_twin = Scheduler(SchedulerConfig(incremental=False))
    rng_a = np.random.default_rng(7)
    rng_b = np.random.default_rng(7)
    resident_cycles = 0
    resident_cycles_with_binds = 0
    structural_at = 11
    recovered_after_structural = False
    late_misses = 0
    for cyc in range(24):
        rep = WATCHER.report()["entries"]
        calls_before = {k: v["calls"] for k, v in rep.items()}
        misses_before = rep.get("resident_cycle", {}).get("misses", 0)
        r1 = s_res.run_once(c_res)
        rep = WATCHER.report()["entries"]
        calls_after = {k: v["calls"] for k, v in rep.items()}
        if cyc >= 18:
            late_misses += (rep.get("resident_cycle", {})
                            .get("misses", 0) - misses_before)
        r2 = s_twin.run_once(c_twin)
        # --- bit-exactness: the whole commit surface -----------------
        assert r1.bind_requests == r2.bind_requests, cyc
        assert r1.evictions == r2.evictions, cyc
        assert r1.analytics == r2.analytics, cyc
        assert _last_cycle_events(s_res) == _last_cycle_events(s_twin), cyc
        last = s_res._snapshotter.stats.last
        if last["mode"] == "resident":
            resident_cycles += 1
            resident_cycles_with_binds += bool(r1.bind_requests)
            if cyc > structural_at:
                recovered_after_structural = True
            # --- exactly one watched jit dispatch --------------------
            dcalls = {k: calls_after.get(k, 0) - calls_before.get(k, 0)
                      for k in calls_after}
            dcalls = {k: v for k, v in dcalls.items() if v}
            assert dcalls == {"resident_cycle": 1}, (cyc, dcalls)
            # --- exactly one upload, bytes == packed delta size ------
            wire = r1.wire
            assert sorted(wire["by_reason"]) == [REASON_DELTA_APPLY], cyc
            da = wire["by_reason"][REASON_DELTA_APPLY]
            assert da["dispatches"] == 1, cyc
            assert da["bytes"] == last["bytes_shipped"] > 0, cyc
            assert wire["redundant_bytes"] == 0, cyc
            # --- the kai-resident payoff gauge pair ------------------
            # (reused == full resident snapshot: no snapshot leaf
            # touched the wire.  At toy scale the per-group bucket
            # floors dominate the delta, so delta ≪ snapshot is a
            # bench-scale property, not asserted here.)
            assert wire["resident_uploaded_bytes"] == da["bytes"], cyc
            assert (wire["resident_reused_bytes"]
                    == wire["resident_bytes"] > 0), cyc
        if cyc % 3 == 0:
            # fresh gangs through the journal append path — placed by
            # RESIDENT cycles (gang/pod adds are patchable)
            _submit_extra_gang(c_res, cyc)
            _submit_extra_gang(c_twin, cyc)
        if cyc == structural_at:
            # structural change on BOTH clusters: a new node appears —
            # unpatchable, the resident path must fall back whole
            for cl in (c_res, c_twin):
                node = apis.Node(f"node-{num_nodes}",
                                 apis.ResourceVec(8.0, 64.0, 256.0))
                cl.nodes[node.name] = node
                cl.journal.mark_structural("test-node-added")
        _churn(c_res, rng_a, 0.05, num_nodes)
        _churn(c_twin, rng_b, 0.05, num_nodes)
    assert resident_cycles >= 15, s_res._snapshotter.stats.fallbacks
    # the compare is about REAL decisions: resident cycles must have
    # actually placed work (restarted churn pods + appended gangs), not
    # matched an idle twin on empty lists
    assert resident_cycles_with_binds >= 8, resident_cycles_with_binds
    # the structural fallback actually fired and resident mode resumed
    assert "structural" in s_res._snapshotter.stats.fallbacks
    assert recovered_after_structural
    # bucket hysteresis holds: once settled, steady churn never flips
    # the fused entry's signature (a flip = full XLA recompile)
    assert late_misses == 0


def test_repack_fires_with_real_ages_on_nonanalytics_resident_cycle():
    """Regression: the frag streak completes at the end of an analytics
    cycle, so with ``analytics_every > 1`` the repack trigger typically
    fires on the NEXT (analytics-skipped) cycle.  On the resident path
    that cycle feeds the fused entry a zeros ages placeholder — the
    repack solve must still compute REAL pending ages (an all-zero
    vector fails ``plan_repack``'s target gate and burns the cooldown
    on an infeasible plan)."""
    from tests.test_repack import _frag_cluster, _repack_cfg
    import dataclasses

    from kai_scheduler_tpu.binder import Binder
    cluster = _frag_cluster()
    cfg = dataclasses.replace(_repack_cfg(), resident=True,
                              analytics_every=2)
    sched, binder = Scheduler(cfg), Binder()
    fired = placed = None
    fired_mode = None
    for cyc in range(1, 12):
        res = sched.run_once(cluster)
        if res.repack and fired is None:
            fired = cyc
            fired_mode = sched._snapshotter.stats.last["mode"]
            assert res.repack["feasible"], res.repack
            assert res.repack["target_gang"] == "big-gang"
            assert res.repack["migrations_executed"] > 0
        if sum(b.pod_name.startswith("big-")
               for b in res.bind_requests) >= 8:
            placed = cyc
            break
        binder.reconcile(cluster)
        cluster.tick()
    assert fired is not None, "repack never fired"
    # the scenario's point: the firing landed on a RESIDENT cycle (the
    # fused entry ran with the zeros placeholder) and the solve still
    # saw real ages
    assert fired_mode == "resident", fired_mode
    assert placed is not None and placed >= fired


def test_resident_verify_mode_passes_and_catches_divergence():
    cluster = _steady_cluster(num_nodes=8, num_gangs=8)
    sched = Scheduler(SchedulerConfig(resident=True,
                                      verify_incremental=True))
    rng = np.random.default_rng(3)
    sched.run_once(cluster)
    for _ in range(3):
        _churn(cluster, rng, 0.1, 8)
        sched.run_once(cluster)  # verify gathers + compares each cycle
    snap = sched._snapshotter
    assert snap.stats.patched >= 1
    # corrupt ONE mirror element: the gather-and-compare must catch it
    snap._host.nodes.free.reshape(-1)[0] += 1.0
    with pytest.raises(IncrementalVerifyError, match="resident leaf"):
        snap.verify_device_residency()


def test_desync_guard_forces_full_rebuild():
    """A staged delta that was never adopted (aborted cycle) must not
    leave the mirror ahead of the device: the next resident refresh
    rebuilds in full instead of diffing against a future the device
    never saw."""
    cluster = _steady_cluster(num_nodes=8, num_gangs=8)
    snap = IncrementalSnapshotter()
    rr = snap.refresh_resident(cluster, now=cluster.now)
    assert rr.mode == "full"
    cluster.tick()
    rr = snap.refresh_resident(cluster, now=cluster.now)
    assert rr.mode == "resident"
    # abort: no adopt_device_state — the guard is armed
    cluster.tick()
    rr = snap.refresh_resident(cluster, now=cluster.now)
    assert rr.mode == "full"
    assert "resident-desync" in snap.stats.fallbacks
    # a clean staged+adopted cycle resumes resident mode
    cluster.tick()
    rr = snap.refresh_resident(cluster, now=cluster.now)
    assert rr.mode == "resident"
    from kai_scheduler_tpu.ops.resident import apply_delta
    snap.adopt_device_state(
        jax.jit(apply_delta)(snap.device_state, rr.delta))
    snap.verify_device_residency()  # device == mirror after adopt


def test_delta_upload_is_transient_on_the_ledger():
    """Delta uploads ride the wire books (bytes/dispatches) but never
    join the device-residency watermark — donated consumable buffers
    must not double-count against the resident snapshot."""
    before = LEDGER.residency()["bytes"]
    out = LEDGER.device_put(
        {"idx": np.zeros((64,), np.int32),
         "val": np.zeros((64,), np.float32)},
        reason=REASON_DELTA_APPLY, site="delta-test", transient=True)
    assert int(np.asarray(out["idx"]).sum()) == 0
    after = LEDGER.residency()["bytes"]
    assert after == before
    totals = LEDGER.totals()["by_reason"][REASON_DELTA_APPLY]
    assert totals["bytes"] >= 64 * 8

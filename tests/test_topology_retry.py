"""In-cycle domain retry for fragmented required topology — VERDICT r2
item 9: a fragmented fullest domain must not cost the gang a cycle when
the next-fullest domain fits (ref allocateSubGroupSet's per-subset
checkpoint/rollback search)."""
from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime.cluster import Cluster


def _node(name, rack, accel, used=0.0):
    return apis.Node(
        name=name, allocatable=apis.ResourceVec(accel, 32.0, 128.0),
        labels={"rack": rack, "kubernetes.io/hostname": name})


def test_fragmented_fullest_domain_retries_next():
    """rack-a is the binpack-preferred domain (6 accel free, exactly the
    gang's total) but fragmented — no node fits the 4-accel task; rack-b
    (8 free) does.  The gang locks rack-a first, fails the fill, and
    must land wholly in rack-b within the SAME cycle."""
    topology = apis.Topology(name="default",
                             levels=["rack", "kubernetes.io/hostname"])
    nodes = [
        _node("a0", "rack-a", 2.0), _node("a1", "rack-a", 2.0),
        _node("a2", "rack-a", 2.0),
        _node("b0", "rack-b", 4.0), _node("b1", "rack-b", 4.0),
    ]
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=16.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=16.0))]
    pg = apis.PodGroup(
        name="gang", queue="q", min_member=2,
        topology_constraint=apis.TopologyConstraint(
            topology="default", required_level="rack"))
    pods = [
        apis.Pod(name="t0-small", group="gang",
                 resources=apis.ResourceVec(2.0, 1.0, 1.0)),
        apis.Pod(name="t1-big", group="gang",
                 resources=apis.ResourceVec(4.0, 1.0, 1.0)),
    ]
    cluster = Cluster.from_objects(nodes, queues, [pg], pods, topology)
    res = Scheduler().run_once(cluster)
    by_name = {b.pod_name: b.selected_node for b in res.bind_requests}
    assert set(by_name) == {"t0-small", "t1-big"}, by_name
    assert all(n.startswith("b") for n in by_name.values()), by_name


def test_binpack_prefers_most_packed_fitting_domain():
    """Domain choice binpacks: the domain with the LEAST free capacity
    that still fits the gang wins (ref topology/node_scoring.go domain
    ordering) — rack-b (6 free, fits exactly) beats rack-a (8 free)."""
    topology = apis.Topology(name="default",
                             levels=["rack", "kubernetes.io/hostname"])
    nodes = [
        _node("a0", "rack-a", 4.0), _node("a1", "rack-a", 4.0),
        _node("b0", "rack-b", 4.0), _node("b1", "rack-b", 2.0),
    ]
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=16.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=16.0))]
    pg = apis.PodGroup(
        name="gang", queue="q", min_member=2,
        topology_constraint=apis.TopologyConstraint(
            topology="default", required_level="rack"))
    pods = [
        apis.Pod(name="t0-small", group="gang",
                 resources=apis.ResourceVec(2.0, 1.0, 1.0)),
        apis.Pod(name="t1-big", group="gang",
                 resources=apis.ResourceVec(4.0, 1.0, 1.0)),
    ]
    cluster = Cluster.from_objects(nodes, queues, [pg], pods, topology)
    res = Scheduler().run_once(cluster)
    by_name = {b.pod_name: b.selected_node for b in res.bind_requests}
    assert set(by_name) == {"t0-small", "t1-big"}
    assert all(n.startswith("b") for n in by_name.values()), by_name

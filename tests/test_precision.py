"""f32 precision bounds at production scale (SURVEY §7 hard-part 5).

The reference runs its fairness/victim arithmetic in Go float64
(``resource_division.go:26-41``); the TPU kernels run f32.  These
property tests pin the divergence:

- the hierarchical DRF division's f32 result tracks the SAME algorithm
  evaluated in f64 to ~1 ulp at contended GiB-scale shapes;
- the victims' 50k-unit cumulative tables use the compensated
  double-single scan (``utils.numerics.cumsum_ds``), which tracks a
  numpy float64 reference orders of magnitude tighter than the plain
  f32 scan whose tail error (~1.4 GiB measured) exceeded a small pod's
  request.
"""
import jax
import jax.experimental
import jax.numpy as jnp
import numpy as np

from kai_scheduler_tpu.framework.session import Session
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.state import make_cluster
from kai_scheduler_tpu.utils.numerics import cumsum_ds

import pytest

pytestmark = pytest.mark.core


def _to64(tree):
    return jax.tree.map(
        lambda a: jnp.asarray(np.asarray(a), jnp.float64)
        if a.dtype == jnp.float32 else jnp.asarray(np.asarray(a)), tree)


def test_drf_f32_tracks_f64_at_contended_scale():
    """128 queues in 8 departments with messy GiB-scale requests and
    quotas: the f32 division stays within 1e-6 relative of the f64 run
    of the same passes (deserved, water-fill, remainders)."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=1000, node_accel=8.0, num_gangs=3000, tasks_per_gang=4,
        num_departments=8, queues_per_department=16)
    ses = Session.open(nodes, queues, groups, pods, topo)
    q = ses.state.queues
    rng = np.random.default_rng(3)
    req = np.asarray(q.request)
    messy_req = np.where(req > 0, rng.uniform(0.3, 900.0, req.shape), req)
    quota = np.asarray(q.quota)
    messy_quota = np.where(quota > 0, rng.uniform(1.0, 500.0, quota.shape),
                           quota)
    state32 = ses.state.replace(queues=q.replace(
        request=jnp.asarray(messy_req, jnp.float32),
        quota=jnp.asarray(messy_quota, jnp.float32)))
    fs32 = np.asarray(drf.set_fair_share(state32, num_levels=2))

    with jax.experimental.enable_x64(True):
        state64 = ses.state.replace(
            queues=_to64(q).replace(
                request=jnp.asarray(messy_req, jnp.float64),
                quota=jnp.asarray(messy_quota, jnp.float64)),
            nodes=_to64(ses.state.nodes))
        fs64 = np.asarray(drf.set_fair_share(state64, num_levels=2))

    rel = np.abs(fs32 - fs64) / np.maximum(np.abs(fs64), 1.0)
    assert rel.max() < 1e-6, rel.max()
    assert np.abs(fs32 - fs64).max() < 1e-2, np.abs(fs32 - fs64).max()


def test_victim_cumulative_tables_track_f64():
    """50k GiB-scale unit requests (the reclaim tables' shape): the
    compensated scan matches numpy float64 to ≤1e-3 absolute, where the
    plain f32 scan drifts by more than a small pod's request."""
    rng = np.random.default_rng(7)
    M = 50_000
    vals = np.stack([
        rng.uniform(0.1, 8.0, M),      # accel fractions
        rng.uniform(0.25, 64.0, M),    # cpu cores
        rng.uniform(0.5, 256.0, M),    # mem GiB
    ], axis=1)
    ref = np.cumsum(vals, axis=0)                    # float64
    comp = np.asarray(cumsum_ds(jnp.asarray(vals, jnp.float32), axis=0))
    plain = np.asarray(jnp.cumsum(jnp.asarray(vals, jnp.float32), axis=0))
    comp_err = np.abs(comp - ref).max()
    plain_err = np.abs(plain - ref).max()
    # representation of the f32 OUTPUT alone costs ~rel 6e-8 of the
    # ~6.4M tail => ~0.4; the compensated scan must sit at that floor
    tail = ref[-1].max()
    assert comp_err <= tail * 1.2e-7 + 1e-3, (comp_err, tail)
    assert comp_err < plain_err, (comp_err, plain_err)


def test_two_sum_carries_residue_exactly():
    """The compensated scan recovers a tiny addend buried under a large
    prefix — the failure mode of the plain f32 scan."""
    big = np.float32(2.0**22)
    x = jnp.asarray([big, 0.25, 0.25, 0.25, 0.25], jnp.float32)
    out = np.asarray(cumsum_ds(x))
    # plain f32: each +0.25 rounds away against 2^22 (ulp = 0.5)
    plain = np.asarray(jnp.cumsum(x))
    assert out[-1] == np.float32(2.0**22 + 1.0), out
    assert plain[-1] == big, plain

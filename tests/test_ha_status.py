"""HA leader election + async status updater — ref
``cmd/scheduler/app/server.go:60-63`` and ``cache/status_updater``."""
import time

from kai_scheduler_tpu.framework.scheduler import Scheduler, SchedulerConfig
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.leader import Lease
from kai_scheduler_tpu.runtime.status_updater import AsyncStatusUpdater
from kai_scheduler_tpu.state import make_cluster


def _cluster():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=4.0, num_gangs=2, tasks_per_gang=2)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_single_leader_commits():
    """Two Scheduler instances sharing one lease: only the leader binds —
    never both (the VERDICT r2 item-7 'done' bar)."""
    cluster = _cluster()
    lease = Lease()
    s1 = Scheduler(SchedulerConfig(leader_lease=lease, identity="a"))
    s2 = Scheduler(SchedulerConfig(leader_lease=lease, identity="b"))
    r1 = s1.run_once(cluster)
    r2 = s2.run_once(cluster)
    assert len(r1.bind_requests) == 4
    assert r2.bind_requests == [] and r2.tensors is None  # follower idle
    # every pod got exactly ONE bind request — no double commit
    assert len(cluster.bind_requests) == 4


def test_leader_failover_on_expiry():
    cluster = _cluster()
    lease = Lease(duration_s=15.0)
    s1 = Scheduler(SchedulerConfig(leader_lease=lease, identity="a"))
    s2 = Scheduler(SchedulerConfig(leader_lease=lease, identity="b"))
    assert s1.run_once(cluster).tensors is not None
    # leader a dies; b takes over once the lease expires
    cluster.now += 16.0
    assert s2.run_once(cluster).tensors is not None
    assert lease.holder == "b"
    # a comes back but is now a follower
    assert s1.run_once(cluster).tensors is None


def test_resign_hands_off_immediately():
    lease = Lease()
    assert lease.try_acquire_or_renew("a", 0.0)
    lease.release("a")
    assert lease.try_acquire_or_renew("b", 0.1)


def test_async_status_updates_off_cycle_path():
    """Cycle wall time must be independent of status-write latency; the
    writes land once the pool drains."""
    cluster = _cluster()
    # an unschedulable gang: request exceeds every node
    from kai_scheduler_tpu.apis import types as apis
    for p in cluster.pods.values():
        if p.group == "gang-1":
            p.resources = apis.ResourceVec(99.0, p.resources.cpu,
                                           p.resources.memory)
    updater = AsyncStatusUpdater(workers=2)
    # the delay must dominate scheduler wall-time noise on a loaded CI
    # machine (a cycle alone measured ~0.4 s under 3 concurrent suites)
    slow = {"delay": 1.5}
    orig_enqueue = updater.enqueue

    def slow_enqueue(key, apply):
        def wrapped():
            time.sleep(slow["delay"])
            apply()
        orig_enqueue(key, wrapped)

    updater.enqueue = slow_enqueue
    sched = Scheduler(status_updater=updater)
    sched.run_once(cluster)  # compile
    t0 = time.perf_counter()
    sched.run_once(cluster)
    cycle_s = time.perf_counter() - t0
    assert updater.flush(10.0)
    group = cluster.pod_groups["gang-1"]
    assert group.fit_failures >= 1 and group.unschedulable_reason
    # the per-write latency must not appear in the cycle wall time (a
    # synchronous path would cost >= one 1.5 s write)
    assert cycle_s < slow["delay"]
    updater.stop()


def test_coalescing_keeps_latest():
    updater = AsyncStatusUpdater(workers=1)
    state = {"v": 0}
    # saturate the single worker so queued updates coalesce
    updater.enqueue("block", lambda: time.sleep(0.2))
    for i in range(1, 6):
        def setv(i=i):
            state["v"] = i
        updater.enqueue("k", setv)
    assert updater.flush(5.0)
    assert state["v"] == 5
    updater.stop()

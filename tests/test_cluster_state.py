"""Snapshot-builder tests — analogue of the reference's cluster_info tests
(``pkg/scheduler/cache/cluster_info/cluster_info_test.go``)."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.state import build_snapshot, make_cluster

import pytest

pytestmark = pytest.mark.core


def test_build_snapshot_shapes_and_padding():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=10, num_gangs=5, tasks_per_gang=3)
    state, index = build_snapshot(nodes, queues, groups, pods, topo)
    assert state.nodes.valid.shape[0] >= 10
    assert int(state.nodes.valid.sum()) == 10
    assert int(state.gangs.valid.sum()) == 5
    assert int(state.gangs.task_valid.sum()) == 15
    assert len(index.node_names) == 10


def test_total_capacity_ignores_padding():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, node_cpu=32.0, node_mem=128.0)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    cap = np.asarray(state.total_capacity)
    np.testing.assert_allclose(cap, [32.0, 128.0, 512.0])


def test_running_pods_reduce_free_and_fill_queue_allocated():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, num_gangs=4, tasks_per_gang=2, running_fraction=0.5,
        task_accel=1.0)
    state, index = build_snapshot(nodes, queues, groups, pods, topo)
    assert int(state.running.valid.sum()) == 4  # 2 gangs x 2 tasks
    free = np.asarray(state.nodes.free)
    alloc = np.asarray(state.nodes.allocatable)
    assert (free <= alloc).all()
    # total allocated accel across queues at leaf level == 4 devices
    q = state.queues
    leaf = (np.asarray(q.depth) == 1) & np.asarray(q.valid)
    assert np.asarray(q.allocated)[leaf, apis.RESOURCE_ACCEL].sum() == 4.0
    # and the department level rolls up the same total
    top = (np.asarray(q.depth) == 0) & np.asarray(q.valid)
    assert np.asarray(q.allocated)[top, apis.RESOURCE_ACCEL].sum() == 4.0


def test_queue_request_includes_pending():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=2, num_gangs=2, tasks_per_gang=2, task_accel=1.0)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    q = state.queues
    top = (np.asarray(q.depth) == 0) & np.asarray(q.valid)
    assert np.asarray(q.request)[top, apis.RESOURCE_ACCEL].sum() == 4.0


def test_topology_domains_nest():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=16, topology_levels=(2, 2))
    state, index = build_snapshot(nodes, queues, groups, pods, topo)
    t = np.asarray(state.nodes.topology)[:16]
    # level 0 has 2 domains, level 1 has 4 distinct domains
    assert len(np.unique(t[:, 0])) == 2
    assert len(np.unique(t[:, 1])) == 4
    # nodes sharing a level-1 domain must share the level-0 domain
    for d in np.unique(t[:, 1]):
        rows = t[t[:, 1] == d]
        assert len(np.unique(rows[:, 0])) == 1


def test_plain_gang_running_pods_fill_default_subgroup_quorum():
    """Running pods of a gang with no declared subgroups must count
    toward the default subgroup slot 0 (regression: a fast-path guard
    skipped them, inflating subgroup_min_needed to the full minMember)."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    groups = [apis.PodGroup("g", queue="q", min_member=4,
                            last_start_timestamp=0.0)]
    pods = [apis.Pod(f"r{i}", "g", apis.ResourceVec(1, 1, 1),
                     status=apis.PodStatus.RUNNING, node="n0")
            for i in range(3)]
    pods += [apis.Pod(f"p{i}", "g", apis.ResourceVec(1, 1, 1))
             for i in range(3)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    assert int(np.asarray(state.gangs.subgroup_min_needed)[0, 0]) == 1
    assert int(np.asarray(state.gangs.min_needed)[0]) == 1


def test_runtime_seconds_precision_at_unix_epoch_scale():
    """runtime_s must not quantize to float32 at unix-timestamp scale
    (regression: 90s became 128s, corrupting minruntime windows)."""
    start = 1753800000.0
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    groups = [apis.PodGroup("g", queue="q", min_member=1,
                            last_start_timestamp=start)]
    pods = [apis.Pod("r0", "g", apis.ResourceVec(1, 1, 1),
                     status=apis.PodStatus.RUNNING, node="n0")]
    state, _ = build_snapshot(nodes, queues, groups, pods, now=start + 90.0)
    assert abs(float(np.asarray(state.running.runtime_s)[0]) - 90.0) < 1.0

"""Snapshot-builder tests — analogue of the reference's cluster_info tests
(``pkg/scheduler/cache/cluster_info/cluster_info_test.go``)."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.state import build_snapshot, make_cluster


def test_build_snapshot_shapes_and_padding():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=10, num_gangs=5, tasks_per_gang=3)
    state, index = build_snapshot(nodes, queues, groups, pods, topo)
    assert state.nodes.valid.shape[0] >= 10
    assert int(state.nodes.valid.sum()) == 10
    assert int(state.gangs.valid.sum()) == 5
    assert int(state.gangs.task_valid.sum()) == 15
    assert len(index.node_names) == 10


def test_total_capacity_ignores_padding():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, node_cpu=32.0, node_mem=128.0)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    cap = np.asarray(state.total_capacity)
    np.testing.assert_allclose(cap, [32.0, 128.0, 512.0])


def test_running_pods_reduce_free_and_fill_queue_allocated():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, num_gangs=4, tasks_per_gang=2, running_fraction=0.5,
        task_accel=1.0)
    state, index = build_snapshot(nodes, queues, groups, pods, topo)
    assert int(state.running.valid.sum()) == 4  # 2 gangs x 2 tasks
    free = np.asarray(state.nodes.free)
    alloc = np.asarray(state.nodes.allocatable)
    assert (free <= alloc).all()
    # total allocated accel across queues at leaf level == 4 devices
    q = state.queues
    leaf = (np.asarray(q.depth) == 1) & np.asarray(q.valid)
    assert np.asarray(q.allocated)[leaf, apis.RESOURCE_ACCEL].sum() == 4.0
    # and the department level rolls up the same total
    top = (np.asarray(q.depth) == 0) & np.asarray(q.valid)
    assert np.asarray(q.allocated)[top, apis.RESOURCE_ACCEL].sum() == 4.0


def test_queue_request_includes_pending():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=2, num_gangs=2, tasks_per_gang=2, task_accel=1.0)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    q = state.queues
    top = (np.asarray(q.depth) == 0) & np.asarray(q.valid)
    assert np.asarray(q.request)[top, apis.RESOURCE_ACCEL].sum() == 4.0


def test_topology_domains_nest():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=16, topology_levels=(2, 2))
    state, index = build_snapshot(nodes, queues, groups, pods, topo)
    t = np.asarray(state.nodes.topology)[:16]
    # level 0 has 2 domains, level 1 has 4 distinct domains
    assert len(np.unique(t[:, 0])) == 2
    assert len(np.unique(t[:, 1])) == 4
    # nodes sharing a level-1 domain must share the level-0 domain
    for d in np.unique(t[:, 1]):
        rows = t[t[:, 1] == d]
        assert len(np.unique(rows[:, 0])) == 1

"""Multi-device mesh sharding tests (8 virtual CPU devices via conftest).

Validates SURVEY.md §2.9: the node axis of the cluster tensors shards
over a ``jax.sharding.Mesh`` and the full scheduling step produces
placements identical to the unsharded run — the sharded kernels are a
pure layout change, not a semantic one.  Reuses the cycle/state builders
from ``__graft_entry__`` so the tested path is exactly the one the
driver dry-runs.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge
from kai_scheduler_tpu.parallel import make_mesh, shard_state, state_shardings


@pytest.fixture(scope="module")
def eight_devices():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs 8 virtual devices (conftest XLA_FLAGS)")
    return devs[:8]


def test_sharded_cycle_matches_unsharded(eight_devices):
    mesh = make_mesh(eight_devices)
    state = ge._make_state(num_nodes=24, num_gangs=12, tasks_per_gang=2,
                           pad=8)
    cycle = ge._cycle_fn()

    base_placements, base_allocated, base_free = jax.jit(cycle)(state)

    sharded = shard_state(state, mesh)
    fn = jax.jit(cycle, in_shardings=(state_shardings(state, mesh),))
    placements, allocated, free = fn(sharded)

    np.testing.assert_array_equal(np.asarray(placements),
                                  np.asarray(base_placements))
    np.testing.assert_array_equal(np.asarray(allocated),
                                  np.asarray(base_allocated))
    np.testing.assert_allclose(np.asarray(free), np.asarray(base_free),
                               atol=1e-4)
    assert bool(jnp.any(allocated))


def test_shard_state_places_node_axis(eight_devices):
    mesh = make_mesh(eight_devices)
    state = ge._make_state(num_nodes=24, num_gangs=4, tasks_per_gang=2,
                           pad=8)
    sharded = shard_state(state, mesh)
    sh = sharded.nodes.free.sharding
    # node axis split across the mesh, trailing axes replicated
    assert sh.shard_shape(sharded.nodes.free.shape)[0] \
        == sharded.nodes.free.shape[0] // mesh.size
    # non-node tensors replicated
    assert sharded.gangs.task_req.sharding.is_fully_replicated


def test_shard_state_rejects_indivisible_axis(eight_devices):
    mesh = make_mesh(eight_devices)
    # 20 nodes with pad=4 stays 20 — not divisible by the 8-way mesh
    state = ge._make_state(num_nodes=20, num_gangs=4, tasks_per_gang=2,
                           pad=4)
    assert state.nodes.valid.shape[0] % mesh.size != 0
    with pytest.raises(ValueError, match="not divisible"):
        shard_state(state, mesh)


def test_dryrun_multichip_entrypoint(eight_devices):
    ge.dryrun_multichip(8)

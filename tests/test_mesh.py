"""Multi-device mesh sharding tests (virtual CPU devices via conftest).

Validates SURVEY.md §2.9: the node axis of the cluster tensors shards
over a ``jax.sharding.Mesh`` and the full scheduling step produces
placements identical to the unsharded run — the sharded kernels are a
pure layout change, not a semantic one.  Reuses the cycle/state builders
from ``__graft_entry__`` so the tested path is exactly the one the
driver dry-runs.

Also pins ``state_shardings`` against the kai-comms seed registry in
BOTH directions (meta-test): the auditor's inferred seed specs are only
trustworthy while they agree leaf-for-leaf with the layout the mesh
module actually declares.
"""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

import __graft_entry__ as ge
from kai_scheduler_tpu.parallel import make_mesh, shard_state, state_shardings
from kai_scheduler_tpu.parallel.mesh import VIRTUAL_DEVICE_COUNT


def test_sharded_cycle_matches_unsharded(virtual_devices):
    mesh = make_mesh(virtual_devices)
    state = ge._make_state(num_nodes=24, num_gangs=12, tasks_per_gang=2,
                           pad=8)
    cycle = ge._cycle_fn()

    base_placements, base_allocated, base_free = jax.jit(cycle)(state)

    sharded = shard_state(state, mesh)
    fn = jax.jit(cycle, in_shardings=(state_shardings(state, mesh),))
    placements, allocated, free = fn(sharded)

    np.testing.assert_array_equal(np.asarray(placements),
                                  np.asarray(base_placements))
    np.testing.assert_array_equal(np.asarray(allocated),
                                  np.asarray(base_allocated))
    np.testing.assert_allclose(np.asarray(free), np.asarray(base_free),
                               atol=1e-4)
    assert bool(jnp.any(allocated))


def test_shard_state_places_node_axis(virtual_devices):
    mesh = make_mesh(virtual_devices)
    state = ge._make_state(num_nodes=24, num_gangs=4, tasks_per_gang=2,
                           pad=8)
    sharded = shard_state(state, mesh)
    sh = sharded.nodes.free.sharding
    # node axis split across the mesh, trailing axes replicated
    assert sh.shard_shape(sharded.nodes.free.shape)[0] \
        == sharded.nodes.free.shape[0] // mesh.size
    # non-node tensors replicated
    assert sharded.gangs.task_req.sharding.is_fully_replicated


def test_shard_state_rejects_indivisible_axis(virtual_devices):
    mesh = make_mesh(virtual_devices)
    # 20 nodes with pad=4 stays 20 — not divisible by the 8-way mesh
    state = ge._make_state(num_nodes=20, num_gangs=4, tasks_per_gang=2,
                           pad=4)
    assert state.nodes.valid.shape[0] % mesh.size != 0
    with pytest.raises(ValueError, match="not divisible"):
        shard_state(state, mesh)


def test_dryrun_multichip_entrypoint(virtual_devices):
    ge.dryrun_multichip(VIRTUAL_DEVICE_COUNT)


def test_state_shardings_pins_comms_seed_registry(virtual_devices):
    """Meta-test: mesh.state_shardings and comms.seed_state_specs agree
    leaf-for-leaf, both directions.  A new NodeState field with the node
    axis somewhere other than dim 0 must be registered in BOTH modules
    (NODE_AXIS_SECOND in comms.py, the replace() in state_shardings) —
    this test is the tripwire."""
    from kai_scheduler_tpu.analysis import comms

    mesh = make_mesh(virtual_devices)
    state = ge._make_state(num_nodes=24, num_gangs=4, tasks_per_gang=2,
                           pad=8)

    declared = state_shardings(state, mesh)
    seeds = comms.seed_state_specs(state)

    decl_leaves, decl_tree = jax.tree_util.tree_flatten_with_path(declared)
    seed_leaves, seed_tree = jax.tree_util.tree_flatten_with_path(seeds)
    # direction 1: same pytree structure — a leaf present in one view
    # but not the other is itself drift
    assert decl_tree == seed_tree
    arr_leaves = jax.tree_util.tree_leaves(state)
    assert len(arr_leaves) == len(decl_leaves)

    for (path, sharding), (_, seed), arr in zip(
            decl_leaves, seed_leaves, arr_leaves):
        ndim = np.ndim(arr)
        spec = sharding.spec
        decl_dims = tuple(spec) + (None,) * (ndim - len(tuple(spec)))
        decl_dims = tuple(d[0] if isinstance(d, tuple) else d
                          for d in decl_dims)
        # direction 2: per-leaf exact equality of the partition dims
        assert decl_dims == seed.dims, (
            f"{jax.tree_util.keystr(path)}: declared {decl_dims} "
            f"!= inferred seed {seed.dims}")

    # and the full-state KAI302 check (what the CLI runs) agrees: clean
    assert comms.check_declared_shardings(state, mesh=mesh) == []

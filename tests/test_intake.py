"""Podgrouper + admission tests — ref ``pkg/podgrouper`` plugin tests
(one per workload kind) and ``pkg/admission`` webhook tests."""
import pytest

from kai_scheduler_tpu.admission import (AdmissionError, PodMutator,
                                         PodValidator)
from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.podgrouper import (GrouperHub, PodGroupReconciler,
                                          Workload)
from kai_scheduler_tpu.runtime.cluster import Cluster

pytestmark = pytest.mark.core

Vec = apis.ResourceVec


def pods_for(name, n):
    return [apis.Pod(f"{name}-{i}", "", resources=Vec(1.0, 1.0, 1.0))
            for i in range(n)]


class TestGroupers:
    def setup_method(self):
        self.hub = GrouperHub()

    def test_catalog_covers_reference_kinds(self):
        # the workload-kind catalog from SURVEY.md §2.8
        for kind in ["Pod", "Job", "CronJob", "Deployment", "RunaiJob",
                     "AMLJob", "PyTorchJob", "TFJob", "XGBoostJob",
                     "MPIJob", "JAXJob", "Notebook", "RayCluster",
                     "RayJob", "RayService", "SparkApplication", "JobSet",
                     "LeaderWorkerSet", "PodGangSet", "Revision",
                     "SpotRequest"]:
            assert kind in self.hub.kinds(), kind

    def test_pytorch_job_replicas(self):
        w = Workload(kind="PyTorchJob", name="train",
                     labels={"kai.scheduler/queue": "team-a"},
                     spec={"pytorchReplicaSpecs": {
                         "Master": {"replicas": 1},
                         "Worker": {"replicas": 3}}})
        group = self.hub.group(w, pods_for("train", 4))
        assert group.min_member == 4
        assert group.queue == "team-a"
        assert {s.name for s in group.sub_groups} == {"master", "worker"}

    def test_jax_job_min_available_override(self):
        w = Workload(kind="JAXJob", name="train",
                     spec={"jaxReplicaSpecs": {"Worker": {"replicas": 8}},
                           "runPolicy": {"minAvailable": 6}})
        group = self.hub.group(w, pods_for("train", 8))
        assert group.min_member == 6      # elastic: 6 of 8 suffice

    def test_ray_cluster_min_replicas(self):
        w = Workload(kind="RayCluster", name="rc",
                     spec={"workerGroupSpecs": [
                         {"groupName": "small", "replicas": 4,
                          "minReplicas": 2},
                         {"groupName": "big", "replicas": 2}]})
        group = self.hub.group(w, pods_for("rc", 7))
        assert group.min_member == 1 + 2 + 2    # head + mins

    def test_jobset_replicated_jobs(self):
        w = Workload(kind="JobSet", name="js",
                     spec={"replicatedJobs": [
                         {"name": "a", "replicas": 2,
                          "template": {"spec": {"parallelism": 3}}},
                         {"name": "b", "replicas": 1}]})
        group = self.hub.group(w, pods_for("js", 7))
        assert group.min_member == 7

    def test_leader_worker_set(self):
        w = Workload(kind="LeaderWorkerSet", name="lws",
                     spec={"leaderWorkerTemplate": {"size": 5}})
        group = self.hub.group(w, pods_for("lws", 5))
        assert group.min_member == 5

    def test_spark_driver_plus_executors(self):
        w = Workload(kind="SparkApplication", name="spark",
                     spec={"executor": {"instances": 4}})
        group = self.hub.group(w, pods_for("spark", 5))
        assert group.min_member == 5

    def test_notebook_nonpreemptible(self):
        w = Workload(kind="Notebook", name="nb")
        group = self.hub.group(w, pods_for("nb", 1))
        assert group.preemptibility == apis.Preemptibility.NON_PREEMPTIBLE

    def test_owner_chain_resolution(self):
        job = Workload(kind="Job", name="step",
                       spec={"parallelism": 2},
                       owner=Workload(kind="CronJob", name="nightly",
                                      spec={"jobTemplate": {
                                          "spec": {"parallelism": 2}}}))
        group = self.hub.group(job, pods_for("j", 2))
        assert "cronjob" in group.name

    def test_skip_top_owner(self):
        # Argo Workflow owns a Job: grouping stops at the Job
        job = Workload(kind="Job", name="wf-step", spec={"parallelism": 3},
                       owner=Workload(kind="Workflow", name="wf"))
        group = self.hub.group(job, pods_for("j", 3))
        assert group.min_member == 3
        assert "job" in group.name

    def test_topology_annotations(self):
        w = Workload(kind="Job", name="j", spec={"parallelism": 2},
                     annotations={
                         "kai.scheduler/topology-required-level": "rack"})
        group = self.hub.group(w, pods_for("j", 2))
        assert group.topology_constraint.required_level == "rack"

    def test_unknown_kind_falls_back_to_default(self):
        w = Workload(kind="SomethingNew", name="x")
        group = self.hub.group(w, pods_for("x", 1))
        assert group.min_member == 1


class TestReconciler:
    def test_submit_workload_creates_group_and_pods(self):
        cluster = Cluster()
        rec = PodGroupReconciler()
        pods = pods_for("train", 4)
        w = Workload(kind="PyTorchJob", name="train",
                     spec={"pytorchReplicaSpecs": {
                         "Worker": {"replicas": 4}}})
        group = rec.submit_workload(cluster, w, pods)
        assert group.name in cluster.pod_groups
        assert all(p.group == group.name for p in pods)
        assert len(cluster.pods) == 4

    def test_orphan_pods_get_group(self):
        cluster = Cluster()
        pod = apis.Pod("orphan", "some-group",
                       resources=Vec(1.0, 1.0, 1.0))
        cluster.pods[pod.name] = pod
        created = PodGroupReconciler().reconcile(cluster)
        assert len(created) == 1
        assert "some-group" in cluster.pod_groups


class TestAdmission:
    def test_mutator_translates_fraction_annotation(self):
        pod = apis.Pod("p", "g")
        PodMutator().mutate(pod, annotations={
            "kai.scheduler/accel-fraction": "0.5"})
        assert pod.accel_portion == 0.5

    def test_mutator_node_selector(self):
        pod = apis.Pod("p", "g")
        PodMutator().mutate(pod, annotations={
            "kai.scheduler/node-selector": "pool=a, zone=z1"})
        assert pod.node_selector == {"pool": "a", "zone": "z1"}

    def test_validator_rejects_bad_fractions(self):
        v = PodValidator()
        with pytest.raises(AdmissionError):
            v.validate(apis.Pod("p", "g", accel_portion=1.5))
        with pytest.raises(AdmissionError):
            v.validate(apis.Pod("p", "g", accel_portion=-0.1))
        with pytest.raises(AdmissionError):
            v.validate(apis.Pod("p", "g", accel_portion=0.5,
                                accel_memory_gib=8.0))
        with pytest.raises(AdmissionError):
            v.validate(apis.Pod("p", "g", resources=Vec(1.0, 1, 1),
                                accel_portion=0.5))
        with pytest.raises(AdmissionError):
            v.validate(apis.Pod("p", "g", resources=Vec(1.5, 1, 1)))
        v.validate(apis.Pod("p", "g", accel_portion=0.5))  # ok
        v.validate(apis.Pod("p", "g", resources=Vec(2.0, 1, 1)))  # ok


class TestIntakeToScheduleFlow:
    def test_pytorch_job_schedules_as_gang(self):
        from kai_scheduler_tpu.binder import Binder
        from kai_scheduler_tpu.framework import Scheduler, SchedulerConfig
        from kai_scheduler_tpu.framework.session import SessionConfig

        cluster = Cluster.from_objects(
            [apis.Node("node-0", Vec(8.0, 64.0, 256.0))],
            [apis.Queue("team-a", accel=apis.QueueResource(quota=8.0))],
            [], [])
        rec = PodGroupReconciler()
        w = Workload(kind="PyTorchJob", name="train",
                     labels={"kai.scheduler/queue": "team-a"},
                     spec={"pytorchReplicaSpecs": {
                         "Master": {"replicas": 1},
                         "Worker": {"replicas": 3}}})
        pods = [apis.Pod(f"train-{i}", "", resources=Vec(2.0, 1.0, 4.0))
                for i in range(4)]
        rec.submit_workload(cluster, w, pods)

        sched = Scheduler(SchedulerConfig(
            actions=("allocate",), session=SessionConfig(num_levels=1)))
        r = sched.run_once(cluster)
        assert len(r.bind_requests) == 4
        Binder().reconcile(cluster)
        assert all(p.status == apis.PodStatus.BOUND
                   for p in cluster.pods.values())

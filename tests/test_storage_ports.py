"""VolumeBinding + NodePorts — ref the VolumeBinding/NodePorts entries
of the reference filter chain
(``k8s_internal/predicates/predicates.go:70-140``) and the
volume-binding binder plugin (``pkg/binder/plugins/``)."""
from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.binder.binder import Binder
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime.cluster import Cluster


def _zoned_cluster():
    nodes = [
        apis.Node(name=f"node-{z}-{i}",
                  allocatable=apis.ResourceVec(4.0, 32.0, 128.0),
                  labels={"topology.kubernetes.io/zone": f"zone-{z}"})
        for z in ("a", "b") for i in range(2)]
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=16.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=16.0))]
    cluster = Cluster.from_objects(nodes, queues, [], [])
    cluster.storage_classes["zonal-b"] = apis.StorageClass(
        name="zonal-b", bind_mode="WaitForFirstConsumer",
        allowed_topology={"topology.kubernetes.io/zone": "zone-b"})
    cluster.storage_classes["anywhere"] = apis.StorageClass(
        name="anywhere", bind_mode="WaitForFirstConsumer")
    return cluster


def _pvc_pod(cluster, name, pvc, sc, bound=False, affinity=None):
    cluster.volume_claims[pvc] = apis.PersistentVolumeClaim(
        name=pvc, storage_class=sc, bound=bound,
        node_affinity=affinity or {})
    group = apis.PodGroup(name=f"{name}-pg", queue="q", min_member=1)
    pod = apis.Pod(name=name, group=group.name,
                   resources=apis.ResourceVec(1.0, 1.0, 1.0),
                   volume_claims=[pvc])
    cluster.submit(group, [pod])
    return pod


def test_bound_pvc_pins_pod_to_volume_zone():
    """The VERDICT r2 item-5 'done' bar: a pod with a zone-bound PVC
    only lands in that zone."""
    cluster = _zoned_cluster()
    _pvc_pod(cluster, "p1", "pvc1", "anywhere", bound=True,
             affinity={"topology.kubernetes.io/zone": "zone-b"})
    res = Scheduler().run_once(cluster)
    assert len(res.bind_requests) == 1
    assert res.bind_requests[0].selected_node.startswith("node-b")


def test_unbound_wffc_claim_respects_class_topology_and_binds():
    cluster = _zoned_cluster()
    _pvc_pod(cluster, "p1", "pvc1", "zonal-b")
    res = Scheduler().run_once(cluster)
    assert res.bind_requests[0].selected_node.startswith("node-b")
    result = Binder().reconcile(cluster)
    assert result.bound == ["p1"]
    pvc = cluster.volume_claims["pvc1"]
    assert pvc.bound
    assert pvc.node_affinity == {"topology.kubernetes.io/zone": "zone-b"}


def test_volume_bind_rollback():
    """A failing later bind step unbinds the claims bound this attempt."""
    cluster = _zoned_cluster()
    pod = _pvc_pod(cluster, "p1", "pvc1", "anywhere")
    res = Scheduler().run_once(cluster)
    target = res.bind_requests[0].selected_node
    # sabotage the accel bind: fill the target node's devices
    blocker_pg = apis.PodGroup(name="blk-pg", queue="q", min_member=1)
    blocker = apis.Pod(name="blk", group="blk-pg",
                       resources=apis.ResourceVec(4.0, 1.0, 1.0),
                       status=apis.PodStatus.RUNNING, node=target,
                       accel_devices=[0, 1, 2, 3])
    cluster.pod_groups["blk-pg"] = blocker_pg
    cluster.pods["blk"] = blocker
    result = Binder().reconcile(cluster)
    assert result.retrying == ["p1"]
    pvc = cluster.volume_claims["pvc1"]
    assert not pvc.bound and pvc.node_affinity == {}
    assert pod.status == apis.PodStatus.PENDING


def test_node_ports_conflict_excludes_node():
    """NodePorts predicate: a pod needing a host port avoids nodes where
    a running pod already holds it."""
    nodes = [apis.Node(name=f"n{i}",
                       allocatable=apis.ResourceVec(4.0, 32.0, 128.0))
             for i in range(2)]
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=8.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=8.0))]
    rg = apis.PodGroup(name="rg", queue="q", min_member=1,
                       last_start_timestamp=0.0)
    holder = apis.Pod(name="holder", group="rg",
                      resources=apis.ResourceVec(1.0, 1.0, 1.0),
                      host_ports=[8080], status=apis.PodStatus.RUNNING,
                      node="n0", accel_devices=[0])
    pg = apis.PodGroup(name="pg", queue="q", min_member=1)
    pend = apis.Pod(name="want-port", group="pg",
                    resources=apis.ResourceVec(1.0, 1.0, 1.0),
                    host_ports=[8080])
    cluster = Cluster.from_objects(nodes, queues, [rg, pg], [holder, pend])
    res = Scheduler().run_once(cluster)
    assert len(res.bind_requests) == 1
    assert res.bind_requests[0].selected_node == "n1"


def test_node_ports_no_conflict_different_ports():
    nodes = [apis.Node(name="n0",
                       allocatable=apis.ResourceVec(4.0, 32.0, 128.0))]
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=8.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=8.0))]
    rg = apis.PodGroup(name="rg", queue="q", min_member=1,
                       last_start_timestamp=0.0)
    holder = apis.Pod(name="holder", group="rg",
                      resources=apis.ResourceVec(1.0, 1.0, 1.0),
                      host_ports=[8080], status=apis.PodStatus.RUNNING,
                      node="n0", accel_devices=[0])
    pg = apis.PodGroup(name="pg", queue="q", min_member=1)
    pend = apis.Pod(name="other-port", group="pg",
                    resources=apis.ResourceVec(1.0, 1.0, 1.0),
                    host_ports=[9090])
    cluster = Cluster.from_objects(nodes, queues, [rg, pg], [holder, pend])
    res = Scheduler().run_once(cluster)
    assert len(res.bind_requests) == 1

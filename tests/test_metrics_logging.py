"""Metrics registry + leveled logging tests (ref
``pkg/scheduler/metrics/metrics.go``, ``pkg/scheduler/log/log.go``)."""
from kai_scheduler_tpu.utils.logging import InfraLogger
from kai_scheduler_tpu.utils.metrics import Registry


def test_counter_gauge_histogram_and_exposition():
    reg = Registry()
    c = reg.counter("kai_podgroups_scheduled_total", "x", ("action",))
    g = reg.gauge("kai_queue_fair_share", "y", ("queue", "resource"))
    h = reg.histogram("kai_e2e_scheduling_latency_seconds", "z",
                      buckets=(0.01, 0.1, 1.0))
    c.inc("allocate")
    c.inc("allocate", by=2)
    g.set("team-a", "accel", value=4.5)
    h.observe(value=0.05)
    h.observe(value=5.0)
    assert c.value("allocate") == 3
    assert g.value("team-a", "accel") == 4.5
    assert h.count() == 2
    text = reg.render()
    assert 'kai_podgroups_scheduled_total{action="allocate"} 3' in text
    assert 'kai_queue_fair_share{queue="team-a",resource="accel"} 4.5' in text
    assert 'kai_e2e_scheduling_latency_seconds_bucket{le="0.1"} 1' in text
    assert 'kai_e2e_scheduling_latency_seconds_bucket{le="+Inf"} 2' in text
    assert "# TYPE kai_queue_fair_share gauge" in text


def test_scheduler_cycle_populates_metrics():
    from kai_scheduler_tpu.apis import types as apis
    from kai_scheduler_tpu.framework import metrics
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.cluster import Cluster

    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    groups = [apis.PodGroup("g", queue="q", min_member=1)]
    pods = [apis.Pod("p", "g", apis.ResourceVec(1, 1, 1))]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)
    before = metrics.podgroups_scheduled.value("all")
    Scheduler().run_once(cluster)
    assert metrics.podgroups_scheduled.value("all") >= before + 1
    assert metrics.queue_fair_share.value("q", "accel") > 0
    assert metrics.e2e_latency.count() >= 1
    assert "kai_queue_fair_share" in metrics.registry.render()


def test_victim_wavefront_gauges_populated():
    """PR-5 observability: a cycle whose preempt action runs chunks
    must surface chunk count, lane occupancy, and the sparse-path
    fallback count through /metrics (``wavefront_stats`` rides the
    packed commit transfer)."""
    from kai_scheduler_tpu.apis import types as apis
    from kai_scheduler_tpu.framework import metrics
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.runtime.cluster import Cluster

    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    low = apis.PodGroup("low", queue="q", min_member=1, priority=1,
                        last_start_timestamp=0.0)
    high = apis.PodGroup("high", queue="q", min_member=2, priority=9,
                         creation_timestamp=1.0)
    pods = [apis.Pod(f"v{i}", "low", apis.ResourceVec(1, 1, 4),
                     status=apis.PodStatus.RUNNING, node="n0")
            for i in range(8)]
    pods += [apis.Pod(f"h{i}", "high", apis.ResourceVec(2, 1, 4),
                      creation_timestamp=1.0) for i in range(2)]
    cluster = Cluster.from_objects(nodes, queues, [low, high], pods)
    cluster.now = 100.0
    res = Scheduler().run_once(cluster)
    assert len(res.evictions) > 0          # preempt actually fired
    assert metrics.victim_wavefront_chunks.value("preempt") >= 1
    occ = metrics.victim_wavefront_lane_occupancy.value("preempt")
    assert 0 < occ <= 1.0
    assert metrics.victim_wavefront_sparse_fallbacks.value("preempt") == 0
    assert (metrics.victim_wavefront_leftover_demotions.value("preempt")
            >= 0)
    text = metrics.registry.render()
    for name in ("kai_victim_wavefront_chunks",
                 "kai_victim_wavefront_lane_occupancy",
                 "kai_victim_wavefront_sparse_fallbacks",
                 "kai_victim_wavefront_leftover_demotions"):
        assert name in text


def test_starvation_alarm_gauge_and_decision_event():
    """PR-9 kai-pulse: a gang pending past ``starvation_alarm_cycles``
    fires exactly one ``starved`` DecisionLog event carrying the
    FIT_REASONS text of its blocker, and the top-K
    ``kai_gang_starvation_age_cycles`` gauge tracks its pending age."""
    from kai_scheduler_tpu.apis import types as apis
    from kai_scheduler_tpu.framework import metrics
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    from kai_scheduler_tpu.framework.session import FIT_REASONS
    from kai_scheduler_tpu.runtime import events as gang_events
    from kai_scheduler_tpu.runtime.cluster import Cluster

    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    groups = [apis.PodGroup("hungry", queue="q", min_member=1)]
    # requests no node can ever satisfy — the gang starves forever
    pods = [apis.Pod("p0", "hungry", apis.ResourceVec(64, 1, 1))]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)
    sched = Scheduler(SchedulerConfig(starvation_alarm_cycles=2))
    for _ in range(3):
        res = sched.run_once(cluster)
    assert res.bind_requests == []
    # gauge: the top-K table carries the gang at its current age
    assert metrics.gang_starvation_age.value("hungry") == 3.0
    # the /debug/cluster starvation family agrees
    starv = res.analytics["starvation"]
    assert starv["oldest"][0]["gang"] == "hungry"
    assert starv["oldest"][0]["age_cycles"] == 3
    assert starv["oldest"][0]["blocker"] == FIT_REASONS[1]
    # exactly ONE starved event, fired at the crossing, blocker text in
    # the detail
    evs = [e for e in sched.decisions.events(gang="hungry")
           if e["outcome"] == gang_events.OUTCOME_STARVED]
    assert len(evs) == 1
    assert FIT_REASONS[1] in evs[0]["detail"]
    assert "pending 2 cycles" in evs[0]["detail"]
    # the starved outcome is counted in the cycle summary it fired in
    assert any(
        c[3].get(gang_events.OUTCOME_STARVED) == 1
        for c in sched.decisions._cycles)
    text = metrics.registry.render()
    assert "kai_gang_starvation_age_cycles" in text
    assert "kai_cluster_fragmentation_score" in text


def test_infra_logger_verbosity_and_scope(capsys):
    log = InfraLogger(name="kai-test", verbosity=3)
    scoped = log.with_scope(session=7, action="allocate")
    scoped.V(2).infof("placed %d pods", 5)
    scoped.V(5).infof("should not appear")
    err = capsys.readouterr().err
    assert "placed 5 pods" in err
    assert "session=7" in err and "action=allocate" in err
    assert "should not appear" not in err


def test_render_consistent_under_concurrent_observation():
    """A /metrics scrape renders while the cycle thread observes.
    Pre-PR-4 the histogram renderer iterated the LIVE bucket lists and
    read ``_sums`` afterwards, so a scrape overlapping observes could
    expose sum != count * value — a torn, never-was state.  The locked
    snapshot pins sum == count exactly (every observed value is 1.0)."""
    import threading

    reg = Registry()
    hist = reg.histogram("h_seconds", "h", buckets=(0.5, 2.0))
    stop = threading.Event()

    def writer():
        while not stop.is_set():
            hist.observe(value=1.0)

    t = threading.Thread(target=writer, daemon=True)
    t.start()
    torn = []
    try:
        for _ in range(400):
            text = reg.render()
            got_sum = got_count = None
            for line in text.splitlines():
                if line.startswith("h_seconds_sum"):
                    got_sum = float(line.rsplit(" ", 1)[1])
                elif line.startswith("h_seconds_count"):
                    got_count = float(line.rsplit(" ", 1)[1])
            if got_sum is not None and got_sum != got_count:
                torn.append((got_sum, got_count))
    finally:
        stop.set()
        t.join(timeout=10)
    assert not torn, f"torn expositions: {torn[:3]}"

"""Controller + stalegangeviction tests — ref
``pkg/podgroupcontroller``/``pkg/queuecontroller`` unit tests and
``actions/stalegangeviction`` integration tests."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.controllers import PodGroupController, QueueController
from kai_scheduler_tpu.framework import Scheduler, SchedulerConfig
from kai_scheduler_tpu.framework.session import SessionConfig
from kai_scheduler_tpu.runtime.cluster import Cluster

Vec = apis.ResourceVec
QR = apis.QueueResource


def small_cluster(gang_pods=4, min_member=4):
    nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
    queues = [apis.Queue("q0", accel=QR(quota=8.0))]
    group = apis.PodGroup("g0", queue="q0", min_member=min_member)
    pods = [apis.Pod(f"g0-p{i}", "g0", resources=Vec(1.0, 1.0, 4.0))
            for i in range(gang_pods)]
    cluster = Cluster.from_objects(nodes, queues, [group], pods)
    return cluster


class TestPodGroupController:
    def test_phase_lifecycle(self):
        cluster = small_cluster()
        ctl = PodGroupController()
        ctl.reconcile(cluster)
        g = cluster.pod_groups["g0"]
        assert g.phase == apis.PodGroupPhase.PENDING

        for i in range(4):
            cluster.pods[f"g0-p{i}"].status = apis.PodStatus.BOUND
            cluster.pods[f"g0-p{i}"].node = "node-0"
        ctl.reconcile(cluster)
        assert g.phase == apis.PodGroupPhase.SCHEDULED
        assert g.last_start_timestamp is not None

        cluster.tick()
        ctl.reconcile(cluster)
        assert g.phase == apis.PodGroupPhase.RUNNING

    def test_staleness_stamped_when_below_min_member(self):
        cluster = small_cluster()
        ctl = PodGroupController()
        for i in range(4):
            cluster.pods[f"g0-p{i}"].status = apis.PodStatus.RUNNING
            cluster.pods[f"g0-p{i}"].node = "node-0"
        ctl.reconcile(cluster)
        # two pods die
        cluster.now = 10.0
        del cluster.pods["g0-p2"], cluster.pods["g0-p3"]
        ctl.reconcile(cluster)
        g = cluster.pod_groups["g0"]
        assert g.phase == apis.PodGroupPhase.STALE
        assert g.stale_since == 10.0
        # recovery clears staleness
        cluster.submit(g, [apis.Pod(f"g0-p{i}", "g0",
                                    resources=Vec(1.0, 1.0, 4.0),
                                    status=apis.PodStatus.RUNNING,
                                    node="node-0") for i in (2, 3)])
        ctl.reconcile(cluster)
        assert g.stale_since is None


class TestQueueController:
    def test_status_rollup(self):
        nodes = [apis.Node("node-0", Vec(8.0, 64.0, 256.0))]
        queues = [apis.Queue("dept"), apis.Queue("q0", parent="dept"),
                  apis.Queue("q1", parent="dept")]
        g0 = apis.PodGroup("g0", queue="q0", min_member=1)
        g1 = apis.PodGroup(
            "g1", queue="q1", min_member=1,
            preemptibility=apis.Preemptibility.NON_PREEMPTIBLE)
        pods = [
            apis.Pod("a", "g0", resources=Vec(2.0, 2.0, 8.0),
                     status=apis.PodStatus.RUNNING, node="node-0"),
            apis.Pod("b", "g0", resources=Vec(1.0, 1.0, 4.0)),  # pending
            apis.Pod("c", "g1", resources=Vec(3.0, 1.0, 4.0),
                     status=apis.PodStatus.RUNNING, node="node-0"),
        ]
        cluster = Cluster.from_objects(nodes, queues, [g0, g1], pods)
        status = QueueController().reconcile(cluster)
        assert status["q0"].allocated.accel == 2.0
        assert status["q0"].requested.accel == 3.0
        assert status["q1"].allocated_non_preemptible.accel == 3.0
        assert status["dept"].allocated.accel == 5.0
        assert status["dept"].requested.accel == 6.0


class TestStaleGangEviction:
    def test_stale_gang_evicted_after_grace(self):
        cluster = small_cluster()
        ctl = PodGroupController()
        for i in range(4):
            cluster.pods[f"g0-p{i}"].status = apis.PodStatus.RUNNING
            cluster.pods[f"g0-p{i}"].node = "node-0"
        ctl.reconcile(cluster)
        del cluster.pods["g0-p3"]          # gang drops below minMember=4
        cluster.now = 5.0
        ctl.reconcile(cluster)

        sched = Scheduler(SchedulerConfig(
            actions=("stalegangeviction",),
            session=SessionConfig(num_levels=1, stale_grace_s=60.0)))
        # within grace: no eviction
        r1 = sched.run_once(cluster)
        assert len(r1.evictions) == 0
        # past grace: remaining 3 pods evicted
        cluster.now = 70.0
        r2 = sched.run_once(cluster)
        assert {e.pod_name for e in r2.evictions} == {
            "g0-p0", "g0-p1", "g0-p2"}

    def test_healthy_gang_not_evicted(self):
        cluster = small_cluster()
        ctl = PodGroupController()
        for i in range(4):
            cluster.pods[f"g0-p{i}"].status = apis.PodStatus.RUNNING
            cluster.pods[f"g0-p{i}"].node = "node-0"
        ctl.reconcile(cluster)
        cluster.now = 100.0
        sched = Scheduler(SchedulerConfig(
            actions=("stalegangeviction",),
            session=SessionConfig(num_levels=1)))
        assert len(sched.run_once(cluster).evictions) == 0

"""Real DRA: ResourceClaim / DeviceClass objects through schedule + bind.

Mirrors the reference's ``dra_fake`` test suites
(``pkg/scheduler/test_utils/dra_fake``,
``plugins/dynamicresources/dynamicresources.go:30-70``,
``bindrequest_types.go`` ResourceClaimAllocations) and the binder's
claim binding/rollback.
"""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.binder.binder import Binder
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime import snapshot
from kai_scheduler_tpu.runtime.cluster import Cluster


def _dra_cluster(num_nodes=4, big_nodes=2):
    """Nodes 0..big-1 have 80GiB devices + the matching label; the rest
    16GiB."""
    nodes = []
    for i in range(num_nodes):
        big = i < big_nodes
        nodes.append(apis.Node(
            name=f"node-{i}",
            allocatable=apis.ResourceVec(4.0, 32.0, 128.0),
            accel_memory_gib=80.0 if big else 16.0,
            labels={"accel": "a100" if big else "t4"},
        ))
    queues = [apis.Queue(name="dept", accel=apis.QueueResource(quota=16.0)),
              apis.Queue(name="q", parent="dept",
                         accel=apis.QueueResource(quota=16.0))]
    cluster = Cluster.from_objects(nodes, queues, [], [])
    cluster.device_classes["big-gpu"] = apis.DeviceClass(
        name="big-gpu", min_memory_gib=40.0, node_selector={"accel": "a100"})
    cluster.device_classes["any-gpu"] = apis.DeviceClass(name="any-gpu")
    return cluster


def _claim_pod(cluster, name, claim_name, device_class, count=2):
    cluster.resource_claims[claim_name] = apis.ResourceClaim(
        name=claim_name, device_class=device_class, count=count)
    group = apis.PodGroup(name=f"{name}-pg", queue="q", min_member=1)
    pod = apis.Pod(name=name, group=group.name,
                   resources=apis.ResourceVec(0.0, 1.0, 1.0),
                   resource_claims=[claim_name])
    cluster.submit(group, [pod])
    return pod


def test_claim_constraints_steer_placement():
    """A claim's DeviceClass (min memory + node selector) confines the
    pod to matching nodes — the scheduler-side CEL analogue."""
    cluster = _dra_cluster()
    _claim_pod(cluster, "p-big", "claim-big", "big-gpu", count=2)
    res = Scheduler().run_once(cluster)
    assert len(res.bind_requests) == 1
    br = res.bind_requests[0]
    assert br.selected_node in ("node-0", "node-1")      # a100 nodes only
    assert br.resource_claim_allocations == ["claim-big"]


def test_binder_allocates_and_records_devices():
    cluster = _dra_cluster()
    _claim_pod(cluster, "p1", "c1", "any-gpu", count=2)
    Scheduler().run_once(cluster)
    result = Binder().reconcile(cluster)
    assert result.bound == ["p1"]
    claim = cluster.resource_claims["c1"]
    assert claim.node is not None and len(claim.devices) == 2
    assert claim.owner_pod == "p1"
    # claimed devices are not free for anyone else
    free = cluster.node_device_free(claim.node)
    assert all(free[d] == 0.0 for d in claim.devices)


def test_claim_devices_excluded_from_next_snapshot():
    """Bound claims debit the device table: a follow-up whole-device pod
    cannot double-book the claimed devices."""
    cluster = _dra_cluster(num_nodes=1, big_nodes=0)     # 4 devices total
    _claim_pod(cluster, "p1", "c1", "any-gpu", count=3)
    Scheduler().run_once(cluster)
    Binder().reconcile(cluster)
    cluster.tick()
    # 1 device left; a 2-device pod must NOT place
    group = apis.PodGroup(name="pg2", queue="q", min_member=1)
    cluster.submit(group, [apis.Pod(
        name="p2", group="pg2", resources=apis.ResourceVec(2.0, 1.0, 1.0))])
    res = Scheduler().run_once(cluster)
    assert all(b.pod_name != "p2" for b in res.bind_requests)
    # ... but a 1-device pod fits the remaining device
    group3 = apis.PodGroup(name="pg3", queue="q", min_member=1)
    cluster.submit(group3, [apis.Pod(
        name="p3", group="pg3", resources=apis.ResourceVec(1.0, 1.0, 1.0))])
    res3 = Scheduler().run_once(cluster)
    assert any(b.pod_name == "p3" for b in res3.bind_requests)


def test_bind_rollback_deallocates_claim():
    cluster = _dra_cluster(num_nodes=1, big_nodes=1)
    _claim_pod(cluster, "p1", "c1", "any-gpu", count=2)
    Scheduler().run_once(cluster)
    # sabotage: another claim grabs every device before the binder runs
    cluster.resource_claims["thief"] = apis.ResourceClaim(
        name="thief", device_class="any-gpu", count=4,
        node="node-0", devices=[0, 1, 2, 3], owner_pod="elsewhere")
    result = Binder().reconcile(cluster)
    assert result.retrying == ["p1"]
    claim = cluster.resource_claims["c1"]
    assert claim.node is None and claim.devices == [] \
        and claim.owner_pod is None


def test_claims_release_on_pod_deletion():
    cluster = _dra_cluster(num_nodes=1, big_nodes=0)
    pod = _claim_pod(cluster, "p1", "c1", "any-gpu", count=2)
    Scheduler().run_once(cluster)
    Binder().reconcile(cluster)
    cluster.tick()
    assert cluster.resource_claims["c1"].node == "node-0"
    cluster.evict_pod("p1")
    cluster.tick()
    assert cluster.resource_claims["c1"].node is None
    assert pod.name not in cluster.pods


def test_dra_snapshot_roundtrip():
    cluster = _dra_cluster()
    _claim_pod(cluster, "p1", "c1", "big-gpu", count=1)
    doc = snapshot.dump_cluster(cluster)
    back = snapshot.load_cluster(doc)
    assert back.resource_claims["c1"].device_class == "big-gpu"
    assert back.device_classes["big-gpu"].min_memory_gib == 40.0
    res = Scheduler().run_once(back)
    assert res.bind_requests[0].resource_claim_allocations == ["c1"]


def test_mig_gang_reclaims_mig_victim():
    """MIG credit-back (VERDICT r2 item 6): the ONLY path to placing a
    MIG gang is evicting the MIG-holding victim — the freed extended
    resources must flow back into the scenario pools.

    The victim holds 1 accel so its zero-quota queue sits strictly OVER
    its fair share (extended scalars are not part of queue shares, so a
    cpu-only victim would leave qv exactly AT share — not reclaimable,
    in line with the reference's strict over-share strategy; an earlier
    version of this test leaned on extended-blind consolidation moves
    to evict, which double-booked the MIG slices).  Accel itself is
    plentiful, so the placement still stands or falls with the MIG
    credit-back alone."""
    nodes = [apis.Node(name="n0",
                       allocatable=apis.ResourceVec(4.0, 32.0, 128.0),
                       extended={"mig-1g.5gb": 2.0})]
    queues = [
        apis.Queue(name="d0", accel=apis.QueueResource(quota=2.0)),
        apis.Queue(name="qv", parent="d0",
                   accel=apis.QueueResource(quota=0.0)),
        apis.Queue(name="qr", parent="d0",
                   accel=apis.QueueResource(quota=2.0)),
    ]
    victim_pg = apis.PodGroup(name="vg", queue="qv", min_member=1,
                              last_start_timestamp=0.0)
    victim = apis.Pod(name="v0", group="vg",
                      resources=apis.ResourceVec(1.0, 1.0, 1.0),
                      extended={"mig-1g.5gb": 2.0},
                      status=apis.PodStatus.RUNNING, node="n0")
    pend_pg = apis.PodGroup(name="rg", queue="qr", min_member=1)
    pend = apis.Pod(name="r0", group="rg",
                    resources=apis.ResourceVec(0.0, 1.0, 1.0),
                    extended={"mig-1g.5gb": 2.0})
    cluster = Cluster.from_objects(
        nodes, queues, [victim_pg, pend_pg], [victim, pend])
    res = Scheduler().run_once(cluster)
    assert {e.pod_name for e in res.evictions} == {"v0"}
    placements = np.asarray(res.tensors.placements)
    allocated = np.asarray(res.tensors.allocated)
    # the MIG gang is placed (pipelined onto the victim's capacity)
    assert allocated.any()
    assert (placements >= 0).any()


def test_rejected_pod_does_not_inflate_claim_consumers():
    """A pod rejected by ANY claim gate must not grow the virtual
    ReservedFor count of its OTHER claims: per-claim admissions commit
    only after the pod passes every gate (the reference's preFilter
    never reserves for a pod it rejected).  Previously pod A's good
    claim was counted even though A was rejected, pushing the shared
    claim to its consumer cap and wrongly rejecting pod B."""
    cluster = _dra_cluster()
    # one consumer slot left on the shared claim
    cluster.resource_claims["c-share"] = apis.ResourceClaim(
        name="c-share", device_class="any-gpu", count=1,
        from_template=False,
        labels={apis.QUEUE_LABEL: "q"},
        reserved_for=apis.RESERVED_FOR_MAX - 1)
    # a shared claim missing the queue label — always rejected
    cluster.resource_claims["c-bad"] = apis.ResourceClaim(
        name="c-bad", device_class="any-gpu", count=1,
        from_template=False)
    ga = apis.PodGroup(name="pg-a", queue="q", min_member=1)
    pod_a = apis.Pod(name="pod-a", group="pg-a",
                     resources=apis.ResourceVec(0.0, 1.0, 1.0),
                     resource_claims=["c-share", "c-bad"])
    gb = apis.PodGroup(name="pg-b", queue="q", min_member=1)
    pod_b = apis.Pod(name="pod-b", group="pg-b",
                     resources=apis.ResourceVec(0.0, 1.0, 1.0),
                     resource_claims=["c-share"])
    cluster.submit(ga, [pod_a])
    cluster.submit(gb, [pod_b])
    result = Scheduler().run_once(cluster)
    bound = {br.pod_name for br in result.bind_requests}
    assert "pod-a" not in bound   # its c-bad gate rejects it
    assert "pod-b" in bound       # the last consumer slot is still free

"""Allocate-action kernel tests — analogue of the reference's
``actions/allocate/allocate_test.go`` + ``allocateGang_test.go`` suites
(fake-cluster table tests from ``test_utils/``)."""
import jax.numpy as jnp
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
from kai_scheduler_tpu.state import build_snapshot, make_cluster

import pytest

pytestmark = pytest.mark.core


def run_allocate(state, *, num_levels=2, **cfg):
    fs = drf.set_fair_share(state, num_levels=num_levels)
    state = state.replace(queues=state.queues.replace(fair_share=fs))
    return allocate(state, fs, num_levels=num_levels,
                    config=AllocateConfig(**cfg))


def test_simple_allocation_places_all():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=2)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    res = run_allocate(state)
    g_valid = np.asarray(state.gangs.valid)
    assert np.asarray(res.allocated)[g_valid].all()
    pl = np.asarray(res.placements)
    tv = np.asarray(state.gangs.task_valid)
    assert (pl[tv] >= 0).all()
    assert (pl[~tv] == -1).all()


def test_capacity_respected():
    """8 gangs x 2 tasks x 1 accel onto one 8-accel node: exactly 4 gangs fit."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=1, node_accel=8.0, node_cpu=1000.0, node_mem=1000.0,
        num_gangs=8, tasks_per_gang=2)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    res = run_allocate(state)
    assert int(np.asarray(res.allocated).sum()) == 4
    free = np.asarray(res.free)
    assert free[0, apis.RESOURCE_ACCEL] >= -1e-5


def test_gang_all_or_nothing():
    """A gang needing 3 devices on a 2-device cluster must place nothing —
    ref Statement rollback semantics (framework/statement.go:43-60)."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=10))]
    groups = [apis.PodGroup("gang", queue="q", min_member=3)]
    pods = [apis.Pod(f"p{i}", "gang", apis.ResourceVec(1, 1, 1))
            for i in range(3)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=1)
    assert not np.asarray(res.allocated)[0]
    assert (np.asarray(res.placements)[0] == -1).all()
    # free untouched by the rolled-back partial placement
    np.testing.assert_allclose(np.asarray(res.free)[0],
                               np.asarray(state.nodes.free)[0])


def test_elastic_gang_partial_above_min():
    """min_member=1 with 3 tasks on a 2-device node: gang commits with the
    2 tasks that fit (elastic plugin semantics)."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=10))]
    groups = [apis.PodGroup("gang", queue="q", min_member=1)]
    pods = [apis.Pod(f"p{i}", "gang", apis.ResourceVec(1, 1, 1))
            for i in range(3)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=1)
    assert np.asarray(res.allocated)[0]
    assert int((np.asarray(res.placements)[0] >= 0).sum()) == 2


def test_queue_limit_gates_allocation():
    """Queue with limit=1 accel can only take 1 of its 2 single-task gangs."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue(
        "q", accel=apis.QueueResource(quota=1.0, limit=1.0))]
    groups = [apis.PodGroup(f"g{i}", queue="q", min_member=1) for i in range(2)]
    pods = [apis.Pod(f"p{i}", f"g{i}", apis.ResourceVec(1, 1, 1))
            for i in range(2)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=1)
    assert int(np.asarray(res.allocated).sum()) == 1


def test_nonpreemptible_gated_by_quota():
    """Non-preemptible gangs must stay within deserved quota
    (capacity_policy.IsNonPreemptibleJobOverQuota); preemptible ones may
    go over quota up to the limit."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue(
        "q", accel=apis.QueueResource(quota=1.0),
        cpu=apis.QueueResource(quota=apis.UNLIMITED),
        memory=apis.QueueResource(quota=apis.UNLIMITED))]

    def mk(preempt):
        groups = [apis.PodGroup(
            f"g{i}", queue="q", min_member=1,
            preemptibility=(apis.Preemptibility.PREEMPTIBLE if preempt
                            else apis.Preemptibility.NON_PREEMPTIBLE))
            for i in range(3)]
        pods = [apis.Pod(f"p{i}", f"g{i}", apis.ResourceVec(1, 1, 1))
                for i in range(3)]
        return build_snapshot(nodes, queues, groups, pods)[0]

    res_np = run_allocate(mk(False), num_levels=1)
    assert int(np.asarray(res_np.allocated).sum()) == 1  # quota=1
    res_p = run_allocate(mk(True), num_levels=1)
    assert int(np.asarray(res_p.allocated).sum()) == 3   # no limit


def test_hierarchical_limit_on_parent():
    """Parent queue limit caps the sum of its children."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [
        apis.Queue("dept", accel=apis.QueueResource(quota=4.0, limit=2.0)),
        apis.Queue("a", parent="dept", accel=apis.QueueResource(quota=2.0)),
        apis.Queue("b", parent="dept", accel=apis.QueueResource(quota=2.0)),
    ]
    groups = [apis.PodGroup(f"g{i}", queue=("a" if i % 2 == 0 else "b"),
                            min_member=1) for i in range(4)]
    pods = [apis.Pod(f"p{i}", f"g{i}", apis.ResourceVec(1, 1, 1))
            for i in range(4)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=2)
    assert int(np.asarray(res.allocated).sum()) == 2


def test_fairness_order_interleaves_queues():
    """Two queues with equal quota on a cluster that only fits half the
    demand: DRF ordering must give each queue its fair share rather than
    letting the first queue drain the cluster."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=2, node_accel=4.0, node_cpu=1000.0, node_mem=1000.0,
        num_departments=2, queues_per_department=1,
        num_gangs=8, tasks_per_gang=2)   # demand 16 accel, capacity 8
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    res = run_allocate(state)
    qi = np.asarray(state.gangs.queue)
    alloc = np.asarray(res.allocated)
    per_queue = {}
    for gq, a in zip(qi[: len(groups)], alloc[: len(groups)]):
        per_queue[gq] = per_queue.get(gq, 0) + int(a)
    assert len(per_queue) == 2
    counts = sorted(per_queue.values())
    assert counts == [2, 2], counts


def test_pipelined_placement_on_releasing():
    """A task that fits only counting releasing resources gets placed with
    pipelined=True (stmt.Pipeline equivalent)."""
    nodes = [apis.Node("n0", apis.ResourceVec(1, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=10))]
    groups = [
        apis.PodGroup("old", queue="q", min_member=1,
                      last_start_timestamp=0.0),
        apis.PodGroup("new", queue="q", min_member=1),
    ]
    pods = [
        apis.Pod("vic", "old", apis.ResourceVec(1, 1, 1),
                 status=apis.PodStatus.RELEASING, node="n0"),
        apis.Pod("inc", "new", apis.ResourceVec(1, 1, 1)),
    ]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=1)
    g = 1  # "new" is the second group
    assert np.asarray(res.allocated)[g]
    assert np.asarray(res.pipelined)[g, 0]


def test_wavefront_lanes_cannot_share_idle_capacity_as_bind_now():
    """Two gangs racing for one idle device in the same wavefront chunk:
    only one may bind immediately; the other must pipeline behind the
    releasing pod (it would otherwise bind onto a still-occupied node).
    Regression for cross-lane staleness of the pipelined flags."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=10))]
    groups = [
        apis.PodGroup("old", queue="q", min_member=1,
                      last_start_timestamp=0.0),
        apis.PodGroup("a", queue="q", min_member=1),
        apis.PodGroup("b", queue="q", min_member=1),
    ]
    pods = [
        apis.Pod("vic", "old", apis.ResourceVec(1, 1, 1),
                 status=apis.PodStatus.RELEASING, node="n0"),
        apis.Pod("pa", "a", apis.ResourceVec(1, 1, 1)),
        apis.Pod("pb", "b", apis.ResourceVec(1, 1, 1)),
    ]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=1, batch_size=8)
    allocated = np.asarray(res.allocated)
    pipelined = np.asarray(res.pipelined)
    assert allocated[1] and allocated[2]
    # exactly one of the two new tasks binds now; the other pipelines
    assert int(pipelined[1, 0]) + int(pipelined[2, 0]) == 1


def test_queue_depth_limits_attempts_per_queue():
    """queue_depth=1 (ref QueueDepthPerAction): at most one gang per queue
    is attempted per action, independently of how many would fit."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 640, 2560))]
    queues = [apis.Queue("qa", accel=apis.QueueResource(quota=4)),
              apis.Queue("qb", accel=apis.QueueResource(quota=4))]
    groups = ([apis.PodGroup(f"ga{i}", queue="qa", min_member=1)
               for i in range(3)]
              + [apis.PodGroup(f"gb{i}", queue="qb", min_member=1)
                 for i in range(3)])
    pods = [apis.Pod(f"p{g.name}", g.name, apis.ResourceVec(1, 1, 1))
            for g in groups]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, num_levels=1, queue_depth=1)
    g_queue = np.asarray(state.gangs.queue)
    attempted = np.asarray(res.attempted)
    valid = np.asarray(state.gangs.valid)
    for qi in (0, 1):
        assert int(attempted[valid & (g_queue == qi)].sum()) == 1
    # and the attempted gangs actually allocated (capacity was ample)
    assert int(np.asarray(res.allocated).sum()) == 2


def test_static_order_matches_dynamic_on_single_queue():
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=2, node_accel=8.0, num_departments=1,
        queues_per_department=1, num_gangs=6, tasks_per_gang=2)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    res_d = run_allocate(state, dynamic_order=True)
    res_s = run_allocate(state, dynamic_order=False)
    np.testing.assert_array_equal(
        np.asarray(res_d.allocated), np.asarray(res_s.allocated))


def test_jit_compiles_and_matches_eager():
    import jax

    from kai_scheduler_tpu.ops.allocate import allocate_jit
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, num_gangs=4, tasks_per_gang=2)
    state, _ = build_snapshot(nodes, queues, groups, pods, topo)
    fs = drf.set_fair_share(state, num_levels=2)
    state = state.replace(queues=state.queues.replace(fair_share=fs))
    res_e = allocate(state, fs, num_levels=2)
    res_j = allocate_jit(state, fs, num_levels=2)
    np.testing.assert_array_equal(
        np.asarray(res_e.placements), np.asarray(res_j.placements))

"""Wide-predicate tests: taints/tolerations, node-affinity operators,
inter-pod (anti-)affinity, nominated node — the analogue of the upstream
filter surface wrapped by ``k8s_internal/predicates/predicates.go:70-140``
and the ``podaffinity`` / ``nominatednode`` plugins."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import build_snapshot


def run_allocate(state, *, num_levels=1, **cfg):
    fs = drf.set_fair_share(state, num_levels=num_levels)
    state = state.replace(queues=state.queues.replace(fair_share=fs))
    return allocate(state, fs, num_levels=num_levels,
                    config=AllocateConfig(**cfg))


def _one_queue():
    return [apis.Queue("q", accel=apis.QueueResource(quota=100))]


def test_hard_taint_excludes_untolerated_pod():
    nodes = [apis.Node("tainted", apis.ResourceVec(8, 64, 256),
                       taints=[apis.Taint("dedicated", "infra")])]
    groups = [apis.PodGroup("g", queue="q", min_member=1)]
    pods = [apis.Pod("p", "g", apis.ResourceVec(1, 1, 1))]
    state, _ = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    assert not np.asarray(res.allocated)[0]


def test_toleration_admits_pod_equal_and_exists():
    nodes = [apis.Node("tainted", apis.ResourceVec(8, 64, 256),
                       taints=[apis.Taint("dedicated", "infra")])]
    groups = [apis.PodGroup("ge", queue="q", min_member=1),
              apis.PodGroup("gx", queue="q", min_member=1),
              apis.PodGroup("gw", queue="q", min_member=1)]
    pods = [
        apis.Pod("pe", "ge", apis.ResourceVec(1, 1, 1),
                 tolerations=[apis.Toleration("dedicated", "Equal", "infra")]),
        apis.Pod("px", "gx", apis.ResourceVec(1, 1, 1),
                 tolerations=[apis.Toleration("dedicated", "Exists")]),
        # wrong value on an Equal toleration does NOT tolerate
        apis.Pod("pw", "gw", apis.ResourceVec(1, 1, 1),
                 tolerations=[apis.Toleration("dedicated", "Equal", "other")]),
    ]
    state, _ = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    allocated = np.asarray(res.allocated)
    assert allocated[0] and allocated[1] and not allocated[2]


def test_prefer_noschedule_is_soft():
    """PreferNoSchedule steers away from the tainted node but does not
    exclude it when it is the only option."""
    nodes = [
        apis.Node("pref-tainted", apis.ResourceVec(8, 64, 256),
                  taints=[apis.Taint("flaky", "", "PreferNoSchedule")]),
        apis.Node("clean", apis.ResourceVec(8, 64, 256)),
    ]
    groups = [apis.PodGroup("g", queue="q", min_member=1)]
    pods = [apis.Pod("p", "g", apis.ResourceVec(1, 1, 1))]
    state, idx = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    assert idx.node_names[int(np.asarray(res.placements)[0, 0])] == "clean"

    # only the tainted node exists -> still schedulable
    state2, _ = build_snapshot(nodes[:1], _one_queue(), groups, pods)
    res2 = run_allocate(state2)
    assert np.asarray(res2.allocated)[0]


def test_node_affinity_operators():
    nodes = [
        apis.Node("a", apis.ResourceVec(8, 64, 256),
                  labels={"zone": "z1", "gen": "7"}),
        apis.Node("b", apis.ResourceVec(8, 64, 256),
                  labels={"zone": "z2", "gen": "5"}),
        apis.Node("c", apis.ResourceVec(8, 64, 256)),
    ]
    cases = [
        ([apis.AffinityExpr("zone", "In", ("z1", "z3"))], {"a"}),
        ([apis.AffinityExpr("zone", "NotIn", ("z1",))], {"b", "c"}),
        ([apis.AffinityExpr("zone", "Exists")], {"a", "b"}),
        ([apis.AffinityExpr("zone", "DoesNotExist")], {"c"}),
        ([apis.AffinityExpr("gen", "Gt", ("6",))], {"a"}),
        ([apis.AffinityExpr("gen", "Lt", ("6",))], {"b"}),
        # ANDed expressions
        ([apis.AffinityExpr("zone", "Exists"),
          apis.AffinityExpr("gen", "Lt", ("6",))], {"b"}),
    ]
    for exprs, expected in cases:
        groups = [apis.PodGroup("g", queue="q", min_member=3)]
        pods = [apis.Pod(f"p{i}", "g", apis.ResourceVec(1, 1, 1),
                         node_affinity=list(exprs)) for i in range(3)]
        state, idx = build_snapshot(nodes, _one_queue(), groups, pods)
        res = run_allocate(state)
        if len(expected) >= 3:
            assert np.asarray(res.allocated)[0], exprs
        pl = np.asarray(res.placements)[0]
        placed_nodes = {idx.node_names[n] for n in pl if n >= 0}
        assert placed_nodes <= expected, (exprs, placed_nodes, expected)


def test_required_pod_anti_affinity_against_running():
    """A required anti-affinity term keeps the new pod off nodes already
    running pods matching the selector."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256)),
             apis.Node("n1", apis.ResourceVec(8, 64, 256))]
    groups = [apis.PodGroup("old", queue="q", min_member=1,
                            last_start_timestamp=0.0),
              apis.PodGroup("new", queue="q", min_member=1)]
    pods = [
        apis.Pod("vic", "old", apis.ResourceVec(1, 1, 1),
                 status=apis.PodStatus.RUNNING, node="n0",
                 labels={"app": "db"}),
        apis.Pod("inc", "new", apis.ResourceVec(1, 1, 1),
                 pod_affinity=[apis.PodAffinityTerm(
                     match_labels=(("app", "db"),), anti=True)]),
    ]
    state, idx = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    assert idx.node_names[int(np.asarray(res.placements)[1, 0])] == "n1"


def test_required_pod_affinity_colocates():
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256)),
             apis.Node("n1", apis.ResourceVec(8, 64, 256))]
    groups = [apis.PodGroup("old", queue="q", min_member=1,
                            last_start_timestamp=0.0),
              apis.PodGroup("new", queue="q", min_member=1)]
    pods = [
        apis.Pod("svc", "old", apis.ResourceVec(1, 1, 1),
                 status=apis.PodStatus.RUNNING, node="n1",
                 labels={"app": "cache"}),
        apis.Pod("inc", "new", apis.ResourceVec(1, 1, 1),
                 pod_affinity=[apis.PodAffinityTerm(
                     match_labels=(("app", "cache"),))]),
    ]
    state, idx = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    assert idx.node_names[int(np.asarray(res.placements)[1, 0])] == "n1"


def test_self_anti_affinity_spreads_gang():
    """Gang whose pods anti-affine to their own label: one task per node."""
    nodes = [apis.Node(f"n{i}", apis.ResourceVec(8, 64, 256))
             for i in range(3)]
    groups = [apis.PodGroup("g", queue="q", min_member=3)]
    pods = [apis.Pod(f"p{i}", "g", apis.ResourceVec(1, 1, 1),
                     labels={"app": "web"},
                     pod_affinity=[apis.PodAffinityTerm(
                         match_labels=(("app", "web"),), anti=True)])
            for i in range(3)]
    state, _ = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    pl = np.asarray(res.placements)[0]
    placed = pl[pl >= 0]
    assert len(placed) == 3 and len(set(placed.tolist())) == 3

    # 4 pods onto 3 nodes with the same constraint: gang cannot place
    groups4 = [apis.PodGroup("g", queue="q", min_member=4)]
    pods4 = pods + [apis.Pod("p3", "g", apis.ResourceVec(1, 1, 1),
                             labels={"app": "web"},
                             pod_affinity=[apis.PodAffinityTerm(
                                 match_labels=(("app", "web"),), anti=True)])]
    state4, _ = build_snapshot(nodes, _one_queue(), groups4, pods4)
    res4 = run_allocate(state4)
    assert not np.asarray(res4.allocated)[0]


def test_self_anti_affinity_at_rack_level():
    """Anti-affinity at a coarser topology level spreads across racks."""
    topo = apis.Topology("t", levels=["rack", "host"])
    nodes = [apis.Node(f"n{i}", apis.ResourceVec(8, 64, 256),
                       labels={"rack": f"r{i // 2}", "host": f"n{i}"})
             for i in range(4)]
    groups = [apis.PodGroup("g", queue="q", min_member=2)]
    pods = [apis.Pod(f"p{i}", "g", apis.ResourceVec(1, 1, 1),
                     labels={"app": "web"},
                     pod_affinity=[apis.PodAffinityTerm(
                         match_labels=(("app", "web"),), anti=True,
                         topology_key="rack")])
            for i in range(2)]
    state, _ = build_snapshot(nodes, _one_queue(), groups, pods, topo)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    pl = np.asarray(res.placements)[0]
    racks = {int(n) // 2 for n in pl if n >= 0}
    assert len(racks) == 2


def test_nominated_node_dominates_scoring():
    """The nominatednode bonus outweighs binpack preferences."""
    nodes = [apis.Node("full-ish", apis.ResourceVec(8, 64, 256)),
             apis.Node("target", apis.ResourceVec(8, 64, 256))]
    groups = [apis.PodGroup("old", queue="q", min_member=1,
                            last_start_timestamp=0.0),
              apis.PodGroup("new", queue="q", min_member=1)]
    pods = [
        # make full-ish the binpack favourite
        apis.Pod("filler", "old", apis.ResourceVec(6, 6, 6),
                 status=apis.PodStatus.RUNNING, node="full-ish"),
        apis.Pod("inc", "new", apis.ResourceVec(1, 1, 1),
                 nominated_node="target"),
    ]
    state, idx = build_snapshot(nodes, _one_queue(), groups, pods)
    res = run_allocate(state)
    assert idx.node_names[int(np.asarray(res.placements)[1, 0])] == "target"


def test_filter_class_dedup():
    """Identical specs share one class; snapshot hints derive correctly."""
    from kai_scheduler_tpu.state.node_filters import pod_filter_spec
    tol = [apis.Toleration("dedicated", "Exists")]
    p1 = apis.Pod("a", "g", tolerations=list(tol))
    p2 = apis.Pod("b", "g", tolerations=list(tol))
    assert pod_filter_spec(p1) == pod_filter_spec(p2)

    nodes = [apis.Node("n", apis.ResourceVec(8, 64, 256))]
    groups = [apis.PodGroup("g", queue="q", min_member=2)]
    pods = [apis.Pod(f"p{i}", "g", apis.ResourceVec(1, 1, 1),
                     tolerations=list(tol)) for i in range(2)]
    state, idx = build_snapshot(nodes, _one_queue(), groups, pods)
    # class 0 (empty) + one shared class for the two pods
    assert state.nodes.filter_masks.shape[0] == 2
    assert idx.uniform_gangs


class TestCrossGangAntiAffinity:
    """In-cycle cross-gang required anti-affinity (the round-2 advisor's
    medium finding): two gangs whose pods carry a required anti term
    matching each other's labels must NOT share a domain within one
    cycle — the allocate wavefront tracks claimed domains per anti
    group."""

    @staticmethod
    def _cluster(levels=None, key="kubernetes.io/hostname"):
        topo = None
        nodes = []
        for i in range(4):
            labels = {"kubernetes.io/hostname": f"n{i}"}
            if levels:
                labels["rack"] = f"r{i % 2}"
            nodes.append(apis.Node(
                name=f"n{i}",
                allocatable=apis.ResourceVec(8.0, 64.0, 256.0),
                labels=labels))
        if levels:
            topo = apis.Topology(name="default",
                                 levels=["rack", "kubernetes.io/hostname"])
        queues = [
            apis.Queue(name="dept", accel=apis.QueueResource(quota=32.0)),
            apis.Queue(name="q", parent="dept",
                       accel=apis.QueueResource(quota=32.0))]
        term = apis.PodAffinityTerm(
            match_labels=(("app", "db"),), topology_key=key,
            anti=True, required=True)
        groups, pods = [], []
        for gname in ("db-a", "db-b", "db-c"):
            groups.append(apis.PodGroup(name=gname, queue="q",
                                        min_member=1))
            pods.append(apis.Pod(
                name=f"{gname}-0", group=gname,
                resources=apis.ResourceVec(1.0, 1.0, 1.0),
                labels={"app": "db"}, pod_affinity=[term]))
        return Cluster.from_objects(nodes, queues, groups, pods, topo)

    def test_three_gangs_three_distinct_nodes(self):
        cluster = self._cluster()
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 3
        assert len(set(by_pod.values())) == 3, by_pod   # pairwise distinct

    def test_rack_level_groups_use_distinct_racks(self):
        cluster = self._cluster(levels=True, key="rack")
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        # only two racks exist: exactly two of the three gangs can place
        # this cycle, in DIFFERENT racks; the third waits
        racks = {int(n[1]) % 2 for n in by_pod.values()}
        assert len(by_pod) == 2, by_pod
        assert len(racks) == 2, by_pod


class TestInCycleExclusion:
    """The generalized in-cycle exclusion terms (round-3 VERDICT item 2):
    asymmetric required anti-affinity, pending-vs-pending NodePorts, and
    reverse anti-affinity — enforced by EVERY placement action through
    the cycle's claimed-domain table, including victim placements.

    Ref ``k8s_internal/predicates/predicates.go:70-140`` (InterPodAffinity
    and NodePorts dispatched per candidate node against virtually-
    allocated session state)."""

    @staticmethod
    def _nodes(n=4, accel=8.0):
        return [apis.Node(name=f"n{i}",
                          allocatable=apis.ResourceVec(accel, 64.0, 256.0),
                          labels={"kubernetes.io/hostname": f"n{i}"})
                for i in range(n)]

    @staticmethod
    def _queues(quota=32.0):
        return [apis.Queue(name="dept", accel=apis.QueueResource(quota=quota)),
                apis.Queue(name="q", parent="dept",
                           accel=apis.QueueResource(quota=quota))]

    def test_asymmetric_anti_same_cycle(self):
        """Gang `victim-labels` carries app=db labels and NO terms; gang
        `avoider` carries a required anti term vs app=db.  Arriving in
        ONE cycle they must not co-land on a node, whichever places
        first (forward + reverse term rows)."""
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),),
                                    anti=True, required=True)
        groups = [apis.PodGroup(name="labels", queue="q", min_member=2),
                  apis.PodGroup(name="avoider", queue="q", min_member=2)]
        pods = (
            [apis.Pod(name=f"labels-{i}", group="labels",
                      resources=apis.ResourceVec(1.0, 1.0, 1.0),
                      labels={"app": "db"}) for i in range(2)]
            + [apis.Pod(name=f"avoider-{i}", group="avoider",
                        resources=apis.ResourceVec(1.0, 1.0, 1.0),
                        pod_affinity=[term]) for i in range(2)])
        cluster = Cluster.from_objects(self._nodes(), self._queues(),
                                       groups, pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        label_nodes = {v for k, v in by_pod.items() if k.startswith("labels")}
        avoid_nodes = {v for k, v in by_pod.items() if k.startswith("avoider")}
        assert len(by_pod) == 4, by_pod
        assert not (label_nodes & avoid_nodes), by_pod

    def test_pending_nodeports_never_collide(self):
        """Two pending gangs requesting the same host port cannot share a
        node in one cycle (upstream NodePorts over assumed pods); a
        third gang without ports packs freely."""
        groups = [apis.PodGroup(name=g, queue="q", min_member=1)
                  for g in ("pa", "pb", "plain")]
        pods = [
            apis.Pod(name="pa-0", group="pa",
                     resources=apis.ResourceVec(1.0, 1.0, 1.0),
                     host_ports=[8080]),
            apis.Pod(name="pb-0", group="pb",
                     resources=apis.ResourceVec(1.0, 1.0, 1.0),
                     host_ports=[8080]),
            apis.Pod(name="plain-0", group="plain",
                     resources=apis.ResourceVec(1.0, 1.0, 1.0)),
        ]
        cluster = Cluster.from_objects(self._nodes(), self._queues(),
                                       groups, pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 3, by_pod
        assert by_pod["pa-0"] != by_pod["pb-0"], by_pod

    def test_port_replicas_spread_within_gang(self):
        """Replicas of ONE gang sharing a host port spread one-per-node
        (the NodePorts filter forbids two on a node)."""
        groups = [apis.PodGroup(name="svc", queue="q", min_member=3)]
        pods = [apis.Pod(name=f"svc-{i}", group="svc",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         host_ports=[9090]) for i in range(3)]
        cluster = Cluster.from_objects(self._nodes(), self._queues(),
                                       groups, pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 3, by_pod
        assert len(set(by_pod.values())) == 3, by_pod

    def test_reverse_anti_vs_running(self):
        """A RUNNING pod's own required anti term excludes a matching
        incoming pod from its node — the reverse InterPodAffinity
        direction, via the snapshot filter masks."""
        term = apis.PodAffinityTerm(match_labels=(("app", "web"),),
                                    anti=True, required=True)
        groups = [apis.PodGroup(name="guard", queue="q", min_member=1,
                                last_start_timestamp=0.0),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [
            apis.Pod(name="guard-0", group="guard",
                     resources=apis.ResourceVec(1.0, 1.0, 1.0),
                     status=apis.PodStatus.RUNNING, node="n0",
                     pod_affinity=[term]),
            apis.Pod(name="web-0", group="web",
                     resources=apis.ResourceVec(1.0, 1.0, 1.0),
                     labels={"app": "web"}),
        ]
        cluster = Cluster.from_objects(self._nodes(), self._queues(),
                                       groups, pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert by_pod.get("web-0") not in (None, "n0"), by_pod

    def test_reclaim_placement_respects_anti_terms(self):
        """A preemptor placed by RECLAIM claims its domains: a
        conflicting gang placed later in the same cycle (by allocate
        next action or the same wavefront) cannot co-land — the victim
        actions honour and update the claimed-domain table."""
        # 2 nodes x 2 accel, fully occupied by over-quota queue qv;
        # under-served queue q reclaims for two 1-pod gangs that carry
        # mutual anti terms (must land on distinct nodes even though
        # both are placed by reclaim in one cycle).
        nodes = self._nodes(n=2, accel=2.0)
        queues = [
            apis.Queue(name="dept", accel=apis.QueueResource(quota=4.0)),
            apis.Queue(name="q", parent="dept",
                       accel=apis.QueueResource(quota=2.0)),
            apis.Queue(name="qv", parent="dept",
                       accel=apis.QueueResource(quota=1.0)),
        ]
        term = apis.PodAffinityTerm(match_labels=(("app", "ha"),),
                                    anti=True, required=True)
        groups, pods = [], []
        for i in range(4):  # 4 running pods fill both nodes
            groups.append(apis.PodGroup(
                name=f"run-{i}", queue="qv", min_member=1,
                last_start_timestamp=0.0))
            pods.append(apis.Pod(
                name=f"run-{i}-0", group=f"run-{i}",
                resources=apis.ResourceVec(1.0, 1.0, 1.0),
                status=apis.PodStatus.RUNNING, node=f"n{i % 2}"))
        for gname in ("ha-a", "ha-b"):
            groups.append(apis.PodGroup(name=gname, queue="q",
                                        min_member=1))
            pods.append(apis.Pod(
                name=f"{gname}-0", group=gname,
                resources=apis.ResourceVec(1.0, 1.0, 1.0),
                labels={"app": "ha"}, pod_affinity=[term]))
        cluster = Cluster.from_objects(nodes, queues, groups, pods, None)
        res = Scheduler().run_once(cluster)
        placed = np.asarray(res.tensors.placements)
        alloc = np.asarray(res.tensors.allocated)
        # both ha gangs placed (pipelined onto victim capacity), on
        # DISTINCT nodes
        ha_rows = [gi for gi in range(placed.shape[0])
                   if alloc[gi] and (placed[gi] >= 0).any()]
        ha_nodes = [placed[gi][placed[gi] >= 0][0] for gi in ha_rows]
        assert len(res.evictions) >= 2, res.evictions
        assert len(ha_nodes) == 2 and ha_nodes[0] != ha_nodes[1], ha_nodes

    def test_six_terms_widen_slots_no_silent_drop(self):
        """A gang carrying SIX distinct required anti terms gets every
        term enforced in-cycle: the snapshot widens the slot dimension
        to fit (``ANTI_SLOTS`` is a floor, not a cap), so overflow terms
        are never silently dropped (round-4 VERDICT weak 3).

        Ref ``k8s_internal/predicates/predicates.go:70-140`` — upstream
        evaluates EVERY term of every pod, with no term-count cap."""
        terms = [apis.PodAffinityTerm(match_labels=(("app", f"a{i}"),),
                                      anti=True, required=True)
                 for i in range(6)]
        groups = [apis.PodGroup(name=f"l{i}", queue="q", min_member=1)
                  for i in range(6)]
        groups.append(apis.PodGroup(name="hub", queue="q", min_member=1))
        pods = [apis.Pod(name=f"l{i}-0", group=f"l{i}",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": f"a{i}"}) for i in range(6)]
        pods.append(apis.Pod(name="hub-0", group="hub",
                             resources=apis.ResourceVec(1.0, 1.0, 1.0),
                             pod_affinity=terms))
        # 1-accel nodes: every pod owns a node, so each label gang
        # lands somewhere distinct and hub must dodge ALL six
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(1.0, 64.0, 256.0),
                           labels={"kubernetes.io/hostname": f"n{i}"})
                 for i in range(8)]
        state, _ = build_snapshot(nodes, self._queues(), groups, pods, None)
        # hub needs >= 6 slots in each direction -> widened to 8
        assert state.gangs.anti_marks.shape[1] == 8, \
            state.gangs.anti_marks.shape
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 7, by_pod
        label_nodes = {v for k, v in by_pod.items() if k != "hub-0"}
        assert by_pod["hub-0"] not in label_nodes, by_pod


class TestInCycleAttraction:
    """Same-cycle required POSITIVE affinity (round-4 VERDICT item 5):
    a depender whose required positive term matches a gang placed
    earlier this cycle gets its feasibility restricted to the anchor's
    claimed domain instead of failing the prefilter — anchor and
    depender arriving in ONE cycle co-land.

    Ref ``k8s_internal/predicates/predicates.go:70-140`` (InterPodAffinity
    evaluated per task against virtually-allocated session state)."""

    @staticmethod
    def _queues(quota=64.0):
        return [apis.Queue(name="dept", accel=apis.QueueResource(quota=quota)),
                apis.Queue(name="q", parent="dept",
                           accel=apis.QueueResource(quota=quota))]

    def test_anchor_and_depender_coland_same_node(self):
        """web requires app=db on its node; db and web arrive in one
        cycle (db first in creation order) -> both place, co-located."""
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(8.0, 64.0, 256.0),
                           labels={"kubernetes.io/hostname": f"n{i}"})
                 for i in range(4)]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),))
        groups = [apis.PodGroup(name="db", queue="q", min_member=1),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [apis.Pod(name="db-0", group="db",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"}),
                apis.Pod(name="web-0", group="web",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         pod_affinity=[term])]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 2, by_pod
        assert by_pod["web-0"] == by_pod["db-0"], by_pod

    def test_anchor_and_depender_coland_same_rack(self):
        """Rack-level positive term: the depender lands in the anchor's
        rack (not necessarily its node) in the same cycle."""
        topo = apis.Topology("t", levels=["rack", "host"])
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(2.0, 64.0, 256.0),
                           labels={"rack": f"r{i // 3}", "host": f"n{i}"})
                 for i in range(9)]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),),
                                    topology_key="rack")
        groups = [apis.PodGroup(name="db", queue="q", min_member=1),
                  apis.PodGroup(name="web", queue="q", min_member=2)]
        pods = [apis.Pod(name="db-0", group="db",
                         resources=apis.ResourceVec(2.0, 1.0, 1.0),
                         labels={"app": "db"})]
        # 2 accel each: the rack's other nodes must host the dependers
        pods += [apis.Pod(name=f"web-{i}", group="web",
                          resources=apis.ResourceVec(2.0, 1.0, 1.0),
                          pod_affinity=[term]) for i in range(2)]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, topo)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 3, by_pod
        rack = {n: f"r{i // 3}" for i, n in
                enumerate(f"n{j}" for j in range(9))}
        anchor_rack = rack[by_pod["db-0"]]
        assert rack[by_pod["web-0"]] == anchor_rack, by_pod
        assert rack[by_pod["web-1"]] == anchor_rack, by_pod

    def test_depender_without_anchor_fails_cleanly(self):
        """No running or placeable pending match -> the depender does
        not place (and does not land somewhere arbitrary)."""
        nodes = [apis.Node(name="n0",
                           allocatable=apis.ResourceVec(8.0, 64.0, 256.0))]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),))
        # the anchor gang exists but its pod cannot fit (9 accel > 8)
        groups = [apis.PodGroup(name="db", queue="q", min_member=1),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [apis.Pod(name="db-0", group="db",
                         resources=apis.ResourceVec(9.0, 1.0, 1.0),
                         labels={"app": "db"}),
                apis.Pod(name="web-0", group="web",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         pod_affinity=[term])]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert "web-0" not in by_pod, by_pod

    def test_depender_joins_running_match_statically(self):
        """A RUNNING match and a pending anchor coexist: the depender
        may use either domain (static marks pre-fill the table)."""
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(3.0, 64.0, 256.0))
                 for i in range(3)]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),))
        groups = [apis.PodGroup(name="run", queue="q", min_member=1,
                                last_start_timestamp=0.0),
                  apis.PodGroup(name="db", queue="q", min_member=1),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [apis.Pod(name="run-0", group="run",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"},
                         status=apis.PodStatus.RUNNING, node="n0"),
                apis.Pod(name="db-0", group="db",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"}),
                apis.Pod(name="web-0", group="web",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         pod_affinity=[term])]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 2, by_pod
        assert by_pod["web-0"] in ("n0", by_pod["db-0"]), by_pod

    def test_self_match_bootstrap_colocates(self):
        """A gang whose own pods match its positive rack-level term
        places all pods in ONE rack (the upstream greedy: every pod
        joins the first pod's virtual domain), even with no other
        match anywhere."""
        topo = apis.Topology("t", levels=["rack", "host"])
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(1.0, 64.0, 256.0),
                           labels={"rack": f"r{i // 2}", "host": f"n{i}"})
                 for i in range(6)]
        term = apis.PodAffinityTerm(match_labels=(("app", "peer"),),
                                    topology_key="rack")
        groups = [apis.PodGroup(name="peers", queue="q", min_member=2)]
        pods = [apis.Pod(name=f"peer-{i}", group="peers",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "peer"}, pod_affinity=[term])
                for i in range(2)]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, topo)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 2, by_pod
        racks = {int(n[1:]) // 2 for n in by_pod.values()}
        assert len(racks) == 1, by_pod

    def test_mixed_label_anchor_never_violates(self):
        """An anchor gang whose pods do NOT all match the selector may
        not anchor (marking is gang-granular, so a mixed gang would
        claim domains without a matching pod).  The depender defers to
        next-cycle convergence instead of binding beside a non-match."""
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(1.0, 64.0, 256.0))
                 for i in range(4)]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),))
        groups = [apis.PodGroup(name="mixed", queue="q", min_member=2),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [apis.Pod(name="mixed-0", group="mixed",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"}),
                apis.Pod(name="mixed-1", group="mixed",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0)),
                apis.Pod(name="web-0", group="web",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         pod_affinity=[term])]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        # 1-accel nodes: if web placed at all it must share mixed-0's
        # node (the only node that will hold an app=db pod) — with
        # 1 accel per node that is impossible, so web must NOT place
        assert "web-0" not in by_pod, by_pod

    def test_self_fold_keeps_stricter_required_level(self):
        """A rack-level self-affinity term must not LOOSEN an explicit
        host-level required topology constraint (stricter = finer)."""
        topo = apis.Topology("t", levels=["rack", "host"])
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(4.0, 64.0, 256.0),
                           labels={"rack": f"r{i // 2}", "host": f"n{i}"})
                 for i in range(4)]
        term = apis.PodAffinityTerm(match_labels=(("app", "peer"),),
                                    topology_key="rack")
        groups = [apis.PodGroup(
            name="peers", queue="q", min_member=3,
            topology_constraint=apis.TopologyConstraint(
                topology="t", required_level="host"))]
        pods = [apis.Pod(name=f"peer-{i}", group="peers",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "peer"}, pod_affinity=[term])
                for i in range(3)]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, topo)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        assert len(by_pod) == 3, by_pod
        assert len(set(by_pod.values())) == 1, by_pod

    def test_hostname_self_affinity_with_depender_not_weakened(self):
        """A hostname-level self-affine gang coexisting with a depender
        gang must not lose its own enforcement (the attract row
        disables the shared static fold; the self gang gets a need row
        instead): with nothing claimed anywhere, NEITHER may place
        spread across empty hosts."""
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(1.0, 64.0, 256.0))
                 for i in range(4)]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),))
        groups = [apis.PodGroup(name="db", queue="q", min_member=2),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [apis.Pod(name=f"db-{i}", group="db",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"}, pod_affinity=[term])
                for i in range(2)]
        pods.append(apis.Pod(name="web-0", group="web",
                             resources=apis.ResourceVec(1.0, 1.0, 1.0),
                             pod_affinity=[term]))
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, None)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        # 1-accel nodes: db's pods can never share a host, so a correct
        # scheduler binds NOTHING of db (all-or-nothing) and web has no
        # matching host to join
        assert not by_pod, by_pod

    def test_self_anchor_with_running_match_must_join_domain(self):
        """A self-anchored gang with a RUNNING match must still join a
        matched domain even when a depender row disables the shared
        static fold: with the matched rack full, the gang stays pending
        instead of opening a fresh rack (upstream InterPodAffinity)."""
        topo = apis.Topology("t", levels=["rack", "host"])
        nodes = [apis.Node(name=f"n{i}",
                           allocatable=apis.ResourceVec(1.0, 64.0, 256.0),
                           labels={"rack": f"r{i // 2}", "host": f"n{i}"})
                 for i in range(4)]
        term = apis.PodAffinityTerm(match_labels=(("app", "db"),),
                                    topology_key="rack")
        groups = [apis.PodGroup(name="run", queue="q", min_member=1,
                                last_start_timestamp=0.0),
                  apis.PodGroup(name="fill", queue="q", min_member=1,
                                last_start_timestamp=0.0),
                  apis.PodGroup(name="selfg", queue="q", min_member=1),
                  apis.PodGroup(name="web", queue="q", min_member=1)]
        pods = [apis.Pod(name="run-0", group="run",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"},
                         status=apis.PodStatus.RUNNING, node="n0"),
                apis.Pod(name="fill-0", group="fill",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         status=apis.PodStatus.RUNNING, node="n1"),
                apis.Pod(name="self-0", group="selfg",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         labels={"app": "db"}, pod_affinity=[term]),
                apis.Pod(name="web-0", group="web",
                         resources=apis.ResourceVec(1.0, 1.0, 1.0),
                         pod_affinity=[term])]
        cluster = Cluster.from_objects(nodes, self._queues(), groups,
                                       pods, topo)
        res = Scheduler().run_once(cluster)
        by_pod = {b.pod_name: b.selected_node for b in res.bind_requests}
        # rack r0 (the only app=db rack) is full: nothing may bind in
        # r1, where no matching pod exists
        assert not by_pod, by_pod

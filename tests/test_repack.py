"""kai-repack tests — the proactive defragmentation solver
(``ops/repack.py``) and its trigger/execution surfaces (ISSUE 10
tentpole).

Layers:

1. **NumPy-oracle bit-exactness** on randomized small snapshots: the
   kernel's vectorized min-migration solve (fixed marginal unit gains +
   per-rack prefix sums) must match a SEQUENTIAL host reference that
   literally simulates canonical-order evictions one at a time and
   first-fit ascending-node re-placement — pod indices, destination
   nodes, counts and feasibility all exactly equal.
2. **ROADMAP-5 end-to-end scenario**: a fragmented two-rack cluster
   where a rack-required gang is cluster-feasible but rack-stranded —
   the trigger fires after ``repack_trigger_cycles`` high-frag cycles,
   the plan migrates the minimum pods, the gang places within
   ``repack_cooldown + 1`` cycles of the firing, and ``frag_score``
   drops THE SAME cycle the gang places.
3. **No-op guarantees**: repack disabled leaves the stranded gang
   permanently unplaced (seed behavior), and an enabled-but-untriggered
   scheduler produces byte-identical commits and wire bytes to a
   disabled twin on every cycle.
4. **Single rack-domain knob**: ``RepackConfig`` has NO rack_level of
   its own (it embeds the AnalyticsConfig), and the ``rackLevel``
   config-document key steers both gauges and solver at once.
5. **Pipelined-rebind unification**: consolidation moves and repack
   migrations commit through ONE ``Session.pipelined_rebind`` helper
   with identical bind shapes and parallel DecisionLog event shapes.
6. **Coverage meta + endpoint**: the kernel is registered in the jaxpr
   probe and CompileWatcher; ``GET /debug/repack`` serves the trigger
   state.
"""
import dataclasses
import json
import urllib.request

import numpy as np
import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import analytics as pulse
from kai_scheduler_tpu.ops import repack
from kai_scheduler_tpu.ops.allocate import EPS

# ---------------------------------------------------------------------------
# oracle — the sequential reference spec of the repack solve
# ---------------------------------------------------------------------------


def _units_row(avail, valid, unit):
    """f32 — canonical unit pods for one node row (the analytics
    ``_unit_pods_per_node`` formula, sequentially)."""
    f32 = np.float32
    if not valid:
        return f32(0.0)
    if not all(avail[r] + f32(EPS) >= unit[r] for r in range(len(unit))):
        return f32(0.0)
    u = np.inf
    for r in range(len(unit)):
        if unit[r] > 0:
            u = min(u, np.floor(f32(avail[r] / max(unit[r], f32(EPS)))))
    return f32(0.0) if not np.isfinite(u) else f32(max(u, 0.0))


def _oracle_plan(state, ages, cfg):
    """Sequential reference: simulate canonical-order evictions per
    rack one at a time (recomputing unit counts from scratch after
    every eviction) and first-fit ascending-node re-placement."""
    f32 = np.float32
    n, g, r = state.nodes, state.gangs, state.running
    topo = np.asarray(n.topology)
    nvalid = np.asarray(n.valid)
    free = np.maximum(np.asarray(n.free), f32(0.0))
    N, L = topo.shape
    rl = min(max(cfg.analytics.rack_level, 0), L - 1)
    P = cfg.max_migrations
    junk = N * L + N
    empty = dict(move_pod=[], move_node=[], num_moves=0, feasible=False,
                 target_gang=-1, target_rack=-1)

    # target gang: oldest starving rack-required pending gang
    gvalid = np.asarray(g.valid)
    req_level = np.asarray(g.required_level)
    cand = gvalid & (req_level == rl)
    keys = np.where(cand, ages, f32(-1.0))
    target = int(np.argmax(keys))
    if keys[target] <= 0:
        return empty
    unit = np.asarray(g.task_req)[target, 0]
    needed = f32(max(int(np.asarray(g.min_needed)[target]), 0))
    if needed <= 0:
        return empty

    seg = np.full((N,), junk, np.int64)
    for i in range(N):
        if nvalid[i]:
            seg[i] = topo[i, rl] if topo[i, rl] >= 0 else N * L + i
    units0 = np.array([_units_row(free[i], nvalid[i], unit)
                       for i in range(N)], f32)
    have = {}
    for i in range(N):
        if seg[i] != junk:
            have[seg[i]] = f32(have.get(seg[i], f32(0.0)) + units0[i])
    total = f32(units0.sum())
    max_rack = max(have.values(), default=f32(0.0))
    if not (total >= needed and max_rack < needed):
        return empty

    rvalid = np.asarray(r.valid)
    rgang = np.asarray(r.gang)
    # consolidation-mode minruntime protection (victim_candidates):
    # gang runtime = max pod runtime, -1 when never started
    G = gvalid.shape[0]
    grt = np.full((G,), f32(-1.0))
    runt_all = np.asarray(r.runtime_s)
    for m in range(rgang.shape[0]):
        if rvalid[m] and rgang[m] >= 0:
            grt[rgang[m]] = max(grt[rgang[m]], runt_all[m])
    mrt = np.asarray(state.queues.preempt_min_runtime_eff)[
        np.maximum(np.asarray(g.queue), 0)]
    prot_g = (grt >= 0) & (grt < mrt)
    movable = (rvalid & ~np.asarray(r.releasing)
               & np.asarray(r.preemptible) & (np.asarray(r.node) >= 0)
               & (rgang >= 0) & (rgang != target)
               & ~prot_g[np.clip(rgang, 0, G - 1)])
    node_m = np.asarray(r.node)
    prio = np.asarray(r.priority)
    runt = np.asarray(r.runtime_s)
    reqs = np.asarray(r.req)
    order = [m for m in np.lexsort((runt, prio)).tolist() if movable[m]]

    # per-rack sequential simulation: evict in canonical order,
    # recomputing the rack's unit count from scratch each step
    k_of = {}
    victims_of = {}
    for d in sorted({int(seg[node_m[m]]) for m in order}):
        pods_d = [m for m in order if int(seg[node_m[m]]) == d]
        free_d = free.copy()
        taken = []
        found = None
        for k, m in enumerate(pods_d[:P], start=1):
            free_d[node_m[m]] = free_d[node_m[m]] + reqs[m]
            taken.append(m)
            rack_units = f32(sum(
                _units_row(free_d[i], nvalid[i], unit)
                for i in range(N) if seg[i] == d))
            if rack_units >= needed:
                found = k
                break
        if found is not None:
            k_of[d] = found
            victims_of[d] = taken
    if not k_of:
        return empty
    best = min(k_of, key=lambda d: (k_of[d], d))
    victims = victims_of[best]

    # destination: first-fit ascending node id outside the target rack
    fmask = np.asarray(n.filter_masks)
    free_dest = np.where((nvalid & (seg != best))[:, None], free,
                         f32(0.0))
    moves = []
    for m in victims:
        fc = min(max(int(np.asarray(r.filter_class)[m]), 0),
                 fmask.shape[0] - 1)
        dest = -1
        for i in range(N):
            if (nvalid[i] and seg[i] != best and fmask[fc, i]
                    and all(free_dest[i, x] + f32(EPS) >= reqs[m, x]
                            for x in range(reqs.shape[1]))):
                dest = i
                break
        if dest < 0:
            return empty
        free_dest[dest] = free_dest[dest] - reqs[m]
        moves.append((m, dest))
    return dict(move_pod=[m for m, _ in moves],
                move_node=[d for _, d in moves],
                num_moves=len(moves), feasible=True,
                target_gang=target, target_rack=int(best))


def _random_snapshot(seed, **kw):
    from kai_scheduler_tpu.state.cluster_state import build_snapshot
    from kai_scheduler_tpu.state.synthetic import make_cluster
    kw.setdefault("num_nodes", 12)
    kw.setdefault("node_accel", 4.0)
    kw.setdefault("num_gangs", 10)
    kw.setdefault("tasks_per_gang", 3)
    kw.setdefault("running_fraction", 0.6)
    kw.setdefault("priority_spread", 3)
    kw.setdefault("topology_levels", (3,))
    kw.setdefault("required_level", "topo/level0")
    kw.setdefault("seed", seed)
    nodes, queues, groups, pods, topo = make_cluster(**kw)
    return build_snapshot(nodes, queues, groups, pods, topo, now=100.0)


def _stranded_snapshot(seed):
    """A randomized rack-stranded instance: 3 racks x 3 nodes x 4
    accel, each node holding 1-3 single-accel fillers with random
    priorities (a random minority non-preemptible — the movable filter
    must prune them), and a rack-required 8-pod pending gang.  Depending
    on the draw the instance is feasible, infeasible-by-candidacy (some
    rack already hosts the gang / cluster-infeasible), or
    infeasible-by-budget — the oracle must agree everywhere."""
    from kai_scheduler_tpu.state.cluster_state import build_snapshot
    rng = np.random.default_rng(seed)
    topo = apis.Topology(name="default",
                         levels=["topo/rack", "kubernetes.io/hostname"])
    nodes, pods, groups = [], [], []
    for i in range(9):
        name = f"node-{i}"
        nodes.append(apis.Node(
            name, apis.ResourceVec(4, 64, 256),
            labels={"topo/rack": f"rack-{i // 3}",
                    "kubernetes.io/hostname": name}))
    # a random minority of draws protects the fillers via queue
    # preempt-minruntime (fillers start at t<=50, snapshot now=100, so
    # mrt=200 protects everything and mrt=75 a random subset)
    mrt = float(rng.choice([0.0, 0.0, 75.0, 200.0]))
    queues = [apis.Queue("fill", accel=apis.QueueResource(quota=36),
                         preempt_min_runtime=mrt),
              apis.Queue("big", accel=apis.QueueResource(quota=8))]
    gi = 0
    for i in range(9):
        for t in range(int(rng.integers(1, 4))):
            kind = (apis.Preemptibility.NON_PREEMPTIBLE
                    if rng.random() < 0.2
                    else apis.Preemptibility.PREEMPTIBLE)
            grp = apis.PodGroup(
                f"fill-{gi}", queue="fill", min_member=1,
                priority=int(rng.integers(0, 3)), preemptibility=kind,
                last_start_timestamp=float(rng.integers(0, 50)))
            groups.append(grp)
            pods.append(apis.Pod(
                f"fill-{gi}-0", grp.name, apis.ResourceVec(1, 1, 4),
                status=apis.PodStatus.RUNNING, node=f"node-{i}"))
            gi += 1
    gang = apis.PodGroup(
        "big-gang", queue="big", min_member=8,
        topology_constraint=apis.TopologyConstraint(
            topology="default", required_level="topo/rack"))
    groups.append(gang)
    for t in range(8):
        pods.append(apis.Pod(f"big-{t}", "big-gang",
                             apis.ResourceVec(1, 1, 4)))
    return build_snapshot(nodes, queues, groups, pods, topo, now=100.0)


def _randomized_case(family, seed):
    """(state, ages) for one oracle-equivalence draw."""
    import jax.numpy as jnp
    rng = np.random.default_rng(seed + 1000)
    if family == "random":
        state, _ = _random_snapshot(seed)
        # perturb the free pool so unit counts vary per node
        state = state.replace(nodes=state.nodes.replace(
            free=jnp.maximum(
                state.nodes.free
                - jnp.asarray(rng.integers(0, 3, state.nodes.free.shape)
                              .astype(np.float32)), 0.0)))
    else:
        state, _ = _stranded_snapshot(seed)
    ages = np.zeros((state.gangs.g,), np.float32)
    idx = np.nonzero(np.asarray(state.gangs.valid))[0]
    ages[idx] = rng.integers(0, 6, idx.size).astype(np.float32)
    return state, ages


@pytest.mark.parametrize("family,seed", [
    ("random", 0), ("random", 1), ("random", 2),
    ("stranded", 0), ("stranded", 1), ("stranded", 2), ("stranded", 3),
])
def test_numpy_oracle_bit_exactness(family, seed):
    """The vectorized min-migration solve == the sequential eviction
    simulation, bit for bit (integer-valued resources keep f32 exact)."""
    state, ages = _randomized_case(family, seed)
    cfg = repack.RepackConfig(max_migrations=8)
    # destinations drawn from the snapshot pool (the oracle's view;
    # production passes the cycle's post-decision AllocationResult.free)
    plan = repack.plan_repack_jit(state, ages, state.nodes.free,
                                  config=cfg)
    want = _oracle_plan(state, ages, cfg)
    assert bool(plan.feasible) == want["feasible"]
    if not want["feasible"]:
        assert int(plan.num_moves) == 0
        assert np.all(np.asarray(plan.move_pod) == -1)
        return
    assert int(plan.target_gang) == want["target_gang"]
    assert int(plan.target_rack) == want["target_rack"]
    assert int(plan.num_moves) == want["num_moves"]
    mp = np.asarray(plan.move_pod)
    mn = np.asarray(plan.move_node)
    live = mp >= 0
    np.testing.assert_array_equal(mp[live], np.asarray(want["move_pod"]))
    np.testing.assert_array_equal(mn[live],
                                  np.asarray(want["move_node"]))


def test_oracle_exercises_both_outcomes():
    """The randomized families must cover feasible AND infeasible plans
    — otherwise the bit-exactness parametrization proves less than it
    claims."""
    cfg = repack.RepackConfig(max_migrations=8)
    outcomes = {
        _oracle_plan(*_randomized_case(family, seed), cfg)["feasible"]
        for family, seed in (("random", 0), ("stranded", 0),
                             ("stranded", 1), ("stranded", 2),
                             ("stranded", 3))}
    assert outcomes == {True, False}


# ---------------------------------------------------------------------------
# the ROADMAP-5 end-to-end scenario
# ---------------------------------------------------------------------------

RACK = "topo/rack"


def _frag_cluster(preemptible_fillers=True):
    """Two racks x 4 nodes x 4 accel, every node 3/4 full with fillers:
    each rack strands 4 free devices, so a rack-required 8-pod gang is
    cluster-feasible (8 free) but unplaceable in any single rack.  With
    PREEMPTIBLE fillers the repack solver can free a rack by migrating
    4 of them across; the PR-9 analytics scenario used non-preemptible
    fillers precisely so nothing could."""
    from kai_scheduler_tpu.runtime.cluster import Cluster
    topo = apis.Topology(name="default",
                         levels=[RACK, "kubernetes.io/hostname"])
    nodes, pods, groups = [], [], []
    for i in range(8):
        name = f"node-{i}"
        nodes.append(apis.Node(
            name, apis.ResourceVec(4, 64, 256),
            labels={RACK: f"rack-{i // 4}",
                    "kubernetes.io/hostname": name}))
    queues = [apis.Queue("fill", accel=apis.QueueResource(quota=24)),
              apis.Queue("big", accel=apis.QueueResource(quota=8))]
    kind = (apis.Preemptibility.PREEMPTIBLE if preemptible_fillers
            else apis.Preemptibility.NON_PREEMPTIBLE)
    for i in range(8):
        g = apis.PodGroup(f"fill-{i}", queue="fill", min_member=3,
                          preemptibility=kind, last_start_timestamp=0.0)
        groups.append(g)
        for t in range(3):
            pods.append(apis.Pod(
                f"fill-{i}-{t}", g.name, apis.ResourceVec(1, 1, 4),
                status=apis.PodStatus.RUNNING, node=f"node-{i}"))
    gang = apis.PodGroup(
        "big-gang", queue="big", min_member=8,
        topology_constraint=apis.TopologyConstraint(
            topology="default", required_level=RACK))
    groups.append(gang)
    for t in range(8):
        pods.append(apis.Pod(f"big-{t}", "big-gang",
                             apis.ResourceVec(1, 1, 4)))
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def _repack_cfg(**kw):
    from kai_scheduler_tpu.framework.scheduler import SchedulerConfig
    # consolidation excluded: it is the REACTIVE mover and would race
    # the proactive solver for the same fillers — this scenario isolates
    # the repack path (the production default keeps both; first mover
    # wins and the other finds nothing left to move)
    kw.setdefault("actions",
                  ("allocate", "reclaim", "preempt", "stalegangeviction"))
    kw.setdefault("repack_frag_threshold", 0.2)
    kw.setdefault("repack_trigger_cycles", 2)
    kw.setdefault("repack_cooldown", 3)
    return SchedulerConfig(**kw)


def test_repack_unblocks_rack_required_gang():
    """The acceptance scenario: trigger fires after the streak, the
    plan migrates the minimum 4 fillers within budget, the gang places
    within ``repack_cooldown + 1`` cycles of the firing, and the
    fragmentation score drops the SAME cycle it places."""
    from kai_scheduler_tpu.binder import Binder
    from kai_scheduler_tpu.framework import metrics
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    cluster = _frag_cluster()
    cfg = _repack_cfg()
    sched, binder = Scheduler(cfg), Binder()
    unblocked0 = metrics.repack_gangs_unblocked.value()
    fired_cycle = placed_cycle = None
    stranded_score = None
    for cyc in range(1, 10):
        res = sched.run_once(cluster)
        if stranded_score is None:
            stranded_score = res.analytics["fragmentation"]["score"]
        if res.repack:
            assert fired_cycle is None, "repack fired twice (no cooldown)"
            fired_cycle = cyc
            assert res.repack["feasible"]
            assert res.repack["target_gang"] == "big-gang"
            # min-migration: exactly one filler per target-rack node,
            # within the configured budget
            assert res.repack["migrations_executed"] == 4
            assert (res.repack["migrations_executed"]
                    <= cfg.repack_max_migrations)
            assert res.repack["rack_units_after"] >= 8.0
            moved = [ev for ev in res.evictions if ev.reason == "repack"]
            assert len(moved) == 4
            assert all(ev.move_to is not None for ev in moved)
            assert len(res.move_bind_requests) == 4
        if any(b.pod_name.startswith("big-")
               for b in res.bind_requests):
            placed_cycle = cyc
            # frag_score drops the unblocking cycle (the predictive
            # property: fragmentation reads the pre-decision pool the
            # repacked capacity now consolidates)
            assert (res.analytics["fragmentation"]["score"]
                    < stranded_score)
            assert len([b for b in res.bind_requests
                        if b.pod_name.startswith("big-")]) == 8
            break
        binder.reconcile(cluster)
        cluster.tick()
    assert stranded_score > 0.2          # the trigger's signal was real
    assert fired_cycle is not None, "repack trigger never fired"
    assert fired_cycle == cfg.repack_trigger_cycles + 1
    assert placed_cycle is not None, "gang never placed"
    assert placed_cycle - fired_cycle <= cfg.repack_cooldown + 1
    # the payoff metric observed the unblock
    assert metrics.repack_gangs_unblocked.value() == unblocked0 + 1
    # repacked-for decision events name the beneficiary
    evs = [e for e in sched.decisions.events()
           if e["outcome"] == "repacked-for"]
    assert evs and all("big-gang" in e["detail"] for e in evs)
    # /debug/repack status doc reflects the firing
    status = sched.repack_status()
    assert status["ok"] and status["last"]["target_gang"] == "big-gang"
    assert status["last"]["migrations_executed"] == 4


def test_minruntime_protected_fillers_are_not_movable():
    """The consolidation-mode victim protection applies to repack too:
    fillers inside their queue's preempt-minruntime window expose no
    movable pods, so the plan is infeasible until they age out."""
    from kai_scheduler_tpu.state.cluster_state import build_snapshot

    def snap(mrt):
        cluster = _frag_cluster()
        cluster.queues["fill"] = dataclasses.replace(
            cluster.queues["fill"], preempt_min_runtime=mrt)
        cluster.now = 100.0
        return build_snapshot(*cluster.snapshot_lists(), now=cluster.now)

    cfg = repack.RepackConfig()
    for mrt, want in ((1000.0, False), (50.0, True)):
        state, index = snap(mrt)
        ages = np.zeros((state.gangs.g,), np.float32)
        ages[index.gang_names.index("big-gang")] = 3.0
        plan = repack.plan_repack_jit(state, ages, state.nodes.free,
                                      config=cfg)
        assert bool(plan.feasible) is want, mrt
        assert _oracle_plan(state, ages, cfg)["feasible"] is want


def test_unblock_metric_with_zero_cooldown():
    """Regression for the watch window arithmetic: with
    ``repack_cooldown=0`` the same-cycle decrement must not expire the
    observation window before the gang's next-cycle placement."""
    from kai_scheduler_tpu.binder import Binder
    from kai_scheduler_tpu.framework import metrics
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    cluster = _frag_cluster()
    sched, binder = Scheduler(_repack_cfg(repack_cooldown=0)), Binder()
    base = metrics.repack_gangs_unblocked.value()
    for _ in range(8):
        res = sched.run_once(cluster)
        if any(b.pod_name.startswith("big-") for b in res.bind_requests):
            break
        binder.reconcile(cluster)
        cluster.tick()
    else:
        raise AssertionError("gang never placed")
    assert metrics.repack_gangs_unblocked.value() == base + 1


def test_repack_disabled_leaves_gang_stranded():
    """Seed behavior with the knob off: the rack-required gang stays
    permanently unplaceable and no migration ever happens."""
    from kai_scheduler_tpu.binder import Binder
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    cluster = _frag_cluster()
    sched = Scheduler(_repack_cfg(repack_enable=False))
    binder = Binder()
    for _ in range(6):
        res = sched.run_once(cluster)
        assert res.repack == {}
        assert res.evictions == []
        assert not any(b.pod_name.startswith("big-")
                       for b in res.bind_requests)
        binder.reconcile(cluster)
        cluster.tick()
    assert sched.repack_status()["ok"] is False


def test_untriggered_repack_is_byte_identical_to_disabled():
    """Zero overhead below threshold: an enabled scheduler whose
    trigger never fires commits byte-identically to a disabled twin —
    same bind/eviction documents, same wire bytes, every cycle."""
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.framework.server import _commit_doc
    from kai_scheduler_tpu.runtime.cluster import Cluster
    from kai_scheduler_tpu.state.synthetic import make_cluster

    def run(enable: bool):
        nodes, queues, groups, pods, topo = make_cluster(
            num_nodes=16, num_gangs=12, tasks_per_gang=2,
            running_fraction=0.5, seed=7)
        cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
        sched = Scheduler(_repack_cfg(repack_enable=enable))
        rows = []
        for step in range(6):
            res = sched.run_once(cluster)
            assert res.repack == {} and res.repack_seconds == 0.0
            doc = _commit_doc(res)
            doc.pop("action_seconds")         # wall time, not a commit
            rows.append((json.dumps(doc, sort_keys=True),
                         res.wire["bytes"]))
            running = sorted(p.name for p in cluster.pods.values()
                             if p.status == apis.PodStatus.RUNNING)
            if running:
                cluster.evict_pod(running[step % len(running)])
            cluster.tick()
        return rows

    assert run(True) == run(False)


# ---------------------------------------------------------------------------
# single rack-domain knob
# ---------------------------------------------------------------------------


def test_rack_level_has_one_source_of_truth():
    """``RepackConfig`` carries NO rack level of its own — it embeds the
    AnalyticsConfig, so the fragmentation trigger and the solver derive
    the rack partition from the same knob by construction."""
    fields = {f.name for f in dataclasses.fields(repack.RepackConfig)}
    assert "rack_level" not in fields
    assert fields == {"analytics", "max_migrations"}
    # the embedded config IS the analytics one (same dataclass, which
    # carries the one rack_level the gauges use)
    assert (type(repack.RepackConfig().analytics)
            is pulse.AnalyticsConfig)


def test_conf_rack_level_knob_plumbs_both_consumers():
    from kai_scheduler_tpu.conf import effective_config_doc, load_config
    cfg = load_config({"rackLevel": 1,
                       "repack": {"fragThreshold": 0.7,
                                  "triggerCycles": 3,
                                  "cooldownCycles": 5,
                                  "maxMigrations": 16,
                                  "enabled": True}})
    assert cfg.session.analytics.rack_level == 1
    assert cfg.repack_frag_threshold == 0.7
    assert cfg.repack_trigger_cycles == 3
    assert cfg.repack_cooldown == 5
    assert cfg.repack_max_migrations == 16
    # the solver config built the way the scheduler builds it sees the
    # SAME level — there is no second field to diverge
    rcfg = repack.RepackConfig(analytics=cfg.session.analytics)
    assert rcfg.analytics.rack_level == 1
    doc = effective_config_doc(cfg)
    assert doc["rackLevel"] == 1
    assert doc["repack"]["maxMigrations"] == 16
    # round-trip: feeding the effective repack/rack keys back keeps them
    cfg2 = load_config({"rackLevel": doc["rackLevel"],
                        "repack": doc["repack"]})
    assert cfg2.session.analytics.rack_level == 1
    assert cfg2.repack_cooldown == 5


# ---------------------------------------------------------------------------
# pipelined-rebind unification (consolidation move == repack move path)
# ---------------------------------------------------------------------------


def _consolidation_cluster():
    from kai_scheduler_tpu.runtime.cluster import Cluster
    nodes = [apis.Node(f"node-{i}", apis.ResourceVec(4.0, 64.0, 256.0))
             for i in range(2)]
    queues = [apis.Queue("q0", accel=apis.QueueResource(quota=8.0))]
    frag0 = apis.PodGroup("frag0", queue="q0", min_member=1,
                          last_start_timestamp=0.0)
    frag1 = apis.PodGroup("frag1", queue="q0", min_member=1,
                          creation_timestamp=0.5,
                          last_start_timestamp=0.5)
    pending = apis.PodGroup("big", queue="q0", min_member=1,
                            creation_timestamp=1.0)
    pods = [
        apis.Pod("f0", "frag0", resources=apis.ResourceVec(2.0, 1.0, 4.0),
                 status=apis.PodStatus.RUNNING, node="node-0",
                 accel_devices=[0, 1]),
        apis.Pod("f1", "frag1", resources=apis.ResourceVec(2.0, 1.0, 4.0),
                 status=apis.PodStatus.RUNNING, node="node-1",
                 accel_devices=[0, 1]),
        apis.Pod("big-0", "big", resources=apis.ResourceVec(4.0, 1.0, 4.0),
                 creation_timestamp=1.0),
    ]
    c = Cluster.from_objects(nodes, queues, [frag0, frag1, pending], pods)
    c.now = 100.0
    return c


def test_consolidation_and_repack_share_one_rebind_path(monkeypatch):
    """Both movers flow through ``Session.pipelined_rebind`` (counted),
    emit BindRequests of identical shape, and log DecisionLog events of
    identical shape — the satellite's regression bar."""
    from kai_scheduler_tpu.framework.scheduler import (Scheduler,
                                                       SchedulerConfig)
    from kai_scheduler_tpu.framework.session import Session
    calls = []
    orig = Session.pipelined_rebind

    def spy(self, cluster, ev):
        out = orig(self, cluster, ev)
        calls.append((ev.reason, ev.pod_name, out))
        return out

    monkeypatch.setattr(Session, "pipelined_rebind", spy)

    # consolidation move
    sched_c = Scheduler(SchedulerConfig())
    res_c = sched_c.run_once(_consolidation_cluster())
    consol = [c for c in calls if c[0] != "repack"]
    assert len(consol) == len(res_c.move_bind_requests) == 1

    # repack move
    calls.clear()
    sched_r = Scheduler(_repack_cfg())
    cluster = _frag_cluster()
    res_r = None
    for _ in range(4):
        res_r = sched_r.run_once(cluster)
        if res_r.repack:
            break
        cluster.tick()
    assert res_r is not None and res_r.repack
    rep = [c for c in calls if c[0] == "repack"]
    assert len(rep) == len(res_r.move_bind_requests) == 4

    # identical bind SHAPE: same dataclass fields populated the same way
    bc, br = res_c.move_bind_requests[0], res_r.move_bind_requests[0]
    assert dataclasses.asdict(bc).keys() == dataclasses.asdict(br).keys()
    for b in (bc, br):
        assert b.received_resource_type == apis.ReceivedResourceType.REGULAR
        assert b.phase == "Pending"
        assert b.backoff_limit == 3
    # identical EVENT shape: same doc keys, the shared rebind phrasing,
    # outcomes split only by mover
    ev_c = [e for e in sched_c.decisions.events()
            if e["outcome"] == "preempted-for"
            and "pipelined rebind" in e["detail"]][0]
    ev_r = [e for e in sched_r.decisions.events()
            if e["outcome"] == "repacked-for"][0]
    assert ev_c.keys() == ev_r.keys()
    assert "(pipelined rebind)" in ev_c["detail"]
    assert "(pipelined rebind)" in ev_r["detail"]


def test_gang_with_repack_and_plain_evictions_reports_both():
    """A gang can lose pods to a repack migration AND a plain
    preemption in one cycle — the DecisionLog must report BOTH
    outcomes (counts and events), not collapse them into one."""
    from kai_scheduler_tpu.framework.session import Session, SessionConfig
    from kai_scheduler_tpu.ops.allocate import init_result
    from kai_scheduler_tpu.runtime import events as gang_events
    state, index = _stranded_snapshot(0)
    session = Session.from_state(state, index, SessionConfig())
    res = init_result(state)
    host = session.gather_host(res)
    group = index.gang_names[0]
    evictions = [
        apis.Eviction(pod_name="p0", group=group,
                      reason=Session.REPACK_REASON, move_to="node-1"),
        apis.Eviction(pod_name="p1", group=group),
    ]
    events, _dropped, counts = session.decision_events(
        res, host=host, evictions=evictions, repack_for="big-gang")
    assert counts[gang_events.OUTCOME_REPACKED_FOR] == 1
    assert counts[gang_events.OUTCOME_PREEMPTED_FOR] == 1
    got = {e.outcome for e in events if e.gang == group}
    assert {gang_events.OUTCOME_REPACKED_FOR,
            gang_events.OUTCOME_PREEMPTED_FOR} <= got


# ---------------------------------------------------------------------------
# coverage meta + endpoint
# ---------------------------------------------------------------------------


def test_repack_registered_in_probe_and_watcher():
    from kai_scheduler_tpu.analysis.trace_probe import registered_ops
    from kai_scheduler_tpu.runtime.compile_watch import WATCHER
    assert "repack" in registered_ops()
    assert "repack" in WATCHER.entries()
    assert hasattr(repack.plan_repack_jit, "_cache_size")


def test_debug_repack_endpoint():
    from kai_scheduler_tpu.framework.scheduler import Scheduler
    from kai_scheduler_tpu.framework.server import SchedulerServer
    srv = SchedulerServer(_frag_cluster(), Scheduler(_repack_cfg()))
    srv.start()
    base = f"http://127.0.0.1:{srv.port}"
    try:
        doc = json.load(urllib.request.urlopen(
            f"{base}/debug/repack", timeout=10))
        assert doc["ok"] is False and doc["enabled"] is True
        assert doc["frag_threshold"] == 0.2
        assert doc["last"] == {}
        # drive stored cycles until the trigger fires; the endpoint
        # then serves the firing's immutable plan doc
        for _ in range(3):
            req = urllib.request.Request(f"{base}/cycle/stored",
                                         data=b"", method="POST")
            urllib.request.urlopen(req, timeout=60).read()
        doc = json.load(urllib.request.urlopen(
            f"{base}/debug/repack", timeout=10))
        assert doc["ok"] is True
        assert doc["last"]["target_gang"] == "big-gang"
        assert doc["cooldown_remaining"] > 0
    finally:
        srv.stop()

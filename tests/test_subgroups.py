"""Hierarchical subgroup gang allocation — ref
``actions/common/allocate.go:71-140`` (allocateSubGroupSet) and the
``allocate_subgroups_test.go`` shapes: per-subgroup quorums and
per-subgroup topology domains, atomic per chunk."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import build_snapshot

TOPO = apis.Topology("t", levels=["rack", "host"])


def _rack_nodes(racks=2, per_rack=2, accel=2.0):
    return [
        apis.Node(f"n{r}-{i}", apis.ResourceVec(accel, 64, 256),
                  labels={"rack": f"r{r}", "host": f"n{r}-{i}"})
        for r in range(racks) for i in range(per_rack)]


def _queue():
    return [apis.Queue("q", accel=apis.QueueResource(quota=100))]


def _pytorch_gang(workers=4, rack_required=True):
    """Leader(min 1) + workers(min N, rack-constrained) — the
    PyTorchJob-style subgroup tree the podgrouper produces."""
    tc = (apis.TopologyConstraint(topology="t", required_level="rack")
          if rack_required else None)
    group = apis.PodGroup(
        "ptj", queue="q", min_member=1 + workers,
        sub_groups=[
            apis.SubGroup("leader", min_member=1),
            apis.SubGroup("worker", min_member=workers,
                          topology_constraint=tc),
        ])
    pods = [apis.Pod("leader-0", "ptj", apis.ResourceVec(1, 1, 1),
                     subgroup="leader")]
    pods += [apis.Pod(f"worker-{i}", "ptj", apis.ResourceVec(1, 1, 1),
                      subgroup="worker") for i in range(workers)]
    return group, pods


def run_allocate(state, **cfg):
    fs = drf.set_fair_share(state, num_levels=1)
    state = state.replace(queues=state.queues.replace(fair_share=fs))
    return allocate(state, fs, num_levels=1, config=AllocateConfig(**cfg))


def test_subgroup_rack_constraint_packs_workers_in_one_rack():
    group, pods = _pytorch_gang(workers=4)
    state, idx = build_snapshot(_rack_nodes(), _queue(), [group], pods,
                                TOPO)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    pl = np.asarray(res.placements)[0]
    names = [idx.node_names[n] for n in pl if n >= 0]
    assert len(names) == 5
    # tasks sort leader-first (same priority, name order keeps input
    # order); workers are the rack-constrained subgroup — all 4 workers
    # share one rack
    worker_nodes = [idx.node_names[pl[t]]
                    for t, pod in enumerate(idx.task_names[0])
                    if pod and pod.startswith("worker")]
    racks = {n.split("-")[0] for n in worker_nodes}
    assert len(racks) == 1, worker_nodes


def test_subgroup_gang_fails_atomically_when_rack_too_small():
    """5 workers need one rack; racks hold only 4 accel: nothing places."""
    group, pods = _pytorch_gang(workers=5)
    state, _ = build_snapshot(_rack_nodes(), _queue(), [group], pods, TOPO)
    res = run_allocate(state)
    assert not np.asarray(res.allocated)[0]
    assert (np.asarray(res.placements)[0] == -1).all()


def test_subgroup_quorums_enforced_independently():
    """Leader fits but workers' quorum does not -> atomic failure, even
    though gang min_member would allow elastic partial placement."""
    group, pods = _pytorch_gang(workers=4, rack_required=False)
    group.min_member = 1  # gang-level would tolerate leader alone
    nodes = [apis.Node("only", apis.ResourceVec(2, 64, 256))]
    state, _ = build_snapshot(nodes, _queue(), [group], pods)
    res = run_allocate(state)
    assert not np.asarray(res.allocated)[0]


def test_subgroups_unconstrained_span_racks():
    """Without the rack constraint 5 workers may span racks."""
    group, pods = _pytorch_gang(workers=5, rack_required=False)
    state, _ = build_snapshot(_rack_nodes(), _queue(), [group], pods, TOPO)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    assert int((np.asarray(res.placements)[0] >= 0).sum()) == 6


def test_subgroup_running_pods_count_toward_quorum():
    """Workers already running reduce the subgroup's needed quorum."""
    group, pods = _pytorch_gang(workers=4, rack_required=False)
    # two workers already running on a node
    nodes = _rack_nodes()
    running = [
        apis.Pod(f"old-worker-{i}", "ptj", apis.ResourceVec(1, 1, 1),
                 subgroup="worker", status=apis.PodStatus.RUNNING,
                 node="n0-0") for i in range(2)]
    pending = [p for p in pods if p.name in
               ("leader-0", "worker-0", "worker-1")]
    state, _ = build_snapshot(nodes, _queue(), [group], running + pending,
                              TOPO)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    assert int((np.asarray(res.placements)[0] >= 0).sum()) == 3


def test_end_to_end_cycle_with_subgroups():
    group, pods = _pytorch_gang(workers=4)
    cluster = Cluster.from_objects(_rack_nodes(), _queue(), [group], pods,
                                   TOPO)
    res = Scheduler().run_once(cluster)
    assert len(res.bind_requests) == 5

"""kai-trace tests — cycle flight recorder, per-gang decision events,
and the debug endpoints (ISSUE 6 tentpole).

Covers the acceptance properties directly:

* the cycle's phase breakdown (snapshot / upload / solve_dispatch /
  device_wait / host_decode / commit) partitions the measured wall time
  (contiguous checkpoints on one clock — within 10% by construction);
* ``GET /debug/trace`` returns valid Chrome-trace JSON (loadable by
  ``json.loads``) whose events are strictly nested per lane;
* ``GET /debug/events?gang=`` answers "why is my job not running";
* the endpoints never serve torn documents under a concurrent cycle
  hammer (the kai-race cleanliness half lives in tests/test_analysis.py,
  which lints the new modules with the rest of the package).
"""
import json
import urllib.request

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler, SchedulerConfig
from kai_scheduler_tpu.framework.server import SchedulerServer
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.events import DecisionLog, GangDecision
from kai_scheduler_tpu.runtime.tracing import CycleTracer

PHASES = {"snapshot", "upload", "solve_dispatch", "device_wait",
          "host_decode", "commit"}


def _small_cluster():
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    groups = [apis.PodGroup("g", queue="q", min_member=1),
              apis.PodGroup("toobig", queue="q", min_member=1)]
    pods = [apis.Pod("p", "g", apis.ResourceVec(1, 1, 1)),
            apis.Pod("pb", "toobig", apis.ResourceVec(64, 1, 1))]
    return Cluster.from_objects(nodes, queues, groups, pods)


def _preempt_cluster():
    """One node saturated by a low-priority gang, a boosted pending
    gang — preempt must evict (mirrors test_metrics_logging)."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    low = apis.PodGroup("low", queue="q", min_member=1, priority=1,
                        last_start_timestamp=0.0)
    high = apis.PodGroup("high", queue="q", min_member=2, priority=9,
                         creation_timestamp=1.0)
    pods = [apis.Pod(f"v{i}", "low", apis.ResourceVec(1, 1, 4),
                     status=apis.PodStatus.RUNNING, node="n0")
            for i in range(8)]
    pods += [apis.Pod(f"h{i}", "high", apis.ResourceVec(2, 1, 4),
                      creation_timestamp=1.0) for i in range(2)]
    cluster = Cluster.from_objects(nodes, queues, [low, high], pods)
    cluster.now = 100.0
    return cluster


def _assert_strictly_nested(doc: dict) -> int:
    """Chrome-trace "X" events must nest per (pid, tid) lane: any two
    either disjoint or one containing the other.  Returns the event
    count checked."""
    lanes: dict = {}
    for e in doc["traceEvents"]:
        if e.get("ph") != "X":
            continue
        assert {"name", "ts", "dur", "pid", "tid", "args"} <= set(e)
        lanes.setdefault((e["pid"], e["tid"]), []).append(e)
    eps = 0.5  # us of float-rounding slack
    total = 0
    for evs in lanes.values():
        evs.sort(key=lambda e: (e["ts"], -e["dur"]))
        stack = []
        for e in evs:
            while stack and e["ts"] >= (stack[-1]["ts"]
                                        + stack[-1]["dur"] - eps):
                stack.pop()
            if stack:
                parent = stack[-1]
                assert (e["ts"] + e["dur"]
                        <= parent["ts"] + parent["dur"] + eps), (
                    f"partial overlap: {e['name']} vs {parent['name']}")
            stack.append(e)
            total += 1
    return total


# ---------------------------------------------------------------------------
# tracer unit behaviour
# ---------------------------------------------------------------------------


def test_tracer_nesting_ring_and_detached_spans():
    tr = CycleTracer(retain_cycles=3)
    # a span outside any cycle records nothing (bench/CLI paths)
    with tr.span("orphan") as sp:
        sp.attrs["x"] = 1
    assert tr.last() == [] and tr.export_chrome()["traceEvents"]
    for i in range(5):
        with tr.cycle(n=i) as trace:
            with tr.span("a"):
                with tr.span("b", device_sync=True):
                    pass
            tr.add_span("c", trace.root.start, trace.root.start + 0.001,
                        leaves=2)
    ring = tr.last(10)
    assert len(ring) == 3  # bounded
    assert [t.cycle_id for t in ring] == [2, 3, 4]
    t = ring[-1]
    assert [s.name for s in t.root.children] == ["a", "c"]
    assert t.root.children[0].children[0].device_sync is True
    assert t.phase_seconds().keys() == {"a", "c"}
    doc = tr.export_chrome(cycles=2)
    json.loads(json.dumps(doc))  # fully JSON-serializable
    assert _assert_strictly_nested(doc) >= 6
    # the device-sync marker survives export
    marks = [e for e in doc["traceEvents"]
             if e.get("args", {}).get("device_sync")]
    assert marks and all(e["name"] == "b" for e in marks)


def test_tracer_thread_local_recording():
    """Two threads recording cycles concurrently never corrupt each
    other's span trees (the open trace is thread-local; only completed
    traces ring)."""
    import threading

    tr = CycleTracer(retain_cycles=64)
    errors = []

    def run(tag):
        try:
            for _ in range(20):
                with tr.cycle(tag=tag):
                    with tr.span(f"{tag}-outer"):
                        with tr.span(f"{tag}-inner"):
                            pass
        except Exception as exc:  # noqa: BLE001
            errors.append(exc)

    threads = [threading.Thread(target=run, args=(f"t{i}",))
               for i in range(4)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not errors
    for trace in tr.last(64):
        tag = trace.root.attrs["tag"]
        assert [s.name for s in trace.root.children] == [f"{tag}-outer"]
        assert ([s.name for s in trace.root.children[0].children]
                == [f"{tag}-inner"])
    _assert_strictly_nested(tr.export_chrome())


def test_decision_log_bounds_and_query():
    log = DecisionLog(retain_cycles=2, max_events_per_cycle=3)
    evs = [GangDecision(gang=f"g{i}", queue="q", outcome="allocated")
           for i in range(5)]
    log.record_cycle(0, evs, dropped=1)
    log.record_cycle(1, [GangDecision(gang="g0", queue="q",
                                      outcome="fit-failure",
                                      detail="no node")])
    log.record_cycle(2, [])
    s = log.summary()
    assert s["cycle"] == 2 and s["events"] == 0
    got = log.events(gang="g0")
    # newest cycle first; cycle 0 fell off the 2-cycle ring
    assert [e["cycle"] for e in got] == [1]
    assert got[0]["outcome"] == "fit-failure"
    # the per-cycle cap adds to the producer's dropped count
    log.record_cycle(3, evs, dropped=2)
    assert log.summary()["dropped"] == 2 + 2 and log.summary()["events"] == 3


# ---------------------------------------------------------------------------
# the instrumented cycle
# ---------------------------------------------------------------------------


def test_phase_breakdown_partitions_wall_time():
    cluster = _small_cluster()
    sched = Scheduler()
    sched.run_once(cluster)           # compile
    res = sched.run_once(cluster)     # measured cycle
    assert set(res.phase_seconds) == PHASES
    total = sum(res.phase_seconds.values())
    # contiguous checkpoints on one clock: the phases partition the
    # cycle wall (well inside the 10% acceptance bar)
    assert total <= res.session_seconds * 1.001 + 1e-6
    assert total >= res.session_seconds * 0.9
    # legacy wall fields still line up with the phase view
    assert abs(res.open_seconds
               - (res.phase_seconds["snapshot"]
                  + res.phase_seconds["upload"])) < 1e-6
    assert res.commit_seconds >= res.phase_seconds["device_wait"]


def test_trace_and_result_phase_surfaces_agree():
    """The two phase-attribution surfaces — CycleResult.phase_seconds
    (contiguous checkpoints) and CycleTrace.phase_seconds() (span-
    derived, with the upload child promoted) — must agree per phase, so
    /debug/trace numbers and the metrics/healthz/bench numbers can be
    cross-checked.  Guards against a phase added to one surface only."""
    cluster = _small_cluster()
    sched = Scheduler()
    sched.run_once(cluster)           # compile
    cluster.tick()
    res = sched.run_once(cluster)     # warm cycle
    trace_phases = sched.tracer.last(1)[0].phase_seconds()
    for phase, secs in res.phase_seconds.items():
        got = trace_phases.get(phase, 0.0)
        # spans bracket the work tightly while checkpoints partition the
        # timeline, so tiny inter-phase slivers are tolerated
        assert abs(got - secs) < max(0.005, 0.05 * secs), (
            phase, got, secs)
    stray = set(trace_phases) - set(res.phase_seconds) - {"cycle"}
    assert not stray, f"span-only phases missing from the result: {stray}"


def test_cycle_trace_spans_and_chrome_export():
    cluster = _small_cluster()
    sched = Scheduler()
    sched.run_once(cluster)
    sched.run_once(cluster)
    traces = sched.tracer.last(2)
    assert len(traces) == 2
    names = {s.name for s in traces[-1].root.children}
    assert {"snapshot", "solve_dispatch", "device_wait", "host_decode",
            "commit"} <= names
    # the device-sync marker brackets the first blocking transfer
    dw = [s for s in traces[-1].root.children if s.name == "device_wait"]
    assert dw and dw[0].device_sync
    # snapshot span carries the journal-delta attribution
    snap = [s for s in traces[-1].root.children if s.name == "snapshot"]
    assert snap[0].attrs.get("mode") in ("patched", "full", "open")
    doc = sched.tracer.export_chrome()
    parsed = json.loads(json.dumps(doc))
    assert _assert_strictly_nested(parsed) >= 10
    evnames = {e["name"] for e in parsed["traceEvents"]
               if e.get("ph") == "X"}
    assert {"cycle", "snapshot", "solve_dispatch", "device_wait",
            "host_decode", "commit"} <= evnames


def test_cycle_phase_metrics_populated():
    from kai_scheduler_tpu.framework import metrics
    cluster = _small_cluster()
    before = metrics.cycle_phase_seconds.count("device_wait")
    Scheduler().run_once(cluster)
    assert metrics.cycle_phase_seconds.count("device_wait") == before + 1
    text = metrics.registry.render()
    assert "kai_cycle_phase_seconds" in text
    # profiler counters are registered even while idle (satellite)
    assert "kai_profiler_pushed_windows_total" in text
    assert "kai_profiler_push_errors_total" in text


def test_decision_events_fit_failure_and_allocated():
    cluster = _small_cluster()
    sched = Scheduler()
    sched.run_once(cluster)
    events = sched.decisions.events()
    by_gang = {e["gang"]: e for e in events}
    assert by_gang["g"]["outcome"] == "allocated"
    assert by_gang["toobig"]["outcome"] in ("fit-failure", "quota-gate")
    assert by_gang["toobig"]["detail"]  # FIT_REASONS text, not a code
    s = sched.decisions.summary()
    assert s["outcomes"].get("allocated", 0) >= 1
    assert sum(s["outcomes"].values()) == s["events"]


def test_decision_events_preempted_for():
    cluster = _preempt_cluster()
    sched = Scheduler()
    res = sched.run_once(cluster)
    assert res.evictions  # preempt actually fired
    events = sched.decisions.events(gang="low")
    assert events and events[0]["outcome"] == "preempted-for"
    high = sched.decisions.events(gang="high")
    assert high and high[0]["outcome"] == "allocated"


def test_incremental_snapshot_span_attribution():
    """The snapshot span records the journal-delta stats (mode, dirty
    rows, leaves/bytes uploaded) once the incremental path warms up."""
    cluster = _small_cluster()
    sched = Scheduler()
    sched.run_once(cluster)
    cluster.tick()  # journaled time advance -> patchable delta
    sched.run_once(cluster)
    snap = [s for s in sched.tracer.last(1)[0].root.children
            if s.name == "snapshot"][0]
    assert snap.attrs["mode"] in ("patched", "full")
    if snap.attrs["mode"] == "patched":
        assert {"leaves_shipped", "bytes_shipped",
                "fallback_reason"} <= set(snap.attrs)
        child_names = [c.name for c in snap.children]
        assert "snapshot.patch" in child_names


# ---------------------------------------------------------------------------
# server endpoints
# ---------------------------------------------------------------------------


def _get_json(base, path):
    return json.load(urllib.request.urlopen(f"{base}{path}", timeout=10))


def test_debug_trace_and_events_endpoints():
    server = SchedulerServer(_small_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # before any cycle: valid, empty-ish documents
        doc = _get_json(base, "/debug/trace")
        assert "traceEvents" in doc
        req = urllib.request.Request(
            f"{base}/cycle/stored", data=b"{}",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=30)
        doc = _get_json(base, "/debug/trace?cycles=1")
        assert _assert_strictly_nested(doc) >= 5
        names = {e["name"] for e in doc["traceEvents"]
                 if e.get("ph") == "X"}
        assert {"cycle", "device_wait", "commit"} <= names
        ev = _get_json(base, "/debug/events?gang=toobig")
        assert ev["gang"] == "toobig"
        assert ev["events"][0]["outcome"] in ("fit-failure", "quota-gate")
        allg = _get_json(base, "/debug/events")
        assert allg["summary"]["events"] >= 2
        # /healthz folds the phase breakdown + decision summary in
        health = _get_json(base, "/healthz")
        stats = health["last_cycle"]
        assert set(stats["phase_seconds"]) == PHASES
        assert stats["decisions"]["events"] >= 2
    finally:
        server.stop()


def test_profile_cycle_reuses_tracer_phases():
    from kai_scheduler_tpu.framework.server import profile_cycle
    cluster = _small_cluster()
    sched = Scheduler()
    sched.run_once(cluster)  # compile outside the profiled cycle
    doc = profile_cycle(cluster, sched, top=5)
    assert set(doc["phases"]) == PHASES
    assert doc["total_seconds"] >= sum(doc["phases"].values()) * 0.9
    assert doc["hottest"]


def test_debug_endpoints_hammer_no_torn_documents():
    """Cycles run while /debug/trace, /debug/events and
    /debug/pprof/continuous are scraped concurrently: every response
    must be a complete, valid document (tracer rings only immutable
    completed traces; the decision log rings immutable tuples)."""
    import concurrent.futures

    sched = Scheduler(SchedulerConfig(profiler_sample_hz=50.0))
    server = SchedulerServer(_small_cluster(), sched).start()
    base = f"http://127.0.0.1:{server.port}"

    def post_cycle(_i):
        req = urllib.request.Request(
            f"{base}/cycle/stored", data=b"{}",
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60).status

    def get_trace(_i):
        doc = _get_json(base, "/debug/trace")
        _assert_strictly_nested(doc)
        return 200

    def get_events(_i):
        doc = _get_json(base, "/debug/events")
        assert {"events", "summary"} <= set(doc)
        for e in doc["events"]:
            assert {"cycle", "gang", "outcome"} <= set(e)
        return 200

    def get_prof(_i):
        return urllib.request.urlopen(
            f"{base}/debug/pprof/continuous", timeout=60).status

    try:
        post_cycle(0)  # compile before the storm
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futures = []
            for i in range(8):
                futures.append(pool.submit(post_cycle, i))
                futures.append(pool.submit(get_trace, i))
                futures.append(pool.submit(get_events, i))
                futures.append(pool.submit(get_prof, i))
            statuses = [f.result() for f in futures]
        assert all(s == 200 for s in statuses)
    finally:
        server.stop()

"""Config layering + CLI — ref ``conf_util/scheduler_conf_util.go`` merge
semantics and ``cmd/scheduler/app/options``."""
import json
import subprocess
import sys

from kai_scheduler_tpu import conf
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime import snapshot
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import make_cluster

import pytest

pytestmark = pytest.mark.core

DOC = """
actions: "allocate, reclaim"
tiers:
- plugins:
  - name: proportion
    arguments: {kValue: 0.25}
  - name: nodeplacement
    arguments: {gpu: spread, cpu: binpack}
  - name: gpuspread
  - name: resourcetype
queueDepthPerAction: {allocate: 7, reclaim: 3, preempt: 5}
schedulePeriod: 2.5
"""


def test_defaults_without_doc():
    cfg = conf.load_config(None)
    assert cfg.actions == ("allocate", "consolidation", "reclaim",
                           "preempt", "stalegangeviction")
    assert cfg.session.allocate.placement.binpack_accel


def test_document_merges_over_defaults():
    cfg = conf.load_config(DOC)
    assert cfg.actions == ("allocate", "reclaim")
    assert cfg.schedule_period_s == 2.5
    assert cfg.session.k_value == 0.25
    pl = cfg.session.allocate.placement
    assert not pl.binpack_accel and pl.binpack_cpu
    assert not pl.device_pack                 # gpuspread
    assert cfg.session.allocate.queue_depth == 7
    assert cfg.session.victims.queue_depth == 3
    assert cfg.session.victims.queue_depth_preempt == 5
    # victim placement inherits the strategy knobs
    assert not cfg.session.victims.placement.placement.binpack_accel
    # configured score-plugin order is reflected in the tiers
    assert "resourcetype" in pl.tiers


def test_unknown_action_rejected():
    try:
        conf.load_config('actions: "allocate, nosuch"')
    except ValueError as exc:
        assert "nosuch" in str(exc)
    else:
        raise AssertionError("expected ValueError")


def test_config_drives_scheduler_pipeline():
    """Changing actions via a config document — no code edits — changes
    which actions run (VERDICT r2 item 8's 'done' bar)."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=4.0, num_gangs=2, tasks_per_gang=2)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    cfg = conf.load_config('actions: "allocate"')
    res = Scheduler(cfg).run_once(cluster)
    assert set(res.action_seconds) in ({"allocate"}, {"pipeline"})
    assert len(res.bind_requests) == 4


def test_effective_config_roundtrip():
    cfg = conf.load_config(DOC)
    doc = conf.effective_config_doc(cfg)
    assert doc["actions"] == "allocate, reclaim"
    assert doc["placement"]["gpu"] == "spread"
    assert doc["queueDepthPerAction"]["reclaim"] == 3


def test_cli_print_config_and_cycle(tmp_path):
    conf_path = tmp_path / "sched.yaml"
    conf_path.write_text(DOC)
    out = subprocess.run(
        [sys.executable, "-m", "kai_scheduler_tpu", "print-config",
         "--config", str(conf_path)],
        capture_output=True, text=True, check=True)
    doc = json.loads(out.stdout)
    assert doc["actions"] == "allocate, reclaim"

    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=4, node_accel=4.0, num_gangs=2, tasks_per_gang=2)
    cluster = Cluster.from_objects(nodes, queues, groups, pods, topo)
    snap_path = tmp_path / "cluster.json.gz"
    snapshot.save(cluster, str(snap_path))
    out = subprocess.run(
        [sys.executable, "-m", "kai_scheduler_tpu", "cycle",
         "--snapshot", str(snap_path)],
        capture_output=True, text=True, check=True)
    result = json.loads(out.stdout.strip().splitlines()[-1])
    assert result["bind_requests"] == 4

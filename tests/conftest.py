"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the driver separately dry-runs the
multi-chip path via ``__graft_entry__.dryrun_multichip``).

The CI image's sitecustomize registers the TPU-tunnel PJRT plugin and
forces ``jax_platforms="axon,cpu"`` through ``jax.config.update`` — env
vars alone cannot undo that, so we update the config here (before any
backend initialisation) to pin tests to CPU.  ``XLA_FLAGS`` must be in
the environment before the CPU backend first initialises.
"""
import os

os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

# the single source of the virtual-device count (shared with
# __graft_entry__'s dryrun and the kai-comms lowering stage); importing
# the mesh module does NOT initialise a jax backend
from kai_scheduler_tpu.parallel.mesh import (  # noqa: E402
    VIRTUAL_DEVICE_COUNT, ensure_virtual_cpu_devices)

ensure_virtual_cpu_devices()

import jax  # noqa: E402
import pytest  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's cost is dominated by jit
# compiles of the solver kernels (heavy nested control flow), most of
# which recur across tests, xdist workers, and runs.  The cache is
# content-addressed, so stale entries are never wrongly reused.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

# Shape-unify the test snapshots: pad every snapshot axis to multiples
# of 32 (instead of the production default 8), so the dozens of small
# synthetic clusters across the suite collapse onto a handful of padded
# tensor shapes and REUSE each other's compiled kernels — the single
# biggest lever on cold-suite wall time (each distinct (shape, config)
# pair is a fresh XLA compile of the solver pipeline).  Semantics are
# unchanged: padding rows are invalid/masked by construction.
import functools  # noqa: E402

import kai_scheduler_tpu.framework.session as _session_mod  # noqa: E402
import kai_scheduler_tpu.state as _state_pkg  # noqa: E402
import kai_scheduler_tpu.state.cluster_state as _cs  # noqa: E402

_orig_build_snapshot = _cs.build_snapshot


@functools.wraps(_orig_build_snapshot)
def _padded_build_snapshot(*args, **kwargs):
    kwargs.setdefault("pad", 32)
    return _orig_build_snapshot(*args, **kwargs)


_cs.build_snapshot = _padded_build_snapshot
_state_pkg.build_snapshot = _padded_build_snapshot
_session_mod.build_snapshot = _padded_build_snapshot

# The suite is COMPILE-bound: the fused 5-action pipeline is a huge XLA
# program and every (shape, config) variant costs 1-6 min of CPU
# compile at full optimization, while the test shapes execute in
# milliseconds either way.  Compile at -O0 for tests.
jax.config.update("jax_disable_most_optimizations", True)


@pytest.fixture(scope="session")
def virtual_devices():
    """The VIRTUAL_DEVICE_COUNT CPU devices every multi-device test
    shares.  Skips (rather than fails) if the backend initialised
    before the XLA flag landed — a harness problem, not a product one."""
    devs = jax.devices("cpu")
    if len(devs) < VIRTUAL_DEVICE_COUNT:
        pytest.skip(f"need {VIRTUAL_DEVICE_COUNT} virtual CPU devices, "
                    f"got {len(devs)} (backend initialised too early)")
    return devs[:VIRTUAL_DEVICE_COUNT]

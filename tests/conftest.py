"""Test configuration: run everything on a virtual 8-device CPU mesh.

Multi-chip TPU hardware is not available in CI; sharding correctness is
validated on 8 virtual CPU devices (the driver separately dry-runs the
multi-chip path via ``__graft_entry__.dryrun_multichip``).

The CI image's sitecustomize registers the TPU-tunnel PJRT plugin and
forces ``jax_platforms="axon,cpu"`` through ``jax.config.update`` — env
vars alone cannot undo that, so we update the config here (before any
backend initialisation) to pin tests to CPU.  ``XLA_FLAGS`` must be in
the environment before the CPU backend first initialises.
"""
import os

os.environ.setdefault("JAX_ENABLE_X64", "0")
os.environ["JAX_PLATFORMS"] = "cpu"

from __graft_entry__ import _ensure_cpu_device_count  # noqa: E402

_ensure_cpu_device_count(8)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# Persistent XLA compilation cache: the suite's cost is dominated by jit
# compiles of the solver kernels (heavy nested control flow), most of
# which recur across tests, xdist workers, and runs.  The cache is
# content-addressed, so stale entries are never wrongly reused.
jax.config.update("jax_compilation_cache_dir",
                  os.path.join(os.path.dirname(__file__), "..", ".jax_cache"))
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.5)

"""Topology-aware allocation tests — ref
``actions/allocate/allocateTopology_test.go`` scenarios (required-level
domain confinement, preferred-level locality, binpack domain choice)."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import allocate
from kai_scheduler_tpu.state import build_snapshot

Vec = apis.ResourceVec
QR = apis.QueueResource

RACK = "topo/rack"
HOST = "kubernetes.io/hostname"
TOPOLOGY = apis.Topology(name="default", levels=[RACK, HOST])


def racked_nodes(racks=2, nodes_per_rack=2, accel=4.0):
    nodes = []
    for r in range(racks):
        for i in range(nodes_per_rack):
            name = f"node-{r}-{i}"
            nodes.append(apis.Node(
                name, Vec(accel, 64.0, 256.0),
                labels={RACK: f"rack-{r}", HOST: name}))
    return nodes


def run_allocate(nodes, groups, pods, queues=None):
    queues = queues or [apis.Queue("q0", accel=QR(quota=1000.0))]
    state, index = build_snapshot(nodes, queues, groups, pods, TOPOLOGY)
    fair_share = drf.set_fair_share(state, num_levels=1)
    res = allocate(state, fair_share, num_levels=1)
    return res, state, index


def rack_of(index, state, res, gi, ti):
    node = int(np.asarray(res.placements)[gi, ti])
    return index.node_names[node].rsplit("-", 1)[0]  # "node-<rack>"


class TestRequiredLevel:
    def test_gang_confined_to_one_rack(self):
        # 2 racks x 2 nodes x 4 accel; gang of 4 x 2-accel tasks fits only
        # if all land in one rack (8 accel per rack) -- and must.
        nodes = racked_nodes()
        group = apis.PodGroup(
            "g0", queue="q0", min_member=4,
            topology_constraint=apis.TopologyConstraint(
                required_level=RACK))
        pods = [apis.Pod(f"p{i}", "g0", resources=Vec(2.0, 1.0, 4.0))
                for i in range(4)]
        res, state, index = run_allocate(nodes, [group], pods)
        gi = index.gang_names.index("g0")
        assert bool(res.allocated[gi])
        racks = {rack_of(index, state, res, gi, t) for t in range(4)}
        assert len(racks) == 1

    def test_gang_too_big_for_any_rack_fails(self):
        # 12 accel needed; each rack has 8; cluster has 16.  Without the
        # constraint it would fit; with required rack level it must fail.
        nodes = racked_nodes()
        group = apis.PodGroup(
            "g0", queue="q0", min_member=6,
            topology_constraint=apis.TopologyConstraint(
                required_level=RACK))
        pods = [apis.Pod(f"p{i}", "g0", resources=Vec(2.0, 1.0, 4.0))
                for i in range(6)]
        res, state, index = run_allocate(nodes, [group], pods)
        gi = index.gang_names.index("g0")
        assert not bool(res.allocated[gi])
        assert int((np.asarray(res.placements)[gi] >= 0).sum()) == 0

    def test_binpacks_fuller_domain(self):
        # rack-0 partially used (less free) -- new constrained gang should
        # binpack into the fuller rack that still fits.
        nodes = racked_nodes()
        filler = apis.PodGroup("filler", queue="q0", min_member=1,
                               last_start_timestamp=0.0)
        running = [apis.Pod("f0", "filler", resources=Vec(4.0, 1.0, 4.0),
                            status=apis.PodStatus.RUNNING, node="node-0-0")]
        group = apis.PodGroup(
            "g0", queue="q0", min_member=2,
            topology_constraint=apis.TopologyConstraint(
                required_level=RACK))
        pods = running + [
            apis.Pod(f"p{i}", "g0", resources=Vec(2.0, 1.0, 4.0))
            for i in range(2)]
        res, state, index = run_allocate(nodes, [filler, group], pods)
        gi = index.gang_names.index("g0")
        assert bool(res.allocated[gi])
        racks = {rack_of(index, state, res, gi, t) for t in range(2)}
        assert racks == {"node-0"}       # fuller rack chosen

    def test_unconstrained_gang_can_span_racks(self):
        nodes = racked_nodes()
        group = apis.PodGroup("g0", queue="q0", min_member=6)
        pods = [apis.Pod(f"p{i}", "g0", resources=Vec(2.0, 1.0, 4.0))
                for i in range(6)]
        res, state, index = run_allocate(nodes, [group], pods)
        gi = index.gang_names.index("g0")
        assert bool(res.allocated[gi])
        assert int((np.asarray(res.placements)[gi] >= 0).sum()) == 6


class TestPreferredLevel:
    def test_tasks_cluster_in_one_rack_when_possible(self):
        # 2-task gang, 1 accel each; binpack alone would already cluster,
        # so spread cpu/accel via a bigger cluster and check the preferred
        # band keeps tasks together in one rack.
        nodes = racked_nodes(racks=3, nodes_per_rack=2, accel=2.0)
        group = apis.PodGroup(
            "g0", queue="q0", min_member=4,
            topology_constraint=apis.TopologyConstraint(
                preferred_level=RACK))
        pods = [apis.Pod(f"p{i}", "g0", resources=Vec(1.0, 1.0, 4.0))
                for i in range(4)]
        res, state, index = run_allocate(nodes, [group], pods)
        gi = index.gang_names.index("g0")
        assert bool(res.allocated[gi])
        racks = [rack_of(index, state, res, gi, t) for t in range(4)]
        assert len(set(racks)) == 1      # 4 x 1 accel fits one 2x2 rack

"""Incremental snapshot engine tests — journaled dirty-set refresh
(``state/incremental.py``).

The load-bearing property: a PATCHED snapshot must be element-wise
identical to a fresh full ``build_snapshot`` — every ``ClusterState``
leaf and every ``SnapshotIndex`` name map.  ``IncrementalSnapshotter``
(verify=True) asserts exactly that after every patch, so these tests
drive churn through it and then check the patch path actually engaged
(a fallback-to-full would pass verification vacuously).
"""
import dataclasses

import numpy as np
import pytest

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.binder import Binder
from kai_scheduler_tpu.framework.scheduler import Scheduler, SchedulerConfig
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import make_cluster
from kai_scheduler_tpu.state.incremental import (
    IncrementalSnapshotter,
    MutationJournal,
)

pytestmark = pytest.mark.core


def build(num_nodes=8, num_gangs=6, tasks_per_gang=2, **kw) -> Cluster:
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, num_gangs=num_gangs,
        tasks_per_gang=tasks_per_gang, **kw)
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def refresh(snap, cluster):
    return snap.refresh(cluster, now=cluster.now)


# ---------------------------------------------------------------------------
# Journal
# ---------------------------------------------------------------------------


class TestJournal:
    def test_cursor_consume_resets(self):
        j = MutationJournal()
        cur = j.register()
        j.mark_pod("a")
        j.mark_pod_added("b")
        j.mark_gang("g")
        j.mark_time()
        got = cur.consume()
        assert got.pods_dirty == {"a"}
        assert got.pods_added == ["b"]
        assert got.gangs_dirty == {"g"}
        assert got.time_dirty
        empty = cur.consume()
        assert not empty.pods_dirty and not empty.pods_added
        assert not empty.time_dirty

    def test_multiple_consumers_each_see_all_marks(self):
        j = MutationJournal()
        c1, c2 = j.register(), j.register()
        j.mark_pod("p")
        assert c1.consume().pods_dirty == {"p"}
        # c2's view is independent — not drained by c1
        assert c2.consume().pods_dirty == {"p"}

    def test_cluster_ops_are_journaled(self):
        cluster = build()
        cur = cluster.journal.register()
        pod = next(p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.PENDING)
        cluster.bind_pod(pod.name, list(cluster.nodes)[0])
        cluster.evict_pod(pod.name)
        cluster.tick()
        got = cur.consume()
        assert pod.name in got.pods_dirty
        assert pod.name in got.pods_removed  # reaped by the tick
        assert got.time_dirty

    def test_submit_appends(self):
        cluster = build()
        cur = cluster.journal.register()
        g = apis.PodGroup(name="new-gang", queue="queue-0-0",
                          min_member=1)
        cluster.submit(g, [apis.Pod(name="new-pod", group="new-gang")])
        got = cur.consume()
        assert got.gangs_added == ["new-gang"]
        assert got.pods_added == ["new-pod"]


# ---------------------------------------------------------------------------
# Patch equivalence (verify=True asserts bit-identity internally)
# ---------------------------------------------------------------------------


class TestPatchEquivalence:
    def test_bind_evict_submit_cycle_patches_identically(self):
        cluster = build(num_nodes=8, num_gangs=6, tasks_per_gang=2)
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        # bind two pods, evict one, submit a new gang, tick — patched
        pend = [p for p in cluster.pods.values()
                if p.status == apis.PodStatus.PENDING]
        cluster.bind_pod(pend[0].name, "node-0")
        cluster.bind_pod(pend[1].name, "node-1")
        refresh(snap, cluster)
        cluster.evict_pod(pend[0].name)
        cluster.tick()
        refresh(snap, cluster)
        g = apis.PodGroup(name="late", queue="queue-0-0", min_member=1)
        cluster.submit(g, [apis.Pod(
            name="late-0", group="late",
            resources=apis.ResourceVec(1, 1, 4))])
        refresh(snap, cluster)
        assert snap.stats.patched == 3
        assert snap.stats.full_builds == 1  # the cold build only

    def test_direct_status_mutation_is_swept_and_patched(self):
        """Un-journaled in-place writes (tests/controllers do this) are
        detected by the drift sweep and patched correctly."""
        cluster = build(num_gangs=4, running_fraction=0.5)
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        pod = next(p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.RUNNING)
        pod.status = apis.PodStatus.SUCCEEDED  # direct, no journal
        refresh(snap, cluster)
        assert snap.stats.patched == 1

    def test_randomized_churn_property(self):
        """Randomized bind/evict/submit/delete/tick streams over many
        cycles: every patched snapshot must equal a fresh full rebuild
        (asserted by verify=True), including forced-fallback cycles."""
        rng = np.random.default_rng(42)
        cluster = build(num_nodes=8, num_gangs=8, tasks_per_gang=2,
                        running_fraction=0.25,
                        topology_levels=(2, 2))
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        submitted = 0
        for cycle in range(12):
            for _ in range(int(rng.integers(1, 4))):
                op = rng.choice(["bind", "evict", "submit", "tick",
                                 "mutate"])
                pods = list(cluster.pods.values())
                if op == "bind":
                    pend = [p for p in pods
                            if p.status == apis.PodStatus.PENDING]
                    if pend:
                        p = pend[int(rng.integers(len(pend)))]
                        node = f"node-{rng.integers(8)}"
                        try:
                            cluster.bind_pod(p.name, node)
                        except RuntimeError:
                            pass
                elif op == "evict":
                    run = [p for p in pods if p.status in
                           (apis.PodStatus.BOUND, apis.PodStatus.RUNNING)]
                    if run:
                        cluster.evict_pod(
                            run[int(rng.integers(len(run)))].name)
                elif op == "submit":
                    submitted += 1
                    name = f"extra-{submitted}"
                    g = apis.PodGroup(name=name, queue="queue-0-0",
                                      min_member=1)
                    cluster.submit(g, [apis.Pod(
                        name=f"{name}-p{i}", group=name,
                        resources=apis.ResourceVec(1, 1, 4))
                        for i in range(int(rng.integers(1, 3)))])
                elif op == "tick":
                    cluster.tick()
                else:
                    run = [p for p in pods if p.status
                           == apis.PodStatus.RUNNING]
                    if run:
                        run[int(rng.integers(len(run)))].status = \
                            apis.PodStatus.SUCCEEDED
            refresh(snap, cluster)
        # the stream must exercise the patch path, not just fall back
        assert snap.stats.patched >= 8, snap.stats

    def test_patch_through_binder_devices(self):
        """Binder-bound pods carry concrete accel devices — the
        recorded-device occupancy path must patch identically."""
        cluster = build(num_nodes=4, num_gangs=4, tasks_per_gang=2)
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        sched = Scheduler(SchedulerConfig(incremental=False))
        binder = Binder()
        refresh(snap, cluster)
        sched.run_once(cluster)
        binder.reconcile(cluster)
        refresh(snap, cluster)
        cluster.tick()
        refresh(snap, cluster)
        assert snap.stats.patched == 2

    def test_shapes_stay_pinned_across_churn(self):
        """Capacity floors keep every compiled shape identical across
        patched cycles (shape changes would recompile the kernels)."""
        cluster = build(num_nodes=8, num_gangs=6, tasks_per_gang=2)
        snap = IncrementalSnapshotter(dirty_threshold=1.0)
        state0, _ = refresh(snap, cluster)
        shapes0 = [leaf.shape for leaf in
                   __import__("jax").tree_util.tree_leaves(state0)]
        pend = [p.name for p in cluster.pods.values()
                if p.status == apis.PodStatus.PENDING]
        for i, name in enumerate(pend[:4]):
            cluster.bind_pod(name, f"node-{i % 8}")
        cluster.tick()
        state1, _ = refresh(snap, cluster)
        shapes1 = [leaf.shape for leaf in
                   __import__("jax").tree_util.tree_leaves(state1)]
        assert shapes0 == shapes1
        assert snap.stats.patched == 1

    def test_unchanged_leaves_reuse_device_buffers(self):
        cluster = build(num_nodes=8, num_gangs=6, tasks_per_gang=2)
        snap = IncrementalSnapshotter(dirty_threshold=1.0)
        state0, _ = refresh(snap, cluster)
        pod = next(p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.PENDING)
        cluster.bind_pod(pod.name, "node-0")
        state1, _ = refresh(snap, cluster)
        # node labels/topology never changed — same device buffer
        assert state1.nodes.labels is state0.nodes.labels
        assert state1.nodes.topology is state0.nodes.topology
        assert state1.nodes.allocatable is state0.nodes.allocatable
        # the running table did change
        assert state1.running.valid is not state0.running.valid


# ---------------------------------------------------------------------------
# Fallback triggers
# ---------------------------------------------------------------------------


class TestFallbacks:
    def test_structural_node_change_falls_back(self):
        cluster = build()
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        cluster.nodes["node-extra"] = apis.Node(
            name="node-extra",
            allocatable=apis.ResourceVec(8, 64, 256))
        refresh(snap, cluster)
        assert snap.stats.patched == 0
        assert "node-membership-drift" in snap.stats.fallbacks

    def test_queue_set_change_falls_back(self):
        cluster = build()
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        cluster.queues["q-late"] = apis.Queue(name="q-late",
                                              parent="dept-0")
        refresh(snap, cluster)
        assert snap.stats.patched == 0
        assert "queue-set-changed" in snap.stats.fallbacks

    def test_feature_pod_falls_back(self):
        """Fractional-share pods ride the irregular intake paths — the
        snapshotter must fall back, not mis-patch."""
        cluster = build()
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        g = apis.PodGroup(name="frac-gang", queue="queue-0-0",
                          min_member=1)
        cluster.submit(g, [apis.Pod(
            name="frac-pod", group="frac-gang", accel_portion=0.5,
            resources=apis.ResourceVec(0, 1, 1))])
        refresh(snap, cluster)
        assert "nonplain-pods" in snap.stats.fallbacks
        # once the feature pod leaves, patching resumes
        cluster.evict_pod("frac-pod")
        cluster.tick()
        refresh(snap, cluster)  # full (ledger had the nonplain pod)
        pod = next(p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.PENDING)
        cluster.bind_pod(pod.name, "node-0")
        refresh(snap, cluster)
        assert snap.stats.patched >= 1

    def test_dirty_threshold_falls_back(self):
        cluster = build()
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=0.0)
        refresh(snap, cluster)
        pod = next(p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.PENDING)
        cluster.bind_pod(pod.name, "node-0")
        refresh(snap, cluster)
        assert snap.stats.patched == 0
        assert "dirty-threshold" in snap.stats.fallbacks

    def test_topology_swap_falls_back(self):
        cluster = build(topology_levels=(2, 2))
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        cluster.topology = dataclasses.replace(cluster.topology)
        refresh(snap, cluster)
        assert snap.stats.patched == 0
        assert "topology-changed" in snap.stats.fallbacks


# ---------------------------------------------------------------------------
# Scheduler integration (the verify_incremental flag end-to-end)
# ---------------------------------------------------------------------------


class TestSchedulerIntegration:
    def test_multi_cycle_e2e_with_verify_incremental(self):
        """Scheduler + binder over several cycles with
        ``verify_incremental`` on: every patched cycle is asserted
        identical to a fresh rebuild, and scheduling results flow."""
        cluster = build(num_nodes=4, node_accel=8.0, num_gangs=4,
                        tasks_per_gang=2)
        cfg = SchedulerConfig(verify_incremental=True,
                              incremental_dirty_threshold=1.0)
        sched, binder = Scheduler(cfg), Binder()
        r1 = sched.run_once(cluster)
        assert len(r1.bind_requests) == 8
        assert len(binder.reconcile(cluster).bound) == 8
        cluster.tick()
        r2 = sched.run_once(cluster)
        assert r2.bind_requests == []
        # drain one gang and let the next cycle re-place capacity
        for p in list(cluster.pods.values())[:2]:
            p.status = apis.PodStatus.SUCCEEDED
        cluster.tick()
        g = apis.PodGroup(name="late", queue="queue-0-0", min_member=2)
        cluster.submit(g, [apis.Pod(
            name=f"late-{i}", group="late",
            resources=apis.ResourceVec(1, 1, 4)) for i in range(2)])
        r3 = sched.run_once(cluster)
        assert len(r3.bind_requests) == 2
        snap = sched._snapshotter
        assert snap is not None and snap.verify
        assert snap.stats.patched >= 1, snap.stats

    def test_incremental_off_uses_plain_session_open(self):
        cluster = build(num_nodes=4, num_gangs=2)
        sched = Scheduler(SchedulerConfig(incremental=False))
        r = sched.run_once(cluster)
        assert sched._snapshotter is None
        assert len(r.bind_requests) == 4

    def test_sharded_scheduler_bypasses_incremental(self):
        shard = apis.SchedulingShard(name="s0",
                                     partition_label_value=None)
        cluster = build(num_nodes=4, num_gangs=2)
        sched = Scheduler(SchedulerConfig(shard=shard))
        sched.run_once(cluster)
        assert sched._snapshotter is None


class TestBindRequestPresentation:
    def test_direct_bind_request_clear_is_swept(self):
        """A Pending BindRequest presents its pod as bound; clearing the
        store directly (no journal) must still flip the presentation
        back — the sweep covers the BR table too."""
        cluster = build(num_nodes=4, num_gangs=4, tasks_per_gang=2)
        snap = IncrementalSnapshotter(verify=True, dirty_threshold=1.0)
        refresh(snap, cluster)
        pod = next(p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.PENDING)
        cluster.create_bind_request(apis.BindRequest(
            pod_name=pod.name, selected_node="node-0"))
        state, _ = refresh(snap, cluster)
        assert int(np.asarray(state.running.valid).sum()) == 1
        cluster.bind_requests.clear()  # direct, unjournaled
        state, _ = refresh(snap, cluster)
        assert int(np.asarray(state.running.valid).sum()) == 0
        assert snap.stats.patched == 2


# ---------------------------------------------------------------------------
# Concurrency: journal marks racing the snapshotter's consume (PR 4)
# ---------------------------------------------------------------------------


class TestJournalConcurrency:
    """The journal is marked from binder / status-updater / HTTP
    handler threads while the scheduler thread drains cursors.  Before
    the journal lock, ``consume()``'s field swap could drop a mark that
    raced it — and a dropped mark for an in-place field mutation the
    drift sweep does not compare (e.g. pod priority) silently serves a
    stale snapshot."""

    def test_marks_hammered_from_thread_patched_equals_fresh(self):
        import threading

        from kai_scheduler_tpu.state import cluster_state as cs

        cluster = build(num_nodes=6, num_gangs=4, tasks_per_gang=2)
        snap = IncrementalSnapshotter()
        refresh(snap, cluster)  # warm (full build + ledgers)

        pending = [p for p in cluster.pods.values()
                   if p.status == apis.PodStatus.PENDING]
        assert pending
        stop = threading.Event()
        rounds = {"n": 0}

        def hammer():
            # in-place priority bumps + marks: the exact write the
            # sweep cannot attribute without the journal entry
            i = 0
            while not stop.is_set():
                pod = pending[i % len(pending)]
                pod.priority += 1
                cluster.journal.mark_pod(pod.name)
                cluster.journal.mark_time()
                rounds["n"] += 1
                i += 1

        t = threading.Thread(target=hammer, daemon=True)
        t.start()
        # drain the journal under full contention: every consume races
        # in-flight marks
        for _ in range(15):
            refresh(snap, cluster)
        stop.set()
        t.join(timeout=10)
        assert not t.is_alive()
        assert rounds["n"] > 0  # the hammer actually contended

        # with every mark retained, one quiet refresh must converge to
        # a state element-wise identical to a fresh full rebuild
        state, index = refresh(snap, cluster)
        _fresh_state, fresh_index, fresh_host = cs.build_snapshot(
            *cluster.snapshot_lists(), now=cluster.now,
            capacity=snap._capacity, _return_host=True)
        import jax
        for (path, mine), (_, ref) in zip(
                jax.tree_util.tree_flatten_with_path(snap._host)[0],
                jax.tree_util.tree_flatten_with_path(fresh_host)[0]):
            assert np.array_equal(np.asarray(mine), np.asarray(ref)), (
                f"leaf {jax.tree_util.keystr(path)} diverged after "
                f"concurrent journal marks")
        assert index.gang_names == fresh_index.gang_names
        assert index.task_names == fresh_index.task_names

    def test_consume_is_atomic_under_concurrent_marks(self):
        """No mark may vanish: every mark made before a consume returns
        is either in that batch or in a later one."""
        import threading

        j = MutationJournal()
        cur = j.register()
        total = 2000
        seen: set[str] = set()
        done = threading.Event()

        def writer():
            for i in range(total):
                j.mark_pod(f"p{i}")
            done.set()

        t = threading.Thread(target=writer, daemon=True)
        t.start()
        while not done.is_set():
            seen |= cur.consume().pods_dirty
        t.join(timeout=10)
        seen |= cur.consume().pods_dirty
        assert len(seen) == total  # zero lost marks

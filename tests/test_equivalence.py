"""Equivalence properties for the wavefront fast paths.

Round-2 review flagged that the batched kernels' equivalence to the
reference's one-at-a-time semantics was asserted, not tested.  These
properties compare each fast path against its sequential/general
counterpart on randomized clusters:

- chunked victim wavefront (B>1) vs the sequential scan (B=1),
- the whole-gang uniform kernel vs the per-task kernel under binpack.
"""
import dataclasses
import functools

import jax
import numpy as np
import pytest

from kai_scheduler_tpu.framework.session import Session
from kai_scheduler_tpu.ops.allocate import allocate, init_result
from kai_scheduler_tpu.ops.victims import run_victim_action
from kai_scheduler_tpu.state import make_cluster


def _reclaim_setup(seed):
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=24, node_accel=4.0, num_gangs=12, tasks_per_gang=4,
        running_fraction=0.5, queue_accel_quota=8.0,
        partition_queues_by_running=True, seed=seed)
    return Session.open(nodes, queues, groups, pods, topo)


@pytest.mark.parametrize("seed", [0, 1, 2])
def test_chunked_reclaim_matches_sequential(seed):
    """The wavefront must reproduce the sequential scan's FAIRNESS
    outcome: the same number of reclaimers admitted per queue (within a
    chunk the job order is frozen, so WHICH of two equal-fairness gangs
    from one queue lands first may differ — the documented drift), and
    it may free fewer victims (shared minimal prefixes) but never
    more."""
    ses = _reclaim_setup(seed)
    outs = {}
    for b in (1, 16):
        cfg = dataclasses.replace(ses.config.victims, batch_size=b)
        res = jax.block_until_ready(jax.jit(functools.partial(
            run_victim_action, num_levels=2, mode="reclaim", config=cfg))(
                ses.state, ses.state.queues.fair_share,
                init_result(ses.state)))
        outs[b] = res
    queues = np.asarray(ses.state.gangs.queue)
    for b in (1, 16):
        outs[b] = {
            "per_queue": np.bincount(
                queues[np.asarray(outs[b].allocated)],
                minlength=ses.state.queues.q),
            "victims": int(np.asarray(outs[b].victim).sum()),
        }
    assert (outs[1]["per_queue"] == outs[16]["per_queue"]).all(), outs
    assert outs[16]["victims"] <= outs[1]["victims"], outs


@pytest.mark.parametrize("seed,departments,leaves", [
    (0, 1, 1), (3, 1, 1),
    # multi-queue: preempt chunks must stay own-queue-local (a lane's
    # budget prices against its own queue's victims only)
    (0, 2, 2), (1, 2, 2),
])
def test_chunked_preempt_matches_sequential(seed, departments, leaves):
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=16, node_accel=2.0, num_gangs=10, tasks_per_gang=2,
        running_fraction=0.6, num_departments=departments,
        queues_per_department=leaves, priority_spread=3, seed=seed)
    ses = Session.open(nodes, queues, groups, pods, topo)
    outs = {}
    for b in (1, 8):
        cfg = dataclasses.replace(ses.config.victims, batch_size=b)
        res = jax.block_until_ready(jax.jit(functools.partial(
            run_victim_action, num_levels=2, mode="preempt", config=cfg))(
                ses.state, ses.state.queues.fair_share,
                init_result(ses.state)))
        outs[b] = res
    assert (np.asarray(outs[1].allocated)
            == np.asarray(outs[8].allocated)).all()
    assert (int(np.asarray(outs[8].victim).sum())
            <= int(np.asarray(outs[1].victim).sum()))


@pytest.mark.parametrize("strategy", ["binpack", "spread"])
@pytest.mark.parametrize("seed", [0, 1, 4])
def test_uniform_kernel_matches_per_task(seed, strategy):
    """Uniform whole-gang placement ≡ the per-task loop under binpack:
    same gangs allocated, same per-gang placement counts (node choice
    may differ only among equal-scoring nodes).  Under SPREAD the
    whole-gang fill drifts from the per-task re-ranking by design, so
    the Session auto-tune keeps the per-task kernel there — this test
    pins both facts: the auto-tune gate, and that even a FORCED uniform
    kernel under spread still admits the same gang set (only node
    choices drift)."""
    from kai_scheduler_tpu.ops.scoring import PlacementConfig
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=20, node_accel=4.0, num_gangs=14, tasks_per_gang=3,
        seed=seed)
    spread = strategy == "spread"
    base_cfg = None
    if spread:
        from kai_scheduler_tpu.framework.session import SessionConfig
        from kai_scheduler_tpu.ops.allocate import AllocateConfig
        base_cfg = SessionConfig(allocate=AllocateConfig(
            placement=PlacementConfig(binpack_accel=False,
                                      binpack_cpu=False)))
    ses = Session.open(nodes, queues, groups, pods, topo,
                       config=base_cfg)
    if spread:
        # the auto-tune gate: spread shards never get the uniform kernel
        assert not ses.config.allocate.uniform_tasks
    else:
        assert ses.config.allocate.uniform_tasks  # shape qualifies
    outs = {}
    for uniform in (True, False):
        cfg = dataclasses.replace(ses.config.allocate,
                                  uniform_tasks=uniform)
        res = jax.block_until_ready(jax.jit(functools.partial(
            allocate, num_levels=2, config=cfg))(
                ses.state, ses.state.queues.fair_share))
        outs[uniform] = res
    a_u = np.asarray(outs[True].allocated)
    a_t = np.asarray(outs[False].allocated)
    assert (a_u == a_t).all(), (np.nonzero(a_u)[0], np.nonzero(a_t)[0])
    placed_u = (np.asarray(outs[True].placements) >= 0).sum(-1)
    placed_t = (np.asarray(outs[False].placements) >= 0).sum(-1)
    assert (placed_u == placed_t).all()


@pytest.mark.parametrize("seed", [0, 2])
def test_many_queue_preempt_chunk_matches_sequential(seed):
    """One boosted preemptor in EACH of 16 queues (the many-queue
    shape): the one-lane-per-queue chunk must admit exactly the
    sequential scan's preemptors per queue without over-evicting."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=32, node_accel=2.0, num_gangs=48, tasks_per_gang=1,
        running_fraction=32 / 48, num_departments=2,
        queues_per_department=8, pending_priority_boost=100, seed=seed)
    ses = Session.open(nodes, queues, groups, pods, topo)
    outs = {}
    for b in (1, 32):
        cfg = dataclasses.replace(ses.config.victims, batch_size=b,
                                  batch_size_preempt=b)
        res = jax.block_until_ready(jax.jit(functools.partial(
            run_victim_action, num_levels=2, mode="preempt", config=cfg))(
                ses.state, ses.state.queues.fair_share,
                init_result(ses.state)))
        outs[b] = res
    assert (np.asarray(outs[1].allocated)
            == np.asarray(outs[32].allocated)).all()
    assert (int(np.asarray(outs[32].victim).sum())
            <= int(np.asarray(outs[1].victim).sum()))

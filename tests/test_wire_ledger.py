"""kai-wire tests — transfer ledger, compile watcher, and the
``/debug/wire`` surface (ISSUE 7 tentpole).

The acceptance properties directly:

* every ``jax.device_put`` in the package flows through the
  TransferLedger (the KAI071 cleanliness half lives in
  ``tests/test_analysis.py``, which lints the package with the rest of
  the rules — here we pin the runtime side: cycles report their wire
  summary and the full build lands on the ledger);
* the redundancy invariant: a ≥20-cycle soak at 1% journaled churn
  reports re-uploaded-identical bytes == 0 on the patch path, with the
  patched leaves shipped in ONE batched dispatch;
* CompileWatcher attributes an induced shape-churn recompile to the
  right (entry, signature) pair, and a storm of misses raises the
  alarm;
* ``GET /debug/wire`` returns a valid document under a concurrent
  cycles-vs-scrapes hammer (ring entries are immutable once rolled).
"""
import json
import urllib.request

import numpy as np
import pytest

from bench import _churn_cluster
from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.framework.server import SchedulerServer
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.compile_watch import WATCHER, CompileWatcher
from kai_scheduler_tpu.runtime.wire_ledger import (
    LEDGER, REASON_FULL_BUILD, REASON_JOURNAL_PATCH, TransferLedger)
from kai_scheduler_tpu.state import make_cluster

WIRE_SUMMARY_KEYS = {"cycle", "by_reason", "bytes", "leaves",
                     "dispatches", "redundant_bytes", "redundant_leaves",
                     "resident_bytes", "resident_buffers",
                     "peak_resident_bytes", "dropped",
                     "unfingerprinted_bytes"}


# ---------------------------------------------------------------------------
# ledger unit behaviour (private instances — the global LEDGER carries
# whatever other tests shipped)
# ---------------------------------------------------------------------------


def _tree(seed: int = 0):
    rng = np.random.default_rng(seed)
    return {"x": rng.standard_normal(16).astype(np.float32),
            "y": np.arange(12, dtype=np.int32).reshape(3, 4)}


def test_ledger_records_batched_dispatch_and_leaf_events():
    led = TransferLedger(retain_cycles=4)
    tree = _tree()
    out = led.device_put(tree, reason=REASON_FULL_BUILD, site="t",
                         replace_site=True, leaf_names=["x", "y"])
    assert set(out) == {"x", "y"}  # same pytree back, on device
    s = led.roll_cycle(0)
    assert WIRE_SUMMARY_KEYS <= set(s)
    assert s["leaves"] == 2 and s["dispatches"] == 1
    assert s["bytes"] == 16 * 4 + 12 * 4
    assert s["redundant_bytes"] == 0 and s["unfingerprinted_bytes"] == 0
    assert s["resident_buffers"] == 2 and s["resident_bytes"] == s["bytes"]
    [doc] = led.last(1)
    assert [e["leaf"] for e in doc["events"]] == ["x", "y"]
    ev = doc["events"][0]
    assert (ev["nbytes"], ev["dtype"], ev["shape"],
            ev["reason"], ev["redundant"]) == (
        64, "float32", [16], REASON_FULL_BUILD, False)


def test_ledger_redundancy_detector_counts_identical_reuploads():
    led = TransferLedger()
    tree = _tree()
    led.device_put(tree, reason=REASON_FULL_BUILD, site="t",
                   replace_site=True, leaf_names=["x", "y"])
    led.roll_cycle(0)
    # identical re-upload: every byte is redundant
    led.device_put(_tree(), reason=REASON_JOURNAL_PATCH, site="t",
                   leaf_names=["x", "y"])
    s = led.roll_cycle(1)
    assert s["redundant_leaves"] == 2
    assert s["redundant_bytes"] == s["bytes"]
    assert s["by_reason"][REASON_JOURNAL_PATCH]["redundant_bytes"] \
        == s["bytes"]
    # changed content is NOT redundant; unchanged sibling still is
    changed = _tree()
    changed["x"] = changed["x"] + 1.0
    led.device_put(changed, reason=REASON_JOURNAL_PATCH, site="t",
                   leaf_names=["x", "y"])
    s = led.roll_cycle(2)
    assert s["redundant_leaves"] == 1  # only y
    assert s["redundant_bytes"] == 48
    # a full rebuild that re-ships identical bytes is caught even with
    # replace_site=True (the compare happens before supersession)
    led.device_put(changed, reason=REASON_FULL_BUILD, site="t",
                   replace_site=True, leaf_names=["x", "y"])
    s = led.roll_cycle(3)
    assert s["redundant_leaves"] == 2


def test_ledger_residency_replace_site_and_shape_change():
    led = TransferLedger()
    led.device_put({"a": np.zeros(8, np.float32),
                    "b": np.zeros(4, np.float32)},
                   reason=REASON_FULL_BUILD, site="t", replace_site=True,
                   leaf_names=["a", "b"])
    assert led.residency() == {"buffers": 2, "bytes": 48,
                               "peak_bytes": 48}
    # a patch replaces one leaf with a BIGGER buffer: bytes track the
    # latest upload per key
    led.device_put({"a": np.zeros(16, np.float32)},
                   reason=REASON_JOURNAL_PATCH, site="t",
                   leaf_names=["a"])
    assert led.residency()["bytes"] == 64 + 16
    # a full rebuild with a different leaf set supersedes the site:
    # "b" leaves the resident set
    led.device_put({"a": np.zeros(16, np.float32)},
                   reason=REASON_FULL_BUILD, site="t", replace_site=True,
                   leaf_names=["a"])
    r = led.residency()
    assert r["buffers"] == 1 and r["bytes"] == 64
    assert r["peak_bytes"] >= 80  # the pre-supersession watermark held
    led.roll_cycle(0)
    # same content bytes, different shape geometry is NOT redundant
    # (the fingerprint qualifies the crc with nbytes/dtype/shape)
    led.device_put({"a": np.zeros((4, 4), np.float32)},
                   reason=REASON_JOURNAL_PATCH, site="t",
                   leaf_names=["a"])
    assert led.roll_cycle(1)["redundant_leaves"] == 0


def test_ledger_ring_and_event_bounds():
    led = TransferLedger(retain_cycles=2, max_events_per_cycle=3)
    for cid in range(4):
        led.device_put({f"l{i}": np.full(2, cid, np.float32)
                        for i in range(5)},
                       reason=REASON_FULL_BUILD, site="t",
                       leaf_names=[f"l{i}" for i in range(5)])
        s = led.roll_cycle(cid)
        # aggregates count ALL leaves even though the event list is
        # bounded — dropped bytes never vanish from the totals
        assert s["leaves"] == 5 and s["dropped"] == 2
    doc = led.wire_doc()
    assert [c["cycle"] for c in doc["cycles"]] == [2, 3]  # bounded ring
    assert all(len(c["events"]) == 3 for c in doc["cycles"])
    json.dumps(doc)  # fully serializable
    one = led.wire_doc(cycles=1)
    assert [c["cycle"] for c in one["cycles"]] == [3]


def test_ledger_leaf_names_pair_with_flatten_order():
    """jax flattens dict keys SORTED, not in insertion order — leaf
    names must pair with the flattened leaves, or every multi-leaf
    batch records bytes/fingerprints under the wrong keys (regression:
    the patch path passed insertion-ordered names)."""
    led = TransferLedger()
    tree = {}
    tree["z_small"] = np.zeros(2, np.float32)   # insertion order...
    tree["a_big"] = np.zeros(100, np.float32)   # ...inverts sort order
    led.device_put(tree, reason=REASON_JOURNAL_PATCH, site="t",
                   leaf_names=sorted(tree))
    s = led.roll_cycle(0)
    assert s["leaves"] == 2
    [doc] = led.last(1)
    by = {e["leaf"]: e["nbytes"] for e in doc["events"]}
    assert by == {"a_big": 400, "z_small": 8}
    with pytest.raises(ValueError):
        led.device_put(tree, reason=REASON_JOURNAL_PATCH, site="t",
                       leaf_names=["only-one"])


def test_patch_events_name_real_leaves_across_sections():
    """End-to-end ordering regression: a churned cycle patches leaves
    in several ClusterState sections (nodes occupancy + gang state +
    running table); every journal-patch event's (name -> dtype/shape/
    nbytes) must match the snapshotter's actual host leaf of that
    name."""
    import jax

    cluster = _steady_cluster(num_nodes=16, num_gangs=16)
    sched = Scheduler()
    sched.run_once(cluster)
    rng = np.random.default_rng(1)
    checked_sections = set()
    for _ in range(6):
        _churn_cluster(cluster, rng, 0.05, num_nodes=16)
        res = sched.run_once(cluster)
        if sched._snapshotter.stats.last["mode"] != "patched":
            continue
        host = {jax.tree_util.keystr(p): leaf for p, leaf in
                jax.tree_util.tree_flatten_with_path(
                    sched._snapshotter._host)[0]}
        [doc] = LEDGER.last(1)
        assert doc["cycle"] == res.wire["cycle"]
        for ev in doc["events"]:
            if ev["reason"] != REASON_JOURNAL_PATCH:
                continue
            leaf = host[ev["leaf"]]
            assert ev["nbytes"] == int(leaf.nbytes), ev
            assert ev["dtype"] == str(leaf.dtype), ev
            assert ev["shape"] == list(leaf.shape), ev
            checked_sections.add(ev["leaf"].split(".")[1])
    # the churn must actually have exercised a multi-section patch,
    # else the ordering property was never at stake
    assert len(checked_sections) >= 2, checked_sections


def test_ledger_reason_override_and_non_numpy_leaves():
    import jax.numpy as jnp
    led = TransferLedger()
    with led.override_reason("fallback"):
        led.device_put({"x": np.zeros(4, np.float32)},
                       reason=REASON_FULL_BUILD, site="t",
                       leaf_names=["x"])
    # a device-resident leaf is size-counted but not fingerprinted —
    # hashing it would itself force a transfer
    led.device_put({"d": jnp.zeros(4, jnp.float32)}, reason="mesh-shard",
                   site="t", leaf_names=["d"])
    s = led.roll_cycle(0)
    assert set(s["by_reason"]) == {"fallback", "mesh-shard"}
    assert s["by_reason"]["mesh-shard"]["unfingerprinted_bytes"] == 16


# ---------------------------------------------------------------------------
# the instrumented cycle + the redundancy soak
# ---------------------------------------------------------------------------


def _steady_cluster(num_nodes=48, num_gangs=48):
    """Post-binder steady state at a small shape (mirrors bench_churn:
    running pods carry concrete devices so churned rebinds patch)."""
    nodes, queues, groups, pods, topo = make_cluster(
        num_nodes=num_nodes, node_accel=8.0, num_gangs=num_gangs,
        tasks_per_gang=2, running_fraction=0.5)
    cursor: dict = {}
    for p in pods:
        if p.status == apis.PodStatus.RUNNING:
            c = cursor.get(p.node, 0)
            p.accel_devices = [c]
            cursor[p.node] = c + 1
    return Cluster.from_objects(nodes, queues, groups, pods, topo)


def test_cycle_result_carries_wire_summary():
    cluster = _steady_cluster(num_nodes=8, num_gangs=8)
    sched = Scheduler()
    res = sched.run_once(cluster)
    assert WIRE_SUMMARY_KEYS <= set(res.wire)
    # the cold cycle's snapshot build landed on the ledger as the
    # incremental engine's full rebuild
    assert res.wire["by_reason"]["fallback"]["bytes"] > 0
    assert res.wire["by_reason"]["fallback"]["dispatches"] == 1
    assert res.wire["resident_bytes"] > 0
    # the wire counters ride the cycle trace as Chrome "C" lanes
    doc = sched.tracer.export_chrome(cycles=1)
    counters = [e for e in doc["traceEvents"] if e.get("ph") == "C"]
    assert {e["name"] for e in counters} == {"wire bytes/cycle",
                                             "device resident bytes"}
    up = [e for e in counters if e["name"] == "wire bytes/cycle"]
    assert up[0]["args"]["uploaded"] == res.wire["bytes"]
    json.dumps(doc)


def test_soak_patch_path_never_reuploads_identical_bytes():
    """THE redundancy invariant (ROADMAP-1 acceptance substrate): ≥20
    cycles at 1% journaled churn — every patched cycle ships changed
    bytes only (redundant-identical == 0) in ONE batched dispatch."""
    cluster = _steady_cluster()
    sched = Scheduler()
    sched.run_once(cluster)  # cold full build
    rng = np.random.default_rng(0)
    patched = 0
    for _ in range(22):
        _churn_cluster(cluster, rng, 0.01, num_nodes=48)
        res = sched.run_once(cluster)
        last = sched._snapshotter.stats.last
        if last["mode"] != "patched":
            continue
        patched += 1
        pr = res.wire["by_reason"].get(REASON_JOURNAL_PATCH)
        assert pr is not None and pr["bytes"] > 0, res.wire
        # the invariant: zero re-uploaded-identical bytes on the patch
        # path — _ship compares against the cached host leaves, the
        # ledger's content fingerprints independently agree
        assert pr["redundant_bytes"] == 0, res.wire
        # satellite: all patched leaves ride ONE batched device_put
        assert pr["dispatches"] == 1, res.wire
        assert last["ship_dispatches"] == 1
        assert pr["leaves"] == last["leaves_shipped"]
        assert pr["bytes"] == last["bytes_shipped"]
    # the soak is only meaningful if the patch path actually ran
    assert patched >= 15, sched._snapshotter.stats.fallbacks


# ---------------------------------------------------------------------------
# compile watcher
# ---------------------------------------------------------------------------


def test_compile_watcher_attributes_shape_churn_to_entry():
    """Deliberate shape churn: the same entry called at two padded
    shapes records two distinct (entry, signature) misses; a repeat
    call at a seen shape records none."""
    import jax.numpy as jnp

    from kai_scheduler_tpu.framework.session import _set_fair_share_jit

    def snap(n_queues):
        nodes, queues, groups, pods, topo = make_cluster(
            num_nodes=4, node_accel=8.0, num_gangs=4, tasks_per_gang=1,
            num_departments=1, queues_per_department=n_queues)
        from kai_scheduler_tpu.state.cluster_state import build_snapshot
        state, _ = build_snapshot(nodes, queues, groups, pods, topo,
                                  now=1.0)
        return state

    # num_levels=5 is unique to this test, so the signatures are fresh
    # no matter what the rest of the suite compiled before us
    st_small, st_big = snap(2), snap(40)  # queue axis pads 32 vs 64
    before = WATCHER.report()["entries"]["set_fair_share"]
    sigs_before = {e["signature"] for e in WATCHER.events()}
    _set_fair_share_jit(st_small, num_levels=5,
                        k_value=jnp.float32(0.0))
    _set_fair_share_jit(st_big, num_levels=5, k_value=jnp.float32(0.0))
    _set_fair_share_jit(st_small, num_levels=5,
                        k_value=jnp.float32(0.0))  # seen: no new miss
    after = WATCHER.report()["entries"]["set_fair_share"]
    assert after["misses"] - before["misses"] == 2
    assert after["calls"] - before["calls"] == 3
    assert after["seconds"] > before["seconds"]
    new = [e for e in WATCHER.events()
           if e["entry"] == "set_fair_share"
           and e["signature"] not in sigs_before]
    assert len(new) == 2
    # the two induced misses carry DISTINCT abstract signatures
    assert len({e["signature"] for e in new}) == 2


def test_compile_watcher_storm_alarm_and_cache_probe_forwarding():
    import jax

    w = CompileWatcher(storm_threshold=2, storm_window_s=3600.0)
    base = jax.jit(lambda x: x + 1)
    f = w.wrap("toy", base)
    # the jit cache probe and raw function survive the wrapper (the
    # trace probe's compile-once assertion depends on both)
    assert hasattr(f, "_cache_size")
    assert f.__wrapped__ is getattr(base, "__wrapped__", base)
    f(np.zeros(1, np.float32))   # miss 1
    rep = w.report()
    assert rep["alarms"] == 0
    f(np.zeros(2, np.float32))   # miss 2 -> storm threshold reached
    f(np.zeros(1, np.float32))   # seen signature: no new miss
    rep = w.report()
    assert rep["entries"]["toy"] == {
        "signatures": 2, "misses": 2, "calls": 3,
        "seconds": rep["entries"]["toy"]["seconds"]}
    assert rep["alarms"] == 1
    assert [e["storm"] for e in rep["events"]] == [False, True]


def test_compile_watcher_covers_callgraph_jit_entries():
    """Every jit entry the analysis call graph discovers is hooked into
    the watcher — add a new jitted kernel and this fails until it is
    wrapped (mirrors the probe-coverage meta-test)."""
    import os

    from kai_scheduler_tpu.analysis.callgraph import PackageGraph
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    entry_to_watch = {
        "_fused_pipeline": "fused_pipeline",
        "_pack_commit": "pack_commit",
        "allocate_jit": "allocate",
        "set_fair_share": "set_fair_share",
        "stale_gang_eviction": "stale_gang_eviction",
        "run_victim_action_jit": "run_victim_action",
        # kai-pulse cluster-health kernel (ops/analytics.py)
        "cluster_analytics": "analytics",
        # kai-repack defragmentation solver (ops/repack.py)
        "plan_repack": "repack",
        # kai-resident fused cycle entry (framework/scheduler.py)
        "resident_cycle": "resident_cycle",
        # analysis-only probe helper, never on the production cycle
        "cumsum_ds": None,
    }
    graph = PackageGraph(root)
    entries = {q for _m, q in graph._entries()}
    assert entries == set(entry_to_watch), (
        f"jit entry set changed: {sorted(entries)} — hook new entries "
        f"into runtime/compile_watch (and this map)")
    watched = set(WATCHER.entries())
    expected = {w for w in entry_to_watch.values() if w is not None}
    assert expected <= watched, expected - watched


# ---------------------------------------------------------------------------
# server endpoints
# ---------------------------------------------------------------------------


def _get_json(base, path):
    return json.load(urllib.request.urlopen(f"{base}{path}", timeout=10))


def _small_cluster():
    nodes = [apis.Node("n0", apis.ResourceVec(8, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=8))]
    groups = [apis.PodGroup("g", queue="q", min_member=1)]
    pods = [apis.Pod("p", "g", apis.ResourceVec(1, 1, 1))]
    return Cluster.from_objects(nodes, queues, groups, pods)


def test_debug_wire_endpoint_and_healthz_wire_summary():
    server = SchedulerServer(_small_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"
    try:
        # before any cycle: a valid document (possibly with cycles from
        # earlier tests — the ledger is process-global, like /metrics)
        doc = _get_json(base, "/debug/wire")
        assert {"cycles", "window", "residency", "totals",
                "compile"} <= set(doc)
        req = urllib.request.Request(
            f"{base}/cycle/stored", data=b"{}",
            headers={"Content-Type": "application/json"})
        urllib.request.urlopen(req, timeout=60)
        doc = _get_json(base, "/debug/wire?cycles=1")
        assert len(doc["cycles"]) == 1
        cyc = doc["cycles"][0]
        assert cyc["bytes"] > 0 and cyc["events"]
        assert all({"leaf", "nbytes", "dtype", "shape", "reason",
                    "redundant"} <= set(e) for e in cyc["events"])
        assert doc["residency"]["bytes"] > 0
        assert doc["compile"]["entries"]  # per-entry miss attribution
        bad = urllib.request.Request(f"{base}/debug/wire?cycles=zap")
        with pytest.raises(urllib.error.HTTPError):
            urllib.request.urlopen(bad, timeout=10)
        health = _get_json(base, "/healthz")
        wire = health["last_cycle"]["wire"]
        assert WIRE_SUMMARY_KEYS <= set(wire)
    finally:
        server.stop()


def test_debug_wire_hammer_no_torn_documents():
    """Cycles run while /debug/wire and /healthz are scraped
    concurrently: every response is a complete, valid document (ring
    entries are immutable once rolled; the summary doc is swapped)."""
    import concurrent.futures

    server = SchedulerServer(_small_cluster()).start()
    base = f"http://127.0.0.1:{server.port}"

    def post_cycle(_i):
        req = urllib.request.Request(
            f"{base}/cycle/stored", data=b"{}",
            headers={"Content-Type": "application/json"})
        return urllib.request.urlopen(req, timeout=60).status

    def get_wire(_i):
        doc = _get_json(base, "/debug/wire")
        assert {"cycles", "window", "residency", "compile"} <= set(doc)
        for cyc in doc["cycles"]:
            assert WIRE_SUMMARY_KEYS <= set(cyc)
            # a rolled cycle's bounded event list is consistent with
            # its aggregates: retained events + dropped == leaves
            assert len(cyc["events"]) + cyc["dropped"] == cyc["leaves"]
        return 200

    def get_health(_i):
        _get_json(base, "/healthz")
        return 200

    try:
        post_cycle(0)  # compile before the storm
        with concurrent.futures.ThreadPoolExecutor(8) as pool:
            futures = []
            for i in range(8):
                futures.append(pool.submit(post_cycle, i))
                futures.append(pool.submit(get_wire, i))
                futures.append(pool.submit(get_health, i))
            statuses = [f.result() for f in futures]
        assert all(s == 200 for s in statuses)
    finally:
        server.stop()


def test_wire_and_compile_metrics_registered_and_populated():
    from kai_scheduler_tpu.framework import metrics
    Scheduler().run_once(_small_cluster())
    text = metrics.registry.render()
    for name in ("kai_wire_uploaded_bytes_total",
                 "kai_wire_uploaded_leaves_total",
                 "kai_wire_dispatches_total",
                 "kai_wire_redundant_bytes_total",
                 "kai_wire_resident_bytes",
                 "kai_wire_resident_buffers",
                 "kai_wire_cycle_uploaded_bytes",
                 "kai_compile_cache_misses_total",
                 "kai_compile_seconds_total",
                 "kai_compile_storm_alarms_total"):
        assert name in text, name
    assert metrics.wire_uploaded_bytes.value("fallback") > 0
    assert metrics.wire_resident_bytes.value() > 0
    assert metrics.compile_cache_misses.value("fused_pipeline") >= 1

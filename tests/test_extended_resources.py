"""Extended scalar resources (MIG profiles) + DRA device counts — ref
``api/resource_info/gpu_resource_requirment.go`` draGpuCounts /
migResources and ``plugins/dynamicresources``."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import build_snapshot

MIG = "nvidia.com/mig-1g.5gb"


def run_allocate(state, **cfg):
    fs = drf.set_fair_share(state, num_levels=1)
    state = state.replace(queues=state.queues.replace(fair_share=fs))
    return allocate(state, fs, num_levels=1,
                    config=AllocateConfig(extended=True, **cfg))


def _queue():
    return [apis.Queue("q", accel=apis.QueueResource(quota=100))]


def test_mig_profile_capacity_enforced():
    """Node exposes 4 MIG slices; three 2-slice gangs -> only two fit,
    and a node without the profile is never chosen."""
    nodes = [apis.Node("mig", apis.ResourceVec(0, 64, 256),
                       extended={MIG: 4.0}),
             apis.Node("plain", apis.ResourceVec(0, 64, 256))]
    groups = [apis.PodGroup(f"g{i}", queue="q", min_member=1)
              for i in range(3)]
    pods = [apis.Pod(f"p{i}", f"g{i}", apis.ResourceVec(0, 1, 1),
                     extended={MIG: 2.0}) for i in range(3)]
    state, idx = build_snapshot(nodes, _queue(), groups, pods)
    assert idx.has_extended_resources and idx.extended_keys == [MIG]
    res = run_allocate(state)
    allocated = np.asarray(res.allocated)
    assert int(allocated.sum()) == 2
    pl = np.asarray(res.placements)
    placed_nodes = {idx.node_names[pl[i, 0]] for i in range(3)
                    if allocated[i]}
    assert placed_nodes == {"mig"}
    assert float(np.asarray(res.extended_free)[0, 0]) == 0.0


def test_running_pods_hold_mig_slices():
    nodes = [apis.Node("mig", apis.ResourceVec(0, 64, 256),
                       extended={MIG: 4.0})]
    groups = [apis.PodGroup("old", queue="q", min_member=1,
                            last_start_timestamp=0.0),
              apis.PodGroup("new", queue="q", min_member=1)]
    pods = [apis.Pod("r0", "old", apis.ResourceVec(0, 1, 1),
                     extended={MIG: 3.0}, status=apis.PodStatus.RUNNING,
                     node="mig"),
            apis.Pod("p0", "new", apis.ResourceVec(0, 1, 1),
                     extended={MIG: 2.0})]
    state, _ = build_snapshot(nodes, _queue(), groups, pods)
    res = run_allocate(state)
    assert not np.asarray(res.allocated)[1]  # only 1 slice free


def test_dra_counts_add_to_accel_accounting():
    """A pod claiming 2 devices via DRA occupies 2 accel units and the
    BindRequest records the claim allocation."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    groups = [apis.PodGroup("g", queue="q", min_member=1),
              apis.PodGroup("g2", queue="q", min_member=1)]
    pods = [apis.Pod("p0", "g", apis.ResourceVec(0, 1, 1),
                     dra_accel_count=2),
            apis.Pod("p1", "g2", apis.ResourceVec(1, 1, 1))]
    cluster = Cluster.from_objects(nodes, _queue(), groups, pods)
    r = Scheduler().run_once(cluster)
    by_name = {br.pod_name: br for br in r.bind_requests}
    # the DRA pod takes both devices; the whole-device pod cannot fit
    assert "p0" in by_name and "p1" not in by_name
    assert len(by_name["p0"].resource_claim_allocations) == 2


def test_mig_g_equivalents_gate_queue_limit_in_cycle():
    """MIG g-number equivalents enter the placement's in-cycle queue
    delta (ref resource_info.go GetTotalGPURequest), so a queue's hard
    accel limit stops MIG placements in the SAME cycle — previously a
    cycle's own MIG placements only reached the ledger at the next
    snapshot (bounded staleness, closed this round)."""
    nodes = [apis.Node("mig", apis.ResourceVec(0, 64, 256),
                       extended={MIG: 4.0})]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100,
                                                       limit=2.0))]
    groups = [apis.PodGroup(f"g{i}", queue="q", min_member=1)
              for i in range(2)]
    # each pod asks 2 x 1g slices = 2 accel g-equivalents; the node
    # fits both (4 slices), only the queue limit can stop the second
    pods = [apis.Pod(f"p{i}", f"g{i}", apis.ResourceVec(0, 1, 1),
                     extended={MIG: 2.0}) for i in range(2)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    assert float(np.asarray(state.gangs.ext_accel)[0]) == 1.0  # 1g key
    res = run_allocate(state)
    allocated = np.asarray(res.allocated)
    assert int(allocated.sum()) == 1
    # the committed queue ledger carries the g-equivalents
    assert float(np.asarray(res.queue_allocated)[0, 0]) == 2.0

"""Fractional / device-group sharing tests — ref
``actions/allocate/allocateFractionalGpu_test.go`` and
``allocateGpuMemory_test.go`` scenarios plus gpupack/gpuspread ordering."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
from kai_scheduler_tpu.ops.scoring import PlacementConfig
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import build_snapshot

Vec = apis.ResourceVec
QR = apis.QueueResource


def run_allocate(nodes, groups, pods, *, device_pack=True):
    queues = [apis.Queue("q0", accel=QR(quota=1000.0))]
    state, index = build_snapshot(nodes, queues, groups, pods)
    fair_share = drf.set_fair_share(state, num_levels=1)
    cfg = AllocateConfig(
        placement=PlacementConfig(device_pack=device_pack))
    res = allocate(state, fair_share, num_levels=1, config=cfg)
    return res, state, index


def gang(name, n_pods, *, portion=0.0, mem=0.0, accel=0.0, ts=0.0):
    g = apis.PodGroup(name, queue="q0", min_member=n_pods,
                      creation_timestamp=ts)
    pods = [apis.Pod(f"{name}-p{i}", name,
                     resources=Vec(accel, 1.0, 1.0),
                     accel_portion=portion, accel_memory_gib=mem,
                     creation_timestamp=ts)
            for i in range(n_pods)]
    return g, pods


class TestFractional:
    def test_two_halves_share_one_device(self):
        nodes = [apis.Node("node-0", Vec(2.0, 64.0, 256.0))]
        g0, p0 = gang("g0", 2, portion=0.5)
        res, state, index = run_allocate(nodes, [g0], p0)
        gi = index.gang_names.index("g0")
        assert bool(res.allocated[gi])
        devs = np.asarray(res.placement_device)[gi, :2]
        assert (devs >= 0).all()
        # gpupack default: both halves packed onto the SAME device
        assert devs[0] == devs[1]
        # device table: one device fully used, one untouched
        df = np.sort(np.asarray(res.device_free)[0])
        np.testing.assert_allclose(df, [0.0, 1.0], atol=1e-5)

    def test_gpuspread_puts_fractions_on_different_devices(self):
        nodes = [apis.Node("node-0", Vec(2.0, 64.0, 256.0))]
        g0, p0 = gang("g0", 2, portion=0.5)
        res, state, index = run_allocate(nodes, [g0], p0, device_pack=False)
        devs = np.asarray(res.placement_device)[0, :2]
        assert devs[0] != devs[1]

    def test_fraction_too_big_for_any_device_fails(self):
        # 0.6 + 0.6 > 1.0: second pod cannot share the first's device and
        # the node has only one device.
        nodes = [apis.Node("node-0", Vec(1.0, 64.0, 256.0))]
        g0, p0 = gang("g0", 2, portion=0.6)
        res, state, index = run_allocate(nodes, [g0], p0)
        assert not bool(res.allocated[0])

    def test_whole_device_task_needs_fully_free_device(self):
        # devices at 0.5 free each: a whole-device task must NOT fit even
        # though total free accel = 1.0
        nodes = [apis.Node("node-0", Vec(2.0, 64.0, 256.0))]
        frac = apis.PodGroup("frac", queue="q0", min_member=2,
                             last_start_timestamp=0.0)
        frac_pods = [
            apis.Pod(f"f{i}", "frac", resources=Vec(0.0, 1.0, 1.0),
                     accel_portion=0.5, status=apis.PodStatus.RUNNING,
                     node="node-0", accel_devices=[i])
            for i in range(2)]
        whole, whole_pods = gang("whole", 1, accel=1.0, ts=1.0)
        res, state, index = run_allocate(nodes, [frac, whole],
                                         frac_pods + whole_pods)
        wi = index.gang_names.index("whole")
        assert not bool(res.allocated[wi])

    def test_sharing_order_prefers_used_device_node(self):
        # node-0 has a half-used device; node-1 all free.  A new 0.5
        # fraction should go to node-0's shared device (gpusharingorder
        # band + gpupack), keeping node-1's devices whole.
        nodes = [apis.Node(f"node-{i}", Vec(2.0, 64.0, 256.0))
                 for i in range(2)]
        frac = apis.PodGroup("frac", queue="q0", min_member=1,
                             last_start_timestamp=0.0)
        frac_pods = [apis.Pod("f0", "frac", resources=Vec(0.0, 1.0, 1.0),
                              accel_portion=0.5,
                              status=apis.PodStatus.RUNNING,
                              node="node-0", accel_devices=[0])]
        newg, new_pods = gang("new", 1, portion=0.5, ts=1.0)
        res, state, index = run_allocate(nodes, [frac, newg],
                                         frac_pods + new_pods)
        ni = index.gang_names.index("new")
        assert bool(res.allocated[ni])
        node = int(np.asarray(res.placements)[ni, 0])
        dev = int(np.asarray(res.placement_device)[ni, 0])
        assert index.node_names[node] == "node-0"
        assert dev == 0                      # joined the shared device


class TestMemoryBased:
    def test_memory_request_converts_to_portion(self):
        # 8 GiB of a 16 GiB device = 0.5 portion; two such pods share one
        # device.
        nodes = [apis.Node("node-0", Vec(1.0, 64.0, 256.0),
                           accel_memory_gib=16.0)]
        g0, p0 = gang("g0", 2, mem=8.0)
        res, state, index = run_allocate(nodes, [g0], p0)
        assert bool(res.allocated[0])
        df = np.asarray(res.device_free)[0]
        np.testing.assert_allclose(df[0], 0.0, atol=1e-5)

    def test_memory_request_respects_node_device_memory(self):
        # 12 GiB request: fits a 16 GiB device (0.75) but not an 8 GiB
        # one — node choice must respect per-node device memory.
        nodes = [
            apis.Node("small", Vec(1.0, 64.0, 256.0), accel_memory_gib=8.0),
            apis.Node("big", Vec(1.0, 64.0, 256.0), accel_memory_gib=16.0),
        ]
        g0, p0 = gang("g0", 1, mem=12.0)
        res, state, index = run_allocate(nodes, [g0], p0)
        assert bool(res.allocated[0])
        node = int(np.asarray(res.placements)[0, 0])
        assert index.node_names[node] == "big"


class TestEndToEndFraction:
    def test_bind_carries_device_group(self):
        from kai_scheduler_tpu.binder import Binder
        from kai_scheduler_tpu.framework import Scheduler, SchedulerConfig
        from kai_scheduler_tpu.framework.session import SessionConfig
        from kai_scheduler_tpu.runtime.cluster import Cluster

        nodes = [apis.Node("node-0", Vec(2.0, 64.0, 256.0))]
        queues = [apis.Queue("q0", accel=QR(quota=8.0))]
        g0, p0 = gang("g0", 2, portion=0.5)
        cluster = Cluster.from_objects(nodes, queues, [g0], p0)
        sched = Scheduler(SchedulerConfig(
            actions=("allocate",), session=SessionConfig(num_levels=1)))
        r = sched.run_once(cluster)
        assert len(r.bind_requests) == 2
        for br in r.bind_requests:
            assert br.received_resource_type == \
                apis.ReceivedResourceType.FRACTION
            assert len(br.selected_accel_groups) == 1
        Binder().reconcile(cluster)
        devs = {cluster.pods[p.name].accel_devices[0] for p in p0}
        assert len(devs) == 1            # packed onto one shared device


class TestReservations:
    """Shared-device reservation lifecycle — the reservation-pod
    analogue (``binder/binding/resourcereservation`` + the NVML agent in
    ``cmd/resourcereservation``): one reservation per shared device,
    sharers join/leave, the group dies with its last owner."""

    @staticmethod
    def _cluster():
        nodes = [apis.Node(name="n0",
                           allocatable=apis.ResourceVec(2.0, 32.0, 128.0),
                           accel_memory_gib=16.0)]
        queues = [apis.Queue(name="d", accel=apis.QueueResource(quota=4.0)),
                  apis.Queue(name="q", parent="d",
                             accel=apis.QueueResource(quota=4.0))]
        groups, pods = [], []
        for i in range(2):
            groups.append(apis.PodGroup(name=f"f{i}", queue="q",
                                        min_member=1))
            pods.append(apis.Pod(name=f"f{i}-0", group=f"f{i}",
                                 accel_portion=0.5))
        return Cluster.from_objects(nodes, queues, groups, pods)

    def test_sharers_join_one_reservation_and_release(self):
        from kai_scheduler_tpu.binder.binder import Binder
        from kai_scheduler_tpu.framework.scheduler import Scheduler
        cluster = self._cluster()
        Scheduler().run_once(cluster)
        result = Binder().reconcile(cluster)
        assert sorted(result.bound) == ["f0-0", "f1-0"]
        devs = {cluster.pods[p].accel_devices[0] for p in result.bound}
        if len(devs) == 1:  # gpupack default: both share one device
            res = cluster.reservations.get("n0", devs.pop())
            assert res is not None and res.owners == {"f0-0", "f1-0"}
            assert res.uuid.startswith("accel://n0/")
        assert len(cluster.reservations) == len(devs) or devs == set()
        # last sharer leaving deletes the reservation
        cluster.evict_pod("f0-0")
        cluster.tick()
        assert all("f0-0" not in r.owners
                   for r in cluster.reservations.for_pod("f0-0"))
        cluster.evict_pod("f1-0")
        cluster.tick()
        assert len(cluster.reservations) == 0

    def test_rollback_leaves_group_clean(self):
        from kai_scheduler_tpu.binder.binder import Binder, BinderPlugin
        from kai_scheduler_tpu.framework.scheduler import Scheduler

        class Boom:
            name = "boom"

            def pre_bind(self, cluster, pod, request):
                raise RuntimeError("induced bind failure")

            def post_bind(self, cluster, pod, request):
                pass

            def rollback(self, cluster, pod, request):
                pass

        from kai_scheduler_tpu.binder.binder import (
            DynamicResourcesPlugin, GpuSharingPlugin, VolumeBindingPlugin)
        cluster = self._cluster()
        Scheduler().run_once(cluster)
        binder = Binder(plugins=[VolumeBindingPlugin(),
                                 DynamicResourcesPlugin(),
                                 GpuSharingPlugin(), Boom()])
        result = binder.reconcile(cluster)
        assert result.bound == []
        assert len(cluster.reservations) == 0  # acquire rolled back

    def test_reservations_rebuilt_from_snapshot(self):
        from kai_scheduler_tpu.binder.binder import Binder
        from kai_scheduler_tpu.framework.scheduler import Scheduler
        from kai_scheduler_tpu.runtime import snapshot
        cluster = self._cluster()
        Scheduler().run_once(cluster)
        Binder().reconcile(cluster)
        n_before = len(cluster.reservations)
        back = snapshot.load_cluster(snapshot.dump_cluster(cluster))
        assert len(back.reservations) == n_before
        back.evict_pod("f0-0")
        back.evict_pod("f1-0")
        back.tick()
        assert len(back.reservations) == 0

"""Skip machinery tests: feasibility prefilter, scheduling-signature
skip, unschedulable marking + backoff — the analogue of
``actions/common/feasible_nodes.go`` / ``minimal_job_comparison.go`` and
the status_updater's UnschedulableOnNodePool flow."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.ops.allocate import AllocateConfig, allocate
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.state import build_snapshot

import pytest

pytestmark = pytest.mark.core


def run_allocate(state, *, num_levels=1, **cfg):
    fs = drf.set_fair_share(state, num_levels=num_levels)
    state = state.replace(queues=state.queues.replace(fair_share=fs))
    return allocate(state, fs, num_levels=num_levels,
                    config=AllocateConfig(**cfg))


def _setup(n_accel=2.0, gang_reqs=((2.0,),)):
    nodes = [apis.Node("n0", apis.ResourceVec(n_accel, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100))]
    groups, pods = [], []
    for gi, reqs in enumerate(gang_reqs):
        groups.append(apis.PodGroup(f"g{gi}", queue="q",
                                    min_member=len(reqs)))
        for ti, a in enumerate(reqs):
            pods.append(apis.Pod(f"p{gi}-{ti}", f"g{gi}",
                                 apis.ResourceVec(a, 1, 1)))
    return nodes, queues, groups, pods


def test_prefilter_drops_hopeless_gang_without_attempt():
    """A gang whose task fits no node is never attempted (reason 1)."""
    nodes, queues, groups, pods = _setup(
        n_accel=2.0, gang_reqs=((1.0,), (16.0,)))
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    assert not np.asarray(res.attempted)[1]
    assert int(np.asarray(res.fit_reason)[1]) == 1


def test_prefilter_respects_min_needed_quorum():
    """Elastic gang: 3 tasks, min_member=2, only 2 can ever fit — the
    prefilter must NOT drop it (it counts feasible tasks vs min_needed)."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100))]
    groups = [apis.PodGroup("g", queue="q", min_member=2)]
    pods = [apis.Pod(f"p{i}", "g", apis.ResourceVec(1, 1, 1))
            for i in range(3)]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state)
    assert np.asarray(res.allocated)[0]
    assert int((np.asarray(res.placements)[0] >= 0).sum()) == 2


def test_signature_skip_after_equivalent_failure():
    """Three identical single-task gangs on a 2-accel node: the first
    fills the node, the second fails the attempt, the third is skipped
    as an equivalent (reason 2, not attempted)."""
    nodes, queues, groups, pods = _setup(
        n_accel=2.0, gang_reqs=((2.0,), (2.0,), (2.0,)))
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, batch_size=1)
    allocated = np.asarray(res.allocated)
    attempted = np.asarray(res.attempted)
    reasons = np.asarray(res.fit_reason)
    assert allocated[0] and not allocated[1] and not allocated[2]
    assert attempted[1]
    assert int(reasons[1]) == 3
    assert not attempted[2]
    assert int(reasons[2]) == 2


def test_signature_differs_across_queues():
    """Equivalence includes the queue: a failure in one queue must not
    skip an identical gang in another (their capacity gates differ)."""
    nodes = [apis.Node("n0", apis.ResourceVec(4, 64, 256))]
    queues = [
        apis.Queue("qa", accel=apis.QueueResource(quota=0.0, limit=0.0)),
        apis.Queue("qb", accel=apis.QueueResource(quota=4.0)),
    ]
    groups = [apis.PodGroup("ga", queue="qa", min_member=1),
              apis.PodGroup("gb", queue="qb", min_member=1)]
    pods = [apis.Pod("pa", "ga", apis.ResourceVec(2, 1, 1)),
            apis.Pod("pb", "gb", apis.ResourceVec(2, 1, 1))]
    state, _ = build_snapshot(nodes, queues, groups, pods)
    res = run_allocate(state, batch_size=1)
    assert not np.asarray(res.allocated)[0]   # qa is capped to zero
    assert np.asarray(res.allocated)[1]       # qb unaffected by ga's failure


def test_unschedulable_marking_and_churn_reset():
    """scheduling_backoff=1: one failed cycle marks the group
    unschedulable; the snapshot then skips it; pod churn clears it."""
    from kai_scheduler_tpu.controllers.podgroup_controller import \
        PodGroupController
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100))]
    groups = [apis.PodGroup("huge", queue="q", min_member=1,
                            scheduling_backoff=1)]
    pods = [apis.Pod("hp", "huge", apis.ResourceVec(16, 1, 1))]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)
    sched = Scheduler()
    ctl = PodGroupController()
    ctl.reconcile(cluster)
    sched.run_once(cluster)
    g = cluster.pod_groups["huge"]
    assert g.unschedulable and g.unschedulable_reason
    assert g.phase == apis.PodGroupPhase.UNSCHEDULABLE

    # while marked, the gang is skipped (not attempted, reason untouched)
    r2 = sched.run_once(cluster)
    assert not np.asarray(r2.tensors.attempted)[0]

    # pod churn (a new pending pod) clears the condition
    cluster.pods["hp2"] = apis.Pod("hp2", "huge", apis.ResourceVec(1, 1, 1))
    ctl.reconcile(cluster)
    assert not g.unschedulable


def test_default_backoff_never_marks():
    """Default scheduling_backoff=-1: fit failures accumulate but the
    group keeps being retried (ref NoSchedulingBackoff default)."""
    nodes = [apis.Node("n0", apis.ResourceVec(2, 64, 256))]
    queues = [apis.Queue("q", accel=apis.QueueResource(quota=100))]
    groups = [apis.PodGroup("huge", queue="q", min_member=1)]
    pods = [apis.Pod("hp", "huge", apis.ResourceVec(16, 1, 1))]
    cluster = Cluster.from_objects(nodes, queues, groups, pods)
    sched = Scheduler()
    sched.run_once(cluster)
    sched.run_once(cluster)
    g = cluster.pod_groups["huge"]
    assert g.fit_failures == 2 and not g.unschedulable

"""kai-lint tests — rule self-tests, package cleanliness, jaxpr probe.

Three layers of guarantees:

1. **Rule fixtures** — every registered KAI rule carries a must-trigger
   and a must-not-trigger snippet; both are exercised, so a rule edit
   that stops detecting its own hazard (or starts flagging the clean
   idiom) fails here, not in production review.
2. **Package invariants** — the whole package lints clean with NO
   baseline, every inline ``kai-lint: disable`` still matches a live
   finding (no suppression rot), and the shipped lint baseline is
   empty (the tree owes nothing).
3. **Trace probe** — every registered op (cross-checked against the
   call graph's jit entry points, so a new jitted kernel cannot dodge
   coverage) traces without host callbacks or f64, compiles exactly
   once per shape bucket across two independent snapshot builds, and
   stays within the eqn/const budgets of ``analysis/baseline.json``.
"""
import json
import os

import pytest

from kai_scheduler_tpu.analysis import lint_package, lint_source
from kai_scheduler_tpu.analysis.callgraph import PackageGraph
from kai_scheduler_tpu.analysis.engine import RULES, rule_catalog

pytestmark = pytest.mark.core

ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

rule_catalog()  # force rule registration


# ---------------------------------------------------------------------------
# 1. per-rule fixture self-tests

_FIXTURED = sorted(c for c in RULES if RULES[c].fixture_bad)


def test_every_rule_has_fixtures():
    # KAI000 is emitted by the engine's suppression bookkeeping, not a
    # checker — everything else must ship its own self-test snippets
    assert _FIXTURED == sorted(c for c in RULES if c != "KAI000")


@pytest.mark.parametrize("code", _FIXTURED)
def test_rule_fixture_triggers(code):
    findings = lint_source(RULES[code].fixture_bad)
    assert any(f.code == code for f in findings), (
        f"{code} must-trigger fixture produced no {code} finding: "
        f"{findings}")


@pytest.mark.parametrize("code", _FIXTURED)
def test_rule_fixture_negative(code):
    findings = lint_source(RULES[code].fixture_good)
    assert not any(f.code == code for f in findings), (
        f"{code} must-NOT-trigger fixture still fires: "
        f"{[f.render() for f in findings if f.code == code]}")


def test_jit_region_scoping():
    """Host-only code is exempt from the trace-safety families: the
    same .item() that is a finding inside @jax.jit is legal outside."""
    hot = """
import jax

@jax.jit
def op(x):
    return x.item()
"""
    cold = """
def commit(x):
    return x.item()
"""
    assert any(f.code == "KAI001" for f in lint_source(hot))
    assert not lint_source(cold)


def test_jit_region_grows_through_calls():
    """A helper only *called from* a jitted entry is in the region."""
    src = """
import jax
import numpy as np

def helper(x):
    return np.asarray(x)

@jax.jit
def op(x):
    return helper(x)
"""
    findings = lint_source(src)
    assert any(f.code == "KAI002" and f.function == "helper"
               for f in findings)


# ---------------------------------------------------------------------------
# 2. suppression + baseline mechanics

def test_suppression_silences_finding():
    src = """
def f(xs):
    for x in set(xs):  # kai-lint: disable=KAI041
        print(x)
"""
    assert lint_source(src) == []


def test_own_line_suppression_covers_next_line():
    src = """
def f(xs):
    # kai-lint: disable=KAI041
    for x in set(xs):
        print(x)
"""
    assert lint_source(src) == []


def test_stale_suppression_is_a_finding():
    src = """
def f(xs):
    return sorted(xs)  # kai-lint: disable=KAI041
"""
    findings = lint_source(src)
    assert [f.code for f in findings] == ["KAI000"]


def test_docstring_disable_examples_are_inert():
    src = '''
def f(xs):
    """Docs showing `# kai-lint: disable=KAI041` syntax."""
    return sorted(xs)
'''
    assert lint_source(src) == []


# ---------------------------------------------------------------------------
# 3. the package itself

def test_package_lints_clean_without_baseline():
    res = lint_package(ROOT)
    assert res.findings == [], "\n".join(
        f.render() for f in res.findings)


def test_no_stale_suppressions_in_package():
    """Every inline ``kai-lint: disable`` still matches a live finding."""
    res = lint_package(ROOT)
    assert res.stale_suppressions == [], "\n".join(
        f.render() for f in res.stale_suppressions)


def test_lint_baseline_stays_empty():
    """The shipped baseline carries probe stats ONLY — lint findings
    are fixed or inline-suppressed, never parked."""
    path = os.path.join(ROOT, "kai_scheduler_tpu", "analysis",
                        "baseline.json")
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    assert data.get("lint", []) == []


def test_known_jit_entry_points_probed():
    """Every jit entry the call graph detects maps to probe coverage —
    add a new jitted kernel and this fails until the probe registry
    (and its baseline) learn about it."""
    from kai_scheduler_tpu.analysis.trace_probe import registered_ops
    entry_to_ops = {
        "_fused_pipeline": {"fused_pipeline"},
        "_pack_commit": {"pack_commit"},
        "allocate_jit": {"allocate"},
        "set_fair_share": {"set_fair_share"},
        "stale_gang_eviction": {"stale_gang_eviction"},
        "run_victim_action_jit": {"victims_reclaim", "victims_preempt",
                                  "victims_consolidate"},
        "cumsum_ds": {"cumsum_ds"},
        # kai-pulse cluster-health kernel (ops/analytics.py)
        "cluster_analytics": {"analytics"},
        # kai-repack defragmentation solver (ops/repack.py)
        "plan_repack": {"repack"},
        # kai-resident fused cycle entry (framework/scheduler.py)
        "resident_cycle": {"resident_cycle"},
    }
    graph = PackageGraph(ROOT)
    entries = {q for _m, q in graph._entries()}
    ops = set(registered_ops())
    for q in sorted(entries):
        assert q in entry_to_ops, (
            f"new jit entry point `{q}` — register it in "
            f"analysis/trace_probe.py::_registry and refresh the "
            f"baseline (--probe --update-baseline)")
        missing = entry_to_ops[q] - ops
        assert not missing, f"probe registry lost ops {missing} for {q}"


def test_cost_coverage_rides_the_probe_registry():
    """kai-cost (PR 14) audits the SAME registry the probe traces —
    one shared per-entry walk, one coverage surface.  A jit entry that
    passes the probe-coverage test above therefore cannot dodge the
    cost auditor (its own meta-tests live in test_costmodel.py; this
    pin keeps the two registries from ever forking)."""
    from kai_scheduler_tpu.analysis.costmodel import (
        registered_cost_entries)
    from kai_scheduler_tpu.analysis.trace_probe import registered_ops
    assert registered_cost_entries() == registered_ops()


# ---------------------------------------------------------------------------
# 3b. kai-race — thread-root discovery, guarded-by map coverage, and
#     the package's race cleanliness (all pure AST, jax-free)

@pytest.fixture(scope="module")
def race_report():
    from kai_scheduler_tpu.analysis import concurrency
    graph = PackageGraph(ROOT)
    return concurrency.analyze_package(graph,
                                       concurrency.load_guarded_map())


def test_package_races_clean_with_empty_baseline(race_report):
    """The whole package passes the KAI1xx race pass with no baseline
    and zero stale annotations (the PR-4 acceptance bar)."""
    report = race_report
    assert report.findings == [], "\n".join(
        f.render() for f in report.findings)


def test_every_thread_root_covered_by_guarded_by_map(race_report):
    """Discovery == the checked-in audit map, both directions: a new
    daemon thread fails here until its state-sharing is audited, and a
    removed thread leaves no stale map row."""
    from kai_scheduler_tpu.analysis import concurrency
    report = race_report
    mapped = set(concurrency.load_guarded_map()["thread_roots"])
    discovered = {r.root_id for r in report.roots}
    assert discovered == mapped, (
        f"uncovered roots: {sorted(discovered - mapped)}; "
        f"stale map rows: {sorted(mapped - discovered)}")


def test_known_thread_roots_discovered(race_report):
    """The pass must see the package's actual daemon threads — if
    discovery regresses, the race rules silently check nothing."""
    report = race_report
    discovered = {r.root_id for r in report.roots}
    for expected in (
            "kai_scheduler_tpu/runtime/status_updater.py::"
            "AsyncStatusUpdater._worker",
            "kai_scheduler_tpu/runtime/profiling.py::"
            "ContinuousProfiler._run",
            "kai_scheduler_tpu/framework/server.py::"
            "SchedulerServer.__init__.Handler.do_GET",
            "kai_scheduler_tpu/framework/server.py::"
            "SchedulerServer.__init__.Handler.do_POST",
            "kai_scheduler_tpu/intake/router.py::"
            "IntakeRouter._worker"):
        assert expected in discovered, (expected, sorted(discovered))
    # handler threads are per-request: multi-instance conflicts count
    multi = {r.root_id for r in report.roots if r.multi}
    assert any("do_GET" in r for r in multi)
    assert any("_worker" in r for r in multi)
    # the kai-intake worker pool spawns one drain thread per lane — it
    # must register as multi-instance or lane races check nothing
    assert ("kai_scheduler_tpu/intake/router.py::IntakeRouter._worker"
            in multi)


def test_race_pass_sees_intake_lane_discipline(race_report):
    """Detection power for the PR-12 surface: the pass must actually
    OBSERVE _Lane state shared between the drain-worker root and
    handler/coalesce contexts under the lane lock — if type resolution
    of the lane helpers regresses, the lane annotations go stale and
    the race rules silently stop covering the intake path."""
    recs = [r for r in race_report.interp_accesses
            if r.cls == "_Lane" and r.attr in ("queued", "staged")]
    roots = {r.root for r in recs}
    assert any("IntakeRouter._worker" in r for r in roots), roots
    assert len(roots) >= 2, roots
    assert all(("_Lane", "_lock") in r.held for r in recs), [
        (r.function, r.line) for r in recs if ("_Lane", "_lock")
        not in r.held]


def test_guarded_by_annotations_are_live(race_report):
    """The package documents its lock discipline inline and the checker
    verifies every annotation still matches live shared state."""
    report = race_report
    assert report.live_annotations >= 5
    assert not any(f.code == "KAI100" for f in report.findings)


def test_race_pass_catches_dropped_journal_lock():
    """Detection power: deleting the journal lock from a mark path must
    surface KAI102 — the analyzer, not luck, guards the journal."""
    import ast as _ast

    from kai_scheduler_tpu.analysis import concurrency
    from kai_scheduler_tpu.analysis.callgraph import ModuleInfo
    graph = PackageGraph(ROOT)
    target = "kai_scheduler_tpu/state/incremental.py"
    for name, mod in graph.modules.items():
        if mod.relpath != target:
            continue
        src = mod.source.replace(
            "    def mark_time(self) -> None:\n"
            "        with self._lock:\n"
            "            self._apply_mark(\"time\", \"\")",
            "    def mark_time(self) -> None:\n"
            "        if True:\n"
            "            self._apply_mark(\"time\", \"\")")
        assert src != mod.source, "mark_time shape changed — update test"
        graph.modules[name] = ModuleInfo(
            relpath=mod.relpath, modname=mod.modname,
            tree=_ast.parse(src), source=src)
    report = concurrency.analyze_package(graph,
                                         concurrency.load_guarded_map())
    hits = [f for f in report.findings if f.code == "KAI102"
            and "generation" in f.message]
    assert hits, [f.render() for f in report.findings]


def test_race_cli_json_section(capsys):
    """``--race --json`` emits the race section: thread roots, zero
    findings, live annotations (the CI consumer contract)."""
    from kai_scheduler_tpu.analysis.__main__ import main
    rc = main(["--race", "--json", "--root", ROOT])
    out = json.loads(capsys.readouterr().out)
    assert rc == 0
    assert out["findings"] == []
    assert out["race"]["findings"] == []
    assert len(out["race"]["thread_roots"]) >= 4
    assert out["race"]["live_annotations"] >= 5


def test_list_rules_includes_race_family(capsys):
    from kai_scheduler_tpu.analysis.__main__ import main
    assert main(["--list-rules"]) == 0
    out = capsys.readouterr().out
    for code in ("KAI100", "KAI101", "KAI102", "KAI103", "KAI104",
                 "KAI105"):
        assert code in out


def test_race_suppression_and_staleness():
    """KAI1xx findings ride the same inline-suppression machinery as
    the KAI0xx rules, including KAI000 staleness."""
    bad = RULES["KAI101"].fixture_bad.replace(
        "        self.count += 1",
        "        self.count += 1  # kai-lint: disable=KAI101")
    assert lint_source(bad) == []
    stale = RULES["KAI101"].fixture_good.replace(
        "            self.count += 1",
        "            self.count += 1  # kai-lint: disable=KAI101")
    findings = lint_source(stale)
    assert [f.code for f in findings] == ["KAI000"]


def test_lock_order_fixture_is_directional():
    """KAI103 keys on *inverted* order, not on nesting per se."""
    from kai_scheduler_tpu.analysis.engine import RULES as _rules
    consistent = _rules["KAI103"].fixture_good
    assert not any(f.code == "KAI103" for f in lint_source(consistent))


# ---------------------------------------------------------------------------
# 4. jaxpr probe (compiles the real kernels — shares the suite's
#    persistent compile cache and padded shapes)

@pytest.fixture(scope="module")
def probe_reports():
    from kai_scheduler_tpu.analysis.trace_probe import run_probe
    return {r.name: r for r in run_probe()}


def test_probe_covers_all_registered_ops(probe_reports):
    from kai_scheduler_tpu.analysis.trace_probe import registered_ops
    assert sorted(probe_reports) == sorted(registered_ops())


def test_probe_no_forbidden_primitives(probe_reports):
    bad = {n: r.forbidden for n, r in probe_reports.items()
           if r.forbidden}
    assert not bad, f"host callbacks inside compiled ops: {bad}"


def test_probe_no_f64_on_device(probe_reports):
    bad = {n: r.f64_avals for n, r in probe_reports.items()
           if r.f64_avals}
    assert not bad, f"f64 avals leaked into device programs: {bad}"


def test_probe_compiles_once_per_shape_bucket(probe_reports):
    """Two independent builds of an equivalent cluster (fresh host
    objects, different wall clock) must share ONE compile per op —
    the end-to-end nondeterministic-signature guard.  ``is True``, not
    ``is not False``: if a jax upgrade drops the ``_cache_size`` probe,
    every report degrades to None and this must fail LOUDLY rather
    than pass vacuously (re-wire the probe, don't soften the test)."""
    not_hit = {n: r.cache_hit for n, r in probe_reports.items()
               if r.cache_hit is not True}
    assert not not_hit, (
        f"compile-once check not confirmed for {not_hit} (False = "
        f"re-trace missed the jit cache: some input shape/dtype/"
        f"static-config is build-dependent; None = the cache probe "
        f"is gone)")


def test_probe_stats_within_baseline(probe_reports):
    from kai_scheduler_tpu.analysis.trace_probe import (
        check_against_baseline, load_stats_baseline)
    problems = check_against_baseline(list(probe_reports.values()),
                                      load_stats_baseline())
    assert not problems, "\n".join(problems)

"""Time-based fairshare tests — ref ``cache/usagedb`` + the env-test
shapes in ``pkg/env-tests/time_aware_fairness_test.go``: historical
usage shrinks a greedy queue's over-quota fair share via the k term."""
import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler, SchedulerConfig
from kai_scheduler_tpu.framework.session import SessionConfig
from kai_scheduler_tpu.ops import drf
from kai_scheduler_tpu.runtime.cluster import Cluster
from kai_scheduler_tpu.runtime.usagedb import (UsageLister, UsageParams,
                                               cluster_allocation_client,
                                               cluster_capacity_fn)
from kai_scheduler_tpu.state import build_snapshot

R = apis.NUM_RESOURCES


def test_sliding_window_decay_and_normalization():
    alloc = {"qa": np.array([4.0, 0.0, 0.0])}
    lister = UsageLister(
        client=lambda now: alloc,
        params=UsageParams(half_life_s=100.0, fetch_interval_s=10.0),
        capacity_fn=lambda now: np.array([8.0, 0.0, 0.0]))
    for t in range(0, 101, 10):
        lister.fetch(float(t))
    usage = lister.queue_usage(100.0)
    # constant 4-of-8 allocation => normalized usage approaches 0.5
    assert usage is not None
    assert abs(float(usage["qa"][0]) - 0.5) < 1e-6

    # stop allocating: usage decays toward 0 while capacity keeps
    # integrating, so the normalized share shrinks
    alloc.clear()
    for t in range(110, 400, 10):
        lister.fetch(float(t))
    late = lister.queue_usage(390.0)
    assert float(late["qa"][0]) < 0.2


def test_staleness_rejects_old_data():
    lister = UsageLister(
        client=lambda now: {"qa": np.array([1.0, 0.0, 0.0])},
        params=UsageParams(fetch_interval_s=10.0, staleness_period_s=30.0),
        capacity_fn=lambda now: np.array([8.0, 0.0, 0.0]))
    lister.fetch(0.0)
    lister.fetch(10.0)
    assert lister.queue_usage(20.0) is not None
    assert lister.queue_usage(50.0) is None  # > 30s since last data


def test_tumbling_window_resets():
    lister = UsageLister(
        client=lambda now: {"qa": np.array([4.0, 0.0, 0.0])},
        params=UsageParams(window_type="tumbling", tumbling_window_s=100.0,
                           fetch_interval_s=10.0),
        capacity_fn=lambda now: np.array([8.0, 0.0, 0.0]))
    for t in range(0, 100, 10):
        lister.fetch(float(t))
    before = float(lister.queue_usage(90.0)["qa"][0])
    lister.fetch(105.0)  # crosses the boundary: accumulator resets
    lister.fetch(110.0)
    after = float(lister.queue_usage(110.0)["qa"][0])
    assert before > 0.4
    # after the reset only one 5s interval is integrated
    assert after <= before


def _two_queue_state(usage_a: float, k_value: float):
    nodes = [apis.Node("n0", apis.ResourceVec(8, 640, 2560))]
    queues = [
        apis.Queue("qa", accel=apis.QueueResource(quota=0.0,
                                                  over_quota_weight=1.0)),
        apis.Queue("qb", accel=apis.QueueResource(quota=0.0,
                                                  over_quota_weight=1.0)),
    ]
    groups = [apis.PodGroup(f"g{q}", queue=q, min_member=1)
              for q in ("qa", "qb")]
    pods = [apis.Pod(f"p{q}-{i}", f"g{q}", apis.ResourceVec(1, 1, 1))
            for q in ("qa", "qb") for i in range(8)]
    usage = {"qa": np.array([usage_a, 0.0, 0.0], np.float32)}
    state, _ = build_snapshot(nodes, queues, groups, pods,
                              queue_usage=usage)
    fs = drf.set_fair_share(state, num_levels=1, k_value=k_value)
    return np.asarray(fs)


def test_usage_shrinks_fair_share_with_k():
    """Equal-weight queues, queue A historically used half the cluster:
    with k>0 its fair share drops below B's; with k=0 they split evenly."""
    fs_k0 = _two_queue_state(usage_a=0.5, k_value=0.0)
    assert abs(fs_k0[0, 0] - fs_k0[1, 0]) <= 1.0  # even split (± rounding)
    fs_k2 = _two_queue_state(usage_a=0.5, k_value=2.0)
    assert fs_k2[0, 0] < fs_k2[1, 0] - 1.0


def test_scheduler_threads_usage_end_to_end():
    """Scheduler + UsageLister: after queue A hogs the cluster for a
    while, a contended re-schedule gives B the larger share."""
    nodes = [apis.Node("n0", apis.ResourceVec(8, 640, 2560))]
    queues = [
        apis.Queue("qa", accel=apis.QueueResource(quota=0.0,
                                                  over_quota_weight=1.0)),
        apis.Queue("qb", accel=apis.QueueResource(quota=0.0,
                                                  over_quota_weight=1.0)),
    ]
    # phase 1: only A's workload exists and takes the whole cluster
    ga = apis.PodGroup("ga", queue="qa", min_member=1)
    pods_a = [apis.Pod(f"pa{i}", "ga", apis.ResourceVec(1, 1, 1))
              for i in range(8)]
    cluster = Cluster.from_objects(nodes, queues, [ga], pods_a)
    lister = UsageLister(cluster_allocation_client(cluster),
                         UsageParams(half_life_s=1000.0,
                                     fetch_interval_s=10.0),
                         capacity_fn=cluster_capacity_fn(cluster))
    sched = Scheduler(SchedulerConfig(
        session=SessionConfig(k_value=2.0)), usage_lister=lister)
    res = sched.run_once(cluster)
    for br in res.bind_requests:
        cluster.bind_pod(br.pod_name, br.selected_node)
    for t in range(0, 200, 10):
        cluster.tick(10.0)
        lister.maybe_fetch(cluster.now)
    # phase 2: A's pods finish; both queues now submit 8 pods each
    for p in list(cluster.pods.values()):
        p.status = apis.PodStatus.RELEASING
    cluster.tick(1.0)
    cluster.submit(apis.PodGroup("ga2", queue="qa", min_member=1),
                   [apis.Pod(f"pa2-{i}", "ga2", apis.ResourceVec(1, 1, 1))
                    for i in range(8)])
    cluster.submit(apis.PodGroup("gb", queue="qb", min_member=1),
                   [apis.Pod(f"pb{i}", "gb", apis.ResourceVec(1, 1, 1))
                    for i in range(8)])
    res2 = sched.run_once(cluster)
    placed = {"qa": 0, "qb": 0}
    for br in res2.bind_requests:
        placed["qa" if br.pod_name.startswith("pa") else "qb"] += 1
    assert placed["qb"] > placed["qa"], placed

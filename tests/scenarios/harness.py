"""Table-driven scenario harness — the parity analogue of the
reference's per-feature test catalogs.

Each :class:`Case` is one named scenario traceable to a reference test
(``ref`` carries the reference file and test-case name, e.g.
``allocateGang_test.go: "Allocate train gang job"``).  A case builds a
synthetic cluster from terse specs, runs ONE full scheduler cycle
(snapshot → default action pipeline → commit), and asserts the
reference-matching outcome: which gangs placed (and optionally where /
how many tasks), which stayed pending, how many victims were evicted,
and what got pipelined.

The specs are intentionally tiny — a catalog of dozens of cases must
read like the reference's declarative TestTopologyData tables, not like
setup code.
"""
from __future__ import annotations

import dataclasses

import numpy as np

from kai_scheduler_tpu.apis import types as apis
from kai_scheduler_tpu.framework.scheduler import Scheduler
from kai_scheduler_tpu.runtime.cluster import Cluster


@dataclasses.dataclass
class N:
    """Node spec."""

    name: str
    gpu: float = 8.0
    cpu: float = 64.0
    mem: float = 256.0
    gpu_mem_gib: float = 0.0          # per-device memory (memory-based shares)
    labels: dict = dataclasses.field(default_factory=dict)
    taints: list = dataclasses.field(default_factory=list)
    mig: dict = dataclasses.field(default_factory=dict)  # extended resources


@dataclasses.dataclass
class Q:
    """Leaf queue spec (a single shared department is implied unless
    ``parent`` names another spec'd queue)."""

    name: str
    quota: float = -1.0               # UNLIMITED by default
    limit: float = -1.0
    cpu_quota: float = -1.0
    cpu_limit: float = -1.0
    priority: int = 0
    parent: str | None = None
    preempt_min_runtime: float = 0.0
    reclaim_min_runtime: float = 0.0


@dataclasses.dataclass
class G:
    """Gang spec: ``tasks`` pending pods of ``gpu`` each; ``on`` makes
    it RUNNING instead, round-robin over the listed nodes."""

    name: str
    queue: str = "q0"
    tasks: int = 1
    gpu: float = 1.0
    cpu: float = 1.0
    mem: float = 4.0
    min_member: int = 0               # 0 = tasks (whole gang)
    priority: int = 0
    on: list | None = None            # running placements (node names)
    portion: float = 0.0              # fractional share per task
    gpu_mem: float = 0.0              # memory-based share per task (GiB)
    mig: dict = dataclasses.field(default_factory=dict)
    labels: dict = dataclasses.field(default_factory=dict)
    affinity: list = dataclasses.field(default_factory=list)
    preemptible: bool = True
    runtime_s: float = 3600.0         # running pods' age
    subgroups: list = dataclasses.field(default_factory=list)
    subgroup_of: list | None = None   # per-task subgroup names
    topology: tuple | None = None     # (required_level, preferred_level)
    devices: list | None = None       # running pods' device ids (fractions)
    claims: list = dataclasses.field(default_factory=list)
    #: per-task claim-name lists (overrides ``claims``, which every
    #: task shares)
    claims_of: list | None = None
    #: running pods are RELEASING (being deleted) instead of RUNNING
    releasing: bool = False


@dataclasses.dataclass
class Case:
    """One scenario: build → one cycle → assert."""

    name: str
    ref: str                          # reference file + case name
    nodes: list = dataclasses.field(default_factory=list)
    queues: list = dataclasses.field(default_factory=list)
    gangs: list = dataclasses.field(default_factory=list)
    topology_levels: list = dataclasses.field(default_factory=list)
    #: gang -> expected PLACED task count (0 = must stay pending);
    #: True = all tasks placed
    expect: dict = dataclasses.field(default_factory=dict)
    #: gang -> set of allowed node names (all its placements inside)
    expect_nodes: dict = dataclasses.field(default_factory=dict)
    #: exact victim (eviction) count; None = don't check
    expect_evictions: int | None = None
    #: gang -> minimum pipelined task count
    expect_pipelined: dict = dataclasses.field(default_factory=dict)
    #: pairs of gangs that must not share a node
    expect_disjoint: list = dataclasses.field(default_factory=list)
    #: pairs of gangs that MUST share at least one node/domain
    expect_colocated: list = dataclasses.field(default_factory=list)
    #: DRA objects (apis.ResourceClaim / apis.DeviceClass)
    resource_claims: list = dataclasses.field(default_factory=list)
    device_classes: list = dataclasses.field(default_factory=list)
    #: node -> expected IDLE accel in the snapshot (pre-action), and
    #: node -> expected RELEASING accel — the reference's
    #: ``ExpectedNodesResources`` (test_utils.go IdleGPUs/ReleasingGPUs)
    expect_node_idle: dict = dataclasses.field(default_factory=dict)
    expect_node_releasing: dict = dataclasses.field(default_factory=dict)
    #: scheduler cycles to run before asserting — the reference's
    #: ``RoundsUntilMatch`` (multi-cycle convergence: evictions land,
    #: then consolidation/allocate use the freed capacity).  expect /
    #: expect_nodes / expect_pipelined read the FINAL cycle's tensors;
    #: expect_evictions counts across all cycles.
    rounds: int = 1
    #: action pipeline override — the reference's per-suite action
    #: config (allocate_test.go runs allocate ONLY; the victim suites
    #: configure their action sets).  None = the full default pipeline.
    actions: tuple | None = None


#: cluster clock for scenario runs — running gangs' start stamps are
#: _NOW - runtime_s (negative stamps would collide with the nil
#: "never started" sentinel)
_NOW = 1e6


def _build(case: Case):
    nodes = []
    for ns in case.nodes:
        labels = {"kubernetes.io/hostname": ns.name, **ns.labels}
        nodes.append(apis.Node(
            name=ns.name,
            allocatable=apis.ResourceVec(ns.gpu, ns.cpu, ns.mem),
            labels=labels, taints=list(ns.taints),
            accel_memory_gib=ns.gpu_mem_gib or 16.0,
            extended=dict(ns.mig)))
    specs = case.queues or [Q("q0")]
    spec_names = {qs.name for qs in specs}
    # a spec may itself be another spec's parent (multi-level
    # hierarchies); only parents nobody spec'd get bare Queue objects
    # un-spec'd parents impose no cap of their own (accel quota defaults
    # to 0 = nothing deserved, which would starve every non-preemptible
    # descendant at the ancestor gate)
    parents = {qs.parent for qs in specs if qs.parent} - spec_names
    queues = [apis.Queue(name=p, accel=apis.QueueResource(quota=-1.0))
              for p in sorted(parents)]
    need_dept = any(not qs.parent for qs in specs)
    if need_dept:
        queues.append(apis.Queue(name="dept",
                                 accel=apis.QueueResource(quota=-1.0)))
    for qs in specs:
        queues.append(apis.Queue(
            name=qs.name,
            parent=qs.parent or ("dept" if need_dept else None),
            priority=qs.priority,
            accel=apis.QueueResource(quota=qs.quota, limit=qs.limit),
            cpu=apis.QueueResource(quota=qs.cpu_quota,
                                   limit=qs.cpu_limit),
            preempt_min_runtime=qs.preempt_min_runtime,
            reclaim_min_runtime=qs.reclaim_min_runtime))
    groups, pods = [], []
    for gs in case.gangs:
        running = gs.on is not None
        sub_groups = [apis.SubGroup(name=nm, min_member=mm)
                      for nm, mm in gs.subgroups]
        topo = None
        if gs.topology:
            req, pref = gs.topology
            topo = apis.TopologyConstraint(
                topology="default", required_level=req,
                preferred_level=pref)
        groups.append(apis.PodGroup(
            name=gs.name, queue=gs.queue,
            min_member=gs.min_member or gs.tasks,
            priority=gs.priority,
            preemptibility=(apis.Preemptibility.PREEMPTIBLE
                            if gs.preemptible
                            else apis.Preemptibility.NON_PREEMPTIBLE),
            last_start_timestamp=(_NOW - gs.runtime_s) if running
            else None,
            sub_groups=sub_groups,
            topology_constraint=topo))
        for t in range(gs.tasks):
            pod = apis.Pod(
                name=f"{gs.name}-{t}", group=gs.name,
                resources=apis.ResourceVec(gs.gpu, gs.cpu, gs.mem),
                accel_portion=gs.portion,
                accel_memory_gib=gs.gpu_mem,
                labels=dict(gs.labels),
                pod_affinity=list(gs.affinity),
                extended=dict(gs.mig),
                resource_claims=list(gs.claims_of[t] if gs.claims_of
                                     else gs.claims),
                subgroup=(gs.subgroup_of[t]
                          if gs.subgroup_of else None))
            if running:
                pod.status = (apis.PodStatus.RELEASING if gs.releasing
                              else apis.PodStatus.RUNNING)
                pod.node = gs.on[t % len(gs.on)]
                if gs.devices:
                    pod.accel_devices = [gs.devices[t % len(gs.devices)]]
            pods.append(pod)
    cluster = Cluster.from_objects(nodes, queues, groups, pods,
                                (apis.Topology(
                                    name="default",
                                    levels=(case.topology_levels
                                            + ["kubernetes.io/hostname"]))
                                 if case.topology_levels else None))
    for claim in case.resource_claims:
        cluster.resource_claims[claim.name] = claim
    for dc in case.device_classes:
        cluster.device_classes[dc.name] = dc
    cluster.now = _NOW
    return cluster


def run_case(case: Case):
    cluster = _build(case)
    if case.expect_node_idle or case.expect_node_releasing:
        # the reference's ExpectedNodesResources count WHOLE devices
        # (node_info: a shared device is IDLE only when fully free,
        # RELEASING only when every holder is releasing) — derived here
        # from the snapshot's device table, the repo's source of truth
        # for shared-device occupancy
        from kai_scheduler_tpu.state import build_snapshot
        state, idx = build_snapshot(
            list(cluster.nodes.values()), list(cluster.queues.values()),
            list(cluster.pod_groups.values()), list(cluster.pods.values()),
            cluster.topology, resource_claims=cluster.resource_claims,
            device_classes=cluster.device_classes)
        ni = {nm: i for i, nm in enumerate(idx.node_names)}
        dev_free = np.asarray(state.nodes.device_free)
        dev_rel = np.asarray(state.nodes.device_releasing)
        counts = {ns.name: int(round(ns.gpu)) for ns in case.nodes}
        for node, want in case.expect_node_idle.items():
            d = counts[node]
            got = int((dev_free[ni[node], :d] >= 1.0 - 1e-6).sum())
            assert got == want, (
                f"{case.name}: {node} idle devices {got}, expected "
                f"{want} (ref {case.ref})")
        for node, want in case.expect_node_releasing.items():
            d = counts[node]
            fr, rl = dev_free[ni[node], :d], dev_rel[ni[node], :d]
            got = int(((rl > 1e-6) & (fr + rl >= 1.0 - 1e-6)).sum())
            assert got == want, (
                f"{case.name}: {node} releasing devices {got}, "
                f"expected {want} (ref {case.ref})")
    if case.actions is not None:
        from kai_scheduler_tpu.framework.scheduler import SchedulerConfig
        sched = Scheduler(SchedulerConfig(actions=tuple(case.actions)))
    else:
        sched = Scheduler()
    res = sched.run_once(cluster)
    n_evictions = len(res.evictions)
    for _ in range(case.rounds - 1):
        # releasing pods reap (or restart) between cycles, as the
        # reference's multi-round runner lets the cluster converge
        cluster.tick(1.0)
        res = sched.run_once(cluster)
        n_evictions += len(res.evictions)
    # gang -> (placed count, node names, pipelined count)
    placed = {b.pod_name.rsplit("-", 1)[0]: [] for b in res.bind_requests}
    for b in res.bind_requests:
        placed[b.pod_name.rsplit("-", 1)[0]].append(b.selected_node)
    pl = np.asarray(res.tensors.placements)
    pipe = np.asarray(res.tensors.pipelined)
    alloc = np.asarray(res.tensors.allocated)
    gang_names = [gs.name for gs in case.gangs]
    rows = {nm: i for i, nm in enumerate(gang_names)}
    node_names = [ns.name for ns in case.nodes]

    def placements_of(gang):
        gi = rows[gang]
        return [node_names[v] for v in pl[gi][pl[gi] >= 0]]

    for gang, want in case.expect.items():
        got = len(placements_of(gang)) if alloc[rows[gang]] else 0
        total = next(gs.tasks for gs in case.gangs if gs.name == gang)
        want_n = total if want is True else int(want)
        assert got == want_n, (
            f"{case.name}: {gang} placed {got} tasks, expected {want_n} "
            f"(ref {case.ref})")
    for gang, allowed in case.expect_nodes.items():
        ns = set(placements_of(gang))
        assert ns and ns <= set(allowed), (
            f"{case.name}: {gang} on {ns}, allowed {allowed} "
            f"(ref {case.ref})")
    if case.expect_evictions is not None:
        assert n_evictions == case.expect_evictions, (
            f"{case.name}: {n_evictions} evictions, expected "
            f"{case.expect_evictions} (ref {case.ref})")
    for gang, minp in case.expect_pipelined.items():
        got = int(pipe[rows[gang]].sum())
        assert got >= minp, (
            f"{case.name}: {gang} pipelined {got} < {minp} "
            f"(ref {case.ref})")
    for a, b in case.expect_disjoint:
        na, nb = set(placements_of(a)), set(placements_of(b))
        assert not (na & nb), (
            f"{case.name}: {a} and {b} share nodes {na & nb} "
            f"(ref {case.ref})")
    for a, b in case.expect_colocated:
        na, nb = set(placements_of(a)), set(placements_of(b))
        assert na & nb, (
            f"{case.name}: {a} on {na} and {b} on {nb} share nothing "
            f"(ref {case.ref})")
    return res

"""Topology + subgroup scenario catalog, traceable to the reference
suites ``allocateTopology_test.go`` and ``allocate_subgroups_test.go``
(case names quoted in each ``ref``).

Topology tree used throughout: 2 racks × 2 nodes (level label "rack").
"""
import pytest

from .harness import Case, G, N, Q, run_case


def _racked(gpu=4.0, racks=2, per=2):
    return [N(f"n{r}{i}", gpu=gpu, labels={"rack": f"r{r}"})
            for r in range(racks) for i in range(per)]


RACK0 = {"n00", "n01"}
RACK1 = {"n10", "n11"}

CASES = [
    Case(
        name="required_rack_confines_gang",
        ref='allocateTopology_test.go: "Required Topology - allocate '
            'whole PodGroup on a single Rack"',
        nodes=_racked(),
        topology_levels=["rack"],
        gangs=[G("job", tasks=8, gpu=1, topology=("rack", None))],
        expect={"job": True},
        expect_nodes={"job": RACK0 | RACK1},  # checked tighter below
    ),
    Case(
        name="required_rack_too_big_fails",
        ref='allocateTopology_test.go: "Required Topology - PodGroup '
            'larger than any domain stays pending"',
        nodes=_racked(),
        topology_levels=["rack"],
        gangs=[G("big", tasks=12, gpu=1, topology=("rack", None))],
        expect={"big": 0},
    ),
    Case(
        name="binpack_picks_fullest_domain",
        ref='allocateTopology_test.go: "Bin Packing - allocate on '
            'domain with least free resources (most occupied)"',
        nodes=_racked(),
        topology_levels=["rack"],
        gangs=[G("occupant", tasks=2, gpu=1, on=["n10", "n11"]),
               G("job", tasks=4, gpu=1, topology=("rack", None))],
        # rack1 (6 free) is fuller than rack0 (8 free): binpack there
        expect={"job": True},
        expect_nodes={"job": RACK1},
    ),
    Case(
        name="preferred_rack_keeps_gang_local",
        ref='allocateTopology_test.go: "Preferred Topology - allocate '
            'on closest domain"',
        nodes=_racked(),
        topology_levels=["rack"],
        gangs=[G("job", tasks=4, gpu=1, topology=(None, "rack"))],
        expect={"job": True},
    ),
    Case(
        name="two_required_gangs_two_racks",
        ref='allocateTopology_test.go: "Multiple PodGroups with '
            'Required Topology on distinct domains"',
        nodes=_racked(),
        topology_levels=["rack"],
        gangs=[G("a", tasks=6, gpu=1, topology=("rack", None)),
               G("b", tasks=6, gpu=1, topology=("rack", None))],
        expect={"a": True, "b": True},
        expect_disjoint=[("a", "b")],
    ),
    # ---- subgroups (allocate_subgroups_test.go) ------------------------
    Case(
        name="subgroups_quorum_both_sides",
        ref='allocate_subgroups_test.go: "Allocate job with SubGroups"',
        nodes=[N("n0", gpu=4)],
        gangs=[G("ps-wk", tasks=4, gpu=1, min_member=4,
                 subgroups=[("ps", 2), ("wk", 2)],
                 subgroup_of=["ps", "ps", "wk", "wk"])],
        expect={"ps-wk": True},
    ),
    Case(
        name="subgroup_quorum_unsatisfiable_fails_whole_gang",
        ref='allocate_subgroups_test.go: "Allocate job with SubGroups - '
            'cannot satisfy sub group gang"',
        nodes=[N("n0", gpu=3)],
        gangs=[G("ps-wk", tasks=4, gpu=1, min_member=4,
                 subgroups=[("ps", 2), ("wk", 2)],
                 subgroup_of=["ps", "ps", "wk", "wk"])],
        expect={"ps-wk": 0},
    ),
    Case(
        name="multiple_subgroup_jobs",
        ref='allocate_subgroups_test.go: "Allocate multiple jobs with '
            'SubGroups"',
        nodes=[N("n0", gpu=4), N("n1", gpu=4)],
        gangs=[G("j0", tasks=4, gpu=1, min_member=4,
                 subgroups=[("a", 2), ("b", 2)],
                 subgroup_of=["a", "a", "b", "b"]),
               G("j1", tasks=4, gpu=1, min_member=4,
                 subgroups=[("a", 2), ("b", 2)],
                 subgroup_of=["a", "a", "b", "b"])],
        expect={"j0": True, "j1": True},
    ),
    Case(
        name="unbalanced_subgroup_hierarchy",
        ref='allocate_subgroups_test.go: "Allocate job with SubGroups - '
            'unbalanced hierarchy structure"',
        nodes=[N("n0", gpu=6)],
        gangs=[G("uneven", tasks=6, gpu=1, min_member=6,
                 subgroups=[("ps", 1), ("wk", 5)],
                 subgroup_of=["ps", "wk", "wk", "wk", "wk", "wk"])],
        expect={"uneven": True},
    ),
]


@pytest.mark.parametrize("case", CASES, ids=[c.name for c in CASES])
def test_topology_scenarios(case):
    res = run_case(case)
    if case.name == "required_rack_confines_gang":
        # all placements inside ONE rack
        import numpy as np
        pl = np.asarray(res.tensors.placements)
        nodes = [n.name for n in case.nodes]
        used = {nodes[v][1] for v in pl[0][pl[0] >= 0]}
        assert len(used) == 1, used

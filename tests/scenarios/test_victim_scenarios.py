"""Victim-action scenario catalog — reclaim, preempt, consolidation and
stale-gang eviction, traceable to the reference integration suites
``actions/integration_tests/{reclaim,preempt,consolidation,
stalegangeviction}`` and the action unit tests (case names quoted in
each ``ref``).
"""
import numpy as np
import pytest

from kai_scheduler_tpu.apis import types as apis

from .harness import Case, G, N, Q, run_case

CASES = [
    # ---- reclaim --------------------------------------------------------
    Case(
        name="reclaim_over_quota_queue",
        ref='integration_tests/reclaim: "reclaim resources from an '
            'over-quota queue for an under-quota one"',
        nodes=[N("n0", gpu=2), N("n1", gpu=2)],
        queues=[Q("qa", quota=2), Q("qb", quota=2)],
        gangs=[G(f"b{i}", queue="qb", tasks=1, on=[f"n{i % 2}"])
               for i in range(4)]
        + [G("a0", queue="qa", tasks=2, gpu=1)],
        expect={"a0": True},
        expect_pipelined={"a0": 1},
    ),
    Case(
        name="reclaim_respects_fair_share",
        ref='integration_tests/reclaim: "no reclaim when the reclaimer '
            'is already at fair share"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("qa", quota=2), Q("qb", quota=2)],
        gangs=[G("a-run", queue="qa", tasks=2, on=["n0"]),
               G("b-run", queue="qb", tasks=2, on=["n0"]),
               G("a0", queue="qa", tasks=1, gpu=1)],
        # qa is at its 2-GPU share: nothing to reclaim from qb (also at
        # share)
        expect={"a0": 0},
        expect_evictions=0,
    ),
    Case(
        name="reclaim_minruntime_protects_victims",
        ref='integration_tests/reclaim: "reclaimMinRuntime protects '
            'young victims"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("qa", quota=1), Q("qb", quota=1,
                                    reclaim_min_runtime=7200.0)],
        gangs=[G("b-run", queue="qb", tasks=2, on=["n0"],
                 runtime_s=60.0),
               G("a0", queue="qa", tasks=1, gpu=1)],
        # victims ran 60s < 7200s protection: no eviction
        expect={"a0": 0},
        expect_evictions=0,
    ),
    Case(
        name="reclaim_elastic_sheds_surplus_first",
        ref='integration_tests/reclaim: "elastic victim shrinks to '
            'minMember before whole-gang eviction"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("qa", quota=2), Q("qb", quota=2)],
        gangs=[G("b-el", queue="qb", tasks=4, min_member=2, on=["n0"]),
               G("a0", queue="qa", tasks=2, gpu=1)],
        expect={"a0": True},
        expect_evictions=2,  # surplus pods only; quorum survives
    ),
    # ---- preempt --------------------------------------------------------
    Case(
        name="preempt_lower_priority_same_queue",
        ref='integration_tests/preempt: "higher priority preempts lower '
            'within the queue"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("lo", queue="q0", tasks=2, priority=0, on=["n0"]),
               G("hi", queue="q0", tasks=2, gpu=1, priority=10)],
        expect={"hi": True},
        expect_evictions=2,
        expect_pipelined={"hi": 1},
    ),
    Case(
        name="preempt_never_equal_priority",
        ref='integration_tests/preempt: "no preemption among equal '
            'priorities"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("r0", queue="q0", tasks=2, priority=5, on=["n0"]),
               G("p0", queue="q0", tasks=2, gpu=1, priority=5)],
        expect={"p0": 0},
        expect_evictions=0,
    ),
    Case(
        name="preempt_non_preemptible_victim_safe",
        ref='integration_tests/preempt: "non-preemptible victims are '
            'never evicted"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("guard", queue="q0", tasks=2, priority=0, on=["n0"],
                 preemptible=False),
               G("hi", queue="q0", tasks=2, gpu=1, priority=10)],
        expect={"hi": 0},
        expect_evictions=0,
    ),
    Case(
        name="preempt_minruntime_protects",
        ref='integration_tests/preempt: "preemptMinRuntime protects '
            'young victims"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2, preempt_min_runtime=7200.0)],
        gangs=[G("lo", queue="q0", tasks=2, priority=0, on=["n0"],
                 runtime_s=60.0),
               G("hi", queue="q0", tasks=2, gpu=1, priority=10)],
        expect={"hi": 0},
        expect_evictions=0,
    ),
    # ---- consolidation --------------------------------------------------
    Case(
        name="consolidation_defragments_for_gang",
        ref='integration_tests/consolidation: "move running pods to '
            'open a contiguous block"',
        # two nodes each half-full; a 2-GPU single-node gang needs one
        # node emptied — move one runner across
        nodes=[N("n0", gpu=2), N("n1", gpu=2)],
        queues=[Q("q0", quota=4)],
        gangs=[G("r0", queue="q0", tasks=1, on=["n0"]),
               G("r1", queue="q0", tasks=1, on=["n1"]),
               G("want2", queue="q0", tasks=2, gpu=1,
                 subgroups=[], topology=None)],
        # placement may land with moves or without (if it fits spread);
        # with 1 GPU free per node the 2-task gang fits spread — expect
        # plain allocation, no consolidation needed
        expect={"want2": True},
        expect_evictions=0,
    ),
    Case(
        name="consolidation_moves_victim_with_rebind",
        ref='integration_tests/consolidation: "consolidated victim gets '
            'a pipelined rebind"',
        # gang needs BOTH GPUs of one node: runners at 1 GPU on each
        # node must consolidate onto one node
        nodes=[N("n0", gpu=2, labels={"rack": "r0"}),
               N("n1", gpu=2, labels={"rack": "r1"})],
        topology_levels=["rack"],
        queues=[Q("q0", quota=4)],
        gangs=[G("r0", queue="q0", tasks=1, on=["n0"]),
               G("r1", queue="q0", tasks=1, on=["n1"]),
               G("want2", queue="q0", tasks=2, gpu=1,
                 topology=("rack", None))],
        expect={"want2": True},
    ),
    # ---- stale gang eviction -------------------------------------------
    Case(
        name="stale_gang_below_quorum_evicted",
        ref='integration_tests/stalegangeviction: "gang below minMember '
            'past grace is evicted"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=4)],
        gangs=[G("stale", queue="q0", tasks=2, min_member=4, on=["n0"])],
        expect_evictions=2,
    ),
    Case(
        name="healthy_gang_not_stale",
        ref='integration_tests/stalegangeviction: "whole gang keeps '
            'running"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=4)],
        gangs=[G("ok", queue="q0", tasks=4, min_member=4, on=["n0"])],
        expect_evictions=0,
    ),
]


def _prepare(case):
    if case.name == "stale_gang_below_quorum_evicted":
        # the grace window starts when the controller stamps stale_since;
        # backdate it past the default 60s grace
        def patch(cluster):
            for grp in cluster.pod_groups.values():
                grp.stale_since = -120.0
        return patch
    return None


@pytest.mark.parametrize("case", CASES, ids=[c.name for c in CASES])
def test_victim_scenarios(case):
    patch = _prepare(case)
    if patch is None:
        run_case(case)
    else:
        from .harness import Scheduler, _build
        cluster = _build(case)
        patch(cluster)
        res = Scheduler().run_once(cluster)
        assert len(res.evictions) == case.expect_evictions, res.evictions

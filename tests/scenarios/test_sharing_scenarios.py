"""GPU-sharing scenario catalog — fractional portions, memory-based
shares, and MIG extended resources, traceable to the reference suites
``allocateFractionalGpu_test.go``, ``allocateGpuMemory_test.go`` and
``allocateMIG_test.go`` (case names quoted in each ``ref``).
"""
import pytest

from .harness import Case, G, N, Q, run_case

MIG_1G = "nvidia.com/mig-1g.5gb"

CASES = [
    # ---- fractional portions (allocateFractionalGpu_test.go) -----------
    Case(
        name="two_halves_share_one_device",
        ref='allocateFractionalGpu_test.go: "Allocate 2 pods to use '
            'shared GPU"',
        nodes=[N("n0", gpu=1)],
        gangs=[G("f0", tasks=1, gpu=0, portion=0.5),
               G("f1", tasks=1, gpu=0, portion=0.5)],
        expect={"f0": True, "f1": True},
        expect_nodes={"f0": {"n0"}, "f1": {"n0"}},
    ),
    Case(
        name="fraction_and_whole_coexist",
        ref='allocateFractionalGpu_test.go: "Fraction job and whole-GPU '
            'job on one node"',
        nodes=[N("n0", gpu=2)],
        gangs=[G("frac", tasks=1, gpu=0, portion=0.5),
               G("whole", tasks=1, gpu=1)],
        expect={"frac": True, "whole": True},
    ),
    Case(
        name="oversized_fraction_fails",
        ref='allocateFractionalGpu_test.go: "Fill GPU up - fail '
            'allocating 0.6 GPU twice"',
        nodes=[N("n0", gpu=1)],
        gangs=[G("f0", tasks=1, gpu=0, portion=0.6),
               G("f1", tasks=1, gpu=0, portion=0.6)],
        expect={"f0": True, "f1": 0},
    ),
    Case(
        name="three_fractions_two_devices",
        ref='allocateFractionalGpu_test.go: "Allocate 3 fractions over '
            '2 GPUs"',
        nodes=[N("n0", gpu=2)],
        gangs=[G("f0", tasks=1, gpu=0, portion=0.5),
               G("f1", tasks=1, gpu=0, portion=0.5),
               G("f2", tasks=1, gpu=0, portion=0.5)],
        expect={"f0": True, "f1": True, "f2": True},
    ),
    Case(
        name="fraction_joins_running_sharer",
        ref='allocateFractionalGpu_test.go: "Add a fraction to a used '
            'shared GPU"',
        nodes=[N("n0", gpu=1), N("n1", gpu=1)],
        gangs=[G("run", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 devices=[0]),
               G("new", tasks=1, gpu=0, portion=0.5)],
        # gpusharingorder: the new fraction prefers the node whose
        # device already holds a sharer
        expect={"new": True},
        expect_nodes={"new": {"n0"}},
    ),
    Case(
        name="whole_gpu_needs_fully_free_device",
        ref='allocateFractionalGpu_test.go: "Whole GPU job blocked by '
            'fraction"',
        nodes=[N("n0", gpu=1)],
        gangs=[G("run", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 devices=[0]),
               G("whole", tasks=1, gpu=1)],
        expect={"whole": 0},
    ),
    # ---- memory-based shares (allocateGpuMemory_test.go) ---------------
    Case(
        name="memory_request_shares_device",
        ref='allocateGpuMemory_test.go: "Pending job requests gpu '
            'memory"',
        nodes=[N("n0", gpu=1, gpu_mem_gib=16.0)],
        gangs=[G("m0", tasks=1, gpu=0, gpu_mem=8.0),
               G("m1", tasks=1, gpu=0, gpu_mem=8.0)],
        expect={"m0": True, "m1": True},
        expect_nodes={"m0": {"n0"}, "m1": {"n0"}},
    ),
    Case(
        name="memory_over_device_capacity_fails",
        ref='allocateGpuMemory_test.go: "Pending job requests GPU '
            'memory, memory resource cannot be allocated"',
        nodes=[N("n0", gpu=1, gpu_mem_gib=16.0)],
        gangs=[G("m0", tasks=1, gpu=0, gpu_mem=12.0),
               G("m1", tasks=1, gpu=0, gpu_mem=12.0)],
        expect={"m0": True, "m1": 0},
    ),
    Case(
        name="memory_is_node_relative",
        ref='allocateGpuMemory_test.go: "GPU memory across node device '
            'sizes"',
        # 12 GiB share: fits the 16-GiB device, NOT the 8-GiB one
        nodes=[N("small", gpu=1, gpu_mem_gib=8.0),
               N("big", gpu=1, gpu_mem_gib=16.0)],
        gangs=[G("m0", tasks=1, gpu=0, gpu_mem=12.0)],
        expect={"m0": True},
        expect_nodes={"m0": {"big"}},
    ),
    # ---- MIG extended resources (allocateMIG_test.go) ------------------
    Case(
        name="mig_profile_capacity",
        ref='allocateMIG_test.go: "MIG job requesting MIG device"',
        nodes=[N("n0", gpu=8, mig={MIG_1G: 2})],
        gangs=[G("mig0", tasks=1, gpu=0, mig={MIG_1G: 1}),
               G("mig1", tasks=1, gpu=0, mig={MIG_1G: 1}),
               G("mig2", tasks=1, gpu=0, mig={MIG_1G: 1})],
        expect={"mig0": True, "mig1": True, "mig2": 0},
    ),
    Case(
        name="mig_node_selection",
        ref='allocateMIG_test.go: "Pending MIG job with node without '
            'MIG resources"',
        nodes=[N("plain", gpu=8), N("migged", gpu=8, mig={MIG_1G: 1})],
        gangs=[G("mig0", tasks=1, gpu=0, mig={MIG_1G: 1})],
        expect={"mig0": True},
        expect_nodes={"mig0": {"migged"}},
    ),
    Case(
        name="running_mig_slices_held",
        ref='allocateMIG_test.go: "MIG job requesting MIG device on '
            'node with running MIG jobs"',
        nodes=[N("n0", gpu=8, mig={MIG_1G: 2})],
        gangs=[G("run", tasks=2, gpu=0, mig={MIG_1G: 1}, on=["n0"]),
               G("mig0", tasks=1, gpu=0, mig={MIG_1G: 1})],
        expect={"mig0": 0},
    ),
    Case(
        name="mixed_mig_and_whole_gpu",
        ref='allocateMIG_test.go: "MIG job with multiple tasks '
            'requesting MIG device"',
        nodes=[N("n0", gpu=2, mig={MIG_1G: 4})],
        gangs=[G("mig", tasks=3, gpu=0, mig={MIG_1G: 1}),
               G("whole", tasks=2, gpu=1)],
        expect={"mig": True, "whole": True},
    ),
]


@pytest.mark.parametrize("case", CASES, ids=[c.name for c in CASES])
def test_sharing_scenarios(case):
    run_case(case)

"""Deletion / combined-action scenario catalog — the analogues of the
reference suites the round-4 review called out as uncovered:

- ``actions/integration_tests/deletion_tests/deletion_test.go`` —
  releasing fractional pods and the whole-device node accounting
  (``ExpectedNodesResources``: a shared device is IDLE only when fully
  free, RELEASING only when every holder is releasing).
- ``actions/integration_tests/consolidation_and_reclaim/
  consolidation_and_reclaim_test.go`` — consolidation moves and reclaim
  composing in one cycle.
- ``actions/integration_tests/preempt/preemptMIG_test.go`` and
  ``preemptFractional_test.go`` — priority preemption over MIG
  instances and fractional/memory-based shares.
- ``actions/integration_tests/allocate/allocateFractionalGpu_test.go``
  — gpu-memory requests and the gpuSharingOrder packing band.
"""
import pytest

from .harness import Case, G, N, Q, run_case

MIG_1G = "nvidia.com/mig-1g.10gb"

CASES = [
    # ---- deletion_tests (releasing fractional accounting) --------------
    Case(
        name="delete_one_fractional_job",
        ref='deletion_test.go: "delete 1 fractional job from node"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2, limit=2)],
        gangs=[G("rel0", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 releasing=True, devices=[1])],
        expect_node_idle={"n0": 1.0},
        expect_node_releasing={"n0": 1.0},
    ),
    Case(
        name="delete_two_fractional_jobs_same_gpu",
        ref='deletion_test.go: "delete 2 fractional jobs from same GPU"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2, limit=2)],
        gangs=[G("rel0", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 releasing=True, devices=[1]),
               G("rel1", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 releasing=True, devices=[1])],
        expect_node_idle={"n0": 1.0},
        expect_node_releasing={"n0": 1.0},
    ),
    Case(
        name="delete_two_fractional_jobs_different_gpus",
        ref='deletion_test.go: "delete 2 fractional jobs from '
            'different GPUs"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2, limit=2)],
        gangs=[G("rel0", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 releasing=True, devices=[0]),
               G("rel1", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 releasing=True, devices=[1])],
        expect_node_idle={"n0": 0.0},
        expect_node_releasing={"n0": 2.0},
    ),
    Case(
        name="delete_fractional_beside_running_fraction",
        ref='deletion_test.go: "delete 1 fractional job from same GPU '
            'as a different running fractional job"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2, limit=2)],
        gangs=[G("rel0", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 releasing=True, devices=[1]),
               G("run0", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 devices=[1])],
        # the shared device still has a live holder: not releasing, and
        # its free remainder is not node-idle either
        expect_node_idle={"n0": 1.0},
        expect_node_releasing={"n0": 0.0},
    ),
    # ---- consolidation + reclaim in one cycle ---------------------------
    Case(
        name="consolidate_then_reclaim_frees_a_node",
        ref='consolidation_and_reclaim_test.go: "4 jobs of queue0 - 3 '
            'running 1 pending will consolidate, 1 pending job from '
            'queue1 - reclaim"',
        nodes=[N("n0", gpu=4), N("n1", gpu=4)],
        queues=[Q("queue0", quota=4), Q("queue1", quota=4)],
        gangs=[G("run0", queue="queue0", tasks=1, gpu=2, on=["node0"
                 if False else "n0"]),
               G("run1", queue="queue0", tasks=1, gpu=2, on=["n1"]),
               G("run2", queue="queue0", tasks=1, gpu=1, on=["n1"]),
               G("pend0", queue="queue0", tasks=1, gpu=3),
               G("pend1", queue="queue1", tasks=1, gpu=4)],
        # queue1 is owed 4 but no single action suffices: consolidation
        # and reclaim must compose across cycles (the reference's
        # RoundsUntilMatch).  KNOWN DIVERGENCE from the reference
        # trajectory: upstream reclaim may victimize a job ALLOCATED in
        # the same session (pod_status Allocated is alive), while the
        # tensor kernels' victim candidates are snapshot-frozen — a
        # same-cycle consolidation placement is invisible to reclaim
        # until next cycle, so convergence can cost extra (never
        # invalid) evictions.  The catalog asserts the converged
        # outcome: queue1's 4-GPU job lands whole on one node.
        expect={"pend1": True},
        rounds=3,
    ),
    # ---- preempt over MIG instances (preemptMIG_test.go) ----------------
    Case(
        name="mig_build_preempts_train",
        ref='preemptMIG_test.go: "Build preempts train"',
        nodes=[N("n0", gpu=8, mig={MIG_1G: 1})],
        queues=[Q("queue0", quota=8)],
        gangs=[G("train", queue="queue0", tasks=1, gpu=0,
                 mig={MIG_1G: 1}, on=["n0"], priority=50),
               G("build", queue="queue0", tasks=1, gpu=0,
                 mig={MIG_1G: 1}, priority=100)],
        # the single MIG instance is held by the lower-priority train
        # job: build preempts it and takes the instance
        expect={"build": True},
        expect_evictions=1,
        expect_pipelined={"build": 1},
    ),
    Case(
        name="mig_equal_priority_no_preempt",
        ref='preemptMIG_test.go (inverse guard): equal priorities do '
            'not preempt',
        nodes=[N("n0", gpu=8, mig={MIG_1G: 1})],
        queues=[Q("queue0", quota=8)],
        gangs=[G("train", queue="queue0", tasks=1, gpu=0,
                 mig={MIG_1G: 1}, on=["n0"], priority=50),
               G("train2", queue="queue0", tasks=1, gpu=0,
                 mig={MIG_1G: 1}, priority=50)],
        expect={"train2": 0},
        expect_evictions=0,
    ),
    Case(
        name="mig_capacity_no_preempt_needed",
        ref='preemptMIG_test.go: preemption only when the instance '
            'pool is exhausted',
        nodes=[N("n0", gpu=8, mig={MIG_1G: 2})],
        queues=[Q("queue0", quota=8)],
        gangs=[G("train", queue="queue0", tasks=1, gpu=0,
                 mig={MIG_1G: 1}, on=["n0"], priority=50),
               G("build", queue="queue0", tasks=1, gpu=0,
                 mig={MIG_1G: 1}, priority=100)],
        # a second instance is free: allocate, not preempt
        expect={"build": True},
        expect_evictions=0,
    ),
    # ---- preempt over fractions (preemptFractional_test.go) -------------
    Case(
        name="frac_memory_build_preempts_train",
        ref='preemptFractional_test.go: "Preempt fractional train by '
            'fractional interactive GPU memory request job"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("queue0", quota=2)],
        gangs=[G("whole", queue="queue0", tasks=1, gpu=1, on=["n0"],
                 priority=50),
               G("frac-train", queue="queue0", tasks=1, gpu=0,
                 gpu_mem=50, on=["n0"], devices=[1], priority=50),
               G("build", queue="queue0", tasks=1, gpu=0, gpu_mem=60,
                 priority=100)],
        # 60 GiB fits no device beside the 50 GiB holder: the
        # lower-priority fractional train is evicted, build lands on
        # its freed device
        expect={"build": True},
        expect_evictions=1,
        expect_nodes={"build": {"n0"}},
    ),
    Case(
        name="frac_whole_gpu_preempts_fraction",
        ref='preemptFractional_test.go: "Preempt fractional train by '
            'whole GPU job"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("queue0", quota=2)],
        gangs=[G("whole", queue="queue0", tasks=1, gpu=1, on=["n0"],
                 priority=50),
               G("frac-train", queue="queue0", tasks=1, gpu=0,
                 portion=0.5, on=["n0"], devices=[1], priority=50),
               G("build", queue="queue0", tasks=1, gpu=1,
                 priority=100)],
        expect={"build": True},
        expect_evictions=1,
        expect_nodes={"build": {"n0"}},
    ),
    Case(
        name="frac_fraction_preempts_fraction",
        ref='preemptFractional_test.go: "Preempt fractional train by '
            'fractional interactive GPU job"',
        nodes=[N("n0", gpu=1, gpu_mem_gib=100)],
        queues=[Q("queue0", quota=1)],
        gangs=[G("frac-train", queue="queue0", tasks=1, gpu=0,
                 portion=0.6, on=["n0"], devices=[0], priority=50),
               G("build", queue="queue0", tasks=1, gpu=0, portion=0.6,
                 priority=100)],
        # 0.6 + 0.6 never share a device: the train fraction is evicted
        expect={"build": True},
        expect_evictions=1,
    ),
    # ---- gpu-memory / sharing-order allocate ----------------------------
    Case(
        name="gpu_memory_basic_request_empty_cluster",
        ref='allocateFractionalGpu_test.go: "Basic request gpu by '
            'memory when cluster is empty"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("q0", quota=2)],
        gangs=[G("j0", tasks=1, gpu=0, gpu_mem=50)],
        expect={"j0": True},
        expect_nodes={"j0": {"n0"}},
    ),
    Case(
        name="gpu_memory_overflow_takes_new_device",
        ref='allocateFractionalGpu_test.go: "1 shared gpu job running, '
            '1 pending interactive shared gpu job - allocate to new gpu"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("q0", quota=2)],
        gangs=[G("run0", tasks=1, gpu=0, gpu_mem=50, on=["n0"],
                 devices=[0]),
               G("j0", tasks=1, gpu=0, gpu_mem=60)],
        # 60 GiB does not fit beside the 50 GiB holder: second device
        expect={"j0": True},
        expect_nodes={"j0": {"n0"}},
    ),
    Case(
        name="whole_gpu_running_fraction_allocates",
        ref='allocateFractionalGpu_test.go: "1 whole gpu job running, '
            '1 pending interactive shared gpu job - allocate"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("q0", quota=2)],
        gangs=[G("whole", tasks=1, gpu=1, on=["n0"]),
               G("j0", tasks=1, gpu=0, portion=0.5)],
        expect={"j0": True},
        expect_nodes={"j0": {"n0"}},
    ),
    Case(
        name="fractions_fill_to_capacity_elastically",
        ref='allocateFractionalGpu_test.go: "1 interactive shared gpu '
            'job running, 4 pending interactive shared gpus pending - '
            'allocate 3 of the shared GPUs jobs"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("q0", quota=2)],
        gangs=[G("run0", tasks=1, gpu=0, portion=0.5, on=["n0"],
                 devices=[0])]
        + [G(f"j{i}", tasks=1, gpu=0, portion=0.5) for i in range(4)],
        # 2 devices x 1.0 share, 0.5 held: exactly 3 more 0.5 fractions
        # fit
        expect_evictions=0,
    ),
    Case(
        name="sharing_order_packs_onto_shared_node",
        ref='allocateFractionalGpu_test.go: "test gpuSharingOrder - one '
            'node empty and one node with already running frac job - '
            'allocate to the node with already running job"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100),
               N("n1", gpu=2, gpu_mem_gib=100)],
        queues=[Q("q0", quota=4)],
        gangs=[G("run0", tasks=1, gpu=0, portion=0.5, on=["n1"],
                 devices=[0]),
               G("j0", tasks=1, gpu=0, portion=0.4)],
        # gpusharingorder prefers topping up the already-shared device
        expect={"j0": True},
        expect_nodes={"j0": {"n1"}},
    ),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.name)
def test_deletion_mixed_scenario(case):
    run_case(case)


def test_fractions_fill_count():
    """Companion assertion for ``fractions_fill_to_capacity_elastically``
    — exactly 3 of the 4 identical pending fractions place."""
    case = next(c for c in CASES
                if c.name == "fractions_fill_to_capacity_elastically")
    res = run_case(case)
    assert len(res.bind_requests) == 3, [
        b.pod_name for b in res.bind_requests]

"""Allocate-action scenario catalog — core fairness, gang
all-or-nothing, and elastic cases, traceable to the reference suites
``actions/allocate/allocate_test.go``, ``allocateGang_test.go`` and
``allocateElastic_test.go`` (case names quoted in each ``ref``).
"""
import pytest

from .harness import Case, G, N, Q, run_case

CASES = [
    # ---- core allocate (allocate_test.go) ------------------------------
    Case(
        name="single_job_on_single_node",
        ref='allocate_test.go: "One pending job"',
        nodes=[N("n0", gpu=4)],
        gangs=[G("j0", tasks=1, gpu=1)],
        expect={"j0": True},
    ),
    Case(
        name="two_jobs_fill_one_node",
        ref='allocate_test.go: "Two pending jobs fit one node"',
        nodes=[N("n0", gpu=2)],
        gangs=[G("j0", tasks=1), G("j1", tasks=1)],
        expect={"j0": True, "j1": True},
        expect_nodes={"j0": {"n0"}, "j1": {"n0"}},
    ),
    Case(
        name="insufficient_capacity_leaves_pending",
        ref='allocate_test.go: "Non-allocatable job stays pending"',
        nodes=[N("n0", gpu=1)],
        gangs=[G("big", tasks=1, gpu=2)],
        expect={"big": 0},
    ),
    Case(
        name="queue_shares_split_between_queues",
        ref='allocate_test.go: "1 job running on node0 from queue0, 3 '
            'pending jobs from queue1 and 1 pending job from queue0 - '
            'allocate them according to their the queue shares"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=2), Q("q1", quota=2)],
        gangs=[
            G("run0", queue="q0", tasks=1, on=["n0"]),
            G("p0", queue="q0", tasks=1),
            G("p1", queue="q1", tasks=1),
            G("p2", queue="q1", tasks=1),
            G("p3", queue="q1", tasks=1),
        ],
        # q0 holds 1 running + 1 pending = its 2-GPU share; q1 gets 2 of
        # its 3 pending in (deserved 2), the third waits
        expect={"p0": True, "p1": True, "p2": True, "p3": 0},
    ),
    Case(
        name="over_quota_queue_blocked",
        ref='allocate_test.go: "Attempt to allocate job over queue '
            'deserved quota"',
        nodes=[N("n0", gpu=8)],
        queues=[Q("q0", quota=1, limit=1)],
        gangs=[G("j0", queue="q0", tasks=1),
               G("j1", queue="q0", tasks=1)],
        expect={"j0": True, "j1": 0},
    ),
    Case(
        name="higher_priority_job_first",
        ref='allocate_test.go: "Allocate 1 job over quota after '
            'priority job"',
        nodes=[N("n0", gpu=1)],
        gangs=[G("lo", tasks=1, priority=0),
               G("hi", tasks=1, priority=10)],
        expect={"hi": True, "lo": 0},
    ),
    Case(
        name="cpu_only_job_lands_on_cpu_capacity",
        ref='allocate_test.go: "CPU only job"',
        nodes=[N("n0", gpu=0, cpu=8)],
        gangs=[G("cpu", tasks=2, gpu=0, cpu=2)],
        expect={"cpu": True},
    ),
    Case(
        name="queue_limit_caps_allocation",
        ref='allocate_test.go: "maxAllowed caps a queue below capacity"',
        nodes=[N("n0", gpu=8)],
        queues=[Q("q0", quota=2, limit=3)],
        gangs=[G(f"j{i}", queue="q0", tasks=1) for i in range(5)],
        # 3 of 5 single-GPU jobs land (limit 3), 2 wait
        expect={"j3": 0, "j4": 0},
    ),
    Case(
        name="two_queues_one_starved_gets_nothing_extra",
        ref='allocate_test.go: "Allocate jobs according to queue '
            'fair-share (DRF)"',
        nodes=[N("n0", gpu=4), N("n1", gpu=4)],
        queues=[Q("qa", quota=4), Q("qb", quota=4)],
        gangs=[G("a0", queue="qa", tasks=4, gpu=1),
               G("b0", queue="qb", tasks=4, gpu=1),
               G("a1", queue="qa", tasks=4, gpu=1)],
        expect={"a0": True, "b0": True, "a1": 0},
    ),
    # ---- gang all-or-nothing (allocateGang_test.go) --------------------
    Case(
        name="gang_whole_on_one_node",
        ref='allocateGang_test.go: "Allocate train gang job"',
        nodes=[N("n0", gpu=4)],
        gangs=[G("train", tasks=4, gpu=1)],
        expect={"train": True},
        expect_nodes={"train": {"n0"}},
    ),
    Case(
        name="gang_spans_two_nodes",
        ref='allocateGang_test.go: "Allocate build gang job on 2 nodes"',
        nodes=[N("n0", gpu=2), N("n1", gpu=2)],
        gangs=[G("build", tasks=4, gpu=1)],
        expect={"build": True},
    ),
    Case(
        name="gang_not_fully_placeable_places_nothing",
        ref='allocateGang_test.go: "Don\'t allocate gang job if not all '
            'tasks are allocatable"',
        nodes=[N("n0", gpu=3)],
        gangs=[G("gang", tasks=4, gpu=1)],
        expect={"gang": 0},
        expect_evictions=0,
    ),
    Case(
        name="gang_over_quota_places_nothing",
        ref='allocateGang_test.go: "Don\'t allocate gang interactive '
            'job if it will go over quota"',
        nodes=[N("n0", gpu=8)],
        queues=[Q("q0", quota=2, limit=2)],
        gangs=[G("gang", queue="q0", tasks=4, gpu=1)],
        expect={"gang": 0},
    ),
    Case(
        name="gang_min_member_partial_quorum",
        ref='allocateGang_test.go: "Allocate gang job with minmember '
            'smaller than replicas"',
        nodes=[N("n0", gpu=2)],
        gangs=[G("gang", tasks=4, gpu=1, min_member=2)],
        # quorum of 2 fits; elastic re-push cannot place more (capacity)
        expect={"gang": 2},
    ),
    Case(
        name="two_gangs_compete_first_wins_whole",
        ref='allocateGang_test.go: "Two gang jobs compete on capacity"',
        nodes=[N("n0", gpu=4)],
        gangs=[G("g0", tasks=4, gpu=1, priority=5),
               G("g1", tasks=4, gpu=1, priority=0)],
        expect={"g0": True, "g1": 0},
    ),
    # ---- elastic (allocateElastic_test.go) -----------------------------
    Case(
        name="elastic_grows_beyond_min_member",
        ref='allocateElastic_test.go: "Allocate elastic job - full '
            'allocate"',
        nodes=[N("n0", gpu=4)],
        gangs=[G("el", tasks=4, gpu=1, min_member=1)],
        expect={"el": True},
    ),
    Case(
        name="elastic_partial_to_capacity",
        ref='allocateElastic_test.go: "Allocate elastic job - partial '
            'allocate"',
        nodes=[N("n0", gpu=2)],
        gangs=[G("el", tasks=4, gpu=1, min_member=1)],
        expect={"el": 2},
    ),
    Case(
        name="two_elastic_jobs_share_fairly",
        ref='allocateElastic_test.go: "Allocate 2 elastic jobs - both '
            'partial allocate"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("qa", quota=2), Q("qb", quota=2)],
        gangs=[G("ea", queue="qa", tasks=4, gpu=1, min_member=1),
               G("eb", queue="qb", tasks=4, gpu=1, min_member=1)],
        expect={"ea": 2, "eb": 2},
    ),
    Case(
        name="elastic_below_min_goes_first",
        ref='allocateElastic_test.go: "Elastic job below minMember '
            'schedules before scale-ups"',
        nodes=[N("n0", gpu=2)],
        gangs=[
            # running elastic job already at min — its scale-up loses to
            # the below-min pending gang
            G("grown", tasks=2, gpu=1, min_member=1, on=["n0"]),
            G("fresh", tasks=2, gpu=1, min_member=2)],
        expect={"fresh": 0},  # 2 free? no: grown holds 2 of 2 -> fresh 0
        expect_evictions=0,
    ),
    Case(
        name="elastic_scale_up_when_capacity_remains",
        ref='allocateElastic_test.go: "Allocate elastic job - some pods '
            'already running"',
        nodes=[N("n0", gpu=4)],
        gangs=[G("el", tasks=4, gpu=1, min_member=1, on=["n0"])],
        # 1 running (on= round-robins ALL tasks as running) — instead
        # model: 4 tasks, first running, rest pending is not expressible
        # via on=; keep whole-running and expect no change
        expect_evictions=0,
    ),
]


@pytest.mark.parametrize("case", CASES, ids=[c.name for c in CASES])
def test_allocate_scenarios(case):
    run_case(case)

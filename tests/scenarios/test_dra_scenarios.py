"""DRA (dynamic resource allocation) scenario catalog — the analogue of
the reference's 14-case DRA allocate suite
(``actions/integration_tests/allocate/allocate_dra_test.go``, case names
quoted in each ``ref``) plus the draPlugin preFilter rules
(``plugins/dynamicresources/dynamicresources.go:126-195``): claim
consumer caps (``ResourceClaimReservedForMaxSize``) and the shared-claim
queue-label validation.
"""
import pytest

from kai_scheduler_tpu.apis import types as apis

from .harness import Case, G, N, Q, run_case

QL = apis.QUEUE_LABEL
MAX = apis.RESERVED_FOR_MAX


def shared_claim(name, queue=None, count=1, reserved=0, labels=None,
                 device_class="gpu"):
    lab = dict(labels or {})
    if queue is not None:
        lab[QL] = queue
    return apis.ResourceClaim(
        name=name, device_class=device_class, count=count,
        from_template=False, reserved_for=reserved, labels=lab)


def template_claim(name, count=1, device_class="gpu"):
    return apis.ResourceClaim(name=name, device_class=device_class,
                              count=count, from_template=True)


GPU_CLASS = apis.DeviceClass(name="gpu")

CASES = [
    Case(
        name="dra_no_claim_schedules_normally",
        ref='allocate_dra_test.go: "Simple pod with no resource claim"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=1)],
        expect={"j0": True},
        expect_nodes={"j0": {"n0"}},
    ),
    Case(
        name="dra_shared_claim_correct_queue_label",
        ref='allocate_dra_test.go: "Simple pod with simple resource '
            'claim with correct queue label"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="q0")],
        device_classes=[GPU_CLASS],
        expect={"j0": True},
        expect_nodes={"j0": {"n0"}},
    ),
    Case(
        name="dra_claim_requests_too_many_devices",
        ref='allocate_dra_test.go: "Simple pod with simple resource '
            'claim - requesting too many devices"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=8)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="q0", count=2)],
        device_classes=[GPU_CLASS],
        # 2 devices claimed, the only node has 1: never schedulable
        expect={"j0": 0},
    ),
    Case(
        name="dra_node_bound_devices_force_separate_nodes",
        ref='allocate_dra_test.go: "2 pods requesting node-bound '
            'device, can\'t schedule on same node"',
        nodes=[N("n0", gpu=1), N("n1", gpu=1)],
        queues=[Q("q0", quota=2)],
        gangs=[G("ja", tasks=1, gpu=0, claims=["ca"]),
               G("jb", tasks=1, gpu=0, claims=["cb"])],
        resource_claims=[shared_claim("ca", queue="q0"),
                         shared_claim("cb", queue="q0")],
        device_classes=[GPU_CLASS],
        expect={"ja": True, "jb": True},
        expect_disjoint=[("ja", "jb")],
    ),
    Case(
        name="dra_two_claims_two_nodes",
        ref='allocate_dra_test.go: "2 simple pods with simple resource '
            'claims, allocating on separate nodes"',
        nodes=[N("n0", gpu=1), N("n1", gpu=1)],
        queues=[Q("q0", quota=2)],
        gangs=[G("j0", tasks=2, gpu=0, min_member=2,
                 claims_of=[["c0"], ["c1"]])],
        resource_claims=[shared_claim("c0", queue="q0"),
                         shared_claim("c1", queue="q0")],
        device_classes=[GPU_CLASS],
        expect={"j0": True},
        expect_nodes={"j0": {"n0", "n1"}},
    ),
    Case(
        name="dra_exactly_at_max_consumers",
        ref='allocate_dra_test.go: "Exactly at claim max consumers '
            'limit"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="q0",
                                      reserved=MAX - 1)],
        device_classes=[GPU_CLASS],
        expect={"j0": True},
        expect_nodes={"j0": {"n0"}},
    ),
    Case(
        name="dra_partially_over_max_consumers",
        ref='allocate_dra_test.go: "Partially over claim max consumers '
            'limit"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("j0", tasks=2, gpu=0, min_member=2, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="q0",
                                      reserved=MAX - 1)],
        device_classes=[GPU_CLASS],
        # the first referent takes the claim's last consumer slot, the
        # second is rejected at the cap — the all-or-nothing gang stays
        # whole and pending (upstream: the second pod's preFilter fails)
        expect={"j0": 0},
    ),
    Case(
        name="dra_already_at_max_consumers",
        ref='allocate_dra_test.go: "Claim already reached max '
            'consumers limit"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="q0", reserved=MAX)],
        device_classes=[GPU_CLASS],
        expect={"j0": 0},
    ),
    Case(
        name="dra_shared_claim_missing_queue_label",
        ref='allocate_dra_test.go: "Shared claim with no queue label - '
            'blocked from scheduling"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0")],  # no queue label
        device_classes=[GPU_CLASS],
        expect={"j0": 0},
    ),
    Case(
        name="dra_shared_claim_wrong_queue_label",
        ref='allocate_dra_test.go: "Shared claim with wrong queue '
            'label - blocked from scheduling"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="other-queue")],
        device_classes=[GPU_CLASS],
        expect={"j0": 0},
    ),
    Case(
        name="dra_template_claim_exempt_from_queue_label",
        ref='dynamicresources.go validateSharedGpuClaimQueueLabel: '
            '"Template claims are created per-pod and don\'t need '
            'queue validation"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[template_claim("c0")],
        device_classes=[GPU_CLASS],
        expect={"j0": True},
    ),
    Case(
        name="dra_claim_over_quota_nonpreemptible",
        ref='allocate_dra_test.go: "pod with simple resource claim - '
            'requests over quota as non-preemptable"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=2, gpu=0, min_member=2, preemptible=False,
                 claims_of=[["ca"], ["cb"]])],
        resource_claims=[shared_claim("ca", queue="q0"),
                         shared_claim("cb", queue="q0")],
        device_classes=[GPU_CLASS],
        # 2 claimed devices > 1 deserved: a non-preemptible job may not
        # exceed quota
        expect={"j0": 0},
    ),
    Case(
        name="dra_claim_over_limit",
        ref='allocate_dra_test.go: "pod with simple resource claim - '
            'requests over limit"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2, limit=1)],
        gangs=[G("j0", tasks=2, gpu=0, min_member=2,
                 claims_of=[["ca"], ["cb"]])],
        resource_claims=[shared_claim("ca", queue="q0"),
                         shared_claim("cb", queue="q0")],
        device_classes=[GPU_CLASS],
        expect={"j0": 0},
    ),
    Case(
        name="dra_cap_admits_partial_independent_referents",
        ref='dynamicresources.go preFilter: virtual ReservedFor growth '
            '— the consumer cap rejects only the overflow referent, '
            'not every referent',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("ja", tasks=1, gpu=0, claims=["c0"]),
               G("jb", tasks=1, gpu=0, claims=["c0"])],
        resource_claims=[shared_claim("c0", queue="q0", count=1,
                                      reserved=MAX - 1)],
        device_classes=[GPU_CLASS],
        # two INDEPENDENT 1-pod gangs share the claim's last slot: the
        # first admits, the second stays pending
        expect={"ja": True, "jb": 0},
    ),
    Case(
        name="dra_non_accel_class_keeps_node_constraints",
        ref='allocate_dra_test.go non-gpu claims + deviceclass node '
            'selection: an accel=False class still pins the pod to '
            'nodes that HAVE the device',
        nodes=[N("n0", gpu=1), N("n1", gpu=1,
                                 labels={"rdma": "true"})],
        queues=[Q("q0", quota=2)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["nic0"])],
        resource_claims=[shared_claim("nic0", queue="q0",
                                      device_class="rdma-nic")],
        device_classes=[GPU_CLASS,
                        apis.DeviceClass(name="rdma-nic", accel=False,
                                         node_selector={"rdma": "true"})],
        expect={"j0": True},
        expect_nodes={"j0": {"n1"}},
    ),
    Case(
        name="dra_non_accel_shared_claim_exempt_from_queue_label",
        ref='dynamicresources.go validateSharedGpuClaimQueueLabel: the '
            'queue-label rule scopes to GPU claims '
            '(IsGpuResourceClaim)',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["nic0"])],
        resource_claims=[shared_claim("nic0",  # no queue label
                                      device_class="rdma-nic")],
        device_classes=[apis.DeviceClass(name="rdma-nic", accel=False)],
        expect={"j0": True},
    ),
    Case(
        name="dra_non_gpu_claim_not_counted",
        ref='allocate_dra_test.go: "pod with simple resource claim - '
            'non gpu claims doesn\'t count for gpu limit"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=0, limit=0)],
        gangs=[G("j0", tasks=1, gpu=0, claims=["nic0"])],
        resource_claims=[shared_claim("nic0", queue="q0",
                                      device_class="rdma-nic")],
        device_classes=[GPU_CLASS,
                        apis.DeviceClass(name="rdma-nic", accel=False)],
        # the claim's devices are not accelerators: a zero-gpu queue
        # still schedules it
        expect={"j0": True},
    ),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.name)
def test_dra_scenario(case):
    run_case(case)

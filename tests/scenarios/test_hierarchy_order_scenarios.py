"""Allocate-order / quota-gate / hierarchy / fractional-reclaim scenario
catalog — reference-traceable to
``actions/integration_tests/allocate/allocate_test.go`` (allowances,
over-quota rules, creation/priority order, share updates mid-round,
hierarchy depths), ``.../reclaim`` (fractional and MIG reclaim), and
``.../preempt/preemptGang_test.go`` (whole-gang victimhood).

Priority/preemptibility encoding follows the reference's classes:
train = priority 50 preemptible, build/interactive = priority 100
non-preemptible (``constants.PriorityTrainNumber`` /
``PriorityBuildNumber``).
"""
import pytest

from .harness import Case, G, N, Q, run_case

CASES = [
    # ---- allowances and over-quota rules (allocate_test.go) -------------
    Case(
        name="department_allowance_caps_children",
        ref='allocate_test.go: "allocate job but does not allow to '
            'department to go over allowance"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("dept0", limit=2),
                Q("qa", parent="dept0"), Q("qb", parent="dept0")],
        gangs=[G("a0", queue="qa", tasks=2, gpu=1),
               G("b0", queue="qb", tasks=2, gpu=1)],
        # 4 requested, department allowance 2: exactly one job lands
        # whole (gang all-or-nothing keeps 2-task jobs atomic)
        expect_evictions=0,
    ),
    Case(
        name="train_allocates_over_quota",
        ref='allocate_test.go: "allocate pending jobs, allow over quota '
            'for train jobs (with interactive jobs)"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=1)],
        gangs=[G("train0", tasks=2, gpu=1, priority=50)],
        # preemptible train exceeds its 1-GPU deserved (no limit set)
        expect={"train0": True},
    ),
    Case(
        name="build_never_over_quota",
        ref='allocate_test.go: "don\'t allocate over quota build jobs"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=1)],
        gangs=[G("build0", tasks=2, gpu=1, priority=100,
                 preemptible=False)],
        expect={"build0": 0},
    ),
    Case(
        name="creation_time_breaks_equal_share",
        ref='allocate_test.go: "allocate according to creation time '
            'when share is equal"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("qa", quota=1), Q("qb", quota=1)],
        gangs=[G("older", queue="qa", tasks=1, gpu=1),
               G("newer", queue="qb", tasks=1, gpu=1)],
        expect={"older": True, "newer": 0},
    ),
    Case(
        name="priority_beats_creation",
        ref='allocate_test.go: "allocate according to priority"',
        nodes=[N("n0", gpu=1)],
        queues=[Q("q0", quota=1)],
        gangs=[G("older-low", tasks=1, gpu=1, priority=50),
               G("newer-high", tasks=1, gpu=1, priority=100)],
        expect={"newer-high": True, "older-low": 0},
    ),
    Case(
        name="lower_share_queue_served_first",
        ref='allocate_test.go: "1 build job pending for each queue '
            'with different share - allocate the second"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("qa", quota=2), Q("qb", quota=2)],
        gangs=[G("a-run", queue="qa", tasks=3, gpu=1, on=["n0"]),
               G("a0", queue="qa", tasks=1, gpu=1, priority=100,
                 preemptible=False),
               G("b0", queue="qb", tasks=1, gpu=1, priority=100,
                 preemptible=False)],
        # qa sits at 3/2 share: only qb's build may take the last GPU
        # (allocate-only, as the reference suite configures — the full
        # pipeline would ALSO preempt a-run for a0 afterwards)
        expect={"b0": True, "a0": 0},
        actions=("allocate",),
    ),
    Case(
        name="share_updates_during_allocation_round",
        ref='allocate_test.go: "6 pending train jobs - allocate the 1st '
            '2 of each queue (verify the share is being updated during '
            'the allocation)"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("qa", quota=2), Q("qb", quota=2)],
        gangs=[G(f"a{i}", queue="qa", tasks=1, gpu=1) for i in range(3)]
        + [G(f"b{i}", queue="qb", tasks=1, gpu=1) for i in range(3)],
        # live share interleaves the queues: two each, never 3+1
        expect={"a0": True, "a1": True, "b0": True, "b1": True,
                "a2": 0, "b2": 0},
    ),
    Case(
        name="overprovision_round_robins_queues",
        ref='allocate_test.go: "Over provisioning with over quota, many '
            'queues to few GPUs - verify queue share is updated during '
            'the same allocation round"',
        nodes=[N("n0", gpu=3)],
        queues=[Q(f"q{i}", quota=2) for i in range(3)],
        gangs=[G(f"j{i}-{k}", queue=f"q{i}", tasks=1, gpu=1)
               for i in range(3) for k in range(2)],
        # 6 jobs over 3 queues, 3 GPUs: one job per queue
        expect={"j0-0": True, "j1-0": True, "j2-0": True,
                "j0-1": 0, "j1-1": 0, "j2-1": 0},
    ),
    Case(
        name="departments_smaller_ratio_first",
        ref='allocate_test.go: "Allocate Departments with smaller '
            'ratio 1st"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("d0", quota=2), Q("d1", quota=2),
                Q("qa", parent="d0", quota=2),
                Q("qb", parent="d1", quota=2)],
        gangs=[G("a-run", queue="qa", tasks=2, gpu=1, on=["n0"]),
               G("a0", queue="qa", tasks=1, gpu=1),
               G("b0", queue="qb", tasks=1, gpu=1)],
        # d0 is at 2/2, d1 at 0/2: d1's job goes first; d0's train may
        # then take the last GPU over quota
        expect={"b0": True},
    ),
    Case(
        name="interactive_capped_at_department_deserved",
        ref='allocate_test.go: "Don\'t allow allocation of interactive '
            'jobs above the department\'s deserved GPUs"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("d0", quota=1), Q("qa", parent="d0", quota=4)],
        gangs=[G("i0", queue="qa", tasks=2, gpu=1, priority=100,
                 preemptible=False)],
        # the queue's own quota (4) would admit it, the department's
        # deserved (1) does not — non-preemptible stays within ancestry
        expect={"i0": 0},
    ),
    Case(
        name="interactive_preempts_overquota_train",
        ref='allocate_test.go: "try to allocate interactive after train '
            'when over-quota - train should be preempted for '
            'interactive to run"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=1)],
        gangs=[G("train0", tasks=1, gpu=1, on=["n0"], priority=50),
               G("train1", tasks=1, gpu=1, on=["n0"], priority=50),
               G("int0", tasks=1, gpu=1, priority=100,
                 preemptible=False)],
        # queue holds 2 > 1 deserved; the interactive job is entitled
        # to quota capacity: one train is preempted
        expect={"int0": True},
        expect_evictions=1,
    ),
    Case(
        name="train_after_interactive_stays_pending",
        ref='allocate_test.go: "try to allocate train after interactive '
            'when over-quota - train should not run"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("qa", quota=1), Q("qb", quota=1)],
        gangs=[G("int0", queue="qa", tasks=1, gpu=1, on=["n0"],
                 priority=100, preemptible=False),
               G("b-run", queue="qb", tasks=1, gpu=1, on=["n0"]),
               G("train0", queue="qa", tasks=1, gpu=1, priority=50)],
        # cluster full, qb at fair share: the over-share train has
        # nothing to reclaim and nothing to preempt
        expect={"train0": 0},
        expect_evictions=0,
    ),
    Case(
        name="cpu_queue_deserved_gate",
        ref='allocate_test.go: "don\'t allow job over QUEUE deserved '
            'CPU"',
        nodes=[N("n0", gpu=0, cpu=16)],
        queues=[Q("q0", cpu_quota=4)],
        gangs=[G("c0", tasks=1, gpu=0, cpu=8, priority=100,
                 preemptible=False)],
        expect={"c0": 0},
    ),
    Case(
        name="cpu_department_deserved_gate",
        ref='allocate_test.go: "don\'t allow job over DEPARTMENT '
            'deserved CPU"',
        nodes=[N("n0", gpu=0, cpu=16)],
        queues=[Q("d0", cpu_quota=4), Q("q0", parent="d0", cpu_quota=16)],
        gangs=[G("c0", queue="q0", tasks=1, gpu=0, cpu=8, priority=100,
                 preemptible=False)],
        expect={"c0": 0},
    ),
    Case(
        name="project_allowance_caps_queue",
        ref='allocate_test.go: "allocate job but does not allow to '
            'project to go over allowance"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=1, limit=2)],
        gangs=[G("t0", tasks=2, gpu=1), G("t1", tasks=2, gpu=1)],
        # maxAllowed 2 caps the queue even with idle capacity: one
        # 2-GPU job lands, the other stays whole and pending
        expect_evictions=0,
        actions=("allocate",),
    ),
    Case(
        name="interactive_within_quota_alongside_train",
        ref='allocate_test.go: "allocate pending jobs, allow over '
            'quota for train jobs (with interactive jobs)" — the '
            'interactive side',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=2)],
        gangs=[G("i0", tasks=2, gpu=1, priority=100,
                 preemptible=False),
               G("t0", tasks=2, gpu=1, priority=50)],
        # the build lands within deserved; the train then takes the
        # rest over quota
        expect={"i0": True, "t0": True},
        actions=("allocate",),
    ),
    # ---- hierarchy depths (allocate_test.go hierarchy cases) ------------
    Case(
        name="hierarchy_single_level",
        ref='allocate_test.go: "single level queue hierarchy - '
            'allocate job"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("j0", tasks=2, gpu=1)],
        expect={"j0": True},
    ),
    Case(
        name="hierarchy_three_levels",
        ref='allocate_test.go: "three level queue hierarchy - allocate '
            'jobs across teams"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("org", quota=4),
                Q("team-a", parent="org", quota=2),
                Q("team-b", parent="org", quota=2),
                Q("qa", parent="team-a", quota=2),
                Q("qb", parent="team-b", quota=2)],
        gangs=[G("a0", queue="qa", tasks=2, gpu=1),
               G("b0", queue="qb", tasks=2, gpu=1)],
        expect={"a0": True, "b0": True},
    ),
    Case(
        name="hierarchy_four_levels_deepest_leaf",
        ref='allocate_test.go: "four level queue hierarchy - allocate '
            'job at deepest level"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("org", quota=2),
                Q("div", parent="org", quota=2),
                Q("team", parent="div", quota=2),
                Q("leaf", parent="team", quota=2)],
        gangs=[G("j0", queue="leaf", tasks=2, gpu=1)],
        expect={"j0": True},
    ),
    # ---- fractional / MIG reclaim (reclaim suite) -----------------------
    Case(
        name="reclaim_fractional_by_whole_gpu",
        ref='reclaim: "reclaim fractional train by whole GPU job"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("qa", quota=1), Q("qb", quota=1)],
        gangs=[G("a-f0", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[0]),
               G("a-f1", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[0]),
               G("a-f2", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[1]),
               G("a-f3", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[1]),
               G("b0", queue="qb", tasks=1, gpu=1)],
        # qa holds both devices (2.0 > 1 deserved); a whole-GPU
        # reclaimer needs one device fully vacated: both sharers of one
        # device are evicted
        expect={"b0": True},
        expect_evictions=2,
        expect_pipelined={"b0": 1},
    ),
    Case(
        name="reclaim_fractional_partial",
        ref='reclaim: "reclaim fractional train by fractional train GPU '
            'job - reclaim only part of fractional jobs"',
        nodes=[N("n0", gpu=1, gpu_mem_gib=100)],
        queues=[Q("qa", quota=0.5), Q("qb", quota=0.5)],
        gangs=[G("a-f0", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[0]),
               G("a-f1", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[0]),
               G("b0", queue="qb", tasks=1, gpu=0, portion=0.5)],
        # qa holds 1.0 > 0.5 deserved: ONE fraction suffices for the
        # 0.5 reclaimer
        expect={"b0": True},
        expect_evictions=1,
    ),
    Case(
        name="reclaim_fractional_over_quota_blocked",
        ref='reclaim: "reclaim fractional train by fractional GPU job '
            'will go over quota - don\'t reclaim"',
        nodes=[N("n0", gpu=2, gpu_mem_gib=100)],
        queues=[Q("qa", quota=0.5), Q("qb", quota=0.5),
                Q("qc", quota=0.5)],
        gangs=[G("a-f0", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[0]),
               G("a-f1", queue="qa", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[0]),
               G("b-run", queue="qb", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[1]),
               G("c-f0", queue="qc", tasks=1, gpu=0, portion=0.5,
                 on=["n0"], devices=[1]),
               G("b0", queue="qb", tasks=1, gpu=0, portion=0.5)],
        # the cluster is full and qb already sits at its 0.5 share:
        # reclaiming for b0 would take qb over quota — refused, even
        # though qa is over share
        expect={"b0": 0},
        expect_evictions=0,
    ),
    Case(
        name="reclaim_mig_simple",
        ref='reclaim: "Simple reclaim with MIG jobs" — pure-MIG jobs: '
            'the profiles\' g-numbers count toward queue GPU '
            'accounting (resource_info.go GetTotalGPURequest), so the '
            'holder queue reads over-share and the reclaimed instance '
            'credits back to the preemptor',
        nodes=[N("n0", gpu=8, mig={"nvidia.com/mig-1g.10gb": 2})],
        queues=[Q("qa", quota=1), Q("qb", quota=1)],
        gangs=[G("a0", queue="qa", tasks=1, gpu=0,
                 mig={"nvidia.com/mig-1g.10gb": 1}, on=["n0"]),
               G("a1", queue="qa", tasks=1, gpu=0,
                 mig={"nvidia.com/mig-1g.10gb": 1}, on=["n0"]),
               G("b0", queue="qb", tasks=1, gpu=0,
                 mig={"nvidia.com/mig-1g.10gb": 1})],
        # both instances held by qa (2 GPU-equivalents > 1 deserved);
        # qb's MIG job reclaims one
        expect={"b0": True},
        expect_evictions=1,
    ),
    Case(
        name="reclaim_mig_within_fair_share_safe",
        ref='reclaim: "Should not reclaim jobs if job is within fair '
            'share" (pure-MIG jobs, g-number queue accounting)',
        nodes=[N("n0", gpu=8, mig={"nvidia.com/mig-1g.10gb": 2})],
        queues=[Q("qa", quota=1), Q("qb", quota=1)],
        gangs=[G("a0", queue="qa", tasks=1, gpu=0,
                 mig={"nvidia.com/mig-1g.10gb": 1}, on=["n0"]),
               G("b-run", queue="qb", tasks=1, gpu=0,
                 mig={"nvidia.com/mig-1g.10gb": 1}, on=["n0"]),
               G("b0", queue="qb", tasks=1, gpu=0,
                 mig={"nvidia.com/mig-1g.10gb": 1})],
        # one instance each: qa is within fair share, no eviction
        expect={"b0": 0},
        expect_evictions=0,
    ),
    # ---- whole-gang preemption (preemptGang_test.go) --------------------
    Case(
        name="gang_classic_whole_victim",
        ref='preemptGang_test.go: "Classic gang preempt"',
        nodes=[N("n0", gpu=2)],
        queues=[Q("q0", quota=2)],
        gangs=[G("victim", tasks=2, gpu=1, on=["n0"], priority=50),
               G("pree", tasks=2, gpu=1, priority=100,
                 preemptible=False)],
        # the whole 2-task victim gang goes (gang-atomic victimhood)
        expect={"pree": True},
        expect_evictions=2,
    ),
    Case(
        name="gang_preempt_only_what_is_needed",
        ref='preemptGang_test.go: "Some of the pods are running and '
            'some are pending- preempt those who are needed in order '
            'to allocate all the pods of gang job"',
        nodes=[N("n0", gpu=4)],
        queues=[Q("q0", quota=4)],
        gangs=[G("small", tasks=1, gpu=1, on=["n0"], priority=50),
               G("small2", tasks=1, gpu=1, on=["n0"], priority=50),
               G("pree", tasks=3, gpu=1, priority=100,
                 preemptible=False)],
        # 2 free + 1 from ONE evicted single-task victim suffices: the
        # second low-priority job survives
        expect={"pree": True},
        expect_evictions=1,
    ),
]


@pytest.mark.parametrize("case", CASES, ids=lambda c: c.name)
def test_hierarchy_order_scenario(case):
    run_case(case)
